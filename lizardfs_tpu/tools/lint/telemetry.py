"""``telemetry-coverage``: every client-facing verb maps to a trace
span, an SLO class (or a reasoned waiver), a fault choke point, and
metrics — statically.

PRs 2/3/8/10 built the conventions one at a time: the master RPC loop
traces + times every dispatched op, the chunkserver data plane charges
read/write spans and objectives, the NFS and S3 gateways begin a span
and observe their own SLO class at ONE dispatch boundary, and the fault
engine's frame choke points cover every proto message generically. Each
new verb since then was hand-audited against that matrix at review
time. This checker turns the audit into a standing gate:

* **the verb inventory is total** — every client-facing catalog class
  (``Cltoma*`` master RPCs, ``Cltocs*`` data-plane requests) must have
  an inventory entry below, and every entry must still name a catalog
  class. Adding a verb without deciding its telemetry story fails lint.
* **SLO mapping is real** — an entry either names a class from
  ``runtime/slo.py``'s ``OP_CLASSES`` (and the verb's handler file must
  actually ``observe`` that class) or carries a waiver REASON saying
  why the verb has no latency objective.
* **the fault path exists** — each verb's choke point must be an
  inventoried ``runtime/faults.py`` site whose implementing file really
  consults it (a renamed site string otherwise leaves the verb
  undrillable while the inventory still claims coverage).
* **the generic instruments stand** — the per-surface span/metric
  anchors (master per-op timing + span record, chunkserver op spans,
  gateway boundary spans) must exist in the handler sources; deleting
  or renaming one fails here, not in a post-incident review.
* **no dead objectives** — every ``OP_CLASSES`` entry must be observed
  by at least one surface (a class nobody feeds burns no rate yet
  still reads "healthy" on dashboards).
"""

from __future__ import annotations

import ast
import os
import re

from lizardfs_tpu.tools.lint.engine import Finding

RULE = "telemetry-coverage"

# ---- surfaces --------------------------------------------------------------
MASTER = "lizardfs_tpu/master/server.py"
CS = "lizardfs_tpu/chunkserver/server.py"
NFS = "lizardfs_tpu/nfs/server.py"
S3 = "lizardfs_tpu/s3/server.py"
FRAMING = "lizardfs_tpu/proto/framing.py"

# fault site -> the file that consults it (runtime/faults.py names the
# site; the implementing file must pass the literal to the engine)
SITE_IMPL = {
    "frame_send": FRAMING,
    "frame_recv": FRAMING,
    "disk_pread": "lizardfs_tpu/chunkserver/chunk_store.py",
    "disk_pwrite": "lizardfs_tpu/chunkserver/chunk_store.py",
    # every dialer (pool, RPC links, client data plane) funnels through
    # faults.dial_point — the literal lives with the choke point
    "dial": "lizardfs_tpu/runtime/faults.py",
    "serve_read": CS,
    "http_recv": S3,
    "http_send": S3,
}

# ---- the verb inventory ----------------------------------------------------
# verb -> SLO class its handler surface must observe
SLO_CLASSES = {
    # chunk grant / commit RPCs are the master's latency-critical class
    "CltomaReadChunk": "locate",
    "CltomaWriteChunk": "locate",
    "CltomaWriteChunkEnd": "locate",
    "CltomaWriteChunkEndBatch": "locate",
    # data plane: the chunkserver charges read/write objectives
    "CltocsRead": "read",
    "CltocsReadBulk": "read",
    "CltocsWriteData": "write",
    "CltocsWriteBulk": "write",
    "CltocsWriteBulkPart": "write",
    "CltocsShmWritePart": "write",
    "CltocsWriteEnd": "write",
    "CltocsWriteInit": "write",
}

_META = (
    "namespace metadata RPC — per-op latency histogram + master span "
    "cover it; the latency objective rides the locate class (chunk "
    "grants) by design, metadata breaches surface via the per-op "
    "timings and the health rollup"
)
_SESSION = (
    "session/control RPC — fires once per mount or failover, not on "
    "the request path; per-op timing + trace span only"
)
_ADMIN = (
    "operator/introspection verb — human-paced, budget-bounded "
    "server-side; per-op timing + trace span only"
)
_TAPE = (
    "tape-tier verb — latency is dominated by the archival backend and "
    "bounded by the caller's deadline; recall progress is tracked via "
    "tape_* health counts, not a latency objective"
)

# verb -> why it carries NO latency objective (the reason is the
# waiver; an empty reason fails lint)
SLO_WAIVERS = {
    **{v: _META for v in (
        "CltomaLookup", "CltomaGetattr", "CltomaMkdir", "CltomaCreate",
        "CltomaReaddir", "CltomaUnlink", "CltomaRmdir", "CltomaRename",
        "CltomaSetGoal", "CltomaSetEattr", "CltomaTruncate",
        "CltomaSetattr", "CltomaSymlink", "CltomaReadlink", "CltomaLink",
        "CltomaSnapshot", "CltomaSetXattr", "CltomaGetXattr",
        "CltomaListXattr", "CltomaStatFs", "CltomaAccess",
        "CltomaSetAcl", "CltomaGetAcl", "CltomaSetRichAcl",
        "CltomaGetRichAcl", "CltomaLockOp", "CltomaOpen", "CltomaRelease",
        "CltomaSetQuota", "CltomaGetQuota", "CltomaAppendChunks",
    )},
    **{v: _SESSION for v in (
        "CltomaRegister", "CltomaGoodbye", "CltomaIoLimitRequest",
    )},
    "CltomaSessionStats": (
        "periodic best-effort workload-summary push (gateway -> "
        "master, ~1/5s) feeding the `top` rollup — telemetry about "
        "telemetry; per-op timing + master span cover it"
    ),
    **{v: _ADMIN for v in (
        "CltomaTrashList", "CltomaUndelete", "CltomaFileRepair",
        "CltomaChunkDamaged",
    )},
    **{v: _TAPE for v in (
        "CltomaTapeInfo", "CltomaTapeDemote", "CltomaTapeRecall",
    )},
    "CltocsPrefetch": (
        "fire-and-forget page-cache hint with no reply frame — there "
        "is no completion to time"
    ),
    "CltocsShmInit": (
        "one-shot ring negotiation per (client, chunkserver) pair, "
        "acked via CstoclWriteStatus; not a data op"
    ),
}

# per-verb fault choke point (default: the frame plane covers every
# proto message at recv time)
VERB_SITES = {
    "CltocsRead": "serve_read",
    "CltocsReadBulk": "serve_read",
    "CltocsWriteData": "disk_pwrite",
    "CltocsWriteBulk": "disk_pwrite",
    "CltocsWriteBulkPart": "disk_pwrite",
    "CltocsShmWritePart": "disk_pwrite",
}
DEFAULT_SITE = "frame_recv"

# generic per-surface instruments: (file, regex, what broke if absent)
DAEMON = "lizardfs_tpu/runtime/daemon.py"
CLIENT = "lizardfs_tpu/client/client.py"
HEAT = "lizardfs_tpu/master/heat.py"
ELECTION = "lizardfs_tpu/ha/election.py"
SLO = "lizardfs_tpu/runtime/slo.py"
TRACING = "lizardfs_tpu/runtime/tracing.py"
NATIVE_SERVE = "lizardfs_tpu/chunkserver/native_serve.py"
ANCHORS = (
    (MASTER, r"metrics\.timing\(type\(msg\)\.__name__\)",
     "master per-op latency histograms (request_log analog)"),
    (MASTER, r"trace_ring\.record\(", "master RPC span recording"),
    (CS, r"trace_ring\.record\(", "chunkserver op span recording"),
    (CS, r"slo\.observe\(", "chunkserver data-plane SLO accounting"),
    (NFS, r"tracing\.begin\(\)", "NFS gateway boundary span"),
    (NFS, r"observe\(\s*\n?\s*[\"']nfs[\"']", "NFS SLO class accounting"),
    (S3, r"tracing\.begin\(\)", "S3 gateway boundary span"),
    (S3, r"observe\(\s*\n?\s*[\"']s3[\"']", "S3 SLO class accounting"),
    # per-session op accounting (ISSUE 14): the master RPC loop and
    # the chunkserver data plane must keep charging the originating
    # session, or `top` silently reads empty
    (MASTER, r"session_ops\.record\(",
     "master per-session op accounting (`top` rollup input)"),
    (CS, r"session_ops\.record\(",
     "chunkserver per-session data-plane accounting"),
    (MASTER, r"def top_report\(", "master cluster-wide `top` rollup"),
    # gateway observability surfaces: both front doors must keep their
    # /metrics + /healthz HTTP endpoints AND their master stats push —
    # a deleted endpoint is a lint failure, not a dashboard mystery
    (NFS, r"[\"']/metrics[\"']", "NFS gateway /metrics endpoint"),
    (NFS, r"[\"']/healthz[\"']", "NFS gateway /healthz endpoint"),
    (NFS, r"gateway_stats_push_loop\(",
     "NFS gateway workload-summary push (CltomaSessionStats)"),
    (S3, r"_op_metrics", "S3 gateway /metrics endpoint"),
    (S3, r"_op_healthz", "S3 gateway /healthz endpoint"),
    (S3, r"gateway_stats_push_loop\(",
     "S3 gateway workload-summary push (CltomaSessionStats)"),
    # the always-on sampling profiler's dump path (admin `profile`)
    (DAEMON, r"profiler\.collapsed\(",
     "daemon profiler collapsed-stack dump (admin `profile`)"),
    # multi-tenant QoS (ISSUE 15): the shed/throttle labeled counter
    # families and the BUSY handling chain must stand on every surface
    # — deleting any of them silently un-instruments load shedding
    (MASTER, r"labeled_counter\(\s*\n?\s*[\"']qos_shed[\"']",
     "master per-tenant shed counter (qos_shed{tenant,op})"),
    (CS, r"labeled_counter\(\s*\n?\s*[\"']qos_throttle[\"']",
     "chunkserver per-tenant throttle counter (qos_throttle{tenant})"),
    (CLIENT, r"st\.BUSY",
     "client BUSY (QoS shed) backoff-retry handling"),
    (CLIENT, r"qos_busy_waits",
     "client shed-retry counter (qos_busy_waits)"),
    (S3, r"st\.BUSY", "S3 gateway BUSY -> 503 SlowDown mapping"),
    (NFS, r"NFS3ERR_JUKEBOX",
     "NFS gateway BUSY -> JUKEBOX delay mapping"),
    # cluster heat loop (ISSUE 17): the lizardfs_heat_* families, the
    # heat section of `health`, and the SLO→QoS auto-arm chain are
    # standing surfaces — deleting any of them silently blinds the
    # heat map or disarms the second auto-arm action
    (HEAT, r"labeled_counter\(\s*\n?\s*[\"']heat_ops[\"']",
     "heat sketch per-key op counter (heat_ops{kind,key})"),
    (HEAT, r"labeled_counter\(\s*\n?\s*[\"']heat_bytes[\"']",
     "heat sketch per-key byte counter (heat_bytes{kind,key})"),
    (HEAT, r"labeled_timing\(\s*\n?\s*[\"']heat_hot_ops[\"']",
     "hot-key latency histogram with trace-id exemplars (heat_hot_ops)"),
    (MASTER, r"[\"']heat[\"']:\s*heat_doc",
     "heat section of the cluster `health` rollup"),
    (MASTER, r"def _slo_qos_arm\(",
     "SLO burn-rate breach -> QoS pressure auto-arm action"),
    (MASTER, r"labeled_counter\(\s*\n?\s*[\"']slo_qos_armed[\"']",
     "auto-armed QoS pressure counter (slo_qos_armed{tenant,op})"),
    (SLO, r"qos_arm\(",
     "SLO engine second auto-arm hook (breach -> qos_arm call)"),
    (CS, r"_heat_fold_json\(",
     "chunkserver per-chunk heat heartbeat fold (heat map input)"),
    # read-path microscope (ISSUE 18): phase-instrumented reads, the
    # queue-wait gates, and the attribution engine are standing
    # surfaces — losing any leg silently blanks a `top` column, a
    # queue_wait family, or the slowops/incident attribution embed
    (CLIENT, r"PHASE_SINK\.set\(",
     "client read-phase sink activation at the read_file boundary"),
    (CLIENT, r"read_phases\.add_wall\(",
     "client exactly-once read wall/rep accounting (PhaseBreakdown)"),
    (CLIENT, r"charge_queue_wait\(",
     "client queue-wait gates (dial / busy_retry / write_credit)"),
    (CS, r"charge_queue_wait\(",
     "chunkserver DRR disk-gate queue-wait charge (drr_disk gate)"),
    (CS, r"queue_us",
     "chunkserver native trace-slot queue-wait fold (queue_us slot)"),
    (TRACING, r"def attribute_timeline\(",
     "latency attribution engine (queue/disk/net/compute buckets)"),
    (TRACING, r"def charge_queue_wait\(",
     "shared queue-wait charge helper (metric + ambient trace span)"),
    (SLO, r"attribute_timeline\(",
     "slowops/incident latency-attribution embed"),
    (MASTER, r"read_phases",
     "per-session read-phase lift into the `top` rollup"),
    (NATIVE_SERVE, r"lz_serve_trace3",
     "native 10-slot trace drain (queue_us-bearing slot contract)"),
    # autopilot failover (ISSUE 19): the lizardfs_ha_* families, the
    # `ha` section of health/admin, and the epoch fence are standing
    # surfaces — losing a gauge blinds the operator mid-incident, and
    # losing the fence silently re-opens the split-brain window
    (MASTER, r"gauge\(\s*\n?\s*[\"']ha_epoch[\"']",
     "HA epoch gauge on every personality (lizardfs_ha_epoch)"),
    (MASTER, r"gauge\(\s*\n?\s*[\"']ha_is_active[\"']",
     "HA active-posture gauge (lizardfs_ha_is_active)"),
    (MASTER, r"counter\([\"']ha_fenced[\"']\)\.inc\(",
     "zombie ex-primary fence counter (lizardfs_ha_fenced_total)"),
    (MASTER, r"def _ha_status\(",
     "the `ha` admin command / health section (failover posture)"),
    (MASTER, r"[\"']ha[\"']:\s*self\._ha_status\(\)",
     "ha section of the cluster `health` rollup"),
    (ELECTION, r"stale_votes_granted",
     "arbiter leaderless-relaxation counter in election status"),
)

# files searched for OP_CLASSES coverage (who feeds each objective)
SLO_SURFACES = (MASTER, CS, NFS, S3)


def extra_inputs(cfg) -> list[str]:
    root = cfg.root
    paths = {os.path.join(root, p) for p in SITE_IMPL.values()}
    paths.update(os.path.join(root, p) for p in SLO_SURFACES)
    paths.update(os.path.join(root, rel) for rel, _, _ in ANCHORS)
    paths.add(os.path.join(root, "lizardfs_tpu/runtime/slo.py"))
    paths.add(os.path.join(root, "lizardfs_tpu/runtime/faults.py"))
    if cfg.messages_path:
        paths.add(cfg.messages_path)
    return sorted(p for p in paths if os.path.exists(p))


def _tuple_of_strs(path: str, var: str) -> list[str]:
    """Module-level ``VAR = ("a", "b", ...)`` literal, without import."""
    try:
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read())
    except (OSError, SyntaxError):
        return []
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == var
        ):
            try:
                val = ast.literal_eval(node.value)
            except (ValueError, SyntaxError):
                return []
            if isinstance(val, (tuple, list)):
                return [v for v in val if isinstance(v, str)]
    return []


def _observes(text: str, cls: str) -> bool:
    return re.search(
        r"observe\(\s*\n?\s*[\"']" + re.escape(cls) + r"[\"']", text
    ) is not None


def check_global(cfg, collections: dict) -> list[Finding]:
    root = cfg.root
    findings: list[Finding] = []
    missing: set[str] = set()

    def read(rel: str) -> str:
        """Text of an inventoried surface file. An unreadable surface
        is a FINDING (reported once), never a silent skip — otherwise a
        renamed master/server.py would vacuously pass every check this
        rule makes about it."""
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as fh:
                return fh.read()
        except OSError:
            if rel not in missing:
                missing.add(rel)
                findings.append(Finding(
                    RULE, rel, 0,
                    "telemetry surface file is missing/unreadable — the "
                    "inventory in tools/lint/telemetry.py names it; update "
                    "the inventory to the file's new home (every check "
                    "against it would otherwise pass vacuously)",
                ))
            return ""

    # inventory anchors are configurable so fixtures can exercise the
    # rule without a full tree
    slo_classes = getattr(cfg, "tc_slo_classes", SLO_CLASSES)
    slo_waivers = getattr(cfg, "tc_slo_waivers", SLO_WAIVERS)
    verb_sites = getattr(cfg, "tc_verb_sites", VERB_SITES)
    anchors = getattr(cfg, "tc_anchors", ANCHORS)
    site_impl = getattr(cfg, "tc_site_impl", SITE_IMPL)
    slo_path = getattr(
        cfg, "slo_path", os.path.join(root, "lizardfs_tpu/runtime/slo.py")
    )
    faults_path = getattr(
        cfg, "faults_path",
        os.path.join(root, "lizardfs_tpu/runtime/faults.py"),
    )

    # ---- catalog <-> inventory bijection ---------------------------------
    verbs: dict[str, int] = {}
    cat_rel = ""
    if cfg.messages_path and os.path.exists(cfg.messages_path):
        cat_rel = os.path.relpath(cfg.messages_path, root)
        try:
            with open(cfg.messages_path, encoding="utf-8") as fh:
                tree = ast.parse(fh.read())
        except (OSError, SyntaxError) as e:
            return [Finding(RULE, cat_rel, 0, f"cannot parse catalog: {e}")]
        for node in tree.body:
            if isinstance(node, ast.ClassDef) and node.name.startswith(
                ("Cltoma", "Cltocs")
            ):
                verbs[node.name] = node.lineno
    if not verbs:
        return findings

    op_classes = _tuple_of_strs(slo_path, "OP_CLASSES")
    fault_sites = _tuple_of_strs(faults_path, "SITES")
    master_text = read(MASTER)
    cs_text = read(CS)

    for verb, line in sorted(verbs.items()):
        handler_rel = MASTER if verb.startswith("Cltoma") else CS
        handler_text = master_text if handler_rel == MASTER else cs_text
        in_slo = verb in slo_classes
        in_waiver = verb in slo_waivers
        if not in_slo and not in_waiver:
            findings.append(Finding(
                RULE, cat_rel, line,
                f"{verb}: client-facing verb with no telemetry inventory "
                "entry — map it to an SLO class in tools/lint/telemetry.py "
                "(or add a waiver REASON there saying why it carries no "
                "latency objective)",
            ))
            continue
        if in_slo and in_waiver:
            findings.append(Finding(
                RULE, cat_rel, line,
                f"{verb}: both an SLO class and a waiver — pick one",
            ))
        if in_slo:
            cls = slo_classes[verb]
            if op_classes and cls not in op_classes:
                findings.append(Finding(
                    RULE, cat_rel, line,
                    f"{verb}: inventory maps it to SLO class {cls!r} which "
                    "runtime/slo.py OP_CLASSES does not define",
                ))
            elif handler_text and not _observes(handler_text, cls):
                findings.append(Finding(
                    RULE, cat_rel, line,
                    f"{verb}: inventory claims SLO class {cls!r} but "
                    f"{handler_rel} never observes it — the objective is "
                    "a dead letter for this verb",
                ))
        elif not str(slo_waivers[verb]).strip():
            findings.append(Finding(
                RULE, cat_rel, line,
                f"{verb}: SLO waiver with no reason — a reasonless waiver "
                "is not a waiver",
            ))
        # word-boundary match: CltomaWriteChunkEnd must not pass on the
        # strength of CltomaWriteChunkEndBatch still being handled
        if handler_text and not re.search(
            r"\b" + re.escape(verb) + r"\b", handler_text
        ):
            findings.append(Finding(
                RULE, cat_rel, line,
                f"{verb}: not referenced by its handler surface "
                f"({handler_rel}) — either a dead verb or a dispatch gap; "
                "remove it from the catalog or handle it",
            ))
        site = verb_sites.get(verb, DEFAULT_SITE)
        if fault_sites and site not in fault_sites:
            findings.append(Finding(
                RULE, cat_rel, line,
                f"{verb}: fault choke point {site!r} is not in "
                "runtime/faults.py SITES — the verb cannot be drilled",
            ))

    # ---- fault sites really consulted ------------------------------------
    # verb-mapped sites need a SITE_IMPL row; every SITE_IMPL row (not
    # just the ones a verb maps to today) must really pass its literal
    # to the fault engine, or a renamed "http_recv"/"disk_pread" string
    # leaves the site undrillable while the inventory still claims it
    checked_sites = {verb_sites.get(v, DEFAULT_SITE) for v in verbs}
    for site in sorted(checked_sites - set(site_impl)):
        findings.append(Finding(
            RULE, "lizardfs_tpu/tools/lint/telemetry.py", 0,
            f"fault site {site!r} has no SITE_IMPL mapping — name the "
            "file that consults it",
        ))
    for site, impl in sorted(site_impl.items()):
        text = read(impl)
        if text and f'"{site}"' not in text and f"'{site}'" not in text:
            findings.append(Finding(
                RULE, impl, 0,
                f"fault site {site!r} is claimed by the inventory but this "
                "file never passes the literal to the fault engine — the "
                "choke point is gone",
            ))

    # ---- generic instruments ---------------------------------------------
    for rel, pattern, what in anchors:
        text = read(rel)
        if text and re.search(pattern, text) is None:
            findings.append(Finding(
                RULE, rel, 0,
                f"missing instrument: {what} (expected /{pattern}/) — "
                "restore it or update the telemetry inventory with the "
                "new spelling",
            ))

    # ---- no dead objectives ----------------------------------------------
    if op_classes:
        surface_texts = [read(p) for p in SLO_SURFACES]
        for cls in op_classes:
            if not any(_observes(t, cls) for t in surface_texts if t):
                findings.append(Finding(
                    RULE, os.path.relpath(slo_path, root), 0,
                    f"SLO class {cls!r} is defined but no surface observes "
                    "it — dashboards read it as forever-healthy; feed it "
                    "or retire it",
                ))
    return findings
