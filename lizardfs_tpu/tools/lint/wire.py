"""``wire-skew``: the trailing-field version-skew contract, statically.

Every additive wire evolution in this tree (trace_id, meta_version,
health_json, replica_ok, mirror) rides the same convention: optional
fields are a TRAILING suffix declared by ``SKEW_TOLERANT_FROM``, the
codec constructor-defaults them (old call sites keep working) and the
decoder default-fills them (old senders keep parsing). The codec
enforces the mechanics at class-definition time; this checker pins the
*conventions* before the code ever runs, by parsing the message catalog
(``proto/messages.py``) without importing it:

* ``SKEW_TOLERANT_FROM`` must be a literal int with ``1 <= v <
  len(FIELDS)`` — ``0`` would make every field optional (fail-open
  decode: a truncated status reply would parse as OK), ``>= len``
  is a dead marker;
* the conventionally-optional field names (trace_id, meta_version,
  health_json, replica_ok, mirror) must sit AT OR PAST the skew index —
  never required mid-message, where an old peer's encoding would
  misalign every following field;
* a skew-variable message (own optional tail, or transitively via its
  terminal nested message) may be nested only as the FINAL field of a
  container and never inside a ``list:`` — its encoding has no fixed
  length;
* ``MSG_TYPE`` ids are unique; field types must be valid codec grammar;
* message classes must not override ``__init__``/``pack_body``/
  ``unpack_body``/``_field_is_default`` — an override silently breaks
  the constructor-default/decode-fill halves of the contract.
"""

from __future__ import annotations

import ast
import os

from lizardfs_tpu.tools.lint.engine import Finding, SourceFile

RULE = "wire-skew"

# field names that by repo convention ONLY ever ride as skew-tolerant
# trailing fields (eattr is excluded: Seteattr carries it as required
# request payload)
OPTIONAL_BY_CONVENTION = {
    "trace_id",
    "meta_version",
    "health_json",
    "replica_ok",
    "mirror",
    # HA fencing epoch (ISSUE 19): rides every register/heartbeat
    # surface as an additive tail; 0 = pre-HA peer, fencing disengaged
    "epoch",
}

# (message, field) pairs that are additive-convention fields WITHIN one
# message even though the same name is required payload elsewhere — the
# PR-10 wire surface: the tape server's own cluster-client session id
# rides TstomaRegister's optional tail (legacy sid-0 peers keep the
# permissive demoted-write standdown), while session_id stays a
# required field of CltomaRegister/MatoclRegister. Same pattern for any
# future S3/tape-era trailing field whose name is taken: scope it here
# instead of widening the global set.
OPTIONAL_BY_CONVENTION_SCOPED = {
    ("TstomaRegister", "session_id"),
    # per-session op accounting (ISSUE 14): the originating session
    # rides the data-plane requests as an additive tail (old peers
    # send/serve 0 = unattributed) while session_id stays required
    # payload in the Register messages
    ("CltocsRead", "session_id"),
    ("CltocsReadBulk", "session_id"),
    ("CltocsWriteInit", "session_id"),
}

_SCALARS = {"u8", "u16", "u32", "u64", "i32", "i64", "bool"}
_CONTRACT_METHODS = {
    "__init__",
    "pack_body",
    "unpack_body",
    "_field_is_default",
}


def _valid_ftype(ftype: str, classes: dict) -> bool:
    if ftype in _SCALARS or ftype in ("bytes", "str"):
        return True
    if ftype.startswith("list:"):
        return _valid_ftype(ftype[5:], classes)
    if ftype.startswith("msg:"):
        return ftype[4:] in classes
    return False


class _Msg:
    def __init__(self, name: str, line: int):
        self.name = name
        self.line = line
        self.fields: list[tuple[str, str]] | None = None
        self.skew: int | None = None
        self.msg_type: int | None = None
        self.overrides: list[tuple[str, int]] = []
        self.fields_literal = True


def _literal(node):
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


def extra_inputs(cfg) -> list[str]:
    """The one catalog file this global pass reads (feeds the engine's
    global-results cache key)."""
    return [cfg.messages_path] if cfg.messages_path else []


def _parse_catalog(tree: ast.Module) -> dict[str, _Msg]:
    out: dict[str, _Msg] = {}
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        msg = _Msg(node.name, node.lineno)
        for st in node.body:
            if isinstance(st, ast.Assign) and len(st.targets) == 1 and (
                isinstance(st.targets[0], ast.Name)
            ):
                tname = st.targets[0].id
                if tname == "FIELDS":
                    val = _literal(st.value)
                    if isinstance(val, (tuple, list)):
                        msg.fields = list(val)
                    else:
                        msg.fields = []
                        msg.fields_literal = False
                elif tname == "SKEW_TOLERANT_FROM":
                    msg.skew = _literal(st.value)
                elif tname == "MSG_TYPE":
                    msg.msg_type = _literal(st.value)
            elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if st.name in _CONTRACT_METHODS:
                    msg.overrides.append((st.name, st.lineno))
        if msg.fields is not None:
            out[msg.name] = msg
    return out


def _tail_elides(name: str, classes: dict[str, _Msg], seen=()) -> bool:
    msg = classes.get(name)
    if msg is None or name in seen:
        return False
    if msg.skew is not None:
        return True
    if msg.fields:
        _, ftype = msg.fields[-1]
        if isinstance(ftype, str) and ftype.startswith("msg:"):
            return _tail_elides(ftype[4:], classes, seen + (name,))
    return False


def check_global(cfg, collections: dict) -> list[Finding]:
    # parses its one target itself (a single file) — the engine's
    # per-file cache can then skip parsing everything else on warm runs
    path = cfg.messages_path
    if not path or not os.path.exists(path):
        return []
    rel = os.path.relpath(path, cfg.root)
    try:
        with open(path, encoding="utf-8") as fh:
            src = SourceFile(path, rel, fh.read())
    except (OSError, SyntaxError) as e:
        return [Finding(RULE, rel, 0, f"cannot parse catalog: {e}")]
    classes = _parse_catalog(src.tree)
    findings: list[Finding] = []

    def f(msg: _Msg, text: str, line: int | None = None):
        findings.append(Finding(RULE, rel, line or msg.line, text))

    by_type: dict[int, str] = {}
    for msg in classes.values():
        fields = msg.fields or []
        if not msg.fields_literal:
            f(msg, f"{msg.name}: FIELDS is not a literal tuple — the "
                   "checker (and any reader) must be able to see the wire "
                   "schema without executing code")
        for entry in fields:
            if (
                not isinstance(entry, tuple)
                or len(entry) != 2
                or not all(isinstance(x, str) for x in entry)
            ):
                f(msg, f"{msg.name}: FIELDS entry {entry!r} is not a "
                       "(name, type) pair of string literals")
                continue
            fname, ftype = entry
            if not _valid_ftype(ftype, classes):
                f(msg, f"{msg.name}.{fname}: unknown codec field type "
                       f"{ftype!r}")
        # MSG_TYPE uniqueness
        if msg.msg_type is not None:
            prev = by_type.get(msg.msg_type)
            if prev is not None:
                f(msg, f"{msg.name}: MSG_TYPE {msg.msg_type} already "
                       f"used by {prev}")
            else:
                by_type[msg.msg_type] = msg.name
        # skew index shape
        if msg.skew is not None:
            if not isinstance(msg.skew, int) or isinstance(msg.skew, bool):
                f(msg, f"{msg.name}: SKEW_TOLERANT_FROM must be a literal "
                       "int")
            elif msg.skew < 1:
                f(msg, f"{msg.name}: SKEW_TOLERANT_FROM={msg.skew} makes "
                       "required fields optional — a truncated reply would "
                       "fail OPEN (decode defaults instead of a parse "
                       "error); the optional suffix must start at >= 1")
            elif msg.skew >= len(fields):
                f(msg, f"{msg.name}: SKEW_TOLERANT_FROM={msg.skew} covers "
                       f"no field (only {len(fields)} declared) — dead "
                       "marker, drop it or add the optional suffix")
        # conventionally-optional names must be in the optional suffix
        for i, entry in enumerate(fields):
            if not (isinstance(entry, tuple) and len(entry) == 2):
                continue
            fname = entry[0]
            if fname in OPTIONAL_BY_CONVENTION or (
                (msg.name, fname) in OPTIONAL_BY_CONVENTION_SCOPED
            ):
                if msg.skew is None or i < msg.skew:
                    f(msg, f"{msg.name}.{fname}: {fname!r} is an additive "
                           "convention field — it must sit at or past "
                           "SKEW_TOLERANT_FROM (trailing, constructor-"
                           "defaulted, decode default-filled), or an old "
                           "peer's shorter encoding misaligns every "
                           "following field")
        # skew-variable nesting: terminal msg: only, never in lists
        for i, entry in enumerate(fields):
            if not (isinstance(entry, tuple) and len(entry) == 2):
                continue
            fname, ftype = entry
            if not isinstance(ftype, str):
                continue
            if ftype.startswith("list:msg:"):
                inner = ftype[9:]
                if _tail_elides(inner, classes):
                    f(msg, f"{msg.name}.{fname}: skew-tolerant {inner} "
                           "inside a list — elements have no fixed length, "
                           "the decode misaligns")
            elif ftype.startswith("msg:"):
                inner = ftype[4:]
                if _tail_elides(inner, classes) and i != len(fields) - 1:
                    f(msg, f"{msg.name}.{fname}: skew-tolerant {inner} "
                           "nested non-terminally — its optional tail "
                           "elides, misaligning every following field")
        # contract-method overrides
        for mname, line in msg.overrides:
            f(msg, f"{msg.name}.{mname}: overriding {mname} breaks the "
                   "codec's constructor-default/decode-fill contract — "
                   "extend FIELDS + SKEW_TOLERANT_FROM instead", line)
    return findings
