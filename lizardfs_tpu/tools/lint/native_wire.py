"""``native-wire``: the Python<->C++ wire contract, cross-checked
without compiling anything.

The native sources speak the same frames as ``proto/messages.py`` but
declare their half of the contract as hand-written constants, layout
comments, and byte offsets. Drift is silent until a mixed deployment
corrupts a decode — and the LZ_NO_UDS spelling-parity inversion (PR 9)
showed even the env-gate half can invert between languages. This
checker parses the C sources textually and pins four contracts:

* **message-type constants** — every ``kType<Suffix> = N`` /
  ``k<ClassName> = N`` in ``native/`` must name a catalog ``MSG_TYPE``
  (value match), the named Python class must match the constant's
  spelling (``kTypeWriteBulkPart`` -> a class ending ``WriteBulkPart``,
  ``kCltomaRegister`` -> exactly ``CltomaRegister``), and the same
  constant name must agree across native files;
* **frame layouts** — every message a native file speaks (defines a
  type constant for) must carry a machine-readable layout declaration
  ``//   <ClassName>(<type>): field[:ty] field[:ty] ...`` (continuation
  comment lines allowed), and the declaration must match the catalog:
  right MSG_TYPE, field names a prefix of FIELDS in order (trailing
  skew-tolerant fields may be omitted — old native peers legally elide
  them), scalar type annotations exact;
* **status codes** — ``st<NAME> = N`` / ``kStatus<CamelName> = N``
  must match ``proto/status.py`` (name + value);
* **proto version + kill-switch spelling parity** — ``kProtoVersion``
  equals ``framing.PROTO_VERSION``, and any ``getenv("LZ_<switch>")``
  of an inventoried boolean switch must spell out all four documented
  off values (0/off/false/no) in the enclosing function — the standing
  gate generalizing the LZ_NO_UDS fix.
"""

from __future__ import annotations

import ast
import glob
import os
import re

from lizardfs_tpu.tools.lint.engine import Finding, SourceFile, native_sources
from lizardfs_tpu.tools.lint import killswitch
from lizardfs_tpu.tools.lint.wire import _parse_catalog

RULE = "native-wire"

_SCALAR_SIZES = {"u8": 1, "u16": 2, "u32": 4, "u64": 8, "bool": 1}

_CONST_RE = re.compile(
    r"^\s*(?:constexpr\s+)?(?:uint(?:8|16|32|64)_t|int|unsigned)?\s*"
    r"(k[A-Z]\w+|st[A-Z_]\w*)\s*=\s*(\d+)\s*[,;]"
)
_LAYOUT_HEAD_RE = re.compile(
    r"^\s*//\s{0,3}([A-Z]\w+)\s*\((\d+)\):\s*(.*)$"
)
_LAYOUT_CONT_RE = re.compile(r"^\s*//\s{2,}(\S.*)$")
_FIELD_TOKEN_RE = re.compile(r"^([a-z_][a-z0-9_]*)(?::([a-zA-Z0-9:]+))?$")
# role prefixes that make a bare k<ClassName> constant a wire constant
# even when the catalog no longer has the class (that is the drift the
# rule exists to catch, not a reason to skip the check)
_ROLE_PREFIX_RE = re.compile(
    r"^k(?:Cltoma|Matocl|Cltocs|Cstocl|Cstoma|Matocs|Mltoma|Matoml|"
    r"Tstoma|Matots)[A-Z]"
)
_GETENV_RE = re.compile(r'getenv\(\s*"(LZ_[A-Z0-9_]*)"')
_OFF_SPELLINGS = ('"0"', '"off"', '"false"', '"no"')


def extra_inputs(cfg) -> list[str]:
    out = native_sources(cfg.native_dir)
    for p in (cfg.messages_path, getattr(cfg, "status_path", None),
              getattr(cfg, "framing_path", None)):
        if p:
            out.append(p)
    return out


class _NativeFile:
    def __init__(self, rel: str, text: str):
        self.rel = rel
        self.lines = text.splitlines()
        # constant name -> (value, line)
        self.consts: dict[str, tuple[int, int]] = {}
        # catalog-class layout declarations:
        # name -> (declared type, [(field, ty|None)], line)
        self.layouts: dict[str, tuple[int, list, int]] = {}
        self.getenvs: list[tuple[int, str]] = []
        self._parse()

    def _parse(self):
        cur: list | None = None  # tokens of the open layout declaration
        for i, line in enumerate(self.lines, start=1):
            m = _LAYOUT_HEAD_RE.match(line)
            if m:
                name, mtype, rest = m.group(1), int(m.group(2)), m.group(3)
                tokens: list = []
                cur = tokens
                self.layouts[name] = (mtype, tokens, i)
                self._eat_tokens(rest, tokens)
            elif cur is not None:
                mc = _LAYOUT_CONT_RE.match(line)
                if mc and all(
                    _FIELD_TOKEN_RE.match(t) for t in mc.group(1).split()
                ):
                    self._eat_tokens(mc.group(1), cur)
                else:
                    cur = None
            mconst = _CONST_RE.match(line)
            if mconst:
                self.consts[mconst.group(1)] = (int(mconst.group(2)), i)
            for mg in _GETENV_RE.finditer(line):
                self.getenvs.append((i, mg.group(1)))

    @staticmethod
    def _eat_tokens(text: str, tokens: list) -> None:
        for tok in text.split():
            m = _FIELD_TOKEN_RE.match(tok)
            if m is None:
                tokens.append((None, tok))  # opaque token: ends checking
                return
            tokens.append((m.group(1), m.group(2)))


def _enclosing_block(lines: list[str], idx: int, cap: int = 400) -> str:
    """Text of the brace-delimited block enclosing ``lines[idx]`` — the
    C function body the getenv sits in (approximate: brace counting,
    good enough for the tree's formatting; capped so a pathological
    file cannot make this quadratic). Falls back to a +/-12-line window
    when no enclosing brace is found."""
    depth = 0
    start = None
    for i in range(idx, max(-1, idx - cap), -1):
        # walk each line right-to-left so a '{' closed on the same line
        # doesn't count as the opener
        for ch in reversed(lines[i]):
            if ch == "}":
                depth += 1
            elif ch == "{":
                if depth == 0:
                    start = i
                    break
                depth -= 1
        if start is not None:
            break
    if start is None:
        lo, hi = max(0, idx - 12), min(len(lines), idx + 13)
        return "\n".join(lines[lo:hi])
    depth = 0
    end = min(len(lines), start + cap)
    for i in range(start, end):
        for ch in lines[i]:
            if ch == "{":
                depth += 1
            elif ch == "}":
                depth -= 1
                if depth == 0:
                    return "\n".join(lines[start:i + 1])
    return "\n".join(lines[start:end])


def _camel_to_upper_snake(name: str) -> str:
    return re.sub(r"(?<!^)(?=[A-Z])", "_", name).upper()


def _parse_int_consts(path: str) -> dict[str, int]:
    """Module-level ``NAME = <int>`` assignments, without importing."""
    out: dict[str, int] = {}
    try:
        with open(path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read())
    except (OSError, SyntaxError):
        return out
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, int)
            and not isinstance(node.value.value, bool)
        ):
            out[node.targets[0].id] = node.value.value
    return out


def check_global(cfg, collections: dict) -> list[Finding]:
    native_dir = cfg.native_dir
    if not native_dir or not os.path.isdir(native_dir):
        return []
    findings: list[Finding] = []

    # ---- the Python half --------------------------------------------------
    classes = {}
    if cfg.messages_path and os.path.exists(cfg.messages_path):
        try:
            with open(cfg.messages_path, encoding="utf-8") as fh:
                src = SourceFile(
                    cfg.messages_path,
                    os.path.relpath(cfg.messages_path, cfg.root),
                    fh.read(),
                )
            classes = _parse_catalog(src.tree)
        except (OSError, SyntaxError) as e:
            return [Finding(RULE, "proto/messages.py", 0,
                            f"cannot parse catalog: {e}")]
    by_type = {
        msg.msg_type: msg for msg in classes.values()
        if msg.msg_type is not None
    }
    status_codes = _parse_int_consts(getattr(cfg, "status_path", "") or "")
    framing_consts = _parse_int_consts(getattr(cfg, "framing_path", "") or "")
    switches = getattr(cfg, "ks_switches", killswitch.SWITCHES)

    # ---- the C half -------------------------------------------------------
    nfiles: list[_NativeFile] = []
    for path in native_sources(native_dir):
        rel = os.path.relpath(path, cfg.root)
        try:
            with open(path, encoding="utf-8", errors="replace") as fh:
                nfiles.append(_NativeFile(rel, fh.read()))
        except OSError:
            continue

    # message-type constants: value + spelling + cross-file agreement
    seen_consts: dict[str, tuple[int, str, int]] = {}
    spoken: dict[str, dict[int, int]] = {}  # rel -> {msg type: line}
    for nf in nfiles:
        for cname, (value, line) in nf.consts.items():
            if cname.startswith("st") or cname.startswith("kStatus"):
                continue
            if not (
                cname.startswith("kType")
                or _ROLE_PREFIX_RE.match(cname)
                or (cname.startswith("k") and cname[1:] in classes)
            ):
                # kBlockSize/kChunkSize and friends: not wire types
                continue
            prev = seen_consts.get(cname)
            if prev is not None and prev[0] != value:
                findings.append(Finding(
                    RULE, nf.rel, line,
                    f"{cname} = {value} disagrees with {prev[1]}:{prev[2]} "
                    f"({cname} = {prev[0]}) — one of them frames garbage",
                ))
            seen_consts.setdefault(cname, (value, nf.rel, line))
            msg = by_type.get(value)
            if msg is None:
                findings.append(Finding(
                    RULE, nf.rel, line,
                    f"{cname} = {value}: no catalog message declares "
                    f"MSG_TYPE {value} — the native side speaks a frame "
                    "Python cannot parse",
                ))
                continue
            suffix = cname[5:] if cname.startswith("kType") else cname[1:]
            if not (msg.name == suffix or (
                cname.startswith("kType") and msg.name.endswith(suffix)
            )):
                findings.append(Finding(
                    RULE, nf.rel, line,
                    f"{cname} = {value} but MSG_TYPE {value} belongs to "
                    f"{msg.name} — constant name and catalog class "
                    "disagree; rename one",
                ))
            spoken.setdefault(nf.rel, {}).setdefault(value, line)

    # layout declarations: well-formed, catalog-true, and present for
    # every message a file defines a type constant for
    declared: dict[str, set[int]] = {}  # rel -> types with a declaration
    for nf in nfiles:
        for name, (mtype, tokens, line) in nf.layouts.items():
            msg = classes.get(name)
            if msg is None:
                findings.append(Finding(
                    RULE, nf.rel, line,
                    f"layout comment for {name} ({mtype}): no such class "
                    "in the catalog",
                ))
                continue
            declared.setdefault(nf.rel, set()).add(mtype)
            if msg.msg_type != mtype:
                findings.append(Finding(
                    RULE, nf.rel, line,
                    f"layout comment says {name} ({mtype}) but the catalog "
                    f"declares MSG_TYPE {msg.msg_type}",
                ))
            fields = [
                e for e in (msg.fields or [])
                if isinstance(e, tuple) and len(e) == 2
            ]
            for i, (fname, fty) in enumerate(tokens):
                if fname is None:
                    break  # opaque token: prefix checked up to here
                if i >= len(fields):
                    findings.append(Finding(
                        RULE, nf.rel, line,
                        f"layout {name}: declares field {fname!r} past the "
                        f"catalog's {len(fields)} fields",
                    ))
                    break
                cat_name, cat_ty = fields[i]
                if fname != cat_name:
                    findings.append(Finding(
                        RULE, nf.rel, line,
                        f"layout {name}: field {i} is {fname!r}, catalog "
                        f"says {cat_name!r} — the byte offsets that follow "
                        "are wrong on one side",
                    ))
                    break
                if fty is not None and fty != cat_ty:
                    findings.append(Finding(
                        RULE, nf.rel, line,
                        f"layout {name}.{fname}: declared :{fty}, catalog "
                        f"says :{cat_ty}",
                    ))
            # every NON-skew field must be covered (a declaration may
            # stop at an opaque token or the skew boundary, not before)
            ncovered = next(
                (i for i, t in enumerate(tokens) if t[0] is None),
                len(tokens),
            )
            required = min(
                msg.skew if isinstance(msg.skew, int) else len(fields),
                len(fields),
            )
            if ncovered < required:
                findings.append(Finding(
                    RULE, nf.rel, line,
                    f"layout {name}: declares only {ncovered} of "
                    f"{required} required fields — partial declarations "
                    "hide drift in the undeclared tail",
                ))
    all_declared: set[int] = set()
    for types in declared.values():
        all_declared |= types
    for nf in nfiles:
        for t, line in sorted(spoken.get(nf.rel, {}).items()):
            if t not in all_declared and t in by_type:
                findings.append(Finding(
                    RULE, nf.rel, line,
                    f"message type {t} ({by_type[t].name}) is spoken here "
                    "but no native file declares its layout — add the "
                    "machine-checkable `//   Name(type): field:ty ...` "
                    "comment next to the framing code",
                ))

    # status constants
    for nf in nfiles:
        for cname, (value, line) in nf.consts.items():
            if cname.startswith("st"):
                pyname = cname[2:]
            elif cname.startswith("kStatus"):
                pyname = _camel_to_upper_snake(cname[7:])
            else:
                continue
            if not status_codes:
                continue
            expect = status_codes.get(pyname)
            if expect is None:
                findings.append(Finding(
                    RULE, nf.rel, line,
                    f"{cname}: no status named {pyname} in proto/status.py",
                ))
            elif expect != value:
                findings.append(Finding(
                    RULE, nf.rel, line,
                    f"{cname} = {value} but proto/status.py says "
                    f"{pyname} = {expect}",
                ))

    # proto version
    py_ver = framing_consts.get("PROTO_VERSION")
    for nf in nfiles:
        kv = nf.consts.get("kProtoVersion")
        if kv is not None and py_ver is not None and kv[0] != py_ver:
            findings.append(Finding(
                RULE, nf.rel, kv[1],
                f"kProtoVersion = {kv[0]} but framing.PROTO_VERSION = "
                f"{py_ver}",
            ))

    # kill-switch spelling parity at native getenv sites
    for nf in nfiles:
        for line, var in nf.getenvs:
            if var not in switches:
                continue  # inventory membership is the kill-switch rule
            window = _enclosing_block(nf.lines, line - 1)
            missing = [s for s in _OFF_SPELLINGS if s not in window]
            if missing:
                findings.append(Finding(
                    RULE, nf.rel, line,
                    f'getenv("{var}"): boolean switch read without the '
                    f"full off-spelling set nearby (missing "
                    f"{', '.join(missing)}) — C side must honor the same "
                    "0/off/false/no contract as constants.env_flag or the "
                    "two languages invert on the same deployment",
                ))
    return findings
