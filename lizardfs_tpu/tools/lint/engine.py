"""Shared lint plumbing: parsed-file model, waivers, cache, runner.

The engine owns everything rule-agnostic so a checker is just "walk
this parsed file (or the whole repo context) and yield findings":

* :class:`SourceFile` — one parse per file per run, shared by every
  per-file checker (the AST is the expensive part at 60+ files).
* Waivers — ``# lint: waive(<rule>): <reason>`` on the finding's line
  or the line directly above. A waiver must carry a reason, is counted
  in the report, and MUST match a finding: stale waivers are reported
  as findings themselves (rule ``stale-waiver``), so suppressions
  cannot quietly outlive the code they excused.
* Per-file caching — keyed by (content sha1, engine fingerprint);
  editing any file under ``tools/lint/`` invalidates the whole cache,
  editing a source file invalidates that file only. A warm hit skips
  the parse AND the tokenize: findings, waivers, and the global
  checkers' per-file summaries (``collect_file``) all ride the cache
  entry.
* Global-results caching — each ``check_global`` pass's findings are
  cached under a key closing over every input it can read: the scanned
  files' sha1s, the config, and the checker's declared non-Python
  inputs (``extra_inputs(cfg) -> list[str]``). A global checker that
  reads files outside the scanned Python set (native C sources, docs,
  ``tests/``) MUST list them in ``extra_inputs`` or its cached verdict
  goes stale when they change; with them declared, an untouched tree
  skips even the global passes, and an edit to e.g. ``native/wire.h``
  re-runs exactly the passes that read it.
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field

_WAIVE_RE = re.compile(
    r"#\s*lint:\s*waive\(([a-z0-9_*-]+)\)\s*:\s*(\S.*?)\s*$"
)


@dataclass
class Finding:
    rule: str
    path: str  # repo-relative where possible
    line: int
    message: str
    waived: bool = False
    waive_reason: str = ""

    def render(self) -> str:
        tag = " [waived]" if self.waived else ""
        return f"{self.path}:{self.line}: [{self.rule}]{tag} {self.message}"


@dataclass
class Waiver:
    rule: str
    path: str
    line: int  # line the comment sits on
    reason: str
    used: bool = False


class SourceFile:
    """A parsed Python file plus its waiver comments."""

    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self.waivers: list[Waiver] = []
        # real COMMENT tokens only: the waiver pattern quoted inside a
        # docstring (e.g. this engine's own docs) is not a waiver
        try:
            tokens = tokenize.generate_tokens(io.StringIO(text).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _WAIVE_RE.search(tok.string)
                if m:
                    self.waivers.append(
                        Waiver(m.group(1), rel, tok.start[0], m.group(2))
                    )
        except tokenize.TokenError:
            pass  # ast.parse above succeeded; comments are best-effort

    def sha1(self) -> str:
        return hashlib.sha1(self.text.encode("utf-8")).hexdigest()


@dataclass
class LintConfig:
    """What to lint and where the cross-file anchors live. The defaults
    describe the real tree; tests point the anchors at fixtures."""

    root: str
    paths: list[str] = field(default_factory=list)
    rules: list[str] | None = None  # None = every registered rule
    messages_path: str | None = None  # wire-skew / native-wire catalog
    doc_paths: list[str] = field(default_factory=list)  # kill-switch docs
    tests_dir: str | None = None  # kill-switch equivalence tests
    native_dir: str | None = None  # kill-switch + native-wire C sweep
    metadata_path: str | None = None  # changelog-durability op dispatch
    status_path: str | None = None  # native-wire status codes
    framing_path: str | None = None  # native-wire proto version
    use_cache: bool = True
    cache_path: str | None = None

    @classmethod
    def for_tree(cls, root: str | None = None, **kw) -> "LintConfig":
        if root is None:
            here = os.path.dirname(os.path.abspath(__file__))
            # tools/lint/engine.py -> repo root is 3 levels up from lint/
            root = os.path.dirname(os.path.dirname(os.path.dirname(here)))
        pkg = os.path.join(root, "lizardfs_tpu")
        cfg = cls(
            root=root,
            paths=[pkg],
            messages_path=os.path.join(pkg, "proto", "messages.py"),
            doc_paths=[os.path.join(root, "doc", "operations.md")],
            tests_dir=os.path.join(root, "tests"),
            native_dir=os.path.join(root, "native"),
            metadata_path=os.path.join(pkg, "master", "metadata.py"),
            status_path=os.path.join(pkg, "proto", "status.py"),
            framing_path=os.path.join(pkg, "proto", "framing.py"),
            cache_path=os.path.join(root, ".lint-cache.json"),
        )
        for k, v in kw.items():
            setattr(cfg, k, v)
        return cfg


@dataclass
class LintResult:
    findings: list[Finding]
    waivers: list[Waiver]
    files: int

    @property
    def unwaived(self) -> list[Finding]:
        return [f for f in self.findings if not f.waived]

    @property
    def waived(self) -> list[Finding]:
        return [f for f in self.findings if f.waived]

    def by_rule(self, *, waived: bool | None = None) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            if waived is not None and f.waived is not waived:
                continue
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def render(self) -> str:
        lines = [f.render() for f in self.findings]
        lines.append(
            f"lint: {self.files} files, {len(self.unwaived)} findings, "
            f"{len(self.waived)} waived"
        )
        wr = self.by_rule(waived=True)
        if wr:
            lines.append(
                "waived by rule: "
                + ", ".join(f"{r}={n}" for r, n in sorted(wr.items()))
            )
        return "\n".join(lines)


def _registry():
    # imported lazily: checker modules import Finding from here
    from lizardfs_tpu.tools.lint import (
        awaits,
        changelog,
        killswitch,
        native_wire,
        races,
        telemetry,
        wire,
    )

    return {
        races.RULE: races,
        awaits.RULE: awaits,
        wire.RULE: wire,
        killswitch.RULE: killswitch,
        changelog.RULE: changelog,
        native_wire.RULE: native_wire,
        telemetry.RULE: telemetry,
    }


def all_rules() -> list[str]:
    return sorted(_registry())


def _engine_fingerprint() -> str:
    """sha1 over the lint package's own sources: edit a checker, lose
    the cache."""
    here = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha1()
    for name in sorted(os.listdir(here)):
        if name.endswith(".py"):
            with open(os.path.join(here, name), "rb") as fh:
                h.update(name.encode())
                h.update(fh.read())
    return h.hexdigest()


def native_sources(native_dir: str | None) -> list[str]:
    """The native C surface the cross-language checkers read — ONE
    definition so a checker's sweep and its ``extra_inputs`` cache key
    can never drift apart (a file the sweep reads but the key does not
    hash would serve stale cached verdicts)."""
    import glob

    if not native_dir or not os.path.isdir(native_dir):
        return []
    return sorted(
        glob.glob(os.path.join(native_dir, "*.h"))
        + glob.glob(os.path.join(native_dir, "*.cpp"))
    )


def iter_py_files(paths: list[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return out


def _load_cache_doc(path: str | None) -> dict:
    if not path or not os.path.exists(path):
        return {}
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
        return data.get("entries", {}) if isinstance(data, dict) else {}
    except (OSError, ValueError):
        return {}


def _load_cache(path: str | None, fingerprint: str) -> dict:
    return _load_cache_doc(path).get(fingerprint, {})


def _save_cache(path: str | None, fingerprint: str, files: dict) -> None:
    """MERGE into the cache, keyed by fingerprint: a targeted run
    (`lizardfs-lint one_file.py`, or `--rule X` with its own
    fingerprint) must update only its slice, never clobber the
    full-tree entries the next `make lint` relies on. Bounded to the
    8 most-recently-used fingerprints."""
    if not path:
        return
    entries = _load_cache_doc(path)
    merged = dict(entries.pop(fingerprint, {}))
    merged.update(files)
    entries[fingerprint] = merged  # re-insert: most-recently-used last
    while len(entries) > 8:
        entries.pop(next(iter(entries)))
    try:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump({"entries": entries}, fh)
    except OSError:
        pass  # caching is best-effort; a read-only tree still lints


def run_lint(cfg: LintConfig) -> LintResult:
    registry = _registry()
    rules = cfg.rules if cfg.rules is not None else sorted(registry)
    unknown = [r for r in rules if r not in registry]
    if unknown:
        raise ValueError(f"unknown lint rules: {unknown}")

    fingerprint = _engine_fingerprint() + ":" + ",".join(sorted(rules))
    cache = _load_cache(cfg.cache_path, fingerprint) if cfg.use_cache else {}
    new_cache: dict = {}

    findings: list[Finding] = []
    waivers: list[Waiver] = []
    per_file = [registry[r] for r in rules if hasattr(registry[r], "check_file")]
    collectors = {
        r: registry[r] for r in rules if hasattr(registry[r], "collect_file")
    }
    # rule -> rel -> cacheable per-file summary fed to check_global
    collections: dict[str, dict] = {r: {} for r in collectors}
    nfiles = 0
    for path in iter_py_files(cfg.paths):
        rel = os.path.relpath(path, cfg.root)
        try:
            with open(path, "rb") as fh:
                raw = fh.read()
        except OSError as e:
            findings.append(Finding("parse", rel, 0, str(e)))
            continue
        nfiles += 1
        key = hashlib.sha1(raw).hexdigest()
        cached = cache.get(rel)
        if (
            cached is not None
            and cached.get("sha1") == key
            and set(cached.get("collected", {})) >= set(collections)
        ):
            # warm hit: findings, waivers, AND the global checkers'
            # per-file summaries all ride the entry — the file is
            # neither parsed nor tokenized again
            for rule, line, message in cached["findings"]:
                findings.append(Finding(rule, rel, line, message))
            for rule, line, reason in cached.get("waivers", ()):
                waivers.append(Waiver(rule, rel, line, reason))
            for r in collections:
                collections[r][rel] = cached["collected"][r]
            new_cache[rel] = cached
            continue
        try:
            src = SourceFile(path, rel, raw.decode("utf-8"))
        except (UnicodeDecodeError, SyntaxError) as e:
            findings.append(
                Finding("parse", rel, getattr(e, "lineno", 0) or 0, str(e))
            )
            continue
        waivers.extend(src.waivers)
        file_findings: list[Finding] = []
        for checker in per_file:
            file_findings.extend(checker.check_file(src))
        findings.extend(file_findings)
        collected = {r: c.collect_file(src) for r, c in collectors.items()}
        for r in collections:
            collections[r][rel] = collected[r]
        new_cache[rel] = {
            "sha1": key,
            "findings": [[f.rule, f.line, f.message] for f in file_findings],
            "waivers": [[w.rule, w.line, w.reason] for w in src.waivers],
            "collected": collected,
        }

    # ---- global passes ---------------------------------------------------
    # Cached per rule under a key closing over EVERY input the pass can
    # read: the scanned files (per-file sha1s — collections are derived
    # from them), the config (anchor paths + test overrides), and the
    # checker's declared non-Python inputs (``extra_inputs(cfg)``:
    # native C sources, the ops doc, tests/). Editing native/wire.h
    # therefore invalidates the native-wire entries even though the
    # per-file half of the cache only keys Python content — the
    # staleness class this key exists to kill.
    scan_h = hashlib.sha1()
    for rel in sorted(new_cache):
        scan_h.update(rel.encode())
        scan_h.update(new_cache[rel]["sha1"].encode())
    scan_digest = scan_h.hexdigest()
    cfg_digest = hashlib.sha1(repr(sorted(
        (k, repr(v)) for k, v in vars(cfg).items()
        if k not in ("use_cache", "cache_path")
    )).encode()).hexdigest()
    _ext_memo: dict[str, str] = {}

    def _ext_sha(path: str) -> str:
        h = _ext_memo.get(path)
        if h is None:
            try:
                with open(path, "rb") as fh:
                    h = hashlib.sha1(fh.read()).hexdigest()
            except OSError:
                h = "<missing>"
            _ext_memo[path] = h
        return h

    for rule in rules:
        checker = registry[rule]
        if not hasattr(checker, "check_global"):
            continue
        ext_h = hashlib.sha1()
        for p in (
            checker.extra_inputs(cfg)
            if hasattr(checker, "extra_inputs") else ()
        ):
            ext_h.update(p.encode())
            ext_h.update(_ext_sha(p).encode())
        gkey = "//global/" + rule  # no real rel starts with //
        key = f"{scan_digest}:{cfg_digest}:{ext_h.hexdigest()}"
        cached = cache.get(gkey) if cfg.use_cache else None
        if cached is not None and cached.get("key") == key:
            gf = [
                Finding(r, path, line, message)
                for r, path, line, message in cached["findings"]
            ]
        else:
            gf = checker.check_global(cfg, collections.get(rule, {}))
        findings.extend(gf)
        new_cache[gkey] = {
            "key": key,
            "findings": [
                [f.rule, f.path, f.line, f.message] for f in gf
            ],
        }

    # ---- waiver matching -------------------------------------------------
    # a waiver covers findings of its rule on its own line or the line
    # below (comment-above style for statements that don't fit inline)
    wmap: dict[tuple[str, str, int], Waiver] = {}
    for w in waivers:
        wmap[(w.rule, w.path, w.line)] = w
    for f in findings:
        w = wmap.get((f.rule, f.path, f.line)) or wmap.get(
            (f.rule, f.path, f.line - 1)
        )
        if w is not None:
            f.waived = True
            f.waive_reason = w.reason
            w.used = True
    for w in waivers:
        if not w.used and (cfg.rules is None or w.rule in rules):
            findings.append(
                Finding(
                    "stale-waiver",
                    w.path,
                    w.line,
                    f"waiver for [{w.rule}] matches no finding — remove it "
                    f"(reason was: {w.reason})",
                )
            )

    if cfg.use_cache:
        _save_cache(cfg.cache_path, fingerprint, new_cache)

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintResult(findings=findings, waivers=waivers, files=nfiles)
