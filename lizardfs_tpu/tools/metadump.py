"""`lizardfs-metadump` — dump a metadata image as readable text.

The mfsmetadump analog (reference: src/metadump/mfsmetadump.cc).

    python -m lizardfs_tpu.tools.metadump /path/to/data-dir
"""

from __future__ import annotations

import argparse
import sys

from lizardfs_tpu.core import geometry
from lizardfs_tpu.master.changelog import load_image
from lizardfs_tpu.master.metadata import MetadataStore

TYPE_NAMES = {1: "file", 2: "dir", 3: "symlink"}


def dump(data_dir: str, out=None) -> int:
    out = out if out is not None else sys.stdout  # bind at call time
    loaded = load_image(data_dir)
    if loaded is None:
        print(f"no metadata image in {data_dir}", file=sys.stderr)
        return 1
    version, doc = loaded
    store = MetadataStore()
    store.load_sections(doc)
    fs = store.fs
    print(f"# metadata version {version}", file=out)
    print(f"# checksum {store.checksum()}", file=out)
    print(f"# {len(fs.nodes)} inodes, {len(store.registry.chunks)} chunks,"
          f" {len(fs.trash)} trashed", file=out)
    print("\n[nodes]", file=out)

    def walk(inode: int, path: str):
        n = fs.nodes[inode]
        kind = TYPE_NAMES.get(n.ftype, "?")
        extra = ""
        if n.ftype == 1:
            extra = f" length={n.length} goal={n.goal} chunks={[hex(c) for c in n.chunks]}"
        elif n.ftype == 3:
            extra = f" -> {n.symlink_target}"
        print(
            f"{n.inode:>8d} {kind:<7s} mode={n.mode:o} uid={n.uid} gid={n.gid}"
            f"{extra}  {path}", file=out,
        )
        if n.ftype == 2:
            for name, child in sorted(n.children.items()):
                walk(child, f"{path}{name}" + ("/" if fs.nodes[child].ftype == 2 else ""))

    walk(1, "/")
    print("\n[chunks]", file=out)
    for c in sorted(store.registry.chunks.values(), key=lambda c: c.chunk_id):
        t = geometry.SliceType(c.slice_type)
        print(
            f"{c.chunk_id:016X} v{c.version} {t.to_string()} copies={c.copies}"
            f" refs={c.refcount} goal={c.goal_id}", file=out,
        )
    print("\n[trash]", file=out)
    for inode, (name, expires, parent) in sorted(fs.trash.items()):
        print(f"{inode:>8d} expires={expires} parent={parent} {name}", file=out)
    if store.quotas.entries:
        print("\n[quotas]", file=out)
        for (kind, oid), e in sorted(store.quotas.entries.items()):
            print(f"{kind}:{oid} {e.to_dict()}", file=out)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="lizardfs-metadump", description=__doc__)
    p.add_argument("data_dir")
    args = p.parse_args(argv)
    return dump(args.data_dir)


if __name__ == "__main__":
    sys.exit(main())
