"""Chaos harness: seeded fault schedules against REAL process clusters.

    python -m lizardfs_tpu.tools.chaos --schedule bitflip-read --seed 42
    python -m lizardfs_tpu.tools.chaos --all --seeds 1,2,3

Each schedule boots a multi-process cluster (master [+ shadow] + N
chunkservers as subprocesses — the reference's system-test tier,
tests/tools/lizardfs.sh), injects faults mid-traffic (SIGKILL, rules
armed over the admin channel into runtime/faults.py, frame partitions),
and asserts the standing invariants:

  * byte identity — every read returns exactly what was written;
  * bounded time — the whole schedule completes inside its budget
    (a wedged session is a failure, not a hang);
  * rebuild convergence — injected damage drains through the
    RebuildEngine;
  * observability — health/`faults` output NAMES the injected fault.

Determinism: the seed steers every choice (victim selection, kill
timing, fault-rule seeds) through one ``random.Random(seed)``, and the
armed rules' own draws are seeded server-side, so a failing run replays
exactly:  the driver prints the seed + replay command on failure.

Schedules:
  kill-write     SIGKILL a chunkserver mid-windowed-write
  bitflip-read   flip a stored ec(3,2) part bit under a live read
                 (client CRC-rejects, decodes, reports; master rebuilds)
  stall-acks     delay write acks on one chunkserver (adaptive window
                 back-pressure; no wedged sessions)
  shadow-stale   partition the chunkserver->shadow mirror plane so the
                 shadow serves stale locates; clients recover through
                 the primary
  s3-multipart   SIGKILL a chunkserver mid-multipart-upload; the S3
                 gateway completes byte-identically or fails cleanly
                 (no torn object visible to GET)
  noisy-neighbor one tenant floods the master's locate plane while a
                 victim tenant keeps reading: fair-share admission
                 sheds ONLY the abuser (BUSY, retried — never errored),
                 the victim's p99 and goodput hold within bounds, and
                 health/metrics NAME the throttled tenant
  hot-spot       one file goes viral: the heat loop goal-boosts the hot
                 chunk (real extra replicas via the RebuildEngine),
                 read p99 holds through the storm with byte identity,
                 and demotion lands once the heat decays
  kill-primary   SIGKILL the ACTIVE master of an elected master+shadow+
                 metalogger quorum with a windowed ec(8,4) write stream,
                 a rebuild, and a multipart upload all in flight: the
                 survivor SELF-promotes (no operator), chunkservers and
                 clients converge on it, zero acknowledged writes are
                 lost, and the detect->elect->promote->first-acked-write
                 outage is measured and bounded (the
                 cluster_failover_rto_s bench fiducial shares this drill)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import random
import signal
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# per-schedule wall-clock budget: "bounded-time completion" is an
# asserted invariant, not a hope
BUDGET_S = 180.0


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


async def admin(port: int, command: str, payload: str = "{}"):
    from lizardfs_tpu.proto import framing
    from lizardfs_tpu.proto import messages as m

    r, w = await asyncio.wait_for(
        asyncio.open_connection("127.0.0.1", port), 5.0
    )
    try:
        if command == "info":
            await framing.send_message(w, m.AdminInfo(req_id=1))
        else:
            await framing.send_message(
                w, m.AdminCommand(req_id=1, command=command, json=payload)
            )
        return await framing.read_message(r)
    finally:
        w.close()


class ChaosCluster:
    """Master (+ optional shadow) + N chunkservers as subprocesses.

    Chunkservers run with NATIVE_DATA_PLANE=false: fault rules armed
    over the admin channel mid-run must bite, and the C++ plane is not
    instrumentable (the same stand-down the servers apply themselves
    when rules are armed at startup)."""

    def __init__(self, tmp: str, n_cs: int = 4, shadow: bool = False,
                 qos_cfg: str | None = None, ha: bool = False):
        self.tmp = tmp
        self.n_cs = n_cs
        # ha: full autopilot quorum — master + shadow masters running
        # FailoverControllers plus a vote-only metalogger, all wired
        # through ELECTION_* config. Whoever wins the boot election is
        # the active; use active_master_port() to find it.
        self.ha = ha
        self.want_shadow = shadow or ha
        # JSON QoS config (runtime/qos.py parse_config schema): written
        # to disk and wired as the master's QOS_CFG
        self.qos_cfg = qos_cfg
        self.master_port = _free_port()
        self.shadow_port = _free_port() if self.want_shadow else None
        self.cs_ports: list[int] = []
        self.procs: dict[str, subprocess.Popen] = {}

    def _spawn(self, name: str, module: str, cfg_text: str) -> None:
        cfg = os.path.join(self.tmp, f"{name}.cfg")
        with open(cfg, "w") as f:
            f.write(cfg_text)
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
                   PALLAS_AXON_POOL_IPS="")
        env.pop("LZ_FAULTS", None)  # schedules arm rules explicitly
        self.procs[name] = subprocess.Popen(
            [sys.executable, "-m", module, cfg],
            stdout=open(os.path.join(self.tmp, f"{name}.log"), "wb"),
            stderr=subprocess.STDOUT, env=env,
        )

    def _ha_cfg(self, node_id: str) -> str:
        """ELECTION_*/MASTER_PEERS lines for one quorum member (na =
        the boot master, nb = the boot shadow, nw = the metalogger)."""
        peers = ",".join(
            f"{nid}=127.0.0.1:{port}"
            for nid, port in self.election_ports.items() if nid != node_id
        )
        return (
            f"ELECTION_ID = {node_id}\n"
            f"ELECTION_LISTEN = 127.0.0.1:{self.election_ports[node_id]}\n"
            f"ELECTION_PEERS = {peers}\n"
            f"MASTER_PEERS = na=127.0.0.1:{self.master_port},"
            f"nb=127.0.0.1:{self.shadow_port}\n"
            # RTO knobs: roomy enough that a loaded CI box's scheduling
            # hiccups don't trigger spurious elections mid-drill
            "ELECTION_TIMEOUT_MIN = 0.3\n"
            "ELECTION_TIMEOUT_MAX = 0.6\n"
            "HEARTBEAT_INTERVAL = 0.1\n"
        )

    async def start(self) -> None:
        with open(os.path.join(self.tmp, "goals.cfg"), "w") as f:
            f.write("1 one : _\n5 ec32 : $ec(3,2)\n12 ec84 : $ec(8,4)\n")
        qos_line = ""
        if self.qos_cfg is not None:
            with open(os.path.join(self.tmp, "qos.cfg"), "w") as f:
                f.write(self.qos_cfg)
            qos_line = f"QOS_CFG = {self.tmp}/qos.cfg\n"
        if self.ha:
            self.election_ports = {
                nid: _free_port() for nid in ("na", "nb", "nw")
            }
        self._spawn(
            "master", "lizardfs_tpu.master",
            f"DATA_PATH = {self.tmp}/master\n"
            f"LISTEN_PORT = {self.master_port}\n"
            f"GOALS_CFG = {self.tmp}/goals.cfg\n"
            "HEALTH_INTERVAL = 0.3\n" + qos_line
            + (self._ha_cfg("na") if self.ha else ""),
        )
        await self._wait_port(self.master_port)
        if self.want_shadow:
            self._spawn(
                "shadow", "lizardfs_tpu.master",
                f"DATA_PATH = {self.tmp}/shadow\n"
                f"LISTEN_PORT = {self.shadow_port}\n"
                f"GOALS_CFG = {self.tmp}/goals.cfg\n"
                "PERSONALITY = shadow\n"
                f"ACTIVE_MASTER = 127.0.0.1:{self.master_port}\n"
                "HEALTH_INTERVAL = 0.3\n"
                + (self._ha_cfg("nb") if self.ha else ""),
            )
            await self._wait_port(self.shadow_port)
        if self.ha:
            self._spawn(
                "metalogger", "lizardfs_tpu.metalogger",
                f"DATA_PATH = {self.tmp}/metalogger\n"
                f"MASTER_ADDRS = 127.0.0.1:{self.master_port},"
                f"127.0.0.1:{self.shadow_port}\n"
                "IMAGE_INTERVAL = 5.0\n" + self._ha_cfg("nw"),
            )
            # the boot election must settle before chunkservers spawn:
            # they register with whichever master holds the leadership
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if await self.active_master_port() is not None:
                    break
                await asyncio.sleep(0.2)
            else:
                raise AssertionError("boot election never settled")
        addrs = f"127.0.0.1:{self.master_port}"
        if self.want_shadow:
            addrs += f",127.0.0.1:{self.shadow_port}"
        for i in range(self.n_cs):
            port = _free_port()
            self.cs_ports.append(port)
            self._spawn(
                f"cs{i}", "lizardfs_tpu.chunkserver",
                f"DATA_PATH = {self.tmp}/cs{i}\n"
                f"LISTEN_PORT = {port}\n"
                f"MASTER_ADDRS = {addrs}\n"
                "HEARTBEAT_INTERVAL = 0.3\n"
                "NATIVE_DATA_PLANE = false\n",
            )
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if await self._cs_count() >= self.n_cs:
                return
            await asyncio.sleep(0.1)
        raise AssertionError("chunkservers never registered")

    async def active_master_port(self) -> int | None:
        """The service port of whichever master currently holds the
        leadership (HA topologies only; either may have won)."""
        for port in (self.master_port, self.shadow_port):
            if port is None:
                continue
            try:
                doc = json.loads((await admin(port, "ha")).json)
            except (ConnectionError, OSError, asyncio.TimeoutError):
                continue
            # both conditions: a boot master that just LOST the first
            # election still reports personality=master for a beat
            if doc.get("personality") == "master" \
                    and doc.get("state") == "leader":
                return port
        return None

    async def _cs_count(self) -> int:
        port = self.master_port
        if self.ha:
            port = await self.active_master_port()
            if port is None:
                return 0
        try:
            reply = await admin(port, "info")
            return sum(
                1 for s in json.loads(reply.json)["chunkservers"]
                if s["connected"] and not s.get("mirror")
            )
        except (ConnectionError, OSError):
            return 0

    async def _wait_port(self, port: int, timeout: float = 20.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                _, w = await asyncio.wait_for(
                    asyncio.open_connection("127.0.0.1", port), 2.0
                )
                w.close()
                return
            except (ConnectionError, OSError, asyncio.TimeoutError):
                await asyncio.sleep(0.1)
        raise AssertionError(f"port {port} never came up")

    async def arm(self, port: int, rule: str) -> None:
        reply = await admin(port, "faults-arm", json.dumps({"rule": rule}))
        assert getattr(reply, "status", 1) == 0, f"faults-arm failed: {rule}"

    async def faults(self, port: int) -> dict:
        reply = await admin(port, "faults")
        return json.loads(reply.json)

    def kill9(self, name: str) -> None:
        self.procs[name].send_signal(signal.SIGKILL)
        self.procs[name].wait(timeout=10)

    def stop(self) -> None:
        for p in self.procs.values():
            if p.poll() is None:
                p.terminate()
        for p in self.procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


async def _client(cluster: ChaosCluster, shadow: bool = False,
                  info: str = "chaos"):
    from lizardfs_tpu.client.client import Client

    addrs = [("127.0.0.1", cluster.master_port)]
    if shadow and cluster.shadow_port:
        addrs.append(("127.0.0.1", cluster.shadow_port))
    c = Client(*addrs[0], wave_timeout=0.3, master_addrs=addrs)
    # lint: waive(unbounded-await): delegates to Client.connect — dials via the 5 s-bounded RpcConnection.connect and a 30 s-capped register RPC
    await c.connect(info=info)
    return c


async def _wait_rebuilt(cluster: ChaosCluster, min_completed: int = 1,
                        timeout: float = 60.0) -> dict:
    """Rebuild convergence invariant: the engine completed >= N
    rebuilds and nothing is left in flight."""
    deadline = time.monotonic() + timeout
    doc: dict = {}
    while time.monotonic() < deadline:
        reply = await admin(cluster.master_port, "rebuild-status")
        doc = json.loads(reply.json)
        if (
            doc.get("completed", 0) >= min_completed
            and not doc.get("active")
        ):
            return doc
        await asyncio.sleep(0.3)
    raise AssertionError(f"rebuild never converged: {doc}")


async def _wait_redundant(c, inode: int, expected_parts: int,
                          timeout: float = 90.0) -> None:
    """Rebuild convergence via the source of truth: the chunk's locate
    reply lists ``expected_parts`` distinct parts on live servers."""
    deadline = time.monotonic() + timeout
    seen: set = set()
    while time.monotonic() < deadline:
        loc = await c.chunk_info(inode, 0)
        seen = {l.part_id for l in loc.locations}
        if len(seen) >= expected_parts:
            return
        await asyncio.sleep(0.3)
    raise AssertionError(
        f"redundancy never restored: {len(seen)}/{expected_parts} parts"
    )


def _payload(seed: int, n: int) -> bytes:
    from lizardfs_tpu.utils import data_generator

    return data_generator.generate(seed, n).tobytes()


# --- schedules --------------------------------------------------------------


async def run_kill_write(cluster: ChaosCluster, rng: random.Random,
                         log) -> None:
    """SIGKILL a chunkserver mid-windowed-write: the write completes
    through retries, reads stay byte-identical, rebuild restores
    redundancy."""
    c = await _client(cluster)
    try:
        f = await c.create(1, "victim.bin")
        await c.setgoal(f.inode, 5)  # ec(3,2)
        payload = _payload(rng.randrange(1 << 20), 5 * 2**20 + 333)
        victim = rng.randrange(cluster.n_cs)
        delay = rng.uniform(0.02, 0.25)

        async def killer():
            await asyncio.sleep(delay)
            log(f"  SIGKILL cs{victim} after {delay * 1e3:.0f} ms")
            cluster.kill9(f"cs{victim}")

        kill_task = asyncio.ensure_future(killer())
        await c.write_file(f.inode, payload)
        await kill_task
        c.cache.invalidate(f.inode)
        got = await c.read_file(f.inode)
        assert got == payload, "byte identity after SIGKILL mid-write"
        # rebuild convergence: all 5 ec(3,2) parts live again on the
        # 3 survivors (victim may or may not have held parts — the
        # locate reply, not the engine's counters, is the invariant)
        await _wait_redundant(c, f.inode, expected_parts=5)
    finally:
        await c.close()


async def run_bitflip_read(cluster: ChaosCluster, rng: random.Random,
                           log) -> None:
    """Flip one stored-part bit under a live read: the client
    CRC-rejects the part, recovers the stripe via decode, reports the
    damage, and the master re-queues the part through the
    RebuildEngine."""
    from lizardfs_tpu.runtime import faults as faultsmod

    # sentinel rule in the DRIVER process: never matches (no such
    # site) but sets ACTIVE, standing the client's native fast paths
    # down so the CRC rejection takes the deterministic Python path
    faultsmod.arm("client:__sentinel__ delay=1")
    c = await _client(cluster)
    try:
        f = await c.create(1, "flip.bin")
        await c.setgoal(f.inode, 5)  # ec(3,2)
        payload = _payload(rng.randrange(1 << 20), 3 * 2**20 + 17)
        await c.write_file(f.inode, payload)
        victim = rng.randrange(cluster.n_cs)
        port = cluster.cs_ports[victim]
        await cluster.arm(
            port, "chunkserver:disk_pread flip,limit=1"
        )
        log(f"  armed disk_pread flip on cs{victim}")
        c.cache.invalidate(f.inode)
        got = await c.read_file(f.inode)
        assert got == payload, "byte identity through CRC-reject + decode"
        # the fault actually fired, and the CS's health names it
        doc = await cluster.faults(port)
        assert any(r["fired"] for r in doc["rules"]), doc
        health = json.loads((await admin(port, "health")).json)
        assert "disk_pread" in json.dumps(health.get("faults", {})), health
        # detection -> report -> rebuild: the client told the master,
        # the engine re-replicated the part
        assert c.metrics.counter("damaged_parts_reported").total >= 1
        await _wait_rebuilt(cluster, min_completed=1, timeout=90.0)
        # prometheus surface: the CS exported the labeled fire counter
        prom = json.loads((await admin(port, "metrics-prom")).json)["text"]
        assert 'lizardfs_faults_injected_total{' in prom, "faults counter"
    finally:
        faultsmod.clear()
        await c.close()


async def run_stall_acks(cluster: ChaosCluster, rng: random.Random,
                         log) -> None:
    """Delay write-status acks on one chunkserver: back-pressure must
    slow the windowed write, never wedge it; bytes stay identical."""
    c = await _client(cluster)
    try:
        victim = rng.randrange(cluster.n_cs)
        delay_ms = rng.choice((40, 60, 80))
        await cluster.arm(
            cluster.cs_ports[victim],
            f"chunkserver:frame_send:CstoclWriteStatus delay={delay_ms},p=0.5",
        )
        log(f"  armed {delay_ms} ms ack stall (p=0.5) on cs{victim}")
        f = await c.create(1, "stall.bin")
        await c.setgoal(f.inode, 5)
        payload = _payload(rng.randrange(1 << 20), 4 * 2**20 + 999)
        await c.write_file(f.inode, payload)
        c.cache.invalidate(f.inode)
        got = await c.read_file(f.inode)
        assert got == payload, "byte identity under ack stalls"
        doc = await cluster.faults(cluster.cs_ports[victim])
        assert any(r["fired"] for r in doc["rules"]), doc
    finally:
        await c.close()


async def run_shadow_stale(cluster: ChaosCluster, rng: random.Random,
                           log) -> None:
    """Partition the chunkserver->shadow mirror plane: the shadow keeps
    serving (increasingly stale) locates; clients detect missing
    locations and recover through the primary. Reads stay correct the
    whole time."""
    c = await _client(cluster, shadow=True)
    try:
        f = await c.create(1, "stale.bin")
        await c.setgoal(f.inode, 5)
        payload = _payload(rng.randrange(1 << 20), 2 * 2**20 + 5)
        await c.write_file(f.inode, payload)
        # let the shadow catch up + serve a few replica reads
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            await c.getattr(f.inode)
            if c.metrics.counter("shadow_reads").total > 0:
                break
            await asyncio.sleep(0.2)
        assert c.metrics.counter("shadow_reads").total > 0, \
            "shadow never served"
        # partition: every mirror registration/report into the shadow
        # drops at the frame boundary from now on
        await cluster.arm(
            cluster.shadow_port, "master:frame_recv:CstomaRegister drop"
        )
        await cluster.arm(
            cluster.shadow_port, "master:frame_recv:CstomaChunkNew drop"
        )
        log("  mirror plane partitioned at the shadow")
        # new data written AFTER the partition: the shadow's changelog
        # still flows (follow link untouched) but it has no locations
        # for the new chunks — replica locates come back empty and the
        # client re-locates through the primary
        f2 = await c.create(1, "post-partition.bin")
        await c.setgoal(f2.inode, 5)
        payload2 = _payload(rng.randrange(1 << 20), 2 * 2**20 + 77)
        await c.write_file(f2.inode, payload2)
        c.cache.invalidate(f.inode)
        c.cache.invalidate(f2.inode)
        assert await c.read_file(f.inode) == payload, "pre-partition file"
        assert await c.read_file(f2.inode) == payload2, \
            "post-partition file readable despite stale shadow locates"
        doc = await cluster.faults(cluster.shadow_port)
        assert doc["active"], doc
    finally:
        await c.close()


async def run_s3_multipart(cluster: ChaosCluster, rng: random.Random,
                           log) -> None:
    """SIGKILL a chunkserver mid-multipart-upload: the S3 gateway's
    CompleteMultipartUpload either yields the byte-identical object
    (appendchunks assembly over the survivors) or fails cleanly — a
    GET must never observe a torn object."""
    from lizardfs_tpu.s3.client import S3Client, S3Error
    from lizardfs_tpu.s3.server import S3Gateway

    c = await _client(cluster)
    gw = S3Gateway("127.0.0.1", cluster.master_port)
    await gw.start()
    s3 = S3Client("127.0.0.1", gw.port)
    try:
        await s3.create_bucket("chaos")
        # force ec(3,2) on both the bucket AND the gateway's staging
        # area (part/assembly files live there): every object byte must
        # survive one chunkserver loss
        await s3.put_object("chaos", "warmup", b"x")
        for path in ("/chaos", "/.s3mpu"):
            node = await c.resolve(path)
            await c.setgoal(node.inode, 5)
        parts = [
            _payload(rng.randrange(1 << 20), 2 * 2**20 + rng.randrange(999))
            for _ in range(3)
        ]
        upload = await s3.create_multipart("chaos", "obj")
        victim = rng.randrange(cluster.n_cs)
        delay = rng.uniform(0.02, 0.4)

        async def killer():
            await asyncio.sleep(delay)
            log(f"  SIGKILL cs{victim} after {delay * 1e3:.0f} ms")
            cluster.kill9(f"cs{victim}")

        kill_task = asyncio.ensure_future(killer())
        etags: list[tuple[int, str]] = []
        completed = False
        try:
            for i, p in enumerate(parts):
                etags.append(
                    (i + 1,
                     await s3.upload_part("chaos", "obj", upload, i + 1, p))
                )
            await s3.complete_multipart("chaos", "obj", upload, etags)
            completed = True
        except S3Error as e:
            log(f"  upload failed cleanly: HTTP {e.status} {e.code}")
        await kill_task
        if completed:
            got = await s3.get_object("chaos", "obj")
            assert got.body == b"".join(parts), \
                "multipart byte identity after SIGKILL"
            log("  completed; object byte-identical through the loss")
        else:
            # clean failure: the key must not exist at all — a torn
            # object visible to GET is the invariant violation
            try:
                await s3.get_object("chaos", "obj")
                raise AssertionError(
                    "torn object visible after failed complete"
                )
            except S3Error as e:
                assert e.status == 404, f"torn object state: {e}"
    finally:
        await s3.close()
        await gw.stop()
        await c.close()


# QoS config the noisy-neighbor drill arms on its master: the victim
# tenant holds 3x the abuser's weight; 150 locates/s total means the
# flood is shed hard while the victim's paced 20/s sits far under its
# ~112/s contended share
NOISY_QOS_CFG = json.dumps({
    "tenants": {
        "victim": {"weight": 3, "match": ["nn-victim*"], "p99_ms": 1000},
        "abuser": {"weight": 1, "match": ["nn-abuser*"]},
    },
    "rates": {"locate": 150},
    "data_inflight_mb": 32,
})

# the drill's victim-side bounds (asserted, not hoped): paced-locate
# p99 and total wall clock vs the unconstrained ideal
NOISY_VICTIM_P99_MS = 250.0
NOISY_VICTIM_OPS = 120
NOISY_VICTIM_PACE_S = 0.05
NOISY_ABUSER_OPS = 250


async def run_noisy_neighbor(cluster: ChaosCluster, rng: random.Random,
                             log) -> None:
    """One tenant floods the master's locate plane; fair-share
    admission sheds ONLY the abuser (as transient BUSY the client
    retries — never an error), the victim's p99 and goodput hold
    within the configured bounds, and health + Prometheus NAME the
    throttled tenant."""
    victim = await _client(cluster, info="nn-victim")
    abuser = await _client(cluster, info="nn-abuser")
    try:
        fv = await victim.create(1, "victim.bin")
        fa = await abuser.create(1, "abuser.bin")
        pay = _payload(rng.randrange(1 << 20), 128 * 1024 + 7)
        await victim.write_file(fv.inode, pay)
        await abuser.write_file(fa.inode, pay)
        # seed-steered start skew: the flood may lead or trail the
        # victim's first paced op
        skew = rng.uniform(0.0, 0.3)
        lat: list[float] = []

        async def flood():
            await asyncio.sleep(skew)
            for _ in range(NOISY_ABUSER_OPS):
                # every shed is retried inside the client (BUSY
                # backoff); an exception here fails the drill
                await abuser.chunk_info(fa.inode, 0)

        async def paced():
            for _ in range(NOISY_VICTIM_OPS):
                t0 = time.monotonic()
                await victim.chunk_info(fv.inode, 0)
                lat.append(time.monotonic() - t0)
                await asyncio.sleep(NOISY_VICTIM_PACE_S)

        t0 = time.monotonic()
        await asyncio.gather(flood(), paced())
        victim_wall = time.monotonic() - t0
        lat.sort()
        p99_ms = lat[int(len(lat) * 0.99)] * 1e3
        ideal = NOISY_VICTIM_OPS * NOISY_VICTIM_PACE_S
        log(f"  victim p99 {p99_ms:.1f} ms, wall {victim_wall:.1f}s "
            f"(ideal {ideal:.1f}s); abuser busy-waits "
            f"{abuser.metrics.counter('qos_busy_waits').total:.0f}")
        # victim p99 holds within the configured bound
        assert p99_ms <= NOISY_VICTIM_P99_MS, f"victim p99 {p99_ms:.1f}ms"
        # victim goodput within 2x of its unconstrained fair share
        assert victim_wall <= 2.0 * ideal + 2.0, victim_wall
        # the abuser WAS shed and retried through it
        assert abuser.metrics.counter("qos_busy_waits").total > 0, \
            "flood was never shed"
        assert victim.metrics.counter("qos_busy_waits").total == 0, \
            "victim was shed"
        # master side: sheds labeled abuser only; health + prom NAME it
        prom = json.loads(
            (await admin(cluster.master_port, "metrics-prom")).json
        )["text"]
        shed_lines = [
            line for line in prom.splitlines()
            if "lizardfs_qos_shed_total{" in line
        ]
        assert any('tenant="abuser"' in line for line in shed_lines), \
            "shed counter family missing from /metrics"
        assert all('tenant="victim"' not in line for line in shed_lines), \
            f"victim shed on the master: {shed_lines}"
        health = json.loads((await admin(cluster.master_port, "health")).json)
        assert "abuser" in health.get("qos", {}).get("throttled", []), health
        qos_doc = json.loads(
            (await admin(cluster.master_port, "qos")).json
        )
        assert qos_doc["sheds"].get("abuser", {}).get("count", 0) > 0
    finally:
        await victim.close()
        await abuser.close()


# hot-spot drill bounds: the viral file's read p99 must hold through
# the storm (generous — a shared CI box still has to clear it), and the
# boost must land within the storm window
HOTSPOT_READ_P99_MS = 2000.0
HOTSPOT_READERS = 3
HOTSPOT_STORM_S = 30.0
HOTSPOT_DEMOTE_S = 60.0


async def run_hot_spot(cluster: ChaosCluster, rng: random.Random,
                       log) -> None:
    """One file goes viral: a read storm hammers a single goal-1 chunk.
    The heat loop must goal-boost it (extra replicas appear through the
    RebuildEngine), fleet read p99 must hold through the storm with
    every read byte-identical (zero acknowledged-op loss), and once the
    storm ends and heat decays, the demotion must land and shed the
    extra copies."""
    c = await _client(cluster, info="hotspot-writer")
    try:
        f = await c.create(1, "viral.bin")
        payload = _payload(
            rng.randrange(1 << 20), 2 * 2**20 + rng.randrange(4096)
        )
        await c.write_file(f.inode, payload)
        # drill-sized thresholds via the operator path (admin
        # tweaks-set): boost after ~4 MiB of decayed heat, demote
        # under 1 MiB
        for name, value in (("heat_boost_bytes", 4 * 2**20),
                            ("heat_demote_bytes", 1 * 2**20)):
            reply = await admin(
                cluster.master_port, "tweaks-set",
                json.dumps({"name": name, "value": value}),
            )
            assert getattr(reply, "status", 1) == 0, f"tweaks-set {name}"
        lat: list[float] = []
        boosted: dict = {}
        stop = asyncio.Event()

        async def reader(idx: int) -> None:
            rdr = await _client(cluster, info=f"hotspot-r{idx}")
            try:
                while not stop.is_set():
                    t0 = time.monotonic()
                    rdr.cache.invalidate(f.inode)
                    got = await rdr.read_file(f.inode)
                    lat.append(time.monotonic() - t0)
                    # zero acknowledged-op loss: every read returns the
                    # acknowledged bytes, boost/demote never tears one
                    assert got == payload, "viral read byte identity"
            finally:
                await rdr.close()

        async def watch_boost() -> None:
            deadline = time.monotonic() + HOTSPOT_STORM_S
            while time.monotonic() < deadline:
                doc = json.loads(
                    (await admin(cluster.master_port, "heat")).json
                )
                if doc.get("boosted"):
                    boosted.update(doc["boosted"])
                    return
                await asyncio.sleep(0.3)

        readers = [
            asyncio.ensure_future(reader(i))
            for i in range(HOTSPOT_READERS)
        ]
        try:
            await watch_boost()
        finally:
            stop.set()
            await asyncio.gather(*readers)
        assert boosted, "viral chunk never goal-boosted under the storm"
        lat.sort()
        p99_ms = lat[int(len(lat) * 0.99)] * 1e3
        log(f"  boosted {boosted}; {len(lat)} storm reads, "
            f"p99 {p99_ms:.1f} ms")
        assert p99_ms <= HOTSPOT_READ_P99_MS, f"storm read p99 {p99_ms:.1f}ms"
        # the boost is real replication, not bookkeeping: extra copies
        # of the viral chunk appear through the RebuildEngine
        loc = await c.chunk_info(f.inode, 0)
        deadline = time.monotonic() + HOTSPOT_DEMOTE_S
        copies = 1
        while time.monotonic() < deadline:
            loc = await c.chunk_info(f.inode, 0)
            copies = len({(l.addr.host, l.addr.port) for l in loc.locations})
            if copies >= 2:
                break
            await asyncio.sleep(0.3)
        assert copies >= 2, f"boost never materialized ({copies} copies)"
        log(f"  {copies} live copies of the viral chunk")
        # the health rollup NAMES the hot spot while boosted
        health = json.loads(
            (await admin(cluster.master_port, "health")).json
        )
        assert health.get("heat", {}).get("boosted"), health.get("heat")
        # storm over: collapse the decay half-life (operator knob) and
        # the demotion must follow the heat down
        reply = await admin(
            cluster.master_port, "tweaks-set",
            json.dumps({"name": "heat_half_life_s", "value": 1.0}),
        )
        assert getattr(reply, "status", 1) == 0
        deadline = time.monotonic() + HOTSPOT_DEMOTE_S
        while time.monotonic() < deadline:
            doc = json.loads(
                (await admin(cluster.master_port, "heat")).json
            )
            if not doc.get("boosted"):
                break
            await asyncio.sleep(0.5)
        else:
            raise AssertionError("goal demote never landed after the storm")
        log("  demotion landed after the storm")
        # the file is still byte-identical after boost + demote
        c.cache.invalidate(f.inode)
        assert await c.read_file(f.inode) == payload, "post-storm identity"
    finally:
        await c.close()


# kill-primary bound: the whole detect -> elect -> promote -> first-
# acked-write outage, wall clock, on a loaded CI box (the election
# itself settles in ~1s with the drill's 0.3-0.6s timeouts; the rest is
# client redial + re-register + the first windowed write completing)
KILL_PRIMARY_RTO_S = 45.0


async def run_kill_primary(cluster: ChaosCluster, rng: random.Random,
                           log) -> dict:
    """SIGKILL the ACTIVE master of an elected master+shadow+metalogger
    quorum while a windowed ec(8,4) write stream, a rebuild, and a
    multipart upload are ALL in flight. The survivor must SELF-promote
    (no operator command anywhere), chunkservers and clients must
    converge on it, ZERO acknowledged writes may be lost, the fenced
    epoch must be claimed, and the detect->elect->promote->first-acked-
    write outage must fit inside KILL_PRIMARY_RTO_S. Returns the RTO
    doc (the cluster_failover_rto_s bench fiducial reuses this drill).
    """
    from lizardfs_tpu.proto import status as st
    from lizardfs_tpu.s3.client import S3Client, S3Error
    from lizardfs_tpu.s3.server import S3Gateway

    active_port = await cluster.active_master_port()
    assert active_port is not None, "no elected active master"
    active_name = (
        "master" if active_port == cluster.master_port else "shadow"
    )
    survivor_port = (
        cluster.shadow_port if active_name == "master"
        else cluster.master_port
    )
    log(f"  active is the '{active_name}' process (:{active_port})")

    c = await _client(cluster, shadow=True)
    # S3 gateway for the mid-multipart leg: its embedded client must
    # know BOTH masters or it can never converge after the kill
    gw = S3Gateway("127.0.0.1", cluster.master_port)
    gw.client.master_addrs = [
        ("127.0.0.1", cluster.master_port),
        ("127.0.0.1", cluster.shadow_port),
    ]
    await gw.start()
    s3 = S3Client("127.0.0.1", gw.port)
    acked: list[tuple[str, bytes]] = []
    stop_writes = asyncio.Event()
    t_kill = [0.0]
    t_first_ack = [0.0]
    try:
        # --- continuous windowed ec(8,4) write stream ------------------
        async def writer() -> None:
            seq = 0
            while not stop_writes.is_set():
                name = f"wr_{seq}.bin"
                # payload derived from seq, not rng: draws inside a
                # concurrent task would make the schedule's rng stream
                # depend on kill timing and break seeded replay
                payload = _payload(1000 + seq, 192 * 1024 + 7 * seq)
                while not stop_writes.is_set():
                    try:
                        try:
                            f = await c.create(1, name)
                        except st.StatusError as e:
                            # created on the old master before it died:
                            # the name exists, the bytes may not
                            if e.code != st.EEXIST:
                                raise
                            f = await c.lookup(1, name)
                        await c.setgoal(f.inode, 12)  # ec(8,4), windowed
                        await c.write_file(f.inode, payload)
                    except (ConnectionError, OSError, st.StatusError,
                            asyncio.TimeoutError):
                        await asyncio.sleep(0.1)
                        continue
                    # ACKNOWLEDGED: from here on this write may never
                    # be lost, whatever dies
                    acked.append((name, payload))
                    if t_kill[0] and not t_first_ack[0]:
                        t_first_ack[0] = time.monotonic()
                    break
                seq += 1
                await asyncio.sleep(0.05)

        writer_task = asyncio.ensure_future(writer())
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and len(acked) < 3:
            await asyncio.sleep(0.1)
        assert len(acked) >= 3, "baseline write stream never flowed"

        # --- mid-multipart leg: upload part 1 of 3, then the kill ------
        await s3.create_bucket("chaos")
        await s3.put_object("chaos", "warmup", b"x")
        mpu_client_root = await c.resolve("/chaos")
        await c.setgoal(mpu_client_root.inode, 12)
        staging = await c.resolve("/.s3mpu")
        await c.setgoal(staging.inode, 12)
        parts = [
            _payload(rng.randrange(1 << 20), 2 * 2**20 + rng.randrange(999))
            for _ in range(3)
        ]
        upload = await s3.create_multipart("chaos", "obj")
        etags = [(1, await s3.upload_part("chaos", "obj", upload, 1,
                                          parts[0]))]

        # --- mid-rebuild leg: lose a chunkserver just before the kill --
        cs_victim = rng.randrange(cluster.n_cs)
        cluster.kill9(f"cs{cs_victim}")
        log(f"  SIGKILL cs{cs_victim} (rebuild in flight at the kill)")
        await asyncio.sleep(0.3)

        # --- THE KILL --------------------------------------------------
        log(f"  SIGKILL the active '{active_name}' master")
        t_kill[0] = time.monotonic()
        cluster.kill9(active_name)

        # the survivor must promote ITSELF: no admin command from here
        promote_s = None
        deadline = time.monotonic() + KILL_PRIMARY_RTO_S
        while time.monotonic() < deadline:
            try:
                doc = json.loads((await admin(survivor_port, "ha")).json)
            except (ConnectionError, OSError, asyncio.TimeoutError):
                doc = {}
            if doc.get("personality") == "master" \
                    and doc.get("state") == "leader":
                promote_s = time.monotonic() - t_kill[0]
                break
            await asyncio.sleep(0.1)
        assert promote_s is not None, "survivor never self-promoted"
        assert doc.get("promotions", 0) >= 1, doc
        assert doc.get("epoch", 0) >= 1, f"promotion not fenced: {doc}"
        epoch = doc["epoch"]

        # first acknowledged write AFTER the kill: the measured RTO
        while time.monotonic() < deadline and not t_first_ack[0]:
            await asyncio.sleep(0.05)
        assert t_first_ack[0], "write stream never resumed"
        rto_s = t_first_ack[0] - t_kill[0]
        log(f"  promote {promote_s:.2f}s, first acked write {rto_s:.2f}s")
        assert rto_s <= KILL_PRIMARY_RTO_S, f"RTO {rto_s:.1f}s"

        # the in-flight multipart upload completes byte-identically
        # through the promoted master (the gateway's client redials)
        mpu_deadline = time.monotonic() + 60.0
        for part_n in (2, 3):
            while True:
                try:
                    etags.append((part_n, await s3.upload_part(
                        "chaos", "obj", upload, part_n, parts[part_n - 1]
                    )))
                    break
                except S3Error:
                    assert time.monotonic() < mpu_deadline, \
                        "multipart upload never recovered"
                    await asyncio.sleep(0.3)
        while True:
            try:
                await s3.complete_multipart("chaos", "obj", upload, etags)
                break
            except S3Error:
                assert time.monotonic() < mpu_deadline, \
                    "multipart complete never recovered"
                await asyncio.sleep(0.3)
        got = await s3.get_object("chaos", "obj")
        assert got.body == b"".join(parts), \
            "multipart byte identity across the failover"

        # every surviving chunkserver re-registers with the new active
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if await cluster._cs_count() >= cluster.n_cs - 1:
                break
            await asyncio.sleep(0.2)
        assert await cluster._cs_count() >= cluster.n_cs - 1, \
            "chunkservers never converged on the new active"

        # stop the stream; ZERO acknowledged-write loss: every acked
        # file reads back byte-identical through the new active (the
        # cs kill leg makes some of these degraded ec(8,4) reads)
        stop_writes.set()
        await writer_task
        for name, payload in acked:
            node = await c.lookup(1, name)
            c.cache.invalidate(node.inode)
            got = await c.read_file(node.inode)
            assert got == payload, f"acked write {name} lost or torn"
        log(f"  all {len(acked)} acknowledged writes intact")

        # rebuild convergence on the NEW master: the first stream
        # file's redundancy is restored to all 12 ec(8,4) parts
        first = await c.lookup(1, acked[0][0])
        await _wait_redundant(c, first.inode, expected_parts=12,
                              timeout=90.0)

        # observability: the promoted master's health names the HA
        # standing, and the metrics page exports the epoch gauge
        health = json.loads((await admin(survivor_port, "health")).json)
        assert health.get("ha", {}).get("epoch") == epoch, health.get("ha")
        prom = json.loads(
            (await admin(survivor_port, "metrics-prom")).json
        )["text"]
        assert "lizardfs_ha_epoch" in prom, "ha gauges missing"
        return {
            "rto_s": round(rto_s, 2),
            "promote_s": round(promote_s, 2),
            "epoch": epoch,
            "acked_writes": len(acked),
            "lost_writes": 0,
            "rto_budget_s": KILL_PRIMARY_RTO_S,
        }
    finally:
        stop_writes.set()
        await s3.close()
        await gw.stop()
        await c.close()


SCHEDULES = {
    "kill-write": (run_kill_write, dict(n_cs=4)),
    "bitflip-read": (run_bitflip_read, dict(n_cs=3)),
    "stall-acks": (run_stall_acks, dict(n_cs=3)),
    "shadow-stale": (run_shadow_stale, dict(n_cs=3, shadow=True)),
    "s3-multipart": (run_s3_multipart, dict(n_cs=4)),
    "noisy-neighbor": (run_noisy_neighbor,
                       dict(n_cs=2, qos_cfg=NOISY_QOS_CFG)),
    "hot-spot": (run_hot_spot, dict(n_cs=3)),
    "kill-primary": (run_kill_primary, dict(n_cs=5, ha=True)),
}


async def run_schedule(name: str, seed: int, workdir: str | None = None,
                       log=print):
    """Run one schedule at one seed; raises on any invariant violation.
    The whole run sits under the bounded-time budget. Returns whatever
    the schedule returns (kill-primary's RTO doc feeds the
    cluster_failover_rto_s bench fiducial; the rest return None)."""
    fn, topo = SCHEDULES[name]
    rng = random.Random(seed)
    tmp_ctx = (
        tempfile.TemporaryDirectory(prefix=f"chaos-{name}-")
        if workdir is None else None
    )
    tmp = workdir if workdir is not None else tmp_ctx.name
    cluster = ChaosCluster(tmp, **topo)
    try:
        return await asyncio.wait_for(
            _run_body(cluster, fn, rng, log), BUDGET_S
        )
    finally:
        cluster.stop()
        if tmp_ctx is not None:
            tmp_ctx.cleanup()


async def _run_body(cluster, fn, rng, log):
    await cluster.start()
    return await fn(cluster, rng, log)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="chaos", description=__doc__)
    p.add_argument("--schedule", choices=sorted(SCHEDULES),
                   help="one schedule (default: --all)")
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--seeds", default="1,2,3",
                   help="comma-separated seed list for --all runs")
    p.add_argument("--all", action="store_true",
                   help="run every schedule at every seed")
    p.add_argument("--workdir", default=None,
                   help="keep cluster state/logs here instead of a tmpdir")
    args = p.parse_args(argv)

    names = [args.schedule] if args.schedule else sorted(SCHEDULES)
    if args.seed is not None:
        seeds = [args.seed]
    else:
        seeds = [int(s) for s in args.seeds.split(",") if s.strip()]
    failed = 0
    for name in names:
        for seed in seeds:
            t0 = time.monotonic()
            print(f"=== {name} seed={seed}")
            try:
                asyncio.run(run_schedule(name, seed,
                                         workdir=args.workdir))
                print(f"=== {name} seed={seed} PASS "
                      f"({time.monotonic() - t0:.1f}s)")
            except (KeyboardInterrupt, SystemExit):
                raise  # an interrupted matrix must stop, not keep booting
            except BaseException as e:  # noqa: BLE001 — report + replay line
                failed += 1
                print(f"=== {name} seed={seed} FAIL: {e!r}")
                print(f"    replay: python -m lizardfs_tpu.tools.chaos "
                      f"--schedule {name} --seed {seed}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
