"""`lizardfs-metarestore` — offline metadata recovery tool.

The metarestore analog (reference: src/metarestore/main.cc + merger.cc):
merge a metadata image with changelog files (the master's own, a
shadow's, or a metalogger's archive) into a fresh image, so a new master
can start from the most recent durable state.

    python -m lizardfs_tpu.tools.metarestore \
        -d /path/to/data-dir [-o /path/to/output-dir] [--dry-run]

Reads ``metadata.liz`` + every ``changelog*.log`` in the data dir,
replays lines newer than the image, and writes the merged image.
"""

from __future__ import annotations

import argparse
import glob
import os
import sys

from lizardfs_tpu.master.changelog import Changelog, load_image, save_image
from lizardfs_tpu.master.metadata import MetadataStore


def restore(data_dir: str, output_dir: str | None = None,
            dry_run: bool = False, verbose: bool = True) -> tuple[int, int]:
    """Returns (start_version, final_version)."""
    store = MetadataStore()
    start_version = 0
    loaded = load_image(data_dir)
    if loaded is not None:
        start_version, doc = loaded
        store.load_sections(doc)
        if verbose:
            print(f"loaded metadata image at version {start_version}")
    # gather every changelog line from all logs present, sorted by version
    entries: dict[int, dict] = {}
    for path in sorted(glob.glob(os.path.join(data_dir, "changelog*.log"))):
        count = 0
        with open(path, encoding="utf-8") as f:
            for line in f:
                parsed = Changelog.parse_line(line)
                if parsed is None:
                    continue
                version, op = parsed
                if version > start_version:
                    entries.setdefault(version, op)
                    count += 1
        if verbose:
            print(f"{os.path.basename(path)}: {count} applicable entries")
    version = start_version
    for v in sorted(entries):
        if v != version + 1:
            print(
                f"warning: changelog gap at version {v} (expected {version + 1})"
                " — stopping replay here", file=sys.stderr,
            )
            break
        store.apply(entries[v])
        version = v
    if verbose:
        print(f"replayed {version - start_version} entries -> version {version}")
        print(f"checksum: {store.checksum()}")
    if not dry_run:
        out = output_dir or data_dir
        os.makedirs(out, exist_ok=True)
        path = save_image(out, version, store.to_sections())
        if verbose:
            print(f"wrote {path}")
    return start_version, version


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="lizardfs-metarestore", description=__doc__)
    p.add_argument("-d", "--data-dir", required=True)
    p.add_argument("-o", "--output-dir", default=None)
    p.add_argument("--dry-run", action="store_true")
    args = p.parse_args(argv)
    try:
        restore(args.data_dir, args.output_dir, args.dry_run)
    except Exception as e:  # noqa: BLE001
        print(f"error: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
