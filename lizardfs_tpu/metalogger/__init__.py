"""Metalogger: changelog archiver daemon (metadata disaster recovery)."""
