"""Run a metalogger: python -m lizardfs_tpu.metalogger [config]

Config keys: DATA_PATH, MASTER_ADDRS (host:port,host:port,...),
IMAGE_INTERVAL, LOG_LEVEL, and optional quorum membership (the uraft
arbiter analog — the metalogger VOTES in leader elections but can never
lead, so a 2-master + 1-metalogger deployment has a 3-node quorum):
ELECTION_ID, ELECTION_LISTEN (host:port), ELECTION_PEERS
(id=host:port,...), MASTER_PEERS (id=host:port,... — each master
node's SERVICE address, so the archive re-points at whoever leads).
All election wiring is gated on the LZ_HA kill switch.
"""

import asyncio
import logging
import signal
import sys

from lizardfs_tpu import constants
from lizardfs_tpu.metalogger.server import Metalogger
from lizardfs_tpu.runtime.config import Config
from lizardfs_tpu.runtime.daemon import setup_logging


def _hostport(s: str) -> tuple[str, int]:
    host, _, port = s.rpartition(":")
    return host, int(port)


async def _run(cfg: Config) -> None:
    addrs = []
    for item in cfg.get_str("MASTER_ADDRS", "127.0.0.1:9420").split(","):
        host, _, port = item.strip().rpartition(":")
        addrs.append((host, int(port)))
    ml = Metalogger(
        cfg.get_str("DATA_PATH", "./metalogger-data"),
        addrs,
        image_interval=cfg.get_float("IMAGE_INTERVAL", 3600.0, min_value=1.0),
    )
    node = None
    if cfg.get_str("ELECTION_ID", "") and constants.ha_enabled():
        from lizardfs_tpu.ha.election import ElectionNode

        peers = {}
        for item in cfg.get_str("ELECTION_PEERS", "").split(","):
            if item.strip():
                pid, _, addr = item.strip().partition("=")
                peers[pid] = _hostport(addr)
        service_addrs = {}
        for item in cfg.get_str("MASTER_PEERS", "").split(","):
            if item.strip():
                pid, _, addr = item.strip().partition("=")
                service_addrs[pid] = _hostport(addr)
        log = logging.getLogger("metalogger")

        async def on_leader() -> None:
            # unreachable with can_lead=False; a vote-only node never
            # starts an election, so it can never win one
            log.error("vote-only metalogger won an election (bug)")

        async def on_follower(leader_id: str) -> None:
            addr = service_addrs.get(leader_id)
            if addr is not None:
                ml.prefer(addr)

        node = ElectionNode(
            cfg.get_str("ELECTION_ID"),
            _hostport(cfg.get_str("ELECTION_LISTEN", "127.0.0.1:0")),
            peers,
            # the vote carries our archived changelog position: the
            # election's up-to-date rule compares candidates against it
            get_version=lambda: ml.version,
            on_leader=on_leader,
            on_follower=on_follower,
            can_lead=False,
            election_timeout=(
                cfg.get_float("ELECTION_TIMEOUT_MIN", 0.15, min_value=0.01),
                cfg.get_float("ELECTION_TIMEOUT_MAX", 0.30, min_value=0.02),
            ),
            heartbeat_interval=cfg.get_float(
                "HEARTBEAT_INTERVAL", 0.05, min_value=0.005
            ),
        )
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    await ml.start()
    if node is not None:
        await node.start()
    # lint: waive(unbounded-await): the daemon parks here until SIGTERM/SIGINT by design
    await stop.wait()
    if node is not None:
        await node.stop()
    await ml.stop()


def main() -> None:
    cfg = Config(sys.argv[1] if len(sys.argv) > 1 else None)
    setup_logging("metalogger", cfg.get_str("LOG_LEVEL", "INFO"))
    asyncio.run(_run(cfg))


if __name__ == "__main__":
    main()
