"""Run a metalogger: python -m lizardfs_tpu.metalogger [config]

Config keys: DATA_PATH, MASTER_ADDRS (host:port,host:port,...),
IMAGE_INTERVAL, LOG_LEVEL.
"""

import asyncio
import signal
import sys

from lizardfs_tpu.metalogger.server import Metalogger
from lizardfs_tpu.runtime.config import Config
from lizardfs_tpu.runtime.daemon import setup_logging


async def _run(cfg: Config) -> None:
    addrs = []
    for item in cfg.get_str("MASTER_ADDRS", "127.0.0.1:9420").split(","):
        host, _, port = item.strip().rpartition(":")
        addrs.append((host, int(port)))
    ml = Metalogger(
        cfg.get_str("DATA_PATH", "./metalogger-data"),
        addrs,
        image_interval=cfg.get_float("IMAGE_INTERVAL", 3600.0, min_value=1.0),
    )
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    await ml.start()
    # lint: waive(unbounded-await): the daemon parks here until SIGTERM/SIGINT by design
    await stop.wait()
    await ml.stop()


def main() -> None:
    cfg = Config(sys.argv[1] if len(sys.argv) > 1 else None)
    setup_logging("metalogger", cfg.get_str("LOG_LEVEL", "INFO"))
    asyncio.run(_run(cfg))


if __name__ == "__main__":
    main()
