"""Metalogger daemon: archives the master's changelog + metadata images.

The reference's metalogger is the master's changelog-subscriber module
running standalone (reference: src/metalogger/init.h:35-42 — just the
masterconn module). Same here: subscribe to the changelog stream, append
lines to ``changelog_ml.0.log``, periodically snapshot a downloaded
metadata image. Restoring a lost master = metarestore over these files
(tools/metarestore analog).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os

from lizardfs_tpu.master.changelog import Changelog, save_image
from lizardfs_tpu.proto import framing
from lizardfs_tpu.proto import messages as m
from lizardfs_tpu.proto import status as st
from lizardfs_tpu.runtime import retry as retrymod


class Metalogger:
    def __init__(
        self,
        data_dir: str,
        master_addrs: list[tuple[str, int]],
        image_interval: float = 3600.0,
    ):
        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self.master_addrs = master_addrs
        self.image_interval = image_interval
        self.version = 0
        # highest cluster fencing epoch seen in the archived stream
        # (epoch_bump lines / image sections): a master whose reply
        # epoch is BEHIND this is a deposed ex-primary — refuse to
        # follow it, or the archive forks off the elected leader's
        # history. 0 until the first promotion = fencing disengaged.
        self.epoch = 0
        self._log_file = None
        self._task: asyncio.Task | None = None
        self._stopping = asyncio.Event()
        self.log = logging.getLogger("metalogger")
        self._load_state()

    def _load_state(self) -> None:
        """Resume from the last archived line's version."""
        path = os.path.join(self.data_dir, "changelog_ml.0.log")
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                for line in f:
                    parsed = Changelog.parse_line(line)
                    if parsed:
                        self.version = max(self.version, parsed[0])
                        self._note_epoch(parsed[1])

    def _note_epoch(self, line: str) -> None:
        """Fold an archived changelog line into the known cluster epoch
        (substring pre-filter: one json.loads per PROMOTION, not per
        line)."""
        if '"epoch_bump"' not in line:
            return
        try:
            op = json.loads(line)
        except ValueError:
            return
        if op.get("op") == "epoch_bump":
            self.epoch = max(self.epoch, int(op.get("epoch", 0)))

    def _append(self, version: int, line: str) -> None:
        if self._log_file is None:
            self._log_file = open(
                os.path.join(self.data_dir, "changelog_ml.0.log"),
                "a",
                encoding="utf-8",
            )
        self._log_file.write(f"{version}: {line}\n")
        self._log_file.flush()
        self.version = version
        self._note_epoch(line)

    def prefer(self, addr: tuple[str, int]) -> None:
        """Move an address to the front of the follow cycle. The
        election wiring calls this when a leader is named, so the next
        (re)connect lands on the elected master first instead of
        probing deposed peers in config order."""
        if addr in self.master_addrs and self.master_addrs[0] != addr:
            self.master_addrs.remove(addr)
            self.master_addrs.insert(0, addr)

    async def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stopping.set()
        if self._task is not None:
            self._task.cancel()
            await asyncio.gather(self._task, return_exceptions=True)
        if self._log_file is not None:
            self._log_file.close()
            self._log_file = None

    async def _run(self) -> None:
        while not self._stopping.is_set():
            for addr in self.master_addrs:
                try:
                    await self._follow(addr)
                except (ConnectionError, OSError,
                        asyncio.IncompleteReadError, asyncio.TimeoutError):
                    continue
                except asyncio.CancelledError:
                    return
            await asyncio.sleep(1.0)

    async def _follow(self, addr: tuple[str, int]) -> None:
        # dial bound: a blackholed master costs 5 s, not the OS SYN
        # timeout, before the follow loop tries the next address
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(*addr), 5.0
        )
        try:
            await framing.send_message(
                writer, m.MltomaRegister(
                    req_id=1, version_known=self.version,
                    # our replayed epoch: a zombie we dial steps down
                    epoch=self.epoch,
                )
            )
            hello = await framing.read_message(reader)
            if not isinstance(hello, m.MatomlRegisterReply) or hello.status != st.OK:
                raise ConnectionError("not the active master")
            hello_epoch = getattr(hello, "epoch", 0)
            if hello_epoch and hello_epoch < self.epoch:
                # deposed ex-primary: it never applied the epoch_bump we
                # already archived — its lines would fork our archive off
                # the elected leader's history. Try the next address.
                raise ConnectionError(
                    f"refusing stale active (epoch {hello_epoch} < "
                    f"ours {self.epoch})"
                )
            self.epoch = max(self.epoch, hello_epoch)
            self.log.info("following master at %s:%d (v%d)", *addr, hello.version)
            last_image = 0.0
            loop = asyncio.get_running_loop()
            while True:
                if loop.time() - last_image > self.image_interval:
                    await framing.send_message(
                        writer, m.MltomaDownloadImage(req_id=2)
                    )
                    last_image = loop.time()
                msg = await framing.read_message(reader)
                if isinstance(msg, m.MatomlChangelogLine):
                    if msg.version > self.version:
                        self._append(msg.version, msg.line)
                elif isinstance(msg, m.MatomlImage) and msg.status == st.OK:
                    doc = json.loads(msg.image.decode())
                    doc.pop("format", None)  # save_image stamps its own
                    save_image(self.data_dir, msg.version, doc)
                    # the image's epoch section covers promotions whose
                    # epoch_bump line predates our archive window
                    self.epoch = max(self.epoch, int(doc.get("epoch", 0)))
                    self.log.info("archived metadata image v%d", msg.version)
        finally:
            await retrymod.close_writer(writer, swallow_cancel=True)
