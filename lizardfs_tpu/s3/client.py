"""Minimal asyncio S3/HTTP client for the gateway's consumers in-tree:
tests, the chaos harness, and the cluster bench.

Deliberately tiny — one keep-alive connection, no signing (the gateway
does not verify signatures), bytes in / bytes out. Not a general S3
SDK; it speaks exactly the subset the gateway serves.
"""

from __future__ import annotations

import asyncio
import urllib.parse
import xml.etree.ElementTree as ET

from lizardfs_tpu.runtime import retry as retrymod

IO_TIMEOUT_S = 60.0


class S3Error(Exception):
    def __init__(self, status: int, code: str, body: bytes):
        self.status = status
        self.code = code
        self.body = body
        super().__init__(f"HTTP {status} {code}")


class _Response:
    __slots__ = ("status", "headers", "body")

    def __init__(self, status: int, headers: dict, body: bytes):
        self.status = status
        self.headers = headers
        self.body = body

    @property
    def etag(self) -> str:
        return self.headers.get("etag", "").strip('"')


def _error_code(body: bytes) -> str:
    try:
        root = ET.fromstring(body)
        el = root.find("Code")
        return el.text or "" if el is not None else ""
    except ET.ParseError:
        return ""


class S3Client:
    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None

    async def __aenter__(self) -> "S3Client":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    async def close(self) -> None:
        if self._writer is not None:
            await retrymod.close_writer(self._writer, swallow_cancel=True)
            self._reader = self._writer = None

    async def _conn(self):
        if self._writer is None or self._writer.is_closing():
            self._reader, self._writer = await retrymod.bounded_wait(
                asyncio.open_connection(self.host, self.port), 10.0
            )
        return self._reader, self._writer

    async def request(
        self, method: str, path: str, query: dict | None = None,
        body: bytes = b"", ok=(200, 204, 206), headers: dict | None = None,
    ) -> _Response:
        qs = urllib.parse.urlencode(query or {})
        target = urllib.parse.quote(path) + (f"?{qs}" if qs else "")
        req = [f"{method} {target} HTTP/1.1",
               f"Host: {self.host}:{self.port}",
               f"Content-Length: {len(body)}"]
        req += [f"{k}: {v}" for k, v in (headers or {}).items()]
        for attempt in (0, 1):
            reader, writer = await self._conn()
            try:
                writer.write(("\r\n".join(req) + "\r\n\r\n").encode() + body)
                await asyncio.wait_for(writer.drain(), IO_TIMEOUT_S)
                resp = await self._read_response(
                    reader, head_only=(method == "HEAD")
                )
                break
            except (ConnectionError, asyncio.IncompleteReadError, OSError):
                # server closed the keep-alive connection; one redial
                await self.close()
                if attempt:
                    raise
        if resp.status not in ok:
            raise S3Error(resp.status, _error_code(resp.body), resp.body)
        return resp

    async def _read_response(self, reader, head_only: bool) -> _Response:
        line = await retrymod.bounded_wait(reader.readline(), IO_TIMEOUT_S)
        if not line:
            raise ConnectionError("gateway closed the connection")
        parts = line.decode("latin-1").split(" ", 2)
        status = int(parts[1])
        headers: dict[str, str] = {}
        while True:
            hl = await retrymod.bounded_wait(reader.readline(), IO_TIMEOUT_S)
            if hl in (b"\r\n", b"\n", b""):
                break
            name, _, value = hl.decode("latin-1").partition(":")
            headers[name.strip().lower()] = value.strip()
        body = b""
        clen = int(headers.get("content-length", "0") or "0")
        if clen and not head_only:
            body = await retrymod.bounded_wait(
                reader.readexactly(clen), IO_TIMEOUT_S
            )
        return _Response(status, headers, body)

    # --- convenience verbs -------------------------------------------------

    async def create_bucket(self, bucket: str) -> None:
        await self.request("PUT", f"/{bucket}")

    async def delete_bucket(self, bucket: str) -> None:
        await self.request("DELETE", f"/{bucket}")

    async def list_buckets(self) -> list[str]:
        r = await self.request("GET", "/")
        root = ET.fromstring(r.body)
        for el in root.iter():
            el.tag = el.tag.rsplit("}", 1)[-1]
        return [el.text for el in root.iter("Name") if el.text]

    async def put_object(self, bucket: str, key: str,
                         data: bytes) -> _Response:
        return await self.request("PUT", f"/{bucket}/{key}", body=data)

    async def get_object(self, bucket: str, key: str,
                         range_: str | None = None) -> _Response:
        hdrs = {"Range": range_} if range_ else None
        return await self.request("GET", f"/{bucket}/{key}", headers=hdrs)

    async def head_object(self, bucket: str, key: str) -> _Response:
        return await self.request("HEAD", f"/{bucket}/{key}")

    async def delete_object(self, bucket: str, key: str) -> None:
        await self.request("DELETE", f"/{bucket}/{key}")

    async def list_objects(
        self, bucket: str, prefix: str = "", delimiter: str = "",
        max_keys: int = 1000, token: str = "",
    ) -> dict:
        q = {"list-type": "2", "max-keys": str(max_keys)}
        if prefix:
            q["prefix"] = prefix
        if delimiter:
            q["delimiter"] = delimiter
        if token:
            q["continuation-token"] = token
        r = await self.request("GET", f"/{bucket}", query=q)
        root = ET.fromstring(r.body)
        for el in root.iter():
            el.tag = el.tag.rsplit("}", 1)[-1]
        return {
            "keys": [
                {
                    "key": c.findtext("Key"),
                    "size": int(c.findtext("Size") or 0),
                    "etag": (c.findtext("ETag") or "").strip('"'),
                }
                for c in root.iter("Contents")
            ],
            "prefixes": [
                p.findtext("Prefix") for p in root.iter("CommonPrefixes")
            ],
            "truncated": (root.findtext("IsTruncated") == "true"),
            "token": root.findtext("NextContinuationToken") or "",
        }

    async def create_multipart(self, bucket: str, key: str) -> str:
        r = await self.request("POST", f"/{bucket}/{key}",
                               query={"uploads": ""})
        root = ET.fromstring(r.body)
        for el in root.iter():
            el.tag = el.tag.rsplit("}", 1)[-1]
        return root.findtext("UploadId") or ""

    async def upload_part(self, bucket: str, key: str, upload_id: str,
                          part_no: int, data: bytes) -> str:
        r = await self.request(
            "PUT", f"/{bucket}/{key}",
            query={"partNumber": str(part_no), "uploadId": upload_id},
            body=data,
        )
        return r.etag

    async def complete_multipart(
        self, bucket: str, key: str, upload_id: str,
        parts: list[tuple[int, str]],
    ) -> _Response:
        rows = "".join(
            f"<Part><PartNumber>{n}</PartNumber><ETag>\"{e}\"</ETag></Part>"
            for n, e in parts
        )
        body = (f"<CompleteMultipartUpload>{rows}"
                f"</CompleteMultipartUpload>").encode()
        return await self.request(
            "POST", f"/{bucket}/{key}", query={"uploadId": upload_id},
            body=body,
        )

    async def abort_multipart(self, bucket: str, key: str,
                              upload_id: str) -> None:
        await self.request("DELETE", f"/{bucket}/{key}",
                           query={"uploadId": upload_id})

    async def put_lifecycle(self, bucket: str, demote_after_s: float) -> None:
        body = (
            "<LifecycleConfiguration><Rule><Status>Enabled</Status>"
            f"<Transition><Seconds>{demote_after_s:g}</Seconds>"
            "<StorageClass>TAPE</StorageClass></Transition>"
            "</Rule></LifecycleConfiguration>"
        ).encode()
        await self.request("PUT", f"/{bucket}", query={"lifecycle": ""},
                           body=body)

    async def get_lifecycle(self, bucket: str) -> bytes:
        r = await self.request("GET", f"/{bucket}", query={"lifecycle": ""})
        return r.body

    async def metrics(self) -> str:
        r = await self.request("GET", "/metrics")
        return r.body.decode()
