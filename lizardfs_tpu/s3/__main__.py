"""Run the S3 gateway: python -m lizardfs_tpu.s3 MASTER_HOST:PORT
[--host H] [--port N] [--root /path]
"""

import asyncio

from lizardfs_tpu.runtime import faults as faultsmod
from lizardfs_tpu.runtime.daemon import setup_logging
from lizardfs_tpu.s3.server import main


def run() -> None:
    setup_logging("s3")
    faultsmod.set_role("s3")
    asyncio.run(main())


if __name__ == "__main__":
    run()
