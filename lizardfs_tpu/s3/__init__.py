"""S3-compatible object gateway over the POSIX namespace.

Third protocol front door after FUSE and NFS (ROADMAP item 3): an
asyncio HTTP server speaking an S3 REST subset, backed by the same
internal :class:`~lizardfs_tpu.client.client.Client` and master
namespace as the other gateways. Buckets are directories under an
export root, objects are files, multipart uploads assemble through the
master's O(1) ``appendchunks`` chunk-share concat, and per-bucket
lifecycle rules demote cold objects to the ``tapeserver/`` tier with
recall on GET.
"""
