"""Minimal XML helpers for the S3 REST dialect.

Rendering is string-building with escaping (the response schemas are
small and fixed); parsing uses the stdlib ElementTree with namespaces
stripped, because real S3 clients send ``xmlns=`` on every request body
and the gateway must not care.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET

XML_DECL = '<?xml version="1.0" encoding="UTF-8"?>\n'
S3_NS = "http://s3.amazonaws.com/doc/2006-03-01/"


def esc(value) -> str:
    s = str(value)
    return (
        s.replace("&", "&amp;").replace("<", "&lt;").replace(">", "&gt;")
        .replace('"', "&quot;")
    )


def _strip_ns(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


def parse(body: bytes) -> ET.Element | None:
    """Parse an XML body, namespaces stripped; None on malformed
    input (callers answer MalformedXML)."""
    try:
        root = ET.fromstring(body)
    except ET.ParseError:
        return None
    for el in root.iter():
        el.tag = _strip_ns(el.tag)
    return root


def parse_complete_multipart(body: bytes) -> list[tuple[int, str]] | None:
    """CompleteMultipartUpload body -> [(part_number, etag)] sorted by
    part number; None on malformed XML / missing fields."""
    root = parse(body)
    if root is None or root.tag != "CompleteMultipartUpload":
        return None
    parts: list[tuple[int, str]] = []
    for part in root.findall("Part"):
        num = part.findtext("PartNumber")
        etag = part.findtext("ETag") or ""
        try:
            parts.append((int(num), etag.strip().strip('"')))
        except (TypeError, ValueError):
            return None
    parts.sort(key=lambda p: p[0])
    return parts


def parse_lifecycle(body: bytes) -> dict | None:
    """LifecycleConfiguration body -> {"demote_after_s", "enabled"}.

    The S3 schema's ``<Transition><Days>N</Days>`` expresses the
    demote age; a nonstandard ``<Seconds>`` sibling is honored for
    sub-day tuning (tests, aggressive tiering). The first Rule with a
    Transition wins; None = malformed / no transition rule."""
    root = parse(body)
    if root is None or root.tag != "LifecycleConfiguration":
        return None
    for rule in root.findall("Rule"):
        enabled = (rule.findtext("Status") or "Enabled").strip() == "Enabled"
        trans = rule.find("Transition")
        if trans is None:
            continue
        secs = trans.findtext("Seconds")
        days = trans.findtext("Days")
        try:
            if secs is not None:
                after = float(secs)
            elif days is not None:
                after = float(days) * 86400.0
            else:
                return None
        except ValueError:
            return None
        return {"demote_after_s": max(after, 0.0), "enabled": enabled}
    return None


def render_lifecycle(rule: dict) -> str:
    after = float(rule.get("demote_after_s", 0.0))
    status = "Enabled" if rule.get("enabled", True) else "Disabled"
    days = int(after // 86400)
    body = (
        f"{XML_DECL}<LifecycleConfiguration xmlns=\"{S3_NS}\">"
        f"<Rule><ID>tiering</ID><Status>{status}</Status>"
        f"<Transition><Days>{days}</Days><Seconds>{after:g}</Seconds>"
        f"<StorageClass>TAPE</StorageClass></Transition>"
        f"</Rule></LifecycleConfiguration>"
    )
    return body


def error_xml(code: str, message: str, resource: str = "") -> str:
    return (
        f"{XML_DECL}<Error><Code>{esc(code)}</Code>"
        f"<Message>{esc(message)}</Message>"
        f"<Resource>{esc(resource)}</Resource></Error>"
    )
