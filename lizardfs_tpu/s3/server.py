"""S3-compatible object gateway: asyncio HTTP server over the cluster
``Client``.

The third protocol front door (after FUSE and the NFS gateway),
following the proven pattern: protocol server -> internal ``Client`` ->
data plane. One asyncio process, one cluster client session shared by
every consumer.

Namespace mapping (bucket = directory, object = file):

* buckets are directories directly under the export root; bucket names
  follow the S3 grammar (3-63 chars of ``[a-z0-9.-]``) and never start
  with a dot — dot-names are the gateway's private area;
* object keys map to paths under the bucket; ``/`` in a key creates
  real intermediate directories (so FUSE/NFS see the same tree);
* every PUT lands in the hidden ``.s3mpu`` staging dir and RENAMES
  into place — a GET never observes a torn object;
* multipart uploads stage parts as files; CompleteMultipartUpload maps
  chunk-aligned parts onto the master's O(1) ``appendchunks``
  chunk-share concat (no re-copy of uploaded bytes; a non-aligned tail
  falls back to a positional copy, counted separately in metrics).

Lifecycle tiering: ``PUT /bucket?lifecycle`` stores the rule as the
``S3_LIFECYCLE_XATTR`` JSON on the bucket directory plus the
``EATTR_LIFECYCLE`` marker bit; the MASTER's lifecycle scanner demotes
cold objects through the tapeserver flow, and a GET of a demoted object
triggers a recall (``CltomaTapeRecall``) and then serves the original
bytes.

Runtime substrate: every request begins an ``s3_<op>`` trace span whose
id propagates into master RPCs and the data plane, feeds the ``s3`` SLO
class (FlightRecorder on breach), counts into a metrics-lint-clean
registry served at ``GET /metrics``, passes the ``http_recv``/
``http_send`` fault-injection sites, and runs under one end-to-end
request deadline (ambient ``RetryPolicy`` budget on every nested dial).

No AWS signature verification: the gateway trusts its network like the
NFS gateway trusts AUTH_SYS — front it with your own authn or keep it
on a private network (doc/operations.md runbook).
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import json
import logging
import secrets
import time
import urllib.parse

from lizardfs_tpu import constants
from lizardfs_tpu.client.client import Client
from lizardfs_tpu.constants import EATTR_LIFECYCLE, MFSCHUNKSIZE
from lizardfs_tpu.proto import messages as m
from lizardfs_tpu.proto import status as st
from lizardfs_tpu.runtime import accounting
from lizardfs_tpu.runtime import faults as faultsmod
from lizardfs_tpu.runtime import profiler as profmod
from lizardfs_tpu.runtime import retry as retrymod
from lizardfs_tpu.runtime import slo as slomod
from lizardfs_tpu.runtime import tracing
from lizardfs_tpu.runtime.metrics import Metrics
from lizardfs_tpu.s3 import xmlutil

log = logging.getLogger("lizardfs.s3")

MPU_DIR = ".s3mpu"  # staging area under the export root (never listed)
MAX_KEYS_CAP = 1000
# one request's wall budget: bounds every nested master RPC / data-plane
# dial through the ambient RetryPolicy deadline
REQUEST_DEADLINE_S = 120.0
MAX_HEADER_BYTES = 16 * 1024
MAX_BODY_BYTES = 1 << 31  # 2 GiB per PUT/part; multipart scales beyond
IO_TIMEOUT_S = 60.0  # per read/write on the HTTP socket

_HOP_STATUS = {
    200: "OK", 204: "No Content", 206: "Partial Content",
    307: "Temporary Redirect", 400: "Bad Request", 403: "Forbidden",
    404: "Not Found", 405: "Method Not Allowed", 409: "Conflict",
    411: "Length Required", 413: "Payload Too Large",
    416: "Range Not Satisfiable", 500: "Internal Server Error",
    501: "Not Implemented", 503: "Service Unavailable",
}


class _HttpError(Exception):
    """Maps straight to an S3 error response."""

    def __init__(self, http: int, code: str, message: str):
        self.http = http
        self.code = code
        self.message = message
        super().__init__(f"{http} {code}: {message}")


def _status_error(e: st.StatusError, resource: str) -> _HttpError:
    table = {
        st.ENOENT: (404, "NoSuchKey", "not found"),
        st.ENOTDIR: (404, "NoSuchKey", "not found"),
        st.EISDIR: (404, "NoSuchKey", "key names a directory"),
        st.EEXIST: (409, "BucketAlreadyExists", "already exists"),
        st.ENOTEMPTY: (409, "BucketNotEmpty", "bucket not empty"),
        st.EACCES: (403, "AccessDenied", "access denied"),
        st.EPERM: (403, "AccessDenied", "access denied"),
        st.EROFS: (403, "AccessDenied", "read-only session"),
        st.QUOTA_EXCEEDED: (403, "QuotaExceeded", "quota exceeded"),
        st.TAPE_RECALL: (
            503, "RestoreInProgress",
            "object is on the tape tier; restore in progress — retry",
        ),
        st.CHUNK_BUSY: (503, "SlowDown", "busy; retry"),
        # QoS fair-share shed: this bucket's tenant is over budget —
        # S3 semantics are exactly SlowDown (client backs off)
        st.BUSY: (503, "SlowDown", "tenant over fair share; slow down"),
        st.NO_CHUNK_SERVERS: (503, "SlowDown", "no chunkservers"),
        # recall-path failures are transient by contract (tape server
        # restarting / restore outliving one RPC budget): retryable,
        # never a permanent InternalError
        st.NOT_POSSIBLE: (503, "SlowDown",
                          "tape tier unavailable; retry"),
        st.TIMEOUT: (503, "SlowDown", "timed out; retry"),
    }
    http, code, msg = table.get(e.code, (500, "InternalError", str(e)))
    return _HttpError(http, code, f"{msg} ({resource})")


def _valid_bucket(name: str) -> bool:
    if not (3 <= len(name) <= 63) or name in (
        "metrics", "healthz", "profile", "top"
    ):
        return False
    if name[0] in ".-" or name[-1] in ".-":
        return False
    return all(c.islower() or c.isdigit() or c in ".-" for c in name)


def _key_segments(key: str) -> list[str]:
    """Split an object key into path segments; reject anything that
    could escape the bucket or collide with gateway-private names."""
    if not key or len(key) > 4096 or key.endswith("/"):
        raise _HttpError(400, "InvalidArgument", f"bad key {key!r}")
    segs = key.split("/")
    for s in segs:
        if not s or s in (".", "..") or len(s) > 255:
            raise _HttpError(400, "InvalidArgument", f"bad key {key!r}")
    if segs[0].startswith("."):
        raise _HttpError(400, "InvalidArgument", "keys may not start with .")
    return segs


def _http_date(epoch: int) -> str:
    return time.strftime(
        "%a, %d %b %Y %H:%M:%S GMT", time.gmtime(max(epoch, 0))
    )


def _iso8601(epoch: int) -> str:
    return time.strftime(
        "%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(max(epoch, 0))
    )


class _Request:
    __slots__ = ("method", "path", "query", "headers", "body", "peer")

    def __init__(self, method, path, query, headers, body, peer):
        self.method = method
        self.path = path
        self.query = query  # dict[str, str] (first value wins)
        self.headers = headers  # dict[str, str], lower-cased keys
        self.body = body
        self.peer = peer


class S3Gateway:
    """One process serving the S3 REST subset (plus ``/metrics`` and
    ``/healthz`` observability endpoints) over one cluster session.

    ``root`` names the cluster directory exported as the bucket
    namespace ("/" by default — buckets appear at the filesystem
    root, visible identically over FUSE and NFS)."""

    def __init__(
        self,
        master_host: str,
        master_port: int,
        host: str = "127.0.0.1",
        port: int = 0,
        root: str = "/",
    ) -> None:
        # gateway-local registry shared with the embedded Client (the
        # NFS gateway pattern): client-side write-window/cache series
        # land next to the s3 op counters and SLO gauges, all served
        # from GET /metrics in one lint-clean page
        self.metrics = Metrics()
        self.client = Client(master_host, master_port, metrics=self.metrics)
        self.host = host
        self.port = port
        self.root = root
        self.root_inode = 0
        self._mpu_inode = 0
        self._server: asyncio.Server | None = None
        self.request_deadline_s = REQUEST_DEADLINE_S
        # the s3 SLO class: per-request latency objectives feeding the
        # FlightRecorder (slowops/incidents) and the health rollup
        self.slo = slomod.SloEngine(
            self.metrics, role="s3",
            span_source=self.client.trace_ring.dump,
        )
        # per-session protocol-op accounting, pushed to the master's
        # `top` rollup (CltomaSessionStats) — the NFS gateway pattern
        self.session_ops = accounting.SessionOps(
            self.metrics, "s3", max_sessions=8
        )
        self.stats_push_interval_s = 5.0
        self._stats_task: asyncio.Task | None = None
        # always-on sampling profiler (process-wide shared instance),
        # served at GET /profile
        self.profiler = profmod.process_profiler(role="s3")
        self.slo.profiler = self.profiler
        self.slo.recorder.profile_source = self.profiler.collapsed
        self.metrics.counter(
            "s3_bytes_in", help="object bytes received in PUT/UploadPart"
        )
        self.metrics.counter(
            "s3_bytes_out", help="object bytes served by GET"
        )
        self.metrics.counter(
            "s3_mpu_parts_shared",
            help="multipart parts assembled via O(1) appendchunks "
                 "chunk-share (no byte re-copy)",
        )
        self.metrics.counter(
            "s3_mpu_parts_copied",
            help="multipart parts assembled by positional re-copy "
                 "(previous part left a non-chunk-aligned tail)",
        )
        self.metrics.counter(
            "s3_mpu_copied_bytes",
            help="bytes re-copied by non-aligned multipart assembly",
        )
        self.metrics.counter(
            "s3_recalls",
            help="GETs that triggered a tape-tier recall before serving",
        )

    # --- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        if not constants.s3_enabled():
            raise RuntimeError(
                "S3 gateway disabled by the LZ_S3 kill switch"
            )
        # one 30 s startup budget over every dial the nested connect
        # makes (gateway racing master startup/election — NFS pattern)
        await retrymod.RetryPolicy(
            attempts=10, base_delay=0.2, max_delay=2.0, deadline=30.0,
        ).run(
            lambda: self.client.connect(info="s3-gateway"),
            what="s3 gateway master connect", log=log,
        )
        root = await self.client.resolve(self.root)
        self.root_inode = root.inode
        self._server = await asyncio.start_server(
            self._serve_conn, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        self.profiler.start()  # no-op under LZ_PROF=0
        self._stats_task = asyncio.ensure_future(self._stats_push_loop())
        log.info("s3 gateway on port %d (root %s)", self.port, self.root)

    async def stop(self) -> None:
        if self._stats_task is not None:
            self._stats_task.cancel()
            try:
                await self._stats_task
            except asyncio.CancelledError:
                pass
        self.profiler.stop()
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5.0)
            except asyncio.TimeoutError:
                pass
        await self.client.close()

    def _stats_doc(self) -> dict:
        """Workload summary pushed to the master (`top` rollup) and
        mirrored at GET /top: protocol-op mix + the embedded Client's
        logical data-op accounting."""
        return {
            "role": "s3",
            "endpoint": f"{self.host}:{self.port}",
            "protocol": self.session_ops.top(8),
            "data": self.client.session_ops.top(8),
        }

    def _stats_push_loop(self):
        """The shared gateway push contract (CltomaSessionStats every
        few seconds — runtime/accounting.py owns the loop so the NFS
        and S3 gateways cannot drift apart on it)."""
        return accounting.gateway_stats_push_loop(
            self.client, self._stats_doc, self.stats_push_interval_s, log
        )

    # --- HTTP framing ------------------------------------------------------

    async def _serve_conn(self, reader, writer) -> None:
        peer = writer.get_extra_info("peername")
        peer_s = f"{peer[0]}:{peer[1]}" if isinstance(peer, tuple) else "?"
        try:
            while True:
                try:
                    req = await self._read_request(reader, writer, peer_s)
                except _HttpError as e:
                    # framing-level refusal (chunked TE, oversized body):
                    # answer once, then drop the connection
                    await self._respond(
                        writer, "BadRequest", peer_s, e.http,
                        xmlutil.error_xml(e.code, e.message).encode(),
                        {"Content-Type": "application/xml",
                         "Connection": "close"},
                    )
                    return
                if req is None:
                    return
                keep = await self._dispatch(req, writer)
                if not keep:
                    return
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.TimeoutError, asyncio.LimitOverrunError):
            pass  # peer went away / fault injection killed the exchange
        except Exception:  # noqa: BLE001 — a crashed handler must not kill the gateway
            log.exception("s3 connection from %s crashed", peer_s)
        finally:
            await retrymod.close_writer(writer, swallow_cancel=True)

    async def _read_request(self, reader, writer, peer_s):
        # keep-alive park: an idle client may sit between requests for
        # as long as it likes — the wait owns no budget by design
        # lint: waive(unbounded-await): keep-alive idle park between requests; the peer owns the cadence
        line = await reader.readline()
        if not line:
            return None
        try:
            method, target, _version = line.decode("ascii").split(" ", 2)
        except (UnicodeDecodeError, ValueError):
            return None
        headers: dict[str, str] = {}
        total = len(line)
        while True:
            hl = await retrymod.bounded_wait(reader.readline(), IO_TIMEOUT_S)
            total += len(hl)
            if total > MAX_HEADER_BYTES:
                return None
            if hl in (b"\r\n", b"\n", b""):
                break
            name, _, value = hl.decode("latin-1").partition(":")
            headers[name.strip().lower()] = value.strip()
        if faultsmod.ACTIVE:
            await faultsmod.async_point(
                "http_recv", op=method, peer=peer_s, role="s3"
            )
        if headers.get("transfer-encoding", "").lower() == "chunked":
            raise _HttpError(501, "NotImplemented",
                             "chunked transfer encoding")
        body = b""
        clen = int(headers.get("content-length", "0") or "0")
        if clen:
            if clen > MAX_BODY_BYTES:
                raise _HttpError(413, "EntityTooLarge", "body too large")
            if headers.get("expect", "").lower() == "100-continue":
                writer.write(b"HTTP/1.1 100 Continue\r\n\r\n")
                await asyncio.wait_for(writer.drain(), IO_TIMEOUT_S)
            body = await retrymod.bounded_wait(
                reader.readexactly(clen), IO_TIMEOUT_S
            )
        parsed = urllib.parse.urlsplit(target)
        query = {
            k: (v[0] if v else "")
            for k, v in urllib.parse.parse_qs(
                parsed.query, keep_blank_values=True
            ).items()
        }
        path = urllib.parse.unquote(parsed.path)
        return _Request(method, path, query, headers, body, peer_s)

    async def _respond(
        self, writer, opname: str, peer: str, code: int,
        body: bytes = b"", headers: dict | None = None, head_only=False,
    ) -> None:
        if faultsmod.ACTIVE:
            await faultsmod.async_point(
                "http_send", op=opname, peer=peer, role="s3"
            )
        hdrs = {
            "x-amz-request-id": secrets.token_hex(8),
            "Content-Length": str(len(body)),
            "Connection": "keep-alive",
            **(headers or {}),
        }
        lines = [f"HTTP/1.1 {code} {_HOP_STATUS.get(code, 'OK')}"]
        lines += [f"{k}: {v}" for k, v in hdrs.items()]
        writer.write(("\r\n".join(lines) + "\r\n\r\n").encode("latin-1"))
        if body and not head_only:
            # separate write: headers + a multi-MB object body must not
            # concatenate into a second full copy of the object
            writer.write(body)
        await asyncio.wait_for(writer.drain(), IO_TIMEOUT_S)

    # --- dispatch ----------------------------------------------------------

    def _route(self, req: _Request) -> tuple[str, object, tuple]:
        """(op name, handler, args) for one parsed request."""
        path = req.path.strip("/")
        if req.method == "GET" and path == "metrics":
            return "Metrics", self._op_metrics, ()
        if req.method == "GET" and path == "healthz":
            return "Healthz", self._op_healthz, ()
        if req.method == "GET" and path == "profile":
            return "Profile", self._op_profile, ()
        if req.method == "GET" and path == "top":
            return "Top", self._op_top, ()
        if not path:
            if req.method == "GET":
                return "ListBuckets", self._op_list_buckets, ()
            raise _HttpError(405, "MethodNotAllowed", req.method)
        bucket, _, key = path.partition("/")
        if not key:
            if "lifecycle" in req.query:
                ops = {"PUT": ("PutBucketLifecycle", self._op_put_lifecycle),
                       "GET": ("GetBucketLifecycle", self._op_get_lifecycle),
                       "DELETE": ("DeleteBucketLifecycle",
                                  self._op_delete_lifecycle)}
                if req.method in ops:
                    name, fn = ops[req.method]
                    return name, fn, (bucket,)
                raise _HttpError(405, "MethodNotAllowed", req.method)
            ops = {"PUT": ("CreateBucket", self._op_create_bucket),
                   "DELETE": ("DeleteBucket", self._op_delete_bucket),
                   "HEAD": ("HeadBucket", self._op_head_bucket),
                   "GET": ("ListObjectsV2", self._op_list_objects)}
            if req.method in ops:
                name, fn = ops[req.method]
                return name, fn, (bucket,)
            raise _HttpError(405, "MethodNotAllowed", req.method)
        if req.method == "POST" and "uploads" in req.query:
            return "CreateMultipartUpload", self._op_mpu_create, (bucket, key)
        if req.method == "POST" and "uploadId" in req.query:
            return ("CompleteMultipartUpload", self._op_mpu_complete,
                    (bucket, key))
        if req.method == "PUT" and "uploadId" in req.query:
            return "UploadPart", self._op_mpu_part, (bucket, key)
        if req.method == "DELETE" and "uploadId" in req.query:
            return "AbortMultipartUpload", self._op_mpu_abort, (bucket, key)
        ops = {"PUT": ("PutObject", self._op_put_object),
               "GET": ("GetObject", self._op_get_object),
               "HEAD": ("HeadObject", self._op_head_object),
               "DELETE": ("DeleteObject", self._op_delete_object)}
        if req.method in ops:
            name, fn = ops[req.method]
            return name, fn, (bucket, key)
        raise _HttpError(405, "MethodNotAllowed", req.method)

    async def _dispatch(self, req: _Request, writer) -> bool:
        """Handle one request; returns keep-alive. The request is the
        trace root: the id issued here rides every master RPC and
        data-plane frame the op triggers, and the op feeds the s3 SLO
        class + per-op request counters."""
        opname = "Unknown"
        t0 = time.perf_counter()
        tw0 = time.time()
        tid, fresh = tracing.begin()
        code = 500
        try:
            try:
                opname, handler, args = self._route(req)
                code, body, headers, head_only = await retrymod.RetryPolicy(
                    attempts=1, deadline=self.request_deadline_s,
                ).run(
                    lambda: handler(req, *args),
                    what=f"s3 {opname}", log=log,
                )
            except _HttpError as e:
                code, body, headers, head_only = (
                    e.http,
                    xmlutil.error_xml(e.code, e.message, req.path).encode(),
                    {"Content-Type": "application/xml"},
                    req.method == "HEAD",
                )
            except st.StatusError as e:
                he = _status_error(e, req.path)
                code, body, headers, head_only = (
                    he.http,
                    xmlutil.error_xml(he.code, he.message, req.path).encode(),
                    {"Content-Type": "application/xml"},
                    req.method == "HEAD",
                )
            except retrymod.RetryError:
                code, body, headers, head_only = (
                    503,
                    xmlutil.error_xml(
                        "SlowDown", "request deadline exhausted", req.path
                    ).encode(),
                    {"Content-Type": "application/xml"},
                    req.method == "HEAD",
                )
            await self._respond(
                writer, opname, req.peer, code, body, headers, head_only
            )
            return req.headers.get("connection", "").lower() != "close"
        finally:
            dt = time.perf_counter() - t0
            self.metrics.labeled_counter(
                "s3_requests", {"op": opname, "code": str(code)},
                help="S3 gateway requests by operation and HTTP status",
            ).inc()
            self.client.trace_ring.record(
                tid, f"s3_{opname}", tw0, time.time(), role="s3"
            )
            self.slo.observe("s3", dt, trace_id=tid, name=f"s3_{opname}")
            # per-session protocol accounting: the op charged to this
            # gateway's cluster session for the master's `top` rollup
            self.session_ops.record(
                self.client.session_id, f"s3_{opname}", dt,
                nbytes=len(req.body), trace_id=tid,
            )
            tracing.end(fresh)

    # --- namespace helpers -------------------------------------------------

    async def _bucket_attr(self, bucket: str) -> m.Attr:
        if not _valid_bucket(bucket):
            raise _HttpError(400, "InvalidBucketName", bucket)
        try:
            attr = await self.client.lookup(self.root_inode, bucket)
        except st.StatusError as e:
            if e.code == st.ENOENT:
                raise _HttpError(404, "NoSuchBucket", bucket) from None
            raise
        if attr.ftype != m.FTYPE_DIR:
            raise _HttpError(404, "NoSuchBucket", bucket)
        return attr

    async def _resolve_key(self, bucket_inode: int, key: str) -> m.Attr:
        attr = None
        parent = bucket_inode
        for seg in _key_segments(key):
            attr = await self.client.lookup(parent, seg)
            parent = attr.inode
        if attr is None or attr.ftype != m.FTYPE_FILE:
            raise st.StatusError(st.ENOENT, key)
        return attr

    async def _ensure_dirs(self, parent: int, segs: list[str]) -> int:
        """mkdir -p for a key's intermediate directories."""
        for seg in segs:
            try:
                attr = await self.client.mkdir(parent, seg)
            except st.StatusError as e:
                if e.code != st.EEXIST:
                    raise
                attr = await self.client.lookup(parent, seg)
                if attr.ftype != m.FTYPE_DIR:
                    raise _HttpError(
                        409, "InvalidArgument",
                        f"key prefix {seg!r} names an object",
                    ) from None
            parent = attr.inode
        return parent

    async def _mpu_root(self) -> int:
        if self._mpu_inode:
            return self._mpu_inode
        try:
            attr = await self.client.mkdir(self.root_inode, MPU_DIR)
        except st.StatusError as e:
            if e.code != st.EEXIST:
                raise
            attr = await self.client.lookup(self.root_inode, MPU_DIR)
        self._mpu_inode = attr.inode
        return attr.inode

    async def _write_staged(self, name: str, data: bytes) -> m.Attr:
        """Create + write a file in the staging area (trash-time 0: a
        replaced/aborted stage must free its chunks immediately).
        Names are caller-generated random tokens, so EEXIST only means
        a dead gateway's leftover — replace it."""
        staging = await self._mpu_root()
        try:
            attr = await self.client.create(staging, name)
        except st.StatusError as e:
            if e.code != st.EEXIST:
                raise
            await self.client.unlink(staging, name)
            attr = await self.client.create(staging, name)
        await self.client.settrashtime(attr.inode, 0)
        if data:
            await self.client.write_file(attr.inode, data)
        return attr

    async def _set_etag(self, inode: int, etag: str) -> None:
        await self.client.set_xattr(
            inode, constants.S3_ETAG_XATTR, etag.encode()
        )

    async def _get_etag(self, inode: int) -> str | None:
        try:
            raw = await self.client.get_xattr(
                inode, constants.S3_ETAG_XATTR
            )
            return raw.decode("ascii", "replace")
        except st.StatusError:
            return None

    async def _publish(self, bucket: str, key: str,
                       staged_name: str) -> None:
        """Atomically move a staged object into place: the key becomes
        visible fully-written or not at all (rename replaces any
        previous object under the key in the same step)."""
        battr = await self._bucket_attr(bucket)
        segs = _key_segments(key)
        parent = await self._ensure_dirs(battr.inode, segs[:-1])
        staging = await self._mpu_root()
        await self.client.rename(staging, staged_name, parent, segs[-1])

    # --- service / bucket ops ---------------------------------------------

    async def _op_metrics(self, req: _Request):
        text = self.metrics.to_prometheus().encode()
        return 200, text, {"Content-Type": "text/plain; version=0.0.4"}, False

    async def _op_healthz(self, req: _Request):
        doc = {
            "role": "s3",
            "status": self.slo.status() if slomod.enabled() else "ok",
            "slo": self.slo.snapshot() if slomod.enabled() else {},
            "slow_ops": len(self.slo.recorder.slowops()),
        }
        return (200, json.dumps(doc).encode(),
                {"Content-Type": "application/json"}, False)

    async def _op_profile(self, req: _Request):
        doc = self.profiler.snapshot()
        doc["role"] = "s3"  # process-wide sampler, this surface's dump
        doc["collapsed"] = self.profiler.collapsed()
        return (200, json.dumps(doc).encode(),
                {"Content-Type": "application/json"}, False)

    async def _op_top(self, req: _Request):
        return (200, json.dumps(self._stats_doc()).encode(),
                {"Content-Type": "application/json"}, False)

    async def _op_list_buckets(self, req: _Request):
        entries = await self.client.readdir(self.root_inode)
        rows = []
        for e in sorted(entries, key=lambda e: e.name):
            if e.ftype != m.FTYPE_DIR or not _valid_bucket(e.name):
                continue
            attr = await self.client.getattr(e.inode)
            rows.append(
                f"<Bucket><Name>{xmlutil.esc(e.name)}</Name>"
                f"<CreationDate>{_iso8601(attr.ctime)}</CreationDate>"
                f"</Bucket>"
            )
        body = (
            f"{xmlutil.XML_DECL}<ListAllMyBucketsResult"
            f" xmlns=\"{xmlutil.S3_NS}\"><Owner><ID>lizardfs</ID></Owner>"
            f"<Buckets>{''.join(rows)}</Buckets></ListAllMyBucketsResult>"
        )
        return 200, body.encode(), {"Content-Type": "application/xml"}, False

    async def _op_create_bucket(self, req: _Request, bucket: str):
        if not _valid_bucket(bucket):
            raise _HttpError(400, "InvalidBucketName", bucket)
        try:
            await self.client.mkdir(self.root_inode, bucket)
        except st.StatusError as e:
            if e.code != st.EEXIST:
                raise
            existing = await self.client.lookup(self.root_inode, bucket)
            if existing.ftype != m.FTYPE_DIR:
                raise _HttpError(409, "BucketAlreadyExists", bucket) from None
            # idempotent re-create of an existing bucket: S3 allows it
        return 200, b"", {"Location": f"/{bucket}"}, False

    async def _op_head_bucket(self, req: _Request, bucket: str):
        await self._bucket_attr(bucket)
        return 200, b"", {}, True

    async def _op_delete_bucket(self, req: _Request, bucket: str):
        await self._bucket_attr(bucket)
        await self.client.rmdir(self.root_inode, bucket)
        return 204, b"", {}, False

    # --- lifecycle config --------------------------------------------------

    async def _op_put_lifecycle(self, req: _Request, bucket: str):
        rule = xmlutil.parse_lifecycle(req.body)
        if rule is None:
            raise _HttpError(400, "MalformedXML",
                             "no parseable Transition rule")
        attr = await self._bucket_attr(bucket)
        await self.client.set_xattr(
            attr.inode, constants.S3_LIFECYCLE_XATTR,
            json.dumps(rule).encode(),
        )
        eattr = await self.client.geteattr(attr.inode)
        if not eattr & EATTR_LIFECYCLE:
            await self.client.seteattr(attr.inode, eattr | EATTR_LIFECYCLE)
        return 200, b"", {}, False

    async def _op_get_lifecycle(self, req: _Request, bucket: str):
        attr = await self._bucket_attr(bucket)
        try:
            raw = await self.client.get_xattr(
                attr.inode, constants.S3_LIFECYCLE_XATTR
            )
        except st.StatusError:
            raise _HttpError(
                404, "NoSuchLifecycleConfiguration", bucket
            ) from None
        try:
            rule = json.loads(raw.decode())
        except ValueError:
            raise _HttpError(
                404, "NoSuchLifecycleConfiguration", bucket
            ) from None
        body = xmlutil.render_lifecycle(rule)
        return 200, body.encode(), {"Content-Type": "application/xml"}, False

    async def _op_delete_lifecycle(self, req: _Request, bucket: str):
        attr = await self._bucket_attr(bucket)
        try:
            await self.client.remove_xattr(
                attr.inode, constants.S3_LIFECYCLE_XATTR
            )
        except st.StatusError:
            pass  # idempotent
        eattr = await self.client.geteattr(attr.inode)
        if eattr & EATTR_LIFECYCLE:
            await self.client.seteattr(attr.inode, eattr & ~EATTR_LIFECYCLE)
        return 204, b"", {}, False

    # --- listing -----------------------------------------------------------

    async def _walk_keys(self, dir_inode: int, prefix: str,
                         out: dict[str, int]) -> None:
        """Collect key -> inode for the whole subtree (inodes come from
        readdir, so the listing window never re-resolves keys
        segment-by-segment)."""
        entries = await self.client.readdir(dir_inode)
        for e in sorted(entries, key=lambda e: e.name):
            if e.name.startswith(".") and not prefix:
                continue  # gateway-private names live at bucket root only
            if e.ftype == m.FTYPE_DIR:
                await self._walk_keys(e.inode, prefix + e.name + "/", out)
            elif e.ftype == m.FTYPE_FILE:
                out[prefix + e.name] = e.inode

    async def _op_list_objects(self, req: _Request, bucket: str):
        battr = await self._bucket_attr(bucket)
        prefix = req.query.get("prefix", "")
        delimiter = req.query.get("delimiter", "")
        try:
            max_keys = min(
                int(req.query.get("max-keys", str(MAX_KEYS_CAP))),
                MAX_KEYS_CAP,
            )
            if max_keys < 0:
                raise ValueError(max_keys)
        except ValueError:
            raise _HttpError(400, "InvalidArgument", "max-keys") from None
        token = req.query.get("continuation-token", "")
        after = ""
        if token:
            try:
                after = base64.urlsafe_b64decode(token.encode()).decode()
            except (ValueError, UnicodeDecodeError):
                raise _HttpError(
                    400, "InvalidArgument", "continuation-token"
                ) from None
        key_inodes: dict[str, int] = {}
        await self._walk_keys(battr.inode, "", key_inodes)
        keys = sorted(key_inodes)
        # delimiter grouping over the prefix-filtered, post-token tail:
        # items are (sort key, is_prefix); S3 interleaves Contents and
        # CommonPrefixes in one lexicographic stream
        items: list[tuple[str, bool]] = []
        seen_prefixes: set[str] = set()
        for k in keys:
            if not k.startswith(prefix):
                continue
            if delimiter:
                rest = k[len(prefix):]
                cut = rest.find(delimiter)
                if cut >= 0:
                    cp = prefix + rest[: cut + len(delimiter)]
                    if cp not in seen_prefixes:
                        seen_prefixes.add(cp)
                        items.append((cp, True))
                    continue
            items.append((k, False))
        items = [it for it in items if it[0] > after]
        window = items[:max_keys]
        truncated = len(items) > len(window)
        contents, cprefixes = [], []
        for name, is_prefix in window:
            if is_prefix:
                cprefixes.append(
                    f"<CommonPrefixes><Prefix>{xmlutil.esc(name)}</Prefix>"
                    f"</CommonPrefixes>"
                )
                continue
            attr = await self.client.getattr(key_inodes[name])
            etag = await self._get_etag(attr.inode) or ""
            contents.append(
                f"<Contents><Key>{xmlutil.esc(name)}</Key>"
                f"<LastModified>{_iso8601(attr.mtime)}</LastModified>"
                f"<ETag>&quot;{xmlutil.esc(etag)}&quot;</ETag>"
                f"<Size>{attr.length}</Size>"
                f"<StorageClass>STANDARD</StorageClass></Contents>"
            )
        next_token = ""
        if truncated and window:
            next_token = base64.urlsafe_b64encode(
                window[-1][0].encode()
            ).decode()
        body = (
            f"{xmlutil.XML_DECL}<ListBucketResult xmlns=\"{xmlutil.S3_NS}\">"
            f"<Name>{xmlutil.esc(bucket)}</Name>"
            f"<Prefix>{xmlutil.esc(prefix)}</Prefix>"
            f"<Delimiter>{xmlutil.esc(delimiter)}</Delimiter>"
            f"<KeyCount>{len(window)}</KeyCount>"
            f"<MaxKeys>{max_keys}</MaxKeys>"
            f"<IsTruncated>{'true' if truncated else 'false'}</IsTruncated>"
            + (f"<NextContinuationToken>{next_token}"
               f"</NextContinuationToken>" if next_token else "")
            + "".join(contents) + "".join(cprefixes)
            + "</ListBucketResult>"
        )
        return 200, body.encode(), {"Content-Type": "application/xml"}, False

    # --- object ops --------------------------------------------------------

    async def _op_put_object(self, req: _Request, bucket: str, key: str):
        await self._bucket_attr(bucket)
        _key_segments(key)
        etag = hashlib.md5(req.body).hexdigest()
        name = f"put-{secrets.token_hex(12)}"
        staged = await self._write_staged(name, req.body)
        await self._set_etag(staged.inode, etag)
        await self._publish(bucket, key, name)
        self.metrics.counter("s3_bytes_in").inc(float(len(req.body)))
        return 200, b"", {"ETag": f'"{etag}"'}, False

    def _parse_range(self, req: _Request, length: int):
        spec = req.headers.get("range", "")
        if not spec.startswith("bytes="):
            return 0, length, False
        lo_s, _, hi_s = spec[len("bytes="):].partition("-")
        try:
            if lo_s:
                lo = int(lo_s)
                hi = int(hi_s) if hi_s else length - 1
            else:
                # suffix form: last N bytes
                lo = max(length - int(hi_s), 0)
                hi = length - 1
        except ValueError:
            return 0, length, False
        if lo > hi or lo >= max(length, 1):
            raise _HttpError(416, "InvalidRange", spec)
        hi = min(hi, length - 1)
        return lo, hi - lo + 1, True

    async def _read_with_recall(self, inode: int, off: int,
                                size: int) -> bytes:
        """read_file that survives the tape tier: a TAPE_RECALL status
        triggers the master-side recall (bounded by the ambient request
        deadline) and one retry once the bytes are back."""
        try:
            return await self.client.read_file(inode, off, size)
        except st.StatusError as e:
            if e.code != st.TAPE_RECALL:
                raise
        self.metrics.counter("s3_recalls").inc()
        await self.client.tape_recall(inode)
        return await self.client.read_file(inode, off, size)

    async def _op_get_object(self, req: _Request, bucket: str, key: str,
                             head_only: bool = False):
        battr = await self._bucket_attr(bucket)
        attr = await self._resolve_key(battr.inode, key)
        etag = await self._get_etag(attr.inode)
        info_headers = {
            "Last-Modified": _http_date(attr.mtime),
            "Content-Type": "application/octet-stream",
            "Accept-Ranges": "bytes",
        }
        if etag:
            info_headers["ETag"] = f'"{etag}"'
        if head_only:
            info_headers["Content-Length"] = str(attr.length)
            return 200, b"", info_headers, True
        off, size, partial = self._parse_range(req, attr.length)
        data = b""
        if size > 0 and attr.length > 0:
            data = await self._read_with_recall(attr.inode, off, size)
        self.metrics.counter("s3_bytes_out").inc(float(len(data)))
        if partial:
            info_headers["Content-Range"] = (
                f"bytes {off}-{off + len(data) - 1}/{attr.length}"
            )
            return 206, data, info_headers, False
        return 200, data, info_headers, False

    async def _op_head_object(self, req: _Request, bucket: str, key: str):
        return await self._op_get_object(req, bucket, key, head_only=True)

    async def _op_delete_object(self, req: _Request, bucket: str, key: str):
        battr = await self._bucket_attr(bucket)
        segs = _key_segments(key)
        try:
            parent = battr.inode
            for seg in segs[:-1]:
                parent = (await self.client.lookup(parent, seg)).inode
            await self.client.unlink(parent, segs[-1])
        except st.StatusError as e:
            # idempotent at ANY depth: a missing intermediate prefix is
            # the same "key does not exist" as a missing leaf
            if e.code not in (st.ENOENT, st.ENOTDIR):
                raise
        return 204, b"", {}, False  # S3 DELETE is idempotent

    # --- multipart upload --------------------------------------------------

    async def _mpu_dir(self, upload_id: str, bucket: str,
                       key: str) -> m.Attr:
        if not upload_id.isalnum():
            raise _HttpError(404, "NoSuchUpload", upload_id)
        staging = await self._mpu_root()
        try:
            attr = await self.client.lookup(staging, f"up-{upload_id}")
            raw = await self.client.get_xattr(
                attr.inode, "lizardfs.s3.upload"
            )
            bound = json.loads(raw.decode())
        except (st.StatusError, ValueError):
            raise _HttpError(404, "NoSuchUpload", upload_id) from None
        # an uploadId is bound to the bucket/key it was created for
        # (S3 semantics): a mismatched part/complete/abort must not
        # touch another key's staging
        if bound.get("bucket") != bucket or bound.get("key") != key:
            raise _HttpError(404, "NoSuchUpload", upload_id)
        return attr

    async def _op_mpu_create(self, req: _Request, bucket: str, key: str):
        await self._bucket_attr(bucket)
        _key_segments(key)
        upload_id = secrets.token_hex(16)
        staging = await self._mpu_root()
        attr = await self.client.mkdir(staging, f"up-{upload_id}")
        await self.client.set_xattr(
            attr.inode, "lizardfs.s3.upload",
            json.dumps({"bucket": bucket, "key": key}).encode(),
        )
        body = (
            f"{xmlutil.XML_DECL}<InitiateMultipartUploadResult"
            f" xmlns=\"{xmlutil.S3_NS}\">"
            f"<Bucket>{xmlutil.esc(bucket)}</Bucket>"
            f"<Key>{xmlutil.esc(key)}</Key>"
            f"<UploadId>{upload_id}</UploadId>"
            f"</InitiateMultipartUploadResult>"
        )
        return 200, body.encode(), {"Content-Type": "application/xml"}, False

    async def _op_mpu_part(self, req: _Request, bucket: str, key: str):
        try:
            part_no = int(req.query.get("partNumber", "0"))
        except ValueError:
            raise _HttpError(400, "InvalidArgument", "partNumber") from None
        if not 1 <= part_no <= 10_000:
            raise _HttpError(400, "InvalidArgument", "partNumber")
        updir = await self._mpu_dir(
            req.query.get("uploadId", ""), bucket, key
        )
        etag = hashlib.md5(req.body).hexdigest()
        name = f"part.{part_no:05d}"
        # stage + rename INTO the upload dir: a retransmitted part
        # replaces its predecessor atomically
        tmp_name = f"part-{secrets.token_hex(12)}"
        tmp = await self._write_staged(tmp_name, req.body)
        await self._set_etag(tmp.inode, etag)
        staging = await self._mpu_root()
        await self.client.rename(staging, tmp_name, updir.inode, name)
        self.metrics.counter("s3_bytes_in").inc(float(len(req.body)))
        return 200, b"", {"ETag": f'"{etag}"'}, False

    async def _op_mpu_complete(self, req: _Request, bucket: str, key: str):
        upload_id = req.query.get("uploadId", "")
        updir = await self._mpu_dir(upload_id, bucket, key)
        wanted = xmlutil.parse_complete_multipart(req.body)
        if not wanted:
            raise _HttpError(400, "MalformedXML",
                             "CompleteMultipartUpload body")
        parts: list[tuple[int, m.Attr, str]] = []
        for num, want_etag in wanted:
            try:
                pattr = await self.client.lookup(
                    updir.inode, f"part.{num:05d}"
                )
            except st.StatusError:
                raise _HttpError(400, "InvalidPart",
                                 f"part {num} missing") from None
            etag = await self._get_etag(pattr.inode) or ""
            if want_etag and want_etag != etag:
                raise _HttpError(400, "InvalidPart",
                                 f"part {num} etag mismatch")
            parts.append((num, pattr, etag))
        # assemble into a staged file: chunk-aligned tails concat via
        # the master's O(1) appendchunks chunk share (the uploaded
        # bytes are never copied again); a non-aligned tail forces a
        # positional re-copy of the NEXT part, counted separately
        dest_name = f"asm-{secrets.token_hex(12)}"
        dest = await self._write_staged(dest_name, b"")
        assembled = 0
        for _num, pattr, _etag in parts:
            if pattr.length == 0:
                continue
            if assembled % MFSCHUNKSIZE == 0:
                await self.client.append_chunks(dest.inode, pattr.inode)
                self.metrics.counter("s3_mpu_parts_shared").inc()
            else:
                data = await self.client.read_file(
                    pattr.inode, 0, pattr.length
                )
                await self.client.pwrite(dest.inode, assembled, data)
                self.metrics.counter("s3_mpu_parts_copied").inc()
                self.metrics.counter("s3_mpu_copied_bytes").inc(
                    float(len(data))
                )
            assembled += pattr.length
        digest = hashlib.md5()
        for _num, _pattr, etag in parts:
            digest.update(bytes.fromhex(etag))
        final_etag = f"{digest.hexdigest()}-{len(parts)}"
        await self._set_etag(dest.inode, final_etag)
        await self._publish(bucket, key, dest_name)
        # uploaded part files shared their chunks into the object;
        # dropping them releases only their references
        await self._mpu_cleanup(upload_id, updir)
        body = (
            f"{xmlutil.XML_DECL}<CompleteMultipartUploadResult"
            f" xmlns=\"{xmlutil.S3_NS}\">"
            f"<Bucket>{xmlutil.esc(bucket)}</Bucket>"
            f"<Key>{xmlutil.esc(key)}</Key>"
            f"<ETag>&quot;{final_etag}&quot;</ETag>"
            f"</CompleteMultipartUploadResult>"
        )
        return 200, body.encode(), {"Content-Type": "application/xml"}, False

    async def _mpu_cleanup(self, upload_id: str, updir: m.Attr) -> None:
        staging = await self._mpu_root()
        for e in await self.client.readdir(updir.inode):
            try:
                await self.client.unlink(updir.inode, e.name)
            except st.StatusError:
                pass
        try:
            await self.client.rmdir(staging, f"up-{upload_id}")
        except st.StatusError:
            pass

    async def _op_mpu_abort(self, req: _Request, bucket: str, key: str):
        upload_id = req.query.get("uploadId", "")
        updir = await self._mpu_dir(upload_id, bucket, key)
        await self._mpu_cleanup(upload_id, updir)
        return 204, b"", {}, False


async def main(argv: list[str] | None = None) -> None:
    """``python -m lizardfs_tpu.s3 HOST:PORT [--port N] [--root /path]``"""
    import argparse

    ap = argparse.ArgumentParser(description="LizardFS-TPU S3 gateway")
    ap.add_argument("master", help="master HOST:PORT")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=9444)
    ap.add_argument("--root", default="/",
                    help="cluster directory exported as the bucket root")
    args = ap.parse_args(argv)
    mhost, mport = args.master.rsplit(":", 1)
    gw = S3Gateway(mhost, int(mport), host=args.host, port=args.port,
                   root=args.root)
    await gw.start()
    try:
        # lint: waive(unbounded-await): the gateway process parks here until killed by design
        await asyncio.Event().wait()
    finally:
        await gw.stop()


if __name__ == "__main__":
    asyncio.run(main())
