"""CRC32 (zlib-compatible) golden path + GF(2) matrix machinery.

The reference checksums every 64 KiB block with CRC-32, polynomial
0xEDB88320 reflected, init/final xor 0xFFFFFFFF — exactly zlib's ``crc32``
(reference: src/common/crc.cc:113-151 ``mycrc32``), and concatenates block
CRCs with ``mycrc32_combine`` (crc.cc:207-224), the classic GF(2)
matrix-shift construction.

CRC over a message is *affine* over GF(2) in the message bits:

    crc(msg) = R(msg) xor K_L,   R linear,   K_L = crc(0^L)

and R decomposes over fixed-size sub-blocks:

    R(msg) = sum_i S_B^(n-1-i) @ (C_B @ bits(subblock_i))

with S_B the "shift by B zero bytes" 32x32 matrix and C_B the 32x(8B)
sub-block matrix. That decomposition is what lets the TPU kernel compute
all 1024 block CRCs of a chunk as one batched int8 matmul plus a
log-depth tree of tiny 32x32 combines — no serial byte loop. This module
builds those matrices (host-side, cached) and provides the golden
scalar/functional path used for verification.

Bit convention: bit i of a 32-bit CRC register maps to vector row i
(little-endian); byte bit j likewise.
"""

from __future__ import annotations

import functools
import zlib

import numpy as np

from lizardfs_tpu.constants import CRC_POLY


def crc32(data: bytes | np.ndarray, crc: int = 0) -> int:
    """Golden CRC32, identical to the reference's ``mycrc32``."""
    if isinstance(data, np.ndarray):
        data = data.tobytes()
    return zlib.crc32(data, crc) & 0xFFFFFFFF


@functools.lru_cache(maxsize=1)
def _byte_table() -> np.ndarray:
    """Standard reflected CRC-32 byte table (crc.cc:71-90)."""
    tab = np.zeros(256, dtype=np.uint64)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (CRC_POLY ^ (c >> 1)) if (c & 1) else (c >> 1)
        tab[i] = c
    return tab


def _bits32(x: int) -> np.ndarray:
    return np.array([(x >> i) & 1 for i in range(32)], dtype=np.uint8)


def _from_bits32(v: np.ndarray) -> int:
    return int(sum(int(b) << i for i, b in enumerate(v)))


def _raw_step(crc: int, byte: int) -> int:
    """One raw register update (no init/final xor): linear in (crc, byte)."""
    tab = _byte_table()
    return int(tab[(crc ^ byte) & 0xFF]) ^ (crc >> 8)


@functools.lru_cache(maxsize=1)
def shift_byte_matrix() -> np.ndarray:
    """S8: 32x32 GF(2) matrix advancing the raw register by one zero byte."""
    m = np.zeros((32, 32), dtype=np.uint8)
    for i in range(32):
        m[:, i] = _bits32(_raw_step(1 << i, 0))
    return m


@functools.lru_cache(maxsize=1)
def byte_in_matrix() -> np.ndarray:
    """U: 32x8 GF(2) matrix mapping one input byte's bits into the register."""
    m = np.zeros((32, 8), dtype=np.uint8)
    for j in range(8):
        m[:, j] = _bits32(_raw_step(0, 1 << j))
    return m


def _m2mul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    return (a.astype(np.uint32) @ b.astype(np.uint32) & 1).astype(np.uint8)


@functools.lru_cache(maxsize=64)
def _shift_pow2(level: int) -> np.ndarray:
    """S8^(2^level), by repeated squaring (bounded cache: level < 64)."""
    if level == 0:
        m = shift_byte_matrix()
    else:
        h = _shift_pow2(level - 1)
        m = _m2mul(h, h)
    m.setflags(write=False)
    return m


def shift_matrix(nbytes: int) -> np.ndarray:
    """S8^nbytes, composed from cached power-of-two squarings.

    Arbitrary lengths are composed on the fly (like the reference's
    mycrc32_combine loop, crc.cc:207-224) so long-running daemons don't
    accumulate a cache entry per distinct length.
    """
    result = np.eye(32, dtype=np.uint8)
    n = nbytes
    level = 0
    while n:
        if n & 1:
            result = _m2mul(_shift_pow2(level), result)
        n >>= 1
        level += 1
    result.setflags(write=False)
    return result


@functools.lru_cache(maxsize=None)
def subblock_matrix(nbytes: int) -> np.ndarray:
    """C_B: 32x(8*nbytes) matrix; R(subblock) = C_B @ bits(subblock).

    Column block for byte position p is S8^(B-1-p) @ U (byte 0 is
    processed first, so it is shifted the most).
    """
    u = byte_in_matrix()
    s8 = shift_byte_matrix()
    out = np.zeros((32, 8 * nbytes), dtype=np.uint8)
    v = u
    for p in range(nbytes - 1, -1, -1):
        out[:, 8 * p : 8 * p + 8] = v
        if p:
            v = _m2mul(s8, v)
    out.setflags(write=False)
    return out


@functools.lru_cache(maxsize=64)
def zeros_crc(nbytes: int) -> int:
    """K_L = crc32 of nbytes zero bytes (affine constant)."""
    # crc32(0^L) computed without materializing L bytes: K = (S8^L @ ones) ^ ones
    ones = _bits32(0xFFFFFFFF)
    v = (shift_matrix(nbytes).astype(np.uint32) @ ones & 1).astype(np.uint8)
    return _from_bits32(v ^ ones)


def crc32_combine(crc1: int, crc2: int, len2: int) -> int:
    """CRC of concatenation: combine(crc(A), crc(B), len(B)) == crc(A+B).

    Identical semantics to ``mycrc32_combine`` (crc.cc:207-224): apply the
    "append len2 zero bytes" operator to crc1, xor crc2.
    """
    v = _bits32(crc1 & 0xFFFFFFFF)
    v = (shift_matrix(len2).astype(np.uint32) @ v & 1).astype(np.uint8)
    return (_from_bits32(v) ^ crc2) & 0xFFFFFFFF


@functools.lru_cache(maxsize=None)
def block_crc_matrices(
    block_size: int, subblock: int = 64
) -> tuple[np.ndarray, tuple[np.ndarray, ...], int]:
    """Matrices for batched per-block CRC on TPU.

    Returns (C_sub, level_mats, K):
      * C_sub: (32, 8*subblock) sub-block matrix,
      * level_mats: for each tree level l, the 32x32 shift applied to the
        left child when merging two groups of 2^l sub-blocks
        (= S8^(subblock * 2^l)),
      * K: affine constant = crc32 of block_size zero bytes.

    ``crc(block) = tree_reduce(C_sub @ bits(subblocks)) xor K``.
    """
    assert block_size % subblock == 0
    n = block_size // subblock
    assert n & (n - 1) == 0, "sub-block count must be a power of two"
    levels = []
    l = 0
    while (1 << l) < n:
        levels.append(shift_matrix(subblock * (1 << l)))
        l += 1
    return subblock_matrix(subblock), tuple(levels), zeros_crc(block_size)


def block_crcs_golden(blocks: np.ndarray) -> np.ndarray:
    """CRC32 of each row of a (n, block_size) uint8 array (golden path)."""
    return np.array(
        [crc32(blocks[i].tobytes()) for i in range(blocks.shape[0])], dtype=np.uint32
    )
