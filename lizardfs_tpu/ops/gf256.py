"""GF(2^8) arithmetic and Reed-Solomon matrix machinery (numpy, host-side).

This is the *golden* CPU implementation of the field math used by the
erasure-coding data plane. It is numerically identical to the reference's
codec (reference: src/common/galois_field_isal.cc, src/common/reed_solomon.h):

  * field GF(2^8) with reduction polynomial 0x11d (same as Intel ISA-L),
  * log/exp tables with generator 2,
  * Vandermonde generator matrix (``gen_rs_matrix``) for small parity
    counts, Cauchy-1 matrix (``gen_cauchy1_matrix``) for m >= 5 or
    (m == 4 and k > 20) — the selection rule at reed_solomon.h:168-172,
  * Gauss-Jordan inversion over the field,
  * zero-input column elision and needed-output row selection semantics of
    ``ReedSolomon::createEncodingMatrix`` / ``createRecoveryMatrix``.

Everything here is small host-side matrix work (k, m <= 32); the bulk data
path applies these matrices either with the vectorized numpy kernel in
:mod:`lizardfs_tpu.ops.rs` or with the TPU bit-plane matmul kernels in
:mod:`lizardfs_tpu.ops.jax_ec`.
"""

from __future__ import annotations

import functools

import numpy as np

from lizardfs_tpu.constants import GF_POLY


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    """Build log/exp tables for GF(2^8) with generator 2, poly 0x11d."""
    exp = np.zeros(256, dtype=np.uint8)  # exp[i] = 2^i; exp[255] aliases exp[0] (gf_inv(1) reads it)
    log = np.zeros(256, dtype=np.uint8)  # log[x] for x != 0; log[0] meaningless
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GF_POLY
    exp[255] = exp[0]  # convenience wrap (2^255 == 2^0)
    return log, exp


GF_LOG, GF_EXP = _build_tables()

# Full 256x256 multiplication table; 64 KiB, used to vectorize the golden
# data path and to generate bit-plane matrices.
def _build_mul_table() -> np.ndarray:
    logs = GF_LOG.astype(np.int32)
    s = logs[:, None] + logs[None, :]
    s = np.where(s > 254, s - 255, s)
    t = GF_EXP[s]
    t[0, :] = 0
    t[:, 0] = 0
    return t.astype(np.uint8)


GF_MUL_TABLE = _build_mul_table()


def gf_mul(a, b):
    """Multiply in GF(2^8); accepts scalars or numpy arrays (broadcasting)."""
    return GF_MUL_TABLE[np.asarray(a, dtype=np.uint8), np.asarray(b, dtype=np.uint8)]


def gf_inv(a: int) -> int:
    """Multiplicative inverse; gf_inv(0) == 0 by ISA-L convention."""
    if a == 0:
        return 0
    return int(GF_EXP[255 - int(GF_LOG[a])])


def gf_pow(a: int, n: int) -> int:
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(int(GF_LOG[a]) * n) % 255])


def gen_rs_matrix(rows: int, k: int) -> np.ndarray:
    """Vandermonde-style generator matrix, shape (rows, k).

    Identity on the first k rows; parity row r (0-based among parity rows)
    has entries gen^j where gen = 2^r, matching ISA-L ``gf_gen_rs_matrix``
    (reference: src/common/galois_field_isal.cc:53-69).
    """
    a = np.zeros((rows, k), dtype=np.uint8)
    for i in range(k):
        a[i, i] = 1
    gen = 1
    for i in range(k, rows):
        p = 1
        for j in range(k):
            a[i, j] = p
            p = int(gf_mul(p, gen))
        gen = int(gf_mul(gen, 2))
    return a


def gen_cauchy1_matrix(rows: int, k: int) -> np.ndarray:
    """Cauchy generator matrix, shape (rows, k): identity top, then
    a[i, j] = inv(i ^ j) (reference: galois_field_isal.cc:71-85)."""
    a = np.zeros((rows, k), dtype=np.uint8)
    for i in range(k):
        a[i, i] = 1
    for i in range(k, rows):
        for j in range(k):
            a[i, j] = gf_inv(i ^ j)
    return a


@functools.lru_cache(maxsize=None)
def rs_generator_matrix(k: int, m: int) -> np.ndarray:
    """(k+m, k) generator matrix with the reference's Vandermonde/Cauchy
    selection rule (reed_solomon.h:168-172). Cached per (k, m)."""
    if m >= 5 or (m == 4 and k > 20):
        a = gen_cauchy1_matrix(k + m, k)
    else:
        a = gen_rs_matrix(k + m, k)
    a.setflags(write=False)
    return a


def gf_invert_matrix(mat: np.ndarray) -> np.ndarray:
    """Invert an (n, n) matrix over GF(2^8) by Gauss-Jordan elimination.

    Raises ValueError if singular. Pivot/elimination order matches the
    reference (galois_field_isal.cc:87-139) — with exact arithmetic the
    result is order-independent, but we mirror it anyway.
    """
    n = mat.shape[0]
    a = mat.astype(np.uint8).copy()
    out = np.eye(n, dtype=np.uint8)
    for i in range(n):
        if a[i, i] == 0:
            for j in range(i + 1, n):
                if a[j, i]:
                    a[[i, j]] = a[[j, i]]
                    out[[i, j]] = out[[j, i]]
                    break
            else:
                raise ValueError("singular matrix in GF(2^8) inversion")
        piv = gf_inv(int(a[i, i]))
        a[i] = gf_mul(a[i], piv)
        out[i] = gf_mul(out[i], piv)
        for j in range(n):
            if j == i:
                continue
            f = int(a[j, i])
            if f:
                a[j] ^= gf_mul(f, a[i])
                out[j] ^= gf_mul(f, out[i])
    return out


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Matrix product over GF(2^8): XOR-accumulated gf_mul."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    # products[i, j, l] = a[i, l] * b[l, j]
    prod = GF_MUL_TABLE[a[:, None, :], b.T[None, :, :]]
    return np.bitwise_xor.reduce(prod, axis=2)


def encoding_matrix(k: int, m: int) -> np.ndarray:
    """(m, k) matrix computing all parity parts from all data parts."""
    return rs_generator_matrix(k, m)[k:, :]


def recovery_matrix(
    k: int,
    m: int,
    available: list[int],
    wanted: list[int],
) -> np.ndarray:
    """Matrix computing ``wanted`` parts from ``available`` parts.

    Parts are globally indexed 0..k+m-1 (data first, then parity). Exactly
    k available parts must be given (any k suffice). Mirrors
    ``ReedSolomon::createRecoveryMatrix`` (reed_solomon.h:229-281):
    invert the k rows of the generator matrix for the available parts,
    then (for wanted parity parts) multiply by the wanted generator rows;
    wanted data parts select rows of the inverse directly.

    Returns shape (len(wanted), k); columns ordered by ascending available
    part index (the caller feeds parts in that order).
    """
    if len(available) != k:
        raise ValueError(f"need exactly {k} available parts, got {len(available)}")
    gen = rs_generator_matrix(k, m)
    avail = sorted(available)
    sub = gen[avail, :]  # (k, k) computes available parts from data parts
    decode = gf_invert_matrix(sub)  # computes data parts from available parts
    wanted = list(wanted)
    if all(w < k for w in wanted):
        # recover_only_data path: select rows of the inverse.
        return decode[wanted, :]
    need_rows = gen[wanted, :]  # (w, k) computes wanted parts from data parts
    return gf_matmul(need_rows, decode)


def recovery_selection(
    k: int, m: int, available: list[int], wanted: list[int]
) -> tuple[list[int], np.ndarray]:
    """Choose which available parts to read and the matrix to apply.

    The single source of truth for the reference's recover-dispatch rule
    (reed_solomon.h:97-117): if all k data parts are available, wanted
    (parity) parts are re-encoded straight from data; otherwise the first
    k available parts feed an inverted recovery matrix. Returns
    (used_part_indices, (len(wanted), k) GF matrix over those parts).
    Both the CPU and TPU backends derive their kernels from this helper,
    keeping them byte-identical by construction.
    """
    avail = sorted(available)
    data_avail = [i for i in avail if i < k]
    if len(data_avail) == k:
        return data_avail, rs_generator_matrix(k, m)[list(wanted), :]
    if len(avail) < k:
        raise ValueError(f"need {k} parts to recover, have {len(avail)}")
    used = avail[:k]
    return used, recovery_matrix(k, m, used, list(wanted))


def reduce_columns(matrix: np.ndarray, nonzero_inputs: list[int]) -> np.ndarray:
    """Drop columns whose inputs are known-zero (zero-part elision,
    reed_solomon.h:202-212). ``nonzero_inputs`` indexes into the matrix's
    column order."""
    return matrix[:, sorted(nonzero_inputs)]
