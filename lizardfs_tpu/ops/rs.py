"""Golden (numpy/CPU) Reed-Solomon codec over GF(2^8).

Byte-level mirror of the reference's ``ReedSolomon<MAXK, MAXM>`` class
(reference: src/common/reed_solomon.h:41-369): ``encode`` computes m parity
parts from k data parts, ``recover`` rebuilds any subset of missing parts
from any k available parts. ``None`` input parts are treated as all-zero
(and elided from the computation, reed_solomon.h:140-145, 202-212). The
reference's NULL-output-fragment elision is expressed here by simply
omitting unneeded indices from ``wanted``.

Data parts are 1-D uint8 arrays of equal length. This path is the
correctness oracle for the TPU kernels and the default encoder for small
requests where kernel dispatch overhead dominates.
"""

from __future__ import annotations

import numpy as np

from lizardfs_tpu.ops import gf256


def _apply(matrix: np.ndarray, parts: list[np.ndarray]) -> list[np.ndarray]:
    """out[i] = XOR_j matrix[i, j] * parts[j] over GF(2^8), vectorized.

    Equivalent to ISA-L ``ec_encode_data`` with tables from ``matrix``.
    """
    if not parts:
        size = 0
    else:
        size = parts[0].shape[0]
    rows = matrix.shape[0]
    out = [np.zeros(size, dtype=np.uint8) for _ in range(rows)]
    for j, part in enumerate(parts):
        col = matrix[:, j]
        for i in range(rows):
            c = int(col[i])
            if c == 0:
                continue
            if c == 1:
                out[i] ^= part
            else:
                out[i] ^= gf256.GF_MUL_TABLE[c][part]
    return out


def encode(k: int, m: int, data_parts: list[np.ndarray | None]) -> list[np.ndarray]:
    """Compute the m parity parts of RS(k, m) from the k data parts.

    ``data_parts[i] is None`` means part i is all zeros (elided).
    Mirrors ``ReedSolomon::encode`` (reed_solomon.h:134-155).
    """
    if len(data_parts) != k:
        raise ValueError(f"expected {k} data parts, got {len(data_parts)}")
    nonzero = [i for i, p in enumerate(data_parts) if p is not None]
    if not nonzero:
        # the reference requires at least one non-zero input part
        # (reed_solomon.h:192 assert)
        raise ValueError("at least one data part must be non-None")
    sizes = {p.shape[0] for p in data_parts if p is not None}
    if len(sizes) > 1:
        raise ValueError("all parts must have equal size")
    mat = gf256.encoding_matrix(k, m)
    mat = gf256.reduce_columns(mat, nonzero)
    parts = [np.asarray(data_parts[i], dtype=np.uint8) for i in nonzero]
    return _apply(mat, parts)


def recover(
    k: int,
    m: int,
    parts: dict[int, np.ndarray | None],
    wanted: list[int],
) -> dict[int, np.ndarray]:
    """Recover ``wanted`` part indices from available ``parts``.

    ``parts`` maps global part index (0..k+m-1, data first) to its bytes;
    a present key with value ``None`` means "available and all-zero"
    (elided from computation but still counted as available, matching
    reed_solomon.h:77-80,103-110). Any k available parts suffice; if all
    k data parts are available this reduces to (re-)encoding parity
    (reed_solomon.h:113-117).
    """
    used, mat = gf256.recovery_selection(k, m, list(parts.keys()), wanted)
    nonzero_pos = [j for j, i in enumerate(used) if parts[i] is not None]
    if not nonzero_pos:
        raise ValueError("at least one available part must be non-None")
    mat = gf256.reduce_columns(mat, nonzero_pos)
    in_parts = [np.asarray(parts[used[j]], dtype=np.uint8) for j in nonzero_pos]
    out = _apply(mat, in_parts)
    return {w: out[i] for i, w in enumerate(wanted)}


def xor_parity(parts: list[np.ndarray]) -> np.ndarray:
    """XOR parity over equal-length parts (reference block_xor semantics,
    src/common/block_xor.cc:47-62)."""
    out = np.zeros_like(parts[0])
    for p in parts:
        out ^= p
    return out
