"""Compute kernels: GF(2^8) arithmetic, Reed-Solomon, CRC32, bit-plane JAX ops."""
