"""JAX/XLA erasure-coding kernels: bit-plane GF matmul + batched CRC32.

TPU-native data plane for the ``ChunkEncoder`` boundary. Design notes:

* **GF(2^8) as MXU matmuls.** Parts are bit-sliced (8x expansion along a
  small leading axis), the RS generator/recovery matrix is expanded to its
  (8m, 8k) GF(2) bit-plane form (:mod:`lizardfs_tpu.ops.bitplane`), and
  parity bits come out of one int8 matmul with int32 accumulation
  followed by ``& 1``. No log/exp gathers, no data-dependent control
  flow; XLA tiles the (8m, 8k) x (8k, N) product straight onto the MXU.
  This replaces the reference's per-byte SSSE3/AVX2 nibble-shuffle loop
  (reference: src/common/galois_field_encode.cc:50-95).

* **CRC32 as matmul + log-tree combine.** CRC is GF(2)-affine in the
  message bits; each 64-byte sub-block contributes through a constant
  32x512 matrix and sub-block registers merge with cached 32x32 shift
  matrices (:mod:`lizardfs_tpu.ops.crc32`). All 1024 block CRCs of a
  chunk are one batched matmul plus 10 tiny combines — the serial
  byte-table loop of the reference (src/common/crc.cc:113-151) disappears.

* **Static shapes, jit-cached per geometry.** Each (k, m, part_size)
  combination traces once; chunk geometry is fixed (64 KiB blocks), so in
  steady state there are a handful of compiled programs.

All functions take/return uint8 arrays with parts as equal-length byte
streams, matching the golden codec in :mod:`lizardfs_tpu.ops.rs`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from lizardfs_tpu.constants import MFSBLOCKSIZE
from lizardfs_tpu.ops import bitplane, crc32, gf256

# Sub-block size for the CRC matmul stage. 64 bytes -> C matrix 32x512,
# contraction dim 512: good MXU shape and small VMEM footprint.
CRC_SUBBLOCK = 64


def _unpack_bits_rows(parts: jnp.ndarray) -> jnp.ndarray:
    """(r, N) uint8 -> (8r, N) int8 bit-planes; row j*8+b is bit b of part j."""
    r, n = parts.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)[None, :, None]
    bits = (parts[:, None, :] >> shifts) & 1
    return bits.astype(jnp.int8).reshape(8 * r, n)


def _pack_bits_rows(bits: jnp.ndarray) -> jnp.ndarray:
    """(8w, N) {0,1} -> (w, N) uint8, inverse of :func:`_unpack_bits_rows`."""
    w8, n = bits.shape
    w = w8 // 8
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))[None, :, None]
    return (bits.astype(jnp.uint8).reshape(w, 8, n) * weights).sum(
        axis=1, dtype=jnp.uint8
    )


def apply_gf_bitmatrix(bigm: jnp.ndarray, parts: jnp.ndarray) -> jnp.ndarray:
    """Apply an expanded (8w, 8r) GF(2) matrix to (r, N) byte parts -> (w, N).

    The core primitive behind both encode and recover.
    """
    bits = _unpack_bits_rows(parts)
    acc = jax.lax.dot_general(
        bigm,
        bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return _pack_bits_rows(acc & 1)


def _crc_tree(partial: jnp.ndarray, level_mats_t: tuple[jnp.ndarray, ...]) -> jnp.ndarray:
    """Merge (B, n, 32) sub-block registers down to (B, 32)."""
    b = partial.shape[0]
    for mat_t in level_mats_t:
        partial = partial.reshape(b, -1, 2, 32)
        left = jax.lax.dot_general(
            partial[:, :, 0, :],
            mat_t,
            dimension_numbers=(((2,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        partial = (left & 1) ^ partial[:, :, 1, :]
    return partial.reshape(b, 32)


@functools.partial(jax.jit, static_argnames=("block_size",))
def block_crcs(blocks: jnp.ndarray, block_size: int = MFSBLOCKSIZE) -> jnp.ndarray:
    """CRC32 of each row of a (B, block_size) uint8 array -> (B,) uint32.

    Matmul + tree formulation of the reference's per-block ``mycrc32``.
    """
    c_sub, levels, k_const = crc32.block_crc_matrices(block_size, CRC_SUBBLOCK)
    nsub = block_size // CRC_SUBBLOCK
    b = blocks.shape[0]
    shifts = jnp.arange(8, dtype=jnp.uint8)[None, None, :]
    bits = ((blocks[:, :, None] >> shifts) & 1).astype(jnp.int8)
    bits = bits.reshape(b, nsub, 8 * CRC_SUBBLOCK)
    partial = jax.lax.dot_general(
        bits,
        jnp.asarray(c_sub.T, dtype=jnp.int8),
        dimension_numbers=(((2,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    ) & 1
    mats = tuple(jnp.asarray(m.T, dtype=jnp.int32) for m in levels)
    reg = _crc_tree(partial, mats)
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    crc = (reg.astype(jnp.uint32) * weights[None, :]).sum(axis=1, dtype=jnp.uint32)
    return crc ^ jnp.uint32(k_const)


@functools.partial(jax.jit, static_argnames=("block_size",))
def fused_encode_crc(
    bigm: jnp.ndarray, data: jnp.ndarray, block_size: int = MFSBLOCKSIZE
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Encode parity and checksum every block of data+parity in one program.

    Args:
      bigm: (8m, 8k) expanded encoding matrix (int8).
      data: (k, N) uint8 data parts, N a multiple of block_size.
    Returns:
      (parity (m, N) uint8, data_crcs (k, N/bs) uint32,
       parity_crcs (m, N/bs) uint32).

    This is the TPU analog of the chunkserver's write pipeline: RS encode
    + per-64KiB-block CRC update in a single fused dispatch (reference
    call sites: src/mount/chunk_writer.cc:365-398 parity,
    src/common/write_executor.cc:91-96 CRC).
    """
    k, n = data.shape
    m = bigm.shape[0] // 8
    nb = n // block_size
    parity = apply_gf_bitmatrix(bigm, data)
    data_crcs = block_crcs(data.reshape(k * nb, block_size), block_size)
    parity_crcs = block_crcs(parity.reshape(m * nb, block_size), block_size)
    return parity, data_crcs.reshape(k, nb), parity_crcs.reshape(m, nb)


@jax.jit
def apply_gf(bigm: jnp.ndarray, parts: jnp.ndarray) -> jnp.ndarray:
    """Jitted :func:`apply_gf_bitmatrix` (encode or recover, per matrix)."""
    return apply_gf_bitmatrix(bigm, parts)


@jax.jit
def xor_reduce(parts: jnp.ndarray) -> jnp.ndarray:
    """(r, N) uint8 -> (N,) XOR parity (the xor2..xor9 goal family)."""
    return jax.lax.reduce(parts, jnp.uint8(0), jax.lax.bitwise_xor, (0,))


# ---------------------------------------------------------------------------
# Host-side matrix preparation (cached per geometry, mirrors the
# reference's gf_table_ caching keyed on (needed, erased, non_zero_input),
# reed_solomon.h:194-198).
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=256)
def encoding_bitmatrix(k: int, m: int) -> np.ndarray:
    """Expanded (8m, 8k) encode matrix for RS(k, m)."""
    return bitplane.expand_gf_matrix(gf256.encoding_matrix(k, m))


@functools.lru_cache(maxsize=1024)
def recovery_bitmatrix(
    k: int, m: int, available: tuple[int, ...], wanted: tuple[int, ...]
) -> np.ndarray:
    """Expanded recovery matrix computing ``wanted`` from ``available``.

    Part selection is delegated to :func:`gf256.recovery_selection` (the
    shared dispatch rule), so CPU and TPU stay byte-identical.
    """
    _, mat = gf256.recovery_selection(k, m, list(available), list(wanted))
    return bitplane.expand_gf_matrix(mat)
