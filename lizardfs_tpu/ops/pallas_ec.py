"""Pallas TPU kernels: fused GF(2^8) encode and batched CRC32.

Why Pallas here: the XLA bit-plane path materializes the 8x bit
expansion in HBM (512 MiB of int8 bits per 64 MiB chunk) and pays for
small-matmul launches; these kernels unpack bits **inside VMEM**, run
the GF(2) matmuls on the MXU as s8 x s8 -> s32 (0/1 values: exact, and
int8 runs at twice the bf16 rate), and write only real bytes back — HBM
traffic collapses to data-in + parity-out.

Kernels:
  * :func:`encode` — grid over column tiles of the (k, N) part streams;
    each step unpacks a (k, T) byte tile to (8k, T) bit planes,
    multiplies by the expanded (8m, 8k) generator matrix, reduces mod 2
    and packs to (m, T) parity bytes.
  * :func:`block_crcs` — grid over 64 KiB blocks; each step unpacks one
    block to (1024, 512) sub-block bit rows, multiplies by the constant
    (512, 32) sub-block CRC matrix, then folds the 1024 partial
    registers with a 10-level log-tree of 32x32 shift matrices
    (:mod:`lizardfs_tpu.ops.crc32` machinery).

Numerics are byte-identical to the golden path (tests enforce it).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lizardfs_tpu.constants import MFSBLOCKSIZE
from lizardfs_tpu.ops import crc32 as crc_host

CRC_SUBBLOCK = 64


def supported() -> bool:
    """Pallas kernels need a real TPU backend (Mosaic); the CPU backend
    only runs them in interpret mode (tests)."""
    try:
        return jax.devices()[0].platform != "cpu"
    except Exception:
        return False


def _unpack_tile(bytes_tile: jnp.ndarray) -> jnp.ndarray:
    """(r, T) uint8 -> (8r, T) int8 bit planes; row j*8+b = bit b."""
    r, t = bytes_tile.shape
    x = bytes_tile.astype(jnp.int32)
    shifts = jax.lax.broadcasted_iota(jnp.int32, (r, 8, t), 1)
    bits = (x[:, None, :] >> shifts) & 1
    return bits.reshape(8 * r, t).astype(jnp.int8)


def _stack_q(m: int, tile: int, max_groups: int) -> int:
    """Column-stacking factor q (see _encode_tile): doubles while the
    stacked matmul's M dim stays within _ENC_STACK_MAX, quarters stay
    lane-aligned, and q stays within ``max_groups`` (the fused kernel
    also caps q by its CRC group count so both see the same quarters).
    Pure in (m, tile, max_groups) so the VMEM budget can price the
    stacked generator before committing to a tile size."""
    q = 1
    while (
        2 * q * 8 * m <= _ENC_STACK_MAX
        and tile % (2 * q * 128) == 0
        and 2 * q <= max_groups
    ):
        q *= 2
    return q


def _stack_generator(bigm, k: int, m: int, tile: int, max_groups: int):
    """Build the block-diagonal (q*8m, q*8k) generator for q column
    quarters stacked along the contraction dim."""
    q = _stack_q(m, tile, max_groups)
    bigm_q = jnp.zeros((q * 8 * m, q * 8 * k), dtype=jnp.int8)
    for i in range(q):
        bigm_q = bigm_q.at[
            i * 8 * m:(i + 1) * 8 * m, i * 8 * k:(i + 1) * 8 * k
        ].set(bigm.astype(jnp.int8))
    return q, bigm_q


def _encode_kernel(bigm_ref, data_ref, parity_ref, *, m: int, q: int):
    parity_ref[:] = _encode_tile(bigm_ref, data_ref[:], m, q)


@functools.partial(jax.jit, static_argnames=("tile",))
def encode(bigm: jnp.ndarray, data: jnp.ndarray, tile: int = 16384) -> jnp.ndarray:
    """Fused bit-plane RS encode: (k, N) uint8 -> (m, N) uint8 parity.

    ``bigm`` is the (8m, 8k) expanded generator/recovery matrix.
    Serves both encode and recover (the matrix decides).
    """
    k, n = data.shape
    m = bigm.shape[0] // 8
    # keep bits (int8) + accumulator (int32) + tiles within a
    # conservative VMEM budget
    while tile > 512 and (9 * k + 33 * m) * tile > 8 * 2**20:
        tile //= 2
    if n % tile:
        raise ValueError(f"N={n} not a multiple of tile={tile}")
    q, bigm_q = _stack_generator(bigm, k, m, tile, max_groups=tile // 128)
    grid = (n // tile,)
    return pl.pallas_call(
        functools.partial(_encode_kernel, m=m, q=q),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((q * 8 * m, q * 8 * k), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((m, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
    )(bigm_q, data)


CRC_BLOCKS_PER_STEP = 16


def _crc_partial_kernel(csub_ref, subs_ref, out_ref):
    """Per-sub-block CRC registers: the heavy stage, MXU-bound.

    Sub-blocks are 128 bytes (full vreg lane width). Each bit plane is
    extracted in the uint8 domain and immediately contracted against its
    (128, 32) slice of the sub-block matrix; partial registers go back
    to HBM and a cheap XLA log-tree folds them (32-wide data: the fold
    is ~0.1% of the input volume, not worth fighting Mosaic layouts).
    """
    x = subs_ref[:]  # (rows, 128) uint8
    rows = x.shape[0]
    acc = jnp.zeros((rows, 32), jnp.float32)
    for b in range(8):
        plane = ((x & jnp.uint8(1 << b)) != 0).astype(jnp.bfloat16)
        acc += jax.lax.dot_general(
            plane, csub_ref[b],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    out_ref[:] = acc.astype(jnp.int32) & 1  # exact: sums <= 1024


@functools.partial(jax.jit, static_argnames=("block_size",))
def block_crcs(blocks: jnp.ndarray, block_size: int = MFSBLOCKSIZE) -> jnp.ndarray:
    """CRC32 of each row of (B, block_size) uint8 -> (B,) uint32."""
    b = blocks.shape[0]
    sub = 2 * CRC_SUBBLOCK  # 128-byte sub-blocks: full lane width
    nsub = block_size // sub
    assert nsub & (nsub - 1) == 0, "block size must give power-of-two sub-blocks"
    g = CRC_BLOCKS_PER_STEP
    bp = (b + g - 1) // g * g  # pad block count to the per-step group size
    if bp != b:
        blocks = jnp.concatenate(
            [blocks, jnp.zeros((bp - b, block_size), jnp.uint8)], axis=0
        )
    c_sub, levels, k_const = crc_host.block_crc_matrices(block_size, sub)
    # per-bit-plane slices of C^T: row t of plane b = column for bit b of
    # byte t (C^T row order is 8*t + b)
    csub_t = np.asarray(c_sub.T, dtype=np.float32)  # (8*sub, 32)
    csub_planes = np.stack([csub_t[bb::8, :] for bb in range(8)])  # (8, sub, 32)

    subs = blocks.reshape(bp * nsub, sub)
    partial = pl.pallas_call(
        _crc_partial_kernel,
        out_shape=jax.ShapeDtypeStruct((bp * nsub, 32), jnp.int32),
        grid=(bp // g,),
        in_specs=[
            pl.BlockSpec(csub_planes.shape, lambda i: (0, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((g * nsub, sub), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((g * nsub, 32), lambda i: (i, 0), memory_space=pltpu.VMEM),
    )(jnp.asarray(csub_planes, dtype=jnp.bfloat16), subs)

    # XLA log-tree fold + finalize (tiny: 32 ints per sub-block)
    part = partial.reshape(bp, nsub, 32)
    for mat in levels:
        part = part.reshape(bp, -1, 2, 32)
        left = jax.lax.dot_general(
            part[:, :, 0, :], jnp.asarray(mat.T, dtype=jnp.int32),
            dimension_numbers=(((2,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        ) & 1
        part = left ^ part[:, :, 1, :]
    reg = part.reshape(bp, 32).astype(jnp.uint32)
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    crc = (reg * weights[None, :]).sum(axis=1, dtype=jnp.uint32)
    return (crc ^ jnp.uint32(k_const))[:b]


# ---------------------------------------------------------------------------
# single-pass fused encode + CRC
#
# One pallas_call per column chunk: the data tile is read from HBM once;
# parity is computed on the MXU and written out; CRC partial registers
# for BOTH the data rows and the fresh parity rows are computed and
# folded to one 32-bit register per (row, chunk) while everything is
# still in VMEM. Only the registers (32 ints per row per chunk — ~0.1%
# of the data volume) leave the kernel; a tiny XLA epilogue combines the
# per-chunk registers of each 64 KiB block and applies the affine
# constant. Semantics match the reference's encode + per-block mycrc32
# (src/common/reed_solomon.h:134-155, crc.cc:49-64).

CRC_SUB = 128  # sub-block bytes = one full vreg lane width


def _fused_vmem_bytes(k: int, m: int, tile: int, wide: bool = False) -> int:
    rows = k + m
    kp, mp = -(-k // 8) * 8, -(-m // 8) * 8
    sg = max(tile // CRC_GROUP, 1)
    q = _stack_q(m, tile, max_groups=sg)
    return (
        2 * k * tile            # data in (x2 pipeline)
        + 2 * m * tile          # parity out (x2 pipeline)
        + 8 * k * tile          # unpacked bits, int8 (q-stacked: same)
        + 32 * m * tile         # encode accumulator, int32
        + m * tile              # packed parity bytes
        + 8 * rows * tile       # crc stacked bit planes, int8
        + rows * sg * 32 * 8    # crc acc + scan registers, int32
        + (kp * k + mp * m) * sg      # selection matrices, int8
        + 16 * 32 * 32          # shift stack, int8
        + 64 * q * q * k * m    # block-diagonal bigm_q (q*8m x q*8k int8)
        # wide CRC (ROOFLINE #3): 128-lane stage-1 acc (4x) + 4x W
        + (rows * sg * 32 * 16 + 3 * 8 * CRC_GROUP * 32 if wide else 0)
    )


CRC_GROUP = 512  # stage-1 group bytes: M = rows*T/512 fills MXU sublanes
_ENC_STACK_MAX = 128  # cap on q*8m when stacking column quarters


def _chunk_registers(x, w_ref, shifts_ref, sel_ref, group: int,
                     wide: bool = False):
    """(rows, T) uint8 tile -> (rp, 32) GF(2) CRC registers (rp = rows
    padded to x8 by the selection matrix). Extracts the bit planes and
    delegates to :func:`_registers_from_planes`."""
    rows, t = x.shape
    sc = t // group
    groups = x.reshape(rows * sc, group)
    planes = jnp.concatenate(
        [((groups & jnp.uint8(1 << b)) != 0).astype(jnp.int8)
         for b in range(8)],
        axis=1,
    )  # (n, 8G), plane-major along lanes (W rows match this order)
    return _registers_from_planes(planes, w_ref, shifts_ref, sel_ref,
                                  sc, wide)


def _registers_from_planes(planes, w_ref, shifts_ref, sel_ref, sc: int,
                           wide: bool):
    """(rows*sc, 8G) bit planes -> (rp, 32) GF(2) CRC registers.

    Stage 1 (MXU): one matmul computes the CRC register of every
    ``group``-byte span: the 8 bit planes are concatenated along the
    contraction dim and W has the per-byte-position shift matrices
    folded in, so (rows*Sc, 8G) @ (8G, 32) runs at full M and K
    utilisation (vs. 8 thin matmuls + a long fold in earlier
    revisions). Stage 2: Hillis-Steele suffix scan over each row's Sc
    group registers — level l combines spans of 2^l groups with one
    shared 32x32 shift matmul plus a sublane roll and an iota mask (no
    lane/sublane shape casts, which Mosaic cannot lower). Stage 3
    (MXU): a 0/1 selection matmul extracts each row's j=0 register
    straight into the padded output layout. All in VMEM: no
    partial-register round trip through HBM (the round-1 bottleneck).

    ``wide`` (ROOFLINE #3): stage 1's natural N=32 output fills only a
    quarter of the MXU's 128-lane output tile. The wide path multiplies
    against a (8G, 128) W whose four 32-column blocks are the register
    PRE-SHIFTED by 3G/2G/1G/0 bytes — same MXU tile count, 4x useful
    output — then folds each aligned run of 4 group registers with one
    lane select + two roll/XOR levels, replacing the first two scan
    LEVELS' 32x32 matmuls and shrinking the scan to sc/4 spans.
    """
    n = planes.shape[0]
    acc = jax.lax.dot_general(
        planes, w_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # s8 x s8 -> s32 MXU: exact, 2x the bf16 rate, half the VMEM
    if not wide:
        g = acc & 1  # (n, 32) group registers (i32: pltpu.roll needs 32b)
        j = jax.lax.broadcasted_iota(jnp.int32, (n, 32), 0) & (sc - 1)
        levels = sc.bit_length() - 1
        span = 1  # groups per scan element
    else:
        g128 = acc & 1  # (n, 128): lane block v = register << (v*G bytes)
        # row for group s needs block 3 - s%4 (its position inside the
        # 4-group span); select it into lanes 0..31 and XOR the 4
        # consecutive rows together -> span register at rows s%4 == 0
        j128 = jax.lax.broadcasted_iota(jnp.int32, (n, 128), 0) & (sc - 1)
        lane = jax.lax.broadcasted_iota(jnp.int32, (n, 128), 1)
        want = 3 - (j128 & 3)
        vals = jnp.where((lane >> 5) == want, g128, 0)
        masked = (vals[:, :32] ^ vals[:, 32:64]
                  ^ vals[:, 64:96] ^ vals[:, 96:128])
        r1 = masked ^ pltpu.roll(masked, n - 1, axis=0)
        g = r1 ^ pltpu.roll(r1, n - 2, axis=0)  # rows s%4==0: span regs
        j = jax.lax.broadcasted_iota(jnp.int32, (n, 32), 0) & (sc - 1)
        j = j >> 2  # span index; garbage rows never feed valid ones
        sc = sc // 4
        levels = sc.bit_length() - 1
        span = 4
    for l in range(levels):
        h = 1 << l
        # g'_j = g_j @ S^(span*G*h bytes)  ^  g_{j+h}  (0 past row end)
        shifted = jax.lax.dot_general(
            g.astype(jnp.int8), shifts_ref[l],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        ) & 1
        nxt = pltpu.roll(g, n - span * h, axis=0)  # g[i+span*h] at i
        nxt = jnp.where(j < sc - h, nxt, 0)
        g = shifted ^ nxt
    reg = jax.lax.dot_general(
        sel_ref[:], g.astype(jnp.int8),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # (rp, 32); exact: one 1 per selection row
    return reg & 1


def _encode_tile(bigm_ref, data, m: int, q: int):
    """RS-encode one (k, T) tile -> (m, T) parity bytes.

    ``q`` column quarters are stacked along the contraction dim against
    a block-diagonal generator (q*8m, q*8k): the parity matmul's M dim
    grows from 8m (as low as 8) to q*8m ~ 128, filling the MXU's output
    tile instead of wasting 7/8 of it. (The unused bit-plane outputs
    are dead-code-eliminated under tracing.)
    """
    packed, _bits, _pbits = _encode_tile_bits(bigm_ref, data, m, q)
    return packed


def _encode_tile_bits(bigm_ref, data, m: int, q: int):
    """_encode_tile variant that also returns the UNPACKED bit planes
    of both the data ((q*8k, Tq) int8) and the parity ((q*8m, Tq)
    int8), so the CRC stage can consume them instead of re-deriving
    planes from packed bytes (ROOFLINE #2: the re-extraction costs ~8
    VPU ops per byte over all k+m rows)."""
    k, t = data.shape
    tq = t // q
    if q == 1:
        bits = _unpack_tile(data)
    else:
        bits = jnp.concatenate(
            [_unpack_tile(data[:, i * tq:(i + 1) * tq]) for i in range(q)],
            axis=0,
        )  # (q*8k, Tq)
    acc = jax.lax.dot_general(
        bigm_ref[:], bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # (q*8m, Tq)
    pbits = acc & 1
    weights = jax.lax.broadcasted_iota(jnp.int32, (q * m, 8, tq), 1)
    packed = (pbits.reshape(q * m, 8, tq) << weights).sum(axis=1)
    packed = packed.astype(jnp.uint8)  # (q*m, Tq), quarter-major rows
    if q != 1:
        packed = jnp.concatenate(
            [packed[i * m:(i + 1) * m, :] for i in range(q)], axis=1
        )  # (m, T)
    return packed, bits, pbits.astype(jnp.int8)


def _planes_from_bits(bits, rows: int, q: int, tq: int, group: int):
    """(q*8rows, Tq) quarter-major bit rows -> (rows*sc, 8G) group-major
    CRC planes, by pure in-VMEM relayout (no re-extraction). Element
    mapping: bit b of byte (row j, abs col i_q*Tq + s_local*G + p) lives
    at bits[i_q*8rows + j*8 + b, s_local*G + p] and must land at
    planes[j*sc + (i_q*scq + s_local), b*G + p]."""
    scq = tq // group
    b = bits.reshape(q, rows, 8, scq, group)
    b = b.transpose(1, 0, 3, 2, 4)  # (rows, q, scq, 8, G)
    return b.reshape(rows * q * scq, 8 * group)


def _fused_kernel(bigm_ref, w_ref, shifts_ref, seld_ref, selp_ref,
                  data_ref, parity_ref, dreg_ref, preg_ref,
                  *, m: int, q: int, group: int, wide: bool = False,
                  reuse: bool = False):
    data = data_ref[:]
    k, t = data.shape
    if reuse:
        tq = t // q
        parity, bits, pbits = _encode_tile_bits(bigm_ref, data, m, q)
        parity_ref[:] = parity
        sc = t // group
        dreg_ref[:] = _registers_from_planes(
            _planes_from_bits(bits, k, q, tq, group),
            w_ref, shifts_ref, seld_ref, sc, wide,
        )
        preg_ref[:] = _registers_from_planes(
            _planes_from_bits(pbits, m, q, tq, group),
            w_ref, shifts_ref, selp_ref, sc, wide,
        )
        return
    parity = _encode_tile(bigm_ref, data, m, q)
    parity_ref[:] = parity
    dreg_ref[:] = _chunk_registers(
        data, w_ref, shifts_ref, seld_ref, group, wide
    )
    preg_ref[:] = _chunk_registers(
        parity, w_ref, shifts_ref, selp_ref, group, wide
    )


# Silicon-verified default (r01). The bigger-tile/bigger-budget config
# below halves per-chunk grid steps (benches/ROOFLINE.md #1) but its
# VMEM model is unverified on hardware, so production callers keep the
# proven residency; bench.py opts into the staged configs first (most
# aggressive first) and tags its JSON with whichever actually compiled.
_FUSED_VMEM_BUDGET = 10 * 2**20
# 11.5 MiB of ~16 MiB physical: ec(8,4) fits tile=32 KiB (10.1 MiB ->
# 256 steps/chunk, 2x fewer), ec(3,2) a full 64 KiB block
BIG_TILE_CONFIG = {"tile": 65536, "vmem_budget": 11_534_336}
# ROOFLINE items 2+3 on top of the big tiles: wide_crc fills the CRC
# stage-1 matmul's 128-lane output tile (4 pre-shifted register
# variants) and removes two scan levels; reuse_planes feeds the CRC
# stage from the encode's already-unpacked bit planes via in-VMEM
# relayout instead of re-extracting (~8 VPU ops/byte over k+m rows).
# Byte parity of every combination is pinned in interpret mode
# (tests/test_pallas.py); only the SPEED is a silicon question.
ROOFLINE_CONFIG = {
    "tile": 65536, "vmem_budget": 11_534_336,
    "wide_crc": True, "reuse_planes": True,
}


@functools.partial(
    jax.jit, static_argnames=(
        "block_size", "tile", "interpret", "vmem_budget", "wide_crc",
        "reuse_planes",
    )
)
def fused_encode_crc(
    bigm: jnp.ndarray,
    data: jnp.ndarray,
    block_size: int = MFSBLOCKSIZE,
    tile: int = 16384,
    interpret: bool | None = None,
    vmem_budget: int = _FUSED_VMEM_BUDGET,
    wide_crc: bool = False,
    reuse_planes: bool = False,
):
    """Single-pass fused RS encode + per-block CRC32.

    (k, N) uint8 -> (parity (m, N) uint8, dcrc (k, nb) u32, pcrc (m, nb)
    u32), byte-identical to jax_ec.fused_encode_crc / the golden codec.

    ``tile`` shrinks until it fits the VMEM budget, divides the block
    size, and divides N. Defaults are the silicon-verified residency;
    pass ``**BIG_TILE_CONFIG`` (ROOFLINE #1) or ``**ROOFLINE_CONFIG``
    (#1+#2+#3: + wide 128-lane CRC stage-1, + bit-plane reuse) — both
    numerically pinned, speed pending a live chip.
    """
    if interpret is None:
        interpret = not supported()  # CPU backend: interpret mode
    k, n = data.shape
    m = bigm.shape[0] // 8
    rows = k + m
    while tile > 2 * CRC_SUB and (
        _fused_vmem_bytes(k, m, tile, wide_crc) > vmem_budget
        or block_size % tile or n % tile
    ):
        tile //= 2
    if n % tile:
        raise ValueError(f"N={n} not a multiple of tile={tile}")
    if block_size % tile:
        raise ValueError(f"tile={tile} must divide block_size={block_size}")
    if tile & (tile - 1):
        raise ValueError(
            f"tile={tile} must be a power of two (the CRC scan doubles "
            f"span lengths per level and quarters must stay lane-aligned)"
        )
    nchunks = n // tile
    cpb = block_size // tile  # chunks per 64 KiB block
    nb = n // block_size

    group = min(CRC_GROUP, tile)
    sg = tile // group  # group registers per row per tile
    # the wide fold needs aligned runs of 4 group registers per row
    wide = bool(wide_crc) and sg % 4 == 0 and sg >= 4
    c_sub, _levels, k_const = crc_host.block_crc_matrices(block_size, group)
    # W rows match the kernel's plane-major lane concat: row b*G+p = bit
    # b of byte position p (row 8p+b of C_G^T)
    ct = np.asarray(c_sub.T, dtype=np.float32)  # (8G, 32), rows 8p+b
    w = np.concatenate([ct[b::8, :] for b in range(8)], axis=0)
    if wide:
        # (8G, 128): column block v = the group register pre-shifted by
        # v*G bytes (W @ S(vG)^T over GF(2)); the kernel's lane select
        # assigns block 3 - s%4 to group s
        w64 = w.astype(np.int64)
        w = np.concatenate([
            (w64 @ crc_host.shift_matrix(v * group).T.astype(np.int64)) % 2
            for v in range(4)
        ], axis=1).astype(np.float32)
    # scan shift matrices: level l combines spans of 2^l scan elements
    # (4 groups per element on the wide path), so every row uses the
    # SAME shift matrix at that level
    span_bytes = group * (4 if wide else 1)
    levels = (sg // (4 if wide else 1)).bit_length() - 1
    shifts = np.zeros((max(levels, 1), 32, 32), dtype=np.float32)
    for l in range(levels):
        shifts[l] = crc_host.shift_matrix(span_bytes * (1 << l)).T
    kp, mp = -(-k // 8) * 8, -(-m // 8) * 8  # register rows padded to x8
    # 0/1 selection matrices: row r of the padded output takes the
    # scanned register at sub-row r*sg (row r's full-span register)
    seld = np.zeros((kp, k * sg), dtype=np.float32)
    seld[np.arange(k), np.arange(k) * sg] = 1.0
    selp = np.zeros((mp, m * sg), dtype=np.float32)
    selp[np.arange(m), np.arange(m) * sg] = 1.0
    q, bigm_q = _stack_generator(bigm, k, m, tile, max_groups=sg)
    # plane reuse needs whole groups inside each stacked quarter
    reuse = bool(reuse_planes) and (tile // q) % group == 0 and tile >= group
    # G: combines the cpb chunk registers of one block in XLA (tiny)
    comb = np.zeros((cpb * 32, 32), dtype=np.int32)
    for c in range(cpb):
        comb[c * 32:(c + 1) * 32, :] = \
            crc_host.shift_matrix(tile * (cpb - 1 - c)).T

    kernel = functools.partial(
        _fused_kernel, m=m, q=q, group=group, wide=wide, reuse=reuse
    )
    parity, dreg, preg = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((m, n), jnp.uint8),
            jax.ShapeDtypeStruct((nchunks * kp, 32), jnp.int32),
            jax.ShapeDtypeStruct((nchunks * mp, 32), jnp.int32),
        ),
        grid=(nchunks,),
        in_specs=[
            pl.BlockSpec(bigm_q.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(w.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(shifts.shape, lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(seld.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(selp.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, tile), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((m, tile), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kp, 32), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((mp, 32), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(
        bigm_q,
        jnp.asarray(w, dtype=jnp.int8),
        jnp.asarray(shifts, dtype=jnp.int8),
        jnp.asarray(seld, dtype=jnp.int8),
        jnp.asarray(selp, dtype=jnp.int8),
        data,
    )

    def finalize(regs, nrows, npad):
        # (nchunks*npad, 32) -> (nrows, nb) final CRC values
        r = regs.reshape(nb, cpb, npad, 32)[:, :, :nrows, :]
        r = r.transpose(2, 0, 1, 3)
        r = r.reshape(nrows, nb, cpb * 32)
        folded = jax.lax.dot_general(
            r, jnp.asarray(comb),
            dimension_numbers=(((2,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        ) & 1  # (nrows, nb, 32)
        w = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
        crc = (folded.astype(jnp.uint32) * w).sum(axis=2, dtype=jnp.uint32)
        return crc ^ jnp.uint32(k_const)

    return parity, finalize(dreg, k, kp), finalize(preg, m, mp)


@functools.partial(
    jax.jit, static_argnames=(
        "block_size", "interpret", "tile", "vmem_budget", "wide_crc",
        "reuse_planes",
    )
)
def fused_decode_verify(
    bigm_rec: jnp.ndarray,
    survivors: jnp.ndarray,
    expected_crcs: jnp.ndarray,
    block_size: int = MFSBLOCKSIZE,
    interpret: bool | None = None,
    tile: int = 16384,
    vmem_budget: int = _FUSED_VMEM_BUDGET,
    wide_crc: bool = False,
    reuse_planes: bool = False,
):
    """Fused reconstruct + CRC verify of the recovered parts.

    ``bigm_rec`` is the (8r, 8k) recovery matrix mapping survivor rows
    to the r missing parts (gf256.recovery matrix via the encoder
    boundary); returns (recovered (r, N) uint8, crcs (r, nb) u32,
    ok (r, nb) bool) where ok compares against ``expected_crcs`` — the
    stored per-block CRCs of the lost parts (ReadPlanExecutor's
    post-recovery verify, reference read_plan_executor.cc + crc.cc).
    """
    recovered, _scrc, rcrc = fused_encode_crc(
        bigm_rec, survivors, block_size, interpret=interpret,
        tile=tile, vmem_budget=vmem_budget, wide_crc=wide_crc,
        reuse_planes=reuse_planes,
    )
    return recovered, rcrc, rcrc == expected_crcs.astype(jnp.uint32)
