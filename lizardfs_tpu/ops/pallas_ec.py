"""Pallas TPU kernels: fused GF(2^8) encode and batched CRC32.

Why Pallas here: the XLA bit-plane path materializes the 8x bit
expansion in HBM (512 MiB of int8 bits per 64 MiB chunk) and pays for
small-matmul launches; these kernels unpack bits **inside VMEM**, run
the GF(2) matmuls on the MXU in bf16 (0/1 values: exact in bf16 with
f32 accumulation up to 2^24), and write only real bytes back — HBM
traffic collapses to data-in + parity-out.

Kernels:
  * :func:`encode` — grid over column tiles of the (k, N) part streams;
    each step unpacks a (k, T) byte tile to (8k, T) bit planes,
    multiplies by the expanded (8m, 8k) generator matrix, reduces mod 2
    and packs to (m, T) parity bytes.
  * :func:`block_crcs` — grid over 64 KiB blocks; each step unpacks one
    block to (1024, 512) sub-block bit rows, multiplies by the constant
    (512, 32) sub-block CRC matrix, then folds the 1024 partial
    registers with a 10-level log-tree of 32x32 shift matrices
    (:mod:`lizardfs_tpu.ops.crc32` machinery).

Numerics are byte-identical to the golden path (tests enforce it).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lizardfs_tpu.constants import MFSBLOCKSIZE
from lizardfs_tpu.ops import crc32 as crc_host

CRC_SUBBLOCK = 64


def supported() -> bool:
    """Pallas kernels need a real TPU backend (Mosaic); the CPU backend
    only runs them in interpret mode (tests)."""
    try:
        return jax.devices()[0].platform != "cpu"
    except Exception:
        return False


def _unpack_tile(bytes_tile: jnp.ndarray) -> jnp.ndarray:
    """(r, T) uint8 -> (8r, T) bf16 bit planes; row j*8+b = bit b."""
    r, t = bytes_tile.shape
    x = bytes_tile.astype(jnp.int32)
    shifts = jax.lax.broadcasted_iota(jnp.int32, (r, 8, t), 1)
    bits = (x[:, None, :] >> shifts) & 1
    return bits.reshape(8 * r, t).astype(jnp.bfloat16)


def _encode_kernel(bigm_ref, data_ref, parity_ref):
    bits = _unpack_tile(data_ref[:])  # (8k, T)
    acc = jax.lax.dot_general(
        bigm_ref[:], bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (8m, T) exact integer sums
    pbits = acc.astype(jnp.int32) & 1
    m8, t = pbits.shape
    m = m8 // 8
    weights = jax.lax.broadcasted_iota(jnp.int32, (m, 8, t), 1)
    parity = (pbits.reshape(m, 8, t) << weights).sum(axis=1)
    parity_ref[:] = parity.astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("tile",))
def encode(bigm: jnp.ndarray, data: jnp.ndarray, tile: int = 16384) -> jnp.ndarray:
    """Fused bit-plane RS encode: (k, N) uint8 -> (m, N) uint8 parity.

    ``bigm`` is the (8m, 8k) expanded generator/recovery matrix as bf16.
    Serves both encode and recover (the matrix decides).
    """
    k, n = data.shape
    m = bigm.shape[0] // 8
    # keep bits + accumulator + tiles within a conservative VMEM budget
    while tile > 512 and (8 * k * 2 + 8 * m * 4 + k + m) * tile > 8 * 2**20:
        tile //= 2
    if n % tile:
        raise ValueError(f"N={n} not a multiple of tile={tile}")
    grid = (n // tile,)
    return pl.pallas_call(
        _encode_kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((8 * m, 8 * k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((m, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
    )(bigm.astype(jnp.bfloat16), data)


CRC_BLOCKS_PER_STEP = 16


def _crc_partial_kernel(csub_ref, subs_ref, out_ref):
    """Per-sub-block CRC registers: the heavy stage, MXU-bound.

    Sub-blocks are 128 bytes (full vreg lane width). Each bit plane is
    extracted in the uint8 domain and immediately contracted against its
    (128, 32) slice of the sub-block matrix; partial registers go back
    to HBM and a cheap XLA log-tree folds them (32-wide data: the fold
    is ~0.1% of the input volume, not worth fighting Mosaic layouts).
    """
    x = subs_ref[:]  # (rows, 128) uint8
    rows = x.shape[0]
    acc = jnp.zeros((rows, 32), jnp.float32)
    for b in range(8):
        plane = ((x & jnp.uint8(1 << b)) != 0).astype(jnp.bfloat16)
        acc += jax.lax.dot_general(
            plane, csub_ref[b],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    out_ref[:] = acc.astype(jnp.int32) & 1  # exact: sums <= 1024


@functools.partial(jax.jit, static_argnames=("block_size",))
def block_crcs(blocks: jnp.ndarray, block_size: int = MFSBLOCKSIZE) -> jnp.ndarray:
    """CRC32 of each row of (B, block_size) uint8 -> (B,) uint32."""
    b = blocks.shape[0]
    sub = 2 * CRC_SUBBLOCK  # 128-byte sub-blocks: full lane width
    nsub = block_size // sub
    assert nsub & (nsub - 1) == 0, "block size must give power-of-two sub-blocks"
    g = CRC_BLOCKS_PER_STEP
    bp = (b + g - 1) // g * g  # pad block count to the per-step group size
    if bp != b:
        blocks = jnp.concatenate(
            [blocks, jnp.zeros((bp - b, block_size), jnp.uint8)], axis=0
        )
    c_sub, levels, k_const = crc_host.block_crc_matrices(block_size, sub)
    # per-bit-plane slices of C^T: row t of plane b = column for bit b of
    # byte t (C^T row order is 8*t + b)
    csub_t = np.asarray(c_sub.T, dtype=np.float32)  # (8*sub, 32)
    csub_planes = np.stack([csub_t[bb::8, :] for bb in range(8)])  # (8, sub, 32)

    subs = blocks.reshape(bp * nsub, sub)
    partial = pl.pallas_call(
        _crc_partial_kernel,
        out_shape=jax.ShapeDtypeStruct((bp * nsub, 32), jnp.int32),
        grid=(bp // g,),
        in_specs=[
            pl.BlockSpec(csub_planes.shape, lambda i: (0, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((g * nsub, sub), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((g * nsub, 32), lambda i: (i, 0), memory_space=pltpu.VMEM),
    )(jnp.asarray(csub_planes, dtype=jnp.bfloat16), subs)

    # XLA log-tree fold + finalize (tiny: 32 ints per sub-block)
    part = partial.reshape(bp, nsub, 32)
    for mat in levels:
        part = part.reshape(bp, -1, 2, 32)
        left = jax.lax.dot_general(
            part[:, :, 0, :], jnp.asarray(mat.T, dtype=jnp.int32),
            dimension_numbers=(((2,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        ) & 1
        part = left ^ part[:, :, 1, :]
    reg = part.reshape(bp, 32).astype(jnp.uint32)
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    crc = (reg * weights[None, :]).sum(axis=1, dtype=jnp.uint32)
    return (crc ^ jnp.uint32(k_const))[:b]


# ---------------------------------------------------------------------------
# single-pass fused encode + CRC
#
# One pallas_call per column chunk: the data tile is read from HBM once;
# parity is computed on the MXU and written out; CRC partial registers
# for BOTH the data rows and the fresh parity rows are computed and
# folded to one 32-bit register per (row, chunk) while everything is
# still in VMEM. Only the registers (32 ints per row per chunk — ~0.1%
# of the data volume) leave the kernel; a tiny XLA epilogue combines the
# per-chunk registers of each 64 KiB block and applies the affine
# constant. Semantics match the reference's encode + per-block mycrc32
# (src/common/reed_solomon.h:134-155, crc.cc:49-64).

CRC_SUB = 128  # sub-block bytes = one full vreg lane width


def _fused_vmem_bytes(k: int, m: int, tile: int) -> int:
    rows = k + m
    sc = tile // CRC_SUB
    kp, mp = -(-k // 8) * 8, -(-m // 8) * 8
    return (
        2 * k * tile            # data in (x2 pipeline)
        + 2 * m * tile          # parity out (x2 pipeline)
        + 16 * k * tile         # unpacked bits, bf16
        + 32 * m * tile         # encode accumulator, f32
        + m * tile              # packed parity bytes
        + rows * sc * 32 * 10   # crc planes (bf16) + acc (f32) + scan g (i32)
        + (kp * k + mp * m) * sc * 2  # selection matrices, bf16
        + 16 * 32 * 32 * 2      # scan shift stack, bf16
    )


def _chunk_registers(x, csub_ref, shifts_ref, sel_ref):
    """(rows, T) uint8 tile -> (rp, 32) GF(2) CRC registers (rp = rows
    padded to x8 by the selection matrix).

    Stage 1 (MXU): per-128-byte sub-block partial registers, batched
    over rows*Sc sub-blocks. Stage 2: Hillis-Steele suffix scan over
    each row's Sc consecutive sub-registers — level l combines spans of
    2^l sub-blocks with ONE shared 32x32 shift matmul plus a sublane
    roll and an iota mask (no lane/sublane shape casts, which Mosaic
    cannot lower). Stage 3 (MXU): a 0/1 selection matmul extracts each
    row's j=0 register straight into the padded output layout. All in
    VMEM: no partial-register round trip through HBM (the round-1
    bottleneck).
    """
    rows, t = x.shape
    sc = t // CRC_SUB
    n = rows * sc
    subs = x.reshape(n, CRC_SUB)
    acc = jnp.zeros((n, 32), jnp.float32)
    for b in range(8):
        plane = ((subs & jnp.uint8(1 << b)) != 0).astype(jnp.bfloat16)
        acc += jax.lax.dot_general(
            plane, csub_ref[b],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    g = acc.astype(jnp.int32) & 1  # (n, 32) sub-block registers
    j = jax.lax.broadcasted_iota(jnp.int32, (n, 32), 0) & (sc - 1)
    levels = sc.bit_length() - 1
    for l in range(levels):
        h = 1 << l
        # g'_j = g_j @ S^(128h bytes)  ^  g_{j+h}   (0 past the row end)
        shifted = jax.lax.dot_general(
            g.astype(jnp.bfloat16), shifts_ref[l],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(jnp.int32) & 1
        nxt = pltpu.roll(g, n - h, axis=0)  # g[i+h] lands at i
        nxt = jnp.where(j < sc - h, nxt, 0)
        g = shifted ^ nxt
    reg = jax.lax.dot_general(
        sel_ref[:], g.astype(jnp.bfloat16),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (rp, 32); exact: one 1 per selection row
    return reg.astype(jnp.int32) & 1


def _fused_kernel(bigm_ref, csub_ref, shifts_ref, seld_ref, selp_ref,
                  data_ref, parity_ref, dreg_ref, preg_ref):
    data = data_ref[:]
    bits = _unpack_tile(data)  # (8k, T)
    acc = jax.lax.dot_general(
        bigm_ref[:], bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    pbits = acc.astype(jnp.int32) & 1
    m8, t = pbits.shape
    mm = m8 // 8
    weights = jax.lax.broadcasted_iota(jnp.int32, (mm, 8, t), 1)
    parity = (pbits.reshape(mm, 8, t) << weights).sum(axis=1).astype(jnp.uint8)
    parity_ref[:] = parity
    dreg_ref[:] = _chunk_registers(data, csub_ref, shifts_ref, seld_ref)
    preg_ref[:] = _chunk_registers(parity, csub_ref, shifts_ref, selp_ref)


@functools.partial(
    jax.jit, static_argnames=("block_size", "tile", "interpret")
)
def fused_encode_crc(
    bigm: jnp.ndarray,
    data: jnp.ndarray,
    block_size: int = MFSBLOCKSIZE,
    tile: int = 16384,
    interpret: bool | None = None,
):
    """Single-pass fused RS encode + per-block CRC32.

    (k, N) uint8 -> (parity (m, N) uint8, dcrc (k, nb) u32, pcrc (m, nb)
    u32), byte-identical to jax_ec.fused_encode_crc / the golden codec.
    """
    if interpret is None:
        interpret = not supported()  # CPU backend: interpret mode
    k, n = data.shape
    m = bigm.shape[0] // 8
    rows = k + m
    while tile > 2 * CRC_SUB and (
        _fused_vmem_bytes(k, m, tile) > 10 * 2**20 or block_size % tile
    ):
        tile //= 2
    if n % tile:
        raise ValueError(f"N={n} not a multiple of tile={tile}")
    if block_size % tile:
        raise ValueError(f"tile={tile} must divide block_size={block_size}")
    sc = tile // CRC_SUB
    if sc & (sc - 1):
        raise ValueError(
            f"tile={tile} must give a power-of-two sub-block count "
            f"(the CRC scan doubles span lengths per level)"
        )
    nchunks = n // tile
    cpb = block_size // tile  # chunks per 64 KiB block
    nb = n // block_size

    c_sub, _levels, k_const = crc_host.block_crc_matrices(block_size, CRC_SUB)
    csub_t = np.asarray(c_sub.T, dtype=np.float32)
    csub_planes = np.stack([csub_t[bb::8, :] for bb in range(8)])
    # scan shift matrices: level l combines spans of 2^l sub-blocks, so
    # every row uses the SAME shift(128 * 2^l) matrix at that level
    levels = sc.bit_length() - 1
    shifts = np.zeros((max(levels, 1), 32, 32), dtype=np.float32)
    for l in range(levels):
        shifts[l] = crc_host.shift_matrix(CRC_SUB * (1 << l)).T
    kp, mp = -(-k // 8) * 8, -(-m // 8) * 8  # register rows padded to x8
    # 0/1 selection matrices: row r of the padded output takes the
    # scanned register at sub-row r*sc (row r's full-span register)
    seld = np.zeros((kp, k * sc), dtype=np.float32)
    seld[np.arange(k), np.arange(k) * sc] = 1.0
    selp = np.zeros((mp, m * sc), dtype=np.float32)
    selp[np.arange(m), np.arange(m) * sc] = 1.0
    # G: combines the cpb chunk registers of one block in XLA (tiny)
    comb = np.zeros((cpb * 32, 32), dtype=np.int32)
    for c in range(cpb):
        comb[c * 32:(c + 1) * 32, :] = \
            crc_host.shift_matrix(tile * (cpb - 1 - c)).T

    parity, dreg, preg = pl.pallas_call(
        _fused_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((m, n), jnp.uint8),
            jax.ShapeDtypeStruct((nchunks * kp, 32), jnp.int32),
            jax.ShapeDtypeStruct((nchunks * mp, 32), jnp.int32),
        ),
        grid=(nchunks,),
        in_specs=[
            pl.BlockSpec((8 * m, 8 * k), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(csub_planes.shape, lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(shifts.shape, lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(seld.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(selp.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, tile), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((m, tile), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kp, 32), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((mp, 32), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(
        bigm.astype(jnp.bfloat16),
        jnp.asarray(csub_planes, dtype=jnp.bfloat16),
        jnp.asarray(shifts, dtype=jnp.bfloat16),
        jnp.asarray(seld, dtype=jnp.bfloat16),
        jnp.asarray(selp, dtype=jnp.bfloat16),
        data,
    )

    def finalize(regs, nrows, npad):
        # (nchunks*npad, 32) -> (nrows, nb) final CRC values
        r = regs.reshape(nb, cpb, npad, 32)[:, :, :nrows, :]
        r = r.transpose(2, 0, 1, 3)
        r = r.reshape(nrows, nb, cpb * 32)
        folded = jax.lax.dot_general(
            r, jnp.asarray(comb),
            dimension_numbers=(((2,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        ) & 1  # (nrows, nb, 32)
        w = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
        crc = (folded.astype(jnp.uint32) * w).sum(axis=2, dtype=jnp.uint32)
        return crc ^ jnp.uint32(k_const)

    return parity, finalize(dreg, k, kp), finalize(preg, m, mp)


@functools.partial(
    jax.jit, static_argnames=("block_size", "interpret")
)
def fused_decode_verify(
    bigm_rec: jnp.ndarray,
    survivors: jnp.ndarray,
    expected_crcs: jnp.ndarray,
    block_size: int = MFSBLOCKSIZE,
    interpret: bool | None = None,
):
    """Fused reconstruct + CRC verify of the recovered parts.

    ``bigm_rec`` is the (8r, 8k) recovery matrix mapping survivor rows
    to the r missing parts (gf256.recovery matrix via the encoder
    boundary); returns (recovered (r, N) uint8, crcs (r, nb) u32,
    ok (r, nb) bool) where ok compares against ``expected_crcs`` — the
    stored per-block CRCs of the lost parts (ReadPlanExecutor's
    post-recovery verify, reference read_plan_executor.cc + crc.cc).
    """
    recovered, _scrc, rcrc = fused_encode_crc(
        bigm_rec, survivors, block_size, interpret=interpret
    )
    return recovered, rcrc, rcrc == expected_crcs.astype(jnp.uint32)
