"""Pallas TPU kernels: fused GF(2^8) encode and batched CRC32.

Why Pallas here: the XLA bit-plane path materializes the 8x bit
expansion in HBM (512 MiB of int8 bits per 64 MiB chunk) and pays for
small-matmul launches; these kernels unpack bits **inside VMEM**, run
the GF(2) matmuls on the MXU in bf16 (0/1 values: exact in bf16 with
f32 accumulation up to 2^24), and write only real bytes back — HBM
traffic collapses to data-in + parity-out.

Kernels:
  * :func:`encode` — grid over column tiles of the (k, N) part streams;
    each step unpacks a (k, T) byte tile to (8k, T) bit planes,
    multiplies by the expanded (8m, 8k) generator matrix, reduces mod 2
    and packs to (m, T) parity bytes.
  * :func:`block_crcs` — grid over 64 KiB blocks; each step unpacks one
    block to (1024, 512) sub-block bit rows, multiplies by the constant
    (512, 32) sub-block CRC matrix, then folds the 1024 partial
    registers with a 10-level log-tree of 32x32 shift matrices
    (:mod:`lizardfs_tpu.ops.crc32` machinery).

Numerics are byte-identical to the golden path (tests enforce it).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lizardfs_tpu.constants import MFSBLOCKSIZE
from lizardfs_tpu.ops import crc32 as crc_host

CRC_SUBBLOCK = 64


def supported() -> bool:
    """Pallas kernels need a real TPU backend (Mosaic); the CPU backend
    only runs them in interpret mode (tests)."""
    try:
        return jax.devices()[0].platform != "cpu"
    except Exception:
        return False


def _unpack_tile(bytes_tile: jnp.ndarray) -> jnp.ndarray:
    """(r, T) uint8 -> (8r, T) bf16 bit planes; row j*8+b = bit b."""
    r, t = bytes_tile.shape
    x = bytes_tile.astype(jnp.int32)
    shifts = jax.lax.broadcasted_iota(jnp.int32, (r, 8, t), 1)
    bits = (x[:, None, :] >> shifts) & 1
    return bits.reshape(8 * r, t).astype(jnp.bfloat16)


def _encode_kernel(bigm_ref, data_ref, parity_ref):
    bits = _unpack_tile(data_ref[:])  # (8k, T)
    acc = jax.lax.dot_general(
        bigm_ref[:], bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (8m, T) exact integer sums
    pbits = acc.astype(jnp.int32) & 1
    m8, t = pbits.shape
    m = m8 // 8
    weights = jax.lax.broadcasted_iota(jnp.int32, (m, 8, t), 1)
    parity = (pbits.reshape(m, 8, t) << weights).sum(axis=1)
    parity_ref[:] = parity.astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("tile",))
def encode(bigm: jnp.ndarray, data: jnp.ndarray, tile: int = 16384) -> jnp.ndarray:
    """Fused bit-plane RS encode: (k, N) uint8 -> (m, N) uint8 parity.

    ``bigm`` is the (8m, 8k) expanded generator/recovery matrix as bf16.
    Serves both encode and recover (the matrix decides).
    """
    k, n = data.shape
    m = bigm.shape[0] // 8
    # keep bits + accumulator + tiles within a conservative VMEM budget
    while tile > 512 and (8 * k * 2 + 8 * m * 4 + k + m) * tile > 8 * 2**20:
        tile //= 2
    if n % tile:
        raise ValueError(f"N={n} not a multiple of tile={tile}")
    grid = (n // tile,)
    return pl.pallas_call(
        _encode_kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((8 * m, 8 * k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((m, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
    )(bigm.astype(jnp.bfloat16), data)


CRC_BLOCKS_PER_STEP = 16


def _crc_partial_kernel(csub_ref, subs_ref, out_ref):
    """Per-sub-block CRC registers: the heavy stage, MXU-bound.

    Sub-blocks are 128 bytes (full vreg lane width). Each bit plane is
    extracted in the uint8 domain and immediately contracted against its
    (128, 32) slice of the sub-block matrix; partial registers go back
    to HBM and a cheap XLA log-tree folds them (32-wide data: the fold
    is ~0.1% of the input volume, not worth fighting Mosaic layouts).
    """
    x = subs_ref[:]  # (rows, 128) uint8
    rows = x.shape[0]
    acc = jnp.zeros((rows, 32), jnp.float32)
    for b in range(8):
        plane = ((x & jnp.uint8(1 << b)) != 0).astype(jnp.bfloat16)
        acc += jax.lax.dot_general(
            plane, csub_ref[b],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    out_ref[:] = acc.astype(jnp.int32) & 1  # exact: sums <= 1024


@functools.partial(jax.jit, static_argnames=("block_size",))
def block_crcs(blocks: jnp.ndarray, block_size: int = MFSBLOCKSIZE) -> jnp.ndarray:
    """CRC32 of each row of (B, block_size) uint8 -> (B,) uint32."""
    b = blocks.shape[0]
    sub = 2 * CRC_SUBBLOCK  # 128-byte sub-blocks: full lane width
    nsub = block_size // sub
    assert nsub & (nsub - 1) == 0, "block size must give power-of-two sub-blocks"
    g = CRC_BLOCKS_PER_STEP
    bp = (b + g - 1) // g * g  # pad block count to the per-step group size
    if bp != b:
        blocks = jnp.concatenate(
            [blocks, jnp.zeros((bp - b, block_size), jnp.uint8)], axis=0
        )
    c_sub, levels, k_const = crc_host.block_crc_matrices(block_size, sub)
    # per-bit-plane slices of C^T: row t of plane b = column for bit b of
    # byte t (C^T row order is 8*t + b)
    csub_t = np.asarray(c_sub.T, dtype=np.float32)  # (8*sub, 32)
    csub_planes = np.stack([csub_t[bb::8, :] for bb in range(8)])  # (8, sub, 32)

    subs = blocks.reshape(bp * nsub, sub)
    partial = pl.pallas_call(
        _crc_partial_kernel,
        out_shape=jax.ShapeDtypeStruct((bp * nsub, 32), jnp.int32),
        grid=(bp // g,),
        in_specs=[
            pl.BlockSpec(csub_planes.shape, lambda i: (0, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((g * nsub, sub), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((g * nsub, 32), lambda i: (i, 0), memory_space=pltpu.VMEM),
    )(jnp.asarray(csub_planes, dtype=jnp.bfloat16), subs)

    # XLA log-tree fold + finalize (tiny: 32 ints per sub-block)
    part = partial.reshape(bp, nsub, 32)
    for mat in levels:
        part = part.reshape(bp, -1, 2, 32)
        left = jax.lax.dot_general(
            part[:, :, 0, :], jnp.asarray(mat.T, dtype=jnp.int32),
            dimension_numbers=(((2,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        ) & 1
        part = left ^ part[:, :, 1, :]
    reg = part.reshape(bp, 32).astype(jnp.uint32)
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)
    crc = (reg * weights[None, :]).sum(axis=1, dtype=jnp.uint32)
    return (crc ^ jnp.uint32(k_const))[:b]


@functools.partial(jax.jit, static_argnames=("block_size",))
def fused_encode_crc(
    bigm: jnp.ndarray, data: jnp.ndarray, block_size: int = MFSBLOCKSIZE
):
    """Pallas analog of jax_ec.fused_encode_crc: parity + all block CRCs."""
    k, n = data.shape
    m = bigm.shape[0] // 8
    nb = n // block_size
    parity = encode(bigm, data)
    dcrc = block_crcs(data.reshape(k * nb, block_size), block_size)
    pcrc = block_crcs(parity.reshape(m * nb, block_size), block_size)
    return parity, dcrc.reshape(k, nb), pcrc.reshape(m, nb)
