"""Bit-plane expansion of GF(2^8) matrices (host-side, numpy).

A GF(2^8) multiply by a constant c is GF(2)-linear in the operand's bits:
it is an 8x8 binary matrix B_c with column j = bits of ``c * 2^j``. An RS
encode by an (m, k) GF matrix M is therefore an (8m, 8k) binary matrix
over GF(2) applied to bit-sliced data — which on TPU becomes an int8
matmul on the MXU followed by ``& 1``. This module builds those expanded
binary matrices; :mod:`lizardfs_tpu.ops.jax_ec` applies them.
"""

from __future__ import annotations

import numpy as np

from lizardfs_tpu.ops import gf256


def expand_gf_matrix(m: np.ndarray) -> np.ndarray:
    """Expand an (w, r) GF(2^8) matrix to its (8w, 8r) GF(2) bit-plane form.

    Block (i, j) is the 8x8 binary matrix of multiplication by m[i, j]:
    entry (rr, cc) = bit rr of gf_mul(m[i, j], 1 << cc).
    """
    m = np.asarray(m, dtype=np.uint8)
    w, r = m.shape
    basis = (1 << np.arange(8, dtype=np.uint8))  # 2^cc
    # prod[i, j, cc] = m[i, j] * 2^cc in GF(2^8)
    prod = gf256.GF_MUL_TABLE[m[:, :, None], basis[None, None, :]]
    # bits[i, j, cc, rr] = bit rr of prod
    bits = (prod[..., None] >> np.arange(8, dtype=np.uint8)) & 1
    # -> [i, rr, j, cc] -> (8w, 8r)
    out = bits.transpose(0, 3, 1, 2).reshape(8 * w, 8 * r).astype(np.int8)
    return out
