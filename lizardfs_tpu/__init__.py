"""lizardfs_tpu — a TPU-native distributed file system framework.

A brand-new implementation of the LizardFS capability set
(master/chunkserver/client distributed POSIX-ish file system with N-copy,
xor2-9 and Reed-Solomon ec(k,m) replication goals) whose erasure-coding
data plane (GF(2^8) RS encode/decode, XOR parity, CRC32 checksumming)
dispatches through a pluggable ``ChunkEncoder`` boundary to JAX/XLA/Pallas
kernels on TPU, with a numpy golden path kept byte-identical for
verification.

Layout:
  ops/         compute kernels: GF(2^8) math, CRC32, bit-plane JAX kernels
  core/        ChunkEncoder boundary, slice/goal geometry
  parallel/    multi-chip sharded encode (jax.sharding.Mesh / shard_map)
  proto/       wire protocol: framing + typed serializers
  runtime/     daemon harness: event loop, config, logging
  master/      metadata server
  chunkserver/ data server
  client/      client library (read/write paths)
  models/      flagship end-to-end pipelines used by bench + graft entry
  utils/       shared helpers (deterministic data generator, etc.)
"""

__version__ = "0.1.0"

from lizardfs_tpu.constants import (
    MFSBLOCKSIZE,
    MFSBLOCKSINCHUNK,
    MFSCHUNKSIZE,
    CRC_POLY,
)
