"""Library-first client: metadata RPCs + EC read/write data paths.

The analog of the reference's libclient + mount core (reference:
src/mount/client/lizardfs_c_api.h API shape, lizard_client.cc VFS ops,
readdata.cc / writedata.cc / chunk_writer.cc data paths) — as an asyncio
library, FUSE-independent (a FUSE shim mounts on top of this, exactly
like mfs_fuse.cc wraps LizardClient).

Data paths:
  * write: per chunk — acquire (CltomaWriteChunk), split bytes into
    slice parts, **compute xor/RS parity client-side through the
    ChunkEncoder** (chunk_writer.cc:365-398 semantics), push each part
    to its chunkserver (std copies ride one chain; EC parts go direct),
    finish (CltomaWriteChunkEnd).
  * read: per chunk — locate (CltomaReadChunk), plan over available
    parts with the SliceReadPlanner, execute with the wave executor
    (recovery on failures), reassemble stripes; retries with backoff on
    plan failure re-locate and re-plan (readdata.cc:233-329 pattern).
"""

from __future__ import annotations

import asyncio
import logging
import os as _os
import time as _time

import numpy as np

from lizardfs_tpu.constants import (
    EATTR_NOCACHE,
    EATTR_NOENTRYCACHE,
    MFSBLOCKSIZE,
    MFSCHUNKSIZE,
    env_flag,
)
from lizardfs_tpu.core import geometry, plans
from lizardfs_tpu.core.encoder import ChunkEncoder, get_encoder
from lizardfs_tpu.core.read_executor import ReadError, execute_plan
from lizardfs_tpu.proto import framing
from lizardfs_tpu.proto import messages as m
from lizardfs_tpu.proto import status as st
from lizardfs_tpu.client.cache import BlockCache, ReadaheadAdviser
from lizardfs_tpu.runtime import accounting
from lizardfs_tpu.runtime import faults as _faults
from lizardfs_tpu.runtime import qos as qosmod
from lizardfs_tpu.runtime import retry as retrymod
from lizardfs_tpu.runtime import tracing
from lizardfs_tpu.runtime.metrics import PhaseBreakdown
from lizardfs_tpu.runtime.rpc import RpcConnection
from lizardfs_tpu.utils import striping

log = logging.getLogger("client")

# the pid whose cgroup classifies the current IO for limit-group
# throttling; FUSE sets it per operation from the kernel caller's
# context (reference: src/mount/io_limit_group.cc reads the fuse ctx
# pid the same way). None = this process itself.
import contextvars  # noqa: E402

IO_CALLER_PID: contextvars.ContextVar[int | None] = contextvars.ContextVar(
    "io_caller_pid", default=None
)

# status codes worth retrying a write for (infrastructure trouble);
# everything else (quota, permissions, invalid args) is permanent.
# BUSY is the QoS fair-share shed — transient BY CONTRACT (the master
# asks this tenant to back off and retry, never to error).
_TRANSIENT = {
    st.EIO, st.NO_CHUNK_SERVERS, st.CHUNK_BUSY, st.DISCONNECTED,
    st.TIMEOUT, st.WRONG_VERSION, st.CHUNK_LOST, st.NO_CHUNK, st.BUSY,
}


def _is_transient(e: Exception) -> bool:
    if isinstance(e, st.StatusError):
        return e.code in _TRANSIENT
    return isinstance(
        e, (ReadError, ConnectionError, OSError, asyncio.TimeoutError)
    )


class Client:
    def __init__(
        self,
        master_host: str,
        master_port: int,
        encoder: ChunkEncoder | None = None,
        wave_timeout: float = 0.3,
        retries: int = 5,
        master_addrs: list[tuple[str, int]] | None = None,
        metrics=None,
    ):
        # master_addrs: full list of master addresses (active + shadows);
        # the client cycles until the active one accepts its session
        self.master_addrs = master_addrs or [(master_host, master_port)]
        self.current_master_addr = self.master_addrs[0]
        self.master: RpcConnection | None = None
        self.session_id = 0
        # highest cluster fencing epoch seen on any register reply
        # (primary or replica): echoed on every redial, so a deposed
        # ex-primary this client lands on learns it was superseded and
        # steps down instead of accepting our writes. 0 = pre-HA.
        self.cluster_epoch = 0
        # default "auto": tpu on real silicon, else the native C++ SIMD
        # backend, else numpy — the old hardcoded "cpu" default made any
        # library user pay the golden path's 3.8x penalty (VERDICT r05
        # weak #2); LIZARDFS_TPU_ENCODER still overrides
        self.encoder = encoder or get_encoder(None)
        self.wave_timeout = wave_timeout
        self.retries = retries
        # QoS shed handling: how many BUSY backoff-retries one logical
        # master RPC gets before the shed surfaces to the caller
        self.busy_retries = 8
        self._info = "pyclient"
        self.cache = BlockCache()
        # reads at least this large bypass the block cache (bulk path)
        self.CACHE_BYPASS_BYTES = 4 * 1024 * 1024
        self._readahead: dict[int, ReadaheadAdviser] = {}
        # operation log ring + counters (.oplog / .stats analog)
        from collections import deque

        self.oplog: deque = deque(maxlen=1024)
        self.op_counters: dict[str, int] = {}
        # serialize concurrent writes per (inode, chunk): read-modify-
        # write on a shared stripe must not interleave (FUSE is
        # multithreaded; the reference serializes via its per-inode
        # write journal, writedata.cc)
        # (inode, chunk) -> [asyncio.Lock, refcount]; see _pwrite_chunk
        self._chunk_write_locks: dict[tuple[int, int], list] = {}
        # open handles this client registered: inode -> [handle ids]
        # (release() without an explicit handle drops the most recent)
        self._open_handles: dict[int, list[int]] = {}
        # (parent inode, name) -> (inode, expiry): TTL dentry cache for
        # path walks (see resolve); LRU-bounded
        from collections import OrderedDict as _OD

        self._dentry: "_OD[tuple[int, str], tuple[int, float]]" = _OD()
        # last-seen per-inode extra-attribute flags, learned from every
        # attr-bearing reply (the Attr blob's trailing ``eattr``):
        # EATTR_NOCACHE bypasses the block cache for the inode,
        # EATTR_NOENTRYCACHE keeps it out of the dentry cache
        self._eattr: dict[int, int] = {}
        # reusable stripe-scatter staging buffers, keyed (d, part_len):
        # a fresh 64 MiB allocation pays its page faults inside the
        # scatter copy (~2x measured cost); the write window keeps at
        # most 2 chunks in flight, so 2 buffers per shape suffice
        self._stage_buffers: dict[tuple[int, int], list[np.ndarray]] = {}
        # waiting lock requests: (inode, token) -> grant queue
        self._lock_grants: dict[tuple[int, int], asyncio.Queue] = {}
        # identity attached to permission-checked ops when the caller
        # doesn't supply one (FUSE passes the kernel caller's context)
        self.default_uid = 0
        self.default_gids = [0]
        # cluster-wide QoS (LimiterProxy analog): per limit-group
        # TokenBuckets paced by master-granted shares. Callers are
        # classified into cgroup limit groups (reference:
        # src/mount/io_limit_group.cc) — FUSE sets IO_CALLER_PID so a
        # mount shared by several containers throttles each container
        # under its own group's budget; other consumers fall under this
        # process's own cgroup.
        from lizardfs_tpu.client.io_limit_group import GroupCache

        # group -> {"bucket": TokenBucket|None, "next_renew": float}
        self._io_groups: dict[str, dict] = {}
        self._io_subsystem = ""  # learned from master replies
        self._io_group_cache = GroupCache("")
        # True while the master has ANY limit configured: unthrottled
        # fast paths (FUSE native read pool) must stand down so every
        # byte passes _throttle (the fast path cannot classify or pace)
        self.io_limits_active = False
        self.io_limits_probe_interval = 5.0
        self._limits_probe_task: asyncio.Task | None = None
        # how long a lost master may stay unreachable before ops fail
        # (election + promotion fit well inside this on a sane cluster)
        self.failover_timeout = 15.0
        # single-flight registration: concurrent ops all failing on a
        # dead master each call _reconnect; without serialization every
        # one runs its own registration handshake and the master
        # allocates a session per loser (the cross-await-race class the
        # invariant lint flags). The lock serializes registration, the
        # generation lets queued reconnects detect that a peer already
        # finished the job while they waited.
        self._conn_lock = asyncio.Lock()
        self._conn_gen = 0
        # bumped when a failover window EXHAUSTS: ops queued on the
        # lock behind a failed reconnect must fail fast, not each
        # serially re-run their own full failover_timeout window
        self._reconnect_fail_gen = 0
        # end-to-end budget for one retried data op (_retry_transient):
        # the RetryPolicy deadline that nested dials/RPC waits inherit,
        # so a wedged chunk write fails the caller in bounded time
        # instead of attempts x timeouts wall-clock
        self.op_deadline = 60.0
        # read-locate cache (reference: src/mount/chunk_locator.h
        # ReadChunkLocator's timed cache): repeat reads of a chunk skip
        # the master RPC entirely. Coherence mirrors the BlockCache's
        # three layers: dropped by the SAME invalidations (local writes,
        # truncate, master pushes — via the listener below), bypassed on
        # every read retry (a dead/stale holder re-locates), and
        # TTL-bounded as the backstop.
        self._locate_cache: dict[tuple[int, int], tuple[object, float]] = {}
        self._locate_epoch: dict[int, int] = {}
        # bumped whenever _locate_epoch is bulk-cleared: folded into the
        # per-inode epoch token so a clear can never reset an inode to a
        # previously-seen epoch value (which would let an in-flight
        # locate that raced the clear cache a pre-mutation reply)
        self._locate_gen = 0
        self.locate_cache_ttl = 3.0
        self.cache.add_invalidate_listener(self._drop_locates)
        # per-phase busy-time accounting for the write data path
        # (encode/stage/send/ack/commit); pipelined phases overlap, so
        # the phase sum may exceed wall time — see runtime.metrics.
        # "send" is the push cost (socket copy, or descriptor writes on
        # the shm-ring plane); "ack" is the windowed path's completion
        # wait (downstream backpressure). Through r06 ack waits were
        # folded into send_ms — compare r07+ send_ms to older rounds as
        # send_ms + ack_ms.
        self.write_phases = PhaseBreakdown(
            "client_write", ("encode", "stage", "send", "ack", "commit")
        )
        # the read-side twin: busy-time per logical read decomposed as
        # locate (master RPC), dial (pool-miss connects), wait (QoS
        # throttle + retry backoff + shed waits), net (socket transfer,
        # incl. the native gather call), decode (plan postprocess /
        # EC recovery), gather (stripe de-interleave). Deep layers that
        # can't see the client (conn pool, read executor) charge via
        # tracing.PHASE_SINK, activated around each logical read.
        self.read_phases = PhaseBreakdown(
            "client_read",
            ("locate", "dial", "wait", "net", "decode", "gather"),
        )
        # request-scoped span ring (runtime/tracing.py): phase charges
        # double as client-role spans when the op runs under a trace;
        # merge with daemon `trace-dump` output via tracing.merge_timeline
        self.trace_ring = tracing.SpanRing()
        # double-buffered stripe pipeline for striped (xor/ec) chunk
        # writes: encode stripe segment i+1 while segment i's parts are
        # in flight. LZ_WRITE_PIPELINE=0 is the kill switch (strictly
        # serial stage->encode->send ordering, the byte-identity golden
        # reference); LZ_WRITE_PIPELINE_SEGMENTS tunes pipeline depth.
        self.write_pipeline = env_flag("LZ_WRITE_PIPELINE")
        try:
            self.write_pipeline_segments = max(
                2, int(_os.environ.get("LZ_WRITE_PIPELINE_SEGMENTS", "4"))
            )
        except ValueError:
            self.write_pipeline_segments = 4
        # below this chunk payload size the per-segment handshake
        # overhead outweighs the overlap win — serial path handles it
        self.WRITE_PIPELINE_MIN_BYTES = 8 * 1024 * 1024
        # client-side metrics registry: the write window registers its
        # depth/credit/coalesce series here. Embedders that export a
        # registry pass their own (the NFS gateway shares its
        # gateway-local registry so the window series surface wherever
        # it is scraped); library users get a private one, readable as
        # Client.metrics.to_prometheus().
        from lizardfs_tpu.runtime.metrics import Metrics

        self.metrics = metrics if metrics is not None else Metrics()
        # adaptive N-deep write window (spends PR 1's phase telemetry):
        # up to LZ_WRITE_WINDOW stripe segments ride unacknowledged per
        # striped chunk write under per-chunkserver credits + a shared
        # staging-byte budget, with depth adapted from live encode/send
        # busy fractions; finished chunks coalesce their WriteChunkEnd
        # commits into one master round trip per window flush.
        # LZ_WRITE_WINDOW=0 is the kill switch: the PR-1 double-buffered
        # pipeline (per-segment ack barriers, per-chunk commits) runs
        # byte- and wire-identically to before.
        from lizardfs_tpu.client.write_window import WriteWindow

        try:
            _depth = int(_os.environ.get("LZ_WRITE_WINDOW", "8"))
        except ValueError:
            _depth = 8
        try:
            _cs_credits = int(_os.environ.get("LZ_WRITE_CS_CREDITS", "0"))
        except ValueError:
            _cs_credits = 0
        try:
            _budget_mb = int(
                _os.environ.get("LZ_WRITE_WINDOW_BYTES_MB", "128")
            )
        except ValueError:
            _budget_mb = 128
        self.write_window = (
            WriteWindow(
                _depth, metrics=self.metrics,
                cs_credits=_cs_credits or None,
                budget_bytes=max(_budget_mb, 1) * 2**20,
            )
            if _depth > 0 else None
        )
        # shadow read replicas (LZ_SHADOW_READS kill switch, default on
        # when more than one master address is configured): read-mostly
        # metadata RPCs route to a shadow serving consistency-tokened
        # replies; anything mutating still goes to the primary only.
        # Monotonic reads: every reply's token (meta_version = applied
        # changelog position) ratchets _meta_floor, and a replica reply
        # older than the floor is retried through the primary. With the
        # switch off (or a single address) every RPC goes to the
        # primary exactly as before.
        from lizardfs_tpu.constants import shadow_reads_enabled

        self.shadow_reads = (
            shadow_reads_enabled() and len(self.master_addrs) > 1
        )
        self._meta_floor = 0
        # CRC-rejected parts already reported to the master this
        # session: one report per (chunk, part, holder) — a degraded
        # chunk re-read every second must not spam the master
        self._damage_reported: set = set()
        # fault-injection fires attributed to the client role land in
        # this registry (faults_injected{site,action})
        _faults.attach_metrics("client", self.metrics)
        # per-session op accounting (runtime/accounting.py): LOGICAL
        # reads/writes charge exactly once at the public-API boundary —
        # replica fallbacks, transient retries, and RMW retry loops are
        # implementation detail below this line (the PR-7 double-count
        # class, pinned across detsched seeds in test_op_accounting).
        # Gateways share this registry, so their per-session view rides
        # whatever exporter embeds the client.
        self.session_ops = accounting.SessionOps(
            self.metrics, "client", max_sessions=8
        )
        self._replica: RpcConnection | None = None
        self._replica_addr: tuple[str, int] | None = None
        self._replica_retry_at = 0.0
        self._replica_dialing = False
        if self.shadow_reads:
            self.metrics.counter(
                "shadow_reads",
                help="read RPCs served by a shadow replica",
            )
            self.metrics.counter(
                "shadow_stale_retries",
                help="replica replies older than the monotonic-reads "
                     "floor, retried through the primary",
            )
            self.metrics.counter(
                "shadow_fallbacks",
                help="replica RPCs rerouted to the primary (connection "
                     "failure or replica refusal)",
            )

    def _io_group_of_caller(self) -> str:
        import os

        pid = IO_CALLER_PID.get()
        return self._io_group_cache.classify(
            pid if pid is not None else os.getpid()
        )

    async def _throttle(self, nbytes: int) -> None:
        """Apply the master-coordinated IO limit to a data transfer,
        under the calling process's limit group. Traced as its own
        ``throttle`` span: QoS pacing and the limit-renew RPC are
        deliberately excluded from the send phase (charging pacing as
        transfer time would misattribute), so without a span of their
        own they would be an anonymous hole in every merged timeline."""
        tw0 = _time.time()
        try:
            await self._throttle_inner(nbytes)
        finally:
            self.trace_ring.record(
                tracing.current_trace_id(), "throttle", tw0, _time.time(),
                role="client",
            )

    async def _throttle_inner(self, nbytes: int) -> None:
        group = self._io_group_of_caller()
        state = self._io_groups.setdefault(
            group, {"bucket": None, "next_renew": 0.0}
        )
        now = _time.monotonic()
        if now >= state["next_renew"]:
            state["next_renew"] = now + 1.0
            try:
                r = await self.master.call(
                    m.CltomaIoLimitRequest, group=group, probe=0,
                    timeout=5.0
                )
                rate = float(r.bytes_per_sec)
                state["next_renew"] = now + r.renew_ms / 1000.0
                self.io_limits_active = bool(
                    getattr(r, "limits_active", 0)
                )
                if r.subsystem != self._io_subsystem:
                    # master names the cgroup hierarchy to classify in;
                    # reclassify everyone under it from now on
                    from lizardfs_tpu.client.io_limit_group import GroupCache

                    self._io_subsystem = r.subsystem
                    self._io_group_cache = GroupCache(r.subsystem)
                if rate <= 0:
                    state["bucket"] = None
                elif state["bucket"] is None:
                    from lizardfs_tpu.runtime.limiter import TokenBucket

                    bucket = TokenBucket(rate, burst=rate)
                    bucket._tokens = 0.0  # pace from the start
                    state["bucket"] = bucket
                else:
                    state["bucket"].rate = rate
                    state["bucket"].burst = rate
            except (ConnectionError, asyncio.TimeoutError, st.StatusError):
                pass  # keep the previous allocation
        if state["bucket"] is not None:
            await state["bucket"].acquire(nbytes)

    def _uid(self, uid) -> int:
        return self.default_uid if uid is None else uid

    def _ident(self, uid, gids) -> dict:
        return {
            "uid": self._uid(uid),
            "gids": list(self.default_gids) if gids is None else list(gids),
        }

    def _record(self, op: str, **kw) -> None:
        self.oplog.append((_time.time(), op, kw))
        self.op_counters[op] = self.op_counters.get(op, 0) + 1

    async def _retry_transient(self, what: str, attempt_fn) -> None:
        """Run ``attempt_fn`` under the unified RetryPolicy
        (runtime/retry.py): jittered exponential backoff on TRANSIENT
        failures, permanent errors surface immediately, and the policy's
        end-to-end deadline threads through nested calls (dials, RPC
        timeouts) so stacked retries share ONE budget instead of
        multiplying. Always makes at least one attempt regardless of
        the retries setting."""
        policy = retrymod.RetryPolicy(
            attempts=max(self.retries, 1),
            base_delay=0.2, max_delay=2.0,
            deadline=self.op_deadline,
            transient=_is_transient,
        )
        try:
            await policy.run(attempt_fn, what=what, log=log)
        except retrymod.RetryError as e:
            raise st.StatusError(
                st.EIO, f"{what} failed after retries: {e.last}"
            ) from e.last

    # --- session -----------------------------------------------------------------

    async def connect(self, info: str = "pyclient", password: str = "") -> None:
        # single-flight: registration mutates session identity
        # (session_id, master conn, token floor) across awaits — only
        # one coroutine may run the handshake at a time. _reconnect
        # holds the same lock around its whole failover policy.
        async with self._conn_lock:
            await self._connect_locked(info, password)

    async def _connect_locked(self, info: str, password: str) -> None:
        """Registration handshake body. Caller MUST hold _conn_lock."""
        self._info = info
        self._password = password
        # spawn the native-IO pool threads while the process is quiet:
        # lazy spawn inside submit() blocks the event loop under GIL
        # pressure (measured 150-600 ms during EC write fan-out)
        from lizardfs_tpu.core import native_io

        if native_io.available():
            native_io.prestart_executors()
        last: Exception | None = None
        for addr in self.master_addrs:
            try:
                conn = await RpcConnection.connect(*addr)
                reply = await conn.call_ok(
                    m.CltomaRegister, session_id=self.session_id, info=info,
                    password=password,
                    # fencing epoch echo: a zombie ex-primary steps down
                    # on seeing a higher epoch than it ever applied
                    epoch=self.cluster_epoch,
                )
                self.cluster_epoch = max(
                    self.cluster_epoch, getattr(reply, "epoch", 0)
                )
                self.master = conn
                self.current_master_addr = addr  # failover moves this
                # lint: waive(cross-await-race): every caller holds _conn_lock (connect/_reconnect) — the handshake is single-flight and adopts the server-issued id
                self.session_id = reply.session_id
                # the identity this process's data-plane requests carry
                # (CltocsRead/WriteInit trailing session_id): module-
                # global because read_executor is module functions
                accounting.set_process_session(self.session_id)
                # the primary's position at registration seeds the
                # monotonic-reads floor: a replica must be at least
                # this caught up before any of its replies are accepted
                self._note_token(reply)
                if self._replica_addr == addr:
                    # the old replica peer is the new primary
                    await self._drop_replica()
                conn.on_push(m.MatoclLockGranted, self._on_lock_granted)
                conn.on_push(
                    m.MatoclCacheInvalidate, self._on_cache_invalidate
                )
                # one-shot probe: fast paths (FUSE native reads) need to
                # know AT MOUNT TIME whether any IO limit is configured
                # — a read-only workload would otherwise never learn.
                # Errors stay inside the helper: registration already
                # succeeded, so a failed probe must not fail over to
                # the next master address
                await self._probe_limits_active()
                # keep the flag tracking RUNTIME config changes: a
                # read-only workload on the native fast path never
                # calls _throttle, so a SIGHUP that enables limits
                # would otherwise go unnoticed forever
                if (self._limits_probe_task is None
                        or self._limits_probe_task.done()):
                    # detached: connect() may run inside a failover
                    # RetryPolicy and this loop outlives its deadline
                    self._limits_probe_task = retrymod.spawn_detached(
                        self._limits_probe_loop()
                    )
                # registration generation: reconnects queued on
                # _conn_lock see the bump and skip their own handshake
                self._conn_gen += 1
                return
            except (OSError, ConnectionError, st.StatusError, asyncio.TimeoutError) as e:
                last = e
        raise ConnectionError(f"no active master reachable: {last}")

    def _t0(self) -> tuple[float, float]:
        """(perf_counter, wall) pair opening a phase: the first feeds
        the PhaseBreakdown, the second anchors the span's timeline."""
        return (_time.perf_counter(), _time.time())

    def _phase(self, name: str, t0: tuple[float, float]) -> None:
        """Charge a write phase and, when the op runs under a trace,
        record the same interval as a client-role span."""
        self.write_phases.add(name, _time.perf_counter() - t0[0])
        self.trace_ring.record(
            tracing.current_trace_id(), name, t0[1], _time.time(),
            role="client",
        )

    def _read_phase(self, name: str, t0: tuple[float, float]) -> None:
        """Charge a read phase (+ client-role span under a trace)."""
        self.read_phases.add(name, _time.perf_counter() - t0[0])
        self.trace_ring.record(
            tracing.current_trace_id(), f"read:{name}", t0[1], _time.time(),
            role="client",
        )

    def _read_sink(self, phase: str, t0, t1) -> None:
        """tracing.PHASE_SINK target: layers below the client (connection
        pool dials, read-executor socket waits and plan postprocess)
        charge the ambient logical read's phases here. Pool-miss dials
        double as the ``dial`` queue-wait gate."""
        self.read_phases.add(phase, max(t1[0] - t0[0], 0.0))
        tid = tracing.current_trace_id()
        if tid:
            self.trace_ring.record(
                tid, f"read:{phase}", t0[1], t1[1], role="client"
            )
        if phase == "dial":
            # ring=None: the read:dial span above already lands in the
            # attribution queue bucket; a twin span would be noise
            tracing.charge_queue_wait(
                self.metrics, None, "dial", "default", t0, role="client"
            )

    async def _busy_retry(self, fn, what: str):
        """Honor QoS fair-share sheds: a BUSY status is retried here
        with a jittered backoff seeded by the server's retry-after
        hint, clamped by the ambient RetryPolicy deadline so stacked
        layers never amplify the wait. Exhausted attempts (or a budget
        too small for even one backoff) surface the BUSY StatusError —
        gateways map it (S3: 503 SlowDown, NFS: JUKEBOX delay)."""
        attempt = 0
        while True:
            try:
                return await fn()
            except st.StatusError as e:
                if e.code != st.BUSY:
                    raise
                if getattr(e, "_busy_exhausted", False):
                    # an INNER busy-retry layer (e.g. _call inside a
                    # _call_read fallback) already burned its attempts:
                    # retrying here would amplify to attempts^2 and
                    # re-record the op on each re-entry
                    raise
                delay = qosmod.busy_backoff_s(e.retry_after_ms, attempt)
                rem = retrymod.budget()
                if attempt >= self.busy_retries or (
                    rem is not None and rem <= delay
                ):
                    e._busy_exhausted = True
                    raise
                self.metrics.counter(
                    "qos_busy_waits",
                    help="master RPCs shed with BUSY by fair-share "
                         "admission and retried after backoff",
                ).inc()
                log.debug("%s shed (BUSY), retry %d in %.3fs",
                          what, attempt + 1, delay)
                # shed-retry waits are a queue-wait gate: the op did no
                # work, it queued behind fair-share admission
                w0 = tracing.phase_t0()
                await asyncio.sleep(delay)
                tracing.charge_queue_wait(
                    self.metrics, self.trace_ring, "busy_retry", "default",
                    w0, role="client",
                )
                tracing.charge_phase("wait", w0)
                attempt += 1

    async def _call(self, msg_cls, **fields):
        """Master RPC with transparent reconnect+retry on a lost or
        demoted master (failover support) and backoff+retry on QoS
        sheds. RPCs whose schema carries the trailing ``trace_id``
        field get the current request trace attached automatically."""
        # record ONCE, outside the busy-retry loop: a shed-and-retried
        # op is one logical op in op_counters/oplog
        self._record(msg_cls.__name__)
        return await self._busy_retry(
            lambda: self._call_once(msg_cls, **fields), msg_cls.__name__
        )

    async def _call_once(self, msg_cls, **fields):
        if msg_cls.FIELDS and msg_cls.FIELDS[-1][0] == "trace_id":
            tid = tracing.current_trace_id()
            if tid:
                fields.setdefault("trace_id", tid)
        try:
            r = await self.master.call_ok(msg_cls, **fields)
        except (ConnectionError, asyncio.TimeoutError):
            await self._reconnect()
            r = await self.master.call_ok(msg_cls, **fields)
        self._note_token(r)
        self._note_eattr(getattr(r, "attr", None))
        return r

    @staticmethod
    def _token_of(reply) -> int:
        """Consistency token of a reply: its trailing ``meta_version``,
        or the nested Attr's (MatoclAttrReply carries the token on the
        Attr tail — Attr must stay the message's terminal field)."""
        mv = getattr(reply, "meta_version", 0)
        if not mv:
            mv = getattr(getattr(reply, "attr", None), "meta_version", 0)
        return mv

    def _note_token(self, reply) -> None:
        """Ratchet the monotonic-reads floor from any tokened reply
        (primary or replica — the floor is what the session has
        OBSERVED, wherever it observed it)."""
        mv = self._token_of(reply)
        if mv > self._meta_floor:
            self._meta_floor = mv

    async def _drop_replica(self) -> None:
        conn, self._replica = self._replica, None
        self._replica_addr = None
        if conn is not None:
            await conn.close()

    async def _replica_conn(self) -> "RpcConnection | None":
        """The live replica connection, dialing one lazily. Dial
        failures back off 5 s and the caller falls through to the
        primary — replica trouble must never add latency beyond the one
        failed attempt (primary-fallback contract)."""
        conn = self._replica
        if conn is not None and not conn.closed:
            return conn
        now = _time.monotonic()
        if (
            self._replica_dialing
            or now < self._replica_retry_at
            or not self.session_id
        ):
            return None
        self._replica_dialing = True
        self._replica_retry_at = now + 5.0
        try:
            for addr in self.master_addrs:
                if addr == self.current_master_addr:
                    continue
                conn = None
                try:
                    # bounded dial: a blackholed shadow must cost the
                    # caller ~2 s once per retry window, never the OS
                    # connect timeout (primary-fallback contract)
                    conn = await asyncio.wait_for(
                        RpcConnection.connect(*addr), timeout=2.0
                    )
                    reply = await conn.call(
                        m.CltomaRegister, session_id=self.session_id,
                        info=self._info + "/replica",
                        password=getattr(self, "_password", ""),
                        replica_ok=1, epoch=self.cluster_epoch,
                        timeout=5.0,
                    )
                    # replica replies carry the shadow's replayed epoch:
                    # adopting it here means the NEXT primary redial
                    # presents the post-election epoch even if the
                    # client never reached the new active yet
                    self.cluster_epoch = max(
                        self.cluster_epoch, getattr(reply, "epoch", 0)
                    )
                    if getattr(reply, "status", 1) == st.OK:
                        self._note_token(reply)
                        self._replica = conn
                        self._replica_addr = addr
                        return conn
                    await conn.close()
                except (OSError, ConnectionError, asyncio.TimeoutError):
                    if conn is not None:
                        await conn.close()
            return None
        finally:
            self._replica_dialing = False

    async def _call_read(self, msg_cls, **fields):
        """Read-mostly RPC, routed to a shadow replica when one serves.

        The monotonic-reads contract: accept a replica reply only when
        its token is >= the floor this session has observed; otherwise
        count a stale retry and re-issue through the primary. Replica
        connection failures and refusals (NOT_POSSIBLE — promoted
        shadow, server-side kill switch, non-servable op) fall through
        to the primary too. QoS BUSY sheds (either leg) back off and
        retry via _busy_retry — a shed is never an error and never a
        spurious stale-retry count."""
        if not self.shadow_reads:
            return await self._call(msg_cls, **fields)
        return await self._busy_retry(
            lambda: self._call_read_once(msg_cls, **fields),
            msg_cls.__name__,
        )

    async def _call_read_once(self, msg_cls, **fields):
        # ONE busy-retry layer: every fallback below re-enters
        # _call (whose own busy loop handles primary sheds); a replica
        # BUSY raises out to _call_read's wrapper instead of nesting
        conn = await self._replica_conn()
        if conn is None:
            return await self._call(msg_cls, **fields)
        # same trace attachment as _call: a replica-served read must
        # not vanish from request traces (the serving-master span is
        # exactly what replica-latency debugging needs)
        if msg_cls.FIELDS and msg_cls.FIELDS[-1][0] == "trace_id":
            tid = tracing.current_trace_id()
            if tid:
                fields.setdefault("trace_id", tid)
        try:
            r = await conn.call(msg_cls, timeout=10.0, **fields)
        except (OSError, ConnectionError, asyncio.TimeoutError):
            await self._drop_replica()
            self.metrics.counter("shadow_fallbacks").inc()
            return await self._call(msg_cls, **fields)
        status = getattr(r, "status", 0)
        if status == st.NOT_POSSIBLE:
            # refusal (promoted shadow, cut follow link, server-side
            # kill switch): drop the link and back off — keeping it
            # would pay a wasted round trip on EVERY read for as long
            # as the condition lasts
            await self._drop_replica()
            self._replica_retry_at = _time.monotonic() + 5.0
            self.metrics.counter("shadow_fallbacks").inc()
            return await self._call(msg_cls, **fields)
        if status == st.BUSY:
            # fair-share shed on the replica leg: checked BEFORE the
            # token floor (the tokenless BUSY reply is a shed, not
            # staleness — it must not count a spurious stale retry).
            # The link stays up; _call_read's wrapper backs off and
            # retries through whichever leg serves then.
            raise st.StatusError(
                st.BUSY, msg_cls.__name__,
                retry_after_ms=getattr(r, "retry_after_ms", 0),
            )
        if self._token_of(r) < self._meta_floor:
            self.metrics.counter("shadow_stale_retries").inc()
            return await self._call(msg_cls, **fields)
        self._note_token(r)
        self.metrics.counter("shadow_reads").inc()
        # record ONLY on the replica-served path: every fallback above
        # re-enters _call, which records — one logical op must count
        # once in op_counters/oplog wherever it was served
        self._record(msg_cls.__name__)
        r._replica_served = True  # read-path guards key off this
        if status != st.OK:
            raise st.StatusError(status, msg_cls.__name__)
        self._note_eattr(getattr(r, "attr", None))
        return r

    def _note_eattr(self, attr) -> None:
        """Track per-inode eattr flags from any attr-bearing reply so
        cache paths can enforce NOCACHE/NOENTRYCACHE without a second
        RPC. Zero flags still overwrite (a cleared flag must lift)."""
        if attr is None or not getattr(attr, "inode", 0):
            return
        if len(self._eattr) > 65536:
            # bound by dropping only UNFLAGGED entries: forgetting a
            # zero costs nothing (0 is the default), while forgetting a
            # NOCACHE/NOENTRYCACHE flag would silently re-enable the
            # caches the flag forbids until the next attr reply
            self._eattr = {k: v for k, v in self._eattr.items() if v}
        self._eattr[attr.inode] = attr.eattr

    async def _reconnect(self) -> None:
        """Cycle the master address list with backoff until one accepts
        (or ``failover_timeout`` passes): after the active master dies,
        an election takes time — during it EVERY address refuses (dead)
        or answers NOT_POSSIBLE (still shadow), and a single pass would
        fail exactly the ops the address list exists to save (reference:
        the mount's fs_reconnect loop). Expressed as a RetryPolicy so
        the failover window is ONE deadline every nested dial inherits
        (a blackholed master host — SYN silently dropped — costs a
        bounded attempt, never the OS ~2 min SYN timeout).

        Single-flight: every op failing on the dead master lands here
        at once. The first holds _conn_lock through the whole failover
        window; the rest queue on the lock and, once inside, see the
        bumped registration generation and return without running a
        second handshake against the fresh master."""
        gen = self._conn_gen
        fail_gen = self._reconnect_fail_gen
        async with self._conn_lock:
            if self._conn_gen != gen:
                return  # a queued-ahead reconnect already registered
            if self._reconnect_fail_gen != fail_gen:
                # a queued-ahead reconnect already burned a full
                # failover window and lost — fail this op now instead
                # of serially burning another window per waiter
                raise ConnectionError(
                    "failover window exhausted (concurrent reconnect)"
                )
            policy = retrymod.RetryPolicy(
                attempts=10_000,  # the deadline, not the count, bounds
                base_delay=0.1, max_delay=1.0, jitter=0.2,
                deadline=self.failover_timeout,
                attempt_timeout=5.0 * len(self.master_addrs),
                transient=lambda e: isinstance(
                    e, (ConnectionError, OSError, asyncio.TimeoutError)
                ),
            )
            try:
                await policy.run(
                    lambda: self._connect_locked(
                        self._info, getattr(self, "_password", "")
                    ),
                    what="master failover", log=log,
                )
            except retrymod.RetryError as e:
                self._reconnect_fail_gen += 1
                raise ConnectionError(
                    f"failover window exhausted: {e.last}"
                ) from None

    async def _probe_limits_active(self) -> None:
        """Probe-only IoLimitRequest (probe=1: never joins the
        allocation table): refresh io_limits_active, swallowing every
        transport error — callers must not fail on a lost probe."""
        try:
            r = await self.master.call(
                m.CltomaIoLimitRequest, group="", probe=1, timeout=5.0
            )
            self.io_limits_active = bool(getattr(r, "limits_active", 0))
        except (ConnectionError, OSError, asyncio.TimeoutError,
                st.StatusError):
            pass  # reconnect path re-probes at connect

    def _drop_locates(self, inode: int) -> None:
        """BlockCache invalidate-listener + end-of-write hook: any
        invalidation of an inode's data drops its cached chunk
        locations, and bumps the inode's epoch so an in-flight locate
        that raced the invalidation refuses to store its reply (the
        BlockCache's revoked-put rule, applied to locations)."""
        for key in [k for k in self._locate_cache if k[0] == inode]:
            del self._locate_cache[key]
        self._locate_epoch[inode] = self._locate_epoch.get(inode, 0) + 1
        if len(self._locate_epoch) > 65536:
            # bulk-evict the bound, but never reset an inode to a
            # previously-seen epoch: the generation makes every
            # pre-clear token stale forever (ADVICE r05)
            self._locate_epoch.clear()
            self._locate_gen += 1

    def _locate_token(self, inode: int) -> tuple[int, int]:
        """Epoch token captured before a locate RPC and compared after:
        unequal means an invalidation (or a table clear) raced the RPC
        and the reply must not be cached. Folding the clear generation
        in keeps tokens unique across `_locate_epoch.clear()`."""
        return (self._locate_gen, self._locate_epoch.get(inode, 0))

    async def _limits_probe_loop(self) -> None:
        """Periodic probe so io_limits_active tracks runtime config
        reloads (SIGHUP/admin) even on workloads that never _throttle."""
        while True:
            await asyncio.sleep(self.io_limits_probe_interval)
            await self._probe_limits_active()

    async def close(self) -> None:
        if self._limits_probe_task is not None:
            self._limits_probe_task.cancel()
            self._limits_probe_task = None
        await self._drop_replica()
        if self.master is not None:
            if self.read_phases.reps or self.write_phases.reps:
                # parting stats push: the session's phase breakdowns
                # stay visible in `top` past disconnect (best effort)
                await self.push_session_stats()
            try:
                # clean goodbye: the master releases our locks now
                # instead of holding them for the crash-grace window
                await self.master.call(m.CltomaGoodbye, timeout=2.0)
            except (ConnectionError, OSError, asyncio.TimeoutError,
                    st.StatusError):
                pass
            await self.master.close()

    # --- metadata ops ---------------------------------------------------------------

    async def lookup(self, parent: int, name: str, uid: int | None = None,
                     gids: list[int] | None = None) -> m.Attr:
        r = await self._call_read(
            m.CltomaLookup, parent=parent, name=name, **self._ident(uid, gids)
        )
        return r.attr

    async def open(self, inode: int) -> int:
        """Register an open handle with the master: while held, the
        file survives unlink/trash-expiry (sustained files — reference
        "reserved" namespace). Returns the handle id to pass to
        release() (retry-safe: the master dedupes on it)."""
        import secrets

        handle = secrets.randbits(64)
        await self._call(m.CltomaOpen, inode=inode, handle=handle)
        self._open_handles.setdefault(inode, []).append(handle)
        return handle

    async def release(self, inode: int, handle: int | None = None) -> None:
        """Drop one open handle (best effort: a lost release is cleaned
        up by the master's session teardown / orphan sweep)."""
        handles = self._open_handles.get(inode, [])
        if handle is None:
            handle = handles[-1] if handles else 0
        if handle in handles:
            handles.remove(handle)
            if not handles:
                self._open_handles.pop(inode, None)
        try:
            await self._call(m.CltomaRelease, inode=inode, handle=handle)
        except (st.StatusError, ConnectionError, asyncio.TimeoutError):
            pass

    async def getattr(self, inode: int) -> m.Attr:
        r = await self._call_read(m.CltomaGetattr, inode=inode)
        return r.attr

    async def tape_info(self, inode: int) -> dict:
        """Tape-copy state: {"wanted", "pending", "copies", "fresh",
        "demoted", "recalling", "forced"}."""
        import json as _json

        r = await self._call(m.CltomaTapeInfo, inode=inode)
        return _json.loads(r.json)

    async def tape_demote(self, inode: int, uid: int | None = None,
                          gids: list[int] | None = None) -> None:
        """Demote a file to the tape tier (frees its chunk data once a
        fresh archival copy exists). CHUNK_BUSY means the master queued
        a forced archive — retry after it lands."""
        await self._call(
            m.CltomaTapeDemote, inode=inode, **self._ident(uid, gids)
        )
        self._drop_locates(inode)
        self.cache.invalidate(inode)

    async def tape_recall(self, inode: int) -> None:
        """Recall a demoted file from the tape tier; returns once the
        master restored the bytes (no-op for a live file). Callers that
        hit TAPE_RECALL on a read retry it after this resolves."""
        await self._call(m.CltomaTapeRecall, inode=inode)
        self._drop_locates(inode)
        self.cache.invalidate(inode)

    async def statfs(self) -> tuple[int, int]:
        """Cluster (total_bytes, available_bytes) across chunkservers."""
        r = await self._call(m.CltomaStatFs)
        return r.total_space, r.avail_space

    async def mkdir(
        self, parent: int, name: str, mode: int = 0o755, uid: int = 0, gid: int = 0
    ) -> m.Attr:
        r = await self._call(
            m.CltomaMkdir, parent=parent, name=name, mode=mode, uid=uid, gid=gid
        )
        self._dentry_drop(parent, name)
        return r.attr

    async def create(
        self, parent: int, name: str, mode: int = 0o644, uid: int = 0, gid: int = 0
    ) -> m.Attr:
        r = await self._call(
            m.CltomaCreate, parent=parent, name=name, mode=mode, uid=uid, gid=gid
        )
        self._dentry_drop(parent, name)
        return r.attr

    async def readdir(self, inode: int, uid: int | None = None,
                      gids: list[int] | None = None) -> list[m.DirEntry]:
        r = await self._call_read(
            m.CltomaReaddir, inode=inode, **self._ident(uid, gids)
        )
        return r.entries

    async def unlink(self, parent: int, name: str, uid: int | None = None,
                     gids: list[int] | None = None) -> None:
        await self._call(
            m.CltomaUnlink, parent=parent, name=name, **self._ident(uid, gids)
        )
        self._dentry_drop(parent, name)

    async def rmdir(self, parent: int, name: str, uid: int | None = None,
                     gids: list[int] | None = None) -> None:
        await self._call(
            m.CltomaRmdir, parent=parent, name=name, **self._ident(uid, gids)
        )
        self._dentry_drop(parent, name)

    async def rename(self, psrc: int, nsrc: str, pdst: int, ndst: str,
                     uid: int | None = None,
                     gids: list[int] | None = None) -> None:
        await self._call(
            m.CltomaRename,
            parent_src=psrc, name_src=nsrc, parent_dst=pdst, name_dst=ndst,
            **self._ident(uid, gids),
        )
        self._dentry_drop(psrc, nsrc)
        self._dentry_drop(pdst, ndst)

    async def symlink(self, parent: int, name: str, target: str,
                      uid: int = 0, gid: int = 0) -> m.Attr:
        r = await self._call(
            m.CltomaSymlink, parent=parent, name=name, target=target,
            uid=uid, gid=gid
        )
        self._dentry_drop(parent, name)
        return r.attr

    async def readlink(self, inode: int) -> str:
        r = await self._call_read(m.CltomaReadlink, inode=inode)
        return r.target

    async def link(self, inode: int, parent: int, name: str,
                   uid: int | None = None,
                   gids: list[int] | None = None) -> m.Attr:
        r = await self._call(
            m.CltomaLink, inode=inode, parent=parent, name=name,
            **self._ident(uid, gids),
        )
        self._dentry_drop(parent, name)
        return r.attr

    async def setgoal(self, inode: int, goal: int,
                      uid: int | None = None) -> None:
        await self._call(m.CltomaSetGoal, inode=inode, goal=goal,
                         uid=self._uid(uid))

    async def geteattr(self, inode: int) -> int:
        """Per-inode extra-attribute flags (constants.EATTR_*)."""
        return (await self.getattr(inode)).eattr

    async def seteattr(self, inode: int, eattr: int,
                       uid: int | None = None) -> m.Attr:
        """Set the inode's extra-attribute flags wholesale (the CLI's
        +flag/-flag arithmetic happens client-side over geteattr)."""
        r = await self._call(
            m.CltomaSetEattr, inode=inode, eattr=eattr, uid=self._uid(uid)
        )
        if eattr & EATTR_NOCACHE:
            # stop serving already-cached blocks the moment the flag
            # lands — the flag forbids the cache, not just new fills
            self.cache.invalidate(inode)
        return r.attr

    async def truncate(self, inode: int, length: int, uid: int | None = None,
                       gids: list[int] | None = None) -> m.Attr:
        r = await self._call(
            m.CltomaTruncate, inode=inode, length=length,
            **self._ident(uid, gids),
        )
        self.cache.invalidate(inode)
        return r.attr

    async def setattr(
        self, inode: int, set_mask: int, mode: int = 0, uid: int = 0,
        gid: int = 0, atime: int = 0, mtime: int = 0, trash_time: int = 0,
        caller_uid: int | None = None, caller_gids: list[int] | None = None,
    ) -> m.Attr:
        ident = self._ident(caller_uid, caller_gids)
        r = await self._call(
            m.CltomaSetattr, inode=inode, set_mask=set_mask, mode=mode,
            uid=uid, gid=gid, atime=atime, mtime=mtime, trash_time=trash_time,
            caller_uid=ident["uid"], caller_gids=ident["gids"],
        )
        return r.attr

    async def settrashtime(self, inode: int, seconds: int) -> m.Attr:
        return await self.setattr(inode, 32, trash_time=seconds)

    # directory-entry cache TTL for path walks (reference: the mount's
    # direntry cache / kernel entry_timeout model — staleness across
    # OTHER clients' renames is bounded by this; local mutations
    # invalidate immediately)
    DENTRY_TTL = 1.0

    def _dentry_drop(self, parent: int, name: str) -> None:
        self._dentry.pop((parent, name), None)

    async def resolve(self, path: str) -> m.Attr:
        """Walk an absolute path from the root inode.

        Intermediate DIRECTORY components come from a TTL dentry cache
        (FUSE resolves a path per operation — an uncached walk costs
        O(depth) master RPCs per op); the leaf is always looked up
        fresh so its attributes (size!) are never stale."""
        comps = [c for c in path.strip("/").split("/") if c]
        if not comps:
            return await self.getattr(1)
        now = _time.monotonic()
        parent = 1
        for comp in comps[:-1]:
            hit = self._dentry.get((parent, comp))
            if hit is not None and hit[1] > now:
                self._dentry.move_to_end((parent, comp))
                parent = hit[0]
                continue
            attr = await self.lookup(parent, comp)
            if attr.ftype == m.FTYPE_DIR and not (
                attr.eattr & EATTR_NOENTRYCACHE
            ):
                # lint: waive(cross-await-race): TTL-bounded dentry hint — the key must name the pre-await (parent, comp) the lookup resolved; a racing invalidation costs at most DENTRY_TTL of staleness
                self._dentry[(parent, comp)] = (
                    attr.inode, now + self.DENTRY_TTL
                )
                # reassignment keeps the old LRU slot; a refreshed
                # entry must not be the first evicted
                self._dentry.move_to_end((parent, comp))
                while len(self._dentry) > 65536:
                    self._dentry.popitem(last=False)
            parent = attr.inode
        return await self.lookup(parent, comps[-1])

    async def resolve_parent(self, path: str) -> tuple[m.Attr, str]:
        """-> (parent dir attr, leaf name) for an absolute path."""
        path = path.rstrip("/")
        parent_path, _, name = path.rpartition("/")
        if not name:
            raise st.StatusError(st.EINVAL, "path has no leaf")
        return await self.resolve(parent_path or "/"), name

    async def chunk_info(self, inode: int, chunk_index: int) -> m.MatoclReadChunk:
        """Chunk id/version/locations at a file position (fileinfo)."""
        return await self._call_read(
            m.CltomaReadChunk, inode=inode, chunk_index=chunk_index,
            **self._ident(None, None),
        )

    async def snapshot(self, src_inode: int, dst_parent: int, dst_name: str,
                       uid: int | None = None,
                       gids: list[int] | None = None) -> m.Attr:
        """COW snapshot of a file or subtree (makesnapshot analog)."""
        r = await self._call(
            m.CltomaSnapshot, src_inode=src_inode, dst_parent=dst_parent,
            dst_name=dst_name, **self._ident(uid, gids),
        )
        return r.attr

    async def filerepair(self, inode: int,
                         uid: int | None = None,
                         gids: list[int] | None = None) -> dict:
        """Repair a file with unrecoverable chunks (file_repair.cc
        analog): returns {"repaired_versions", "zeroed",
        "queued_rebuild", "ok_chunks"} counts."""
        import json as _json

        r = await self._call(
            m.CltomaFileRepair, inode=inode, **self._ident(uid, gids)
        )
        return _json.loads(r.json)

    async def append_chunks(self, inode_dst: int, inode_src: int,
                            uid: int | None = None,
                            gids: list[int] | None = None) -> m.Attr:
        """O(1) chunk-level concatenation of src onto dst (appendchunks
        verb; chunks are shared + refcounted, COW on later writes)."""
        r = await self._call(
            m.CltomaAppendChunks, inode_dst=inode_dst,
            inode_src=inode_src, **self._ident(uid, gids),
        )
        self._drop_locates(inode_dst)
        self.cache.invalidate(inode_dst)
        return r.attr

    async def set_xattr(self, inode: int, name: str, value: bytes,
                        uid: int | None = None,
                        gids: list[int] | None = None) -> None:
        await self._call(m.CltomaSetXattr, inode=inode, name=name,
                         value=value, **self._ident(uid, gids))

    async def get_xattr(self, inode: int, name: str,
                        uid: int | None = None,
                        gids: list[int] | None = None) -> bytes:
        r = await self._call(m.CltomaGetXattr, inode=inode, name=name,
                             **self._ident(uid, gids))
        return r.value

    async def remove_xattr(self, inode: int, name: str,
                           uid: int | None = None,
                           gids: list[int] | None = None) -> None:
        await self._call(m.CltomaSetXattr, inode=inode, name=name, value=b"",
                         **self._ident(uid, gids))

    async def list_xattr(self, inode: int, uid: int | None = None,
                         gids: list[int] | None = None) -> list[str]:
        # uid/gids accepted for interface symmetry; listxattr(2) does not
        # require access to the inode, so no identity goes on the wire
        r = await self._call(m.CltomaListXattr, inode=inode)
        return r.names

    async def set_quota(
        self, kind: str, owner_id: int, *, soft_inodes: int = 0,
        hard_inodes: int = 0, soft_bytes: int = 0, hard_bytes: int = 0,
        remove: bool = False, uid: int | None = None,
    ) -> None:
        await self._call(
            m.CltomaSetQuota, kind=kind, owner_id=owner_id,
            soft_inodes=soft_inodes, hard_inodes=hard_inodes,
            soft_bytes=soft_bytes, hard_bytes=hard_bytes, remove=remove,
            uid=self._uid(uid),
        )

    async def get_quota(self, uid: int | None = None,
                        gids: list[int] | None = None) -> list[dict]:
        import json

        r = await self._call(m.CltomaGetQuota, **self._ident(uid, gids))
        return json.loads(r.json)

    async def set_acl(
        self, inode: int, access: dict | None, default: dict | None = None,
        uid: int | None = None, gids: list[int] | None = None,
    ) -> None:
        import json

        await self._call(
            m.CltomaSetAcl, inode=inode,
            json=json.dumps({"access": access, "default": default}),
            **self._ident(uid, gids),
        )

    async def get_acl(self, inode: int) -> dict:
        import json

        r = await self._call(m.CltomaGetAcl, inode=inode)
        return json.loads(r.json)

    async def set_rich_acl(
        self, inode: int, acl: dict | None,
        uid: int | None = None, gids: list[int] | None = None,
    ) -> None:
        import json

        await self._call(
            m.CltomaSetRichAcl, inode=inode,
            json=json.dumps(acl) if acl is not None else "",
            **self._ident(uid, gids),
        )

    async def get_rich_acl(self, inode: int) -> dict | None:
        import json

        r = await self._call(m.CltomaGetRichAcl, inode=inode)
        return json.loads(r.json).get("rich")

    async def access(
        self, inode: int, uid: int, gids: list[int], mask: int
    ) -> bool:
        try:
            await self._call_read(
                m.CltomaAccess, inode=inode, uid=uid, gids=gids, mask=mask
            )
            return True
        except st.StatusError as e:
            if e.code == st.EACCES:
                return False
            raise

    async def trash_list(self, uid: int | None = None) -> list[dict]:
        import json

        r = await self._call(m.CltomaTrashList,
                             uid=self._uid(uid))
        return json.loads(r.json)

    async def undelete(self, inode: int, uid: int | None = None) -> None:
        await self._call(m.CltomaUndelete, inode=inode,
                         uid=self._uid(uid))

    # --- locking -----------------------------------------------------------

    async def flock(
        self, inode: int, ltype: int, token: int = 0, wait: bool = False,
        timeout: float = 30.0,
    ) -> bool:
        """BSD flock (1=shared 2=exclusive 0=unlock). wait=True blocks
        until granted (the master pushes the grant). False = refused."""
        return await self._lock(inode, 1, token, 0, 0, ltype, wait, timeout)

    async def posix_lock(
        self, inode: int, start: int, end: int, ltype: int, token: int = 0,
        wait: bool = False, timeout: float = 30.0,
    ) -> bool:
        return await self._lock(inode, 0, token, start, end, ltype, wait, timeout)

    async def test_lock(self, inode: int, start: int, end: int, ltype: int,
                        token: int = 0) -> bool:
        """True iff the lock would be grantable (F_GETLK)."""
        r = await self.master.call(
            m.CltomaLockOp, op=2, inode=inode, token=token, start=start,
            end=end, ltype=ltype, wait=False,
        )
        return r.status == st.OK

    async def _on_lock_granted(self, push: m.MatoclLockGranted) -> None:
        q = self._lock_grants.get((push.inode, push.token))
        if q is not None:
            q.put_nowait(True)

    async def _on_cache_invalidate(self, push) -> None:
        """Master push: another session mutated this file — drop its
        cached blocks (reference: matoclserv.cc data-cache
        invalidation to mounts). The push carries the mutation's
        changelog position: raising the floor here means the NEXT read
        can't be served pre-mutation by a lagging replica."""
        self._note_token(push)
        ci = None if push.chunk_index == 0xFFFFFFFF else push.chunk_index
        self.cache.invalidate(push.inode, ci)
        self._record("cache_invalidate", inode=push.inode)

    async def _lock(self, inode, op, token, start, end, ltype, wait, timeout):
        key = (inode, token)
        grant_q: asyncio.Queue = asyncio.Queue()
        if wait:
            # one persistent push handler (installed at connect) fans out
            # to per-(inode, token) waiters — concurrent waits don't
            # clobber each other
            self._lock_grants[key] = grant_q
        try:
            r = await self.master.call(
                m.CltomaLockOp, op=op, inode=inode, token=token, start=start,
                end=end, ltype=ltype, wait=wait,
            )
            if r.status == st.OK:
                return True
            if r.status == st.LOCKED and wait:
                try:
                    await asyncio.wait_for(grant_q.get(), timeout)
                    return True
                except asyncio.TimeoutError:
                    # cancel the queued request master-side so it isn't
                    # granted to a caller that already gave up
                    await self.master.call(
                        m.CltomaLockOp, op=op, inode=inode, token=token,
                        start=start, end=end, ltype=0, wait=False,
                    )
                    return False
            return False
        finally:
            if wait:
                self._lock_grants.pop(key, None)

    # --- write path -------------------------------------------------------------------

    async def write_file(self, inode: int, data: bytes | np.ndarray) -> None:
        """Stream-write file contents from offset 0 (create/overwrite).

        Overwriting with shorter content truncates to the new length
        (the master's WriteChunkEnd only ever grows the file, matching
        the reference's extend-on-write semantics)."""
        data = np.frombuffer(bytes(data), dtype=np.uint8)
        total = len(data)
        wall_t0 = _time.perf_counter()
        # each top-level write is one traced request (unless the caller
        # already runs under a trace); chunk tasks inherit the context,
        # and a trace WE started is cleared on the way out so the next
        # op in this task gets its own id
        tid, fresh_trace = tracing.begin()
        tw0 = _time.time()
        try:
            # every chunk task spawned below copies this context — the
            # native scatter path reads the session from it in-task
            session_ctx = accounting.task_session(self.session_id)
            session_ctx.__enter__()
            old_length = (await self.getattr(inode)).length
            self.trace_ring.record(
                tid, "getattr", tw0, _time.time(), role="client"
            )
            # a small in-flight window pipelines chunk N+1's grant +
            # transfer behind chunk N's tail (write_cache_window
            # analog); chunks are independent (separate ids/versions)
            # and the master's WriteChunkEnd only ever grows the file,
            # so completion order doesn't matter
            window = asyncio.Semaphore(2)
            # with the write window active, clean chunk ends coalesce
            # into one CltomaWriteChunkEndBatch per flush instead of a
            # commit handshake per chunk (multi-chunk files pay one
            # master round trip per window drain)
            defer = self.write_window is not None

            async def write_one(ci: int, piece: np.ndarray, end: int) -> None:
                async with window:
                    async def attempt():
                        await self._write_chunk(
                            inode, ci, piece, file_length=end,
                            defer_end=defer,
                        )

                    await self._retry_transient(f"write chunk {ci}", attempt)

            tasks = []
            pos = 0
            index = 0
            while pos < total:
                end = min(pos + MFSCHUNKSIZE, total)
                tasks.append(asyncio.ensure_future(
                    write_one(index, data[pos:end], end)
                ))
                pos = end
                index += 1
            ok = False
            try:
                for t in tasks:
                    await t
                ok = True
            finally:
                for t in tasks:
                    t.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)
                if ok:
                    # quota raises here must surface like a per-chunk
                    # end's would
                    await self._flush_chunk_ends()
                else:
                    # error unwind: chunks that DID land must still
                    # commit (their bytes are on the chunkservers), but
                    # a flush failure must not mask the original error
                    try:
                        await self._flush_chunk_ends()
                    except (st.StatusError, ConnectionError, OSError,
                            asyncio.TimeoutError):
                        log.warning(
                            "coalesced commit flush failed during unwind"
                        )
            if old_length > total:
                await self.truncate(inode, total)
            self.write_phases.add_wall(_time.perf_counter() - wall_t0)
            self.trace_ring.record(
                tid, "write_file", tw0, _time.time(), role="client",
                bytes=total,
            )
            # ONE logical write == ONE accounting record, regardless of
            # how many transient retries the chunks above burned
            self.session_ops.record(
                self.session_id, "write",
                _time.perf_counter() - wall_t0, nbytes=total, trace_id=tid,
            )
        finally:
            # manual __enter__/__exit__ pair: the session scope must
            # cover the whole body without re-indenting it under a
            # second with-block (tokens reset in reverse order, same
            # task, so pairing across the try/finally is sound)
            session_ctx.__exit__(None, None, None)
            tracing.end(fresh_trace)

    async def pwrite(self, inode: int, offset: int, data: bytes | np.ndarray) -> None:
        """Positional write at an arbitrary offset (POSIX pwrite).

        Partial stripes are handled with read-modify-write: the affected
        stripes' current data is read back (with recovery if parts are
        down), patched, parity recomputed client-side, and all affected
        blocks rewritten — the chunk_writer.cc:471-533 pattern.
        """
        data = np.frombuffer(bytes(data), dtype=np.uint8)
        if len(data) == 0:
            return
        wall_t0 = _time.perf_counter()
        tid, fresh_trace = tracing.begin()
        tw0 = _time.time()
        try:
            # session scope for the RMW read-backs + native write path
            # (paired __exit__ in the finally, as in write_file)
            session_ctx = accounting.task_session(self.session_id)
            session_ctx.__enter__()
            old_length = (await self.getattr(inode)).length
            end = offset + len(data)
            pos = offset
            while pos < end:
                ci = pos // MFSCHUNKSIZE
                coff = pos % MFSCHUNKSIZE
                take = min(MFSCHUNKSIZE - coff, end - pos)
                await self._pwrite_chunk(
                    inode, ci, coff,
                    data[pos - offset : pos - offset + take],
                    old_length, max(old_length, end),
                )
                pos += take
            # the RMW path charges encode/send phases above — close the
            # rep so phase sums stay attributable against wall time for
            # pwrite-heavy workloads too
            self.write_phases.add_wall(_time.perf_counter() - wall_t0)
            self.trace_ring.record(
                tid, "pwrite", tw0, _time.time(), role="client",
                bytes=len(data),
            )
            # one logical pwrite counts once — RMW retries inside
            # _pwrite_chunk are implementation detail
            self.session_ops.record(
                self.session_id, "write",
                _time.perf_counter() - wall_t0, nbytes=len(data),
                trace_id=tid,
            )
        finally:
            session_ctx.__exit__(None, None, None)
            tracing.end(fresh_trace)

    async def _pwrite_chunk(
        self, inode: int, ci: int, coff: int, piece: np.ndarray,
        old_length: int, new_length: int,
    ) -> None:
        key = (inode, ci)
        # [lock, refcount]: long-lived mounts touch unboundedly many
        # (inode, chunk) pairs, so entries are dropped once nobody holds
        # or awaits them (a plain locked() check would race with waiters)
        entry = self._chunk_write_locks.get(key)
        if entry is None:
            entry = self._chunk_write_locks[key] = [asyncio.Lock(), 0]
        entry[1] += 1
        try:
            async with entry[0]:
                # a failed attempt can leave parts torn (some written,
                # some not, parity stale); each retry takes a FRESH grant
                # — the version bump drops unreachable holders and the
                # full region rewrite restores stripe consistency on the
                # survivors. The RMW read-back happens ONCE and is
                # reused across retries (rmw_cache): a retry that
                # re-read the region would decode a MIX of first-attempt
                # and stale parts — torn state — and write the garbage
                # back over the preserved bytes (caught by the
                # s3-multipart chaos schedule: SIGKILL mid-RMW)
                rmw_cache: dict = {}

                async def attempt():
                    await self._pwrite_chunk_locked(
                        inode, ci, coff, piece, old_length, new_length,
                        rmw_cache,
                    )

                await self._retry_transient(f"pwrite chunk {ci}", attempt)
        finally:
            entry[1] -= 1
            if entry[1] == 0 and self._chunk_write_locks.get(key) is entry:
                del self._chunk_write_locks[key]

    async def _pwrite_chunk_locked(
        self, inode: int, ci: int, coff: int, piece: np.ndarray,
        old_length: int, new_length: int,
        rmw_cache: dict | None = None,
    ) -> None:
        grant = await self._call(
            m.CltomaWriteChunk, inode=inode, chunk_index=ci,
            **self._ident(None, None),
        )
        self.cache.invalidate(inode, ci)
        status_code = st.EIO
        try:
            copies: dict[int, list[m.PartLocation]] = {}
            slice_type = None
            for loc in grant.locations:
                cpt = geometry.ChunkPartType.from_id(loc.part_id)
                slice_type = cpt.type if slice_type is None else slice_type
                copies.setdefault(cpt.part, []).append(loc)
            if slice_type is None:
                raise st.StatusError(st.NO_CHUNK_SERVERS, "no locations granted")
            if slice_type.is_standard:
                # plain copies: patch the byte range in every replica chain
                await self._write_part(
                    grant.chunk_id, grant.version, copies[0], piece,
                    len(piece), part_offset=coff,
                )
            else:
                # use the grant's file length, not the caller's snapshot:
                # concurrent writers may have extended the file since
                await self._rmw_striped(grant, slice_type, copies, ci, coff,
                                        piece, grant.file_length, rmw_cache)
            status_code = st.OK
        finally:
            await self._call(
                m.CltomaWriteChunkEnd,
                chunk_id=grant.chunk_id, inode=inode, chunk_index=ci,
                file_length=new_length, status=status_code,
            )
            # a locate cached BETWEEN this write's grant and its end
            # carries the pre-write length/identity — drop again now
            # (the master's end-of-write push excludes our own session)
            self._drop_locates(inode)

    async def _rmw_striped(
        self, grant, slice_type, copies, ci: int, coff: int,
        piece: np.ndarray, old_length: int,
        rmw_cache: dict | None = None,
    ) -> None:
        d = slice_type.data_parts
        first_data = 1 if slice_type.is_xor else 0
        stripe_bytes = d * MFSBLOCKSIZE
        lo_s = coff // stripe_bytes
        hi_s = (coff + len(piece) - 1) // stripe_bytes
        nstripes = hi_s - lo_s + 1
        region_start = lo_s * stripe_bytes
        if rmw_cache is not None and "region" in rmw_cache:
            # retry after a torn first attempt: re-reading the stripes
            # now would decode a mix of already-rewritten and stale
            # parts — reuse the region assembled BEFORE any of our
            # writes touched the wire, making retries write-only
            region = rmw_cache["region"]
            await self._rmw_send(grant, slice_type, copies, lo_s,
                                 nstripes, region)
            return
        region = np.zeros(nstripes * stripe_bytes, dtype=np.uint8)

        chunk_len_old = min(max(old_length - ci * MFSCHUNKSIZE, 0), MFSCHUNKSIZE)
        overlap_end = min(chunk_len_old, region_start + len(region))
        fully_covered = (
            coff == region_start and coff + len(piece) >= overlap_end
        )
        if overlap_end > region_start and not fully_covered:
            # read back the stripes being partially overwritten,
            # preferring healthy copies (same scoring as the read path)
            from lizardfs_tpu.core.cs_stats import GLOBAL_STATS

            def best(locs):
                top = max(
                    locs,
                    key=lambda l: GLOBAL_STATS.score(
                        (l.addr.host, l.addr.port)
                    ),
                )
                return ((top.addr.host, top.addr.port), top.part_id)

            by_part = {p: best(locs) for p, locs in copies.items()}
            part_sizes = {
                p: striping.part_length(slice_type, p, chunk_len_old)
                for p in range(slice_type.expected_parts)
            }
            wanted = [first_data + i for i in range(d)]
            planner = plans.SliceReadPlanner(
                slice_type, list(by_part.keys()),
                scores={p: GLOBAL_STATS.score(a)
                        for p, (a, _) in by_part.items()},
                encoder=self.encoder,
            )
            if not planner.is_readable(wanted):
                raise ReadError("not enough parts for read-modify-write")
            plan = planner.build_plan(wanted, lo_s, nstripes, part_sizes)
            buf = await execute_plan(
                plan, grant.chunk_id, grant.version, by_part,
                wave_timeout=self.wave_timeout,
            )
            bps = nstripes * MFSBLOCKSIZE
            data_parts = {
                wanted[i]: buf[i * bps : (i + 1) * bps] for i in range(d)
            }
            region[:] = striping.assemble_chunk(
                data_parts, slice_type, len(region)
            )
        region[coff - region_start : coff - region_start + len(piece)] = piece
        if rmw_cache is not None:
            # stash the patched region BEFORE any write hits the wire:
            # this is the one pre-torn snapshot a retry may trust
            rmw_cache["region"] = region
        await self._rmw_send(grant, slice_type, copies, lo_s, nstripes,
                             region)

    async def _rmw_send(self, grant, slice_type, copies, lo_s: int,
                        nstripes: int, region: np.ndarray) -> None:
        """Encode + rewrite the RMW region's parts (the write half of
        _rmw_striped, shared by first attempts and torn-state
        retries)."""
        t0 = self._t0()
        parts = await asyncio.to_thread(
            striping.split_chunk, region, slice_type, self.encoder
        )
        self._phase("encode", t0)
        sends = []
        for part_idx, locs in copies.items():
            stream = parts.get(part_idx)
            if stream is None:
                continue
            sends.append(
                self._write_part(
                    grant.chunk_id, grant.version, locs,
                    stream[: nstripes * MFSBLOCKSIZE],
                    nstripes * MFSBLOCKSIZE,
                    part_offset=lo_s * MFSBLOCKSIZE,
                )
            )
        t0 = self._t0()
        await asyncio.gather(*sends)
        self._phase("send", t0)

    async def _write_chunk(
        self, inode: int, chunk_index: int, chunk_data: np.ndarray,
        file_length: int, defer_end: bool = False,
    ) -> None:
        t0 = self._t0()
        grant = await self._call(
            m.CltomaWriteChunk, inode=inode, chunk_index=chunk_index,
            **self._ident(None, None),
        )
        self._phase("commit", t0)
        self.cache.invalidate(inode, chunk_index)
        status_code = st.EIO
        try:
            await self._push_chunk_parts(grant, chunk_data)
            status_code = st.OK
        finally:
            if (defer_end and status_code == st.OK
                    and self.write_window is not None):
                # commit coalescing: queue the end record; the window's
                # owner (write_file) flushes the batch as ONE master
                # round trip. Only CLEAN ends coalesce — a failed write
                # must release the master's chunk lock before the retry
                # takes a fresh grant, so it commits immediately below.
                if self.write_window.queue_end(
                    grant.chunk_id, inode, chunk_index, file_length,
                    st.OK,
                ):
                    await self._flush_chunk_ends()
            else:
                t0 = self._t0()
                await self._call(
                    m.CltomaWriteChunkEnd,
                    chunk_id=grant.chunk_id,
                    inode=inode,
                    chunk_index=chunk_index,
                    file_length=file_length,
                    status=status_code,
                )
                self._phase("commit", t0)
            # see _write_chunk's twin: locates cached mid-write carry
            # pre-write length/identity and must not outlive the write
            self._drop_locates(inode)

    async def _flush_chunk_ends(self) -> None:
        """Flush queued end-of-write records as one coalesced
        CltomaWriteChunkEndBatch (the window pays one commit handshake
        per flush instead of one per chunk)."""
        win = self.write_window
        if win is None or not win.pending_ends:
            return
        batch = win.drain_ends()
        t0 = self._t0()
        try:
            await self._call(
                m.CltomaWriteChunkEndBatch,
                ends=[m.WriteChunkEndEntry(**e) for e in batch],
            )
        except st.StatusError:
            # a STATUS reply proves the master consumed the batch (it
            # applies every entry it can and reports the first failure,
            # e.g. quota): surface the error but do NOT requeue —
            # re-sending would re-apply applied entries and park a
            # permanently-failing one in front of every future flush
            raise
        except BaseException:
            # transport failure: the batch may never have arrived, and
            # it may hold ANOTHER concurrent write's commits — requeue
            # so a later flush retries instead of silently losing that
            # write's length/locks to this one's failure
            win.requeue_ends(batch)
            raise
        self._phase("commit", t0)
        win.note_coalesced(len(batch))
        self._record("write_commit_batch")

    async def _push_chunk_parts(self, grant, chunk_data: np.ndarray) -> None:
        # group locations by part index
        by_part: dict[int, list[m.PartLocation]] = {}
        slice_type = None
        for loc in grant.locations:
            cpt = geometry.ChunkPartType.from_id(loc.part_id)
            slice_type = cpt.type if slice_type is None else slice_type
            by_part.setdefault(cpt.part, []).append(loc)
        if slice_type is None:
            raise st.StatusError(st.NO_CHUNK_SERVERS, "no locations granted")

        # abort handles for every native send this chunk issues: a
        # cancelled write must kill zombie executor threads before the
        # staging buffer they stream from can go back to the pool
        send_cells: list[dict] = []

        def send_of(part_idx: int, payload: np.ndarray,
                    skip_throttle: bool = False):
            length = striping.part_length(
                slice_type, part_idx, len(chunk_data)
            )
            cell: dict = {}
            send_cells.append(cell)
            return self._write_part(
                grant.chunk_id, grant.version, by_part[part_idx],
                payload, length, skip_throttle=skip_throttle, cell=cell,
            )

        async def send_batch(
            items: list[tuple[int, np.ndarray]], skip_throttle: bool = False
        ) -> None:
            """Write several whole parts: ONE native poll-driven call
            when every part has a single holder (no relay chain),
            per-part sends otherwise or on native failure.
            ``skip_throttle``: the caller already charged these bytes
            (QoS rule: charge once, not per retry/fallback)."""
            from lizardfs_tpu.core import native_io

            items = [(p, pay) for p, pay in items if p in by_part]
            if not items:
                return
            lengths = [
                striping.part_length(slice_type, p, len(chunk_data))
                for p, _ in items
            ]
            if not skip_throttle:
                # charged BEFORE the send timer starts: QoS queueing
                # (token-bucket waits, the limit-renew RPC) must not be
                # booked as send_ms, or a throttled client's phase row
                # misattributes pacing as chunkserver transfer time
                await self._throttle(sum(lengths))
            t0 = self._t0()
            try:
                if (
                    native_io.parts_scatter_available()
                    and not _faults.ACTIVE
                    and len(items) > 1
                    and all(len(by_part[p]) == 1 for p, _ in items)
                ):
                    cell: dict = {"submitted": True}
                    send_cells.append(cell)
                    try:
                        await native_io.run(
                            native_io.write_parts_scatter_blocking,
                            [(by_part[p][0].addr.host,
                              by_part[p][0].addr.port)
                             for p, _ in items],
                            grant.chunk_id, grant.version,
                            [by_part[p][0].part_id for p, _ in items],
                            [pay for _, pay in items], lengths, 0, cell,
                        )
                        self._record("parts_scatter_write")
                        return
                    except (native_io.NativeIOError, OSError,
                            ConnectionError, st.StatusError):
                        self._record("parts_scatter_fallback")
                        # fall through per-part — bytes were already
                        # charged to the throttle above, don't pay twice
                        await asyncio.gather(*(
                            send_of(p, pay, skip_throttle=True)
                            for p, pay in items
                        ))
                        return
                # bytes already charged above — per-part sends must not
                # pay again (and their throttle would pollute the timer)
                await asyncio.gather(*(
                    send_of(p, pay, skip_throttle=True)
                    for p, pay in items
                ))
            finally:
                self._phase("send", t0)

        from lizardfs_tpu.core import native_io

        def _abort_zombie_sends() -> list[dict]:
            """Kill executor threads of cancelled/failed native sends:
            run_in_executor threads are unkillable, so a cancelled send
            would otherwise keep streaming from its buffer for up to
            120 s while pinning a native-IO worker."""
            zombies = [
                c for c in send_cells
                if c.get("submitted") and not c.get("finished")
            ]
            for c in zombies:
                native_io.abort_write(c)
            return zombies

        if slice_type.is_standard or slice_type.is_tape:
            # whole-chunk copies: stream the caller's buffer directly
            # (_write_part only reads it) — no 64 MiB staging copy
            copy_tasks = [
                asyncio.ensure_future(send_of(p, chunk_data))
                for p in by_part
            ]
            try:
                for t in copy_tasks:
                    await t
            finally:
                for t in copy_tasks:
                    t.cancel()
                await asyncio.gather(*copy_tasks, return_exceptions=True)
                _abort_zombie_sends()
            return
        # striped slices: scatter into contiguous part streams first
        # (one memcpy, the `stage` phase), then hand off to one of:
        #   * the segmented stripe pipeline (default, preconditions
        #     permitting): encode segment i+1 while segment i's data AND
        #     parity are in flight — parity lands straight in the send
        #     buffer, no second staging copy;
        #   * the overlapped whole-chunk path (pipeline on, but chains/
        #     missing parts/no native scatter): whole-chunk encode
        #     overlaps the data-part transfer (chunk_writer.cc computes
        #     parity inline per stripe; this is its coarse analog);
        #   * the strictly serial path (LZ_WRITE_PIPELINE=0 kill
        #     switch): stage -> encode -> send(data) -> send(parity),
        #     the byte-identity golden reference whose phase totals sum
        #     to ~the rep wall time.
        d = slice_type.data_parts
        nblocks = -(-len(chunk_data) // MFSBLOCKSIZE)
        part_len = -(-nblocks // d) * MFSBLOCKSIZE
        stage = self._stage_acquire(d, part_len)
        t0 = self._t0()
        stacked, _ = await asyncio.to_thread(
            striping.padded_data_parts, chunk_data, d, stage
        )
        self._phase("stage", t0)
        first = 1 if slice_type.is_xor else 0
        full_chunk = len(chunk_data) == MFSCHUNKSIZE

        async def parity_parts() -> dict[int, np.ndarray]:
            t0 = self._t0()
            try:
                if slice_type.is_xor:
                    par = await asyncio.to_thread(
                        self.encoder.xor_parity, stacked
                    )
                    return {0: par}
                par = await asyncio.to_thread(
                    self.encoder.encode, d, slice_type.parity_parts,
                    list(stacked),
                )
                return {d + j: p for j, p in enumerate(par)}
            finally:
                self._phase("encode", t0)

        try:
            throttled = False
            if self.write_pipeline and self._pipeline_eligible(
                slice_type, by_part, chunk_data, part_len
            ):
                # charge the QoS budget up front (one acquire for the
                # chunk); a fallback below must then not charge again
                await self._throttle(sum(
                    striping.part_length(slice_type, p, len(chunk_data))
                    for p in by_part
                ))
                throttled = True
                try:
                    if (self.write_window is not None
                            and native_io.parts_scatterv_available()):
                        # adaptive window: N unacked segments in flight
                        # over shared per-chunkserver connections
                        await self._push_striped_windowed(
                            grant, chunk_data, slice_type, by_part,
                            stacked, part_len, full_chunk, send_cells,
                        )
                        self._record("write_window")
                    else:
                        await self._push_striped_pipelined(
                            grant, chunk_data, slice_type, by_part, stacked,
                            part_len, full_chunk, send_cells,
                        )
                    # both overlapped paths count as the pipeline for
                    # observability (the window is its deeper form)
                    self._record("write_pipeline")
                    return
                except (native_io.NativeIOError, OSError, ConnectionError,
                        st.StatusError):
                    # torn segments are healed by the full-part rewrite
                    # the paths below perform
                    self._record("write_pipeline_fallback")
            if not self.write_pipeline:
                par = await parity_parts()
                await send_batch(
                    [(first + i, stacked[i]) for i in range(d)],
                    skip_throttle=throttled,
                )
                await send_batch(sorted(par.items()), skip_throttle=throttled)
                return
            par_task = asyncio.ensure_future(parity_parts())
            tasks = [asyncio.ensure_future(
                send_batch(
                    [(first + i, stacked[i]) for i in range(d)],
                    skip_throttle=throttled,
                )
            )]
            try:
                par = await par_task
                tasks.append(asyncio.ensure_future(
                    send_batch(sorted(par.items()), skip_throttle=throttled)
                ))
                for t in tasks:
                    await t
            finally:
                par_task.cancel()
                for t in tasks:
                    t.cancel()
                await asyncio.gather(par_task, *tasks, return_exceptions=True)
        finally:
            # the coroutines are done, but a cancelled native send's
            # executor thread may still be streaming from the staging
            # buffer: kill it now, and never pool a buffer a zombie
            # thread might still read
            zombies = _abort_zombie_sends()
            self._stage_release(
                stage, poolable=full_chunk and not zombies
            )

    def _stage_acquire(self, d: int, part_len: int) -> np.ndarray | None:
        # stage buffers only serve the native scatter; the numpy
        # fallback ignores out= and would pool never-written memory
        from lizardfs_tpu.core import native

        if not native.stripe_helpers_available():
            return None
        bucket = self._stage_buffers.get((d, part_len))
        if bucket:
            return bucket.pop()
        return np.empty((d, part_len), dtype=np.uint8)

    def _stage_release(self, buf: np.ndarray | None, poolable: bool) -> None:
        # pool ONLY the full-chunk shape: tail chunks produce one shape
        # per distinct file length, and keeping 2 buffers per shape
        # forever would grow without bound on a long-lived mount
        if buf is None or not poolable:
            return
        bucket = self._stage_buffers.setdefault(buf.shape, [])
        if len(bucket) < 2:
            bucket.append(buf)

    def _parity_acquire(self, m: int, part_len: int) -> np.ndarray:
        """Parity send buffer for the pipelined path ((m, part_len),
        pooled with the stage buffers): the encoder writes parity
        straight into it and the native scatter streams from it — the
        per-chunk parity staging copy is gone."""
        bucket = self._stage_buffers.get((m, part_len))
        if bucket:
            return bucket.pop()
        return np.empty((m, part_len), dtype=np.uint8)

    def _pipeline_eligible(
        self, slice_type, by_part, chunk_data, part_len: int
    ) -> bool:
        """Segmented stripe pipeline preconditions: native scatter
        built, every expected part granted with exactly one holder (no
        relay chains — the session sends chain-less frames), and a
        payload big enough that per-segment overlap beats the extra
        segment barriers. Anything else takes the fallback paths."""
        from lizardfs_tpu.core import native_io

        if not native_io.parts_scatter_available():
            return False
        if _faults.ACTIVE:
            # armed faults: native scatter sessions can't be
            # instrumented — the hookable per-part senders serve
            return False
        if len(chunk_data) < self.WRITE_PIPELINE_MIN_BYTES:
            return False
        if part_len < 2 * MFSBLOCKSIZE:
            return False  # a single slot per part: nothing to overlap
        return all(
            p in by_part and len(by_part[p]) == 1
            for p in range(slice_type.expected_parts)
        )

    def _stripe_send_plan(
        self, grant, chunk_data, slice_type, by_part, stacked,
        part_len: int, send_cells: list[dict], share: bool, nseg_min: int,
    ):
        """Shared prologue of the two overlapped stripe senders (the
        double-buffered pipeline and the adaptive window): part order
        and per-part lengths, the pooled parity send buffer, the
        scatter session + abort cell, slot-aligned segment bounds, and
        the per-segment encode/payload/length closures — a stripe-
        geometry or encoder-boundary change lands in exactly one place.
        Returns ``(par_buf, cell, session, bounds, encode_segment,
        seg_payloads, seg_lengths)``."""
        from lizardfs_tpu.core import native_io

        d = slice_type.data_parts
        first = 1 if slice_type.is_xor else 0
        m_par = 1 if slice_type.is_xor else slice_type.parity_parts
        order = [first + i for i in range(d)] + (
            [0] if slice_type.is_xor else [d + j for j in range(m_par)]
        )
        plens = {
            p: striping.part_length(slice_type, p, len(chunk_data))
            for p in order
        }
        par_buf = self._parity_acquire(m_par, part_len)
        cell: dict = {}
        send_cells.append(cell)
        session = native_io.PartsScatterSession(
            [(by_part[p][0].addr.host, by_part[p][0].addr.port)
             for p in order],
            grant.chunk_id, grant.version,
            [by_part[p][0].part_id for p in order],
            cell, share_connections=share,
        )
        blocks_per_part = part_len // MFSBLOCKSIZE
        nseg = min(
            max(self.write_pipeline_segments, nseg_min), blocks_per_part
        )
        seg_blocks = -(-blocks_per_part // nseg)
        bounds = [
            (a * MFSBLOCKSIZE,
             min(a + seg_blocks, blocks_per_part) * MFSBLOCKSIZE)
            for a in range(0, blocks_per_part, seg_blocks)
        ]

        def encode_segment(a: int, b: int, views=None) -> None:
            data_seg = [stacked[i][a:b] for i in range(d)]
            if views is not None:
                # shm-ring staging: parity is encoded STRAIGHT into the
                # chunkserver-mapped arena (zero copies end to end);
                # data rows stay in the stage buffer — their single
                # GIL-free memcpy into the arena happens inside the
                # native descriptor send (native/shm_ring.h). The
                # later "send" phase moves descriptors, not megabytes.
                par_out = [views[d + j] for j in range(m_par)]
                if par_out[0] is None:
                    return  # segment past every part's live length
                if slice_type.is_xor:
                    self.encoder.xor_parity_into(data_seg, par_out[0])
                else:
                    self.encoder.encode_into(d, m_par, data_seg, par_out)
                return
            if slice_type.is_xor:
                self.encoder.xor_parity_into(data_seg, par_buf[0][a:b])
            else:
                self.encoder.encode_into(
                    d, m_par, data_seg,
                    [par_buf[j][a:b] for j in range(m_par)],
                )

        def seg_payloads(a: int, b: int) -> list:
            return (
                [stacked[i][a:b] for i in range(d)]
                + [par_buf[j][a:b] for j in range(m_par)]
            )

        def seg_lengths(a: int, b: int) -> list[int]:
            return [max(min(b, plens[p]) - a, 0) for p in order]

        return (par_buf, cell, session, bounds, encode_segment,
                seg_payloads, seg_lengths)

    async def _push_striped_pipelined(
        self, grant, chunk_data, slice_type, by_part, stacked,
        part_len: int, full_chunk: bool, send_cells: list[dict],
    ) -> None:
        """Double-buffered stripe pipeline: ONE WriteInit/End handshake
        pair per part for the whole chunk, the part streams cut into
        slot-aligned segments, and segment i+1's parity encoding (into
        the send buffer, via the ChunkEncoder boundary) overlapping
        segment i's data+parity transfer.

        Byte-identical to the serial path by construction: RS/xor
        parity is columnwise (parity[j][x] depends only on column x of
        the data parts), so a per-segment encode equals the matching
        slice of a whole-part encode; segment boundaries stay 64 KiB
        aligned, so the chunkservers see the same per-block pieces and
        store the same CRCs. Raises on any failure — the caller falls
        back to the serial path, whose full-part rewrite heals torn
        segments. The caller has already charged the QoS throttle."""
        from lizardfs_tpu.core import native_io

        (par_buf, cell, session, bounds, encode_segment, seg_payloads,
         seg_lengths) = self._stripe_send_plan(
            grant, chunk_data, slice_type, by_part, stacked, part_len,
            send_cells, share=False, nseg_min=2,
        )

        async def send_segment(a: int, b: int, wid: int, after) -> None:
            # chained on the previous segment's task: the session's
            # sockets carry one exchange at a time, and a predecessor's
            # failure propagates down the chain
            if after is not None:
                await after
            t0 = self._t0()
            await native_io.run(
                session.send_segment, seg_payloads(a, b),
                seg_lengths(a, b), a, wid,
            )
            self._phase("send", t0)

        send_tasks: list[asyncio.Task] = []
        try:
            t0 = self._t0()
            await native_io.run(session.open)
            self._phase("send", t0)
            for wid, (a, b) in enumerate(bounds, start=1):
                t0 = self._t0()
                await asyncio.to_thread(encode_segment, a, b)
                self._phase("encode", t0)
                send_tasks.append(asyncio.ensure_future(send_segment(
                    a, b, wid, send_tasks[-1] if send_tasks else None
                )))
            await send_tasks[-1]
            t0 = self._t0()
            await native_io.run(session.finish)
            self._phase("send", t0)
        except BaseException:
            for t in send_tasks:
                t.cancel()
            await asyncio.gather(*send_tasks, return_exceptions=True)
            # the session's executor thread may still be streaming from
            # stacked/par_buf — kill the exchange before those buffers
            # can be released (the caller's zombie-abort also covers
            # this cell, but do it promptly here)
            native_io.abort_write(cell)
            raise
        finally:
            self._stage_release(
                par_buf,
                poolable=full_chunk and not (
                    cell.get("submitted") and not cell.get("finished")
                ),
            )

    async def _push_striped_windowed(
        self, grant, chunk_data, slice_type, by_part, stacked,
        part_len: int, full_chunk: bool, send_cells: list[dict],
    ) -> None:
        """Adaptive N-deep write window over the stripe pipeline: up to
        ``write_window.depth`` slot-aligned segments ride UNACKNOWLEDGED
        (part-addressed 1215 frames, vectored header+payload sendmsg,
        parts sharing a chunkserver multiplexed over one connection),
        with per-chunkserver credits + a shared staging-byte budget as
        flow control. Acks are collected oldest-first as the window
        fills — the per-segment round-trip barrier the PR-1 pipeline
        paid (its send phase dominated the ec(8,4) telemetry) is gone.

        Byte-identical to the serial path for the same reason the
        pipelined path is: parity is columnwise, segments stay 64 KiB
        aligned, and the chunkservers land the same per-block pieces
        and CRCs — only the framing and ack cadence differ. Raises on
        any failure; the caller's serial fallback heals torn segments.
        The caller has already charged the QoS throttle."""
        from lizardfs_tpu.core import native_io

        win = self.write_window
        d = slice_type.data_parts  # ring widths: data rows vs parity
        # nseg_min=win.max_depth: enough segments that the window can
        # actually fill (a 4-deep window over 4 segments would
        # degenerate to the old barrier)
        (par_buf, cell, session, bounds, encode_segment, seg_payloads,
         seg_lengths) = self._stripe_send_plan(
            grant, chunk_data, slice_type, by_part, stacked, part_len,
            send_cells, share=True, nseg_min=win.max_depth,
        )

        from collections import deque

        # (write_id, credited bytes, encode seconds, send seconds so far)
        outstanding: deque[list] = deque()
        try:
            t0 = self._t0()
            await native_io.run(session.open)
            self._phase("send", t0)
            for wid, (a, b) in enumerate(bounds, start=1):
                lengths = seg_lengths(a, b)
                # shm-ring staging: reserve this segment's arena regions
                # BEFORE encoding so parity lands straight in mapped
                # memory. A full ring reaps the oldest segment's acks
                # (freeing its regions) and retries; with nothing left
                # to reap, this segment takes the socket-copy send.
                views = None
                if session.ring_ready():
                    # parity regions are allocated at the full padded
                    # segment width (the encoder writes the whole
                    # column range); only the live bytes go on the wire
                    widths = lengths[:d] + [b - a] * (len(lengths) - d)
                    views = session.ring_stage(wid, lengths, widths)
                    while views is None and outstanding:
                        await self._window_collect(session, win, outstanding)
                        views = session.ring_stage(wid, lengths, widths)
                t0 = self._t0()
                try:
                    await asyncio.to_thread(encode_segment, a, b, views)
                except BaseException:
                    session.ring_unstage(wid)
                    raise
                enc_dt = _time.perf_counter() - t0[0]
                self._phase("encode", t0)
                payloads = seg_payloads(a, b)
                if views is not None:
                    # parity already lives in its staged arena view —
                    # hand THAT as the payload so the native send sees
                    # src == dst and moves zero parity bytes; data rows
                    # keep their stage-buffer source for the C memcpy
                    for idx in range(d, len(views)):
                        if views[idx] is not None:
                            payloads[idx] = views[idx]
                seg_bytes = sum(lengths)
                # credits BEFORE the send: per-chunkserver in-flight
                # frames + the client-wide staging budget (returned as
                # each segment's commit acks come back). NEVER block on
                # credits while holding outstanding segments — reap the
                # oldest instead (two concurrent chunk writes jointly
                # exhausting a bucket would otherwise deadlock, each
                # waiting for credits only the other's reap can free);
                # blocking with nothing outstanding is safe, since any
                # credit holder then has acks of its own to reap.
                waited = False
                w0 = tracing.phase_t0()
                while not win.try_acquire(session.unique_addrs, seg_bytes):
                    waited = True
                    if outstanding:
                        await self._window_collect(session, win, outstanding)
                    else:
                        await win.acquire(session.unique_addrs, seg_bytes)
                        break
                win.note_segment(waited)
                if waited:
                    # credit-gate queue wait (reap-or-block included):
                    # the segment did no work while the window was full
                    tracing.charge_queue_wait(
                        self.metrics, self.trace_ring, "write_credit",
                        "default", w0, role="client",
                    )
                try:
                    t0 = self._t0()
                    await native_io.run(
                        session.send_segment_window, payloads, lengths,
                        a, wid,
                    )
                    send_dt = _time.perf_counter() - t0[0]
                    self._phase("send", t0)
                except BaseException:
                    win.release(session.unique_addrs, seg_bytes)
                    raise
                outstanding.append([wid, seg_bytes, enc_dt, send_dt])
                # window full: reap the oldest segment's acks (depth is
                # LIVE — adaptation may have moved it since the last
                # segment, so reap down to the current depth)
                while len(outstanding) >= max(win.depth, 1):
                    await self._window_collect(session, win, outstanding)
            while outstanding:
                await self._window_collect(session, win, outstanding)
            t0 = self._t0()
            await native_io.run(session.finish)
            self._phase("send", t0)
        except BaseException:
            # the session's executor thread may still be streaming from
            # stacked/par_buf — kill the exchange before those buffers
            # can be released
            native_io.abort_write(cell)
            raise
        finally:
            self._fold_ring_stats(session)
            # failure path: return credits the reap loop never got to
            for wid, seg_bytes, *_rest in outstanding:
                win.release(session.unique_addrs, seg_bytes)
            self._stage_release(
                par_buf,
                poolable=full_chunk and not (
                    cell.get("submitted") and not cell.get("finished")
                ),
            )

    _SHM_RING_HELP = {
        "segments_mapped": "shm ring segments negotiated with same-host "
                           "chunkservers (memfd mappings created)",
        "desc_parts": "part writes handed off as shm-ring descriptors "
                      "(payload moved via shared memory, not the socket)",
        "full_waits": "segment stagings that found a ring full and had "
                      "to reap acks first (ring backpressure events)",
        "fallbacks": "windowed segments sent via socket copy while rings "
                     "were active (ring-full or unstaged fallbacks)",
    }

    def _fold_ring_stats(self, session) -> None:
        """Fold one scatter session's shm-ring counters into the client
        registry (Prometheus-exported wherever the owner exposes it)."""
        stats = getattr(session, "ring_stats", None)
        if not stats:
            return
        for key, val in stats.items():
            if val:
                self.metrics.counter(
                    f"shm_ring_{key}", help=self._SHM_RING_HELP[key]
                ).inc(float(val))
        if stats.get("desc_parts"):
            # visible alongside write_pipeline/write_window counters:
            # this chunk moved (at least partly) over the ring plane
            self._record("write_shm")
        session.ring_stats = {k: 0 for k in stats}

    async def _window_collect(self, session, win, outstanding) -> None:
        """Reap the oldest outstanding segment: collect its acks,
        return its credits, and feed the adaptive depth controller."""
        from lizardfs_tpu.core import native_io

        wid, seg_bytes, enc_dt, send_dt = outstanding.popleft()
        try:
            t0 = self._t0()
            await native_io.run(session.collect_acks, wid)
            # ack-reaping is backpressure (downstream disk/CPU), not
            # push cost — charge it to its own phase so send_ms keeps
            # measuring the copy the shm ring exists to eliminate; the
            # depth controller still sees the combined time (ack wait
            # is exactly the send-bound signal that should deepen it)
            send_dt += _time.perf_counter() - t0[0]
            self._phase("ack", t0)
        finally:
            win.release(session.unique_addrs, seg_bytes)
        win.observe(enc_dt, send_dt)

    async def _write_part(
        self,
        chunk_id: int,
        version: int,
        locs: list[m.PartLocation],
        payload: np.ndarray,
        length: int,
        part_offset: int = 0,
        skip_throttle: bool = False,
        cell: dict | None = None,
    ) -> None:
        """Write ``payload[:length]`` at ``part_offset`` within one part:
        head of the chain + forwarding for extra copies (WriteExecutor
        analog, write_executor.cc:66-96). Pieces never cross 64 KiB block
        boundaries; each carries its own CRC. ``skip_throttle``: the
        caller already charged these bytes (QoS rule: charge once, not
        per retry/fallback). ``cell``: abort handle for the native path —
        a cancelled caller must be able to kill the executor thread that
        is still streaming from ``payload`` (native_io.abort_write)."""
        if not skip_throttle:
            await self._throttle(max(length, 0))
        head = locs[0]
        chain = locs[1:]

        # bulk writes stream their pieces in C++ off the event loop
        from lizardfs_tpu.core import native_io

        if (
            native_io.available()
            and length >= native_io.NATIVE_WRITE_THRESHOLD
            # armed faults: the C++ streamer can't be instrumented —
            # the framed asyncio path below serves (LZ_FAULTS unset:
            # byte-identical, the gate is one module-attribute check)
            and not _faults.ACTIVE
        ):
            if cell is not None:
                # marked BEFORE the executor hand-off: an abort racing
                # the thread's connect phase must still see a zombie
                cell["submitted"] = True
            try:
                await native_io.run(
                    native_io.write_part_blocking,
                    (head.addr.host, head.addr.port),
                    chunk_id, version, head.part_id, chain,
                    payload[:length], part_offset, cell,
                )
                return
            except native_io.NativeIOError as e:
                raise st.StatusError(
                    e.code if e.code > 0 else st.EIO, str(e)
                ) from None
            except (OSError, ConnectionError) as e:
                raise st.StatusError(st.EIO, f"native write: {e}") from None

        if _faults.ACTIVE:
            # client data-plane dial choke point (runtime/faults.py)
            await _faults.dial_point(
                "cs", f"{head.addr.host}:{head.addr.port}", role="client"
            )
        # bounded dial (unbounded-await audit): honors any ambient
        # RetryPolicy deadline on top of the 5 s cap
        reader, writer = await retrymod.bounded_wait(
            asyncio.open_connection(head.addr.host, head.addr.port), 5.0
        )
        try:
            await framing.send_message(
                writer,
                m.CltocsWriteInit(
                    req_id=1,
                    chunk_id=chunk_id,
                    version=version,
                    part_id=head.part_id,
                    chain=chain,
                    create=False,
                    session_id=self.session_id,
                ),
            )
            # every reply wait is deadline-bounded (unbounded-await
            # audit): a chunkserver that accepts frames but never acks
            # fails this part write in bounded time instead of wedging
            # the session forever
            init = await retrymod.bounded_wait(
                framing.read_message(reader), 30.0
            )
            if not isinstance(init, m.CstoclWriteStatus) or init.status != st.OK:
                raise st.StatusError(getattr(init, "status", st.EIO), "write init")
            nbytes = max(length, 0)
            write_id = 0
            expected = set()
            from lizardfs_tpu.ops import crc32 as crc_mod

            pos = 0
            while pos < nbytes:
                abs_off = part_offset + pos
                block = abs_off // MFSBLOCKSIZE
                block_off = abs_off % MFSBLOCKSIZE
                take = min(MFSBLOCKSIZE - block_off, nbytes - pos)
                piece = payload[pos : pos + take].tobytes()
                pos += take
                if not piece:
                    continue
                write_id += 1
                expected.add(write_id)
                await framing.send_message(
                    writer,
                    m.CltocsWriteData(
                        req_id=write_id,
                        chunk_id=chunk_id,
                        write_id=write_id,
                        block=block,
                        offset=block_off,
                        crc=crc_mod.crc32(piece),
                        data=piece,
                    ),
                )
            while expected:
                msg = await retrymod.bounded_wait(
                    framing.read_message(reader), 30.0
                )
                if not isinstance(msg, m.CstoclWriteStatus):
                    raise st.StatusError(st.EIO, "unexpected write reply")
                if msg.status != st.OK:
                    raise st.StatusError(msg.status, f"write id {msg.write_id}")
                expected.discard(msg.write_id)
            await framing.send_message(
                writer, m.CltocsWriteEnd(req_id=0, chunk_id=chunk_id)
            )
            end = await retrymod.bounded_wait(
                framing.read_message(reader), 30.0
            )
            if not isinstance(end, m.CstoclWriteStatus) or end.status != st.OK:
                raise st.StatusError(getattr(end, "status", st.EIO), "write end")
        finally:
            await retrymod.close_writer(writer, swallow_cancel=True)

    # --- read path ---------------------------------------------------------------------

    async def read_file(self, inode: int, offset: int = 0, size: int | None = None) -> bytes:
        t0 = _time.perf_counter()
        tw0 = _time.time()
        tid, fresh_trace = tracing.begin()
        # the read-phase sink is scoped to THIS logical read: every
        # locate/dial/wait/net/decode/gather charge below — including
        # ones from the conn pool and read executor — lands on this
        # client's read_phases exactly once (retries/fallbacks re-enter
        # phases, never the wall/rep accounting)
        sink_tok = tracing.PHASE_SINK.set(self._read_sink)
        try:
            with accounting.task_session(self.session_id):
                data = await self._read_file_inner(inode, offset, size)
        finally:
            tracing.PHASE_SINK.reset(sink_tok)
            tracing.end(fresh_trace)
        # ONE logical read == ONE accounting record: replica fallbacks
        # and dead-holder retries below this line never double-count
        dt = _time.perf_counter() - t0
        self.read_phases.add_wall(dt)
        # root span: the attribution wall anchor (`trace-dump --attribute`)
        self.trace_ring.record(
            tid, "read_file", tw0, _time.time(), role="client",
            bytes=len(data),
        )
        self.session_ops.record(
            self.session_id, "read", dt, nbytes=len(data), trace_id=tid,
        )
        return data

    def session_stats_doc(self) -> dict:
        """Workload summary for the master's `top` rollup: the client's
        read/write phase breakdowns ride the same CltomaSessionStats
        push the protocol gateways use, so `lizardfs-admin top` (and
        the webui) name each session's read roofline."""
        return {
            "role": "client",
            "read_phases": self.read_phases.snapshot(),
            "write_phases": self.write_phases.snapshot(),
        }

    async def push_session_stats(self) -> None:
        """Push :meth:`session_stats_doc` to the master (best effort —
        telemetry must never fail the caller)."""
        import json as _json

        try:
            await self._call(
                m.CltomaSessionStats,
                stats_json=_json.dumps(self.session_stats_doc()),
            )
        except (ConnectionError, OSError, asyncio.TimeoutError,
                st.StatusError):
            log.debug("session-stats push failed", exc_info=True)

    async def _read_file_inner(
        self, inode: int, offset: int, size: int | None
    ) -> bytes:
        if size is not None and size > 0:
            ci = offset // MFSCHUNKSIZE
            if (offset + size - 1) // MFSCHUNKSIZE == ci:
                # sized single-chunk read (every FUSE/NFS READ is this
                # shape): ONE master RPC — the locate reply carries
                # file_length, so the separate getattr round trip that
                # used to precede every read is gone (reference:
                # fs_readchunk returns the length the same way)
                piece = await self._read_chunk_range(
                    inode, ci, offset - ci * MFSCHUNKSIZE, size, None
                )
                return b"" if piece is None else piece.tobytes()
        attr = await self.getattr(inode)
        length = attr.length
        if size is None:
            size = max(length - offset, 0)
        end = min(offset + size, length)
        if end <= offset:
            return b""
        out = np.zeros(end - offset, dtype=np.uint8)
        await self._read_into(inode, offset, out, length)
        return out.tobytes()

    async def read_file_into(
        self, inode: int, offset: int, out: np.ndarray
    ) -> int:
        """pread-style zero-extra-copy read: fill ``out`` with file bytes
        at ``offset``; returns bytes read (short at EOF). On the bulk
        path the network recv lands directly in ``out``. ``out`` must be
        C-contiguous uint8."""
        tid, fresh_trace = tracing.begin()
        tw0 = _time.time()
        tp0 = _time.perf_counter()
        sink_tok = tracing.PHASE_SINK.set(self._read_sink)
        try:
            attr = await self.getattr(inode)
            length = attr.length
            end = min(offset + out.size, length)
            if end <= offset:
                return 0
            n = end - offset
            with accounting.task_session(self.session_id):
                await self._read_into(inode, offset, out[:n], length)
            self.trace_ring.record(
                tid, "read_file", tw0, _time.time(), role="client", bytes=n
            )
            self.read_phases.add_wall(_time.perf_counter() - tp0)
            self.session_ops.record(
                self.session_id, "read", _time.time() - tw0, nbytes=n,
                trace_id=tid,
            )
            return n
        finally:
            tracing.PHASE_SINK.reset(sink_tok)
            tracing.end(fresh_trace)

    async def _read_into(
        self, inode: int, offset: int, out: np.ndarray, length: int
    ) -> None:
        """Fill ``out`` (C-contiguous uint8) with [offset, offset+len(out)).

        Pipelines chunk ranges: while one chunk's bytes stream in C++,
        the next chunk's locate RPC and stream startup proceed (each
        task writes a disjoint slice of ``out``)."""
        end = offset + out.size
        window = asyncio.Semaphore(3)

        async def read_one(index, chunk_off, take, dst):
            async with window:
                piece = await self._read_chunk_range(
                    inode, index, chunk_off, take, length,
                    into=out, into_offset=dst,
                )
                if piece is not None:
                    out[dst : dst + take] = piece

        tasks = []
        pos = offset
        while pos < end:
            index = pos // MFSCHUNKSIZE
            chunk_off = pos % MFSCHUNKSIZE
            take = min(MFSCHUNKSIZE - chunk_off, end - pos)
            tasks.append(asyncio.ensure_future(
                read_one(index, chunk_off, take, pos - offset)
            ))
            pos += take
        try:
            for t in tasks:
                await t
        finally:
            for t in tasks:
                t.cancel()
            # join the stragglers: their native reader threads may still
            # be scattering into `out`; the caller must never see the
            # exception before every writer is done with the buffer
            await asyncio.gather(*tasks, return_exceptions=True)

    async def _read_chunk_range(
        self, inode: int, chunk_index: int, off: int, size: int,
        file_length: int | None, into: np.ndarray | None = None,
        into_offset: int = 0,
    ) -> np.ndarray | None:
        """Read one chunk range. Returns the bytes — or ``None`` when
        they were scattered directly into ``into`` (bulk aligned reads
        of standard chunks land network bytes in the caller's buffer).

        ``file_length=None``: length unknown — learn it from the locate
        reply (MatoclReadChunk.file_length, like the reference's
        fs_readchunk) and clamp there, saving sized reads the separate
        getattr round trip. Only valid with ``into=None``."""
        if file_length is None:
            assert into is None, "length-from-locate needs the copy path"
            chunk_len = MFSCHUNKSIZE  # provisional; clamped post-locate
        else:
            chunk_len = min(
                max(file_length - chunk_index * MFSCHUNKSIZE, 0),
                MFSCHUNKSIZE,
            )
        # bulk reads skip the block cache entirely: probing + filling it
        # costs a per-64KiB-block copy, and streaming workloads would
        # only evict it anyway (the reference's readcache is similarly
        # bypassed by its readahead path for large requests). An inode
        # flagged EATTR_NOCACHE takes the same bypass for every read —
        # its bytes must never be served from or land in the cache
        bulk = (
            size >= self.CACHE_BYPASS_BYTES
            or bool(self._eattr.get(inode, 0) & EATTR_NOCACHE)
        )
        lo_b = off // MFSBLOCKSIZE
        hi_b = (off + size - 1) // MFSBLOCKSIZE
        if not bulk:
            # cache fast path: all covering blocks resident
            cached = [
                self.cache.get(inode, chunk_index, b)
                for b in range(lo_b, hi_b + 1)
            ]
            if all(c is not None for c in cached):
                joined = b"".join(cached)
                rel = off - lo_b * MFSBLOCKSIZE
                if len(joined) >= rel + size:
                    return np.frombuffer(joined, dtype=np.uint8)[rel : rel + size]

        # block-align the request and extend by the readahead window;
        # bulk reads skip the extension — they bypass the cache, so
        # extra bytes would be fetched only to be discarded, and an
        # extended range disqualifies the zero-copy direct scatter
        adviser = self._readahead.setdefault(inode, ReadaheadAdviser())
        extra = (
            0 if bulk
            else adviser.advise(chunk_index * MFSCHUNKSIZE + off, size)
        )
        aligned_off = lo_b * MFSBLOCKSIZE
        # the unclamped end the caller asked for: re-clamps against a
        # fresher file_length (growth during retries) start from here
        aligned_target = -(-(off + size + extra) // MFSBLOCKSIZE) * MFSBLOCKSIZE
        aligned_end = min(aligned_target, chunk_len)
        read_size = aligned_end - aligned_off
        req_size = size

        throttled = file_length is not None
        if throttled:
            t0 = self._t0()
            await self._throttle(read_size)  # QoS: charge once, not per retry
            self._read_phase("wait", t0)
        last_error: Exception | None = None
        bad_addrs: set[tuple[str, int]] = set()  # replicas that failed us
        for attempt in range(self.retries):
            if attempt:
                t0 = self._t0()
                await asyncio.sleep(min(0.1 * 2 ** attempt, 2.0))  # backoff
                self._read_phase("wait", t0)
            loc = None
            fresh = False
            if attempt == 0:
                cached = self._locate_cache.get((inode, chunk_index))
                if (cached is not None and _time.monotonic() - cached[1]
                        <= self.locate_cache_ttl):
                    loc = cached[0]
                    self.op_counters["locate_cache_hit"] = (
                        self.op_counters.get("locate_cache_hit", 0) + 1
                    )
            if loc is None:
                t0 = self._t0()
                token = self._locate_token(inode)
                # first attempt may serve the locate from a replica;
                # RETRY locates go to the primary — a failed read may
                # mean the replica's mirrored location set lags (e.g.
                # empty for a chunk just written), and the primary's is
                # authoritative
                locate = self._call_read if attempt == 0 else self._call
                loc = await locate(
                    m.CltomaReadChunk, inode=inode, chunk_index=chunk_index,
                    **self._ident(None, None),
                )
                fresh = True
                if (
                    loc.chunk_id and not loc.locations
                    and getattr(loc, "_replica_served", False)
                ):
                    # a real chunk with no locations FROM A REPLICA: its
                    # mirrored location set lags (parts registered with
                    # the primary only so far). Re-locate through the
                    # primary instead of failing the plan. A primary
                    # answer with no locations is authoritative — never
                    # re-ask (that would double locate load during a
                    # chunkserver outage).
                    loc = await self._call(
                        m.CltomaReadChunk, inode=inode,
                        chunk_index=chunk_index, **self._ident(None, None),
                    )
                # locate phase: the master round trip(s), replica
                # fallback included; cache hits charge nothing
                self._read_phase("locate", t0)
                if self._locate_token(inode) == token:
                    # refuse stores that raced an invalidation: the
                    # reply may predate the mutation that bumped epoch
                    # (the token folds in the clear generation, so a
                    # bulk clear can never alias an old epoch value)
                    self._locate_cache[(inode, chunk_index)] = (
                        loc, _time.monotonic()
                    )
                    if len(self._locate_cache) > 4096:
                        self._locate_cache.clear()  # crude bound
            # revalidate cached blocks against the chunk identity this
            # locate returned: a rewrite bumps the version, a truncate+
            # regrow swaps the chunk_id — either way stale blocks drop
            chunk_tag = (loc.chunk_id, loc.version)
            self.cache.note_version(inode, chunk_index, chunk_tag)
            if file_length is None or (
                fresh and loc.file_length > file_length
            ):
                # clamp the provisional geometry with the length the
                # locate just taught us — and RE-clamp on every fresh
                # (non-cached) reply that reports growth: a read racing
                # an append must not return short against the stale
                # length a first (possibly cached) locate pinned
                # (ADVICE r05). Growth after the throttle charge leaves
                # a few bytes unbilled — QoS charges once, not per retry.
                file_length = loc.file_length
                chunk_len = min(
                    max(file_length - chunk_index * MFSCHUNKSIZE, 0),
                    MFSCHUNKSIZE,
                )
                size = min(req_size, max(chunk_len - off, 0))
                if size <= 0:
                    return np.zeros(0, dtype=np.uint8)  # past EOF
                aligned_end = min(aligned_target, chunk_len)
                read_size = aligned_end - aligned_off
            if not throttled:
                # deferred until the locate-taught clamp: charging the
                # provisional geometry would bill EOF reads for bytes
                # never transferred
                throttled = True
                t0 = self._t0()
                await self._throttle(read_size)
                self._read_phase("wait", t0)
            if loc.chunk_id == 0:
                if into is not None:
                    into[into_offset : into_offset + size] = 0
                    return None
                return np.zeros(size, dtype=np.uint8)  # hole
            # direct scatter into the caller's buffer is possible only
            # when the network range IS the requested range
            direct = (
                into is not None and aligned_off == off and read_size == size
            )
            try:
                data = await self._read_located(
                    loc, chunk_index, aligned_off, read_size, file_length,
                    attempt=attempt, avoid=bad_addrs,
                    into=into if direct else None,
                    into_offset=into_offset,
                )
            except (ReadError, ConnectionError, OSError) as e:
                last_error = e
                bad_addrs.update(getattr(e, "used_addrs", ()))
                log.info("read retry %d for chunk %d: %s", attempt + 1, loc.chunk_id, e)
                continue
            if not bulk:
                # data is None when the bytes landed directly in `into`
                # (zero-copy scatter) — cache from there in that case
                src = (
                    data if data is not None
                    else into[into_offset : into_offset + size]
                )
                src_base = aligned_off if data is not None else off
                for b in range(lo_b, aligned_end // MFSBLOCKSIZE + 1):
                    s = b * MFSBLOCKSIZE - src_base
                    if s < 0:
                        continue
                    blk = src[s : s + MFSBLOCKSIZE]
                    if len(blk):
                        self.cache.put(
                            inode, chunk_index, b, blk.tobytes(),
                            version=chunk_tag,
                        )
            if extra > 0 and aligned_end < chunk_len:
                # sequential stream detected: warm the chunkservers' page
                # cache for the region after this one (PREFETCH analog)
                asyncio.ensure_future(
                    self._send_prefetch(
                        loc, aligned_end, min(extra, chunk_len - aligned_end)
                    )
                )
            if data is None:
                return None  # landed in `into` already
            rel = off - aligned_off
            return data[rel : rel + size]
        raise st.StatusError(st.EIO, f"read failed after retries: {last_error}")

    async def _send_prefetch(self, loc, chunk_off: int, size: int) -> None:
        """Fire-and-forget CltocsPrefetch to the data-part holders for
        the chunk byte range [chunk_off, chunk_off+size)."""
        try:
            slice_type = None
            targets = []
            for pl in loc.locations:
                cpt = geometry.ChunkPartType.from_id(pl.part_id)
                slice_type = cpt.type if slice_type is None else slice_type
                if cpt.is_data:
                    targets.append((pl, cpt))
            if slice_type is None:
                return
            d = slice_type.data_parts
            lo_slot = (chunk_off // MFSBLOCKSIZE) // d
            hi_slot = ((chunk_off + size - 1) // MFSBLOCKSIZE) // d
            part_off = lo_slot * MFSBLOCKSIZE
            part_size = (hi_slot - lo_slot + 1) * MFSBLOCKSIZE
            from lizardfs_tpu.core.conn_pool import GLOBAL_POOL

            for pl, cpt in targets[:8]:
                addr = (pl.addr.host, pl.addr.port)
                try:
                    conn = await GLOBAL_POOL.acquire(addr)
                    await framing.send_message(
                        conn.writer,
                        m.CltocsPrefetch(
                            req_id=0, chunk_id=loc.chunk_id,
                            version=loc.version, part_id=pl.part_id,
                            offset=part_off, size=part_size,
                        ),
                    )
                    GLOBAL_POOL.release(addr, conn)
                except (OSError, ConnectionError):
                    pass
        except Exception:  # noqa: BLE001 — prefetch must never hurt reads
            log.debug("prefetch failed", exc_info=True)

    async def _read_located(
        self, loc, chunk_index: int, off: int, size: int, file_length: int,
        attempt: int = 0, avoid: set[tuple[str, int]] | None = None,
        into: np.ndarray | None = None, into_offset: int = 0,
    ) -> np.ndarray | None:
        from lizardfs_tpu.core import chunk_planner
        from lizardfs_tpu.core.cs_stats import GLOBAL_STATS

        # whole-chunk planning (chunk_read_planner.cc analog): a chunk
        # may have several representations at once (std copy + ec parts
        # mid-conversion); rank them by viability/health/cost and fall
        # through to the next on failure
        cands = chunk_planner.candidates(
            loc.locations, GLOBAL_STATS.score, avoid or set()
        )
        if not cands:
            raise ReadError("no locations for chunk")
        last: Exception | None = None
        failed_addrs: list[tuple[str, int]] = []
        for cand in cands:
            try:
                return await self._read_slice(
                    cand.type, cand.copies, loc, chunk_index, off, size,
                    file_length, attempt=attempt, avoid=avoid,
                    into=into, into_offset=into_offset,
                )
            except (ReadError, ConnectionError, OSError) as e:
                # aggregate every candidate's failed replicas so the
                # caller's blacklist learns them all, not just the
                # last slice's
                failed_addrs.extend(getattr(e, "used_addrs", ()))
                last = e
        if last is None:
            raise ReadError("unreachable")
        if failed_addrs:
            last.used_addrs = failed_addrs
        raise last

    def _part_failure_observer(self, loc):
        """execute_plan ``on_part_failure`` hook: a CRC-flagged part
        failure (the holder SERVED bytes that fail their checksum)
        reports the damaged part to the master, which drops it from the
        holder and queues the chunk through the RebuildEngine — closing
        the loop from client-side detection to re-replication even
        though the read itself recovers via decode."""
        def observe(part, wire_part_id, addr, exc):
            if not getattr(exc, "crc", False):
                return
            key = (loc.chunk_id, wire_part_id, addr)
            if key in self._damage_reported:
                return
            if len(self._damage_reported) > 4096:
                self._damage_reported.clear()
            self._damage_reported.add(key)
            self.metrics.counter(
                "damaged_parts_reported",
                help="chunk parts this client CRC-rejected and "
                     "reported to the master for rebuild",
            ).inc()
            # detached: the report must not inherit (and die with) the
            # reading op's retry deadline
            retrymod.spawn_detached(
                self._report_damaged(loc.chunk_id, wire_part_id, addr)
            )
        return observe

    async def _report_damaged(self, chunk_id: int, part_id: int,
                              addr: tuple[str, int]) -> None:
        try:
            await self._call(
                m.CltomaChunkDamaged, chunk_id=chunk_id, part_id=part_id,
                host=addr[0], port=addr[1],
            )
        except (st.StatusError, ConnectionError, OSError,
                asyncio.TimeoutError):
            pass  # best-effort: the scrubber is the backstop

    async def _read_slice(
        self, slice_type, copies, loc, chunk_index: int, off: int,
        size: int, file_length: int, attempt: int = 0,
        avoid: set[tuple[str, int]] | None = None,
        into: np.ndarray | None = None, into_offset: int = 0,
    ) -> np.ndarray | None:
        import random

        from lizardfs_tpu.core.cs_stats import GLOBAL_STATS

        # copy choice within the slice: health scores demote flaky/slow
        # replicas; topology order (master sorts closest first) breaks
        # ties. Retries avoid replicas that already failed THIS read,
        # then randomize among what is left.
        def pick(locs):
            good = [l for l in locs if l[0] not in (avoid or ())]
            pool = good or locs
            if attempt > 0 and len(pool) > 1:
                return random.choice(pool)
            best = max(range(len(pool)),
                       key=lambda i: (GLOBAL_STATS.score(pool[i][0]), -i))
            return pool[best]

        by_part = {p: pick(locs) for p, locs in copies.items()}

        def _tag(err):
            err.used_addrs = [addr for addr, _ in by_part.values()]
            return err
        chunk_len = min(
            max(file_length - chunk_index * MFSCHUNKSIZE, 0), MFSCHUNKSIZE
        )
        part_sizes = {
            p: striping.part_length(slice_type, p, chunk_len)
            for p in range(slice_type.expected_parts)
        }
        if slice_type.is_standard:
            # single part: read only [off, off+size)
            plan = plans.SliceReadPlan(
                slice_type, [plans.RequestedPartInfo(0, size)], size
            )
            plan.read_operations.append(plans.ReadOp(0, off, size, 0, 0))
            in_place = (
                into is not None and into.flags.c_contiguous
                and into.dtype == np.uint8
            )
            buffer = (
                into[into_offset : into_offset + size] if in_place else None
            )
            try:
                result = await execute_plan(
                    plan, loc.chunk_id, loc.version, by_part,
                    wave_timeout=self.wave_timeout,
                    buffer=buffer,
                    on_part_failure=self._part_failure_observer(loc),
                )
            except (ReadError, ConnectionError, OSError) as e:
                raise _tag(e)
            if in_place:
                return None  # bytes landed in `into`
            return np.asarray(result[:size])
        # striped slice: read covering stripe slots from all data parts
        d = slice_type.data_parts
        first_data = 1 if slice_type.is_xor else 0
        lo_block = off // MFSBLOCKSIZE
        hi_block = (off + size - 1) // MFSBLOCKSIZE
        lo_slot = lo_block // d
        hi_slot = hi_block // d
        nslots = hi_slot - lo_slot + 1
        wanted = [first_data + i for i in range(d)]

        # whole-stripe fast path: all data parts healthy, the request is
        # exactly a slot-aligned region, and the caller gave us a
        # contiguous destination — ONE native call reads every part over
        # polled sockets and de-interleaves in C (no per-part thread
        # dispatch, no separate gather pass). Any failure falls through
        # to the wave executor below, which handles recovery.
        from lizardfs_tpu.core import native_io

        region_blocks = hi_block - lo_block + 1
        if (
            native_io.parts_gather_available()
            # armed faults: the C gather can't be instrumented — the
            # wave executor below serves (LZ_FAULTS unset: unchanged)
            and not _faults.ACTIVE
            and into is not None
            and off == lo_slot * d * MFSBLOCKSIZE
            and size == region_blocks * MFSBLOCKSIZE
            and into.flags.c_contiguous and into.dtype == np.uint8
            and all(p in by_part for p in wanted)
            and attempt == 0
        ):
            cell: dict = {}
            fut = asyncio.get_running_loop().run_in_executor(
                native_io.EXECUTOR,
                # partial_with_trace: run_in_executor drops context, so
                # the request trace id rides the partial instead
                native_io.partial_with_trace(
                    native_io.read_parts_gather_blocking,
                    [by_part[p][0] for p in wanted],
                    loc.chunk_id, loc.version,
                    [by_part[p][1] for p in wanted],
                    lo_slot * MFSBLOCKSIZE, region_blocks,
                    into[into_offset : into_offset + size],
                    cell,
                ),
            )
            # run_in_executor does not propagate the phase-sink context;
            # the whole native gather (sockets + C de-interleave) is
            # timed at the await and charged as net — the chunkserver's
            # queue/disk/net attrs refine it in the attribution view
            t0 = self._t0()
            try:
                await asyncio.shield(fut)
                self._read_phase("net", t0)
                for p in wanted:
                    GLOBAL_STATS.record_success(by_part[p][0])
                # counted so tests/operators can see the fast path is
                # actually taken (a silent precondition miss would
                # quietly forfeit the 3x read win)
                self._record("stripe_gather_fast")
                return None
            except asyncio.CancelledError:
                native_io.abort_parts_gather(cell)
                try:
                    await asyncio.wait_for(asyncio.shield(fut), 10.0)
                except (Exception, asyncio.CancelledError):
                    pass
                raise
            except (native_io.NativeIOError, OSError, ConnectionError):
                self._record("stripe_gather_fallback")
                # degrade to the plan path (waves + recovery)
        # per-part scores from the shared chunkserver health registry:
        # an unhealthy holder's part drops in rank, so recovery reads
        # prefer parts on healthy servers (read_plan_executor.cc:95)
        planner = plans.SliceReadPlanner(
            slice_type, list(by_part.keys()),
            scores={p: GLOBAL_STATS.score(a[0])
                    for p, a in by_part.items()},
            encoder=self.encoder,
        )
        if not planner.is_readable(wanted):
            raise ReadError("not enough parts available")
        plan = planner.build_plan(wanted, lo_slot, nslots, part_sizes)
        # striped plans rotate bad parts internally via waves — no
        # blacklist tagging here, or one dead server would push every
        # healthy part off its topology-preferred copy on retry
        buf = await execute_plan(
            plan, loc.chunk_id, loc.version, by_part,
            wave_timeout=self.wave_timeout,
            on_part_failure=self._part_failure_observer(loc),
        )
        # reassemble the stripes we read, then slice the requested bytes.
        # The gather runs off-loop (native stripe_gather releases the
        # GIL) — at 64 MiB chunks an on-loop de-interleave serialized
        # every concurrent read behind ~40 ms of memcpy.
        bps = nslots * MFSBLOCKSIZE
        data_parts = {
            wanted[i]: buf[i * bps : (i + 1) * bps] for i in range(len(wanted))
        }
        rel = off - lo_slot * d * MFSBLOCKSIZE
        if (
            into is not None and rel == 0
            and into.flags.c_contiguous and into.dtype == np.uint8
        ):
            # zero-copy: de-interleave straight into the caller's buffer
            t0 = self._t0()
            await asyncio.to_thread(
                striping.assemble_chunk, data_parts, slice_type, size,
                into[into_offset : into_offset + size],
            )
            self._read_phase("gather", t0)
            return None
        t0 = self._t0()
        region = await asyncio.to_thread(
            striping.assemble_chunk, data_parts, slice_type,
            d * bps,  # bytes covered by these stripes
        )
        self._read_phase("gather", t0)
        return np.asarray(region[rel : rel + size])
