"""Client: library-first file system access (liblizardfs-client analog)."""
