"""FUSE frontend: mount the file system through libfuse2 via ctypes.

The analog of the reference's FUSE client (reference: src/mount/fuse/
mfs_fuse.cc + main.cc) for environments without python-fuse packages:
a minimal ctypes binding of libfuse 2.9's high-level API (the same
surface fusepy wraps) driving the async :class:`Client` from a
dedicated event-loop thread.

Usage:
    python -m lizardfs_tpu.client.fuse_mount --master host:port /mnt/liz

Implemented operations: getattr, readdir, mkdir, rmdir, create, unlink,
rename, link, symlink, readlink, open, read, write, truncate, chmod,
chown, utimens, statfs, getxattr/setxattr/listxattr/removexattr, flush.
"""

from __future__ import annotations

import asyncio
import ctypes
import ctypes.util
import errno
import stat as stat_mod
import sys
import threading
import time

from lizardfs_tpu.client.client import Client
from lizardfs_tpu.constants import MFSBLOCKSIZE
from lizardfs_tpu.proto import messages as m
from lizardfs_tpu.proto import status as st

c_off_t = ctypes.c_int64
c_mode_t = ctypes.c_uint32
c_dev_t = ctypes.c_uint64
c_uid_t = ctypes.c_uint32
c_gid_t = ctypes.c_uint32


class Timespec(ctypes.Structure):
    _fields_ = [("tv_sec", ctypes.c_int64), ("tv_nsec", ctypes.c_int64)]


class Stat(ctypes.Structure):
    # x86_64 linux struct stat
    _fields_ = [
        ("st_dev", ctypes.c_uint64),
        ("st_ino", ctypes.c_uint64),
        ("st_nlink", ctypes.c_uint64),
        ("st_mode", ctypes.c_uint32),
        ("st_uid", ctypes.c_uint32),
        ("st_gid", ctypes.c_uint32),
        ("__pad0", ctypes.c_int),
        ("st_rdev", ctypes.c_uint64),
        ("st_size", ctypes.c_int64),
        ("st_blksize", ctypes.c_int64),
        ("st_blocks", ctypes.c_int64),
        ("st_atim", Timespec),
        ("st_mtim", Timespec),
        ("st_ctim", Timespec),
        ("__unused", ctypes.c_int64 * 3),
    ]


class FuseFileInfo(ctypes.Structure):
    _fields_ = [
        ("flags", ctypes.c_int),
        ("fh_old", ctypes.c_ulong),
        ("writepage", ctypes.c_int),
        ("bits", ctypes.c_uint),
        ("fh", ctypes.c_uint64),
        ("lock_owner", ctypes.c_uint64),
    ]


class StatVfs(ctypes.Structure):
    _fields_ = [
        ("f_bsize", ctypes.c_ulong),
        ("f_frsize", ctypes.c_ulong),
        ("f_blocks", ctypes.c_uint64),
        ("f_bfree", ctypes.c_uint64),
        ("f_bavail", ctypes.c_uint64),
        ("f_files", ctypes.c_uint64),
        ("f_ffree", ctypes.c_uint64),
        ("f_favail", ctypes.c_uint64),
        ("f_fsid", ctypes.c_ulong),
        ("f_flag", ctypes.c_ulong),
        ("f_namemax", ctypes.c_ulong),
        ("__f_spare", ctypes.c_int * 6),
    ]


CB = ctypes.CFUNCTYPE
c_char_p = ctypes.c_char_p
c_void_p = ctypes.c_void_p
c_int = ctypes.c_int
c_size_t = ctypes.c_size_t

FILL_DIR_T = CB(c_int, c_void_p, c_char_p, ctypes.POINTER(Stat), c_off_t)

_FIELDS = [
    ("getattr", CB(c_int, c_char_p, ctypes.POINTER(Stat))),
    ("readlink", CB(c_int, c_char_p, c_void_p, c_size_t)),
    ("getdir", c_void_p),  # deprecated
    ("mknod", CB(c_int, c_char_p, c_mode_t, c_dev_t)),
    ("mkdir", CB(c_int, c_char_p, c_mode_t)),
    ("unlink", CB(c_int, c_char_p)),
    ("rmdir", CB(c_int, c_char_p)),
    ("symlink", CB(c_int, c_char_p, c_char_p)),
    ("rename", CB(c_int, c_char_p, c_char_p)),
    ("link", CB(c_int, c_char_p, c_char_p)),
    ("chmod", CB(c_int, c_char_p, c_mode_t)),
    ("chown", CB(c_int, c_char_p, c_uid_t, c_gid_t)),
    ("truncate", CB(c_int, c_char_p, c_off_t)),
    ("utime", c_void_p),  # superseded by utimens
    ("open", CB(c_int, c_char_p, ctypes.POINTER(FuseFileInfo))),
    # NOTE: data buffers are c_void_p, NOT c_char_p — ctypes converts
    # c_char_p arguments to NUL-truncated bytes copies, corrupting
    # binary IO (the classic fusepy pitfall)
    ("read", CB(c_int, c_char_p, c_void_p, c_size_t, c_off_t,
                ctypes.POINTER(FuseFileInfo))),
    ("write", CB(c_int, c_char_p, c_void_p, c_size_t, c_off_t,
                 ctypes.POINTER(FuseFileInfo))),
    ("statfs", CB(c_int, c_char_p, ctypes.POINTER(StatVfs))),
    ("flush", CB(c_int, c_char_p, ctypes.POINTER(FuseFileInfo))),
    ("release", CB(c_int, c_char_p, ctypes.POINTER(FuseFileInfo))),
    ("fsync", CB(c_int, c_char_p, c_int, ctypes.POINTER(FuseFileInfo))),
    ("setxattr", CB(c_int, c_char_p, c_char_p, c_void_p, c_size_t, c_int)),
    ("getxattr", CB(c_int, c_char_p, c_char_p, c_void_p, c_size_t)),
    ("listxattr", CB(c_int, c_char_p, c_void_p, c_size_t)),
    ("removexattr", CB(c_int, c_char_p, c_char_p)),
    ("opendir", CB(c_int, c_char_p, ctypes.POINTER(FuseFileInfo))),
    ("readdir", CB(c_int, c_char_p, c_void_p, FILL_DIR_T, c_off_t,
                   ctypes.POINTER(FuseFileInfo))),
    ("releasedir", CB(c_int, c_char_p, ctypes.POINTER(FuseFileInfo))),
    ("fsyncdir", CB(c_int, c_char_p, c_int, ctypes.POINTER(FuseFileInfo))),
    ("init", CB(c_void_p, c_void_p)),
    ("destroy", CB(None, c_void_p)),
    ("access", CB(c_int, c_char_p, c_int)),
    ("create", CB(c_int, c_char_p, c_mode_t, ctypes.POINTER(FuseFileInfo))),
    ("ftruncate", CB(c_int, c_char_p, c_off_t, ctypes.POINTER(FuseFileInfo))),
    ("fgetattr", CB(c_int, c_char_p, ctypes.POINTER(Stat),
                    ctypes.POINTER(FuseFileInfo))),
    ("lock", c_void_p),
    ("utimens", CB(c_int, c_char_p, ctypes.POINTER(Timespec))),
    ("bmap", c_void_p),
    ("flags", ctypes.c_uint),
    ("ioctl", c_void_p),
    ("poll", c_void_p),
    ("write_buf", c_void_p),
    ("read_buf", c_void_p),
    ("flock", c_void_p),
    ("fallocate", c_void_p),
]


class FuseOperations(ctypes.Structure):
    _fields_ = _FIELDS


def _load_libfuse():
    for name in ("libfuse.so.2", ctypes.util.find_library("fuse")):
        if not name:
            continue
        try:
            return ctypes.CDLL(name)
        except OSError:
            continue
    return None


class FuseContext(ctypes.Structure):
    _fields_ = [
        ("fuse", c_void_p),
        ("uid", ctypes.c_uint32),
        ("gid", ctypes.c_uint32),
        ("pid", ctypes.c_int32),
        ("private_data", c_void_p),
        ("umask", ctypes.c_uint32),
    ]


class LizardFuse:
    """Bridges libfuse callbacks to the async Client."""

    def __init__(self, master_addrs: list[tuple[str, int]]):
        self.libfuse = None  # set by mount(); enables caller identity
        self.loop = asyncio.new_event_loop()
        self.client = Client("", 0, master_addrs=master_addrs)
        self._loop_thread = threading.Thread(
            target=self.loop.run_forever, daemon=True
        )
        # open-time snapshots of special-inode content so piecewise
        # kernel reads see a consistent document (no torn .oplog)
        self._special_snap: dict[bytes, bytes] = {}
        # direct C read path: libfuse callback threads call liz_read
        # without a hop through the asyncio loop (latency path; see
        # client/native_client.py)
        from lizardfs_tpu.client import native_client

        self._native_reads = (
            native_client.NativeReadPool(
                lambda: self.client.current_master_addr
            )
            if native_client.available() else None
        )

    def start(self) -> None:
        self._loop_thread.start()
        self._run(self.client.connect(info="fuse-mount"))
        # local master proxy (masterproxy.cc analog): tools inside the
        # mount reach the master via the address in .masterinfo
        from lizardfs_tpu.client.masterproxy import MasterProxy

        self.proxy = MasterProxy(lambda: self.client.current_master_addr)
        self._run(self.proxy.start())

    def _run(self, coro, timeout: float = 60.0):
        # capture the kernel caller's pid HERE (fuse_get_context is only
        # valid on the callback thread) and carry it into the coroutine:
        # the client throttles IO under the caller's cgroup limit group
        # (reference: src/mount/io_limit_group.cc classification)
        pid = self._caller_pid()

        async def _with_caller():
            from lizardfs_tpu.client.client import IO_CALLER_PID

            token = IO_CALLER_PID.set(pid)
            try:
                return await coro
            finally:
                IO_CALLER_PID.reset(token)

        return asyncio.run_coroutine_threadsafe(
            _with_caller(), self.loop
        ).result(timeout)

    def _caller_pid(self) -> int | None:
        if self.libfuse is None:
            return None
        try:
            ctx = self.libfuse.fuse_get_context()
            if ctx:
                return int(ctx.contents.pid) or None
        except Exception:  # noqa: BLE001
            pass
        return None

    # --- helpers ----------------------------------------------------------

    def _resolve(self, path: bytes) -> m.Attr:
        return self._run(self.client.resolve(path.decode()))

    def _resolve_parent(self, path: bytes):
        return self._run(self.client.resolve_parent(path.decode()))

    def _caller(self) -> tuple[int, list[int]]:
        """Kernel caller identity from fuse_get_context: uid + primary
        gid + supplementary groups (fuse_getgroups, best effort)."""
        if self.libfuse is None:
            return 0, [0]
        try:
            ctx = self.libfuse.fuse_get_context()
            if not ctx:
                return 0, [0]
            c = ctx.contents
            gids = [int(c.gid)]
            try:
                arr = (ctypes.c_uint32 * 32)()
                n = self.libfuse.fuse_getgroups(32, arr)
                if 0 < n <= 32:
                    for g in arr[:n]:
                        if int(g) not in gids:
                            gids.append(int(g))
            except Exception:  # noqa: BLE001
                pass
            return int(c.uid), gids
        except Exception:  # noqa: BLE001
            pass
        return 0, [0]

    @staticmethod
    def _errno(e: Exception) -> int:
        if isinstance(e, st.StatusError):
            return -{
                st.ENOENT: errno.ENOENT, st.EEXIST: errno.EEXIST,
                st.EACCES: errno.EACCES, st.EPERM: errno.EPERM,
                st.ENOTDIR: errno.ENOTDIR, st.EISDIR: errno.EISDIR,
                st.ENOTEMPTY: errno.ENOTEMPTY, st.EINVAL: errno.EINVAL,
                st.QUOTA_EXCEEDED: errno.EDQUOT, st.ENOATTR: errno.ENODATA,
                st.NAME_TOO_LONG: errno.ENAMETOOLONG,
            }.get(e.code, errno.EIO)
        return -errno.EIO

    def _fill_stat(self, attr: m.Attr, out) -> None:
        ctypes.memset(ctypes.byref(out), 0, ctypes.sizeof(out))
        kind = {
            m.FTYPE_FILE: stat_mod.S_IFREG,
            m.FTYPE_DIR: stat_mod.S_IFDIR,
            m.FTYPE_SYMLINK: stat_mod.S_IFLNK,
        }.get(attr.ftype, stat_mod.S_IFREG)
        out.st_ino = attr.inode
        out.st_mode = kind | attr.mode
        out.st_nlink = max(attr.nlink, 1)
        out.st_uid = attr.uid
        out.st_gid = attr.gid
        out.st_size = attr.length
        out.st_blksize = MFSBLOCKSIZE
        out.st_blocks = (attr.length + 511) // 512
        out.st_atim.tv_sec = attr.atime
        out.st_mtim.tv_sec = attr.mtime
        out.st_ctim.tv_sec = attr.ctime

    # --- special inodes (.oplog / .stats / .masterinfo analogs,
    #     src/mount/special_inode*.cc) ----------------------------------

    def _special_content(self, path: bytes) -> bytes | None:
        name = path.decode()
        if name == "/.stats":
            lines = [
                f"{op}: {count}"
                for op, count in sorted(self.client.op_counters.items())
            ]
            lines.append(f"cache_hits: {self.client.cache.hits}")
            lines.append(f"cache_misses: {self.client.cache.misses}")
            return ("\n".join(lines) + "\n").encode()
        if name == "/.oplog":
            lines = [
                f"{ts:.3f} {op}" for ts, op, _ in list(self.client.oplog)
            ]
            return ("\n".join(lines) + "\n").encode()
        if name == "/.masterinfo":
            addr = self.client.current_master_addr
            proxy = getattr(self, "proxy", None)
            return (
                f"master: {addr[0]}:{addr[1]}\n"
                f"masterproxy: 127.0.0.1:{proxy.port if proxy else 0}\n"
                f"session: {self.client.session_id}\n"
            ).encode()
        return None

    # --- operations -------------------------------------------------------

    def build_operations(self) -> FuseOperations:
        ops = FuseOperations()
        keep = self._keepalive = []

        def wrap(name, fn):
            cb_type = dict(_FIELDS)[name]

            def guarded(*args):
                try:
                    return fn(*args)
                except Exception as e:  # noqa: BLE001
                    return self._errno(e)

            cb = cb_type(guarded)
            keep.append(cb)
            setattr(ops, name, cb)

        def op_getattr(path, out):
            special = self._special_content(path)
            if special is not None:
                ctypes.memset(
                    ctypes.byref(out.contents), 0, ctypes.sizeof(Stat)
                )
                out.contents.st_mode = stat_mod.S_IFREG | 0o444
                out.contents.st_nlink = 1
                out.contents.st_size = len(special)
                out.contents.st_blksize = MFSBLOCKSIZE
                return 0
            self._fill_stat(self._resolve(path), out.contents)
            return 0

        def op_fgetattr(path, out, fi):
            # by HANDLE, not path: fstat(fd) must work on an
            # unlinked-but-open (sustained) file whose name is gone
            inode = fi.contents.fh if fi else 0
            if inode:
                self._fill_stat(
                    self._run(self.client.getattr(inode)), out.contents
                )
                return 0
            return op_getattr(path, out)

        def op_readdir(path, buf, filler, offset, fi):
            uid, gids = self._caller()
            node = self._resolve(path)
            filler(buf, b".", None, 0)
            filler(buf, b"..", None, 0)
            for entry in self._run(
                self.client.readdir(node.inode, uid=uid, gids=gids)
            ):
                filler(buf, entry.name.encode(), None, 0)
            return 0

        def op_mkdir(path, mode):
            uid, gids = self._caller()
            parent, name = self._resolve_parent(path)
            self._run(
                self.client.mkdir(
                    parent.inode, name, mode & 0o7777, uid=uid, gid=gids[0]
                )
            )
            return 0

        def op_rmdir(path):
            uid, gids = self._caller()
            parent, name = self._resolve_parent(path)
            self._run(self.client.rmdir(parent.inode, name, uid=uid, gids=gids))
            return 0

        def op_create(path, mode, fi):
            uid, gids = self._caller()
            parent, name = self._resolve_parent(path)
            attr = self._run(
                self.client.create(
                    parent.inode, name, mode & 0o7777, uid=uid, gid=gids[0]
                )
            )
            # the create handle is an open handle (kernel will send a
            # matching release)
            self._run(self.client.open(attr.inode))
            fi.contents.fh = attr.inode
            return 0

        def op_open(path, fi):
            special = self._special_content(path)
            if special is not None:
                self._special_snap[bytes(path)] = special
                fi.contents.fh = 0
                return 0
            node = self._resolve(path)
            # enforce at open like default_permissions: read or write
            # intent from O_ACCMODE against mode bits + ACLs
            uid, gids = self._caller()
            if uid != 0:
                accmode = fi.contents.flags & 3  # O_RDONLY/O_WRONLY/O_RDWR
                want = {0: 4, 1: 2, 2: 6}.get(accmode, 4)
                ok = self._run(self.client.access(node.inode, uid, gids, want))
                if not ok:
                    return -errno.EACCES
            # register the handle: the file now survives unlink until
            # op_release (sustained files)
            self._run(self.client.open(node.inode))
            fi.contents.fh = node.inode
            return 0

        def op_unlink(path):
            uid, gids = self._caller()
            parent, name = self._resolve_parent(path)
            self._run(
                self.client.unlink(parent.inode, name, uid=uid, gids=gids)
            )
            return 0

        def op_rename(old, new):
            uid, gids = self._caller()
            ps, ns = self._resolve_parent(old)
            pd, nd = self._resolve_parent(new)
            self._run(
                self.client.rename(
                    ps.inode, ns, pd.inode, nd, uid=uid, gids=gids
                )
            )
            return 0

        def op_link(target, link):
            uid, gids = self._caller()
            t = self._resolve(target)
            parent, name = self._resolve_parent(link)
            self._run(
                self.client.link(
                    t.inode, parent.inode, name, uid=uid, gids=gids
                )
            )
            return 0

        def op_symlink(target, link):
            uid, gids = self._caller()
            parent, name = self._resolve_parent(link)
            self._run(self.client.symlink(
                parent.inode, name, target.decode(), uid=uid,
                gid=gids[0] if gids else 0,
            ))
            return 0

        def op_readlink(path, buf, size):
            node = self._resolve(path)
            target = self._run(self.client.readlink(node.inode)).encode()[: size - 1]
            ctypes.memmove(buf, target + b"\0", len(target) + 1)
            return 0

        def op_read(path, buf, size, offset, fi):
            special = self._special_snap.get(bytes(path))
            if special is None:
                special = self._special_content(path)
            if special is not None:
                piece = special[offset : offset + size]
                ctypes.memmove(buf, piece, len(piece))
                return len(piece)
            inode = fi.contents.fh or self._resolve(path).inode
            data = None
            # the native pool cannot classify callers or pace, so it
            # stands down while ANY cluster IO limit is active — every
            # byte must pass the client's group throttle
            if (
                self._native_reads is not None
                and not self.client.io_limits_active
            ):
                data = self._native_reads.read(inode, offset, size)
            if data is None:  # striped/degraded or pool busy: planner path
                data = self._run(self.client.read_file(inode, offset, size))
            ctypes.memmove(buf, data, len(data))
            return len(data)

        def op_write(path, buf, size, offset, fi):
            inode = fi.contents.fh or self._resolve(path).inode
            data = ctypes.string_at(buf, size)
            self._run(self.client.pwrite(inode, offset, data))
            return size

        def op_truncate(path, length):
            uid, gids = self._caller()
            node = self._resolve(path)
            self._run(
                self.client.truncate(node.inode, length, uid=uid, gids=gids)
            )
            return 0

        def op_ftruncate(path, length, fi):
            # by HANDLE: ftruncate(fd) on a sustained file has no path
            inode = fi.contents.fh if fi else 0
            if inode:
                uid, gids = self._caller()
                self._run(
                    self.client.truncate(inode, length, uid=uid, gids=gids)
                )
                return 0
            return op_truncate(path, length)

        def op_chmod(path, mode):
            cuid, cgids = self._caller()
            node = self._resolve(path)
            self._run(
                self.client.setattr(
                    node.inode, 1, mode=mode & 0o7777,
                    caller_uid=cuid, caller_gids=cgids,
                )
            )
            return 0

        def op_chown(path, uid, gid):
            cuid, cgids = self._caller()
            node = self._resolve(path)
            mask = (2 if uid != 0xFFFFFFFF else 0) | (4 if gid != 0xFFFFFFFF else 0)
            self._run(
                self.client.setattr(
                    node.inode, mask, uid=uid, gid=gid,
                    caller_uid=cuid, caller_gids=cgids,
                )
            )
            return 0

        def op_utimens(path, times):
            node = self._resolve(path)
            atime = times[0].tv_sec if times else 0
            mtime = times[1].tv_sec if times else 0
            self._run(
                self.client.setattr(node.inode, 8 | 16, atime=atime, mtime=mtime)
            )
            return 0

        statfs_cache = {"t": 0.0, "v": (1 << 30 << 16, 1 << 29 << 16)}

        def op_statfs(path, out):
            ctypes.memset(ctypes.byref(out.contents), 0, ctypes.sizeof(StatVfs))
            out.contents.f_bsize = MFSBLOCKSIZE
            out.contents.f_frsize = MFSBLOCKSIZE
            # desktop tools poll statvfs aggressively; one master RPC
            # per few seconds, stale-on-error
            now = time.monotonic()
            if now - statfs_cache["t"] > 5.0:
                try:
                    statfs_cache["v"] = self._run(self.client.statfs())
                    statfs_cache["t"] = now
                except Exception:
                    statfs_cache["t"] = now - 4.0  # retry soon, serve stale
            total, avail = statfs_cache["v"]
            out.contents.f_blocks = total // MFSBLOCKSIZE
            out.contents.f_bfree = avail // MFSBLOCKSIZE
            out.contents.f_bavail = avail // MFSBLOCKSIZE
            out.contents.f_namemax = 255
            return 0

        def op_access(path, amode):
            self._resolve(path)
            return 0

        def op_flush(path, fi):
            return 0

        def op_release(path, fi):
            self._special_snap.pop(bytes(path), None)
            inode = fi.contents.fh
            if inode:
                try:
                    self._run(self.client.release(inode), timeout=10.0)
                except Exception:  # noqa: BLE001 — release is best effort
                    pass
            return 0

        def op_fsync(path, datasync, fi):
            return 0

        def op_setxattr(path, name, value, size, flags):
            uid, gids = self._caller()
            node = self._resolve(path)
            raw = ctypes.string_at(value, size)
            self._run(self.client.set_xattr(
                node.inode, name.decode(), raw, uid=uid, gids=gids))
            return 0

        def op_getxattr(path, name, value, size):
            uid, gids = self._caller()
            node = self._resolve(path)
            data = self._run(self.client.get_xattr(
                node.inode, name.decode(), uid=uid, gids=gids))
            if size == 0:
                return len(data)
            if size < len(data):
                return -errno.ERANGE
            ctypes.memmove(value, data, len(data))
            return len(data)

        def op_listxattr(path, buf, size):
            node = self._resolve(path)
            names = self._run(self.client.list_xattr(node.inode))
            blob = b"".join(n.encode() + b"\0" for n in names)
            if size == 0:
                return len(blob)
            if size < len(blob):
                return -errno.ERANGE
            ctypes.memmove(buf, blob, len(blob))
            return len(blob)

        def op_removexattr(path, name):
            uid, gids = self._caller()
            node = self._resolve(path)
            self._run(self.client.remove_xattr(
                node.inode, name.decode(), uid=uid, gids=gids))
            return 0

        for name, fn in (
            ("getattr", op_getattr), ("fgetattr", op_fgetattr),
            ("readdir", op_readdir), ("mkdir", op_mkdir), ("rmdir", op_rmdir),
            ("create", op_create), ("open", op_open), ("unlink", op_unlink),
            ("rename", op_rename), ("link", op_link), ("symlink", op_symlink),
            ("readlink", op_readlink), ("read", op_read), ("write", op_write),
            ("truncate", op_truncate), ("ftruncate", op_ftruncate),
            ("chmod", op_chmod), ("chown", op_chown), ("utimens", op_utimens),
            ("statfs", op_statfs), ("access", op_access), ("flush", op_flush),
            ("release", op_release), ("fsync", op_fsync),
            ("setxattr", op_setxattr), ("getxattr", op_getxattr),
            ("listxattr", op_listxattr), ("removexattr", op_removexattr),
        ):
            wrap(name, fn)
        return ops


def mount(master_addrs: list[tuple[str, int]], mountpoint: str,
          foreground: bool = True, extra_args: list[str] | None = None) -> int:
    lib = _load_libfuse()
    if lib is None:
        print("error: libfuse2 not found", file=sys.stderr)
        return 1
    lib.fuse_get_context.restype = ctypes.POINTER(FuseContext)
    bridge = LizardFuse(master_addrs)
    bridge.libfuse = lib
    bridge.start()
    ops = bridge.build_operations()
    argv_list = [b"lizardfs-fuse", mountpoint.encode()]
    if foreground:
        argv_list.append(b"-f")
    argv_list += [a.encode() for a in (extra_args or [])]
    argv = (ctypes.c_char_p * len(argv_list))(*argv_list)
    lib.fuse_main_real.argtypes = [
        c_int, ctypes.POINTER(c_char_p), ctypes.POINTER(FuseOperations),
        c_size_t, c_void_p,
    ]
    try:
        return lib.fuse_main_real(
            len(argv_list), argv, ctypes.byref(ops), ctypes.sizeof(ops), None
        )
    finally:
        if bridge._native_reads is not None:
            bridge._native_reads.close()


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="lizardfs-fuse", description=__doc__)
    p.add_argument("--master", default="127.0.0.1:9420")
    p.add_argument("mountpoint")
    p.add_argument("-o", dest="options", default="", help="fuse options")
    args = p.parse_args(argv)
    addrs = []
    for item in args.master.split(","):
        host, _, port = item.strip().rpartition(":")
        addrs.append((host or "127.0.0.1", int(port)))
    extra = ["-o", args.options] if args.options else []
    return mount(addrs, args.mountpoint, extra_args=extra)


if __name__ == "__main__":
    sys.exit(main())
