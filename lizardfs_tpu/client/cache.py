"""Client-side read cache + readahead adviser.

Analog of the reference's per-inode read machinery (reference:
src/mount/readdata_cache.h block-aligned ReadCache,
src/mount/readahead_adviser.h window sizing): a block-granular LRU
shared across inodes with byte budget, and a per-inode sequentiality
detector that grows the readahead window on streaming reads and resets
it on seeks.
"""

from __future__ import annotations

from collections import OrderedDict

from lizardfs_tpu.constants import MFSBLOCKSIZE


class BlockCache:
    """LRU of 64 KiB chunk blocks keyed (inode, chunk_index, block).

    Coherence is three-layered (reference: src/mount/readdata_cache.h
    timeout expiry; src/master/matoclserv.cc data-cache invalidation;
    src/mount/chunk_locator.h version revalidation):

    - the master pushes ``MatoclCacheInvalidate`` when ANOTHER session
      mutates the file -> ``invalidate()``;
    - every locate returns (chunk_id, version); ``note_version()`` drops
      blocks cached under a different identity, so even a missed push is
      caught at the next locate;
    - entries expire after ``max_age`` seconds as the last-resort bound
      (e.g. this client's master connection dropped mid-push).
    """

    def __init__(self, max_bytes: int = 64 * 2**20, max_age: float = 3.0):
        import time

        self.max_bytes = max_bytes
        self.max_age = max_age
        self._now = time.monotonic
        self._used = 0
        # (inode, ci, block) -> (data, fill-ts, version-tag)
        self._entries: OrderedDict[
            tuple[int, int, int], tuple[bytes, float, object]
        ] = OrderedDict()
        # (inode, ci) -> resident blocks, so note_version/invalidate
        # touch only their own chunk instead of scanning every entry
        self._chunk_blocks: dict[tuple[int, int], set[int]] = {}
        # (inode, ci) -> last version tag seen by a locate; LRU-bounded
        # (evicting a note only costs a skipped cache fill — see put())
        self._versions: OrderedDict[tuple[int, int], object] = OrderedDict()
        self.max_version_notes = 8192
        self.hits = 0
        self.misses = 0
        self._invalidate_listeners: list = []

    def add_invalidate_listener(self, fn) -> None:
        """``fn(inode)`` runs on every explicit invalidation (master
        push, local write, truncate): layers stacked above the client —
        e.g. the NFS gateway's readahead buffers — stay coherent
        without their own push plumbing."""
        self._invalidate_listeners.append(fn)

    def _remove(self, key: tuple[int, int, int]) -> None:
        data, _, _ = self._entries.pop(key)
        self._used -= len(data)
        blocks = self._chunk_blocks.get(key[:2])
        if blocks is not None:
            blocks.discard(key[2])
            if not blocks:
                del self._chunk_blocks[key[:2]]

    def get(self, inode: int, ci: int, block: int) -> bytes | None:
        key = (inode, ci, block)
        entry = self._entries.get(key)
        if entry is None:
            self.misses += 1
            return None
        data, ts, _version = entry
        if self._now() - ts > self.max_age:
            self._remove(key)
            self.misses += 1
            return None
        self._entries.move_to_end(key)
        self.hits += 1
        return data

    def put(
        self, inode: int, ci: int, block: int, data: bytes,
        version: object = None,
    ) -> None:
        # refuse to cache under a version the locate layer no longer
        # vouches for: an invalidation (or a newer locate) that landed
        # while this read was in flight cleared/changed the note, and
        # re-inserting would resurrect exactly the stale bytes the
        # invalidation removed
        if version is not None and self._versions.get((inode, ci)) != version:
            return
        key = (inode, ci, block)
        if key in self._entries:
            self._remove(key)
        self._entries[key] = (data, self._now(), version)
        self._used += len(data)
        self._chunk_blocks.setdefault((inode, ci), set()).add(block)
        while self._used > self.max_bytes and self._entries:
            self._remove(next(iter(self._entries)))

    def note_version(self, inode: int, ci: int, version: object) -> None:
        """Record the chunk identity a locate just returned; drop any
        blocks cached under a different one (stale by definition)."""
        key = (inode, ci)
        if self._versions.get(key) == version:
            self._versions.move_to_end(key)
            return
        self._versions[key] = version
        self._versions.move_to_end(key)
        while len(self._versions) > self.max_version_notes:
            self._versions.popitem(last=False)
        for b in list(self._chunk_blocks.get(key, ())):
            if self._entries[(inode, ci, b)][2] != version:
                self._remove((inode, ci, b))

    def invalidate(self, inode: int, ci: int | None = None) -> None:
        """Drop an inode's blocks (optionally just one chunk's)."""
        chunks = (
            [(inode, ci)] if ci is not None
            else [k for k in self._chunk_blocks if k[0] == inode]
        )
        for ck in chunks:
            for b in list(self._chunk_blocks.get(ck, ())):
                self._remove((ck[0], ck[1], b))
            self._versions.pop(ck, None)
        if ci is None:
            for vk in [k for k in self._versions if k[0] == inode]:
                del self._versions[vk]
        for fn in self._invalidate_listeners:
            fn(inode)


class ReadaheadAdviser:
    """Grows a readahead window while access stays sequential."""

    def __init__(
        self,
        min_window: int = 0,
        max_window: int = 16 * MFSBLOCKSIZE,
    ):
        self.min_window = min_window
        self.max_window = max_window
        self._expected_next = -1
        self._window = min_window

    def advise(self, offset: int, size: int) -> int:
        """Returns extra bytes to read past the request."""
        if offset == self._expected_next:
            self._window = min(
                max(self._window * 2, 2 * MFSBLOCKSIZE), self.max_window
            )
        else:
            self._window = self.min_window
        self._expected_next = offset + size
        return self._window
