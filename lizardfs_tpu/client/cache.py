"""Client-side read cache + readahead adviser.

Analog of the reference's per-inode read machinery (reference:
src/mount/readdata_cache.h block-aligned ReadCache,
src/mount/readahead_adviser.h window sizing): a block-granular LRU
shared across inodes with byte budget, and a per-inode sequentiality
detector that grows the readahead window on streaming reads and resets
it on seeks.
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

from lizardfs_tpu.constants import MFSBLOCKSIZE


class BlockCache:
    """LRU of 64 KiB chunk blocks keyed (inode, chunk_index, block).

    Entries expire after ``max_age`` seconds: this client only sees its
    OWN writes, so the age bound limits how stale a read can be when
    another client mutates the file (the reference's readdata cache uses
    the same timeout-expiry model).
    """

    def __init__(self, max_bytes: int = 64 * 2**20, max_age: float = 3.0):
        import time

        self.max_bytes = max_bytes
        self.max_age = max_age
        self._now = time.monotonic
        self._used = 0
        self._entries: OrderedDict[
            tuple[int, int, int], tuple[bytes, float]
        ] = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, inode: int, ci: int, block: int) -> bytes | None:
        key = (inode, ci, block)
        entry = self._entries.get(key)
        if entry is None:
            self.misses += 1
            return None
        data, ts = entry
        if self._now() - ts > self.max_age:
            self._used -= len(data)
            del self._entries[key]
            self.misses += 1
            return None
        self._entries.move_to_end(key)
        self.hits += 1
        return data

    def put(self, inode: int, ci: int, block: int, data: bytes) -> None:
        key = (inode, ci, block)
        old = self._entries.pop(key, None)
        if old is not None:
            self._used -= len(old[0])
        self._entries[key] = (data, self._now())
        self._used += len(data)
        while self._used > self.max_bytes and self._entries:
            _, (evicted, _) = self._entries.popitem(last=False)
            self._used -= len(evicted)

    def invalidate(self, inode: int, ci: int | None = None) -> None:
        """Drop an inode's blocks (optionally just one chunk's)."""
        keys = [
            k for k in self._entries
            if k[0] == inode and (ci is None or k[1] == ci)
        ]
        for k in keys:
            self._used -= len(self._entries.pop(k)[0])


class ReadaheadAdviser:
    """Grows a readahead window while access stays sequential."""

    def __init__(
        self,
        min_window: int = 0,
        max_window: int = 16 * MFSBLOCKSIZE,
    ):
        self.min_window = min_window
        self.max_window = max_window
        self._expected_next = -1
        self._window = min_window

    def advise(self, offset: int, size: int) -> int:
        """Returns extra bytes to read past the request."""
        if offset == self._expected_next:
            self._window = min(
                max(self._window * 2, 2 * MFSBLOCKSIZE), self.max_window
            )
        else:
            self._window = self.min_window
        self._expected_next = offset + size
        return self._window
