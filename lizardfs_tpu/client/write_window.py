"""Adaptive write window for the striped chunk-write pipeline.

PR 1's phase telemetry blamed the ec(8,4) write gap on stripe-serial
round trips: the double-buffered pipeline paid one ack barrier per
stripe segment. This controller replaces the fixed depth with an
**adaptive N-deep window** (the classic pipeline-depth/flow-control
shape from striped-storage systems — cf. the chain-replication write
executor in the LizardFS reference and credit-based stripe writers in
Colossus-style systems):

* up to ``depth`` stripe segments ride unacknowledged per chunk write
  (``LZ_WRITE_WINDOW`` caps it; 0 kills the window entirely and
  restores the PR-1 double-buffered path);
* **credit-based flow control**: a :class:`CreditBucket` per
  chunkserver bounds unacknowledged bulk frames per connection, and
  one shared byte bucket bounds total staged bytes across every
  concurrent chunk write of the client (both from
  ``runtime/limiter.py``) — credits return when commit acks arrive;
* **adaptation from live PhaseBreakdown busy fractions**: every
  collected segment feeds encode/send EWMAs; an encode-bound pipeline
  shrinks the window (deeper buffering cannot help a compute
  bottleneck), a send-bound one grows it (keep the wire busy);
* **commit coalescing**: finished chunks queue their WriteChunkEnd
  records here and flush as ONE ``CltomaWriteChunkEndBatch`` master
  round trip per window flush instead of one handshake per chunk.

Depth/credit/coalesce counters register into the supplied Metrics
registry (Prometheus-exported wherever the owner exposes it).
"""

from __future__ import annotations

from lizardfs_tpu.runtime.limiter import CreditBucket

# adaptation hysteresis: one phase must out-busy the other by this
# factor (over the EWMA) before the depth moves — a noisy 50/50 split
# must not make the window oscillate
_ADAPT_RATIO = 1.3
# observations between depth moves: segments are short; adapting on
# every one would chase scheduling noise
_ADAPT_EVERY = 4
_EWMA_ALPHA = 0.3


class WriteWindow:
    """Shared, client-wide window state (one instance per Client)."""

    def __init__(
        self,
        max_depth: int,
        metrics=None,
        cs_credits: int | None = None,
        budget_bytes: int = 128 * 2**20,
    ):
        self.max_depth = max(1, int(max_depth))
        # start double-buffered (the PR-1 shape) and adapt from there
        self.depth = min(2, self.max_depth)
        # per-chunkserver credit capacity: how many unacked bulk frames
        # one connection may carry; defaults to the window ceiling so a
        # single writer is never credit-bound before it is depth-bound,
        # while concurrent writers to the same server share the cap
        self.cs_credits = int(cs_credits) if cs_credits else self.max_depth
        self._cs: dict[tuple[str, int], CreditBucket] = {}
        self._budget = CreditBucket(float(budget_bytes))
        self._enc_ewma = 0.0
        self._send_ewma = 0.0
        self._since_adapt = 0
        # commit coalescing: chunk-end records queued by _write_chunk,
        # flushed by the client as one CltomaWriteChunkEndBatch; the
        # batch size bound keeps chunk locks from outliving the window
        self.pending_ends: list[dict] = []
        self.commit_batch = max(self.max_depth, 2)
        self._m_depth = self._m_waits = None
        self._m_segments = self._m_coalesced = None
        if metrics is not None:
            self._m_depth = metrics.gauge(
                "write_window_depth",
                help="current adaptive write-window depth (segments in "
                     "flight per striped chunk write)",
            )
            self._m_depth.set(float(self.depth))
            metrics.gauge(
                "write_window_depth_max",
                help="configured write-window ceiling (LZ_WRITE_WINDOW)",
            ).set(float(self.max_depth))
            self._m_waits = metrics.counter(
                "write_window_credit_waits",
                help="segment sends that blocked on chunkserver or byte "
                     "credits (backpressure events)",
            )
            self._m_segments = metrics.counter(
                "write_window_segments",
                help="stripe segments sent through the windowed path",
            )
            self._m_coalesced = metrics.counter(
                "write_commits_coalesced",
                help="WriteChunkEnd round trips saved by commit "
                     "coalescing (batched ends minus flushes)",
            )

    # --- credits ---------------------------------------------------------

    def _bucket(self, addr: tuple[str, int]) -> CreditBucket:
        b = self._cs.get(addr)
        if b is None:
            b = self._cs[addr] = CreditBucket(float(self.cs_credits))
            if len(self._cs) > 4096:
                # long-lived mounts see unboundedly many servers; only
                # idle (full) buckets are safe to forget
                for a in [a for a, bk in self._cs.items()
                          if bk.available >= bk.capacity and a != addr]:
                    del self._cs[a]
        return b

    def try_acquire(self, addrs, nbytes: float) -> bool:
        """All-or-nothing: one send credit per chunkserver plus
        ``nbytes`` from the shared staging budget, without waiting.
        False leaves every bucket untouched. This is the windowed
        sender's primary path — a writer holding outstanding segments
        must NEVER block here (it would hold credits while waiting for
        credits: two concurrent chunk writes that jointly exhaust a
        bucket would deadlock), it reaps its oldest acks instead."""
        taken = []
        ok = True
        for addr in addrs:
            if self._bucket(addr).try_acquire(1.0):
                taken.append(addr)
            else:
                ok = False
                break
        if ok and not self._budget.try_acquire(float(nbytes)):
            ok = False
        if not ok:
            for addr in taken:
                self._bucket(addr).release(1.0)
        return ok

    async def acquire(self, addrs, nbytes: float) -> None:
        """Blocking acquire — callers must hold NO outstanding
        segments (see try_acquire): then every credit holder is either
        an outstanding writer (which always reaps and releases) or
        another blocked acquirer. Buckets are taken in one GLOBAL
        order (sorted addrs, shared budget last), so blocked-acquirer
        wait chains strictly ascend and can never cycle — two sessions
        whose part layouts order the same chunkservers differently
        would otherwise hold-and-wait on each other."""
        taken = []
        try:
            for addr in sorted(addrs):
                await self._bucket(addr).acquire(1.0)
                taken.append(addr)
            await self._budget.acquire(float(nbytes))
        except BaseException:
            for addr in taken:
                self._bucket(addr).release(1.0)
            raise

    def note_segment(self, waited: bool) -> None:
        if self._m_segments is not None:
            self._m_segments.inc()
        if waited and self._m_waits is not None:
            self._m_waits.inc()

    def release(self, addrs, nbytes: float) -> None:
        for addr in addrs:
            self._bucket(addr).release(1.0)
        self._budget.release(float(nbytes))

    # --- adaptation ------------------------------------------------------

    def observe(self, encode_s: float, send_s: float) -> None:
        """Feed one collected segment's busy split; adapt depth with
        hysteresis. encode-bound -> shrink (buffering cannot beat a
        compute bottleneck), send-bound -> grow (keep the wire busy)."""
        self._enc_ewma += _EWMA_ALPHA * (encode_s - self._enc_ewma)
        self._send_ewma += _EWMA_ALPHA * (send_s - self._send_ewma)
        self._since_adapt += 1
        if self._since_adapt < _ADAPT_EVERY:
            return
        self._since_adapt = 0
        if (self._send_ewma > self._enc_ewma * _ADAPT_RATIO
                and self.depth < self.max_depth):
            self.depth += 1
        elif (self._enc_ewma > self._send_ewma * _ADAPT_RATIO
                and self.depth > 1):
            self.depth -= 1
        if self._m_depth is not None:
            self._m_depth.set(float(self.depth))

    # --- commit coalescing ----------------------------------------------

    def queue_end(self, chunk_id: int, inode: int, chunk_index: int,
                  file_length: int, status: int) -> bool:
        """Queue one chunk's end-of-write record; True = the queue hit
        the batch bound and the caller should flush now."""
        self.pending_ends.append({
            "chunk_id": chunk_id, "inode": inode,
            "chunk_index": chunk_index, "file_length": file_length,
            "status": status,
        })
        return len(self.pending_ends) >= self.commit_batch

    def drain_ends(self) -> list[dict]:
        batch, self.pending_ends = self.pending_ends, []
        return batch

    def requeue_ends(self, batch: list[dict]) -> None:
        """Put a failed flush's records back (oldest first) so a later
        flush retries them — a drained-and-dropped batch would silently
        lose ANOTHER concurrent write's commits."""
        self.pending_ends[:0] = batch

    def note_coalesced(self, batch_len: int) -> None:
        """Count round trips saved — only after the batch RPC landed
        (a requeued batch must not double-count on retry)."""
        if batch_len > 1 and self._m_coalesced is not None:
            self._m_coalesced.inc(batch_len - 1)
