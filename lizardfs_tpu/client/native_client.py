"""ctypes binding to liblizardfs_client.so for latency-critical paths.

The FUSE mount routes kernel reads through this pool: the libfuse
callback thread calls ``liz_read`` directly (ctypes drops the GIL for
the duration), so a cached small read costs one C call + one TCP round
trip to the chunkserver's native data plane — no hop through the
mount's asyncio loop thread. This is the analog of the reference FUSE
client's in-process C read path (src/mount/readdata.cc): Python stays
in control of sessions/metadata, C moves the bytes.

Handles serialize internally (one mutex per liz_t), so the pool holds
several and hands them out round-robin; a busy pool falls back to the
asyncio path rather than queueing.
"""

from __future__ import annotations

import ctypes
import os
import queue
import threading

_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
    "native", "liblizardfs_client.so",
)

_lib = None
try:
    if os.path.exists(_LIB_PATH):
        _lib = ctypes.CDLL(_LIB_PATH)
        _lib.liz_init.restype = ctypes.c_void_p
        _lib.liz_init.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                  ctypes.c_char_p]
        _lib.liz_destroy.argtypes = [ctypes.c_void_p]
        _lib.liz_set_identity.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                          ctypes.c_uint32]
        _lib.liz_read.restype = ctypes.c_int64
        _lib.liz_read.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_char_p,
        ]
except OSError:
    _lib = None


def available() -> bool:
    return _lib is not None


class NativeReadPool:
    """A small pool of C client handles for direct-thread reads."""

    def __init__(self, addr_fn, password: str = "", size: int = 4):
        # addr_fn: () -> (host, port) of the CURRENT master, so handles
        # created after a failover reach the new active
        self.addr_fn = addr_fn
        self.password = password
        self.size = size
        self._handles: queue.SimpleQueue = queue.SimpleQueue()
        self._created = 0
        self._lock = threading.Lock()
        self._dead = False

    def _acquire(self):
        try:
            return self._handles.get_nowait()
        except queue.Empty:
            pass
        with self._lock:
            if self._created >= self.size or self._dead:
                return None
            self._created += 1
        try:
            host, port = self.addr_fn()
        except Exception:  # noqa: BLE001 — not connected yet
            host = None
        if not host:
            with self._lock:
                self._created -= 1
            return None
        h = _lib.liz_init(
            host.encode(), int(port),
            self.password.encode() if self.password else None,
        )
        if not h:
            with self._lock:
                self._created -= 1
            return None
        return h

    def read(self, inode: int, offset: int, size: int) -> bytes | None:
        """One direct read; None = path unavailable (caller falls back)."""
        if _lib is None or self._dead or size <= 0:
            return None
        h = self._acquire()
        if h is None:
            return None
        buf = ctypes.create_string_buffer(size)
        n = _lib.liz_read(h, inode, offset, size, buf)
        if n == -1:
            # connection-level failure (master failover, dead link):
            # retire the handle; a fresh one targets the current master
            _lib.liz_destroy(h)
            with self._lock:
                self._created -= 1
            return None
        self._handles.put(h)
        if n < 0:
            # striped/degraded file or a status error: the asyncio
            # planner path handles recovery
            return None
        return buf.raw[:n]

    def close(self) -> None:
        self._dead = True
        while True:
            try:
                h = self._handles.get_nowait()
            except queue.Empty:
                break
            _lib.liz_destroy(h)
