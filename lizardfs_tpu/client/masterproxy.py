"""Local master proxy: tools inside a mount reach the master through it.

The analog of the reference's masterproxy module (reference:
src/mount/masterproxy.cc): the mount listens on a localhost port and
relays whole TCP streams to the current master, so CLI tools need only
the mount point — they read the proxy address from ``.masterinfo`` and
never have to know the cluster's master list or follow a failover.
"""

from __future__ import annotations

import asyncio


class MasterProxy:
    """Byte-level TCP relay to the (current) master address."""

    def __init__(self, master_addr_fn):
        """``master_addr_fn() -> (host, port)`` — called per connection
        so failover (the client tracking a new master) is picked up."""
        self.master_addr_fn = master_addr_fn
        self.port = 0
        self._server: asyncio.AbstractServer | None = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, "127.0.0.1", 0
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            try:
                # 3.12+ wait_closed also waits for live handlers; a
                # parked relay must not wedge or crash mount teardown
                await asyncio.wait_for(self._server.wait_closed(), 5.0)
            except asyncio.TimeoutError:
                pass
            self._server = None

    async def _handle(self, reader, writer) -> None:
        host, port = self.master_addr_fn()
        try:
            # dial bound: a tool's connection must fail fast when the
            # advertised master is blackholed, like every other dial
            up_reader, up_writer = await asyncio.wait_for(
                asyncio.open_connection(host, port), 5.0
            )
        except (OSError, asyncio.TimeoutError):
            writer.close()
            return

        async def pump(src, dst):
            try:
                while True:
                    # lint: waive(unbounded-await): byte-level relay pump — parks on whichever side speaks next by design; liveness is owned by the two endpoints' own timeouts
                    data = await src.read(65536)
                    if not data:
                        break
                    dst.write(data)
                    # lint: waive(unbounded-await): relay backpressure mirrors the slower endpoint; a timer here would cut live slow tools
                    await dst.drain()
            except (ConnectionError, OSError):
                pass
            finally:
                try:
                    dst.close()
                except RuntimeError:
                    pass

        await asyncio.gather(
            pump(reader, up_writer), pump(up_reader, writer)
        )
