"""cgroup-based IO limit group classification.

Maps a process (by pid) to its cgroup path so per-group bandwidth
limits apply to *workloads*, not just sessions — the reference
classifies every FUSE caller this way (reference:
src/mount/io_limit_group.cc getIoLimitGroupId reads
``/proc/<pid>/cgroup`` and matches the configured subsystem; mount
option ``cgroupsiolimits``). A mount serving several containers can
then give each container its own bandwidth share.

Supports both cgroup layouts:
  * v2 (unified): the ``0::<path>`` line, selected with subsystem "".
  * v1: the line whose controller list contains the configured
    subsystem (the reference's ``subsystem`` config key, e.g. "blkio").

Unclassifiable processes (no /proc entry, no matching line) fall into
``UNCLASSIFIED``, which the master's limit table can target explicitly
— same contract as the reference's "unclassified" limit.
"""

from __future__ import annotations

import time

from lizardfs_tpu.utils.io_limits import (  # noqa: F401 — re-exports
    UNCLASSIFIED, parse_limits_cfg, resolve_limit,
)


def read_cgroup(pid: int, subsystem: str = "", proc_root: str = "/proc") -> str:
    """The cgroup path of ``pid`` for ``subsystem`` ("" = v2 unified)."""
    try:
        with open(f"{proc_root}/{pid}/cgroup", encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except OSError:
        return UNCLASSIFIED
    for line in lines:
        parts = line.split(":", 2)
        if len(parts) != 3:
            continue
        _hid, controllers, path = parts
        if not subsystem:
            if controllers == "":  # v2 unified hierarchy
                return path or "/"
        elif subsystem in controllers.split(","):
            return path or "/"
    return UNCLASSIFIED


class GroupCache:
    """pid -> group with TTL, mirroring the reference's IoLimitGroup
    cache: classification costs a /proc read, and FUSE sees the same
    pids thousands of times per second."""

    def __init__(self, subsystem: str = "", ttl: float = 30.0,
                 proc_root: str = "/proc", max_entries: int = 4096):
        self.subsystem = subsystem
        self.ttl = ttl
        self.proc_root = proc_root
        self.max_entries = max_entries
        self._cache: dict[int, tuple[str, float]] = {}

    def classify(self, pid: int) -> str:
        now = time.monotonic()
        hit = self._cache.get(pid)
        if hit is not None and hit[1] > now:
            return hit[0]
        group = read_cgroup(pid, self.subsystem, self.proc_root)
        if len(self._cache) >= self.max_entries:
            # pids recycle; drop expired entries, or everything if none
            live = {p: v for p, v in self._cache.items() if v[1] > now}
            self._cache = live if len(live) < self.max_entries else {}
        self._cache[pid] = (group, now + self.ttl)
        return group


