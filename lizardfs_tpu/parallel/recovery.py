"""Mesh-sharded wide-stripe reconstruction: rebuild lost parts over ICI.

The decode half of :mod:`lizardfs_tpu.parallel.sharded` — the multichip
story was encode-only while rebuilding lost parts is the reference
replicator's hot loop (reference: src/common/ec_read_plan.h:113-146
recovery read plans, src/chunkserver/slice_recovery_planner.h:29-38).
The formulation is the SAME psum-scatter SPMD matmul as
``sharded_encode_with_crcs``, driven by the *recovery* bit-matrix
instead of the generator:

  * the k surviving parts (chosen by :func:`gf256.recovery_selection`,
    the shared dispatch rule — CPU/TPU/mesh stay byte-identical by
    construction) are sharded over mesh axis "stripe",
  * each chip multiplies its survivor slice by its column slice of the
    expanded (8w, 8k) recovery matrix — a *partial* GF(2) sum,
  * partials meet in a ``psum_scatter`` over the block dimension, so
    the rebuilt parts land block-sharded for the post-rebuild CRC
    (computed locally on whichever chip owns the block),
  * the caller compares those CRCs against the stored per-block CRCs
    of the lost parts — the ReadPlanExecutor's post-recovery verify.

This mirrors the efficient-decoding line of Cauchy MDS array codes
(arxiv 1611.09968: decode is the same bit-matrix product as encode,
with a different constant matrix) — which is exactly what makes the
encode program reusable: only the (8w, 8k) constant changes.

``LZ_SHARDED_RECOVERY=0`` is the subsystem kill switch: the encoder
auto-ladder skips the sharded backend and every ``enabled()`` check
short-circuits to the single-chip paths.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from lizardfs_tpu.ops import gf256, jax_ec
from lizardfs_tpu.parallel.sharded import shard_map


def enabled() -> bool:
    """The subsystem kill switch (``LZ_SHARDED_RECOVERY=0`` disables)."""
    from lizardfs_tpu.constants import env_flag

    return env_flag("LZ_SHARDED_RECOVERY")


def sharded_reconstruct_with_crcs(
    mesh, k: int, m: int, available: list[int], wanted: list[int],
    block_size: int,
):
    """Build a jitted mesh-sharded reconstruct+CRC step.

    Parts are globally indexed 0..k+m-1 (data first).  ``available``
    are the live part indices (>= k of them), ``wanted`` the lost ones
    (up to m).  Returns ``run(survivors)`` where ``survivors`` is
    (k, nb*block_size) holding the **used** parts (``run.used`` — the
    selection rule's choice, ascending) stacked in that order; outputs
    are (recovered (w, nb, block_size) block-sharded, crcs (w, nb)) —
    byte-identical to the cpu/cpp/jax single-chip recover for any
    erasure pattern.  nb and k must divide the mesh like the encode
    step.
    """
    stripe_axis = mesh.axis_names[0]
    n_stripe = mesh.shape[stripe_axis]
    block_axis = mesh.axis_names[1] if len(mesh.axis_names) > 1 else None
    n_block = mesh.shape[block_axis] if block_axis else 1
    if k % n_stripe:
        raise ValueError(f"k={k} not divisible by stripe axis {n_stripe}")
    used, _ = gf256.recovery_selection(k, m, list(available), list(wanted))
    w = len(wanted)
    bigm_host = jax_ec.recovery_bitmatrix(
        k, m, tuple(used), tuple(wanted)
    )  # (8w, 8k) over the used parts, ascending

    def local_step(bigm_local, surv_local):
        # surv_local: (k/n, N) used-part slice; bigm_local: (8w, 8k/n)
        bits = jax_ec._unpack_bits_rows(surv_local)
        partial = jax.lax.dot_general(
            bigm_local,
            bits,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # (8w, N) partial GF sums
        nb = surv_local.shape[1] // block_size
        partial = partial.reshape(8 * w, nb, block_size)
        partial = jax.lax.psum_scatter(
            partial, stripe_axis, scatter_dimension=1, tiled=True
        )  # (8w, nb/n, block_size)
        nb_loc = partial.shape[1]
        rec_bits = (partial & 1).reshape(8 * w, nb_loc * block_size)
        rec_local = jax_ec._pack_bits_rows(rec_bits)  # (w, nb_loc*bs)
        rec_local = rec_local.reshape(w, nb_loc, block_size)
        rcrc = jax_ec.block_crcs(
            rec_local.reshape(w * nb_loc, block_size), block_size
        ).reshape(w, nb_loc)
        return rec_local, rcrc

    if block_axis is None:
        in_specs = (P(None, stripe_axis), P(stripe_axis, None))
        out_specs = (P(None, stripe_axis, None), P(None, stripe_axis))
    else:
        in_specs = (P(None, stripe_axis), P(stripe_axis, block_axis))
        out_specs = (
            P(None, (block_axis, stripe_axis), None),
            P(None, (block_axis, stripe_axis)),
        )

    step = jax.jit(
        shard_map(
            local_step, mesh=mesh, in_specs=in_specs, out_specs=out_specs
        )
    )

    def run(survivors):
        if survivors.shape[0] != k:
            raise ValueError(
                f"need the {k} used parts stacked, got {survivors.shape[0]}"
            )
        nb = survivors.shape[1] // block_size
        if survivors.shape[1] % block_size or nb % (n_stripe * n_block):
            raise ValueError(
                f"part bytes must be nb*{block_size} with nb divisible "
                f"by mesh extent {n_stripe * n_block}; got "
                f"{survivors.shape[1]}"
            )
        return step(jnp.asarray(bigm_host), survivors)

    run.used = used
    return run


def sharded_reconstruct_verify(
    mesh, k: int, m: int, available: list[int], wanted: list[int],
    survivors_by_part: dict[int, np.ndarray], block_size: int,
    expected_crcs: np.ndarray | None = None,
):
    """One-shot reconstruct + post-rebuild CRC verify.

    ``survivors_by_part`` maps live global part index -> byte stream;
    ``expected_crcs`` (w, nb) are the stored per-block CRCs of the lost
    parts.  Returns (recovered (w, N) np.uint8, crcs (w, nb) np.uint32,
    ok bool) — ``ok`` is True when every rebuilt block checksums to its
    stored CRC (or no expectation was given).
    """
    run = sharded_reconstruct_with_crcs(
        mesh, k, m, available, wanted, block_size
    )
    stacked = np.stack([
        np.asarray(survivors_by_part[i], dtype=np.uint8) for i in run.used
    ])
    rec, rcrc = run(stacked)
    rec_np = np.asarray(rec).reshape(len(wanted), -1)
    rcrc_np = np.asarray(rcrc).astype(np.uint32)
    ok = True
    if expected_crcs is not None:
        ok = bool(
            np.array_equal(rcrc_np, np.asarray(expected_crcs, np.uint32))
        )
    return rec_np, rcrc_np, ok
