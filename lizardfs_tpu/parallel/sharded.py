"""Wide-stripe erasure coding sharded over a TPU device mesh.

The reference scales one 64 MiB chunk across up to 64 servers with wide
stripes (ec(32,8), ec(32,32): src/common/slice_traits.h:143-146). The
TPU-native analog maps the **stripe axis onto the device mesh**:

  * data parts are sharded over mesh axis "stripe" (k/n parts per chip),
  * each chip computes a *partial* parity bit-matmul with its column
    slice of the expanded generator matrix,
  * partial sums meet in a ``psum_scatter`` (reduce-scatter) over the
    block axis — parity lands already sharded by block for local CRC —
    riding ICI, the analog of the reference's parity all-gather
    (BASELINE config 5),
  * per-block CRCs are computed locally on whichever chip owns the
    block; no further communication.

GF(2) addition is XOR, which commutes with integer summation followed by
``& 1`` — so XLA's native int32 psum IS the field reduction. This is the
whole trick that makes wide-stripe EC a textbook SPMD matmul.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from lizardfs_tpu.ops import jax_ec

# jax.shard_map graduated from jax.experimental at ~0.4.40; the call
# sites pass mesh/in_specs/out_specs as keywords, which both spellings
# accept — so one shim keeps every jax in the support window working
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pragma: no cover - exercised on older jax only
    from jax.experimental.shard_map import shard_map


def make_mesh(devices=None, axis: str = "stripe") -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), (axis,))


def make_mesh_2d(
    stripe: int, block: int, devices=None,
    axes: tuple[str, str] = ("stripe", "block"),
) -> Mesh:
    """2-D mesh: stripe-parallel x block-parallel.

    The stripe axis is the tensor-parallel analog (parts of one stripe
    spread over chips, joined by the parity reduce-scatter); the block
    axis is the data-parallel analog (disjoint block ranges, no
    communication at all). On multi-host topologies put the stripe axis
    within a slice (ICI) and the block axis across hosts (DCN) — the
    block axis never communicates, so DCN bandwidth is irrelevant.
    """
    devices = devices if devices is not None else jax.devices()
    if stripe * block != len(devices):
        raise ValueError(
            f"mesh {stripe}x{block} needs {stripe * block} devices, "
            f"have {len(devices)}"
        )
    return Mesh(np.array(devices).reshape(stripe, block), axes)


def sharded_encode_with_crcs(mesh: Mesh, k: int, m: int, block_size: int):
    """Build a jitted wide-stripe encode+CRC step over ``mesh``.

    Returns ``step(bigm, data)`` where data is (k, nb*block_size) with the
    part axis sharded over the mesh; outputs are
    (parity (m, nb, block_size) block-sharded, data_crcs (k, nb),
    parity_crcs (m, nb)). nb and k must be divisible by the mesh size.
    """
    stripe_axis = mesh.axis_names[0]
    n_stripe = mesh.shape[stripe_axis]
    block_axis = mesh.axis_names[1] if len(mesh.axis_names) > 1 else None
    n_block = mesh.shape[block_axis] if block_axis else 1
    n_dev = n_stripe
    axis = stripe_axis
    if k % n_stripe:
        raise ValueError(f"k={k} not divisible by stripe axis {n_stripe}")

    def local_step(bigm_local, data_local):
        # data_local: (k/n, N); bigm_local: (8m, 8k/n) column slice
        nloc, nbytes = data_local.shape
        nb = nbytes // block_size
        bits = jax_ec._unpack_bits_rows(data_local)
        partial = jax.lax.dot_general(
            bigm_local,
            bits,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # (8m, N) partial GF sums
        partial = partial.reshape(8 * m, nb, block_size)
        # reduce-scatter over the block axis: parity arrives block-sharded
        partial = jax.lax.psum_scatter(
            partial, axis, scatter_dimension=1, tiled=True
        )  # (8m, nb/n, block_size)
        nb_loc = partial.shape[1]
        parity_bits = (partial & 1).reshape(8 * m, nb_loc * block_size)
        parity_local = jax_ec._pack_bits_rows(parity_bits)  # (m, nb_loc*bs)
        parity_local = parity_local.reshape(m, nb_loc, block_size)
        dcrc = jax_ec.block_crcs(
            data_local.reshape(nloc * nb, block_size), block_size
        ).reshape(nloc, nb)
        pcrc = jax_ec.block_crcs(
            parity_local.reshape(m * nb_loc, block_size), block_size
        ).reshape(m, nb_loc)
        return parity_local, dcrc, pcrc

    if block_axis is None:
        in_specs = (P(None, axis), P(axis, None))
        out_specs = (P(None, axis, None), P(axis, None), P(None, axis))
    else:
        # 2-D: parts over 'stripe', block ranges over 'block' (pure data
        # parallelism, zero communication on that axis). The scattered
        # parity's block dim is partitioned by 'block' first, then by
        # the reduce-scatter within each block group.
        in_specs = (P(None, stripe_axis), P(stripe_axis, block_axis))
        out_specs = (
            P(None, (block_axis, stripe_axis), None),
            P(stripe_axis, block_axis),
            P(None, (block_axis, stripe_axis)),
        )

    step = jax.jit(
        shard_map(
            local_step, mesh=mesh, in_specs=in_specs, out_specs=out_specs
        )
    )

    def run(data):
        nb = data.shape[1] // block_size
        if data.shape[1] % block_size or nb % (n_stripe * n_block):
            raise ValueError(
                f"data bytes per part must be nb*{block_size} with nb "
                f"divisible by mesh extent {n_stripe * n_block}; got "
                f"{data.shape[1]}"
            )
        bigm = jnp.asarray(jax_ec.encoding_bitmatrix(k, m))
        return step(bigm, data)

    return run
