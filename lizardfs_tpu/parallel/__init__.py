"""Multi-chip parallel encode: mesh shardings + collectives over ICI/DCN."""
