"""Wire protocol: framing, declarative serializers, message catalog."""
