"""Message catalog for every link in the system.

Semantic mirror of the reference's per-link packet headers (reference:
src/protocol/{cltoma,matocl,cltocs,cstocl,cstoma,matocs,cstocs}.h and the
id catalog in MFSCommunication.h) with a fresh, uniform encoding via
:mod:`lizardfs_tpu.proto.codec`. Type id ranges by link:

  1000-1099  client -> master (CLTOMA) / master -> client (MATOCL)
  1100-1199  chunkserver <-> master (CSTOMA / MATOCS)
  1200-1299  client/peer <-> chunkserver data plane (CLTOCS / CSTOCL / CSTOCS)
  1300-1399  metalogger/shadow <-> master (MLTOMA / MATOML)
  1400-1499  admin

Requests carry a ``req_id`` echoed by the response so links can pipeline
(the reference pairs messages by message id fields similarly).
"""

from __future__ import annotations

from lizardfs_tpu.proto.codec import Message

# --------------------------------------------------------------------------
# shared sub-structures
# --------------------------------------------------------------------------


class Addr(Message):
    """Network address of a daemon."""

    FIELDS = (("host", "str"), ("port", "u16"))

    def key(self):
        return (self.host, self.port)


class Attr(Message):
    """File attributes (subset of the reference's 35-byte attr blob).

    ``eattr`` (trailing, skew-tolerant): the per-inode extra-attribute
    flags (EATTR_NOOWNER/NOCACHE/NOENTRYCACHE, constants.py) — carried
    on every attr reply so clients can enforce cache semantics without
    an extra RPC; peers predating the field read/serve 0.

    ``meta_version`` (trailing, skew-tolerant): the consistency token —
    NOT a file attribute but the serving master's applied changelog
    position, stamped at reply time. It rides Attr because Attr is the
    skew-variable terminal field of MatoclAttrReply (the codec forbids
    fields after it); see MatoclReadChunk for the token semantics."""

    SKEW_TOLERANT_FROM = 12
    FIELDS = (
        ("inode", "u32"),
        ("ftype", "u8"),  # 1=file, 2=directory, 3=symlink
        ("mode", "u16"),
        ("uid", "u32"),
        ("gid", "u32"),
        ("atime", "u32"),
        ("mtime", "u32"),
        ("ctime", "u32"),
        ("nlink", "u32"),
        ("length", "u64"),
        ("goal", "u8"),
        ("trash_time", "u32"),
        ("eattr", "u8"),
        ("meta_version", "u64"),
    )


FTYPE_FILE = 1
FTYPE_DIR = 2
FTYPE_SYMLINK = 3


class PartLocation(Message):
    """Where one chunk part lives."""

    FIELDS = (("addr", "msg:Addr"), ("part_id", "u32"))  # part_id = ChunkPartType.id


class DirEntry(Message):
    FIELDS = (("name", "str"), ("inode", "u32"), ("ftype", "u8"))


class ChunkPartInfo(Message):
    """A chunk part held by a chunkserver (registration / reports)."""

    FIELDS = (("chunk_id", "u64"), ("version", "u32"), ("part_id", "u32"))


# --------------------------------------------------------------------------
# client <-> master
# --------------------------------------------------------------------------


class CltomaRegister(Message):
    """``replica_ok`` (trailing, skew-tolerant): set by clients willing
    to be served by a shadow master in read-replica mode — the shadow
    accepts the (primary-issued) ``session_id`` without committing a
    session allocation and serves only the read-mostly RPC allowlist.
    Old peers send 0 and are refused by shadows as before.

    ``epoch`` (trailing, skew-tolerant): the highest cluster fencing
    epoch the client has observed (see MatoclRegister). A master whose
    own epoch is LOWER refuses the registration — it is a zombie
    ex-primary a later election superseded. 0 = pre-HA peer / no
    election has ever run (fencing never engages)."""

    MSG_TYPE = 1000
    SKEW_TOLERANT_FROM = 4
    FIELDS = (
        ("req_id", "u32"),
        ("session_id", "u64"),
        ("info", "str"),
        ("password", "str"),
        ("replica_ok", "u8"),
        ("epoch", "u64"),
    )


class MatoclRegister(Message):
    # trailing ``meta_version``: the serving master's applied changelog
    # position — seeds the client's monotonic-reads floor (see
    # MatoclAttrReply); old masters send 0 = no floor.
    # trailing ``epoch``: the serving master's cluster fencing epoch
    # (epoch_bump changelog op, HA failover). The client keeps the max
    # it has ever seen and presents it on every redial, so a zombie
    # ex-primary can never re-adopt a client that outlived it. Old
    # masters send 0.
    MSG_TYPE = 1001
    SKEW_TOLERANT_FROM = 3
    FIELDS = (
        ("req_id", "u32"),
        ("status", "u8"),
        ("session_id", "u64"),
        ("meta_version", "u64"),
        ("epoch", "u64"),
    )


class CltomaLookup(Message):
    MSG_TYPE = 1002
    FIELDS = (
        ("req_id", "u32"),
        ("parent", "u32"),
        ("name", "str"),
        ("uid", "u32"),
        ("gids", "list:u32"),
    )


class MatoclAttrReply(Message):
    """Shared reply for lookup/getattr/mkdir/create/setattr.

    The consistency token rides ``attr.meta_version`` (Attr must stay
    the terminal field — its own skew-tolerant tail elides): the
    serving master's applied changelog position at reply time. A client
    routing reads to a shadow replica keeps the max token it has
    observed (its monotonic-reads floor; mutations through the primary
    raise it) and retries through the primary whenever a replica reply
    carries an older token. Old peers send/read 0 = untokened."""

    MSG_TYPE = 1003
    FIELDS = (("req_id", "u32"), ("status", "u8"), ("attr", "msg:Attr"))


class CltomaGetattr(Message):
    MSG_TYPE = 1004
    FIELDS = (("req_id", "u32"), ("inode", "u32"))


class CltomaMkdir(Message):
    MSG_TYPE = 1006
    FIELDS = (
        ("req_id", "u32"),
        ("parent", "u32"),
        ("name", "str"),
        ("mode", "u16"),
        ("uid", "u32"),
        ("gid", "u32"),
    )


class CltomaCreate(Message):
    MSG_TYPE = 1008
    FIELDS = (
        ("req_id", "u32"),
        ("parent", "u32"),
        ("name", "str"),
        ("mode", "u16"),
        ("uid", "u32"),
        ("gid", "u32"),
    )


class CltomaReaddir(Message):
    MSG_TYPE = 1010
    FIELDS = (
        ("req_id", "u32"),
        ("inode", "u32"),
        ("uid", "u32"),
        ("gids", "list:u32"),
    )


class MatoclReaddir(Message):
    # trailing ``meta_version``: consistency token, see MatoclAttrReply
    MSG_TYPE = 1011
    SKEW_TOLERANT_FROM = 3
    FIELDS = (
        ("req_id", "u32"),
        ("status", "u8"),
        ("entries", "list:msg:DirEntry"),
        ("meta_version", "u64"),
    )


class CltomaUnlink(Message):
    MSG_TYPE = 1012
    FIELDS = (
        ("req_id", "u32"),
        ("parent", "u32"),
        ("name", "str"),
        ("uid", "u32"),
        ("gids", "list:u32"),
    )


class MatoclStatusReply(Message):
    """Generic status-only reply.

    ``meta_version`` (trailing, skew-tolerant): consistency token, see
    MatoclAttrReply — carried on mutation acks too so a client's
    monotonic-reads floor covers read-your-writes through replicas.

    ``retry_after_ms`` (trailing, skew-tolerant): the fair-share
    admission controller's backoff hint on BUSY sheds — QoS sheds
    answer ANY request type with this reply (the RPC pump resolves by
    req_id and call_ok raises before typed-field access), so the hint
    needs exactly one carrier. 0 / absent = no hint."""

    MSG_TYPE = 1013
    SKEW_TOLERANT_FROM = 2
    FIELDS = (
        ("req_id", "u32"), ("status", "u8"), ("meta_version", "u64"),
        ("retry_after_ms", "u32"),
    )


class CltomaRmdir(Message):
    MSG_TYPE = 1014
    FIELDS = (
        ("req_id", "u32"),
        ("parent", "u32"),
        ("name", "str"),
        ("uid", "u32"),
        ("gids", "list:u32"),
    )


class CltomaRename(Message):
    MSG_TYPE = 1016
    FIELDS = (
        ("req_id", "u32"),
        ("parent_src", "u32"),
        ("name_src", "str"),
        ("parent_dst", "u32"),
        ("name_dst", "str"),
        ("uid", "u32"),
        ("gids", "list:u32"),
    )


class CltomaSetGoal(Message):
    MSG_TYPE = 1018
    FIELDS = (
        ("req_id", "u32"),
        ("inode", "u32"),
        ("goal", "u8"),
        ("uid", "u32"),
    )


class CltomaSetEattr(Message):
    """Set the per-inode extra-attribute flags (geteattr reads them
    from any attr reply's trailing ``eattr``). Replied with
    MatoclAttrReply carrying the updated attr."""

    MSG_TYPE = 1070
    FIELDS = (
        ("req_id", "u32"),
        ("inode", "u32"),
        ("eattr", "u8"),
        ("uid", "u32"),
    )


class CltomaReadChunk(Message):
    # ``trace_id`` (request-scoped tracing, runtime/tracing.py) is a
    # skew-tolerant trailing field: a peer predating it decodes as
    # trace 0 = untraced (tests/test_tracing.py pins the skew)
    MSG_TYPE = 1020
    SKEW_TOLERANT_FROM = 5
    FIELDS = (
        ("req_id", "u32"),
        ("inode", "u32"),
        ("chunk_index", "u32"),
        ("uid", "u32"),
        ("gids", "list:u32"),
        ("trace_id", "u64"),
    )


class MatoclReadChunk(Message):
    # trailing ``meta_version``: consistency token, see MatoclAttrReply.
    # On locate replies the token pairs with the client's local
    # locate-epoch machinery: the epoch guards against invalidations
    # racing the RPC, the token guards against a lagging replica.
    MSG_TYPE = 1021
    SKEW_TOLERANT_FROM = 6
    FIELDS = (
        ("req_id", "u32"),
        ("status", "u8"),
        ("chunk_id", "u64"),
        ("version", "u32"),
        ("file_length", "u64"),
        ("locations", "list:msg:PartLocation"),
        ("meta_version", "u64"),
    )


class CltomaWriteChunk(Message):
    # trailing ``trace_id``: see CltomaReadChunk
    MSG_TYPE = 1022
    SKEW_TOLERANT_FROM = 5
    FIELDS = (
        ("req_id", "u32"),
        ("inode", "u32"),
        ("chunk_index", "u32"),
        ("uid", "u32"),
        ("gids", "list:u32"),
        ("trace_id", "u64"),
    )


class MatoclWriteChunk(Message):
    MSG_TYPE = 1023
    FIELDS = (
        ("req_id", "u32"),
        ("status", "u8"),
        ("chunk_id", "u64"),
        ("version", "u32"),
        ("file_length", "u64"),
        ("locations", "list:msg:PartLocation"),
    )


class CltomaWriteChunkEnd(Message):
    # trailing ``trace_id``: see CltomaReadChunk. The verdict-bearing
    # ``status`` stays REQUIRED — only the trace hint is optional.
    MSG_TYPE = 1024
    SKEW_TOLERANT_FROM = 6
    FIELDS = (
        ("req_id", "u32"),
        ("chunk_id", "u64"),
        ("inode", "u32"),
        ("chunk_index", "u32"),
        ("file_length", "u64"),
        ("status", "u8"),
        ("trace_id", "u64"),
    )


class WriteChunkEndEntry(Message):
    """One chunk's end-of-write record inside a coalesced commit."""

    FIELDS = (
        ("chunk_id", "u64"),
        ("inode", "u32"),
        ("chunk_index", "u32"),
        ("file_length", "u64"),
        ("status", "u8"),
    )


class CltomaWriteChunkEndBatch(Message):
    """Coalesced WriteChunkEnd: one master round trip seals every chunk
    the write window has finished since the last flush, instead of one
    handshake per chunk. Entries apply in list order (chain-write
    ordering preserved; the length merge is max() so order cannot
    shrink a file). Trailing ``trace_id``: see CltomaReadChunk."""

    MSG_TYPE = 1075
    SKEW_TOLERANT_FROM = 2
    FIELDS = (
        ("req_id", "u32"),
        ("ends", "list:msg:WriteChunkEndEntry"),
        ("trace_id", "u64"),
    )


class CltomaChunkDamaged(Message):
    """Client-side corruption report: a read CRC-rejected this part
    (the bytes arrived but fail their checksum — the HOLDER's copy is
    bad). The master drops the part from the holder's recorded set and
    queues the chunk through the RebuildEngine, the same handling a
    chunkserver scrubber report (CstomaChunkDamaged) gets; the holder
    is named by address because clients never learn cs_ids."""

    MSG_TYPE = 1076
    FIELDS = (
        ("req_id", "u32"),
        ("chunk_id", "u64"),
        ("part_id", "u32"),
        ("host", "str"),
        ("port", "u16"),
    )


class CltomaTruncate(Message):
    MSG_TYPE = 1026
    FIELDS = (
        ("req_id", "u32"),
        ("inode", "u32"),
        ("length", "u64"),
        ("uid", "u32"),
        ("gids", "list:u32"),
    )


class CltomaSetattr(Message):
    MSG_TYPE = 1028
    FIELDS = (
        ("req_id", "u32"),
        ("inode", "u32"),
        ("set_mask", "u8"),  # 1=mode 2=uid 4=gid 8=atime 16=mtime 32=trash_time
        ("mode", "u16"),
        ("uid", "u32"),
        ("gid", "u32"),
        ("atime", "u32"),
        ("mtime", "u32"),
        ("trash_time", "u32"),
        ("caller_uid", "u32"),
        ("caller_gids", "list:u32"),
    )


class CltomaSymlink(Message):
    MSG_TYPE = 1030
    FIELDS = (
        ("req_id", "u32"),
        ("parent", "u32"),
        ("name", "str"),
        ("target", "str"),
        ("uid", "u32"),
        ("gid", "u32"),
    )


class CltomaReadlink(Message):
    MSG_TYPE = 1032
    FIELDS = (("req_id", "u32"), ("inode", "u32"))


class MatoclReadlink(Message):
    # trailing ``meta_version``: consistency token, see MatoclAttrReply
    MSG_TYPE = 1033
    SKEW_TOLERANT_FROM = 3
    FIELDS = (
        ("req_id", "u32"),
        ("status", "u8"),
        ("target", "str"),
        ("meta_version", "u64"),
    )


class CltomaLink(Message):
    MSG_TYPE = 1034
    FIELDS = (
        ("req_id", "u32"),
        ("inode", "u32"),
        ("parent", "u32"),
        ("name", "str"),
        ("uid", "u32"),
        ("gids", "list:u32"),
    )


class CltomaSnapshot(Message):
    MSG_TYPE = 1036
    FIELDS = (
        ("req_id", "u32"),
        ("src_inode", "u32"),
        ("dst_parent", "u32"),
        ("dst_name", "str"),
        ("uid", "u32"),
        ("gids", "list:u32"),
    )


class CltomaSetXattr(Message):
    """Set (value non-empty) or remove (value empty) an xattr."""

    MSG_TYPE = 1038
    FIELDS = (
        ("req_id", "u32"),
        ("inode", "u32"),
        ("name", "str"),
        ("uid", "u32"),
        ("gids", "list:u32"),
        ("value", "bytes"),
    )


class CltomaGetXattr(Message):
    MSG_TYPE = 1040
    FIELDS = (
        ("req_id", "u32"),
        ("inode", "u32"),
        ("name", "str"),
        ("uid", "u32"),
        ("gids", "list:u32"),
    )


class MatoclXattrReply(Message):
    MSG_TYPE = 1041
    FIELDS = (("req_id", "u32"), ("status", "u8"), ("value", "bytes"))


class CltomaListXattr(Message):
    # carries no identity: listxattr(2) needs no access on the inode
    MSG_TYPE = 1042
    FIELDS = (("req_id", "u32"), ("inode", "u32"))


class MatoclListXattr(Message):
    MSG_TYPE = 1043
    FIELDS = (("req_id", "u32"), ("status", "u8"), ("names", "list:str"))


class CltomaSetQuota(Message):
    """Set/remove quota limits (remove when all limits zero and
    ``remove`` set)."""

    MSG_TYPE = 1044
    FIELDS = (
        ("req_id", "u32"),
        ("kind", "str"),  # user | group | dir
        ("owner_id", "u32"),  # uid/gid/directory inode
        ("soft_inodes", "u64"),
        ("hard_inodes", "u64"),
        ("soft_bytes", "u64"),
        ("hard_bytes", "u64"),
        ("remove", "bool"),
        ("uid", "u32"),
    )


class CltomaStatFs(Message):
    """Cluster-wide space totals (statfs(2) backing; ref CLTOMA_FUSE_STATFS
    in src/protocol/MFSCommunication.h)."""

    MSG_TYPE = 1005
    FIELDS = (("req_id", "u32"),)


class MatoclStatFsReply(Message):
    MSG_TYPE = 1007
    FIELDS = (
        ("req_id", "u32"),
        ("status", "u8"),
        ("total_space", "u64"),
        ("avail_space", "u64"),
        ("inodes", "u32"),
    )


class CltomaTapeInfo(Message):
    """Tape-copy state of a file (matotsserv.cc / tape goal support)."""

    MSG_TYPE = 1009
    FIELDS = (("req_id", "u32"), ("inode", "u32"))


class MatoclTapeInfoReply(Message):
    MSG_TYPE = 1015
    FIELDS = (("req_id", "u32"), ("status", "u8"), ("json", "str"))


class CltomaTapeDemote(Message):
    """Demote a file to the tape tier: with a fresh archival copy the
    master frees its chunk data and marks the inode tape-only;
    otherwise it force-queues an archive (even without a $tape goal)
    and replies CHUNK_BUSY so the caller retries after the copy
    lands. Driven by the master's own lifecycle scanner and by the S3
    gateway / admin tooling."""

    MSG_TYPE = 1077
    FIELDS = (
        ("req_id", "u32"),
        ("inode", "u32"),
        ("uid", "u32"),
        ("gids", "list:u32"),
    )


class CltomaTapeRecall(Message):
    """Recall a demoted file from the tape tier: the master streams the
    archived content back through a registered tape server and replies
    once the file is readable again (OK immediately when the inode is
    not demoted). Bounded server-side; callers put it under their own
    deadline too."""

    MSG_TYPE = 1078
    FIELDS = (("req_id", "u32"), ("inode", "u32"))


class CltomaGetQuota(Message):
    MSG_TYPE = 1046
    FIELDS = (("req_id", "u32"), ("uid", "u32"), ("gids", "list:u32"))


class MatoclQuotaReply(Message):
    MSG_TYPE = 1047
    FIELDS = (("req_id", "u32"), ("status", "u8"), ("json", "str"))


class CltomaLockOp(Message):
    """POSIX byte-range lock / flock / test (op: 0=posix 1=flock 2=test)."""

    MSG_TYPE = 1048
    FIELDS = (
        ("req_id", "u32"),
        ("op", "u8"),
        ("inode", "u32"),
        ("token", "u64"),  # per-session owner discriminator (fd/pid)
        ("start", "u64"),
        ("end", "u64"),  # 0 = EOF/whole file
        ("ltype", "u8"),  # 0=unlock 1=shared 2=exclusive
        ("wait", "bool"),
    )


class MatoclLockReply(Message):
    MSG_TYPE = 1049
    FIELDS = (("req_id", "u32"), ("status", "u8"))  # LOCKED = queued/denied


class MatoclLockGranted(Message):
    """Push: a previously queued lock was granted."""

    MSG_TYPE = 1050
    FIELDS = (("inode", "u32"), ("token", "u64"))


class MatoclCacheInvalidate(Message):
    """Push: another session mutated this file — drop cached blocks.

    ``chunk_index == 0xFFFFFFFF`` means the whole inode. Analog of the
    reference master's data-cache invalidation to mounts (reference:
    src/master/matoclserv.cc client service; mounts revalidate via the
    fs_readchunk version, src/mount/mastercomm.h:67)."""

    MSG_TYPE = 1067
    SKEW_TOLERANT_FROM = 2
    FIELDS = (
        ("inode", "u32"),
        ("chunk_index", "u32"),
        # the mutation's changelog position (trailing, skew-tolerant):
        # raises the client's monotonic-reads floor so a post-push read
        # routed to a still-lagging replica is detected as stale and
        # retried through the primary
        ("meta_version", "u64"),
    )


class CltomaOpen(Message):
    """Register an open file handle: while any session holds one, the
    file survives losing its last name ("reserved"/sustained files,
    reference: src/master/filesystem_node_types.h trash & reserved
    namespaces; sessions carry open files in sessions.mfs).

    ``handle`` is a client-chosen unique id: the client's master RPC
    layer transparently retries over a reconnect, and acquire is not
    idempotent — the master dedupes on (session, handle) so a
    lost-reply retry can't double-count the ref."""

    MSG_TYPE = 1068
    FIELDS = (("req_id", "u32"), ("inode", "u32"), ("handle", "u64"))


class CltomaRelease(Message):
    """Drop one open handle; the last release of a sustained file frees
    its data. ``handle`` matches the open — the master only releases a
    handle it has registered, so a retried release can't double-drop."""

    MSG_TYPE = 1069
    FIELDS = (("req_id", "u32"), ("inode", "u32"), ("handle", "u64"))


class CltomaSetAcl(Message):
    """Set/clear POSIX ACLs; json = {"access": {...}|null,
    "default": {...}|null} (see master/acl.py dict shape). Only the
    file's owner or root may change ACLs."""

    MSG_TYPE = 1056
    FIELDS = (
        ("req_id", "u32"),
        ("inode", "u32"),
        ("json", "str"),
        ("uid", "u32"),
        ("gids", "list:u32"),
    )


class CltomaGetAcl(Message):
    MSG_TYPE = 1058
    FIELDS = (("req_id", "u32"), ("inode", "u32"))


class MatoclAclReply(Message):
    MSG_TYPE = 1059
    FIELDS = (("req_id", "u32"), ("status", "u8"), ("json", "str"))


class CltomaSetRichAcl(Message):
    """Set/clear an NFSv4-style RichACL; json = {"aces": [...]} (see
    master/richacl.py dict shape) or null to clear. Owner/root only.
    A RichACL takes precedence over POSIX ACLs on the inode."""

    MSG_TYPE = 1064
    FIELDS = (
        ("req_id", "u32"),
        ("inode", "u32"),
        ("json", "str"),
        ("uid", "u32"),
        ("gids", "list:u32"),
    )


class CltomaGetRichAcl(Message):
    MSG_TYPE = 1065
    FIELDS = (("req_id", "u32"), ("inode", "u32"))


class CltomaGoodbye(Message):
    """Clean session end: locks release immediately. An ABRUPT
    disconnect (no goodbye) keeps held locks for the master's grace
    window so a reconnecting client reclaims them."""

    MSG_TYPE = 1066
    FIELDS = (("req_id", "u32"),)


class CltomaSessionStats(Message):
    """Periodic per-session workload summary push (gateway -> master).

    Protocol gateways (NFS/S3) serve MANY protocol clients through ONE
    cluster session; the master sees that session's RPC stream but not
    the protocol-level op mix behind it. Every few seconds the gateway
    pushes its local :class:`~lizardfs_tpu.runtime.accounting.SessionOps`
    top-K summary (plus role/endpoint info) as ``stats_json`` so the
    master's cluster-wide ``top`` rollup names what each front door is
    actually doing — the cluster analog of the per-mount ``.stats``
    magic file. Fire-and-forget semantics at the caller (a missed push
    costs one refresh interval); answered with MatoclStatusReply. Old
    masters never see the verb (new type id); the trailing ``trace_id``
    follows the tracing convention."""

    MSG_TYPE = 1079
    SKEW_TOLERANT_FROM = 2
    FIELDS = (
        ("req_id", "u32"),
        ("stats_json", "str"),
        ("trace_id", "u64"),
    )


class CltomaAccess(Message):
    """Permission probe: can (uid, gid) access inode with mask r4/w2/x1?
    Evaluated against the inode's RichACL when one is set, else mode
    bits + POSIX ACLs (access(2) analog)."""

    MSG_TYPE = 1060
    FIELDS = (
        ("req_id", "u32"),
        ("inode", "u32"),
        ("uid", "u32"),
        ("gids", "list:u32"),
        ("mask", "u8"),
    )


class CltomaIoLimitRequest(Message):
    """Request/renew a bandwidth allocation (globaliolimits analog:
    the master divides the cluster budget among limited sessions).

    ``group`` is the requester's cgroup limit group (reference:
    src/mount/io_limit_group.cc classification); "" means
    unclassified. With per-group limits configured, the master matches
    the group against its configured prefixes and divides that group's
    budget among the sessions renewing under it. ``probe=1`` asks only
    whether limits are configured (``limits_active``) WITHOUT joining
    the allocation table — connect-time probes must not dilute real
    consumers' shares for a renew period."""

    # ``group``/``probe`` were added after v0 — a version-skewed peer
    # that omits them means "" / no-probe; ``req_id`` stays required
    MSG_TYPE = 1062
    SKEW_TOLERANT_FROM = 1
    FIELDS = (("req_id", "u32"), ("group", "str"), ("probe", "u8"))


class MatoclIoLimitReply(Message):
    """``subsystem`` tells clients which cgroup hierarchy to classify
    callers with ("" = v2 unified / classification off) — served from
    master config so mounts need no local limits file.

    Only ``subsystem``/``limits_active`` are skew-optional (additive
    hints an older master omits, meaning "no classification, no limits
    configured" — exactly their zero values); a reply cut before the
    verdict-bearing v0 fields (status, bytes_per_sec, renew_ms) is
    corruption and still fails the parse."""

    MSG_TYPE = 1063
    SKEW_TOLERANT_FROM = 4
    FIELDS = (
        ("req_id", "u32"),
        ("status", "u8"),
        ("bytes_per_sec", "u64"),  # 0 = unlimited (for THIS group)
        ("renew_ms", "u32"),
        ("subsystem", "str"),
        # 1 if ANY limit is configured cluster-wide: consumers with
        # unthrottled fast paths (FUSE native read pool) must route
        # through the throttled path whenever this is set — their own
        # group being unlimited says nothing about their callers'
        ("limits_active", "u8"),
    )


class CltomaTrashList(Message):
    MSG_TYPE = 1052
    FIELDS = (("req_id", "u32"), ("uid", "u32"))


class MatoclTrashList(Message):
    MSG_TYPE = 1053
    FIELDS = (("req_id", "u32"), ("status", "u8"), ("json", "str"))


class CltomaUndelete(Message):
    MSG_TYPE = 1054
    FIELDS = (
        ("req_id", "u32"),
        ("inode", "u32"),
        ("uid", "u32"),
    )


class CltomaFileRepair(Message):
    """Repair a file with unrecoverable chunks (src/tools/file_repair.cc
    analog): version-fix chunks whose only surviving parts are at a
    stale version, zero-fill chunks with no parts at all, and route
    still-repairable (readable) chunks through the RebuildEngine rather
    than zeroing them."""

    MSG_TYPE = 1072
    FIELDS = (
        ("req_id", "u32"),
        ("inode", "u32"),
        ("uid", "u32"),
        ("gids", "list:u32"),
    )


class MatoclFileRepair(Message):
    """Repair verdict: json carries {"repaired_versions", "zeroed",
    "queued_rebuild", "ok_chunks"} counts."""

    MSG_TYPE = 1073
    FIELDS = (("req_id", "u32"), ("status", "u8"), ("json", "str"))


class CltomaAppendChunks(Message):
    """O(1) chunk-level concatenation (src/tools/append_file.cc
    analog): pad ``inode_dst`` to a chunk boundary and share
    ``inode_src``'s chunks onto its tail via the snapshot refcount
    machinery (COW on later writes)."""

    MSG_TYPE = 1074
    FIELDS = (
        ("req_id", "u32"),
        ("inode_dst", "u32"),
        ("inode_src", "u32"),
        ("uid", "u32"),
        ("gids", "list:u32"),
    )


# --------------------------------------------------------------------------
# chunkserver <-> master
# --------------------------------------------------------------------------


class CstomaRegister(Message):
    """``mirror`` (trailing, skew-tolerant): 1 = a PASSIVE location
    report to a shadow master (the shadow records parts so replica
    locates have locations; no commands ever flow on the link). The
    active master refuses mirror registrations (a command-less link
    must never be mistaken for a command link) and shadows refuse
    non-mirror ones (a chunkserver's main link must keep cycling to
    the active). Old peers send 0 = normal registration.

    ``epoch`` (trailing, skew-tolerant): the highest cluster fencing
    epoch the chunkserver has observed. An active master with a LOWER
    epoch refuses the registration and steps down — the chunkserver is
    telling it a later election happened. 0 = pre-HA peer."""

    MSG_TYPE = 1100
    SKEW_TOLERANT_FROM = 7
    FIELDS = (
        ("req_id", "u32"),
        ("addr", "msg:Addr"),
        ("label", "str"),
        ("chunks", "list:msg:ChunkPartInfo"),
        ("total_space", "u64"),
        ("used_space", "u64"),
        # native C++ data-plane listener port (0 = none; data ops then
        # go to the control port's asyncio server)
        ("data_port", "u16"),
        ("mirror", "u8"),
        ("epoch", "u64"),
    )


class MatocsRegisterReply(Message):
    """Registration / heartbeat ack to a chunkserver.

    ``qos_json`` (trailing, skew-tolerant): the master's current QoS
    data-plane config for this chunkserver — session->tenant map,
    tenant weights, in-flight byte budget, optional per-session native
    pacing — refreshed on every heartbeat ack so weights/limits changed
    live (admin `qos` / SIGHUP) propagate within one heartbeat. Old
    peers send/receive "" and stay unthrottled (fail-open: QoS degrades
    to the pre-QoS behavior, never to a lockout).

    ``epoch`` (trailing, skew-tolerant): the replying master's cluster
    fencing epoch — stamped on registration AND heartbeat acks (mirror
    acks included), so a chunkserver learns of a promotion within one
    heartbeat and fences its stale command link. Old masters send 0."""

    MSG_TYPE = 1101
    SKEW_TOLERANT_FROM = 3
    FIELDS = (
        ("req_id", "u32"), ("status", "u8"), ("cs_id", "u32"),
        ("qos_json", "str"), ("epoch", "u64"),
    )


class CstomaHeartbeat(Message):
    """``health_json`` (trailing, skew-tolerant): the chunkserver's
    health snapshot (runtime/slo.py health_from — SLO burn, stall
    hits, span drops, disk errors) folded into the heartbeat so the
    master's cluster `health` rollup needs no extra link; an old peer
    sends/receives "" and reads as health-unknown.

    ``heat_json`` (trailing, skew-tolerant): the chunkserver's top-K
    per-chunk heat fold — ``{"chunks": [[chunk_id, ops, bytes], ...]}``
    accumulated since the last heartbeat — feeding the master's heat
    tracker (master/heat.py). "" when LZ_HEAT is off (heartbeats stay
    byte-identical to the pre-heat wire) or from an old peer, which
    reads as no data-plane heat observed.

    ``epoch`` (trailing, skew-tolerant): the chunkserver's highest
    observed fencing epoch, echoed back at the master on every beat —
    a deposed ex-primary hears about the election it lost from its own
    chunkservers and steps down. 0 = pre-HA peer."""

    MSG_TYPE = 1102
    SKEW_TOLERANT_FROM = 4
    FIELDS = (
        ("req_id", "u32"),
        ("cs_id", "u32"),
        ("total_space", "u64"),
        ("used_space", "u64"),
        ("health_json", "str"),
        ("heat_json", "str"),
        ("epoch", "u64"),
    )


class CstomaChunkDamaged(Message):
    MSG_TYPE = 1104
    FIELDS = (("cs_id", "u32"), ("chunks", "list:msg:ChunkPartInfo"))


class CstomaChunkLost(Message):
    MSG_TYPE = 1105
    FIELDS = (("cs_id", "u32"), ("chunks", "list:msg:ChunkPartInfo"))


class CstomaChunkNew(Message):
    """Report parts gained (e.g. after replication)."""

    MSG_TYPE = 1106
    FIELDS = (("cs_id", "u32"), ("chunks", "list:msg:ChunkPartInfo"))


class MatocsCreateChunk(Message):
    MSG_TYPE = 1110
    FIELDS = (
        ("req_id", "u32"),
        ("chunk_id", "u64"),
        ("version", "u32"),
        ("part_id", "u32"),
    )


class MatocsDeleteChunk(Message):
    MSG_TYPE = 1112
    FIELDS = (
        ("req_id", "u32"),
        ("chunk_id", "u64"),
        ("version", "u32"),
        ("part_id", "u32"),
    )


class MatocsSetVersion(Message):
    MSG_TYPE = 1114
    FIELDS = (
        ("req_id", "u32"),
        ("chunk_id", "u64"),
        ("old_version", "u32"),
        ("new_version", "u32"),
        ("part_id", "u32"),
    )


class MatocsReplicate(Message):
    """Recover/copy a part from source parts (EC recovery engine).

    ``trace_id`` (trailing, skew-tolerant): the RebuildEngine's
    per-rebuild trace — the executing chunkserver records its
    replication span under the same id so `trace-dump` renders the
    master-scheduler + chunkserver-executor timeline as one rebuild;
    old peers decode/serve trace 0 = untraced."""

    MSG_TYPE = 1116
    SKEW_TOLERANT_FROM = 5
    FIELDS = (
        ("req_id", "u32"),
        ("chunk_id", "u64"),
        ("version", "u32"),
        ("part_id", "u32"),
        ("sources", "list:msg:PartLocation"),
        ("trace_id", "u64"),
    )


class MatocsTruncateChunk(Message):
    MSG_TYPE = 1118
    FIELDS = (
        ("req_id", "u32"),
        ("chunk_id", "u64"),
        ("old_version", "u32"),
        ("new_version", "u32"),
        ("part_id", "u32"),
        ("chunk_length", "u32"),  # length of the whole chunk, not the part
    )


class MatocsDuplicateChunk(Message):
    """Duplicate a part locally under a new chunk id (snapshot COW)."""

    MSG_TYPE = 1122
    FIELDS = (
        ("req_id", "u32"),
        ("chunk_id", "u64"),  # new chunk id
        ("version", "u32"),  # new version
        ("part_id", "u32"),
        ("src_chunk_id", "u64"),
        ("src_version", "u32"),
    )


class CstomaChunkOpStatus(Message):
    """Ack for any master->CS chunk command."""

    MSG_TYPE = 1120
    FIELDS = (
        ("req_id", "u32"),
        ("status", "u8"),
        ("chunk_id", "u64"),
        ("part_id", "u32"),
    )


# --------------------------------------------------------------------------
# data plane: client/peer <-> chunkserver
# --------------------------------------------------------------------------


class CltocsRead(Message):
    # trailing ``trace_id`` (optional, skew-tolerant): the native C
    # data plane reads it as an optional trailing u64 past the fixed
    # 28-byte body (native/wire.h trace contract); peers predating it
    # decode/serve as trace 0.
    # trailing ``session_id`` (optional, skew-tolerant): the master-
    # issued session of the originating client, feeding the
    # chunkserver's per-session op accounting (runtime/accounting.py);
    # the native server reads fixed offsets and ignores the longer
    # body, old peers send/serve 0 = unattributed
    MSG_TYPE = 1200
    SKEW_TOLERANT_FROM = 6
    FIELDS = (
        ("req_id", "u32"),
        ("chunk_id", "u64"),
        ("version", "u32"),
        ("part_id", "u32"),
        ("offset", "u32"),
        ("size", "u32"),
        ("trace_id", "u64"),
        ("session_id", "u64"),
    )


class CltocsPrefetch(Message):
    """Hint: the client will read this range soon — pull it into the
    page cache (LIZ_CLTOCS_PREFETCH analog). No reply."""

    MSG_TYPE = 1205
    FIELDS = (
        ("req_id", "u32"),
        ("chunk_id", "u64"),
        ("version", "u32"),
        ("part_id", "u32"),
        ("offset", "u32"),
        ("size", "u32"),
    )


class CltocsReadBulk(Message):
    """Bulk read: the whole range comes back in ONE reply frame with a
    per-block CRC table, so the server can sendfile() the data region
    and the receiver can land bytes directly in the destination buffer.
    ``offset`` must be 64 KiB-block-aligned."""

    # trailing ``trace_id`` + ``session_id``: see CltocsRead
    MSG_TYPE = 1206
    SKEW_TOLERANT_FROM = 6
    FIELDS = (
        ("req_id", "u32"),
        ("chunk_id", "u64"),
        ("version", "u32"),
        ("part_id", "u32"),
        ("offset", "u32"),
        ("size", "u32"),
        ("trace_id", "u64"),
        ("session_id", "u64"),
    )


class CstoclReadBulkData(Message):
    """Reply to CltocsReadBulk: piece CRCs (one per touched block; the
    trailing partial piece's CRC covers the bytes as transmitted) + the
    raw range. Integrity is verified by the RECEIVER — the sender vouches
    only for its stored CRC table (the periodic chunk tester still
    verifies server-side)."""

    MSG_TYPE = 1207
    FIELDS = (
        ("req_id", "u32"),
        ("chunk_id", "u64"),
        ("status", "u8"),
        ("offset", "u32"),
        ("crcs", "list:u32"),
        ("data", "bytes"),
    )


class CstoclReadData(Message):
    """One 64 KiB-aligned piece with its CRC (cstocl READ_DATA)."""

    MSG_TYPE = 1201
    FIELDS = (
        ("req_id", "u32"),
        ("chunk_id", "u64"),
        ("offset", "u32"),
        ("crc", "u32"),
        ("data", "bytes"),
    )


class CstoclReadStatus(Message):
    MSG_TYPE = 1202
    FIELDS = (("req_id", "u32"), ("chunk_id", "u64"), ("status", "u8"))


class CltocsWriteInit(Message):
    """Open a write chain: this CS stores the part and forwards to the
    rest of the chain (cltocs WRITE_INIT, network_worker_thread.cc:574)."""

    # trailing ``trace_id``: carries the request trace into the data
    # plane for the whole write session (both the asyncio server and
    # serve_native.cpp read it; peers predating it serve as trace 0).
    # trailing ``session_id``: attributes the whole write session to
    # its originating client session (per-session op accounting);
    # relayed down the chain, 0 = unattributed legacy peer
    MSG_TYPE = 1210
    SKEW_TOLERANT_FROM = 6
    FIELDS = (
        ("req_id", "u32"),
        ("chunk_id", "u64"),
        ("version", "u32"),
        ("part_id", "u32"),
        ("chain", "list:msg:PartLocation"),  # remaining chain after this CS
        ("create", "bool"),  # create part if absent (first write)
        ("trace_id", "u64"),
        ("session_id", "u64"),
    )


class CltocsWriteData(Message):
    MSG_TYPE = 1211
    FIELDS = (
        ("req_id", "u32"),
        ("chunk_id", "u64"),
        ("write_id", "u32"),
        ("block", "u32"),  # block index within the part
        ("offset", "u32"),  # offset within the block
        ("crc", "u32"),  # CRC of this piece
        ("data", "bytes"),
    )


class CltocsWriteBulk(Message):
    """Bulk write: one frame carries a block-aligned range with one CRC
    per touched 64 KiB piece; ONE CstoclWriteStatus acks the whole range
    (vs one ack per piece). Chain forwarding relays the frame verbatim."""

    MSG_TYPE = 1214
    FIELDS = (
        ("req_id", "u32"),
        ("chunk_id", "u64"),
        ("write_id", "u32"),
        ("part_offset", "u32"),  # must be 64 KiB-aligned
        ("crcs", "list:u32"),
        ("data", "bytes"),
    )


class CltocsWriteBulkPart(Message):
    """Part-addressed bulk write: the 1214 layout plus the target
    ``part_id``, so several parts of one chunk can multiplex a single
    connection (the vectored scatter path shares one connection per
    chunkserver; write sessions demux on (chunk_id, part_id))."""

    MSG_TYPE = 1215
    FIELDS = (
        ("req_id", "u32"),
        ("chunk_id", "u64"),
        ("write_id", "u32"),
        ("part_id", "u32"),
        ("part_offset", "u32"),  # must be 64 KiB-aligned
        ("crcs", "list:u32"),
        ("data", "bytes"),
    )


class CltocsShmInit(Message):
    """Negotiate a same-host shared-memory part ring on this data-plane
    connection: the client created a memfd segment of ``seg_size`` bytes
    and attaches its fd as SCM_RIGHTS ancillary data on the sendmsg that
    carries this frame (abstract-UDS connections only, riding the
    SO_PEERCRED gate in native/wire.h). ``pid``/``mem_fd`` name the same
    segment as ``/proc/<pid>/fd/<mem_fd>`` so a receiver that cannot
    take the ancillary fd (the asyncio fallback chunkserver reads
    through StreamReader, which drops cmsgs) can still map it — the
    /proc open enforces the same same-uid gate. Acked with a
    CstoclWriteStatus (chunk_id/write_id 0); any non-OK status leaves
    the connection on the socket-copy path."""

    MSG_TYPE = 1216
    FIELDS = (
        ("req_id", "u32"),
        ("pid", "u32"),
        ("mem_fd", "u32"),
        ("seg_size", "u64"),
    )


class CltocsShmWritePart(Message):
    """Shared-memory part descriptor: the payload already sits in the
    connection's negotiated ring segment at ``ring_off`` — this frame
    carries only addressing + per-64KiB-piece CRCs, so the send phase
    moves tens of bytes instead of megabytes. Demuxed on
    (chunk_id, part_id) like CltocsWriteBulkPart and acked by the same
    CstoclWriteStatus, FIFO per connection (the windowed client's ack
    collector handles both frame kinds identically)."""

    MSG_TYPE = 1217
    FIELDS = (
        ("req_id", "u32"),
        ("chunk_id", "u64"),
        ("write_id", "u32"),
        ("part_id", "u32"),
        ("part_offset", "u32"),  # must be 64 KiB-aligned
        ("ring_off", "u64"),  # payload offset inside the ring segment
        ("length", "u32"),
        ("crcs", "list:u32"),
    )


class CstoclWriteStatus(Message):
    """Per-write ack, flows back up the chain."""

    MSG_TYPE = 1212
    FIELDS = (
        ("req_id", "u32"),
        ("chunk_id", "u64"),
        ("write_id", "u32"),
        ("status", "u8"),
    )


class CltocsWriteEnd(Message):
    MSG_TYPE = 1213
    FIELDS = (("req_id", "u32"), ("chunk_id", "u64"))


# --------------------------------------------------------------------------
# metalogger / shadow <-> master
# --------------------------------------------------------------------------


class MltomaRegister(Message):
    # trailing ``epoch``: the follower's highest observed fencing epoch
    # (HA failover). An active master with a lower epoch refuses the
    # follow link and steps down — it was superseded. 0 = pre-HA peer.
    MSG_TYPE = 1300
    SKEW_TOLERANT_FROM = 2
    FIELDS = (("req_id", "u32"), ("version_known", "u64"),
              ("epoch", "u64"))


class MatomlRegisterReply(Message):
    # trailing ``epoch``: the serving master's fencing epoch. A
    # follower that already knows a HIGHER epoch treats this "active"
    # as a zombie and keeps cycling its address list. 0 = pre-HA peer.
    MSG_TYPE = 1304
    SKEW_TOLERANT_FROM = 3
    FIELDS = (("req_id", "u32"), ("status", "u8"), ("version", "u64"),
              ("epoch", "u64"))


class MatomlChangelogLine(Message):
    """Streamed changelog entry (matoml broadcast_logstring analog)."""

    MSG_TYPE = 1301
    FIELDS = (("version", "u64"), ("line", "str"))


class MltomaDownloadImage(Message):
    MSG_TYPE = 1302
    FIELDS = (("req_id", "u32"),)


class MltomaAck(Message):
    """Shadow -> active: periodic applied-position report. The active
    folds per-shadow replication lag (its own changelog position minus
    the acked ``version``) into ``lizardfs-admin health`` and the
    ``shadow_lag`` gauge. ``serving`` says whether the shadow is
    serving replica reads (LZ_SHADOW_READS)."""

    MSG_TYPE = 1305
    FIELDS = (("version", "u64"), ("serving", "u8"))


class MatomlImage(Message):
    MSG_TYPE = 1303
    FIELDS = (("req_id", "u32"), ("status", "u8"), ("version", "u64"), ("image", "bytes"))


# --------------------------------------------------------------------------
# admin
# --------------------------------------------------------------------------


class AdminInfo(Message):
    MSG_TYPE = 1400
    FIELDS = (("req_id", "u32"),)


class AdminInfoReply(Message):
    MSG_TYPE = 1401
    FIELDS = (("req_id", "u32"), ("status", "u8"), ("json", "str"))


class AdminCommand(Message):
    """Generic admin command with JSON payload (list-chunkservers,
    chunks-health, save-metadata, promote-shadow, ...)."""

    MSG_TYPE = 1402
    FIELDS = (("req_id", "u32"), ("command", "str"), ("json", "str"))


class AdminReply(Message):
    MSG_TYPE = 1403
    FIELDS = (("req_id", "u32"), ("status", "u8"), ("json", "str"))


# --------------------------------------------------------------------------
# tape server link (matotsserv.cc analog): tape servers register with
# the master and archive whole files for goals carrying a $tape slice


class TstomaRegister(Message):
    """``session_id`` (trailing, skew-tolerant; 0 = unknown) names the
    tape server's own cluster-client session, so the master can scope
    the demoted-file write guard to exactly the recalling session
    instead of standing it down for everyone mid-recall."""

    MSG_TYPE = 1500
    SKEW_TOLERANT_FROM = 3
    FIELDS = (
        ("req_id", "u32"),
        ("label", "str"),
        ("capacity", "u64"),
        ("session_id", "u32"),
    )


class MatotsRegisterReply(Message):
    MSG_TYPE = 1501
    FIELDS = (("req_id", "u32"), ("status", "u8"), ("ts_id", "u32"))


class MatotsPutFile(Message):
    """Master -> tape server: archive this file's current content.
    ``length``/``mtime`` stamp the content version; the ack echoes them
    so the master can detect a concurrent modification."""

    MSG_TYPE = 1502
    FIELDS = (
        ("req_id", "u32"),
        ("inode", "u32"),
        ("path", "str"),
        ("length", "u64"),
        ("mtime", "u32"),
    )


class TstomaPutDone(Message):
    MSG_TYPE = 1503
    FIELDS = (
        ("req_id", "u32"),
        ("inode", "u32"),
        ("status", "u8"),
        ("length", "u64"),
        ("mtime", "u32"),
    )


class MatotsDeleteFile(Message):
    """Master -> tape server: reclaim archives of ``inode``. A zero
    (keep_mtime, keep_length) deletes every version; otherwise the
    matching archive is kept and stale versions are removed."""

    MSG_TYPE = 1504
    FIELDS = (
        ("req_id", "u32"),
        ("inode", "u32"),
        ("keep_mtime", "u32"),
        ("keep_length", "u64"),
    )


class MatotsRecallFile(Message):
    """Master -> tape server: write the archived content version
    (``length``/``mtime`` pick the exact archive file) back into the
    live file through the tape server's cluster client session. Sent
    only while the master has the inode in recall-inflight state, so
    the write guard on demoted files stands down for it."""

    MSG_TYPE = 1505
    FIELDS = (
        ("req_id", "u32"),
        ("inode", "u32"),
        ("path", "str"),
        ("length", "u64"),
        ("mtime", "u32"),
    )


class TstomaRecallDone(Message):
    """Tape server -> master: recall finished; ``length``/``mtime``
    echo the archive stamp actually restored (the master refuses a
    stamp it did not ask for)."""

    MSG_TYPE = 1506
    FIELDS = (
        ("req_id", "u32"),
        ("inode", "u32"),
        ("status", "u8"),
        ("length", "u64"),
        ("mtime", "u32"),
    )
