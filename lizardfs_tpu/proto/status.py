"""Status codes shared across the protocol (MFS-style, one byte).

Semantic mirror of the reference's LIZARDFS_STATUS_* / LIZARDFS_ERROR_*
space (src/protocol/MFSCommunication.h): 0 = OK, small ints = errors.
"""

OK = 0
EPERM = 1
ENOENT = 2
EACCES = 3
EEXIST = 4
EINVAL = 5
ENOTDIR = 6
EISDIR = 7
ENOSPC = 8
EIO = 9
ENOTEMPTY = 10
CHUNK_LOST = 11
OUT_OF_MEMORY = 12
INDEX_TOO_BIG = 13
LOCKED = 14
NO_CHUNK_SERVERS = 15
NO_CHUNK = 16
CHUNK_BUSY = 17
REGISTER_FIRST = 18
WRONG_VERSION = 19
CRC_ERROR = 20
DISCONNECTED = 21
TIMEOUT = 22
ENOATTR = 23
QUOTA_EXCEEDED = 24
NAME_TOO_LONG = 25
EROFS = 26
ENODATA = 27
BAD_SESSION = 28
NOT_POSSIBLE = 29
# data lives only on the tape tier (lifecycle-demoted inode): reads and
# writes must recall it first (CltomaTapeRecall); transient by design —
# a client that waits out the recall and retries succeeds
TAPE_RECALL = 30
# fair-share admission shed the op for THIS tenant (multi-tenant QoS):
# transient by design — clients back off (the reply's trailing
# retry_after_ms is the server's hint) and retry through the unified
# RetryPolicy; S3 maps it to 503 SlowDown, NFS to JUKEBOX delay
BUSY = 31

_NAMES = {v: k for k, v in list(globals().items()) if isinstance(v, int)}


def name(code: int) -> str:
    return _NAMES.get(code, f"status_{code}")


class StatusError(Exception):
    """Raised by clients when an RPC returns a non-OK status.

    ``retry_after_ms``: the server's backoff hint on BUSY sheds (0 =
    none given); carried so the client's busy-retry loop can honor it
    without re-parsing the reply."""

    def __init__(self, code: int, context: str = "",
                 retry_after_ms: int = 0):
        self.code = code
        self.retry_after_ms = retry_after_ms
        super().__init__(f"{name(code)}{(': ' + context) if context else ''}")
