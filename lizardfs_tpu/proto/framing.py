"""Packet framing over asyncio streams.

Same shape as the reference's framing (reference: src/protocol/packet.h:
29-57): an 8-byte header — type:u32, length:u32 big-endian — followed by
``length`` payload bytes, with a protocol version byte leading the
payload (the LIZ packet version field).
"""

from __future__ import annotations

import asyncio
import struct

from lizardfs_tpu.proto.codec import Message, message_class_for

HEADER = struct.Struct(">II")
PROTO_VERSION = 1
MAX_PACKET_SIZE = 128 * 1024 * 1024  # sanity bound


class ProtocolError(Exception):
    pass


def encode(msg: Message) -> bytes:
    if msg.MSG_TYPE is None:
        raise ProtocolError(f"{type(msg).__name__} is not a top-level message")
    body = msg.pack_body()
    return HEADER.pack(msg.MSG_TYPE, len(body) + 1) + bytes([PROTO_VERSION]) + body


def decode(msg_type: int, payload: bytes) -> Message:
    if not payload:
        raise ProtocolError("empty payload")
    if payload[0] != PROTO_VERSION:
        raise ProtocolError(f"unsupported protocol version {payload[0]}")
    return message_class_for(msg_type).parse(payload[1:])


async def read_message(reader: asyncio.StreamReader) -> Message:
    header = await reader.readexactly(HEADER.size)
    msg_type, length = HEADER.unpack(header)
    if length > MAX_PACKET_SIZE:
        raise ProtocolError(f"packet too large: {length}")
    payload = await reader.readexactly(length)
    return decode(msg_type, payload)


def write_message(writer: asyncio.StreamWriter, msg: Message) -> None:
    writer.write(encode(msg))


async def send_message(writer: asyncio.StreamWriter, msg: Message) -> None:
    write_message(writer, msg)
    await writer.drain()
