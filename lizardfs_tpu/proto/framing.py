"""Packet framing over asyncio streams.

Same shape as the reference's framing (reference: src/protocol/packet.h:
29-57): an 8-byte header — type:u32, length:u32 big-endian — followed by
``length`` payload bytes, with a protocol version byte leading the
payload (the LIZ packet version field).
"""

from __future__ import annotations

import asyncio
import struct

from lizardfs_tpu.proto.codec import Message, message_class_for
from lizardfs_tpu.runtime import faults as _faults
from lizardfs_tpu.runtime.retry import bounded_wait

HEADER = struct.Struct(">II")
PROTO_VERSION = 1
MAX_PACKET_SIZE = 128 * 1024 * 1024  # sanity bound


class ProtocolError(Exception):
    pass


def encode(msg: Message) -> bytes:
    if msg.MSG_TYPE is None:
        raise ProtocolError(f"{type(msg).__name__} is not a top-level message")
    body = msg.pack_body()
    return HEADER.pack(msg.MSG_TYPE, len(body) + 1) + bytes([PROTO_VERSION]) + body


def decode(msg_type: int, payload: bytes) -> Message:
    if not payload:
        raise ProtocolError("empty payload")
    if payload[0] != PROTO_VERSION:
        raise ProtocolError(f"unsupported protocol version {payload[0]}")
    return message_class_for(msg_type).parse(payload[1:])


def _msg_name(msg_type: int) -> str:
    try:
        return message_class_for(msg_type).__name__
    except KeyError:
        return str(msg_type)


def _peer_of(writer: asyncio.StreamWriter) -> str:
    peer = writer.get_extra_info("peername")
    if isinstance(peer, tuple) and len(peer) >= 2:
        return f"{peer[0]}:{peer[1]}"
    return str(peer) if peer else ""


async def read_message(reader: asyncio.StreamReader) -> Message:
    # bounded_wait with no cap = ambient-deadline-only: a client op
    # under a RetryPolicy budget cannot park past it on a wedged peer,
    # while a server connection loop (no ambient deadline) still parks
    # on the next request frame by design — liveness there is owned by
    # heartbeats/TCP, not a per-frame timer
    header = await bounded_wait(reader.readexactly(HEADER.size))
    msg_type, length = HEADER.unpack(header)
    if length > MAX_PACKET_SIZE:
        raise ProtocolError(f"packet too large: {length}")
    payload = await bounded_wait(reader.readexactly(length))
    if _faults.ACTIVE:
        # fault choke point (runtime/faults.py): delay/drop/flip the
        # received frame. One module-attribute check when injection is
        # off — the clean path is byte-identical.
        payload = await _faults.frame_point(
            "frame_recv", _msg_name(msg_type), payload
        )
    return decode(msg_type, payload)


def write_message(writer: asyncio.StreamWriter, msg: Message) -> None:
    writer.write(encode(msg))


async def send_message(writer: asyncio.StreamWriter, msg: Message) -> None:
    if _faults.ACTIVE:
        # fault choke point: delay/drop/flip/short-write the outbound
        # frame (runtime/faults.py). The sync write_message fast path
        # (shadow acks) stays unhooked by design.
        data = await _faults.frame_point(
            "frame_send", type(msg).__name__, encode(msg),
            peer=_peer_of(writer), writer=writer,
        )
        writer.write(data)
        await bounded_wait(writer.drain())
        return
    write_message(writer, msg)
    # ambient-deadline-bounded like the reads: backpressure from a
    # dead-slow peer charges the caller's budget, not forever
    await bounded_wait(writer.drain())
