"""Declarative binary message codec.

The reference generates typed big-endian serializers for every message
with macro magic (reference: src/common/serialization.h,
serialization_macros.h:82-140). Here the same idea is a dataclass-like
metaclass: a message declares ``FIELDS`` as (name, type) pairs and gets
``pack``/``unpack`` plus equality for free.

Field type language:
  u8 u16 u32 u64 i32 i64      big-endian scalars
  bool                        one byte
  bytes                       u32 length-prefixed byte string
  str                         u32 length-prefixed utf-8 string
  list:<type>                 u32 count-prefixed homogeneous list
  msg:<ClassName>             nested message (class must be registered)

Messages are versioned at the framing layer (see framing.py), matching
the reference's LIZ packet version field (src/protocol/packet.h:29-43).
"""

from __future__ import annotations

import struct
from typing import Any

_SCALARS = {
    "u8": ">B",
    "u16": ">H",
    "u32": ">I",
    "u64": ">Q",
    "i32": ">i",
    "i64": ">q",
    "bool": ">?",
}

_MESSAGE_CLASSES: dict[str, type] = {}
_TYPE_REGISTRY: dict[int, type] = {}


def _pack_value(ftype: str, value: Any, out: bytearray) -> None:
    if ftype in _SCALARS:
        out += struct.pack(_SCALARS[ftype], value)
    elif ftype == "bytes":
        b = bytes(value)
        out += struct.pack(">I", len(b))
        out += b
    elif ftype == "str":
        b = str(value).encode("utf-8")
        out += struct.pack(">I", len(b))
        out += b
    elif ftype.startswith("list:"):
        inner = ftype[5:]
        out += struct.pack(">I", len(value))
        for item in value:
            _pack_value(inner, item, out)
    elif ftype.startswith("msg:"):
        cls = _MESSAGE_CLASSES[ftype[4:]]
        out += value.pack_body()
    else:
        raise TypeError(f"unknown field type {ftype!r}")


def _default_value(ftype: str) -> Any:
    """Zero value of a field type — what a peer that predates the field
    would have meant. Used to default-fill trailing fields missing from
    a version-skewed sender's encoding (see Message.unpack_body)."""
    if ftype in _SCALARS:
        return False if ftype == "bool" else 0
    if ftype == "bytes":
        return b""
    if ftype == "str":
        return ""
    if ftype.startswith("list:"):
        return []
    if ftype.startswith("msg:"):
        cls = _MESSAGE_CLASSES[ftype[4:]]
        return cls(**{n: _default_value(t) for n, t in cls.FIELDS})
    raise TypeError(f"unknown field type {ftype!r}")


def _unpack_value(ftype: str, buf: memoryview, off: int) -> tuple[Any, int]:
    if ftype in _SCALARS:
        fmt = _SCALARS[ftype]
        size = struct.calcsize(fmt)
        return struct.unpack_from(fmt, buf, off)[0], off + size
    if ftype == "bytes":
        (n,) = struct.unpack_from(">I", buf, off)
        off += 4
        return bytes(buf[off : off + n]), off + n
    if ftype == "str":
        (n,) = struct.unpack_from(">I", buf, off)
        off += 4
        return bytes(buf[off : off + n]).decode("utf-8"), off + n
    if ftype.startswith("list:"):
        inner = ftype[5:]
        (n,) = struct.unpack_from(">I", buf, off)
        off += 4
        items = []
        for _ in range(n):
            item, off = _unpack_value(inner, buf, off)
            items.append(item)
        return items, off
    if ftype.startswith("msg:"):
        cls = _MESSAGE_CLASSES[ftype[4:]]
        return cls.unpack_body(buf, off)
    raise TypeError(f"unknown field type {ftype!r}")


def _tail_elides(cls) -> bool:
    """Does this message's encoding have a skew-variable length (its
    own optional tail, or transitively via a terminal nested message)?"""
    if cls.SKEW_TOLERANT_FROM is not None:
        return True
    if cls.FIELDS:
        _, ftype = cls.FIELDS[-1]
        if ftype.startswith("msg:"):
            inner = _MESSAGE_CLASSES.get(ftype[4:])
            return inner is not None and _tail_elides(inner)
    return False


def _nested_msg_refs(cls):
    """Yield (inner class name, is_nonterminal) for every nested-message
    field; list elements are never buffer-terminal."""
    for i, (_, ftype) in enumerate(cls.FIELDS):
        if ftype.startswith("list:msg:"):
            yield ftype[9:], True
        elif ftype.startswith("msg:"):
            yield ftype[4:], i != len(cls.FIELDS) - 1


def _check_skew_nesting(cls) -> None:
    for inner_name, nonterminal in _nested_msg_refs(cls):
        inner = _MESSAGE_CLASSES.get(inner_name)
        if inner is not None and nonterminal and _tail_elides(inner):
            raise TypeError(
                f"{cls.__name__}: skew-tolerant {inner_name} may only be "
                "nested as the final field (its optional tail elides)"
            )
    if _tail_elides(cls):
        # the other definition order: this class just became
        # variable-length; nobody may already nest it non-terminally
        for other in _MESSAGE_CLASSES.values():
            for inner_name, nonterminal in _nested_msg_refs(other):
                if inner_name == cls.__name__ and nonterminal:
                    raise TypeError(
                        f"{other.__name__} nests skew-tolerant "
                        f"{cls.__name__} non-terminally"
                    )


class Message:
    """Base class; subclasses define MSG_TYPE (int or None) and FIELDS."""

    MSG_TYPE: int | None = None
    FIELDS: tuple[tuple[str, str], ...] = ()
    # opt-in version-skew tolerance: the index of the first OPTIONAL
    # field — fields from this index on default-fill when the wire ends
    # before them (an older peer predating the additions); everything
    # before it stays required. STRICTLY opt-in per message and scoped
    # to the genuinely-additive suffix: blanket tolerance would fail
    # OPEN — e.g. a truncated CstoclWriteStatus would decode its
    # missing ``status`` u8 as 0 == OK and report a write committed
    # that no server ever acknowledged, and a reply cut before a
    # verdict-bearing v0 field must still be a parse error, not a
    # zero. None (default) = every field required.
    SKEW_TOLERANT_FROM: int | None = None
    # fast path for data-plane messages: when FIELDS is all scalars plus
    # optionally one trailing ``bytes`` field, the scalar prefix packs/
    # unpacks as one struct call (per-64KiB-piece overhead matters)
    _FAST: struct.Struct | None = None
    _FAST_NAMES: tuple[str, ...] = ()
    _FAST_TAIL: str | None = None

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        # skew-nesting guard (registration-time, zero hot-path cost):
        # pack_body elides default-valued optional trailing fields, so
        # a message with a skew-variable tail has no fixed encoded
        # length — it may only be nested as the LAST field of its
        # container (where the decoder's off==len(buf) default-fill
        # applies). Nesting one non-terminally (or in a list) would
        # silently misalign every field after it; fail the class
        # definition instead.
        _check_skew_nesting(cls)
        _MESSAGE_CLASSES[cls.__name__] = cls
        if cls.MSG_TYPE is not None:
            existing = _TYPE_REGISTRY.get(cls.MSG_TYPE)
            if existing is not None and existing.__name__ != cls.__name__:
                raise TypeError(
                    f"duplicate MSG_TYPE {cls.MSG_TYPE}: "
                    f"{existing.__name__} vs {cls.__name__}"
                )
            _TYPE_REGISTRY[cls.MSG_TYPE] = cls
        fmt = ">"
        names = []
        tail = None
        for i, (name, ftype) in enumerate(cls.FIELDS):
            if ftype in _SCALARS:
                fmt += _SCALARS[ftype][1:]
                names.append(name)
            elif ftype == "bytes" and i == len(cls.FIELDS) - 1:
                tail = name
            else:
                return  # generic path only
        cls._FAST = struct.Struct(fmt)
        cls._FAST_NAMES = tuple(names)
        cls._FAST_TAIL = tail

    def __init__(self, **kwargs):
        optional_from = self.SKEW_TOLERANT_FROM
        for i, (name, ftype) in enumerate(self.FIELDS):
            if name not in kwargs:
                if optional_from is not None and i >= optional_from:
                    # optional-on-the-wire fields are optional in the
                    # constructor too: call sites predating an additive
                    # trailing field keep working (same zero the decoder
                    # would fill for a skewed peer)
                    setattr(self, name, _default_value(ftype))
                    continue
                raise TypeError(f"{type(self).__name__} missing field {name!r}")
            setattr(self, name, kwargs.pop(name))
        if kwargs:
            raise TypeError(f"{type(self).__name__} unknown fields {sorted(kwargs)}")

    def pack_body(self) -> bytes:
        # canonical skew-friendly encoding: OPTIONAL trailing fields
        # still holding their default are not emitted at all, so a
        # message whose additive suffix is unused stays byte-identical
        # to the pre-addition encoding — a new sender interoperates
        # with old receivers (whose parse would reject trailing bytes)
        # unless it actually USES a new field
        n_emit = len(self.FIELDS)
        if self.SKEW_TOLERANT_FROM is not None:
            while (
                n_emit > self.SKEW_TOLERANT_FROM
                and self._field_is_default(n_emit - 1)
            ):
                n_emit -= 1
        if self._FAST is not None and n_emit == len(self.FIELDS):
            head = self._FAST.pack(
                *(getattr(self, n) for n in self._FAST_NAMES)
            )
            if self._FAST_TAIL is None:
                return head
            tail = bytes(getattr(self, self._FAST_TAIL))
            return head + struct.pack(">I", len(tail)) + tail
        out = bytearray()
        for name, ftype in self.FIELDS[:n_emit]:
            _pack_value(ftype, getattr(self, name), out)
        return bytes(out)

    def _field_is_default(self, i: int) -> bool:
        name, ftype = self.FIELDS[i]
        return getattr(self, name) == _default_value(ftype)

    @classmethod
    def unpack_body(cls, buf: memoryview | bytes, off: int = 0):
        optional_from = cls.SKEW_TOLERANT_FROM
        if cls._FAST is not None and (
            optional_from is None or len(buf) - off >= cls._FAST.size
        ):
            msg = cls.__new__(cls)
            for name, value in zip(
                cls._FAST_NAMES, cls._FAST.unpack_from(buf, off)
            ):
                setattr(msg, name, value)
            off += cls._FAST.size
            if cls._FAST_TAIL is not None:
                if (
                    off == len(buf)
                    and optional_from is not None
                    and optional_from <= len(cls.FIELDS) - 1
                ):
                    # sender predates the tail field: default-fill
                    setattr(msg, cls._FAST_TAIL, b"")
                else:
                    (n,) = struct.unpack_from(">I", buf, off)
                    off += 4
                    setattr(msg, cls._FAST_TAIL, bytes(buf[off : off + n]))
                    off += n
            return msg, off
        buf = memoryview(buf)
        values = {}
        for i, (name, ftype) in enumerate(cls.FIELDS):
            if (
                off == len(buf)
                and optional_from is not None
                and i >= optional_from
            ):
                # version skew: the sender's schema ends here — newer
                # trailing fields default-fill instead of failing the
                # whole parse (a rolling upgrade would otherwise break
                # e.g. CltomaIoLimitRequest on its new `probe` field).
                # A REQUIRED field missing, or a field CUT MID-VALUE,
                # still raises: that is truncation/corruption, not skew.
                values[name] = _default_value(ftype)
            else:
                values[name], off = _unpack_value(ftype, buf, off)
        return cls(**values), off

    @classmethod
    def parse(cls, payload: bytes):
        msg, off = cls.unpack_body(payload)
        if off != len(payload):
            raise ValueError(
                f"{cls.__name__}: trailing {len(payload) - off} bytes"
            )
        return msg

    def __eq__(self, other):
        return type(self) is type(other) and all(
            getattr(self, n) == getattr(other, n) for n, _ in self.FIELDS
        )

    def __repr__(self):
        fields = ", ".join(
            f"{n}={_short(getattr(self, n))!r}" for n, _ in self.FIELDS
        )
        return f"{type(self).__name__}({fields})"


def _short(v):
    if isinstance(v, (bytes, bytearray)) and len(v) > 16:
        return v[:16] + b"..."
    return v


def message_class_for(msg_type: int) -> type[Message]:
    try:
        return _TYPE_REGISTRY[msg_type]
    except KeyError:
        raise KeyError(f"unknown message type {msg_type}") from None
