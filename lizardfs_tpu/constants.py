"""Core geometry constants.

Mirrors the reference's fixed geometry (reference: CMakeLists.txt:93-94,
src/protocol/MFSCommunication.h:60-84): a chunk is 1024 blocks of 64 KiB,
each block carries a CRC32 (polynomial 0xEDB88320, zlib-compatible).
"""

# One block: unit of CRC protection and of striping.
MFSBLOCKSIZE = 64 * 1024  # 65536

# Blocks per chunk.
MFSBLOCKSINCHUNK = 1024

# One chunk: unit of replication / erasure coding (64 MiB).
MFSCHUNKSIZE = MFSBLOCKSIZE * MFSBLOCKSINCHUNK

# Chunk header size used by the on-disk format of the reference
# (signature 1 KiB + CRC table 4 KiB); kept for format parity.
MFSHDRSIZE = 4 * 1024 + 1024

# Maximum file size = chunk size * 2^31 (MFSCommunication.h:84).
MAX_FILE_SIZE = MFSCHUNKSIZE * (1 << 31)

# CRC32 polynomial (reflected), identical to zlib's crc32
# (MFSCommunication.h:81).
CRC_POLY = 0xEDB88320

# GF(2^8) reduction polynomial used by the EC codec: x^8+x^4+x^3+x^2+1
# (0x11d), identical to Intel ISA-L and the reference's galois_field
# fallback (src/common/galois_field_isal.cc:37-44).
GF_POLY = 0x11D

# EC parameter bounds (src/common/slice_traits.h:143-146).
EC_MIN_DATA = 2
EC_MAX_DATA = 32
EC_MIN_PARITY = 1
EC_MAX_PARITY = 32

# XOR goal bounds (src/common/slice_traits.h:99-100).
XOR_MIN_LEVEL = 2
XOR_MAX_LEVEL = 9

# The four documented "off" spellings every boolean LZ_* switch honors
# (spelling parity pinned native-side too: lzshm::ring_disabled). An
# operator's LZ_X=off must mean OFF on every plane, never "truthy
# string, so on" — the inversion class the kill-switch lint kills.
OFF_SPELLINGS = ("0", "off", "false", "no")


def env_flag(name: str, default: bool = True) -> bool:
    """THE accessor for boolean ``LZ_*`` kill switches. Unset returns
    ``default``; any set value is ON unless it spells one of the four
    documented offs. Lives here because constants is the one
    dependency-free module every role already imports. Read per call,
    not cached: tests and operators flip switches mid-process. The
    kill-switch lint rule forbids direct environ reads of boolean
    switches anywhere else — one accessor, one spelling set."""
    import os

    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.lower() not in OFF_SPELLINGS


def shadow_reads_enabled() -> bool:
    """LZ_SHADOW_READS kill switch (default ON) for the shadow
    read-replica plane. Consulted by all three roles: the master
    (shadows serve tokened reads, accept passive chunkserver mirrors),
    the chunkserver (mirror registrations to shadow addresses), and the
    client (routing read RPCs to a replica)."""
    return env_flag("LZ_SHADOW_READS")


def qos_enabled() -> bool:
    """LZ_QOS kill switch (default ON) for the multi-tenant QoS plane:
    master fair-share admission (BUSY sheds), chunkserver data-plane
    weighted queueing, and the native per-session byte budgets. Off,
    every enforcement site is this one check and behavior is
    byte-identical to the pre-QoS tree (an UNCONFIGURED engine admits
    everything too, so the switch matters only on clusters that armed
    limits). Read per call: operators flip it live."""
    return env_flag("LZ_QOS")


def heat_enabled() -> bool:
    """LZ_HEAT kill switch (default ON) for the cluster heat loop:
    master heat tracking + `lizardfs_heat_*` families, chunkserver
    per-chunk heartbeat folds (off sends heat_json="" — heartbeats stay
    byte-identical to the pre-heat wire), adaptive goal boosts, load-
    weighted placement, and the SLO→QoS auto-arm. Off, no goal_boost /
    goal_demote op is ever committed and placement falls back to pure
    free-space weighting. Read per call: operators flip it live."""
    return env_flag("LZ_HEAT")


def ha_enabled() -> bool:
    """LZ_HA kill switch (default ON) for the autopilot-failover
    subsystem: quorum leader election among masters + metaloggers
    (metaloggers vote, never lead), automatic fenced promotion of the
    winning shadow (the `epoch_bump` changelog op), and epoch fencing
    of zombie ex-primaries on every register/heartbeat link. Off, no
    election sockets are opened, promotion never commits an epoch bump,
    and every epoch wire field stays 0 — byte-identical to the
    manual-promotion (PR-7) tree; `promote-shadow` still works. Read
    per call: operators flip it live."""
    return env_flag("LZ_HA")


def s3_enabled() -> bool:
    """LZ_S3 kill switch (default ON) for the S3 object gateway: off,
    the gateway refuses to start (a booted gateway keeps serving —
    operators drain by restarting, like any protocol front door)."""
    return env_flag("LZ_S3")


def s3_lifecycle_enabled() -> bool:
    """LZ_S3_LIFECYCLE kill switch (default ON) for the master's
    lifecycle tiering scanner (age-based demote of cold objects to the
    tape tier). Off stops NEW demotions and forced archive queueing;
    recall of already-demoted files always works — data access must
    never be behind a kill switch."""
    return env_flag("LZ_S3_LIFECYCLE")


# Per-inode extra-attribute flags (reference: MFSCommunication.h EATTR_*
# subset; `lizardfs geteattr`/`seteattr`): NOOWNER makes every uid act
# as the owner for permission checks; NOCACHE forbids client-side data
# caching of the inode's blocks; NOENTRYCACHE forbids caching its
# lookup/attr entries (dentry + NFS attr/access caches).


EATTR_NOOWNER = 0x01
EATTR_NOCACHE = 0x02
EATTR_NOENTRYCACHE = 0x04
# Directory carries S3 lifecycle rules (the parameters live in the
# S3_LIFECYCLE_XATTR JSON on the same directory): the marker bit rides
# every Attr reply, so the master's lifecycle scanner and the S3
# gateway can test "has rules?" without an xattr round trip.
EATTR_LIFECYCLE = 0x08

EATTR_NAMES = {
    "noowner": EATTR_NOOWNER,
    "nocache": EATTR_NOCACHE,
    "noentrycache": EATTR_NOENTRYCACHE,
    "lifecycle": EATTR_LIFECYCLE,
}

# Bucket-directory xattr holding the lifecycle rule parameters as JSON
# ({"demote_after_s": seconds}); the EATTR_LIFECYCLE bit marks the
# directory so scanners index it cheaply.
S3_LIFECYCLE_XATTR = "lizardfs.s3.lifecycle"
# Object-file xattr holding the S3 ETag the gateway computed at PUT /
# CompleteMultipartUpload time (served back on GET/HEAD/List).
S3_ETAG_XATTR = "lizardfs.s3.etag"
