"""Flagship pipelines: the chunkserver write-path compute as one program.

Two entry points, matching BASELINE.json configs:

* :func:`single_chip_step` — fused ec(k,m) encode + per-block CRC32 of a
  whole 64 MiB chunk on one chip (BASELINE config 3: ec(8,4), batch =
  128 x 64 KiB stripes => 1024 data blocks + 512 parity blocks).
* :func:`multichip_step` — wide-stripe ec(32,8) with the stripe axis
  sharded over a device mesh and parity reduce-scattered by block
  (BASELINE config 5).

These are what ``bench.py`` times and what ``__graft_entry__.py``
exposes to the driver.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from lizardfs_tpu.constants import MFSBLOCKSIZE
from lizardfs_tpu.ops import jax_ec
from lizardfs_tpu.parallel import sharded


def make_single_chip_step(
    k: int, m: int, block_size: int = MFSBLOCKSIZE, use_pallas: bool | None = None
):
    """Returns a jittable fn(data (k, N) uint8) -> (parity, dcrc, pcrc).

    On a real TPU backend the Pallas kernels run (bits stay in VMEM); on
    CPU the XLA bit-plane path is used (same bytes, tested identical).
    """
    bigm = np.asarray(jax_ec.encoding_bitmatrix(k, m))
    if use_pallas is None:
        from lizardfs_tpu.ops import pallas_ec

        use_pallas = pallas_ec.supported()
    if use_pallas:
        from lizardfs_tpu.ops import pallas_ec

        def step(data: jnp.ndarray):
            return pallas_ec.fused_encode_crc(jnp.asarray(bigm), data, block_size)

        return step

    def step(data: jnp.ndarray):
        return jax_ec.fused_encode_crc(jnp.asarray(bigm), data, block_size)

    return step


def make_multichip_step(
    mesh, k: int = 32, m: int = 8, block_size: int = MFSBLOCKSIZE
):
    """Wide-stripe sharded encode+CRC step over ``mesh`` (see parallel.sharded)."""
    return sharded.sharded_encode_with_crcs(mesh, k, m, block_size)


def make_multichip_reconstruct_step(
    mesh, k: int, m: int, available: list[int], wanted: list[int],
    block_size: int = MFSBLOCKSIZE,
):
    """Mesh-sharded rebuild of ``wanted`` lost parts from survivors —
    the decode leg of the multichip story (see parallel.recovery)."""
    from lizardfs_tpu.parallel import recovery

    return recovery.sharded_reconstruct_with_crcs(
        mesh, k, m, available, wanted, block_size
    )


def example_chunk(k: int, nbytes_per_part: int, seed: int = 0) -> np.ndarray:
    """Deterministic example data (k, nbytes_per_part) uint8."""
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=(k, nbytes_per_part), dtype=np.uint8)
