"""Flagship end-to-end data-plane pipelines (bench + graft entry points)."""
