"""Failover controller: binds an ElectionNode to a MasterServer.

The uRaftController analog (reference: src/uraft/uraftcontroller.cc:78-98
runs promote/demote helper scripts): on winning an election, a shadow
master is promoted in-process; on losing leadership while active, the
daemon logs and keeps serving reads only (full demotion = restart, same
operational rule as the reference).

``promote_exec`` / ``demote_exec`` are the floating-IP glue of the
reference's lizardfs-uraft-helper (lizardfs-uraft-helper.in:81-101
``ip addr add/del`` + arping): shell commands run on every leadership
transition with LIZ_NODE_ID/LIZ_ROLE in the environment, so operators
move a service IP, update DNS, or poke a load balancer without patching
the daemon.
"""

from __future__ import annotations

import asyncio
import logging
import os

from lizardfs_tpu.ha.election import ElectionNode


class FailoverController:
    def __init__(
        self,
        master,  # MasterServer
        node_id: str,
        listen: tuple[str, int],
        peers: dict[str, tuple[str, int]],
        promote_exec: str | None = None,
        demote_exec: str | None = None,
        service_addrs: dict[str, tuple[str, int]] | None = None,
        **election_kwargs,
    ):
        self.master = master
        self.node_id = node_id
        self.promote_exec = promote_exec
        self.demote_exec = demote_exec
        # node id -> master SERVICE address (not the election port):
        # lets every follower re-point its changelog stream at whoever
        # currently leads, instead of a boot-time ACTIVE_MASTER
        self.service_addrs = service_addrs or {}
        # serialize hooks: during flapping, a stale demote finishing
        # after a fresh promote would strip the new leader's service IP
        self._hook_lock = asyncio.Lock()
        self.promotions = 0
        self.demotions = 0
        # monotonic stamp of the last self-promotion this controller
        # performed (RTO attribution: detect->elect->promote)
        self.last_promotion_at: float | None = None
        self.log = logging.getLogger(f"failover[{node_id}]")
        self.node = ElectionNode(
            node_id,
            listen,
            peers,
            get_version=lambda: master.changelog.version,
            on_leader=self._on_leader,
            on_follower=self._on_follower,
            **election_kwargs,
        )

    async def _run_hook(self, cmd: str | None, role: str) -> None:
        if not cmd:
            return
        env = dict(os.environ, LIZ_NODE_ID=self.node_id, LIZ_ROLE=role)
        async with self._hook_lock:
            proc = None
            try:
                proc = await asyncio.create_subprocess_shell(cmd, env=env)
                rc = await asyncio.wait_for(proc.wait(), timeout=30.0)
                if rc != 0:
                    self.log.warning("%s hook exited %d: %s", role, rc, cmd)
            except asyncio.TimeoutError:
                # a hung hook must not linger: it could mutate network
                # state (e.g. re-add a floating IP) minutes later
                self.log.warning("%s hook timed out; killing: %s", role, cmd)
                proc.kill()
                # lint: waive(unbounded-await): reaping a SIGKILLed child — the kernel completes this; a timer could leak the zombie
                await proc.wait()
            except OSError as e:
                self.log.warning("%s hook failed: %s", role, e)

    async def start(self) -> None:
        await self.node.start()

    async def stop(self) -> None:
        await self.node.stop()

    def status(self) -> dict:
        doc = self.node.status()
        doc.update({
            "personality": self.master.personality,
            "epoch": self.master.meta.epoch,
            "promotions": self.promotions,
            "demotions": self.demotions,
        })
        return doc

    async def _on_leader(self) -> None:
        if self.master.personality != "master":
            self.log.info("election won — promoting shadow")
            self.master.promote()
            self.promotions += 1
            self.last_promotion_at = asyncio.get_running_loop().time()
            mx = getattr(self.master, "metrics", None)
            if mx is not None:
                mx.counter("ha_promotions").inc()
            await self._run_hook(self.promote_exec, "master")

    async def _on_follower(self, leader_id: str) -> None:
        was_active = self.master.personality == "master"
        if was_active:
            # split-brain guard: an active master that lost leadership
            # stops accepting work
            self.log.warning(
                "lost leadership to %s — demoting to shadow", leader_id
            )
        addr = self.service_addrs.get(leader_id)
        if addr is not None:
            # follow the CURRENT leader's changelog — every replica
            # must converge on it or the next promotion loses writes
            self.master.follow(addr)
        elif was_active:
            # no service map configured: read-only until restarted
            self.master.personality = "shadow"
        if was_active:
            self.demotions += 1
            mx = getattr(self.master, "metrics", None)
            if mx is not None:
                mx.counter("ha_demotions").inc()
            await self._run_hook(self.demote_exec, "shadow")
