"""Failover controller: binds an ElectionNode to a MasterServer.

The uRaftController analog (reference: src/uraft/uraftcontroller.cc:78-98
runs promote/demote helper scripts): on winning an election, a shadow
master is promoted in-process; on losing leadership while active, the
daemon logs and keeps serving reads only (full demotion = restart, same
operational rule as the reference).
"""

from __future__ import annotations

import logging

from lizardfs_tpu.ha.election import ElectionNode


class FailoverController:
    def __init__(
        self,
        master,  # MasterServer
        node_id: str,
        listen: tuple[str, int],
        peers: dict[str, tuple[str, int]],
        **election_kwargs,
    ):
        self.master = master
        self.log = logging.getLogger(f"failover[{node_id}]")
        self.node = ElectionNode(
            node_id,
            listen,
            peers,
            get_version=lambda: master.changelog.version,
            on_leader=self._on_leader,
            on_follower=self._on_follower,
            **election_kwargs,
        )

    async def start(self) -> None:
        await self.node.start()

    async def stop(self) -> None:
        await self.node.stop()

    async def _on_leader(self) -> None:
        if self.master.personality != "master":
            self.log.info("election won — promoting shadow")
            self.master.promote()

    async def _on_follower(self, leader_id: str) -> None:
        if self.master.personality == "master":
            # split-brain guard: an active master that lost leadership
            # stops accepting work; operators restart it as a shadow
            self.log.warning(
                "lost leadership to %s — demoting to shadow (read-only)",
                leader_id,
            )
            self.master.personality = "shadow"
