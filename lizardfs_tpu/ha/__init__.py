"""High availability: raft-style leader election + failover controller."""
