"""Raft-style leader election (election only, no log replication).

The reference's uraft runs Raft *elections* among master nodes and lets
the metadata version serve as the log (reference: src/uraft/uraft.h:18-27
"data version" == metadata version; quorum check uraft.h:27). Same model
here: each master/shadow runs an ElectionNode over UDP; candidates carry
their metadata version and voters refuse candidates whose version is
behind their own, so only the most-up-to-date shadow can win — then the
controller promotes it (the lizardfs-uraft-helper promote analog,
uraftcontroller.cc:78-98).

States: follower -> candidate -> leader, randomized election timeouts,
terms, majority quorum. Messages are single-datagram JSON (election
traffic is tiny and loss-tolerant by design).
"""

from __future__ import annotations

import asyncio
import json
import logging
import random

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"

# A vote-only arbiter's archived version is only a PROXY for data
# freshness: its archive can momentarily lead the surviving shadow's
# replay (each followed the dead active over its own socket), and with
# the active gone the shadow can never catch up past it — a strict
# up-to-date rule would then deadlock the election forever. After this
# many max election timeouts without ANY leader, an arbiter stops
# refusing behind candidates (availability over the proxy). Real
# masters (can_lead=True) never relax: their version IS the data, and
# relaxing it could elect a stale master and lose acknowledged writes.
ARBITER_RELAX_TIMEOUTS = 10.0


class _Proto(asyncio.DatagramProtocol):
    def __init__(self, node: "ElectionNode"):
        self.node = node

    def datagram_received(self, data, addr):
        try:
            msg = json.loads(data.decode())
        except (ValueError, UnicodeDecodeError):
            return
        self.node._on_message(msg)


class ElectionNode:
    def __init__(
        self,
        node_id: str,
        listen: tuple[str, int],
        peers: dict[str, tuple[str, int]],
        *,
        get_version,  # () -> int: this node's metadata version
        on_leader,  # async () -> None
        on_follower=None,  # async (leader_id) -> None
        election_timeout: tuple[float, float] = (0.15, 0.30),
        heartbeat_interval: float = 0.05,
        can_lead: bool = True,
    ):
        self.node_id = node_id
        self.listen = listen
        self.peers = dict(peers)  # id -> (host, port), excluding self
        self.get_version = get_version
        self.on_leader = on_leader
        self.on_follower = on_follower
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval
        # metaloggers vote but never lead (uraft arbiter analog): a
        # vote-only node never starts an election, so it contributes to
        # quorum without ever being promoted to serve metadata
        self.can_lead = can_lead
        self.elections_started = 0
        self.votes_granted = 0
        self.depositions = 0
        self.stale_votes_granted = 0
        # last time a leader heartbeat arrived: drives the arbiter's
        # leaderless-deadlock relaxation (never reset by vote grants)
        self._leader_seen_at = 0.0

        self.state = FOLLOWER
        self.term = 0
        self.voted_for: str | None = None
        self.leader_id: str | None = None
        self._votes: set[str] = set()
        self._last_heartbeat = 0.0
        self._transport = None
        self._tasks: list[asyncio.Task] = []
        self._rng = random.Random(hash(node_id) & 0xFFFF)
        self.log = logging.getLogger(f"election[{node_id}]")

    @property
    def quorum(self) -> int:
        return (len(self.peers) + 1) // 2 + 1

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: _Proto(self), local_addr=self.listen
        )
        self.listen = self._transport.get_extra_info("sockname")[:2]
        self._last_heartbeat = loop.time()
        self._leader_seen_at = loop.time()
        self._tasks.append(loop.create_task(self._ticker()))

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        if self._transport is not None:
            self._transport.close()

    # --- wire -------------------------------------------------------------

    def _send(self, peer_id: str, msg: dict) -> None:
        addr = self.peers.get(peer_id)
        if addr is not None and self._transport is not None:
            self._transport.sendto(json.dumps(msg).encode(), addr)

    def _broadcast(self, msg: dict) -> None:
        for pid in self.peers:
            self._send(pid, msg)

    # --- state machine ----------------------------------------------------

    async def _ticker(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            if self.state == LEADER:
                self._broadcast({
                    "type": "heartbeat", "term": self.term,
                    "leader": self.node_id,
                })
                await asyncio.sleep(self.heartbeat_interval)
                continue
            timeout = self._rng.uniform(*self.election_timeout)
            await asyncio.sleep(0.02)
            if (
                self.can_lead
                and loop.time() - self._last_heartbeat > timeout
            ):
                self._start_election()

    def status(self) -> dict:
        """Snapshot for the admin `ha` command / health section."""
        return {
            "node_id": self.node_id,
            "state": self.state,
            "term": self.term,
            "leader": self.leader_id,
            "can_lead": self.can_lead,
            "peers": sorted(self.peers),
            "quorum": self.quorum,
            "elections_started": self.elections_started,
            "votes_granted": self.votes_granted,
            "stale_votes_granted": self.stale_votes_granted,
            "depositions": self.depositions,
        }

    def _start_election(self) -> None:
        self.term += 1
        self.state = CANDIDATE
        self.elections_started += 1
        self.voted_for = self.node_id
        self._votes = {self.node_id}
        self.log.debug("starting election for term %d", self.term)
        self._broadcast({
            "type": "vote_req", "term": self.term,
            "candidate": self.node_id, "version": int(self.get_version()),
        })
        self._last_heartbeat = asyncio.get_running_loop().time()
        self._check_quorum()

    def _check_quorum(self) -> None:
        if self.state == CANDIDATE and len(self._votes) >= self.quorum:
            self.state = LEADER
            self.leader_id = self.node_id
            self.log.info("won election for term %d", self.term)
            self._broadcast({
                "type": "heartbeat", "term": self.term, "leader": self.node_id,
            })
            asyncio.get_running_loop().create_task(self.on_leader())

    def _on_message(self, msg: dict) -> None:
        mtype = msg.get("type")
        term = int(msg.get("term", 0))
        if term > self.term:
            self.term = term
            self.voted_for = None
            if self.state == LEADER:
                self.log.warning("deposed by higher term %d", term)
                self.depositions += 1
            self.state = FOLLOWER
        if mtype == "vote_req":
            self._on_vote_req(msg, term)
        elif mtype == "vote":
            if (
                term == self.term
                and self.state == CANDIDATE
                and msg.get("granted")
            ):
                self._votes.add(msg.get("voter", ""))
                self._check_quorum()
        elif mtype == "heartbeat":
            if term >= self.term:
                was_leader = self.state == LEADER and msg.get("leader") != self.node_id
                self.state = FOLLOWER if msg.get("leader") != self.node_id else self.state
                new_leader = msg.get("leader")
                leader_changed = new_leader != self.leader_id
                self.leader_id = new_leader
                now = asyncio.get_running_loop().time()
                self._last_heartbeat = now
                self._leader_seen_at = now
                if (leader_changed or was_leader) and self.on_follower is not None \
                        and new_leader != self.node_id:
                    asyncio.get_running_loop().create_task(
                        self.on_follower(new_leader)
                    )

    def _on_vote_req(self, msg: dict, term: int) -> None:
        candidate = msg.get("candidate", "")
        cand_version = int(msg.get("version", 0))
        # uraft rule: never elect a master whose metadata is behind ours
        up_to_date = cand_version >= int(self.get_version())
        if not up_to_date and not self.can_lead:
            leaderless_s = (
                asyncio.get_running_loop().time() - self._leader_seen_at
            )
            if leaderless_s > ARBITER_RELAX_TIMEOUTS * self.election_timeout[1]:
                self.stale_votes_granted += 1
                self.log.warning(
                    "arbiter granting vote to behind candidate %s "
                    "(v%d < our v%d) after %.1fs without a leader",
                    candidate, cand_version, int(self.get_version()),
                    leaderless_s,
                )
                up_to_date = True
        granted = (
            term == self.term
            and self.voted_for in (None, candidate)
            and up_to_date
        )
        if granted:
            self.voted_for = candidate
            self.votes_granted += 1
            self._last_heartbeat = asyncio.get_running_loop().time()
        self._send(candidate, {
            "type": "vote", "term": self.term, "granted": granted,
            "voter": self.node_id,
        })
