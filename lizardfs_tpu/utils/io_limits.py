"""Shared IO-limit vocabulary: config grammar + group->limit resolution.

Used by both the master (budget allocation) and the client
(classification) — the reference keeps this split the same way
(reference: src/common/io_limits_config_loader.cc shared loader;
src/mount/io_limit_group.cc client-side classification).
"""

from __future__ import annotations

UNCLASSIFIED = "unclassified"


def parse_limits_cfg(text: str) -> tuple[str, dict[str, int]]:
    """Parse an mfsiolimits.cfg-style file (reference:
    src/common/io_limits_config_loader.cc):

        subsystem blkio
        limit unclassified 1048576
        limit /containers/web 10485760

    Returns (subsystem, {group: bytes_per_sec}).
    """
    subsystem = ""
    limits: dict[str, int] = {}
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        fields = line.split()
        if fields[0] == "subsystem" and len(fields) == 2:
            subsystem = fields[1]
        elif fields[0] == "limit" and len(fields) == 3:
            limits[fields[1]] = int(fields[2])
        else:
            raise ValueError(f"iolimits line {lineno}: {raw!r}")
    return subsystem, limits



def resolve_limit(group: str, limits: dict[str, int]) -> tuple[str, int]:
    """Match ``group`` to the closest configured ancestor limit.

    Returns (matched-key, bps). The reference walks up the cgroup path
    until a configured group is found (io_limit_group.cc); unmatched
    paths use the "unclassified" entry, and a missing "unclassified"
    entry means unlimited (0).
    """
    if group in limits:
        return group, limits[group]
    path = group
    while path and path != "/" and path.startswith("/"):
        path = path.rsplit("/", 1)[0] or "/"
        if path in limits:
            return path, limits[path]
    return UNCLASSIFIED, limits.get(UNCLASSIFIED, 0)
