"""Chunk <-> part striping math.

How chunk bytes map onto slice parts (the layout contract shared by the
client write path, the chunkserver replicator, and the read plans):

  * blocks of 64 KiB are striped round-robin over the d data parts
    (block i of the chunk lands in data part i % d at block i // d),
  * xorN slices store data in parts 1..N and the per-stripe XOR parity
    in part 0; ec(k,m) stores data in parts 0..k-1, RS parity in parts
    k..k+m-1,
  * parity is computed over zero-padded 64 KiB blocks; part byte lengths
    follow geometry.chunk_length_to_part_length.

Reference behavior: src/mount/chunk_writer.cc:365-398 (parity from
stripes), src/common/slice_traits.h:311-349 (lengths).
"""

from __future__ import annotations

import numpy as np

from lizardfs_tpu.constants import MFSBLOCKSIZE
from lizardfs_tpu.core import geometry
from lizardfs_tpu.core.encoder import ChunkEncoder, get_encoder


def _padded_data_parts(
    data: np.ndarray, d: int
) -> tuple[list[np.ndarray], int]:
    """Split chunk bytes into d zero-padded equal part streams.

    Returns (parts, part_len) where part_len covers ceil(blocks/d) blocks.
    """
    nbytes = data.shape[0]
    nblocks = (nbytes + MFSBLOCKSIZE - 1) // MFSBLOCKSIZE
    blocks_per_part = (nblocks + d - 1) // d
    part_len = blocks_per_part * MFSBLOCKSIZE
    # scatter: block i -> part i%d, slot i//d
    full = np.zeros(d * blocks_per_part * MFSBLOCKSIZE, dtype=np.uint8)
    full[:nbytes] = data
    blocks = full.reshape(blocks_per_part * d, MFSBLOCKSIZE)[: nblocks]
    parts = [np.zeros(part_len, dtype=np.uint8) for _ in range(d)]
    for i in range(nblocks):
        p, slot = i % d, i // d
        parts[p][slot * MFSBLOCKSIZE : (slot + 1) * MFSBLOCKSIZE] = blocks[i]
    return parts, part_len


def split_chunk(
    data: np.ndarray,
    slice_type: geometry.SliceType,
    encoder: ChunkEncoder | None = None,
) -> dict[int, np.ndarray]:
    """Split chunk bytes into all parts of a slice (padded streams).

    Returned arrays are zero-padded to whole blocks; callers truncate to
    geometry.chunk_length_to_part_length for the on-wire/on-disk length.
    """
    data = np.asarray(data, dtype=np.uint8)
    enc = encoder or get_encoder("cpu")
    if slice_type.is_standard or slice_type.is_tape:
        return {0: data.copy()}
    d = slice_type.data_parts
    parts, _ = _padded_data_parts(data, d)
    if slice_type.is_xor:
        parity = enc.xor_parity(parts)
        out = {0: parity}
        for i, p in enumerate(parts):
            out[i + 1] = p
        return out
    assert slice_type.is_ec
    m = slice_type.parity_parts
    parity = enc.encode(d, m, parts)
    out = {i: p for i, p in enumerate(parts)}
    for j, p in enumerate(parity):
        out[d + j] = p
    return out


def part_length(
    slice_type: geometry.SliceType, part: int, chunk_length: int
) -> int:
    return geometry.chunk_length_to_part_length(
        geometry.ChunkPartType(slice_type, part), chunk_length
    )


def assemble_chunk(
    data_parts: dict[int, np.ndarray],
    slice_type: geometry.SliceType,
    chunk_length: int,
) -> np.ndarray:
    """Reassemble chunk bytes from *data* part streams (inverse of
    split_chunk for the data portion)."""
    if slice_type.is_standard or slice_type.is_tape:
        return np.asarray(data_parts[0][:chunk_length])
    d = slice_type.data_parts
    first_data = 1 if slice_type.is_xor else 0
    nblocks = (chunk_length + MFSBLOCKSIZE - 1) // MFSBLOCKSIZE
    out = np.zeros(nblocks * MFSBLOCKSIZE, dtype=np.uint8)
    for i in range(nblocks):
        p, slot = i % d, i // d
        src = data_parts[first_data + p]
        out[i * MFSBLOCKSIZE : (i + 1) * MFSBLOCKSIZE] = src[
            slot * MFSBLOCKSIZE : (slot + 1) * MFSBLOCKSIZE
        ]
    return out[:chunk_length]
