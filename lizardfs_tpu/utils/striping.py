"""Chunk <-> part striping math.

How chunk bytes map onto slice parts (the layout contract shared by the
client write path, the chunkserver replicator, and the read plans):

  * blocks of 64 KiB are striped round-robin over the d data parts
    (block i of the chunk lands in data part i % d at block i // d),
  * xorN slices store data in parts 1..N and the per-stripe XOR parity
    in part 0; ec(k,m) stores data in parts 0..k-1, RS parity in parts
    k..k+m-1,
  * parity is computed over zero-padded 64 KiB blocks; part byte lengths
    follow geometry.chunk_length_to_part_length.

Reference behavior: src/mount/chunk_writer.cc:365-398 (parity from
stripes), src/common/slice_traits.h:311-349 (lengths).
"""

from __future__ import annotations

import numpy as np

from lizardfs_tpu.constants import MFSBLOCKSIZE
from lizardfs_tpu.core import geometry
from lizardfs_tpu.core.encoder import ChunkEncoder, get_encoder


def padded_data_parts(
    data: np.ndarray, d: int, out: np.ndarray | None = None
) -> tuple[list[np.ndarray], int]:
    """Split chunk bytes into d zero-padded equal part streams.

    Returns (parts, part_len) where part_len covers ceil(blocks/d) blocks.
    One native (GIL-free) or vectorized-numpy pass — this runs on every
    EC/xor chunk write, so a per-block Python loop here throttled the
    whole write pipeline. ``out`` (shape (d, part_len)) reuses a staging
    buffer on the native path.
    """
    nbytes = data.shape[0]
    nblocks = (nbytes + MFSBLOCKSIZE - 1) // MFSBLOCKSIZE
    blocks_per_part = (nblocks + d - 1) // d
    part_len = blocks_per_part * MFSBLOCKSIZE
    from lizardfs_tpu.core import native

    if native.stripe_helpers_available():
        stacked = native.stripe_scatter(data, d, blocks_per_part, out=out)
        return list(stacked), part_len
    # numpy fallback: pad to the full stripe grid, then one strided copy
    # block i -> part i%d, slot i//d
    full = np.zeros(d * blocks_per_part * MFSBLOCKSIZE, dtype=np.uint8)
    full[:nbytes] = data
    grid = full.reshape(blocks_per_part, d, MFSBLOCKSIZE)
    stacked = np.ascontiguousarray(grid.transpose(1, 0, 2))
    return [stacked[p].reshape(part_len) for p in range(d)], part_len


def split_chunk(
    data: np.ndarray,
    slice_type: geometry.SliceType,
    encoder: ChunkEncoder | None = None,
) -> dict[int, np.ndarray]:
    """Split chunk bytes into all parts of a slice (padded streams).

    Returned arrays are zero-padded to whole blocks; callers truncate to
    geometry.chunk_length_to_part_length for the on-wire/on-disk length.
    """
    data = np.asarray(data, dtype=np.uint8)
    enc = encoder or get_encoder("cpu")
    if slice_type.is_standard or slice_type.is_tape:
        return {0: data.copy()}
    d = slice_type.data_parts
    parts, _ = padded_data_parts(data, d)
    if slice_type.is_xor:
        parity = enc.xor_parity(parts)
        out = {0: parity}
        for i, p in enumerate(parts):
            out[i + 1] = p
        return out
    assert slice_type.is_ec
    m = slice_type.parity_parts
    parity = enc.encode(d, m, parts)
    out = {i: p for i, p in enumerate(parts)}
    for j, p in enumerate(parity):
        out[d + j] = p
    return out


def part_length(
    slice_type: geometry.SliceType, part: int, chunk_length: int
) -> int:
    return geometry.chunk_length_to_part_length(
        geometry.ChunkPartType(slice_type, part), chunk_length
    )


def assemble_chunk(
    data_parts: dict[int, np.ndarray],
    slice_type: geometry.SliceType,
    chunk_length: int,
    out: np.ndarray | None = None,
) -> np.ndarray:
    """Reassemble chunk bytes from *data* part streams (inverse of
    split_chunk for the data portion). ``out``, when given, receives the
    bytes directly (must be C-contiguous uint8 of >= chunk_length)."""
    if slice_type.is_standard or slice_type.is_tape:
        piece = np.asarray(data_parts[0][:chunk_length])
        if out is None:
            return piece
        out[:chunk_length] = piece
        return out[:chunk_length]
    d = slice_type.data_parts
    first_data = 1 if slice_type.is_xor else 0
    nblocks = (chunk_length + MFSBLOCKSIZE - 1) // MFSBLOCKSIZE
    blocks_per_part = (nblocks + d - 1) // d
    part_len = blocks_per_part * MFSBLOCKSIZE
    from lizardfs_tpu.core import native

    # each part must cover the slots the gather reads from it: part p's
    # last-used block is the largest i < nblocks with i % d == p
    def _covered(p: int) -> int:
        if nblocks <= p:
            return 0
        last_i = nblocks - 1 - ((nblocks - 1 - p) % d)
        slot = last_i // d
        tail = (
            chunk_length - last_i * MFSBLOCKSIZE
            if last_i == nblocks - 1
            else MFSBLOCKSIZE
        )
        return slot * MFSBLOCKSIZE + tail

    if (
        native.stripe_helpers_available()
        and out is not None
        and out.flags.c_contiguous
        and out.dtype == np.uint8
        and out.shape[0] >= chunk_length
        and all(
            data_parts[first_data + p].shape[0] >= _covered(p)
            and data_parts[first_data + p].flags.c_contiguous
            for p in range(d)
        )
    ):
        native.stripe_gather(
            [data_parts[first_data + p] for p in range(d)],
            chunk_length, out=out,
        )
        return out[:chunk_length]
    # numpy path: stack (d, slots, B), transpose to (slots, d, B) = block
    # order, flatten
    stacked = np.zeros((d, part_len), dtype=np.uint8)
    for p in range(d):
        src = data_parts[first_data + p]
        stacked[p, : min(part_len, src.shape[0])] = src[:part_len]
    grid = stacked.reshape(d, blocks_per_part, MFSBLOCKSIZE)
    flat = np.ascontiguousarray(grid.transpose(1, 0, 2)).reshape(-1)
    if out is not None:
        out[:chunk_length] = flat[:chunk_length]
        return out[:chunk_length]
    return flat[:chunk_length]
