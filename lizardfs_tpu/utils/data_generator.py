"""Deterministic offset-addressable data generator.

The reference validates file contents with generators whose byte at
offset i is a pure function of i (reference: utils/data_generator.h),
so any range can be checked without storing the original. Same idea:
byte(i) = low byte of a Weyl-sequence mix of the 64-bit offset.
"""

from __future__ import annotations

import numpy as np

_MUL = np.uint64(0x9E3779B97F4A7C15)


def generate(offset: int, size: int) -> np.ndarray:
    """Deterministic uint8 array for [offset, offset+size)."""
    idx = np.arange(offset, offset + size, dtype=np.uint64)
    x = idx * _MUL
    x ^= x >> np.uint64(29)
    x *= np.uint64(0xBF58476D1CE4E5B9)
    x ^= x >> np.uint64(32)
    return (x & np.uint64(0xFF)).astype(np.uint8)


def validate(offset: int, data: np.ndarray) -> bool:
    return bool(np.array_equal(np.asarray(data, dtype=np.uint8), generate(offset, len(data))))
