"""Shared helpers: striping math, deterministic data generation."""
