"""ctypes binding to the native C NFSv3 client (liblizardfs_client.so).

The non-Python measuring client for the NFS gateway (VERDICT: the
gateway had only ever been measured with the asyncio wire client, so
server cost and measuring-client cost were confounded). The whole RPC
stack — ONC-RPC record marking, AUTH_SYS, NFS3 XDR — lives in C
(native/client_native.cpp); Python only marshals buffers, and ctypes
drops the GIL for the duration of each blocking call, so a bench can
drive the gateway from a worker thread without the client's event loop
in the measurement.
"""

from __future__ import annotations

import ctypes
import os

# LZ_CLIENT_SO: alternate library path, mirroring LZ_NATIVE_SO — the
# sanitizer matrix (`make sanitize`) points it at the ASan+UBSan build
# so the C NFS client runs instrumented under the real Python gateway
_LIB_PATH = os.environ.get("LZ_CLIENT_SO") or os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
    "native", "liblizardfs_client.so",
)

_lib = None
try:
    if os.path.exists(_LIB_PATH):
        _lib = ctypes.CDLL(_LIB_PATH)
        _lib.liz_nfs_connect.restype = ctypes.c_void_p
        _lib.liz_nfs_connect.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_uint32, ctypes.c_uint32,
        ]
        _lib.liz_nfs_close.argtypes = [ctypes.c_void_p]
        _fh = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
        _lib.liz_nfs_mount.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint32),
        ]
        for fn in (_lib.liz_nfs_lookup, _lib.liz_nfs_create):
            fn.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
                ctypes.c_char_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_uint32),
            ]
        _lib.liz_nfs_read.restype = ctypes.c_int64
        _lib.liz_nfs_read.argtypes = _fh + [
            ctypes.c_uint64, ctypes.c_uint32, ctypes.c_char_p,
        ]
        _lib.liz_nfs_write.restype = ctypes.c_int64
        _lib.liz_nfs_write.argtypes = _fh + [
            ctypes.c_uint64, ctypes.c_uint32, ctypes.c_char_p, ctypes.c_int,
        ]
        _lib.liz_nfs_commit.argtypes = _fh
except (OSError, AttributeError):
    # unloadable .so, or one built before liz_nfs_* existed (ctypes
    # raises AttributeError for a missing symbol): the C row just
    # doesn't run
    _lib = None


def available() -> bool:
    """True when the .so exists and exports the NFS client symbols."""
    return _lib is not None and hasattr(_lib, "liz_nfs_connect")


class CNfs3Error(OSError):
    pass


class CNfs3Client:
    """Blocking NFS3 client over one TCP connection — all wire work in
    C. Use from a worker thread (calls block; the GIL is released)."""

    def __init__(self, host: str, port: int, uid: int = 0, gid: int = 0):
        if not available():
            raise CNfs3Error("liblizardfs_client.so missing liz_nfs_*")
        self._h = _lib.liz_nfs_connect(host.encode(), port, uid, gid)
        if not self._h:
            raise CNfs3Error(f"cannot connect to {host}:{port}")

    def close(self) -> None:
        if self._h:
            _lib.liz_nfs_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _fh_call(self, fn, *args) -> bytes:
        out = ctypes.create_string_buffer(64)
        n = ctypes.c_uint32(0)
        rc = fn(self._h, *args, out, ctypes.byref(n))
        if rc != 0:
            raise CNfs3Error(f"nfs error {rc}")
        return out.raw[: n.value]

    def mnt(self, path: str = "/") -> bytes:
        return self._fh_call(_lib.liz_nfs_mount, path.encode())

    def lookup(self, dirfh: bytes, name: str) -> bytes:
        return self._fh_call(
            _lib.liz_nfs_lookup, dirfh, len(dirfh), name.encode()
        )

    def create(self, dirfh: bytes, name: str) -> bytes:
        return self._fh_call(
            _lib.liz_nfs_create, dirfh, len(dirfh), name.encode()
        )

    def write(self, fh: bytes, offset: int, data: bytes,
              stable: int = 0) -> int:
        n = _lib.liz_nfs_write(
            self._h, fh, len(fh), offset, len(data), data, stable
        )
        if n < 0:
            raise CNfs3Error(f"nfs write error {n}")
        return int(n)

    def read(self, fh: bytes, offset: int, count: int) -> bytes:
        buf = ctypes.create_string_buffer(count)
        n = _lib.liz_nfs_read(self._h, fh, len(fh), offset, count, buf)
        if n < 0:
            raise CNfs3Error(f"nfs read error {n}")
        return buf.raw[: int(n)]

    def commit(self, fh: bytes) -> None:
        rc = _lib.liz_nfs_commit(self._h, fh, len(fh))
        if rc != 0:
            raise CNfs3Error(f"nfs commit error {rc}")
