"""NFSv3 gateway: serve the cluster to standard NFS clients.

The reference ships an NFS-Ganesha FSAL (src/nfs-ganesha/, ~4.2k LoC C)
that adapts its C client library to Ganesha's FSAL API. This package is
the TPU-framework analog with the gateway built in: a self-contained
ONC-RPC + MOUNT3 + NFS3 server (RFC 1813) running on asyncio, backed by
:class:`lizardfs_tpu.client.client.Client`, so any OS NFS client can
reach the cluster without FUSE or Python on the consumer side.
"""

from lizardfs_tpu.nfs.server import NfsGateway

__all__ = ["NfsGateway"]
