"""ONC-RPC v2 (RFC 5531) over TCP with record marking, on asyncio.

Carries the MOUNT3/NFS3 programs of the gateway. The server side is a
program registry: ``(prog, vers) -> async handler(proc, cred, Unpacker)
-> bytes``. AUTH_SYS (flavor 1) credentials are parsed into
:class:`Credential` and become the per-call identity the NFS layer
forwards to the cluster client — same role as Ganesha's op_ctx creds in
the reference FSAL (src/nfs-ganesha/handle.c uses op_ctx->creds for
every op).
"""

from __future__ import annotations

import asyncio
import logging
import struct
from dataclasses import dataclass, field

from lizardfs_tpu.nfs.xdr import Packer, Unpacker, XdrError
from lizardfs_tpu.runtime.retry import bounded_wait, close_writer, \
    spawn_detached

log = logging.getLogger("lizardfs.nfs.rpc")

RPC_VERSION = 2
CALL, REPLY = 0, 1
MSG_ACCEPTED, MSG_DENIED = 0, 1
# accept_stat
SUCCESS, PROG_UNAVAIL, PROG_MISMATCH, PROC_UNAVAIL, GARBAGE_ARGS, SYSTEM_ERR = (
    0, 1, 2, 3, 4, 5,
)
# auth flavors
AUTH_NONE, AUTH_SYS = 0, 1

MAX_RECORD = 1 << 22  # 4 MiB: caps rsize/wsize plus headroom


@dataclass
class Credential:
    uid: int = 0
    gid: int = 0
    gids: list[int] = field(default_factory=list)
    machine: str = ""

    @property
    def all_gids(self) -> list[int]:
        out = [self.gid] + [g for g in self.gids if g != self.gid]
        return out


def parse_auth_sys(body: bytes) -> Credential:
    u = Unpacker(body)
    u.u32()  # stamp
    machine = u.string(255)
    uid = u.u32()
    gid = u.u32()
    n = u.u32()
    if n > 16:
        raise XdrError(f"too many aux gids: {n}")
    gids = [u.u32() for _ in range(n)]
    return Credential(uid=uid, gid=gid, gids=gids, machine=machine)


async def read_record(reader: asyncio.StreamReader) -> bytes:
    """One RPC record: fragments with a last-fragment marker bit.

    Reads are ambient-deadline-bounded (``bounded_wait`` with no cap):
    the gateway's server loop parks on the next request by design (no
    ambient budget), and the client pump runs detached (deadline-free
    — its budget lives on each ``call()``'s bounded reply wait)."""
    chunks: list[bytes] = []
    total = 0
    while True:
        hdr = await bounded_wait(reader.readexactly(4))
        (word,) = struct.unpack(">I", hdr)
        last, flen = bool(word & 0x80000000), word & 0x7FFFFFFF
        total += flen
        if total > MAX_RECORD:
            raise XdrError(f"RPC record too long: {total}")
        chunks.append(await bounded_wait(reader.readexactly(flen)))
        if last:
            return b"".join(chunks)


def frame_record(payload: bytes) -> bytes:
    return struct.pack(">I", 0x80000000 | len(payload)) + payload


def _reply_header(xid: int) -> Packer:
    p = Packer()
    p.u32(xid).u32(REPLY).u32(MSG_ACCEPTED)
    p.u32(AUTH_NONE).u32(0)  # verifier
    return p


def accepted_reply(xid: int, result: bytes) -> bytes:
    return _reply_header(xid).u32(SUCCESS).raw(result).bytes()


def error_reply(xid: int, accept_stat: int) -> bytes:
    return _reply_header(xid).u32(accept_stat).bytes()


class RpcServer:
    """TCP ONC-RPC server dispatching to registered program handlers."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.host, self.port = host, port
        self._programs: dict[tuple[int, int], object] = {}
        self._server: asyncio.AbstractServer | None = None

    def register(self, prog: int, vers: int, handler) -> None:
        """handler: async (proc: int, cred: Credential, args: Unpacker) -> bytes.
        Raise ProcUnavail to signal an unknown procedure."""
        self._programs[(prog, vers)] = handler

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._serve_conn, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            try:
                # 3.12+ wait_closed also waits for live handlers; a
                # client parked in read_record must not wedge (or, past
                # the cap, crash) gateway teardown
                await asyncio.wait_for(self._server.wait_closed(), 5.0)
            except asyncio.TimeoutError:
                pass
            self._server = None

    async def _serve_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        """NFS clients multiplex many outstanding ops on one TCP
        connection; dispatch each record as its own task (replies may
        reorder — xids pair them) and serialize only the writes."""
        peer = writer.get_extra_info("peername")
        wlock = asyncio.Lock()
        inflight: set[asyncio.Task] = set()

        async def run_one(record: bytes) -> None:
            try:
                reply = await self._dispatch(record)
                if reply is None:
                    return
                async with wlock:
                    writer.write(frame_record(reply))
                    # ambient-bounded: gateway ops run under the
                    # cluster client's deadlines; a reply to a wedged
                    # NFS client charges that budget, not forever
                    await bounded_wait(writer.drain())
            except (ConnectionError, OSError):
                pass  # peer went away mid-reply
            except XdrError as e:
                log.warning("nfs rpc: bad record from %s: %s", peer, e)

        try:
            while True:
                try:
                    record = await read_record(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    return
                task = asyncio.ensure_future(run_one(record))
                inflight.add(task)
                task.add_done_callback(inflight.discard)
                if len(inflight) >= 64:  # backpressure: stop reading
                    # lint: waive(unbounded-await): parks on our OWN dispatch tasks, each bounded by the cluster client's op deadlines — a cap here would drop records instead of applying backpressure
                    _, pending = await asyncio.wait(
                        inflight, return_when=asyncio.FIRST_COMPLETED
                    )
        except XdrError as e:
            log.warning("nfs rpc: dropping %s: %s", peer, e)
        except Exception:
            log.exception("nfs rpc: connection error from %s", peer)
        finally:
            for t in inflight:
                t.cancel()
            await close_writer(writer)

    async def _dispatch(self, record: bytes) -> bytes | None:
        u = Unpacker(record)
        xid = u.u32()
        if u.u32() != CALL:
            return None  # ignore stray replies
        if u.u32() != RPC_VERSION:
            # RPC_MISMATCH denial
            p = Packer()
            p.u32(xid).u32(REPLY).u32(MSG_DENIED).u32(0).u32(2).u32(2)
            return p.bytes()
        prog, vers, proc = u.u32(), u.u32(), u.u32()
        cred_flavor = u.u32()
        cred_body = u.opaque(400)
        u.u32()  # verf flavor
        u.opaque(400)  # verf body
        if cred_flavor == AUTH_SYS:
            cred = parse_auth_sys(cred_body)
        else:
            # no credential != root: anonymous callers run as nobody
            cred = Credential(uid=65534, gid=65534)
        handler = self._programs.get((prog, vers))
        if handler is None:
            return error_reply(xid, PROG_UNAVAIL)
        try:
            result = await handler(proc, cred, u)
        except ProcUnavail:
            return error_reply(xid, PROC_UNAVAIL)
        except XdrError:
            return error_reply(xid, GARBAGE_ARGS)
        except Exception:
            log.exception("nfs rpc: handler error prog=%d proc=%d", prog, proc)
            return error_reply(xid, SYSTEM_ERR)
        return accepted_reply(xid, result)


class ProcUnavail(Exception):
    pass


class RpcClient:
    """Minimal ONC-RPC TCP client (tests + in-repo tooling)."""

    def __init__(self, host: str, port: int, cred: Credential | None = None):
        self.host, self.port = host, port
        self.cred = cred or Credential()
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._xid = 1
        # xid-demuxed reply pump: real kernel clients keep MANY calls
        # outstanding on one connection (wsize/rsize deep pipelines);
        # serial request/response here would make every benchmark and
        # multi-gateway drive understate the gateway by the RTT count
        self._pending: dict[int, asyncio.Future] = {}
        self._pump_task: asyncio.Task | None = None
        self._pump_dead = False
        # serialize write+drain: concurrent drain() waiters crash on
        # Python < 3.12 (FlowControlMixin asserts a single waiter)
        self._send_lock: asyncio.Lock | None = None

    async def connect(self) -> None:
        # dial bound like every other dial in the tree (gateway startup
        # additionally retries under a 30 s RetryPolicy budget)
        self._reader, self._writer = await asyncio.wait_for(
            asyncio.open_connection(self.host, self.port), 5.0
        )
        self._pump_dead = False
        self._send_lock = asyncio.Lock()
        # detached: the pump outlives any RetryPolicy attempt that
        # dialed this connection — read_record is ambient-deadline-
        # bounded now, and a pump that inherited the attempt's budget
        # would start timing out the moment the budget expired
        self._pump_task = spawn_detached(self._pump())

    async def _pump(self) -> None:
        try:
            while True:
                record = await read_record(self._reader)
                u = Unpacker(record)
                rxid = u.u32()
                fut = self._pending.pop(rxid, None)
                if fut is not None and not fut.done():
                    fut.set_result(u)
        except (asyncio.CancelledError, Exception) as e:  # noqa: BLE001
            # flag FIRST: a call() registering after this cleanup must
            # fail fast instead of awaiting a future nobody will resolve
            self._pump_dead = True
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError(f"rpc link lost: {e!r}"))
            self._pending.clear()
            if isinstance(e, asyncio.CancelledError):
                raise

    async def close(self) -> None:
        if self._pump_task is not None:
            self._pump_task.cancel()
            try:
                await self._pump_task
            except (asyncio.CancelledError, ConnectionError):
                pass
            self._pump_task = None
        if self._writer is not None:
            await close_writer(self._writer)
            self._writer = None

    def _cred_bytes(self) -> bytes:
        c = Packer()
        c.u32(0).string(self.cred.machine or "pyclient")
        c.u32(self.cred.uid).u32(self.cred.gid)
        c.u32(len(self.cred.gids))
        for g in self.cred.gids:
            c.u32(g)
        return c.bytes()

    async def call(self, prog: int, vers: int, proc: int, args: bytes) -> Unpacker:
        assert self._writer is not None, "not connected"
        if self._pump_dead:
            raise ConnectionError("rpc link lost")
        self._xid += 1
        xid = self._xid
        fut = asyncio.get_running_loop().create_future()
        self._pending[xid] = fut
        p = Packer()
        p.u32(xid).u32(CALL).u32(RPC_VERSION)
        p.u32(prog).u32(vers).u32(proc)
        p.u32(AUTH_SYS).opaque(self._cred_bytes())
        p.u32(AUTH_NONE).u32(0)
        p.raw(args)
        try:
            async with self._send_lock:
                self._writer.write(frame_record(p.bytes()))
                await bounded_wait(self._writer.drain())
            # bounded reply wait: the pump is detached (deadline-free
            # by design), so the budget must live HERE — a gateway
            # that consumes the request and never answers charges the
            # caller min(ambient deadline, 30 s), not forever
            u = await bounded_wait(fut, 30.0)
        finally:
            self._pending.pop(xid, None)
        if u.u32() != REPLY:
            raise XdrError("bad RPC reply header")
        if u.u32() != MSG_ACCEPTED:
            raise XdrError("RPC call denied")
        u.u32()
        u.opaque(400)  # verifier
        stat = u.u32()
        if stat != SUCCESS:
            raise XdrError(f"RPC accept_stat {stat}")
        return u
