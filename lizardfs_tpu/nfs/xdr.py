"""XDR (RFC 4506) encoding primitives for the ONC-RPC/NFS gateway.

Minimal by design: the NFS3/MOUNT3 wire structures only need big-endian
u32/u64, opaque byte strings padded to 4 bytes, and optional/list
combinators. Reference semantics: src/nfs-ganesha/ speaks these via
Ganesha's bundled XDR; here the codec is ~80 lines and allocation-light.
"""

from __future__ import annotations

import struct


class XdrError(Exception):
    pass


class Packer:
    __slots__ = ("_parts",)

    def __init__(self) -> None:
        self._parts: list[bytes] = []

    def u32(self, v: int) -> "Packer":
        self._parts.append(struct.pack(">I", v & 0xFFFFFFFF))
        return self

    def i32(self, v: int) -> "Packer":
        self._parts.append(struct.pack(">i", v))
        return self

    def u64(self, v: int) -> "Packer":
        self._parts.append(struct.pack(">Q", v & 0xFFFFFFFFFFFFFFFF))
        return self

    def boolean(self, v: bool) -> "Packer":
        return self.u32(1 if v else 0)

    def opaque(self, data: bytes) -> "Packer":
        """Variable-length opaque: length + bytes + pad to 4."""
        self.u32(len(data))
        return self.fixed(data)

    def fixed(self, data: bytes) -> "Packer":
        """Fixed-length opaque: bytes + pad to 4 (length implied)."""
        self._parts.append(data)
        if len(data) % 4:
            self._parts.append(b"\x00" * (4 - len(data) % 4))
        return self

    def string(self, s: str) -> "Packer":
        return self.opaque(s.encode("utf-8", "surrogateescape"))

    def raw(self, data: bytes) -> "Packer":
        self._parts.append(data)
        return self

    def bytes(self) -> bytes:
        return b"".join(self._parts)


class Unpacker:
    __slots__ = ("_buf", "_pos")

    def __init__(self, buf: bytes) -> None:
        self._buf = buf
        self._pos = 0

    def _take(self, n: int) -> bytes:
        if self._pos + n > len(self._buf):
            raise XdrError(f"short XDR buffer: need {n} at {self._pos}")
        out = self._buf[self._pos : self._pos + n]
        self._pos += n
        return out

    def u32(self) -> int:
        return struct.unpack(">I", self._take(4))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack(">Q", self._take(8))[0]

    def boolean(self) -> bool:
        return self.u32() != 0

    def opaque(self, max_len: int = 1 << 26) -> bytes:
        n = self.u32()
        if n > max_len:
            raise XdrError(f"opaque too long: {n} > {max_len}")
        data = self._take(n)
        if n % 4:
            self._take(4 - n % 4)
        return data

    def fixed(self, n: int) -> bytes:
        data = self._take(n)
        if n % 4:
            self._take(4 - n % 4)
        return data

    def string(self, max_len: int = 4096) -> str:
        return self.opaque(max_len).decode("utf-8", "surrogateescape")

    def done(self) -> bool:
        return self._pos >= len(self._buf)

    def remaining(self) -> bytes:
        return self._buf[self._pos :]
