"""NFSv3 + MOUNT3 gateway server (RFC 1813) backed by the cluster client.

Role parity with the reference's NFS-Ganesha FSAL
(src/nfs-ganesha/main.c, handle.c, export.c ~4.2k LoC): expose the
filesystem to standard NFS clients with per-RPC AUTH_SYS identity
enforced by the master. Instead of plugging into an external Ganesha
daemon, the gateway embeds the protocol server itself: one asyncio
process, one cluster ``Client`` connection shared by all NFS consumers
(identity travels per-call, like Ganesha's op_ctx credentials).

File handles are stable ``b"LZFH" + u32 inode`` — the master's inode
space is flat and persistent, so handles survive gateway restarts (the
FSAL's wire-handle round-trip, src/nfs-ganesha/handle.c
lzfs_fsal_wire_to_host analog).
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import secrets
import struct
import time

from lizardfs_tpu.client.cache import ReadaheadAdviser
from lizardfs_tpu.client.client import Client
from lizardfs_tpu.constants import EATTR_NOENTRYCACHE, MFSBLOCKSIZE
from lizardfs_tpu.nfs import rpc
from lizardfs_tpu.nfs.xdr import Packer, Unpacker
from lizardfs_tpu.proto import messages as m
from lizardfs_tpu.proto import status as st
from lizardfs_tpu.runtime import accounting
from lizardfs_tpu.runtime import profiler as profmod
from lizardfs_tpu.runtime import retry as retrymod
from lizardfs_tpu.runtime import slo as slomod
from lizardfs_tpu.runtime import tracing
from lizardfs_tpu.runtime.metrics import Metrics
from lizardfs_tpu.runtime.tweaks import Tweaks

log = logging.getLogger("lizardfs.nfs")

PROG_PORTMAP, PROG_NFS, PROG_MOUNT = 100000, 100003, 100005
ROOT_INODE = 1

# NFS3 status codes (RFC 1813 §2.6)
NFS3_OK = 0
NFS3ERR_PERM = 1
NFS3ERR_NOENT = 2
NFS3ERR_IO = 5
NFS3ERR_NXIO = 6
NFS3ERR_ACCES = 13
NFS3ERR_EXIST = 17
NFS3ERR_NOTDIR = 20
NFS3ERR_ISDIR = 21
NFS3ERR_INVAL = 22
NFS3ERR_FBIG = 27
NFS3ERR_NOSPC = 28
NFS3ERR_ROFS = 30
NFS3ERR_MLINK = 31
NFS3ERR_NAMETOOLONG = 63
NFS3ERR_NOTEMPTY = 66
NFS3ERR_DQUOT = 69
NFS3ERR_STALE = 70
NFS3ERR_BADHANDLE = 10001
NFS3ERR_NOT_SYNC = 10002
NFS3ERR_BAD_COOKIE = 10003
NFS3ERR_NOTSUPP = 10004
NFS3ERR_TOOSMALL = 10005
NFS3ERR_SERVERFAULT = 10006
# RFC 1813 §2.6: "the server initiated the request, but was not able
# to complete it in a timely fashion ... retry later" — the jukebox
# (near-line media) delay code every NFS client honors with backoff.
# QoS fair-share sheds (st.BUSY) map here: back off, retry, never fail.
NFS3ERR_JUKEBOX = 10008

_STATUS_MAP = {
    st.OK: NFS3_OK,
    st.EPERM: NFS3ERR_PERM,
    st.ENOENT: NFS3ERR_NOENT,
    st.EACCES: NFS3ERR_ACCES,
    st.EEXIST: NFS3ERR_EXIST,
    st.EINVAL: NFS3ERR_INVAL,
    st.ENOTDIR: NFS3ERR_NOTDIR,
    st.EISDIR: NFS3ERR_ISDIR,
    st.ENOSPC: NFS3ERR_NOSPC,
    st.EIO: NFS3ERR_IO,
    st.ENOTEMPTY: NFS3ERR_NOTEMPTY,
    st.QUOTA_EXCEEDED: NFS3ERR_DQUOT,
    st.NAME_TOO_LONG: NFS3ERR_NAMETOOLONG,
    st.EROFS: NFS3ERR_ROFS,
    st.NO_CHUNK: NFS3ERR_STALE,
    st.BUSY: NFS3ERR_JUKEBOX,
}

# ftype (proto) -> NF3 type
_NF3 = {m.FTYPE_FILE: 1, m.FTYPE_DIR: 2, m.FTYPE_SYMLINK: 5}

# ACCESS3 request bits
ACCESS3_READ = 0x01
ACCESS3_LOOKUP = 0x02
ACCESS3_MODIFY = 0x04
ACCESS3_EXTEND = 0x08
ACCESS3_DELETE = 0x10
ACCESS3_EXECUTE = 0x20


class _NfsError(Exception):
    def __init__(self, code: int):
        self.code = code


def _nfs_code(e: st.StatusError) -> int:
    return _STATUS_MAP.get(e.code, NFS3ERR_IO)


def fh_pack(inode: int) -> bytes:
    return struct.pack(">4sI", b"LZFH", inode)


def fh_unpack(handle: bytes) -> int:
    if len(handle) != 8 or handle[:4] != b"LZFH":
        raise _NfsError(NFS3ERR_BADHANDLE)
    return struct.unpack(">I", handle[4:])[0]


def _pack_fattr3(p: Packer, a: m.Attr) -> None:
    p.u32(_NF3.get(a.ftype, 1))
    p.u32(a.mode & 0o7777)
    p.u32(max(a.nlink, 1))
    p.u32(a.uid).u32(a.gid)
    p.u64(a.length)
    p.u64((a.length + MFSBLOCKSIZE - 1) // MFSBLOCKSIZE * MFSBLOCKSIZE)
    p.u32(0).u32(0)  # rdev
    p.u64(0x4C5A4653)  # fsid ("LZFS")
    p.u64(a.inode)
    p.u32(a.atime).u32(0)
    p.u32(a.mtime).u32(0)
    p.u32(a.ctime).u32(0)


def _post_op_attr(p: Packer, a: m.Attr | None) -> None:
    if a is None:
        p.boolean(False)
    else:
        p.boolean(True)
        _pack_fattr3(p, a)


def _wcc_data(p: Packer, post: m.Attr | None) -> None:
    p.boolean(False)  # pre_op_attr: not tracked
    _post_op_attr(p, post)


class _Sattr3:
    """Decoded sattr3: which attributes a SETATTR/CREATE wants to set."""

    def __init__(self, u: Unpacker):
        self.mode = u.u32() if u.boolean() else None
        self.uid = u.u32() if u.boolean() else None
        self.gid = u.u32() if u.boolean() else None
        self.size = u.u64() if u.boolean() else None
        how = u.u32()  # atime
        self.atime = None
        if how == 1:
            self.atime = int(time.time())
        elif how == 2:
            self.atime = u.u32()
            u.u32()
        how = u.u32()  # mtime
        self.mtime = None
        if how == 1:
            self.mtime = int(time.time())
        elif how == 2:
            self.mtime = u.u32()
            u.u32()

    def set_mask(self) -> tuple[int, dict]:
        mask, kw = 0, {}
        if self.mode is not None:
            mask |= 1
            kw["mode"] = self.mode & 0o7777
        if self.uid is not None:
            mask |= 2
            kw["uid"] = self.uid
        if self.gid is not None:
            mask |= 4
            kw["gid"] = self.gid
        if self.atime is not None:
            mask |= 8
            kw["atime"] = self.atime
        if self.mtime is not None:
            mask |= 16
            kw["mtime"] = self.mtime
        return mask, kw


class _WriteGather:
    """Write-behind buffer for one inode's UNSTABLE writes.

    Sequential 64 KiB WRITEs coalesce into contiguous runs that flush
    as few large pwrites (one striped RMW per run instead of one per
    wire op). The analog of knfsd/Ganesha write gathering; COMMIT and
    any dependent read/attr op force the flush (RFC 1813 §3.3.7/21).
    """

    def __init__(self) -> None:
        self.segs: list[tuple[int, bytearray]] = []  # sorted, disjoint
        self.nbytes = 0
        self.last_add = 0.0

    def try_add(self, offset: int, data: bytes) -> bool:
        """Append/merge; False when the write overlaps existing segments
        (caller flushes first — overlap means a retransmit or random
        rewrite, both rare)."""
        self.last_add = time.monotonic()
        new_end = offset + len(data)
        # overlap check FIRST, against every segment: a merge that runs
        # a segment over a later one would flush stale bytes on top of
        # newer ones
        for start, buf in self.segs:
            if offset < start + len(buf) and new_end > start:
                return False
        for i, (start, buf) in enumerate(self.segs):
            end = start + len(buf)
            if offset == end:
                buf.extend(data)
                # merge with the next segment if we just bridged the gap
                if (i + 1 < len(self.segs)
                        and start + len(buf) == self.segs[i + 1][0]):
                    buf.extend(self.segs[i + 1][1])
                    del self.segs[i + 1]
                self.nbytes += len(data)
                return True
            if new_end == start:
                self.segs[i] = (offset, bytearray(data) + buf)
                self.nbytes += len(data)
                return True
        self.segs.append((offset, bytearray(data)))
        self.segs.sort(key=lambda s: s[0])
        self.nbytes += len(data)
        return True

    @property
    def end(self) -> int:
        return max((s + len(b) for s, b in self.segs), default=0)


class NfsGateway:
    """One process serving MOUNT3 + NFS3 (and a local portmapper view).

    ``exports`` maps export path -> cluster path ("/" by default). The
    master still enforces its own exports/session ACLs on every op via
    the per-RPC AUTH_SYS identity.
    """

    def __init__(
        self,
        master_host: str,
        master_port: int,
        host: str = "127.0.0.1",
        port: int = 0,
        exports: dict[str, str] | None = None,
    ) -> None:
        # one gateway-local registry shared with the embedded Client:
        # the write window's depth/credit/coalesce series land next to
        # the gateway's SLO gauges, so whatever scrapes this registry
        # sees the whole write-path story (the client would otherwise
        # hold them in a private registry nothing exports)
        self.metrics = Metrics()
        self.client = Client(master_host, master_port, metrics=self.metrics)
        self.rpc = rpc.RpcServer(host, port)
        self.exports = exports or {"/": "/"}
        self.write_verf = secrets.token_bytes(8)
        self._mounts: set[tuple[str, str]] = set()
        # export-root inodes, resolved at MNT time: ".." clamps here so
        # a mount can't walk above its export (master-side subtree
        # sessions clamp too when the gateway session itself is rooted).
        # Like knfsd's default no_subtree_check, handles *guessed* for
        # inodes outside an export are not rejected — use master-side
        # subtree exports for hard isolation.
        self._export_roots: set[int] = set()
        # UNSTABLE write gathering: inode -> buffered segments; flushed
        # on COMMIT / stable writes / dependent ops / idle timer / size
        # caps. Serialized per inode so a flush never races an add.
        self._gather: dict[int, _WriteGather] = {}
        self._gather_locks: dict[int, asyncio.Lock] = {}
        self._gather_total = 0  # bytes buffered across all inodes
        self._gather_task: asyncio.Task | None = None
        self.GATHER_FLUSH_BYTES = 8 * 2**20     # per inode
        self.GATHER_TOTAL_BYTES = 64 * 2**20    # whole gateway
        self.GATHER_IDLE_S = 1.0
        # server-side readahead (r04 weak #3: cold per-READ path read at
        # half the gateway's own write speed): per-inode sequentiality
        # detector + one buffered span ahead of the stream, refilled
        # under a per-inode lock so 8 pipelined 64 KiB READs cost one
        # back-end fetch, not 8. Coherence: an invalidate-listener on
        # the client's BlockCache drops the span on ANY invalidation
        # (local write/truncate or master push from another gateway's
        # mutation) + a TTL backstop mirroring the BlockCache's.
        self._ra: dict[int, list] = {}  # inode -> [adviser, off, buf, ts]
        self._ra_locks: dict[int, asyncio.Lock] = {}
        self._ra_epoch: dict[int, int] = {}  # bumped by every drop
        # sequentiality detectors OUTLIVE the spans: a write invalidates
        # cached bytes, not the fact that the reader is streaming
        self._ra_advisers: dict[int, ReadaheadAdviser] = {}
        self.RA_WINDOW_MAX = 4 * 2**20   # per inode
        self.RA_TOTAL_BYTES = 64 * 2**20  # whole gateway
        self.RA_TTL_S = 1.0
        self._ra_total = 0
        # access/attr decision caches: without them every wire READ or
        # WRITE pays 1-2 master RPCs (access + getattr) — kernel NFS
        # servers/clients cache both far longer than this TTL. Both are
        # dropped per inode by (a) the invalidate listener (local
        # writes + master pushes — the master pushes on metadata
        # mutations too: chmod/setattr/seteattr/ACL changes via ANY
        # session revoke these caches promptly) and (b) _meta_dirty()
        # after every metadata-mutating proc THIS gateway serves;
        # the TTL remains the backstop for sessions whose watch
        # subscription on the inode has expired master-side.
        self._access_cache: dict[int, dict[tuple, tuple[bool, float]]] = {}
        self._access_cache_n = 0
        self._attr_cache: dict[int, tuple[object, float]] = {}
        # META_TTL_S is the operator-tunable consistency knob (ADVICE
        # r05 item 4): the master now pushes invalidations on metadata
        # mutations too (chmod/setattr/seteattr/ACLs), so cross-gateway
        # revocation is push-prompt for watched inodes; the TTL bounds
        # staleness only when the watch subscription expired. Still a
        # runtime tweak so operators can trade residual lag against
        # master RPC load without a restart; 0 disables the caches.
        # See doc/operations.md.
        self.tweaks = Tweaks()
        self._meta_ttl = self.tweaks.register("meta_ttl_s", 1.0)
        self.client.cache.add_invalidate_listener(self._on_invalidate)
        # NFS joins the trace domain: every dispatched proc begins (or
        # joins) a trace at the wire boundary, so the id propagates
        # through the shared Client into master RPCs and the data
        # plane — the last anonymous entry point closed. The op's
        # boundary span lands in the client's ring under role "nfs".
        # The "nfs" SLO class accounts per-proc latency; the registry
        # (self.metrics, created up top and shared with the Client) is
        # gateway-local (no admin port on the gateway), the flight
        # recorder's slowops stay queryable in-process.
        self.slo = slomod.SloEngine(
            self.metrics, role="nfs",
            span_source=self.client.trace_ring.dump,
        )
        # per-session protocol-op accounting (runtime/accounting.py):
        # every NFS proc charges the gateway's cluster session under an
        # "nfs_<proc>" class; the top-K summary is pushed to the master
        # (CltomaSessionStats) so `lizardfs-admin top` names what this
        # front door is doing. The embedded Client's own session_ops
        # (logical read/write) share the same registry.
        self.session_ops = accounting.SessionOps(
            self.metrics, "nfs", max_sessions=8
        )
        self.stats_push_interval_s = 5.0
        self._stats_task: asyncio.Task | None = None
        # always-on sampling profiler (runtime/profiler.py; the
        # process-wide shared instance), dumped at GET /profile on the
        # HTTP observability listener
        self.profiler = profmod.process_profiler(role="nfs")
        self.slo.profiler = self.profiler
        self.slo.recorder.profile_source = self.profiler.collapsed
        # HTTP observability endpoint (the S3 gateway serves /metrics +
        # /healthz on its protocol port; NFS can't — the wire speaks
        # ONC-RPC — so a sibling listener owns them). http_port=0
        # binds an ephemeral port (read it back after start()); None
        # disables the listener.
        self.http_host = host
        self.http_port: int | None = 0
        self._http_server: asyncio.Server | None = None

    @property
    def port(self) -> int:
        return self.rpc.port

    # kept as an attribute-style accessor for existing call sites and
    # tests; assignment routes through the tweak so `tweaks`/`META_TTL_S`
    # can never disagree
    @property
    def META_TTL_S(self) -> float:
        return float(self._meta_ttl.value)

    @META_TTL_S.setter
    def META_TTL_S(self, value: float) -> None:
        self._meta_ttl.value = float(value)

    def _lock_entry(self, inode: int) -> list:
        # [lock, refcount] — dropped when nobody holds or awaits it
        # (same pattern as the client's per-chunk write locks)
        e = self._gather_locks.get(inode)
        if e is None:
            e = self._gather_locks[inode] = [asyncio.Lock(), 0]
        return e

    async def _flush_locked(self, inode: int) -> None:
        """Write out the inode's gathered segments; caller holds its
        gather lock. On failure the unwritten segments are RE-QUEUED —
        the server has acked these bytes as UNSTABLE, and dropping them
        while write_verf stays unchanged would make the client discard
        its only copy (RFC 1813 verifier contract)."""
        g = self._gather.pop(inode, None)
        if g is None:
            return
        self._gather_total -= g.nbytes
        for i, (start, buf) in enumerate(g.segs):
            try:
                await self.client.pwrite(inode, start, bytes(buf))
            except Exception:
                requeue = _WriteGather()
                requeue.segs = g.segs[i:]  # current run is idempotent
                requeue.nbytes = sum(len(b) for _, b in requeue.segs)
                requeue.last_add = time.monotonic()
                self._gather[inode] = requeue
                self._gather_total += requeue.nbytes
                raise

    async def _flush_inode(self, inode: int) -> None:
        """Write out an inode's gathered UNSTABLE segments (no-op when
        nothing is buffered)."""
        if inode not in self._gather:
            return
        e = self._lock_entry(inode)
        e[1] += 1
        try:
            async with e[0]:
                await self._flush_locked(inode)
        finally:
            e[1] -= 1
            if e[1] == 0 and self._gather_locks.get(inode) is e:
                del self._gather_locks[inode]

    async def _flush_all(self) -> None:
        for inode in list(self._gather):
            await self._flush_inode(inode)

    async def _gather_sweep(self) -> None:
        """Bound the write-behind window: idle inodes flush after
        GATHER_IDLE_S even without a COMMIT. The task must survive ANY
        flush error (a dead master connection raises ConnectionError,
        not StatusError) — data stays queued and retries next tick."""
        while True:
            await asyncio.sleep(self.GATHER_IDLE_S / 2)
            now = time.monotonic()
            for inode, g in list(self._gather.items()):
                if now - g.last_add >= self.GATHER_IDLE_S:
                    try:
                        await self._flush_inode(inode)
                    except asyncio.CancelledError:
                        raise
                    except Exception:  # noqa: BLE001
                        log.exception("idle flush failed for %d", inode)
            # readahead hygiene: expire stale spans, then drop idle
            # per-inode locks/epochs (an unlocked inode with no span
            # needs neither — the next READ recreates both)
            for inode, e in list(self._ra.items()):
                if now - e[3] > self.RA_TTL_S:
                    self._ra_drop(inode)
            for inode, lock in list(self._ra_locks.items()):
                if not lock.locked() and inode not in self._ra:
                    del self._ra_locks[inode]
                    self._ra_epoch.pop(inode, None)
                    self._ra_advisers.pop(inode, None)

    async def start(self) -> None:
        # unified RetryPolicy: a gateway racing master startup (or an
        # election) retries under one 30 s end-to-end budget instead of
        # dying on the first refused connect; every dial the nested
        # Client.connect makes inherits the same deadline
        await retrymod.RetryPolicy(
            attempts=10, base_delay=0.2, max_delay=2.0, deadline=30.0,
        ).run(
            lambda: self.client.connect(info="nfs-gateway"),
            what="nfs gateway master connect", log=log,
        )
        self._gather_task = asyncio.ensure_future(self._gather_sweep())
        for target in self.exports.values():
            # pre-resolve export roots: clients reusing cached handles
            # after a gateway restart never re-MNT
            try:
                root = await self.client.resolve(target)
                self._export_roots.add(root.inode)
            except st.StatusError:
                pass  # export target may be created later; MNT re-resolves
        self.rpc.register(PROG_MOUNT, 3, self._mount_dispatch)
        self.rpc.register(PROG_NFS, 3, self._nfs_dispatch)
        self.rpc.register(PROG_PORTMAP, 2, self._portmap_dispatch)
        await self.rpc.start()
        if self.http_port is not None:
            self._http_server = await asyncio.start_server(
                self._http_conn, self.http_host, self.http_port
            )
            self.http_port = self._http_server.sockets[0].getsockname()[1]
            log.info("nfs observability endpoint on port %d", self.http_port)
        self.profiler.start()  # no-op under LZ_PROF=0
        self._stats_task = asyncio.ensure_future(self._stats_push_loop())
        log.info("nfs gateway on port %d", self.port)

    async def stop(self) -> None:
        for task in (self._gather_task, self._stats_task):
            if task is not None:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
        self.profiler.stop()
        if self._http_server is not None:
            self._http_server.close()
            try:
                await asyncio.wait_for(self._http_server.wait_closed(), 5.0)
            except asyncio.TimeoutError:
                pass
        try:
            await self._flush_all()
        except Exception:  # noqa: BLE001 — still stop cleanly
            log.exception("final gather flush failed")
        await self.rpc.stop()
        await self.client.close()

    # --- HTTP observability endpoint (/metrics, /healthz, /profile) ------

    def _stats_doc(self) -> dict:
        """The workload summary pushed to the master and served at
        /top: protocol-op mix (this gateway's SessionOps) + the logical
        data-op view the embedded Client accounts."""
        return {
            "role": "nfs",
            "endpoint": f"{self.rpc.host}:{self.port}",
            "http_port": self.http_port,
            "protocol": self.session_ops.top(8),
            "data": self.client.session_ops.top(8),
        }

    def _healthz_doc(self) -> dict:
        return {
            "role": "nfs",
            "status": self.slo.status() if slomod.enabled() else "ok",
            "slo": self.slo.snapshot() if slomod.enabled() else {},
            "slow_ops": len(self.slo.recorder.slowops()),
            "session": self.client.session_id,
            "mounts": len(self._mounts),
        }

    async def _http_conn(self, reader, writer) -> None:
        """Minimal one-shot HTTP/1.0-style server: GET /metrics (the
        Prometheus scrape surface the S3 gateway already has),
        /healthz (probe JSON), /profile (collapsed flamegraph stacks),
        /top (this gateway's per-session summary)."""
        import json as _json

        try:
            line = await retrymod.bounded_wait(reader.readline(), 10.0)
            try:
                method, target, _ = line.decode("ascii").split(" ", 2)
            except (UnicodeDecodeError, ValueError):
                return
            while True:  # drain headers
                hl = await retrymod.bounded_wait(reader.readline(), 10.0)
                if hl in (b"\r\n", b"\n", b""):
                    break
            path = target.split("?", 1)[0]
            code, ctype, body = 404, "text/plain", b"not found\n"
            if method == "GET" and path == "/metrics":
                code, ctype, body = (
                    200, "text/plain; version=0.0.4; charset=utf-8",
                    self.metrics.to_prometheus().encode(),
                )
            elif method == "GET" and path == "/healthz":
                code, ctype, body = (
                    200, "application/json",
                    _json.dumps(self._healthz_doc()).encode(),
                )
            elif method == "GET" and path == "/profile":
                doc = self.profiler.snapshot()
                doc["role"] = "nfs"  # process-wide sampler, this surface
                doc["collapsed"] = self.profiler.collapsed()
                code, ctype, body = (
                    200, "application/json", _json.dumps(doc).encode(),
                )
            elif method == "GET" and path == "/top":
                code, ctype, body = (
                    200, "application/json",
                    _json.dumps(self._stats_doc()).encode(),
                )
            writer.write(
                (
                    f"HTTP/1.1 {code} {'OK' if code == 200 else 'NF'}\r\n"
                    f"Content-Type: {ctype}\r\n"
                    f"Content-Length: {len(body)}\r\n"
                    "Connection: close\r\n\r\n"
                ).encode("latin-1") + body
            )
            await asyncio.wait_for(writer.drain(), 10.0)
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.TimeoutError):
            pass
        finally:
            await retrymod.close_writer(writer, swallow_cancel=True)

    def _stats_push_loop(self):
        """The shared gateway push contract (CltomaSessionStats every
        few seconds — runtime/accounting.py owns the loop so the NFS
        and S3 gateways cannot drift apart on it)."""
        return accounting.gateway_stats_push_loop(
            self.client, self._stats_doc, self.stats_push_interval_s, log
        )

    # --- portmapper (RFC 1833 v2): just enough for clients probing us ----

    async def _portmap_dispatch(
        self, proc: int, cred: rpc.Credential, u: Unpacker
    ) -> bytes:
        if proc == 0:
            return b""
        if proc == 3:  # GETPORT
            prog, _vers = u.u32(), u.u32()
            port = self.port if prog in (PROG_NFS, PROG_MOUNT) else 0
            return Packer().u32(port).bytes()
        raise rpc.ProcUnavail

    # --- MOUNT3 ----------------------------------------------------------

    async def _mount_dispatch(
        self, proc: int, cred: rpc.Credential, u: Unpacker
    ) -> bytes:
        if proc == 0:  # NULL
            return b""
        if proc == 1:  # MNT
            path = u.string()
            target = self.exports.get(path) or self.exports.get(
                path.rstrip("/") or "/"
            )
            p = Packer()
            if target is None:
                return p.u32(NFS3ERR_NOENT).bytes()
            try:
                attr = await self.client.resolve(target)
            except st.StatusError as e:
                return p.u32(_nfs_code(e)).bytes()
            self._mounts.add((cred.machine, path))
            self._export_roots.add(attr.inode)
            p.u32(NFS3_OK).opaque(fh_pack(attr.inode))
            p.u32(1).u32(rpc.AUTH_SYS)  # auth flavors
            return p.bytes()
        if proc == 3:  # UMNT
            path = u.string()
            self._mounts.discard((cred.machine, path))
            return b""
        if proc == 4:  # UMNTALL
            self._mounts = {mt for mt in self._mounts if mt[0] != cred.machine}
            return b""
        if proc == 5:  # EXPORT
            p = Packer()
            for path in self.exports:
                p.boolean(True).string(path).boolean(False)  # no group list
            p.boolean(False)
            return p.bytes()
        raise rpc.ProcUnavail

    # --- NFS3 ------------------------------------------------------------

    async def _nfs_dispatch(
        self, proc: int, cred: rpc.Credential, u: Unpacker
    ) -> bytes:
        handler = self._PROCS.get(proc)
        if handler is None:
            raise rpc.ProcUnavail
        # trace boundary: the NFS proc is the request's root — the id
        # issued here rides every client->master RPC and data-plane
        # frame this op triggers (tracing.begin joins a caller-held
        # trace, which never exists on a fresh RPC task)
        tid, fresh = tracing.begin()
        name = "nfs_" + handler.__name__.removeprefix("_proc_")
        t0 = time.perf_counter()
        tw0 = time.time()
        try:
            return await handler(self, cred, u)
        except _NfsError as e:
            return self._plain_error(proc, e.code)
        except st.StatusError as e:
            return self._plain_error(proc, _nfs_code(e))
        finally:
            dt = time.perf_counter() - t0
            self.client.trace_ring.record(
                tid, name, tw0, time.time(), role="nfs"
            )
            self.slo.observe("nfs", dt, trace_id=tid, name=name)
            # per-session protocol accounting: the proc charged to this
            # gateway's cluster session, pushed to the master's `top`
            self.session_ops.record(
                self.client.session_id, name, dt, trace_id=tid
            )
            tracing.end(fresh)

    def _plain_error(self, proc: int, code: int) -> bytes:
        """Error reply with empty/absent optional attr fields, shaped per
        procedure class (most carry post_op_attr; dir-modifying ops carry
        wcc_data; RENAME/LINK carry two)."""
        p = Packer().u32(code)
        if proc in (7, 8, 9, 10, 11, 12, 13, 21):  # wcc_data
            _wcc_data(p, None)
        elif proc == 14:  # RENAME: two wcc_data
            _wcc_data(p, None)
            _wcc_data(p, None)
        elif proc == 15:  # LINK: post_op_attr + wcc_data
            p.boolean(False)
            _wcc_data(p, None)
        elif proc != 0:
            p.boolean(False)  # post_op_attr absent
        return p.bytes()

    def _meta_dirty(self, *inodes: int) -> None:
        """Drop cached attr/access decisions for inodes whose metadata
        a proc just mutated (setattr, create/remove in a parent, ...):
        the mutating reply's post-op attrs and any guarded follow-up
        must see post-mutation state, not a TTL-stale snapshot."""
        for inode in inodes:
            self._attr_cache.pop(inode, None)
            dropped = self._access_cache.pop(inode, None)
            if dropped:
                self._access_cache_n -= len(dropped)

    def _on_invalidate(self, inode: int) -> None:
        self._ra_drop(inode)
        self._meta_dirty(inode)

    async def _attr(self, inode: int) -> m.Attr:
        e = self._attr_cache.get(inode)
        if (
            e is not None
            and time.monotonic() - e[1] <= self.META_TTL_S
            # serve-time flag check: a snapshot cached BEFORE a
            # seteattr flagged the inode must stop being served now,
            # not at TTL expiry
            and not (
                self.client._eattr.get(inode, 0) & EATTR_NOENTRYCACHE
            )
        ):
            return e[0]
        attr = await self.client.getattr(inode)
        if attr.eattr & EATTR_NOENTRYCACHE:
            # the inode opted out of entry caching: serve fresh, keep
            # any stale cached snapshot from resurfacing
            self._attr_cache.pop(inode, None)
            return attr
        self._attr_cache[inode] = (attr, time.monotonic())
        if len(self._attr_cache) > 65536:
            self._attr_cache.clear()  # crude bound; refills on demand
        return attr

    async def _attr_opt(self, inode: int) -> m.Attr | None:
        try:
            return await self._attr(inode)
        except st.StatusError:
            return None

    async def _access(self, inode: int, cred, mask: int) -> bool:
        # entry-cache opt-out covers access decisions too. The flag
        # comes from the client's _eattr map (fed by every attr reply;
        # NFS procs fetch post-op attrs constantly, so it is hot for
        # any inode a client touches) — checked BEFORE serving so
        # decisions cached before a seteattr stop being served, and
        # any stale sub-cache is dropped on the spot
        if self.client._eattr.get(inode, 0) & EATTR_NOENTRYCACHE:
            dropped = self._access_cache.pop(inode, None)
            if dropped:
                self._access_cache_n -= len(dropped)
            return await self.client.access(
                inode, cred.uid, cred.all_gids, mask
            )
        sub = self._access_cache.get(inode)
        key = (cred.uid, tuple(cred.all_gids), mask)
        now = time.monotonic()
        if sub is not None:
            e = sub.get(key)
            if e is not None and now - e[1] <= self.META_TTL_S:
                return e[0]
        ok = await self.client.access(inode, cred.uid, cred.all_gids, mask)
        if sub is None:
            sub = self._access_cache.setdefault(inode, {})
        if key not in sub:
            self._access_cache_n += 1
        sub[key] = (ok, now)
        if self._access_cache_n > 65536:
            self._access_cache.clear()
            self._access_cache_n = 0
        return ok

    # Each proc_* returns the XDR result body (success or mapped error).

    async def _proc_null(self, cred, u) -> bytes:
        return b""

    async def _proc_getattr(self, cred, u) -> bytes:
        inode = fh_unpack(u.opaque(64))
        await self._flush_inode(inode)  # size must reflect gathered writes
        try:
            attr = await self._attr(inode)
        except st.StatusError as e:
            return Packer().u32(_nfs_code(e)).bytes()
        p = Packer().u32(NFS3_OK)
        _pack_fattr3(p, attr)
        return p.bytes()

    async def _proc_setattr(self, cred, u) -> bytes:
        inode = fh_unpack(u.opaque(64))
        # ordering: a truncate must not race gathered writes (and the
        # ctime guard below must see post-flush attrs)
        await self._flush_inode(inode)
        sattr = _Sattr3(u)
        if u.boolean():  # sattrguard3: compare-and-set on ctime
            guard_ctime = u.u32()
            u.u32()  # nsec (server ctimes are whole seconds)
            # guard reads bypass the TTL cache: compare-and-set against
            # a stale ctime would let a lost-update race through
            self._meta_dirty(inode)
            current = await self._attr(inode)
            if current.ctime != guard_ctime:
                p = Packer().u32(NFS3ERR_NOT_SYNC)
                _wcc_data(p, current)
                return p.bytes()
        if sattr.size is not None:
            await self.client.truncate(
                inode, sattr.size, uid=cred.uid, gids=cred.all_gids
            )
        mask, kw = sattr.set_mask()
        attr = None
        if mask:
            attr = await self.client.setattr(
                inode, mask, caller_uid=cred.uid,
                caller_gids=cred.all_gids, **kw,
            )
            self._meta_dirty(inode)  # mode/owner changed: access too
        else:
            attr = await self._attr_opt(inode)
        p = Packer().u32(NFS3_OK)
        _wcc_data(p, attr)
        return p.bytes()

    async def _proc_lookup(self, cred, u) -> bytes:
        parent = fh_unpack(u.opaque(64))
        name = u.string(255)
        p = Packer()
        try:
            if name == "." or (name == ".." and parent in self._export_roots):
                # ".." clamps at the export root: no walking above a mount
                attr = await self._attr(parent)
            elif name == "..":
                # the master resolves ".." itself (session-root aware)
                attr = await self.client.lookup(
                    parent, "..", uid=cred.uid, gids=cred.all_gids
                )
            else:
                attr = await self.client.lookup(
                    parent, name, uid=cred.uid, gids=cred.all_gids
                )
        except st.StatusError as e:
            p.u32(_nfs_code(e))
            _post_op_attr(p, await self._attr_opt(parent))
            return p.bytes()
        p.u32(NFS3_OK).opaque(fh_pack(attr.inode))
        _post_op_attr(p, attr)
        _post_op_attr(p, await self._attr_opt(parent))
        return p.bytes()

    async def _proc_access(self, cred, u) -> bytes:
        inode = fh_unpack(u.opaque(64))
        want = u.u32()
        attr = await self._attr(inode)
        granted = 0
        checks = (
            (ACCESS3_READ, 4),
            (ACCESS3_LOOKUP | ACCESS3_EXECUTE, 1),
            (ACCESS3_MODIFY | ACCESS3_EXTEND | ACCESS3_DELETE, 2),
        )
        for bits, mask in checks:
            if want & bits and await self._access(inode, cred, mask):
                granted |= want & bits
        p = Packer().u32(NFS3_OK)
        _post_op_attr(p, attr)
        p.u32(granted)
        return p.bytes()

    async def _proc_readlink(self, cred, u) -> bytes:
        inode = fh_unpack(u.opaque(64))
        target = await self.client.readlink(inode)
        p = Packer().u32(NFS3_OK)
        _post_op_attr(p, await self._attr_opt(inode))
        p.string(target)
        return p.bytes()

    def _ra_drop(self, inode: int) -> None:
        """Invalidate-listener + local eviction: drop an inode's
        readahead span (runs synchronously on the loop thread, so it is
        ordered against _ra_read's store)."""
        e = self._ra.pop(inode, None)
        if e is not None:
            self._ra_total -= len(e[2])
        # epoch entries only matter to a reader mid-fetch (one holds
        # the inode's lock); bumping for never-read inodes would leak
        # one dict entry per written file forever
        if inode in self._ra_locks:
            self._ra_epoch[inode] = self._ra_epoch.get(inode, 0) + 1

    async def _ra_read(self, inode: int, offset: int, count: int) -> bytes:
        """READ through the per-inode readahead span: sequential
        streams fetch up to RA_WINDOW_MAX ahead in one back-end read
        and serve the following READs from memory; non-sequential
        offsets reset the window to zero and bypass buffering entirely
        (adviser semantics: client/cache.py ReadaheadAdviser)."""
        lock = self._ra_locks.get(inode)
        if lock is None:
            lock = self._ra_locks[inode] = asyncio.Lock()
        async with lock:
            adviser = self._ra_advisers.get(inode)
            if adviser is None:
                adviser = self._ra_advisers[inode] = ReadaheadAdviser(
                    max_window=self.RA_WINDOW_MAX
                )
            e = self._ra.get(inode)
            if e is not None:
                _adv, off, buf, ts = e
                if (
                    time.monotonic() - ts <= self.RA_TTL_S
                    and off <= offset
                    and offset + count <= off + len(buf)
                ):
                    adviser.advise(offset, count)  # keep the stream hot
                    lo = offset - off
                    return bytes(buf[lo: lo + count])
            extra = adviser.advise(offset, count)
            if extra:
                epoch = self._ra_epoch.get(inode, 0)
                data = await self.client.read_file(
                    inode, offset, count + extra
                )
                self._ra_drop(inode)
                if (len(data) > count
                        and self._ra_epoch.get(inode, 0) == epoch + 1):
                    # store only if no invalidation raced the fetch
                    # (the +1 is our own _ra_drop above) — mirroring
                    # the BlockCache's revoked-put refusal
                    self._ra[inode] = [
                        adviser, offset, bytes(data), time.monotonic()
                    ]
                    self._ra_total += len(data)
                    while self._ra_total > self.RA_TOTAL_BYTES and self._ra:
                        oldest = min(self._ra, key=lambda i: self._ra[i][3])
                        self._ra_drop(oldest)
                return bytes(data[:count])
        # non-sequential miss: nothing to buffer — read OUTSIDE the
        # lock so random READs of one file keep their pipeline
        # concurrency instead of serializing on the adviser
        data = await self.client.read_file(inode, offset, count)
        return bytes(data[:count])

    async def _proc_read(self, cred, u) -> bytes:
        inode = fh_unpack(u.opaque(64))
        offset, count = u.u64(), u.u32()
        count = min(count, 1 << 20)
        await self._flush_inode(inode)  # read-your-own-UNSTABLE-writes
        attr = await self._attr(inode)
        if attr.ftype == m.FTYPE_DIR:
            raise _NfsError(NFS3ERR_ISDIR)
        if not await self._access(inode, cred, 4):
            raise _NfsError(NFS3ERR_ACCES)
        data = await self._ra_read(inode, offset, count)
        p = Packer().u32(NFS3_OK)
        _post_op_attr(p, attr)
        p.u32(len(data))
        p.boolean(offset + len(data) >= attr.length)  # eof
        p.opaque(data)
        return p.bytes()

    async def _proc_write(self, cred, u) -> bytes:
        inode = fh_unpack(u.opaque(64))
        offset, count = u.u64(), u.u32()
        stable = u.u32()  # 0 UNSTABLE, 1 DATA_SYNC, 2 FILE_SYNC
        data = u.opaque(1 << 22)[:count]
        if not await self._access(inode, cred, 2):
            raise _NfsError(NFS3ERR_ACCES)
        if stable == 0:
            # write gathering: buffer UNSTABLE writes and flush them as
            # few large pwrites (sequential 64 KiB wire ops would each
            # pay a full striped read-modify-write otherwise); COMMIT /
            # stable writes / dependent ops / the idle sweep flush
            e = self._lock_entry(inode)
            e[1] += 1
            try:
                async with e[0]:
                    g = self._gather.get(inode)
                    if g is None:
                        g = self._gather[inode] = _WriteGather()
                    if not g.try_add(offset, data):
                        # overlap (retransmit/random rewrite): flush,
                        # then start a fresh gather with this write
                        await self._flush_locked(inode)
                        g = self._gather[inode] = _WriteGather()
                        g.try_add(offset, data)
                    self._gather_total += len(data)
                    if g.nbytes >= self.GATHER_FLUSH_BYTES:
                        await self._flush_locked(inode)
            finally:
                e[1] -= 1
                if e[1] == 0 and self._gather_locks.get(inode) is e:
                    del self._gather_locks[inode]
            # gateway-wide memory cap: flush the LARGEST gathers (not
            # this possibly-tiny one) until under budget — done outside
            # this inode's lock to keep lock acquisition one-at-a-time
            while self._gather_total >= self.GATHER_TOTAL_BYTES:
                biggest = max(
                    self._gather, key=lambda i: self._gather[i].nbytes,
                    default=None,
                )
                if biggest is None:
                    break
                await self._flush_inode(biggest)
            attr = await self._attr_opt(inode)
            if attr is not None and inode in self._gather:
                # advisory post-attr: reflect the buffered tail so the
                # client's size view stays monotonic pre-flush
                attr.length = max(attr.length, self._gather[inode].end)
            p = Packer().u32(NFS3_OK)
            _wcc_data(p, attr)
            p.u32(len(data))
            p.u32(0)  # committed = UNSTABLE: client must COMMIT
            p.fixed(self.write_verf)
            return p.bytes()
        await self._flush_inode(inode)  # ordering vs earlier UNSTABLE
        await self.client.pwrite(inode, offset, data)
        p = Packer().u32(NFS3_OK)
        _wcc_data(p, await self._attr_opt(inode))
        p.u32(len(data))
        p.u32(2)  # committed = FILE_SYNC
        p.fixed(self.write_verf)
        return p.bytes()

    async def _proc_create(self, cred, u) -> bytes:
        parent = fh_unpack(u.opaque(64))
        name = u.string(255)
        how = u.u32()  # 0 UNCHECKED, 1 GUARDED, 2 EXCLUSIVE
        verf = None
        if how in (0, 1):
            sattr = _Sattr3(u)
            mode = sattr.mode if sattr.mode is not None else 0o644
        else:
            # EXCLUSIVE: stash the verifier in atime/mtime (RFC 1813
            # §3.3.8) so a retransmitted create is recognized as ours
            verf = struct.unpack(">II", u.fixed(8))
            mode = 0o644
        try:
            attr = await self.client.create(
                parent, name, mode=mode, uid=cred.uid, gid=cred.gid
            )
            if verf is not None:
                attr = await self.client.setattr(
                    attr.inode, 8 | 16, atime=verf[0], mtime=verf[1],
                    caller_uid=cred.uid, caller_gids=cred.all_gids,
                )
        except st.StatusError as e:
            retryable = False
            if e.code == st.EEXIST and how != 1:
                attr = await self.client.lookup(
                    parent, name, uid=cred.uid, gids=cred.all_gids
                )
                retryable = (
                    how == 0
                    or (attr.atime, attr.mtime) == verf  # our retransmit
                )
            if not retryable:
                p = Packer().u32(_nfs_code(e))
                _wcc_data(p, await self._attr_opt(parent))
                return p.bytes()
        self._meta_dirty(parent)
        p = Packer().u32(NFS3_OK)
        p.boolean(True).opaque(fh_pack(attr.inode))
        _post_op_attr(p, attr)
        _wcc_data(p, await self._attr_opt(parent))
        return p.bytes()

    async def _proc_mkdir(self, cred, u) -> bytes:
        parent = fh_unpack(u.opaque(64))
        name = u.string(255)
        sattr = _Sattr3(u)
        mode = sattr.mode if sattr.mode is not None else 0o755
        try:
            attr = await self.client.mkdir(
                parent, name, mode=mode, uid=cred.uid, gid=cred.gid
            )
        except st.StatusError as e:
            p = Packer().u32(_nfs_code(e))
            _wcc_data(p, await self._attr_opt(parent))
            return p.bytes()
        self._meta_dirty(parent)
        p = Packer().u32(NFS3_OK)
        p.boolean(True).opaque(fh_pack(attr.inode))
        _post_op_attr(p, attr)
        _wcc_data(p, await self._attr_opt(parent))
        return p.bytes()

    async def _proc_symlink(self, cred, u) -> bytes:
        parent = fh_unpack(u.opaque(64))
        name = u.string(255)
        _Sattr3(u)  # symlink attrs: mode is fixed 0777
        target = u.string(4096)
        attr = await self.client.symlink(
            parent, name, target, uid=cred.uid, gid=cred.gid
        )
        self._meta_dirty(parent)
        p = Packer().u32(NFS3_OK)
        p.boolean(True).opaque(fh_pack(attr.inode))
        _post_op_attr(p, attr)
        _wcc_data(p, await self._attr_opt(parent))
        return p.bytes()

    async def _proc_mknod(self, cred, u) -> bytes:
        raise _NfsError(NFS3ERR_NOTSUPP)

    async def _proc_remove(self, cred, u) -> bytes:
        parent = fh_unpack(u.opaque(64))
        name = u.string(255)
        # flush the victim's gathered writes first: local-fs unlink
        # ordering (data lands, THEN the name goes — the client's
        # sillyrename pattern for unlink-while-open depends on it)
        try:
            victim = await self.client.lookup(
                parent, name, uid=cred.uid, gids=cred.all_gids
            )
            await self._flush_inode(victim.inode)
        except st.StatusError:
            pass
        await self.client.unlink(parent, name, uid=cred.uid, gids=cred.all_gids)
        self._meta_dirty(parent)
        p = Packer().u32(NFS3_OK)
        _wcc_data(p, await self._attr_opt(parent))
        return p.bytes()

    async def _proc_rmdir(self, cred, u) -> bytes:
        parent = fh_unpack(u.opaque(64))
        name = u.string(255)
        await self.client.rmdir(parent, name, uid=cred.uid, gids=cred.all_gids)
        self._meta_dirty(parent)
        p = Packer().u32(NFS3_OK)
        _wcc_data(p, await self._attr_opt(parent))
        return p.bytes()

    async def _proc_rename(self, cred, u) -> bytes:
        psrc = fh_unpack(u.opaque(64))
        nsrc = u.string(255)
        pdst = fh_unpack(u.opaque(64))
        ndst = u.string(255)
        await self.client.rename(
            psrc, nsrc, pdst, ndst, uid=cred.uid, gids=cred.all_gids
        )
        self._meta_dirty(psrc, pdst)
        p = Packer().u32(NFS3_OK)
        _wcc_data(p, await self._attr_opt(psrc))
        _wcc_data(p, await self._attr_opt(pdst))
        return p.bytes()

    async def _proc_link(self, cred, u) -> bytes:
        inode = fh_unpack(u.opaque(64))
        parent = fh_unpack(u.opaque(64))
        name = u.string(255)
        attr = await self.client.link(
            inode, parent, name, uid=cred.uid, gids=cred.all_gids
        )
        self._meta_dirty(parent, inode)  # nlink changed on the target
        p = Packer().u32(NFS3_OK)
        _post_op_attr(p, attr)
        _wcc_data(p, await self._attr_opt(parent))
        return p.bytes()

    async def _readdir_common(self, cred, u, plus: bool) -> bytes:
        inode = fh_unpack(u.opaque(64))
        cookie = u.u64()
        client_verf = u.fixed(8)
        if plus:
            u.u32()  # dircount
        maxcount = min(u.u32(), 1 << 20)
        entries = await self.client.readdir(
            inode, uid=cred.uid, gids=cred.all_gids
        )
        dir_attr = await self._attr_opt(inode)
        if inode in self._export_roots:
            dotdot: tuple[int, m.Attr | None] = (inode, dir_attr)
        else:
            try:
                parent = await self.client.lookup(
                    inode, "..", uid=cred.uid, gids=cred.all_gids
                )
                dotdot = (parent.inode, parent)
            except st.StatusError:
                dotdot = (inode, dir_attr)
        listing: list[tuple[str, int, m.Attr | None]] = [
            (".", inode, dir_attr),
            ("..", *dotdot),
        ]
        for e in sorted(entries, key=lambda e: e.name):
            listing.append((e.name, e.inode, None))
        # cookieverf = digest of the listing: cookies are positions in
        # this snapshot, so a changed directory invalidates them
        # (RFC 1813 BAD_COOKIE) instead of silently skipping entries
        h = hashlib.blake2b(digest_size=8)
        for name, ino, _ in listing:
            h.update(name.encode("utf-8", "surrogateescape"))
            h.update(struct.pack(">I", ino))
        verf = h.digest()
        start = int(cookie)
        if start and client_verf != verf:
            raise _NfsError(NFS3ERR_BAD_COOKIE)
        if plus and start < len(listing):
            # batch the per-entry attrs this window could need (bounded
            # by what maxcount can fit: >= 44 bytes/entry on the wire)
            window = listing[start : start + max(maxcount // 44, 1)]
            fetched = await asyncio.gather(
                *(self._attr_opt(ino) for _, ino, attr in window
                  if attr is None)
            )
            it = iter(fetched)
            listing[start : start + len(window)] = [
                (name, ino, attr if attr is not None else next(it))
                for name, ino, attr in window
            ]
        p = Packer().u32(NFS3_OK)
        _post_op_attr(p, dir_attr)
        p.fixed(verf)  # cookieverf
        body = Packer()
        used, i, budget = 0, start, maxcount - 64
        while i < len(listing):
            name, ino, attr = listing[i]
            e = Packer()
            e.boolean(True).u64(ino).string(name).u64(i + 1)
            if plus:
                _post_op_attr(e, attr)
                e.boolean(True).opaque(fh_pack(ino))
            chunk = e.bytes()
            if used + len(chunk) > budget:
                break  # window full; zero progress -> TOOSMALL below
            used += len(chunk)
            body.raw(chunk)
            i += 1
        if i == start and start < len(listing):
            raise _NfsError(NFS3ERR_TOOSMALL)
        body.boolean(False)  # no more entries in this reply
        body.boolean(i >= len(listing))  # eof
        p.raw(body.bytes())
        return p.bytes()

    async def _proc_readdir(self, cred, u) -> bytes:
        return await self._readdir_common(cred, u, plus=False)

    async def _proc_readdirplus(self, cred, u) -> bytes:
        return await self._readdir_common(cred, u, plus=True)

    async def _proc_fsstat(self, cred, u) -> bytes:
        inode = fh_unpack(u.opaque(64))
        total, avail = await self.client.statfs()
        p = Packer().u32(NFS3_OK)
        _post_op_attr(p, await self._attr_opt(inode))
        p.u64(total).u64(avail).u64(avail)
        p.u64(1 << 31).u64(1 << 31).u64(1 << 31)  # file slots: unbounded
        p.u32(0)  # invarsec
        return p.bytes()

    async def _proc_fsinfo(self, cred, u) -> bytes:
        inode = fh_unpack(u.opaque(64))
        p = Packer().u32(NFS3_OK)
        _post_op_attr(p, await self._attr_opt(inode))
        p.u32(1 << 20).u32(1 << 20).u32(MFSBLOCKSIZE)  # rtmax/rtpref/rtmult
        p.u32(1 << 20).u32(1 << 20).u32(MFSBLOCKSIZE)  # wtmax/wtpref/wtmult
        p.u32(1 << 16)  # dtpref
        p.u64((1 << 63) - 1)  # maxfilesize
        p.u32(0).u32(1)  # time_delta
        p.u32(0x1 | 0x2 | 0x8 | 0x10)  # LINK|SYMLINK|HOMOGENEOUS|CANSETTIME
        return p.bytes()

    async def _proc_pathconf(self, cred, u) -> bytes:
        inode = fh_unpack(u.opaque(64))
        p = Packer().u32(NFS3_OK)
        _post_op_attr(p, await self._attr_opt(inode))
        p.u32(65535)  # linkmax
        p.u32(255)  # name_max
        p.boolean(True)  # no_trunc
        p.boolean(True)  # chown_restricted
        p.boolean(False)  # case_insensitive
        p.boolean(True)  # case_preserving
        return p.bytes()

    async def _proc_commit(self, cred, u) -> bytes:
        inode = fh_unpack(u.opaque(64))
        u.u64()
        u.u32()  # offset, count: flushing the whole inode covers any range
        await self._flush_inode(inode)
        p = Packer().u32(NFS3_OK)
        _wcc_data(p, await self._attr_opt(inode))
        p.fixed(self.write_verf)
        return p.bytes()

    _PROCS = {
        0: _proc_null,
        1: _proc_getattr,
        2: _proc_setattr,
        3: _proc_lookup,
        4: _proc_access,
        5: _proc_readlink,
        6: _proc_read,
        7: _proc_write,
        8: _proc_create,
        9: _proc_mkdir,
        10: _proc_symlink,
        11: _proc_mknod,
        12: _proc_remove,
        13: _proc_rmdir,
        14: _proc_rename,
        15: _proc_link,
        16: _proc_readdir,
        17: _proc_readdirplus,
        18: _proc_fsstat,
        19: _proc_fsinfo,
        20: _proc_pathconf,
        21: _proc_commit,
    }


async def main(argv: list[str] | None = None) -> None:
    """``python -m lizardfs_tpu.nfs.server HOST:PORT [--port N]``"""
    import argparse

    ap = argparse.ArgumentParser(description="LizardFS-TPU NFSv3 gateway")
    ap.add_argument("master", help="master HOST:PORT")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=2049)
    ap.add_argument("--export", action="append", default=None,
                    help="EXPORT=CLUSTERPATH (repeatable; default /=/)")
    ap.add_argument("--http-port", type=int, default=0,
                    help="observability endpoint (/metrics /healthz "
                         "/profile /top); 0 = ephemeral, -1 = disabled")
    args = ap.parse_args(argv)
    mhost, mport = args.master.rsplit(":", 1)
    exports = {"/": "/"}
    if args.export:
        exports = dict(e.split("=", 1) for e in args.export)
    gw = NfsGateway(mhost, int(mport), host=args.host, port=args.port,
                    exports=exports)
    gw.http_port = None if args.http_port < 0 else args.http_port
    await gw.start()
    try:
        # lint: waive(unbounded-await): the gateway process parks here until killed by design
        await asyncio.Event().wait()
    finally:
        await gw.stop()


if __name__ == "__main__":
    asyncio.run(main())
