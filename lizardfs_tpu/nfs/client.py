"""Minimal NFSv3 wire client (RFC 1813 XDR over ONC-RPC).

Speaks real wire format against any NFS3 server, primarily this
package's gateway. Used three ways: the gateway's e2e tests (both
directions of the codec exercised against the spec, not against
itself), the NFS throughput bench row, and scripted multi-gateway
drives (see doc/migration.md "NFS scale-out"). Reference analog: the
Ganesha FSAL test clients (reference: src/nfs-ganesha/).
"""

from __future__ import annotations

from lizardfs_tpu.nfs import rpc
from lizardfs_tpu.nfs import server as nfs
from lizardfs_tpu.nfs.xdr import Packer


class Nfs3Client:
    """Minimal NFS3 wire client for the tests."""

    def __init__(self, host: str, port: int, uid: int = 0, gid: int = 0):
        self.rpc = rpc.RpcClient(
            host, port, rpc.Credential(uid=uid, gid=gid, machine="test")
        )

    async def __aenter__(self):
        # lint: waive(unbounded-await): delegates to RpcClient.connect, whose dial is wait_for-bounded at 5 s
        await self.rpc.connect()
        return self

    async def __aexit__(self, *exc):
        await self.rpc.close()

    async def mnt(self, path: str = "/") -> bytes:
        u = await self.rpc.call(nfs.PROG_MOUNT, 3, 1, Packer().string(path).bytes())
        assert u.u32() == nfs.NFS3_OK
        fh = u.opaque(64)
        nflavors = u.u32()
        flavors = [u.u32() for _ in range(nflavors)]
        assert rpc.AUTH_SYS in flavors
        return fh

    async def call(self, proc: int, args: bytes):
        return await self.rpc.call(nfs.PROG_NFS, 3, proc, args)

    @staticmethod
    def skip_post_op(u):
        if u.boolean():
            u.fixed(84)

    @staticmethod
    def read_fattr(u) -> dict:
        ftype, mode, nlink, uid, gid = (u.u32() for _ in range(5))
        size, used = u.u64(), u.u64()
        u.u32(), u.u32(), u.u64()
        fileid = u.u64()
        times = [(u.u32(), u.u32()) for _ in range(3)]
        return dict(ftype=ftype, mode=mode, nlink=nlink, uid=uid, gid=gid,
                    size=size, fileid=fileid, times=times)

    @staticmethod
    def skip_wcc(u):
        if u.boolean():
            u.fixed(24)
        Nfs3Client.skip_post_op(u)

    async def lookup(self, dirfh: bytes, name: str):
        u = await self.call(3, Packer().opaque(dirfh).string(name).bytes())
        code = u.u32()
        if code != nfs.NFS3_OK:
            return code, None, None
        fh = u.opaque(64)
        attr = None
        if u.boolean():
            attr = self.read_fattr(u)
        return nfs.NFS3_OK, fh, attr

    async def getattr(self, fh: bytes) -> dict:
        u = await self.call(1, Packer().opaque(fh).bytes())
        assert u.u32() == nfs.NFS3_OK
        return self.read_fattr(u)

    async def mkdir(self, dirfh: bytes, name: str, mode: int = 0o755) -> bytes:
        args = (Packer().opaque(dirfh).string(name)
                .boolean(True).u32(mode)  # mode
                .boolean(False).boolean(False).boolean(False)  # uid/gid/size
                .u32(0).u32(0)  # atime/mtime: don't change
                .bytes())
        u = await self.call(9, args)
        assert u.u32() == nfs.NFS3_OK
        assert u.boolean()
        return u.opaque(64)

    async def create(self, dirfh: bytes, name: str, mode: int = 0o644,
                     how: int = 0, verf: bytes = b"\x00" * 8):
        p = Packer().opaque(dirfh).string(name).u32(how)
        if how == 2:
            p.fixed(verf)
        else:
            (p.boolean(True).u32(mode)
             .boolean(False).boolean(False).boolean(False)
             .u32(0).u32(0))
        u = await self.call(8, p.bytes())
        code = u.u32()
        if code != nfs.NFS3_OK:
            return code, None
        assert u.boolean()
        return nfs.NFS3_OK, u.opaque(64)

    async def write(self, fh: bytes, offset: int, data: bytes,
                    expect=nfs.NFS3_OK, stable: int = 2) -> int:
        """stable: 0 UNSTABLE (gathered server-side, COMMIT required),
        1 DATA_SYNC, 2 FILE_SYNC (default: durable before reply)."""
        args = (Packer().opaque(fh).u64(offset).u32(len(data)).u32(stable)
                .opaque(data).bytes())
        u = await self.call(7, args)
        code = u.u32()
        assert code == expect, f"WRITE -> {code}"
        if code != nfs.NFS3_OK:
            return 0
        self.skip_wcc(u)
        n = u.u32()
        committed = u.u32()
        # the server may commit MORE strictly than asked, never less
        assert committed >= (2 if stable == 2 else 0)
        return n

    async def commit(self, fh: bytes, offset: int = 0, count: int = 0) -> bytes:
        """COMMIT gathered UNSTABLE writes; returns the write verifier
        (a changed verifier between writes and commit means the server
        rebooted and the client must resend)."""
        u = await self.call(
            21, Packer().opaque(fh).u64(offset).u32(count).bytes()
        )
        assert u.u32() == nfs.NFS3_OK
        self.skip_wcc(u)
        return u.fixed(8)

    async def setattr(self, fh: bytes, mode: int | None = None,
                      size: int | None = None,
                      guard_ctime: int | None = None) -> int:
        """SETATTR (proc 2); returns the NFS3 status (callers assert).
        ``guard_ctime`` packs the sattrguard3 compare-and-set."""
        p = Packer().opaque(fh)
        p.boolean(mode is not None)
        if mode is not None:
            p.u32(mode)
        p.boolean(False).boolean(False)  # uid/gid unchanged
        p.boolean(size is not None)
        if size is not None:
            p.u64(size)
        p.u32(0).u32(0)  # atime/mtime: DONT_CHANGE
        p.boolean(guard_ctime is not None)
        if guard_ctime is not None:
            p.u32(guard_ctime).u32(0)
        u = await self.call(2, p.bytes())
        return u.u32()

    async def fsinfo(self, fh: bytes) -> dict:
        """FSINFO (proc 19): the server's transfer-size preferences —
        real kernel clients size rsize/wsize from these, so bulk
        drivers should too."""
        u = await self.call(19, Packer().opaque(fh).bytes())
        assert u.u32() == nfs.NFS3_OK
        self.skip_post_op(u)
        rtmax, rtpref, _rtmult = u.u32(), u.u32(), u.u32()
        wtmax, wtpref, _wtmult = u.u32(), u.u32(), u.u32()
        return {"rtmax": rtmax, "rtpref": rtpref,
                "wtmax": wtmax, "wtpref": wtpref}

    async def read(self, fh: bytes, offset: int, count: int) -> tuple[bytes, bool]:
        u = await self.call(6, Packer().opaque(fh).u64(offset).u32(count).bytes())
        assert u.u32() == nfs.NFS3_OK
        self.skip_post_op(u)
        n = u.u32()
        eof = u.boolean()
        data = u.opaque(1 << 22)
        assert len(data) == n
        return data, eof

    async def readdir(self, dirfh: bytes, plus: bool = False,
                      maxcount: int = 4096) -> list[str]:
        names, cookie, verf = [], 0, b"\x00" * 8
        while True:
            p = Packer().opaque(dirfh).u64(cookie).fixed(verf)
            if plus:
                p.u32(1 << 16)
            p.u32(maxcount)
            u = await self.call(17 if plus else 16, p.bytes())
            assert u.u32() == nfs.NFS3_OK
            self.skip_post_op(u)
            verf = u.fixed(8)  # cookieverf
            got = 0
            while u.boolean():
                u.u64()  # fileid
                names.append(u.string(255))
                cookie = u.u64()
                if plus:
                    self.skip_post_op(u)
                    if u.boolean():
                        u.opaque(64)
                got += 1
            if u.boolean() or got == 0:  # eof
                return names

