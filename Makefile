# Top-level CI/tooling targets. Native-code targets live in native/Makefile.

PY ?= python
SEEDS ?= 1,2,3

# tier-1: the fast suite CI gates on (ROADMAP.md "Tier-1 verify")
test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
	  --continue-on-collection-errors -p no:cacheprovider

# chaos: the full seeded fault-schedule set against REAL multi-process
# clusters (tools/chaos.py). Every schedule runs at every seed in
# $(SEEDS); on failure the driver prints the exact seed + replay
# command, so a red run reproduces deterministically:
#   make chaos SEEDS=1,2,3,4,5
chaos:
	JAX_PLATFORMS=cpu PYTHONPATH=. $(PY) -m lizardfs_tpu.tools.chaos \
	  --all --seeds $(SEEDS)

# chaos-slow: the same matrix through pytest (includes the slow-marked
# parametrization in tests/test_chaos.py)
chaos-slow:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_chaos.py -q \
	  -p no:cacheprovider

native:
	$(MAKE) -C native

.PHONY: test chaos chaos-slow native
