# Top-level CI/tooling targets. Native-code targets live in native/Makefile.

PY ?= python
CXX ?= g++
SEEDS ?= 1,2,3

# tier-1: the fast suite CI gates on (ROADMAP.md "Tier-1 verify").
# tests/test_invariant_lint.py rides in it, so tier-1 holds the tree
# at zero unwaived lint findings by default.
test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
	  --continue-on-collection-errors -p no:cacheprovider

# invariant lint engine (lizardfs_tpu/tools/lint): the seven repo
# checkers — cross-await-race, unbounded-await, wire-skew, kill-switch,
# changelog-durability, native-wire, telemetry-coverage.
# Exit 0 == zero unwaived findings. Stamps .lint-stamp so `make chaos`
# can tell when the tree changed since the last lint run.
lint:
	$(PY) -m lizardfs_tpu.tools.lint
	@touch .lint-stamp

# metrics-lint: the Prometheus-exposition structural gate alone (the
# whole file also rides tier-1)
metrics-lint:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_metrics_lint.py -q \
	  -p no:cacheprovider

# racehunt: replay the async smoke set across deterministic-scheduler
# seeds (runtime/detsched.py); failures print LZ_DETSCHED=<seed> replay
# commands that re-execute the schedule byte-identically:
#   make racehunt RACEHUNT_SEEDS=10 RACEHUNT_TARGETS=tests/test_shadow_reads.py
RACEHUNT_SEEDS ?= 3
RACEHUNT_TARGETS ?=
racehunt:
	JAX_PLATFORMS=cpu $(PY) -m lizardfs_tpu.tools.racehunt \
	  --seeds $(RACEHUNT_SEEDS) $(RACEHUNT_TARGETS)

# check: the one-command gate — invariant lint, metrics exposition
# lint, tier-1, the read-path microscope smoke, then a racehunt smoke
# (seeds printed for replay)
check: lint metrics-lint test read-smoke racehunt
	@echo "check: lint + metrics-lint + tier-1 + read-smoke + racehunt all green"

# sanitizer matrix over the FULL native surface (native/Makefile
# `sanitize`: ASan+UBSan and TSan over ec/io/serve + the shm plane),
# then the C NFS client instrumented under a real Python gateway
# (LZ_CLIENT_SO points cnfs.py at the ASan build).
sanitize:
	$(MAKE) -C native sanitize
	LZ_CLIENT_SO=$(CURDIR)/native/liblizardfs_client_asan.so \
	  LD_PRELOAD="$$($(CXX) -print-file-name=libasan.so) $$($(CXX) -print-file-name=libubsan.so)" \
	  ASAN_OPTIONS=detect_leaks=0,halt_on_error=1 \
	  UBSAN_OPTIONS=halt_on_error=1,print_stacktrace=1 \
	  JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_nfs.py -q -k c_client \
	  -p no:cacheprovider

# chaos: the full seeded fault-schedule set against REAL multi-process
# clusters (tools/chaos.py). Every schedule runs at every seed in
# $(SEEDS); on failure the driver prints the exact seed + replay
# command, so a red run reproduces deterministically:
#   make chaos SEEDS=1,2,3,4,5
# the nag watches every lint INPUT: package sources (incl. the checker
# modules themselves under lizardfs_tpu/tools/lint/), tests, docs,
# native C sources, and this Makefile — the new checkers read all of
# them, so any edit there can change the lint verdict
chaos:
	@if [ ! -f .lint-stamp ] || [ -n "$$(find lizardfs_tpu tests doc \
	  native Makefile \( -name '*.py' -o -name '*.h' -o -name '*.cpp' \
	  -o -name '*.md' -o -name Makefile \) -newer .lint-stamp \
	  -print -quit)" ]; then \
	  echo "note: invariant lint has not run on this tree state —" \
	       "run 'make lint' before trusting a chaos verdict"; fi
	JAX_PLATFORMS=cpu PYTHONPATH=. $(PY) -m lizardfs_tpu.tools.chaos \
	  --all --seeds $(SEEDS)

# chaos-slow: the same matrix through pytest (includes the slow-marked
# parametrization in tests/test_chaos.py)
chaos-slow:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_chaos.py -q \
	  -p no:cacheprovider

# s3-smoke: boot master + chunkservers + S3 gateway in-process and run
# the PUT/GET/List/multipart round trip (the `smoke`-named subset of
# tests/test_s3.py; the whole non-slow file rides tier-1 too)
s3-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_s3.py -q -k smoke \
	  -p no:cacheprovider

# top-smoke: boot a full observatory cluster (master + CS + both
# gateways) in-process, drive traffic, and pin that `lizardfs-admin
# top` attributes it to the right sessions (the `smoke`-named subset
# of tests/test_top.py; the whole non-slow file rides tier-1 too)
top-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_top.py -q -k smoke \
	  -p no:cacheprovider

# qos-smoke: in-process master + chunkservers, an abuser tenant
# flooding locates next to a paced victim tenant — asserts sheds land
# ONLY on the abuser, the victim's p99 bound holds, and per-session
# accounting counts each logical op exactly once (the `smoke`-named
# subset of tests/test_qos.py; the non-slow file rides tier-1 too).
# The real-process variant is the `noisy-neighbor` schedule in
# `make chaos`.
qos-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_qos.py -q -k smoke \
	  -p no:cacheprovider

# read-smoke: in-process cluster, one TRACED degraded ec(8,4) read on
# the instrumented wave path — asserts the phase breakdown lands in
# `top`, the merged timeline's attribution buckets sum to the wall,
# slowops rows embed the attribution, and the dial queue-wait gate
# charged (the `smoke`-named subset of tests/test_read_phases.py; the
# non-slow file rides tier-1 too)
read-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_read_phases.py -q \
	  -k smoke -p no:cacheprovider

native:
	$(MAKE) -C native

.PHONY: test lint metrics-lint racehunt check sanitize chaos chaos-slow \
	s3-smoke top-smoke qos-smoke read-smoke native
