# Top-level CI/tooling targets. Native-code targets live in native/Makefile.

PY ?= python
CXX ?= g++
SEEDS ?= 1,2,3

# tier-1: the fast suite CI gates on (ROADMAP.md "Tier-1 verify").
# tests/test_invariant_lint.py rides in it, so tier-1 holds the tree
# at zero unwaived lint findings by default.
test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
	  --continue-on-collection-errors -p no:cacheprovider

# invariant lint engine (lizardfs_tpu/tools/lint): the four repo
# checkers — cross-await-race, unbounded-await, wire-skew, kill-switch.
# Exit 0 == zero unwaived findings. Stamps .lint-stamp so `make chaos`
# can tell when the tree changed since the last lint run.
lint:
	$(PY) -m lizardfs_tpu.tools.lint
	@touch .lint-stamp

# sanitizer matrix over the FULL native surface (native/Makefile
# `sanitize`: ASan+UBSan and TSan over ec/io/serve + the shm plane),
# then the C NFS client instrumented under a real Python gateway
# (LZ_CLIENT_SO points cnfs.py at the ASan build).
sanitize:
	$(MAKE) -C native sanitize
	LZ_CLIENT_SO=$(CURDIR)/native/liblizardfs_client_asan.so \
	  LD_PRELOAD="$$($(CXX) -print-file-name=libasan.so) $$($(CXX) -print-file-name=libubsan.so)" \
	  ASAN_OPTIONS=detect_leaks=0,halt_on_error=1 \
	  UBSAN_OPTIONS=halt_on_error=1,print_stacktrace=1 \
	  JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_nfs.py -q -k c_client \
	  -p no:cacheprovider

# chaos: the full seeded fault-schedule set against REAL multi-process
# clusters (tools/chaos.py). Every schedule runs at every seed in
# $(SEEDS); on failure the driver prints the exact seed + replay
# command, so a red run reproduces deterministically:
#   make chaos SEEDS=1,2,3,4,5
chaos:
	@if [ ! -f .lint-stamp ] || [ -n "$$(find lizardfs_tpu tests doc \
	  native \( -name '*.py' -o -name '*.h' -o -name '*.cpp' \
	  -o -name '*.md' \) -newer .lint-stamp -print -quit)" ]; then \
	  echo "note: invariant lint has not run on this tree state —" \
	       "run 'make lint' before trusting a chaos verdict"; fi
	JAX_PLATFORMS=cpu PYTHONPATH=. $(PY) -m lizardfs_tpu.tools.chaos \
	  --all --seeds $(SEEDS)

# chaos-slow: the same matrix through pytest (includes the slow-marked
# parametrization in tests/test_chaos.py)
chaos-slow:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_chaos.py -q \
	  -p no:cacheprovider

# s3-smoke: boot master + chunkservers + S3 gateway in-process and run
# the PUT/GET/List/multipart round trip (the `smoke`-named subset of
# tests/test_s3.py; the whole non-slow file rides tier-1 too)
s3-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_s3.py -q -k smoke \
	  -p no:cacheprovider

native:
	$(MAKE) -C native

.PHONY: test lint sanitize chaos chaos-slow s3-smoke native
