"""Master scalability mechanics at 100k+ entities.

VERDICT round-1 asks (reference analogs: filesystem_checksum.cc
incremental digest, metadata_dumper.h:37 forked dump, chunks.cc
1807-1830 incremental health walk): with 100k+ inodes/chunks, the
checksum probe is O(1), the image dump must not stall the event loop
for the serialization time, and a health tick is O(budget) not
O(all chunks).
"""

import asyncio
import time

import pytest

from lizardfs_tpu.master import fs as fsmod
from lizardfs_tpu.master.chunks import ChunkRegistry
from lizardfs_tpu.master.fs import Node
from lizardfs_tpu.master.metadata import MetadataStore
from lizardfs_tpu.master.server import MasterServer

N_FILES = 100_000


def _populate(meta: MetadataStore, n_files: int = N_FILES) -> None:
    """Bulk-load a big namespace directly (test setup only), then
    re-anchor the incremental digest once."""
    fs = meta.fs
    root = fs.nodes[1]
    for i in range(n_files):
        inode = 10 + i
        node = Node(
            inode=inode, ftype=fsmod.TYPE_FILE, mode=0o644, uid=1, gid=1,
            atime=1, mtime=1, ctime=1, goal=1, trash_time=86400, nlink=1,
            parents=[1], length=65536, chunks=[100 + i],
        )
        fs.nodes[inode] = node
        root.children[f"f{i}"] = inode
        meta.registry.create_chunk(0, chunk_id=100 + i, version=1, copies=2)
    fs.next_inode = 10 + n_files
    meta.reset_digest()


def test_checksum_probe_is_o1():
    meta = MetadataStore()
    _populate(meta)
    t0 = time.perf_counter()
    for _ in range(100):
        meta.checksum()
    per_probe = (time.perf_counter() - t0) / 100
    assert per_probe < 0.001, f"checksum probe {per_probe*1e3:.2f} ms"
    # and the incremental digest tracks ops without recomputation
    t0 = time.perf_counter()
    meta.apply({
        "op": "mknode", "parent": 1, "name": "new", "inode": 5_000_000,
        "ftype": fsmod.TYPE_FILE, "mode": 0o644, "uid": 1, "gid": 1,
        "ts": 2, "goal": 1, "trash_time": 0,
    })
    per_op = time.perf_counter() - t0
    assert per_op < 0.05, f"apply with digest {per_op*1e3:.1f} ms"
    assert meta._digest == meta.full_digest()


def test_health_tick_bounded():
    meta = MetadataStore()
    _populate(meta)
    reg: ChunkRegistry = meta.registry
    # a tick evaluates at most SCAN_BUDGET + endangered items
    t0 = time.perf_counter()
    for _ in range(10):
        reg.health_work(limit=16)
    per_tick = (time.perf_counter() - t0) / 10
    assert per_tick < 0.02, f"health tick {per_tick*1e3:.1f} ms"
    # the cursor makes progress: after enough ticks every chunk has been
    # visited at least once (full cycle of 100k / 256 per tick)
    ticks_for_cycle = (N_FILES // reg.SCAN_BUDGET) + 2
    for _ in range(ticks_for_cycle):
        reg.health_work(limit=16)
    assert reg._scan_idx <= len(reg._scan_ids)


def test_endangered_queue_priority_not_cursor():
    """The endangered queue must hold only marked chunks, drain FIFO,
    and never degenerate into a full-table scan cursor."""
    meta = MetadataStore()
    _populate(meta, n_files=1000)
    reg = meta.registry
    reg.register_server("127.0.0.1", 1, "_", 1 << 40, 0)
    # all chunks have zero live parts -> unreadable, not endangered work
    # items; mark three explicitly and verify they drain first, FIFO
    for cid in (100, 500, 900):
        reg.mark_endangered(cid)
    assert list(reg.endangered) == [100, 500, 900]
    reg.health_work(limit=64)
    assert not reg.endangered  # drained, not re-queued wholesale
    assert len(reg._endangered_set) == 0


@pytest.mark.asyncio
async def test_forked_dump_does_not_stall_loop(tmp_path, monkeypatch):
    # this test pins the FORK path's property (loop pauses for the fork,
    # not the serialization). The test process has jax loaded, which the
    # fork gate refuses (tests/test_fork_safety.py covers that side), so
    # force the gate open here.
    from lizardfs_tpu.master import server as msrv

    monkeypatch.setattr(msrv, "_fork_safe", lambda: True)
    master = MasterServer(str(tmp_path / "m"), image_interval=3600.0)
    await master.start()
    try:
        _populate(master.meta, n_files=50_000)
        # how long a synchronous serialization would block
        t0 = time.perf_counter()
        master.meta.to_sections()
        sync_cost = time.perf_counter() - t0

        gaps = []

        async def ticker():
            prev = time.perf_counter()
            while True:
                await asyncio.sleep(0.005)
                now = time.perf_counter()
                gaps.append(now - prev - 0.005)
                prev = now

        t = asyncio.ensure_future(ticker())
        await asyncio.sleep(0.05)
        await master._dump_image()
        t.cancel()
        worst = max(gaps)
        # the loop may pause for the fork itself, never for the full
        # serialization
        assert worst < max(0.1, sync_cost / 4), (
            f"loop stalled {worst*1e3:.0f} ms during dump "
            f"(sync serialization would be {sync_cost*1e3:.0f} ms)"
        )
    finally:
        await master.stop()


def test_incremental_digest_tracks_every_op():
    """After every op type the incremental digest must equal a full
    recomputation (drift would break shadow divergence detection)."""
    s = MetadataStore()
    ops = [
        {"op": "mknode", "parent": 1, "name": "d", "inode": 2,
         "ftype": fsmod.TYPE_DIR, "mode": 0o755, "uid": 0, "gid": 0,
         "ts": 100, "goal": 1, "trash_time": 86400},
        {"op": "mknode", "parent": 2, "name": "f", "inode": 3,
         "ftype": fsmod.TYPE_FILE, "mode": 0o644, "uid": 5, "gid": 5,
         "ts": 101, "goal": 1, "trash_time": 86400},
        {"op": "create_chunk", "chunk_id": 1, "slice_type": 0,
         "version": 1, "copies": 2, "goal_id": 1},
        {"op": "set_chunk", "inode": 3, "chunk_index": 0, "chunk_id": 1},
        {"op": "set_length", "inode": 3, "length": 12345, "ts": 102,
         "drop_chunks": False},
        {"op": "setattr", "inode": 3, "set_mask": 1, "mode": 0o600,
         "uid": 0, "gid": 0, "atime": 0, "mtime": 0, "ts": 103,
         "trash_time": 0},
        {"op": "set_xattr", "inode": 3, "name": "user.x", "value": "YWJj",
         "ts": 105},
        {"op": "set_quota", "kind": "user", "owner_id": 5,
         "soft_inodes": 1, "hard_inodes": 2, "soft_bytes": 3,
         "hard_bytes": 4, "remove": False},
        {"op": "lock_posix", "inode": 3, "sid": 7, "token": 1, "start": 0,
         "end": 10, "ltype": 2},
        {"op": "lock_release_session", "sid": 7},
        {"op": "unlink", "parent": 2, "name": "f", "ts": 106,
         "to_trash": True},
        {"op": "undelete", "inode": 3, "ts": 107},
        {"op": "rename", "parent_src": 2, "name_src": "f",
         "parent_dst": 1, "name_dst": "g", "ts": 108},
        {"op": "link", "inode": 3, "parent": 1, "name": "hard", "ts": 109},
        {"op": "unlink", "parent": 1, "name": "g", "ts": 110,
         "to_trash": True},
        {"op": "session_new", "sid": 9},
        {"op": "bump_chunk_version", "chunk_id": 1, "version": 2},
        {"op": "snapshot", "src_inode": 3, "dst_parent": 2,
         "dst_name": "snap", "inode_map": {"3": 50}, "ts": 111},
        {"op": "cow_chunk", "inode": 50, "chunk_index": 0,
         "old_chunk_id": 1, "new_chunk_id": 2, "slice_type": 0,
         "version": 1, "copies": 2, "goal_id": 1},
        {"op": "purge_trash", "inode": 999},
    ]
    for op in ops:
        s.apply(op)
        assert s._digest == s.full_digest(), f"drift after {op['op']}"


def test_server_disconnect_is_o_parts_not_o_chunks():
    """A chunkserver bounce must cost O(parts on that server), not
    O(all chunks): the per-server part index (reference: per-server
    chunk lists, matocsserv.cc server entries) bounds the disconnect
    walk. 1M chunks spread over 20 servers -> one disconnect touches
    ~50k parts and completes well under 50 ms."""
    reg = ChunkRegistry()
    n_servers = 20
    servers = [
        reg.register_server("127.0.0.1", 20000 + i, "_", 1 << 40, 0)
        for i in range(n_servers)
    ]
    n_chunks = 1_000_000
    for cid in range(1, n_chunks + 1):
        reg.create_chunk(0, chunk_id=cid, version=1, copies=1)
        chunk = reg.chunks[cid]
        reg.record_part(chunk, servers[cid % n_servers].cs_id, 0)
    victim = servers[3].cs_id
    t0 = time.perf_counter()
    affected = reg.server_disconnected(victim)
    dt = time.perf_counter() - t0
    assert len(affected) == n_chunks // n_servers
    # bound sized for slow 2-core CI boxes; an O(all chunks) walk would
    # be ~20x the O(parts) one, so the margin still pins the property
    assert dt < 0.2, f"disconnect took {dt*1e3:.1f} ms"
    # the dropped parts are really gone from the chunk-side sets
    assert all(
        (victim, 0) not in reg.chunks[cid].parts for cid in affected[:100]
    )
    # reconnect + re-report restores both the chunk set and the index
    reg.register_server("127.0.0.1", 20003, "_", 1 << 40, 0)
    reg.record_part(reg.chunks[affected[0]], victim, 0)
    assert (victim, 0) in reg.chunks[affected[0]].parts
    assert (affected[0], 0) in reg._server_parts[victim]


def test_part_index_stays_consistent_through_lifecycle():
    """add/drop/delete/disconnect keep chunk.parts and the per-server
    index in lockstep."""
    reg = ChunkRegistry()
    s1 = reg.register_server("h", 1, "_", 1 << 30, 0)
    s2 = reg.register_server("h", 2, "_", 1 << 30, 0)
    c = reg.create_chunk(0, chunk_id=7, version=1, copies=2)
    reg.record_part(c, s1.cs_id, 0)
    reg.record_part(c, s2.cs_id, 0)
    assert set(reg._server_parts[s1.cs_id]) == {(7, 0)}
    reg.drop_part(7, s1.cs_id, 0)  # std part id 0 == part 0
    assert not reg._server_parts[s1.cs_id]
    assert c.parts == {(s2.cs_id, 0)}
    reg.record_part(c, s1.cs_id, 0)
    reg.delete_chunk(7)
    assert not reg._server_parts[s1.cs_id]
    assert not reg._server_parts[s2.cs_id]
    # disconnect with an empty index is a no-op
    assert reg.server_disconnected(s1.cs_id) == []


def test_bytes_per_inode_budget():
    """Master RAM per inode stays within budget (doc/migration.md "BDB
    name storage" rationale): ~620 B/inode measured with slots=True at
    1M files; the test uses 200k files and an 800 B ceiling so noise
    and allocator variance don't flake it. If this fails after a Node
    change, re-measure and update migration.md."""
    import gc
    import tracemalloc

    n_files = 200_000
    gc.collect()
    tracemalloc.start()
    meta = MetadataStore()
    fs = meta.fs
    root = fs.nodes[1]
    for i in range(n_files):
        inode = 10 + i
        node = Node(
            inode=inode, ftype=fsmod.TYPE_FILE, mode=0o644, uid=1, gid=1,
            atime=1, mtime=1, ctime=1, goal=1, trash_time=86400, nlink=1,
            parents=[1], length=65536, chunks=[100 + i],
        )
        fs.nodes[inode] = node
        root.children[f"file_with_a_realistic_name_{i:07d}.dat"] = inode
    cur, _ = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    per_inode = cur / n_files
    assert per_inode < 800, f"{per_inode:.0f} bytes/inode exceeds budget"


# --- ISSUE 7: locate-storm scan bounds ------------------------------------
# The storm bench (benches/bench_master_storm.py) exposed the master's
# remaining full-registry walks; these tests pin the fixes so they
# cannot regress into the health/stats/heartbeat tick paths.


@pytest.mark.asyncio
async def test_health_probe_never_sweeps_the_chunk_table(tmp_path):
    """/health (cluster_health with chunk evaluation) must read the
    danger aggregate the routine walk maintains — NEVER evaluate the
    whole table per probe. Pinned hard: with evaluate() poisoned, the
    probe still answers, and its numbers match the published cycle."""
    master = MasterServer(str(tmp_path / "m"), image_interval=3600.0)
    await master.start()
    try:
        reg = master.meta.registry
        srv = reg.register_server("127.0.0.1", 9901, "_", 1 << 40, 0)
        # a mostly-HEALTHY 20k-chunk table (a broken-everywhere table
        # legitimately pins the cursor to the repair work limit) with a
        # known sprinkle of danger SPREAD across the id space so no
        # scan batch's work fills the limit: 50 endangered (copies=2,
        # one part), 50 lost (no parts)
        for i in range(20_000):
            cid = 100 + i
            endangered_here = i % 400 == 0
            lost_here = i % 400 == 200
            reg.create_chunk(
                0, chunk_id=cid, version=1,
                copies=2 if endangered_here else 1,
            )
            if not lost_here:
                reg.record_part(reg.chunks[cid], srv.cs_id, 0)
        # drive the cursor through one full cycle + wrap so the cycle's
        # aggregate publishes (work items per tick stay far below the
        # limit at 0.5% danger density, so the cursor never rewinds)
        ticks = (len(reg.chunks) // reg.SCAN_BUDGET) + 3
        for _ in range(ticks):
            reg.health_work(limit=16)
        endangered, lost, scanned = reg.danger_counts
        assert scanned == 20_000
        assert endangered == 50
        assert lost == 50
        # the probe path: poison evaluate — a full-table sweep would
        # blow up, the aggregate read must not
        real_evaluate = reg.evaluate

        def poisoned(chunk):
            raise AssertionError("health probe swept the chunk table")

        reg.evaluate = poisoned
        try:
            h = master.cluster_health(evaluate_chunks=True)
        finally:
            reg.evaluate = real_evaluate
        assert h["summary"]["lost"] == 50
        assert h["summary"]["endangered"] >= 50
        # and it is O(1)-cheap: 100 probes well under a single sweep
        t0 = time.perf_counter()
        for _ in range(100):
            master.cluster_health(evaluate_chunks=True)
        per_probe = (time.perf_counter() - t0) / 100
        assert per_probe < 0.005, f"health probe {per_probe*1e3:.2f} ms"
    finally:
        await master.stop()


def test_register_server_is_o1_per_registration():
    """A 10k-chunkserver registration storm must cost O(N) total, not
    O(N^2): reconnect lookup rides the addr index, never a table scan."""
    reg = ChunkRegistry()
    n = 10_000
    t0 = time.perf_counter()
    for i in range(n):
        reg.register_server("10.0.0.1", 20000 + i, "_", 1 << 40, 0)
    fresh_s = time.perf_counter() - t0
    assert len(reg.servers) == n
    assert fresh_s < 1.0, f"10k fresh registrations took {fresh_s:.2f}s"
    # reconnections resolve to the SAME entry, still O(1)
    t0 = time.perf_counter()
    for i in range(n):
        srv = reg.register_server("10.0.0.1", 20000 + i, "relabel",
                                  2 << 40, 1)
        assert srv.cs_id == i + 1
    reconn_s = time.perf_counter() - t0
    assert len(reg.servers) == n  # no duplicates
    assert reconn_s < 1.0, f"10k reconnections took {reconn_s:.2f}s"


@pytest.mark.asyncio
async def test_registration_ingest_yields_event_loop(tmp_path):
    """One chunkserver registering a huge part report must not stall
    every other connection for the whole walk: _ingest_parts applies in
    slices with yield points (the storm test's stall-watchdog pin)."""
    from lizardfs_tpu.proto import messages as m

    master = MasterServer(str(tmp_path / "m"), image_interval=3600.0)
    await master.start()
    try:
        _populate(master.meta, n_files=100_000)
        reg = master.meta.registry
        srv = reg.register_server("127.0.0.1", 9902, "_", 1 << 40, 0)
        infos = [
            m.ChunkPartInfo(chunk_id=100 + i, version=1, part_id=0)
            for i in range(100_000)
        ]
        gaps = []

        async def ticker():
            prev = time.perf_counter()
            while True:
                await asyncio.sleep(0.002)
                now = time.perf_counter()
                gaps.append(now - prev - 0.002)
                prev = now

        t = asyncio.ensure_future(ticker())
        await asyncio.sleep(0.02)
        t0 = time.perf_counter()
        stale = await master._ingest_parts(
            srv.cs_id, infos, collect_stale=True
        )
        ingest_s = time.perf_counter() - t0
        t.cancel()
        assert not stale
        assert len(reg._server_parts[srv.cs_id]) == 100_000
        worst = max(gaps)
        # each slice is REGISTER_INGEST_SLICE applies; the loop must
        # breathe between slices (the whole walk would be ~ingest_s)
        assert worst < max(0.05, ingest_s / 4), (
            f"loop stalled {worst*1e3:.0f} ms during a "
            f"{ingest_s*1e3:.0f} ms ingest"
        )
    finally:
        await master.stop()


def test_synth_populate_op_digest_and_convergence():
    """The storm loader's one-op bulk create: incremental digest stays
    exact (shadow divergence detection holds) and two stores applying
    the same op land on the same checksum (what shadow convergence
    rides)."""
    op = {
        "op": "synth_populate", "parent": 1, "base_inode": 1000,
        "base_chunk": 500, "count": 5_000, "servers": 8, "copies": 2,
        "ts": 1234,
    }
    stores = [MetadataStore(), MetadataStore()]
    for s in stores:
        s.apply(dict(op))
        assert s._digest == s.full_digest(), "digest drifted"
    a, b = stores
    assert a.checksum() == b.checksum()
    assert len(a.fs.nodes) == 5_001  # root + files
    assert len(a.registry.chunks) == 5_000
    # parts landed on the synthetic servers (replica locates need them)
    chunk = a.registry.chunks[500]
    assert len(chunk.parts) == 2
    # and the synthetic namespace is a real one: lookup works
    node = a.fs.lookup(1, "sf1000")
    assert node.chunks == [500]
    assert node.length == 65536


@pytest.mark.slow
@pytest.mark.asyncio
async def test_locate_storm_million_inodes():
    """The full-fat storm (ISSUE 7 acceptance shape): ~1M inodes/chunks
    bulk-loaded through the changelog, thousands of synthetic servers,
    real primary+shadow+worker processes. Slow-marked — minutes, not
    tier-1; the compact storm rides bench_cluster and the process-level
    e2e lives in test_process_cluster.py."""
    from benches.bench_master_storm import run_storm

    row = await run_storm(
        files=1_000_000, servers=10_000, secs=5.0, real_cs=64,
        parts_per_cs=2_000,
    )
    assert row["shadow_caught_up"], "shadow never converged on 1M inodes"
    assert row["primary_only"]["locate_qps"] > 0
    assert row["with_replica"]["shadow_reads"] > 0, \
        "replica never engaged under the 1M-inode storm"
    # the loop must keep breathing through populate + ingest + storm
    # (yield-point discipline; a handful of stalls is scheduler noise,
    # a synchronous full walk would be hundreds)
    assert row["loop_stalls"] < 20


def test_danger_aggregate_bootstrap_bounds_first_publish():
    """After a (re)start the danger aggregate must become exact within
    a bounded number of health ticks (budget-sized bootstrap sweeps) —
    NOT after the routine cursor's full cycle (review finding: /health
    reported lost=0 for ~an hour at 1M chunks post-restart)."""
    reg = ChunkRegistry()
    srv = reg.register_server("h", 1, "_", 1 << 40, 0)
    n = 20_000
    for i in range(n):
        cid = 100 + i
        reg.create_chunk(0, chunk_id=cid, version=1, copies=1)
        if i % 100 != 0:  # every 100th chunk is partless -> lost
            reg.record_part(reg.chunks[cid], srv.cs_id, 0)
    assert reg.danger_counts == (0, 0, 0)
    ticks = 0
    while not reg.danger_counts[2]:
        reg.danger_bootstrap(budget=4096)
        ticks += 1
        assert ticks <= (n // 4096) + 2, "bootstrap never published"
    endangered, lost, scanned = reg.danger_counts
    assert scanned == n
    assert lost == n // 100
    assert endangered == 0
    # once published, bootstrap is a no-op (the routine walk owns the
    # aggregate from here) and the counts stay put
    reg.danger_bootstrap()
    assert reg.danger_counts == (endangered, lost, scanned)
