"""Master scalability mechanics at 100k+ entities.

VERDICT round-1 asks (reference analogs: filesystem_checksum.cc
incremental digest, metadata_dumper.h:37 forked dump, chunks.cc
1807-1830 incremental health walk): with 100k+ inodes/chunks, the
checksum probe is O(1), the image dump must not stall the event loop
for the serialization time, and a health tick is O(budget) not
O(all chunks).
"""

import asyncio
import time

import pytest

from lizardfs_tpu.master import fs as fsmod
from lizardfs_tpu.master.chunks import ChunkRegistry
from lizardfs_tpu.master.fs import Node
from lizardfs_tpu.master.metadata import MetadataStore
from lizardfs_tpu.master.server import MasterServer

N_FILES = 100_000


def _populate(meta: MetadataStore, n_files: int = N_FILES) -> None:
    """Bulk-load a big namespace directly (test setup only), then
    re-anchor the incremental digest once."""
    fs = meta.fs
    root = fs.nodes[1]
    for i in range(n_files):
        inode = 10 + i
        node = Node(
            inode=inode, ftype=fsmod.TYPE_FILE, mode=0o644, uid=1, gid=1,
            atime=1, mtime=1, ctime=1, goal=1, trash_time=86400, nlink=1,
            parents=[1], length=65536, chunks=[100 + i],
        )
        fs.nodes[inode] = node
        root.children[f"f{i}"] = inode
        meta.registry.create_chunk(0, chunk_id=100 + i, version=1, copies=2)
    fs.next_inode = 10 + n_files
    meta.reset_digest()


def test_checksum_probe_is_o1():
    meta = MetadataStore()
    _populate(meta)
    t0 = time.perf_counter()
    for _ in range(100):
        meta.checksum()
    per_probe = (time.perf_counter() - t0) / 100
    assert per_probe < 0.001, f"checksum probe {per_probe*1e3:.2f} ms"
    # and the incremental digest tracks ops without recomputation
    t0 = time.perf_counter()
    meta.apply({
        "op": "mknode", "parent": 1, "name": "new", "inode": 5_000_000,
        "ftype": fsmod.TYPE_FILE, "mode": 0o644, "uid": 1, "gid": 1,
        "ts": 2, "goal": 1, "trash_time": 0,
    })
    per_op = time.perf_counter() - t0
    assert per_op < 0.05, f"apply with digest {per_op*1e3:.1f} ms"
    assert meta._digest == meta.full_digest()


def test_health_tick_bounded():
    meta = MetadataStore()
    _populate(meta)
    reg: ChunkRegistry = meta.registry
    # a tick evaluates at most SCAN_BUDGET + endangered items
    t0 = time.perf_counter()
    for _ in range(10):
        reg.health_work(limit=16)
    per_tick = (time.perf_counter() - t0) / 10
    assert per_tick < 0.02, f"health tick {per_tick*1e3:.1f} ms"
    # the cursor makes progress: after enough ticks every chunk has been
    # visited at least once (full cycle of 100k / 256 per tick)
    ticks_for_cycle = (N_FILES // reg.SCAN_BUDGET) + 2
    for _ in range(ticks_for_cycle):
        reg.health_work(limit=16)
    assert reg._scan_idx <= len(reg._scan_ids)


def test_endangered_queue_priority_not_cursor():
    """The endangered queue must hold only marked chunks, drain FIFO,
    and never degenerate into a full-table scan cursor."""
    meta = MetadataStore()
    _populate(meta, n_files=1000)
    reg = meta.registry
    reg.register_server("127.0.0.1", 1, "_", 1 << 40, 0)
    # all chunks have zero live parts -> unreadable, not endangered work
    # items; mark three explicitly and verify they drain first, FIFO
    for cid in (100, 500, 900):
        reg.mark_endangered(cid)
    assert list(reg.endangered) == [100, 500, 900]
    reg.health_work(limit=64)
    assert not reg.endangered  # drained, not re-queued wholesale
    assert len(reg._endangered_set) == 0


@pytest.mark.asyncio
async def test_forked_dump_does_not_stall_loop(tmp_path, monkeypatch):
    # this test pins the FORK path's property (loop pauses for the fork,
    # not the serialization). The test process has jax loaded, which the
    # fork gate refuses (tests/test_fork_safety.py covers that side), so
    # force the gate open here.
    from lizardfs_tpu.master import server as msrv

    monkeypatch.setattr(msrv, "_fork_safe", lambda: True)
    master = MasterServer(str(tmp_path / "m"), image_interval=3600.0)
    await master.start()
    try:
        _populate(master.meta, n_files=50_000)
        # how long a synchronous serialization would block
        t0 = time.perf_counter()
        master.meta.to_sections()
        sync_cost = time.perf_counter() - t0

        gaps = []

        async def ticker():
            prev = time.perf_counter()
            while True:
                await asyncio.sleep(0.005)
                now = time.perf_counter()
                gaps.append(now - prev - 0.005)
                prev = now

        t = asyncio.ensure_future(ticker())
        await asyncio.sleep(0.05)
        await master._dump_image()
        t.cancel()
        worst = max(gaps)
        # the loop may pause for the fork itself, never for the full
        # serialization
        assert worst < max(0.1, sync_cost / 4), (
            f"loop stalled {worst*1e3:.0f} ms during dump "
            f"(sync serialization would be {sync_cost*1e3:.0f} ms)"
        )
    finally:
        await master.stop()


def test_incremental_digest_tracks_every_op():
    """After every op type the incremental digest must equal a full
    recomputation (drift would break shadow divergence detection)."""
    s = MetadataStore()
    ops = [
        {"op": "mknode", "parent": 1, "name": "d", "inode": 2,
         "ftype": fsmod.TYPE_DIR, "mode": 0o755, "uid": 0, "gid": 0,
         "ts": 100, "goal": 1, "trash_time": 86400},
        {"op": "mknode", "parent": 2, "name": "f", "inode": 3,
         "ftype": fsmod.TYPE_FILE, "mode": 0o644, "uid": 5, "gid": 5,
         "ts": 101, "goal": 1, "trash_time": 86400},
        {"op": "create_chunk", "chunk_id": 1, "slice_type": 0,
         "version": 1, "copies": 2, "goal_id": 1},
        {"op": "set_chunk", "inode": 3, "chunk_index": 0, "chunk_id": 1},
        {"op": "set_length", "inode": 3, "length": 12345, "ts": 102,
         "drop_chunks": False},
        {"op": "setattr", "inode": 3, "set_mask": 1, "mode": 0o600,
         "uid": 0, "gid": 0, "atime": 0, "mtime": 0, "ts": 103,
         "trash_time": 0},
        {"op": "set_xattr", "inode": 3, "name": "user.x", "value": "YWJj",
         "ts": 105},
        {"op": "set_quota", "kind": "user", "owner_id": 5,
         "soft_inodes": 1, "hard_inodes": 2, "soft_bytes": 3,
         "hard_bytes": 4, "remove": False},
        {"op": "lock_posix", "inode": 3, "sid": 7, "token": 1, "start": 0,
         "end": 10, "ltype": 2},
        {"op": "lock_release_session", "sid": 7},
        {"op": "unlink", "parent": 2, "name": "f", "ts": 106,
         "to_trash": True},
        {"op": "undelete", "inode": 3, "ts": 107},
        {"op": "rename", "parent_src": 2, "name_src": "f",
         "parent_dst": 1, "name_dst": "g", "ts": 108},
        {"op": "link", "inode": 3, "parent": 1, "name": "hard", "ts": 109},
        {"op": "unlink", "parent": 1, "name": "g", "ts": 110,
         "to_trash": True},
        {"op": "session_new", "sid": 9},
        {"op": "bump_chunk_version", "chunk_id": 1, "version": 2},
        {"op": "snapshot", "src_inode": 3, "dst_parent": 2,
         "dst_name": "snap", "inode_map": {"3": 50}, "ts": 111},
        {"op": "cow_chunk", "inode": 50, "chunk_index": 0,
         "old_chunk_id": 1, "new_chunk_id": 2, "slice_type": 0,
         "version": 1, "copies": 2, "goal_id": 1},
        {"op": "purge_trash", "inode": 999},
    ]
    for op in ops:
        s.apply(op)
        assert s._digest == s.full_digest(), f"drift after {op['op']}"


def test_server_disconnect_is_o_parts_not_o_chunks():
    """A chunkserver bounce must cost O(parts on that server), not
    O(all chunks): the per-server part index (reference: per-server
    chunk lists, matocsserv.cc server entries) bounds the disconnect
    walk. 1M chunks spread over 20 servers -> one disconnect touches
    ~50k parts and completes well under 50 ms."""
    reg = ChunkRegistry()
    n_servers = 20
    servers = [
        reg.register_server("127.0.0.1", 20000 + i, "_", 1 << 40, 0)
        for i in range(n_servers)
    ]
    n_chunks = 1_000_000
    for cid in range(1, n_chunks + 1):
        reg.create_chunk(0, chunk_id=cid, version=1, copies=1)
        chunk = reg.chunks[cid]
        reg.record_part(chunk, servers[cid % n_servers].cs_id, 0)
    victim = servers[3].cs_id
    t0 = time.perf_counter()
    affected = reg.server_disconnected(victim)
    dt = time.perf_counter() - t0
    assert len(affected) == n_chunks // n_servers
    assert dt < 0.05, f"disconnect took {dt*1e3:.1f} ms"
    # the dropped parts are really gone from the chunk-side sets
    assert all(
        (victim, 0) not in reg.chunks[cid].parts for cid in affected[:100]
    )
    # reconnect + re-report restores both the chunk set and the index
    reg.register_server("127.0.0.1", 20003, "_", 1 << 40, 0)
    reg.record_part(reg.chunks[affected[0]], victim, 0)
    assert (victim, 0) in reg.chunks[affected[0]].parts
    assert (affected[0], 0) in reg._server_parts[victim]


def test_part_index_stays_consistent_through_lifecycle():
    """add/drop/delete/disconnect keep chunk.parts and the per-server
    index in lockstep."""
    reg = ChunkRegistry()
    s1 = reg.register_server("h", 1, "_", 1 << 30, 0)
    s2 = reg.register_server("h", 2, "_", 1 << 30, 0)
    c = reg.create_chunk(0, chunk_id=7, version=1, copies=2)
    reg.record_part(c, s1.cs_id, 0)
    reg.record_part(c, s2.cs_id, 0)
    assert set(reg._server_parts[s1.cs_id]) == {(7, 0)}
    reg.drop_part(7, s1.cs_id, 0)  # std part id 0 == part 0
    assert not reg._server_parts[s1.cs_id]
    assert c.parts == {(s2.cs_id, 0)}
    reg.record_part(c, s1.cs_id, 0)
    reg.delete_chunk(7)
    assert not reg._server_parts[s1.cs_id]
    assert not reg._server_parts[s2.cs_id]
    # disconnect with an empty index is a no-op
    assert reg.server_disconnected(s1.cs_id) == []


def test_bytes_per_inode_budget():
    """Master RAM per inode stays within budget (doc/migration.md "BDB
    name storage" rationale): ~620 B/inode measured with slots=True at
    1M files; the test uses 200k files and an 800 B ceiling so noise
    and allocator variance don't flake it. If this fails after a Node
    change, re-measure and update migration.md."""
    import gc
    import tracemalloc

    n_files = 200_000
    gc.collect()
    tracemalloc.start()
    meta = MetadataStore()
    fs = meta.fs
    root = fs.nodes[1]
    for i in range(n_files):
        inode = 10 + i
        node = Node(
            inode=inode, ftype=fsmod.TYPE_FILE, mode=0o644, uid=1, gid=1,
            atime=1, mtime=1, ctime=1, goal=1, trash_time=86400, nlink=1,
            parents=[1], length=65536, chunks=[100 + i],
        )
        fs.nodes[inode] = node
        root.children[f"file_with_a_realistic_name_{i:07d}.dat"] = inode
    cur, _ = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    per_inode = cur / n_files
    assert per_inode < 800, f"{per_inode:.0f} bytes/inode exceeds budget"
