"""Linear-assignment label placement (linear_assignment_optimizer.h
analog): optimal matching where greedy strands constrained slots."""

import random
from dataclasses import dataclass

from lizardfs_tpu.master import assignment


def test_hungarian_known_optimum():
    cost = [
        [4, 1, 3],
        [2, 0, 5],
        [3, 2, 2],
    ]
    sol = assignment.solve(cost)
    total = sum(cost[i][sol[i]] for i in range(3))
    assert sorted(sol) == [0, 1, 2]
    assert total == 5  # 1 + 2 + 2


def test_hungarian_rectangular_leaves_columns_free():
    cost = [[10, 1, 10, 10], [1, 10, 10, 2]]
    sol = assignment.solve(cost)
    assert sol == [1, 0]


@dataclass
class Srv:
    label: str
    free_space: int


def test_labels_never_stranded_by_wildcards():
    """Slots {A, _} on servers {s0:A, s1:B}: the optimizer must give A
    its only matching server, sending the wildcard to B — a free-space
    greedy would grab s0 (more space) for the wildcard."""
    servers = [Srv("A", 1000), Srv("B", 10)]
    idx = assignment.assign_slots(
        ["A", "_"], servers, jitter=lambda i, j: 0
    )
    assert servers[idx[0]].label == "A"
    assert idx[1] != idx[0]


def test_two_constrained_slots_cross_assignment():
    """Slots {A, B} with servers {s0:B, s1:A}: needs the crossing."""
    servers = [Srv("B", 500), Srv("A", 500)]
    idx = assignment.assign_slots(["A", "B"], servers, lambda i, j: 0)
    assert [servers[j].label for j in idx] == ["A", "B"]


def test_mismatch_only_when_unavoidable():
    servers = [Srv("X", 100), Srv("X", 100), Srv("A", 100)]
    idx = assignment.assign_slots(["A", "A", "_"], servers, lambda i, j: 0)
    labels = [servers[j].label for j in idx]
    assert labels.count("A") == 1  # the one A server serves one A slot
    assert len(set(idx)) == 3  # all distinct


def test_free_space_preference_within_labels():
    servers = [Srv("_", 10), Srv("_", 10_000), Srv("_", 10)]
    counts = [0, 0, 0]
    rng = random.Random(7)
    for _ in range(50):
        idx = assignment.assign_slots(
            ["_"], servers, jitter=lambda i, j: rng.randrange(100)
        )
        counts[idx[0]] += 1
    assert counts[1] > 40  # the empty server wins almost always


def test_choose_servers_uses_optimizer(monkeypatch):
    """choose_servers satisfies a tight label pattern that a greedy
    wildcard-first ordering could strand."""
    from lizardfs_tpu.master.chunks import ChunkRegistry

    reg = ChunkRegistry()
    a = reg.register_server("h1", 1, "ssd", 10**12, 0)
    b = reg.register_server("h2", 2, "hdd", 10**12, 10**11)
    got = reg.choose_servers(2, labels=["ssd", "_"])
    assert got[0].cs_id == a.cs_id
    assert got[1].cs_id == b.cs_id


def test_choose_servers_overlong_labels(monkeypatch):
    """More labels than slots must not crash the optimizer gate."""
    from lizardfs_tpu.master.chunks import ChunkRegistry

    reg = ChunkRegistry()
    reg.register_server("h1", 1, "ssd", 10**12, 0)
    reg.register_server("h2", 2, "hdd", 10**12, 0)
    got = reg.choose_servers(2, labels=["ssd", "hdd", "ssd"])
    assert len(got) == 2
