"""Tape server support (matotsserv.cc analog): goals with a $tape slice
get archival whole-file copies on registered tape servers."""

import asyncio
import json
import os

import pytest

from lizardfs_tpu.core import geometry
from lizardfs_tpu.proto import status as st
from lizardfs_tpu.tapeserver.server import TapeServer

from tests.test_cluster import Cluster, make_goals

pytestmark = pytest.mark.asyncio

TAPE_GOAL = 12


def goals_with_tape():
    goals = make_goals()
    goals[TAPE_GOAL] = geometry.parse_goal_line(
        f"{TAPE_GOAL} archived : _ _ | $tape"
    )[1]
    return goals


def test_tape_goal_parsing():
    gid, g = geometry.parse_goal_line("12 archived : _ _ | $tape")
    assert gid == 12
    assert g.disk_slice().type.is_standard
    assert g.tape_copies() == 1
    # two tape copies on labeled tape servers
    _, g2 = geometry.parse_goal_line(
        "13 vault : $ec(3,2) | $tape { vaultA vaultB }"
    )
    assert g2.disk_slice().type.is_ec and g2.tape_copies() == 2
    assert g2.tape_labels() == ["vaultA", "vaultB"]
    # invalid combinations
    for bad in (
        "14 x : $tape",                 # no disk slice
        "14 x : $tape | _ _",           # tape before disk
        "14 x : _ | $tape | $tape",     # two tape slices
        "14 x : _ _ | $xor3",           # two disk slices
        "14 x : _ | $tape { a a }",     # repeated named tape label
    ):
        with pytest.raises(geometry.GoalConfigError):
            geometry.parse_goal_line(bad)
    # repeated wildcards are fine (two copies on any two servers)
    _, g3 = geometry.parse_goal_line("15 x : _ | $tape { _ _ }")
    assert g3.tape_copies() == 2


async def _wait_for(cond, timeout=8.0, interval=0.1):
    for _ in range(int(timeout / interval)):
        if await cond():
            return True
        await asyncio.sleep(interval)
    return False


async def test_tape_archive_and_fileinfo(tmp_path):
    cluster = Cluster(tmp_path, n_cs=3)
    cluster_goals = goals_with_tape()
    # Cluster.start builds its own goals; patch before start
    import tests.test_cluster as tc
    orig = tc.make_goals
    tc.make_goals = goals_with_tape
    try:
        await cluster.start()
    finally:
        tc.make_goals = orig
    ts = TapeServer(
        str(tmp_path / "tape"), ("127.0.0.1", cluster.master.port),
        label="vault",
    )
    await ts.start()
    try:
        c = await cluster.client()
        f = await c.create(1, "precious.dat")
        await c.setgoal(f.inode, TAPE_GOAL)
        payload = os.urandom(200_000)
        await c.write_file(f.inode, payload)

        # the master marks, drains, and records the archival copy
        async def archived():
            info = await c.tape_info(f.inode)
            return info["fresh"] >= 1 and not info["pending"]

        assert await _wait_for(archived), await c.tape_info(f.inode)
        info = await c.tape_info(f.inode)
        assert info["wanted"] == 1
        assert info["copies"][0]["label"] == "vault"

        # archive holds the exact bytes + metadata sidecar
        a = await c.getattr(f.inode)
        dest = tmp_path / "tape" / f"{f.inode}_{a.mtime}_{a.length}.tape"
        assert dest.read_bytes() == payload
        with open(str(dest) + ".json") as fmeta:
            meta = json.load(fmeta)
        assert meta["path"] == "/precious.dat"

        # rewriting the file makes the copy stale and re-archives
        await c.pwrite(f.inode, 0, b"NEWCONTENT")
        async def rearchived():
            i = await c.tape_info(f.inode)
            return i["fresh"] >= 1 and not i["pending"]
        assert await _wait_for(rearchived)
        a2 = await c.getattr(f.inode)
        dest2 = tmp_path / "tape" / f"{f.inode}_{a2.mtime}_{a2.length}.tape"
        assert dest2.read_bytes()[:10] == b"NEWCONTENT"

        # stale archive versions are reclaimed after the fresh copy
        async def reclaimed():
            tapes = [p for p in os.listdir(tmp_path / "tape")
                     if p.startswith(f"{f.inode}_") and p.endswith(".tape")]
            return tapes == [dest2.name]
        assert await _wait_for(reclaimed), os.listdir(tmp_path / "tape")

        # files without a tape goal are untouched
        g = await c.create(1, "plain.dat")
        await c.write_file(g.inode, b"xyz")
        await asyncio.sleep(1.5)
        info = await c.tape_info(g.inode)
        assert info["wanted"] == 0 and not info["copies"]
    finally:
        await ts.stop()
        await cluster.stop()


async def test_tape_label_matching(tmp_path):
    """A named tape label only accepts a server carrying that label; a
    non-matching server must not absorb the copy (and must not stall
    other placeable files behind it)."""
    import tests.test_cluster as tc

    def goals():
        g = make_goals()
        g[12] = geometry.parse_goal_line("12 vaulted : _ _ | $tape { vaultA }")[1]
        g[13] = geometry.parse_goal_line("13 anytape : _ _ | $tape")[1]
        return g

    cluster = Cluster(tmp_path, n_cs=2)
    orig = tc.make_goals
    tc.make_goals = goals
    try:
        await cluster.start()
    finally:
        tc.make_goals = orig
    scratch = TapeServer(
        str(tmp_path / "scratch"), ("127.0.0.1", cluster.master.port),
        label="scratch",
    )
    await scratch.start()
    vault = None
    try:
        c = await cluster.client()
        f_vault = await c.create(1, "vaulted.dat")
        await c.setgoal(f_vault.inode, 12)
        await c.write_file(f_vault.inode, b"v" * 1000)
        f_any = await c.create(1, "anytape.dat")
        await c.setgoal(f_any.inode, 13)
        await c.write_file(f_any.inode, b"a" * 1000)

        # the wildcard file archives on the scratch server even while
        # the vault file (queued first) has no eligible server
        async def any_done():
            i = await c.tape_info(f_any.inode)
            return i["fresh"] >= 1
        assert await _wait_for(any_done)
        info = await c.tape_info(f_vault.inode)
        assert info["pending"] and info["fresh"] == 0

        # a matching server arrives -> the vault copy lands on it
        vault = TapeServer(
            str(tmp_path / "vault"), ("127.0.0.1", cluster.master.port),
            label="vaultA",
        )
        await vault.start()

        async def vault_done():
            i = await c.tape_info(f_vault.inode)
            return i["fresh"] >= 1
        assert await _wait_for(vault_done)
        info = await c.tape_info(f_vault.inode)
        assert info["copies"][0]["label"] == "vaultA"
    finally:
        await scratch.stop()
        if vault is not None:
            await vault.stop()
        await cluster.stop()


async def test_tape_registration_rescan(tmp_path):
    """Files written BEFORE any tape server exists are archived once one
    registers (startup recovery scan)."""
    import tests.test_cluster as tc
    cluster = Cluster(tmp_path, n_cs=3)
    orig = tc.make_goals
    tc.make_goals = goals_with_tape
    try:
        await cluster.start()
    finally:
        tc.make_goals = orig
    ts = None
    try:
        c = await cluster.client()
        f = await c.create(1, "early.dat")
        await c.setgoal(f.inode, TAPE_GOAL)
        await c.write_file(f.inode, b"before tape server" * 100)
        info = await c.tape_info(f.inode)
        assert info["pending"] and info["fresh"] == 0

        ts = TapeServer(
            str(tmp_path / "tape"), ("127.0.0.1", cluster.master.port)
        )
        await ts.start()

        async def archived():
            i = await c.tape_info(f.inode)
            return i["fresh"] >= 1
        assert await _wait_for(archived)
    finally:
        if ts is not None:
            await ts.stop()
        await cluster.stop()
