"""Background task manager: incremental recursive jobs."""

import asyncio
import json

import pytest

from lizardfs_tpu.proto import framing, messages as m

from tests.test_cluster import Cluster, EC_GOAL


async def admin(port, command, payload):
    r, w = await asyncio.open_connection("127.0.0.1", port)
    await framing.send_message(
        w, m.AdminCommand(req_id=1, command=command, json=json.dumps(payload))
    )
    reply = await framing.read_message(r)
    w.close()
    return json.loads(reply.json), reply.status


@pytest.mark.asyncio
async def test_incremental_recursive_jobs(tmp_path):
    cluster = Cluster(tmp_path, n_cs=3)
    await cluster.start()
    try:
        c = await cluster.client()
        top = await c.mkdir(1, "big")
        inodes = []
        for i in range(3):
            d = await c.mkdir(top.inode, f"d{i}")
            for j in range(10):
                f = await c.create(d.inode, f"f{j}")
                await c.write_file(f.inode, b"z" * 1000)
                inodes.append(f.inode)
        port = cluster.master.port

        # subtree setgoal runs in batches off the admin protocol
        doc, status = await admin(
            port, "setgoal-task", {"inode": top.inode, "goal": EC_GOAL}
        )
        assert status == 0
        for _ in range(100):
            await asyncio.sleep(0.05)
            tasks, _ = await admin(port, "list-tasks", {})
            if all(t["finished"] for t in tasks):
                break
        assert (await c.getattr(inodes[0])).goal == EC_GOAL
        assert (await c.getattr(inodes[-1])).goal == EC_GOAL

        # recursive remove of the whole subtree
        doc, status = await admin(
            port, "rremove-task", {"parent": 1, "name": "big"}
        )
        assert status == 0
        for _ in range(200):
            await asyncio.sleep(0.05)
            tasks, _ = await admin(port, "list-tasks", {})
            if all(t["finished"] for t in tasks):
                break
        entries = await c.readdir(1)
        assert "big" not in [e.name for e in entries]
        done = [t for t in tasks if t["kind"] == "rremove-task"][0]
        assert done["done_units"] == 3 * 10 + 3 + 1  # files + dirs + root
        assert done["error"] == ""

        # bad submissions are rejected cleanly
        doc, status = await admin(port, "rremove-task", {"parent": 1, "name": "nope"})
        assert status != 0
    finally:
        await cluster.stop()
