"""End-to-end cluster tests: master + chunkservers + client in-process.

The asyncio analog of the reference's localhost multi-daemon system
tests (tests/tools/lizardfs.sh setup_local_empty_lizardfs): real
daemons, real sockets, fault injection by stopping daemons.
"""

import asyncio

import numpy as np
import pytest

from lizardfs_tpu.chunkserver.server import ChunkServer
from lizardfs_tpu.client.client import Client
from lizardfs_tpu.core import geometry
from lizardfs_tpu.master.server import MasterServer
from lizardfs_tpu.proto import status as st
from lizardfs_tpu.utils import data_generator

EC_GOAL = 10
XOR_GOAL = 11
WIDE_EC_GOAL = 13
STD2_GOAL = 2


def make_goals():
    goals = geometry.default_goals()
    goals[EC_GOAL] = geometry.parse_goal_line(f"{EC_GOAL} ectest : $ec(3,2)")[1]
    goals[XOR_GOAL] = geometry.parse_goal_line(f"{XOR_GOAL} xortest : $xor3")[1]
    goals[WIDE_EC_GOAL] = geometry.parse_goal_line(
        f"{WIDE_EC_GOAL} widetest : $ec(8,4)"
    )[1]
    return goals


class Cluster:
    def __init__(self, tmp_path, n_cs: int = 6, native_data_plane: bool = True):
        self.tmp_path = tmp_path
        self.n_cs = n_cs
        self.native_data_plane = native_data_plane
        self.master: MasterServer | None = None
        self.chunkservers: list[ChunkServer] = []
        self.clients: list[Client] = []

    async def start(self, health_interval=0.2):
        self.master = MasterServer(
            str(self.tmp_path / "master"),
            goals=make_goals(),
            health_interval=health_interval,
        )
        await self.master.start()
        for i in range(self.n_cs):
            cs = ChunkServer(
                str(self.tmp_path / f"cs{i}"),
                master_addr=("127.0.0.1", self.master.port),
                wave_timeout=0.2,
                native_data_plane=self.native_data_plane,
            )
            await cs.start()
            self.chunkservers.append(cs)

    async def client(self) -> Client:
        c = Client("127.0.0.1", self.master.port, wave_timeout=0.2)
        await c.connect()
        self.clients.append(c)
        return c

    async def stop(self):
        for c in self.clients:
            await c.close()
        for cs in self.chunkservers:
            await cs.stop()
        if self.master is not None:
            await self.master.stop()


@pytest.mark.asyncio
async def test_metadata_operations(tmp_path):
    cluster = Cluster(tmp_path, n_cs=1)
    await cluster.start()
    try:
        c = await cluster.client()
        d = await c.mkdir(1, "docs")
        f = await c.create(d.inode, "hello.txt")
        assert (await c.lookup(d.inode, "hello.txt")).inode == f.inode
        entries = await c.readdir(d.inode)
        assert [e.name for e in entries] == ["hello.txt"]
        await c.rename(d.inode, "hello.txt", 1, "moved.txt")
        assert (await c.lookup(1, "moved.txt")).inode == f.inode
        link = await c.link(f.inode, 1, "hard")
        assert link.nlink == 2
        s = await c.symlink(1, "sym", "/moved.txt")
        assert (await c.readlink(s.inode)) == "/moved.txt"
        await c.unlink(1, "moved.txt")
        with pytest.raises(st.StatusError) as e:
            await c.lookup(1, "moved.txt")
        assert e.value.code == st.ENOENT
        # goal validation
        with pytest.raises(st.StatusError):
            await c.setgoal(f.inode, 99)
    finally:
        await cluster.stop()


@pytest.mark.parametrize("goal,size", [
    (STD2_GOAL, 300_000),        # 2-copy replication, multi-block
    (EC_GOAL, 5 * 65536 + 777),  # ec(3,2), partial trailing block
    (XOR_GOAL, 4 * 65536 + 1),   # xor3
])
@pytest.mark.asyncio
async def test_write_read_roundtrip(tmp_path, goal, size):
    cluster = Cluster(tmp_path)
    await cluster.start()
    try:
        c = await cluster.client()
        f = await c.create(1, "data.bin")
        await c.setgoal(f.inode, goal)
        payload = data_generator.generate(0, size).tobytes()
        await c.write_file(f.inode, payload)
        attr = await c.getattr(f.inode)
        assert attr.length == size
        back = await c.read_file(f.inode)
        assert back == payload
        # ranged read crossing block boundaries
        back = await c.read_file(f.inode, offset=65530, size=20)
        assert back == payload[65530:65550]
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_degraded_read_after_chunkserver_death(tmp_path):
    """The round-1 north-star scenario: write at ec(3,2), kill a
    chunkserver, read back through recovery (byte-identical)."""
    cluster = Cluster(tmp_path)
    await cluster.start(health_interval=30.0)  # no repair: test raw recovery
    try:
        c = await cluster.client()
        f = await c.create(1, "ec.bin")
        await c.setgoal(f.inode, EC_GOAL)
        payload = data_generator.generate(7, 7 * 65536 + 4242).tobytes()
        await c.write_file(f.inode, payload)

        # find a chunkserver holding a DATA part of the chunk and kill it
        chunk = next(iter(cluster.master.meta.registry.chunks.values()))
        data_holder = next(cs for cs, p in sorted(chunk.parts) if p < 3)
        victim = next(
            s for s in cluster.chunkservers
            if s.port == cluster.master.meta.registry.servers[data_holder].port
        )
        await victim.stop()
        await asyncio.sleep(0.1)

        back = await c.read_file(f.inode)
        assert back == payload
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_health_loop_rebuilds_missing_part(tmp_path):
    """Kill a part holder; the master's health loop must command EC
    recovery onto a spare server (auto-heal, chunks.cc:1807 analog)."""
    cluster = Cluster(tmp_path)
    await cluster.start(health_interval=0.2)
    try:
        c = await cluster.client()
        f = await c.create(1, "heal.bin")
        await c.setgoal(f.inode, EC_GOAL)
        payload = data_generator.generate(11, 3 * 65536).tobytes()
        await c.write_file(f.inode, payload)

        registry = cluster.master.meta.registry
        chunk = next(iter(registry.chunks.values()))
        assert len(chunk.parts) == 5
        victim_cs_id, victim_part = sorted(chunk.parts)[0]
        victim = next(
            s for s in cluster.chunkservers
            if s.port == registry.servers[victim_cs_id].port
        )
        await victim.stop()

        # wait for the health loop to re-replicate the missing part
        for _ in range(100):
            await asyncio.sleep(0.1)
            state = registry.evaluate(chunk)
            if not state.missing_parts:
                break
        state = registry.evaluate(chunk)
        assert not state.missing_parts, "health loop did not rebuild the part"
        # the rebuilt part must live on a previously-unused server
        back = await c.read_file(f.inode)
        assert back == payload
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_master_restart_recovers_metadata(tmp_path):
    """Changelog replay across master restart (auto-recovery analog)."""
    cluster = Cluster(tmp_path, n_cs=3)
    await cluster.start()
    master_port = cluster.master.port
    try:
        c = await cluster.client()
        d = await c.mkdir(1, "persist")
        f = await c.create(d.inode, "f.bin")
        await c.write_file(f.inode, b"x" * 100_000)
        inode = f.inode
    finally:
        await cluster.stop()

    # restart master on the same data dir (new port); fresh chunkservers
    # re-register their parts
    master2 = MasterServer(str(tmp_path / "master"), goals=make_goals())
    await master2.start()
    try:
        servers = []
        for i in range(3):
            cs = ChunkServer(
                str(tmp_path / f"cs{i}"),
                master_addr=("127.0.0.1", master2.port),
            )
            await cs.start()
            servers.append(cs)
        c2 = Client("127.0.0.1", master2.port)
        await c2.connect()
        d2 = await c2.lookup(1, "persist")
        f2 = await c2.lookup(d2.inode, "f.bin")
        assert f2.inode == inode
        assert f2.length == 100_000
        back = await c2.read_file(f2.inode)
        assert back == b"x" * 100_000
        await c2.close()
        for cs in servers:
            await cs.stop()
    finally:
        await master2.stop()


@pytest.mark.asyncio
async def test_overwrite_shorter_truncates(tmp_path):
    """Overwriting with shorter content must not leave stale tail bytes."""
    cluster = Cluster(tmp_path, n_cs=3)
    await cluster.start()
    try:
        c = await cluster.client()
        f = await c.create(1, "o.bin")
        await c.write_file(f.inode, b"A" * 100_000)
        await c.write_file(f.inode, b"B" * 10_000)
        attr = await c.getattr(f.inode)
        assert attr.length == 10_000
        back = await c.read_file(f.inode)
        assert back == b"B" * 10_000
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_unlink_purge_deletes_parts_on_chunkservers(tmp_path):
    """Released chunks' parts must be deleted on chunkservers."""
    cluster = Cluster(tmp_path, n_cs=3)
    await cluster.start()
    try:
        c = await cluster.client()
        f = await c.create(1, "gone.bin")
        await c.write_file(f.inode, b"x" * 50_000)
        assert sum(len(cs.store.all_parts()) for cs in cluster.chunkservers) > 0
        # bypass trash: truncate to 0 releases the chunk immediately
        await c.truncate(f.inode, 0)
        for _ in range(50):
            await asyncio.sleep(0.1)
            if sum(len(cs.store.all_parts()) for cs in cluster.chunkservers) == 0:
                break
        assert sum(len(cs.store.all_parts()) for cs in cluster.chunkservers) == 0
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_concurrent_clients_create_distinct_chunks(tmp_path):
    """Two clients writing simultaneously must get distinct chunk ids."""
    cluster = Cluster(tmp_path, n_cs=3)
    await cluster.start()
    try:
        c1 = await cluster.client()
        c2 = await cluster.client()
        f1 = await c1.create(1, "c1.bin")
        f2 = await c2.create(1, "c2.bin")
        p1 = data_generator.generate(100, 200_000).tobytes()
        p2 = data_generator.generate(200, 200_000).tobytes()
        await asyncio.gather(
            c1.write_file(f1.inode, p1), c2.write_file(f2.inode, p2)
        )
        assert len(cluster.master.meta.registry.chunks) == 2
        assert (await c1.read_file(f1.inode)) == p1
        assert (await c2.read_file(f2.inode)) == p2
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_wide_ec_more_parts_than_servers(tmp_path):
    """ec(8,4) = 12 parts on 6 chunkservers: every server holds two
    parts of the SAME chunk. Regression: the on-disk filename lacked
    the part id, so sibling parts collided on one path and truncated
    each other (data loss at exactly this geometry)."""
    cluster = Cluster(tmp_path, n_cs=6)
    await cluster.start()
    try:
        c = await cluster.client()
        f = await c.create(1, "wide.bin")
        await c.setgoal(f.inode, WIDE_EC_GOAL)
        payload = data_generator.generate(7, 3_000_000).tobytes()
        await c.write_file(f.inode, payload)
        c.cache.invalidate(f.inode)
        assert (await c.read_file(f.inode)) == payload
        # every data/parity part must exist somewhere
        loc = await c.chunk_info(f.inode, 0)
        parts = {geometry.ChunkPartType.from_id(pl.part_id).part
                 for pl in loc.locations}
        assert parts == set(range(12))
        # degraded read still works after losing one doubled-up server
        kill_port = loc.locations[0].addr.port
        for cs in cluster.chunkservers:
            if cs.port == kill_port:
                await cs.stop()
        c.cache.invalidate(f.inode)
        assert (await c.read_file(f.inode)) == payload
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_mixed_goals_kill_audit_and_degraded_reads(tmp_path):
    """Mirror of the operator smoke scenario: three files at ec(3,2),
    xor3, and 2-copy std on 5 servers; one server killed. Every file
    must stay readable, the registry's per-server part index must stay
    consistent with chunk.parts through write/kill/repair, and the
    repair loop must converge (no endless replicate-failure churn)."""
    import os

    cluster = Cluster(tmp_path, n_cs=5)
    await cluster.start()
    try:
        c = await cluster.client()
        d = await c.mkdir(1, "v")
        payloads = {}
        for name, goal in (("ec.bin", EC_GOAL), ("xor.bin", XOR_GOAL),
                           ("std.bin", 2)):
            f = await c.create(d.inode, name)
            await c.setgoal(f.inode, goal)
            p = os.urandom(1024 * 1024)
            await c.write_file(f.inode, p)
            payloads[name] = (f.inode, p)
        reg = cluster.master.meta.registry
        assert reg.audit_index() == []

        victim = cluster.chunkservers[0]
        await victim.stop()
        await asyncio.sleep(0.5)
        assert reg.audit_index() == []
        for name, (inode, p) in payloads.items():
            c.cache.invalidate(inode)
            back = await c.read_file(inode)
            assert bytes(back) == p, f"degraded mismatch {name}"

        # repair must converge: every chunk healthy again, index clean
        for _ in range(100):
            await asyncio.sleep(0.1)
            if all(not reg.evaluate(ch).needs_work
                   for ch in reg.chunks.values()):
                break
        assert all(not reg.evaluate(ch).needs_work
                   for ch in reg.chunks.values()), "repair did not converge"
        assert reg.audit_index() == []
        for name, (inode, p) in payloads.items():
            c.cache.invalidate(inode)
            back = await c.read_file(inode)
            assert bytes(back) == p, f"post-repair mismatch {name}"
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_emergency_doubled_part_migrates_when_server_joins(tmp_path):
    """ec(3,2) on exactly 5 servers: when one dies, the missing part can
    only be repaired by doubling up on a survivor (degraded but better
    than endangered). Once a replacement server joins, the doubled part
    must migrate off so fault tolerance returns to one-part-per-server."""
    import os

    cluster = Cluster(tmp_path, n_cs=5)
    await cluster.start()
    try:
        c = await cluster.client()
        f = await c.create(1, "e.bin")
        await c.setgoal(f.inode, EC_GOAL)
        payload = os.urandom(1024 * 1024)
        await c.write_file(f.inode, payload)
        reg = cluster.master.meta.registry

        await cluster.chunkservers[0].stop()
        # repair converges by doubling up on a survivor
        chunk = next(ch for ch in reg.chunks.values() if ch.slice_type != 0)
        for _ in range(100):
            await asyncio.sleep(0.1)
            if not reg.evaluate(chunk).missing_parts:
                break
        state = reg.evaluate(chunk)
        assert not state.missing_parts, "repair did not converge"
        assert state.crowded, "expected a doubled-up emergency placement"

        # replacement capacity joins; the doubled part must migrate off
        newcs = ChunkServer(
            str(tmp_path / "cs_new"),
            master_addr=("127.0.0.1", cluster.master.port),
            wave_timeout=0.2,
        )
        await newcs.start()
        cluster.chunkservers.append(newcs)
        for _ in range(150):
            await asyncio.sleep(0.1)
            state = reg.evaluate(chunk)
            if not state.crowded and not state.needs_work:
                break
        assert not state.crowded, "doubled part did not migrate off"
        assert state.is_safe
        assert reg.audit_index() == []
        c.cache.invalidate(f.inode)
        assert bytes(await c.read_file(f.inode)) == payload
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_resolve_dentry_cache(tmp_path):
    """Path walks cache intermediate DIRECTORY components (TTL +
    local-mutation invalidation); the leaf is always fresh so sizes
    can't go stale."""
    cluster = Cluster(tmp_path, n_cs=1)
    await cluster.start()
    try:
        c = await cluster.client()
        d1 = await c.mkdir(1, "a")
        d2 = await c.mkdir(d1.inode, "b")
        f = await c.create(d2.inode, "f.txt")
        await c.write_file(f.inode, b"12345")

        before = c.op_counters.get("CltomaLookup", 0)
        attr = await c.resolve("/a/b/f.txt")
        assert attr.inode == f.inode
        cold = c.op_counters.get("CltomaLookup", 0) - before
        assert cold == 3  # a, b, leaf

        before = c.op_counters.get("CltomaLookup", 0)
        attr = await c.resolve("/a/b/f.txt")
        warm = c.op_counters.get("CltomaLookup", 0) - before
        assert warm == 1, "intermediate dirs should come from the cache"
        assert attr.length == 5  # leaf attrs fresh

        # leaf freshness: a write's new size is visible immediately
        await c.pwrite(f.inode, 0, b"123456789")
        assert (await c.resolve("/a/b/f.txt")).length == 9

        # local rename invalidates the cached component
        await c.rename(1, "a", 1, "z")
        assert (await c.resolve("/z/b/f.txt")).inode == f.inode
        with pytest.raises(st.StatusError):
            await c.resolve("/a/b/f.txt")

        # TTL bounds cross-client staleness: another session's rename
        # becomes visible once the entry EXPIRES (genuinely exercise the
        # expiry comparison: short TTL set BEFORE the caching resolve)
        c.DENTRY_TTL = 0.05
        c._dentry.clear()
        assert (await c.resolve("/z/b/f.txt")).inode == f.inode  # cache @ short TTL
        c2 = await cluster.client()
        await c2.rename(1, "z", 1, "w")
        await asyncio.sleep(0.06)  # entry expires
        with pytest.raises(st.StatusError):
            await c.resolve("/z/b/f.txt")
        assert (await c.resolve("/w/b/f.txt")).inode == f.inode
    finally:
        await cluster.stop()
