"""Encoder auto-ladder: tpu means real silicon (VERDICT r05 weak #2).

On a JAX-installed box WITHOUT a TPU (this test environment — conftest
pins JAX to the CPU platform), "auto" must resolve to the native C++
SIMD backend, not the 3.8x-slower XLA bit-plane path, and Client's
default must follow the ladder instead of hardcoding the numpy golden
path.
"""

import numpy as np
import pytest

from lizardfs_tpu.core import native
from lizardfs_tpu.core.encoder import TpuChunkEncoder, get_encoder


def _jax_is_cpu_only() -> bool:
    import jax

    return all(d.platform == "cpu" for d in jax.devices())


def test_tpu_encoder_refuses_cpu_platform(monkeypatch):
    monkeypatch.delenv("LZ_TPU_ALLOW_CPU", raising=False)
    assert _jax_is_cpu_only(), "test box must be a JAX-without-TPU box"
    with pytest.raises(RuntimeError, match="CPU-platform"):
        TpuChunkEncoder()
    # explicit forcing still works (numerics tests, operators who mean it)
    enc = TpuChunkEncoder(force_cpu=True)
    rng = np.random.default_rng(0)
    data = [rng.integers(0, 256, 256, dtype=np.uint8) for _ in range(3)]
    assert len(enc.encode(3, 2, data)) == 2
    # env escape hatch
    monkeypatch.setenv("LZ_TPU_ALLOW_CPU", "1")
    TpuChunkEncoder()


def test_auto_ladder_degrades_to_cpp(monkeypatch):
    """JAX-without-TPU box => auto = cpp (the pin the satellite asks
    for). With the native .so absent it would degrade to cpu."""
    monkeypatch.delenv("LZ_TPU_ALLOW_CPU", raising=False)
    monkeypatch.delenv("LIZARDFS_TPU_ENCODER", raising=False)
    assert _jax_is_cpu_only()
    e = get_encoder("auto")
    if native.available():
        assert e.name == "cpp", (
            "auto selected the XLA-on-CPU path on a box without silicon"
        )
    else:
        assert e.name == "cpu"


def test_client_defaults_to_auto_ladder(monkeypatch):
    monkeypatch.delenv("LIZARDFS_TPU_ENCODER", raising=False)
    from lizardfs_tpu.client.client import Client

    c = Client("127.0.0.1", 1)  # never connected; just the constructor
    assert c.encoder.name == get_encoder("auto").name
    if native.available():
        assert c.encoder.name == "cpp"  # not the numpy golden default


def test_env_override_still_wins(monkeypatch):
    monkeypatch.setenv("LIZARDFS_TPU_ENCODER", "cpu")
    assert get_encoder(None).name == "cpu"
