"""Direct-thread native read path (FUSE latency path): NativeReadPool
reads bytes through liblizardfs_client.so without the asyncio loop."""

import asyncio
import os
import time

import pytest

from lizardfs_tpu.client import native_client

from tests.test_cluster import Cluster, EC_GOAL

pytestmark = pytest.mark.asyncio


async def test_native_pool_reads_and_fallback(tmp_path):
    if not native_client.available():
        pytest.skip("liblizardfs_client.so not built")
    cluster = Cluster(tmp_path, n_cs=6)
    await cluster.start()
    try:
        c = await cluster.client()
        f = await c.create(1, "hot.dat")
        blob = os.urandom(300_000)
        await c.write_file(f.inode, blob)

        pool = native_client.NativeReadPool(
            lambda: ("127.0.0.1", cluster.master.port)
        )
        try:
            # pool.read is a plain blocking call made from any thread
            got = await asyncio.to_thread(pool.read, f.inode, 0, 100_000)
            assert got == blob[:100_000]
            got = await asyncio.to_thread(pool.read, f.inode, 123_456, 4096)
            assert got == blob[123_456:127_552]
            # read past EOF truncates
            got = await asyncio.to_thread(
                pool.read, f.inode, len(blob) - 10, 4096
            )
            assert got == blob[-10:]
            # missing inode -> None (caller falls back to planner path)
            assert await asyncio.to_thread(pool.read, 999999, 0, 16) is None

            # degraded striped file -> None, planner path still serves it
            e = await c.create(1, "striped.dat")
            await c.setgoal(e.inode, EC_GOAL)
            sblob = os.urandom(200_000)
            await c.write_file(e.inode, sblob)
            locs = await c.chunk_info(e.inode, 0)
            kill_port = locs.locations[0].addr.port
            for cs in cluster.chunkservers:
                if cs.port == kill_port:
                    await cs.stop()
            nat = await asyncio.to_thread(pool.read, e.inode, 0, 1000)
            assert nat is None or nat == sblob[:1000]
            c.cache.invalidate(e.inode)
            assert (await c.read_file(e.inode, 0, 1000)) == sblob[:1000]
        finally:
            await asyncio.to_thread(pool.close)
    finally:
        await cluster.stop()


async def test_native_pool_latency_beats_loop_path(tmp_path):
    """The point of the pool: a small read through the C path costs
    less than the asyncio planner path (loop hop + python framing)."""
    if not native_client.available():
        pytest.skip("liblizardfs_client.so not built")
    cluster = Cluster(tmp_path, n_cs=2)
    await cluster.start()
    try:
        c = await cluster.client()
        f = await c.create(1, "lat.dat")
        blob = os.urandom(1 << 20)
        await c.write_file(f.inode, blob)
        pool = native_client.NativeReadPool(
            lambda: ("127.0.0.1", cluster.master.port)
        )
        try:
            def native_once(off):
                return pool.read(f.inode, off, 4096)

            # warm both paths
            assert (await asyncio.to_thread(native_once, 0)) == blob[:4096]
            await c.read_file(f.inode, 0, 4096)

            n = 50
            t0 = time.perf_counter()
            for i in range(n):
                await asyncio.to_thread(native_once, (i * 8192) % 900_000)
            native_s = time.perf_counter() - t0

            t0 = time.perf_counter()
            for i in range(n):
                c.cache.invalidate(f.inode)
                await c.read_file(f.inode, (i * 8192) % 900_000, 4096)
            loop_s = time.perf_counter() - t0
            # generous bound: just assert the native path isn't slower;
            # absolute numbers land in benches/bench_cluster.py
            assert native_s < loop_s * 1.5, (native_s, loop_s)
        finally:
            await asyncio.to_thread(pool.close)
    finally:
        await cluster.stop()
