"""Cross-role request tracing + Prometheus exposition.

Covers the PR-2 observability subsystem: span rings and timeline
merging (runtime/tracing.py), trailing-trace-field version skew (the
codec must serve peers that predate the field), the in-process-cluster
e2e (one write yields merged client+chunkserver+master spans), the
admin `trace-dump` command, and the Prometheus text format.
"""

import asyncio
import json

import pytest

from lizardfs_tpu.proto import framing, messages as m
from lizardfs_tpu.runtime import tracing
from lizardfs_tpu.runtime.metrics import Metrics

from tests.test_cluster import Cluster, EC_GOAL


# --- span ring + merge -----------------------------------------------------


def test_span_ring_records_and_bounds():
    ring = tracing.SpanRing(maxlen=4)
    for i in range(10):
        ring.record(7, f"op{i}", float(i), float(i) + 0.5, role="client")
    assert len(ring) == 4  # bounded, oldest evicted
    assert [s["name"] for s in ring.dump()] == ["op6", "op7", "op8", "op9"]
    # per-trace filter
    ring.record(9, "other", 0.0, 1.0, role="master")
    assert [s["name"] for s in ring.dump(9)] == ["other"]
    # trace id 0 never records (the disabled-path contract)
    before = len(ring)
    assert ring.record(0, "noop", 0.0, 1.0) == 0
    assert len(ring) == before


def test_trace_context_and_disable():
    tracing.clear_trace()
    assert tracing.current_trace_id() == 0
    tid = tracing.start_trace()
    assert tid != 0 and tracing.current_trace_id() == tid
    assert tracing.ensure_trace() == tid  # no new trace under an active one
    tracing.clear_trace()
    tracing.set_enabled(False)
    try:
        assert tracing.start_trace() == 0
        assert tracing.ensure_trace() == 0
    finally:
        tracing.set_enabled(True)


def test_merge_timeline_coverage():
    ring = tracing.SpanRing()
    tid = 42
    # root span = the rep wall: [0, 1.0]
    ring.record(tid, "write_file", 100.0, 101.0, role="client")
    # phase segments covering 90% of it, with overlap (union must dedupe)
    ring.record(tid, "encode", 100.0, 100.4, role="client")
    ring.record(tid, "send", 100.2, 100.7, role="client")
    ring.record(tid, "cs_write_bulk", 100.7, 100.9, role="chunkserver")
    tl = tracing.merge_timeline(ring.dump(), tid, wall_name="write_file")
    assert tl["wall_ms"] == pytest.approx(1000.0)
    assert tl["coverage_pct"] == pytest.approx(90.0)
    # root excluded from segments/by-role (it would trivially cover 100%)
    assert all(s["name"] != "write_file" for s in tl["segments"])
    assert tl["by_role_ms"]["chunkserver"] == pytest.approx(200.0)
    # client busy time sums raw durations (overlap is real concurrency)
    assert tl["by_role_ms"]["client"] == pytest.approx(900.0)
    # formatting smoke: one line per segment + header
    text = tracing.format_timeline(tl)
    assert "coverage 90.0%" in text and text.count("\n") == 3


def test_merge_timeline_empty_and_no_root():
    assert tracing.merge_timeline([], 5)["coverage_pct"] == 0.0
    spans = [{"trace_id": 3, "span_id": 1, "parent_id": 0, "role": "x",
              "name": "a", "t0": 10.0, "t1": 11.0}]
    tl = tracing.merge_timeline(spans, 3, wall_name="missing-root")
    # envelope fallback: the single span IS the wall -> full coverage
    assert tl["coverage_pct"] == pytest.approx(100.0)


# --- version skew: peers without the trailing trace field ------------------


def test_trailing_trace_field_version_skew():
    """A sender that predates ``trace_id`` still decodes (default 0);
    a frame cut inside a REQUIRED field still fails the parse."""
    msg = m.CltomaReadChunk(
        req_id=1, inode=2, chunk_index=3, uid=0, gids=[0], trace_id=77
    )
    body = msg.pack_body()
    old = body[:-8]  # exactly the pre-trace encoding
    decoded = m.CltomaReadChunk.parse(old)
    assert decoded.trace_id == 0
    assert (decoded.req_id, decoded.inode, decoded.chunk_index) == (1, 2, 3)
    # roundtrip with the field present
    assert m.CltomaReadChunk.parse(body).trace_id == 77
    # cut mid-required-field: still an error, not a zero-fill
    with pytest.raises(Exception):
        m.CltomaReadChunk.parse(old[:-2])

    # same for the data-plane WriteInit and the all-scalar WriteChunkEnd
    wi = m.CltocsWriteInit(
        req_id=1, chunk_id=9, version=1, part_id=64, chain=[], create=True,
        trace_id=55,
    )
    old_wi = wi.pack_body()[:-8]
    assert m.CltocsWriteInit.parse(old_wi).trace_id == 0
    assert m.CltocsWriteInit.parse(old_wi).create is True
    end = m.CltomaWriteChunkEnd(
        req_id=1, chunk_id=9, inode=2, chunk_index=0, file_length=10,
        status=0, trace_id=11,
    )
    old_end = end.pack_body()[:-8]
    decoded_end = m.CltomaWriteChunkEnd.parse(old_end)
    assert decoded_end.trace_id == 0 and decoded_end.file_length == 10
    # constructors may omit the optional trailing field too (call sites
    # predating the addition keep working)
    assert m.CltomaReadChunk(
        req_id=1, inode=2, chunk_index=3, uid=0, gids=[]
    ).trace_id == 0
    # the OTHER skew direction: an UNTRACED new sender elides the
    # default-valued trailing field entirely, so its encoding is
    # byte-identical to the pre-trace schema and an OLD receiver
    # (strict trailing-bytes check) still parses it
    untraced = m.CltomaReadChunk(
        req_id=1, inode=2, chunk_index=3, uid=0, gids=[0], trace_id=0
    )
    assert untraced.pack_body() == old
    assert m.CltocsWriteInit(
        req_id=1, chunk_id=9, version=1, part_id=64, chain=[], create=True,
    ).pack_body() == old_wi


def test_begin_end_scopes_trace_per_op():
    """An op that STARTED its trace clears the context on exit; two
    sequential top-level ops in one task get distinct trace ids, while
    an op under a caller-held trace joins it and leaves it in place."""
    tracing.clear_trace()
    tid1, fresh1 = tracing.begin()
    assert fresh1 and tid1 != 0
    tracing.end(fresh1)
    assert tracing.current_trace_id() == 0
    tid2, fresh2 = tracing.begin()
    tracing.end(fresh2)
    assert tid2 != tid1
    # nested: the inner op joins and must NOT clear the outer trace
    outer = tracing.start_trace()
    inner, fresh = tracing.begin()
    assert inner == outer and not fresh
    tracing.end(fresh)
    assert tracing.current_trace_id() == outer
    tracing.clear_trace()


@pytest.mark.asyncio
async def test_skewed_peer_is_served(tmp_path):
    """E2E skew: a hand-framed CltomaReadChunk WITHOUT the trailing
    trace field, sent over a real master connection, is decoded and
    answered (rolling-upgrade contract)."""
    cluster = Cluster(tmp_path, n_cs=3)
    await cluster.start()
    try:
        c = await cluster.client()
        f = await c.create(1, "skew.bin")
        await c.write_file(f.inode, b"x" * 1000)

        reader, writer = await asyncio.open_connection(
            "127.0.0.1", cluster.master.port
        )
        try:
            await framing.send_message(
                writer,
                m.CltomaRegister(req_id=1, session_id=0, info="old-peer",
                                 password=""),
            )
            reply = await framing.read_message(reader)
            assert reply.status == 0
            # old-schema frame: an untraced message's pack IS the
            # pre-trace encoding (trailing defaults are elided); build
            # the exact bytes an old peer would send by packing the
            # required prefix by hand
            msg = m.CltomaReadChunk(
                req_id=2, inode=f.inode, chunk_index=0, uid=0, gids=[0],
                trace_id=77,  # pack WITH the field...
            )
            body = msg.pack_body()[:-8]  # ...then strip it: old schema
            assert body == m.CltomaReadChunk(
                req_id=2, inode=f.inode, chunk_index=0, uid=0, gids=[0],
            ).pack_body()  # untraced pack == old encoding (elision)
            frame = framing.HEADER.pack(
                m.CltomaReadChunk.MSG_TYPE, len(body) + 1
            ) + bytes([framing.PROTO_VERSION]) + body
            writer.write(frame)
            await writer.drain()
            reply = await asyncio.wait_for(framing.read_message(reader), 10)
            assert isinstance(reply, m.MatoclReadChunk)
            assert reply.status == 0 and reply.file_length == 1000
        finally:
            writer.close()
    finally:
        await cluster.stop()


# --- e2e: one write yields a merged cross-role trace -----------------------


@pytest.mark.asyncio
async def test_traced_write_merges_across_roles(tmp_path):
    cluster = Cluster(tmp_path, n_cs=6)
    await cluster.start()
    try:
        c = await cluster.client()
        f = await c.create(1, "traced.bin")
        await c.setgoal(f.inode, EC_GOAL)  # ec(3,2): striped data plane
        tid = tracing.start_trace()
        try:
            # >= native threshold so the native data plane (when built)
            # records per-op receive/disk timestamps too
            await c.write_file(f.inode, b"t" * (9 * 2**20))
        finally:
            tracing.clear_trace()
        spans = list(c.trace_ring.dump(tid))
        spans += cluster.master.trace_spans(tid)
        for cs in cluster.chunkservers:
            spans += cs.trace_spans(tid)
        roles = {s["role"] for s in spans}
        assert {"client", "chunkserver", "master"} <= roles, roles
        names = {s["name"] for s in spans}
        assert "write_file" in names  # the rep's wall/root span
        assert "CltomaWriteChunk" in names  # master grant under the trace
        tl = tracing.merge_timeline(spans, tid, wall_name="write_file")
        assert tl["wall_ms"] > 0
        # the acceptance bar (>=90%) is measured by the bench on a quiet
        # box; here just require substantial attribution despite CI load
        assert tl["coverage_pct"] >= 50.0, tl
        assert set(tl["by_role_ms"]) >= {"client", "chunkserver"}
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_admin_trace_dump_and_metrics_prom(tmp_path):
    """`lizardfs-admin trace-dump` + `metrics-prom` over the admin link
    on both master and chunkserver ports."""
    cluster = Cluster(tmp_path, n_cs=3)
    await cluster.start()
    try:
        c = await cluster.client()
        f = await c.create(1, "dump.bin")
        tid = tracing.start_trace()
        try:
            await c.write_file(f.inode, b"d" * 300_000)
        finally:
            tracing.clear_trace()

        async def admin(port, command, payload="{}"):
            r, w = await asyncio.open_connection("127.0.0.1", port)
            await framing.send_message(
                w, m.AdminCommand(req_id=1, command=command, json=payload)
            )
            reply = await framing.read_message(r)
            w.close()
            return reply

        reply = await admin(
            cluster.master.port, "trace-dump",
            json.dumps({"trace_id": tid}),
        )
        assert reply.status == 0
        spans = json.loads(reply.json)["spans"]
        assert spans and all(s["trace_id"] == tid for s in spans)
        assert all(s["role"] == "master" for s in spans)
        # bad trace id -> EINVAL, not a crash
        reply = await admin(
            cluster.master.port, "trace-dump", json.dumps({"trace_id": "x"})
        )
        assert reply.status != 0

        for port in (cluster.master.port, cluster.chunkservers[0].port):
            reply = await admin(port, "metrics-prom")
            assert reply.status == 0
            text = json.loads(reply.json)["text"]
            _validate_prometheus(text)
    finally:
        await cluster.stop()


# --- prometheus text format ------------------------------------------------


def _validate_prometheus(text: str) -> None:
    """Structural validation of exposition-format 0.0.4 text."""
    assert text.endswith("\n")
    seen_types = {}
    seen_help = set()
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            name, _, help_text = line[len("# HELP "):].partition(" ")
            assert help_text, f"empty HELP for {name}"
            seen_help.add(name)
            continue
        if line.startswith("# TYPE "):
            _, _, name, mtype = line.split(" ")
            assert mtype in ("counter", "gauge", "histogram")
            # HELP precedes TYPE for every series (metrics-lint rule)
            assert name in seen_help, f"TYPE without HELP: {name}"
            seen_types[name] = mtype
            continue
        assert not line.startswith("#")
        name_part, _, value = line.rpartition(" ")
        float(value)  # parseable sample value
        base = name_part.split("{")[0]
        assert base[0].isalpha()
        assert all(ch.isalnum() or ch in "_:" for ch in base)
    assert seen_types, "no TYPE lines"


def test_prometheus_exposition_format():
    mt = Metrics()
    mt.counter("bytes_read").inc(1000)
    mt.gauge("loop_lag_ms").set(1.5)
    mt.counter("ops.read").inc(3)  # dots must sanitize
    mt.sample_all(1.0)
    mt.define("total", "bytes_read 2 MUL")
    t = mt.timing("CltomaCreate")
    for us in (1, 3, 100, 5000, 5000, 2_000_000):
        t.record(us / 1e6)
    text = mt.to_prometheus()
    _validate_prometheus(text)
    assert "lizardfs_bytes_read_total 1000" in text
    assert "lizardfs_loop_lag_ms 1.5" in text
    assert "lizardfs_ops_read_total 3" in text  # sanitized name
    # derived series export as gauges of their latest value
    assert "lizardfs_total 2000" in text
    # histogram: cumulative monotone buckets, +Inf == count, sum/count
    lines = [l for l in text.splitlines()
             if l.startswith("lizardfs_timing_CltomaCreate_us")]
    buckets = [l for l in lines if "_bucket{" in l]
    counts = [int(l.rpartition(" ")[2]) for l in buckets]
    assert counts == sorted(counts)
    assert buckets[-1].startswith(
        'lizardfs_timing_CltomaCreate_us_bucket{le="+Inf"}'
    )
    assert counts[-1] == 6
    assert any(l.startswith("lizardfs_timing_CltomaCreate_us_sum") for l in lines)
    assert "lizardfs_timing_CltomaCreate_us_count 6" in lines
    # bucket i covers [2^i, 2^(i+1)) us -> a 3 us sample lands in le="4"
    le4 = next(l for l in buckets if 'le="4"' in l)
    assert int(le4.rpartition(" ")[2]) == 2  # the 1us + 3us samples
