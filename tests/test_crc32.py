"""CRC32 golden path + GF(2) matrix machinery tests against zlib."""

import zlib

import numpy as np
import pytest

from lizardfs_tpu.ops import crc32 as crc


def test_crc32_is_zlib():
    rng = np.random.default_rng(0)
    for n in (0, 1, 3, 64, 1000, 65536):
        data = rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()
        assert crc.crc32(data) == zlib.crc32(data) & 0xFFFFFFFF
        assert crc.crc32(data, 0x12345678) == zlib.crc32(data, 0x12345678) & 0xFFFFFFFF


def test_combine_matches_concatenation():
    rng = np.random.default_rng(1)
    for la, lb in [(0, 0), (1, 1), (10, 0), (0, 10), (100, 255), (65536, 64)]:
        a = rng.integers(0, 256, size=la, dtype=np.uint8).tobytes()
        b = rng.integers(0, 256, size=lb, dtype=np.uint8).tobytes()
        assert crc.crc32_combine(crc.crc32(a), crc.crc32(b), lb) == crc.crc32(a + b)


def test_zeros_crc():
    for n in (0, 1, 64, 4096, 65536):
        assert crc.zeros_crc(n) == zlib.crc32(b"\0" * n) & 0xFFFFFFFF


def test_subblock_matrix_linear_map():
    # R(msg) == C_B @ bits(msg) for single sub-blocks, against raw recursion
    rng = np.random.default_rng(2)
    B = 64
    cb = crc.subblock_matrix(B)
    for _ in range(10):
        msg = rng.integers(0, 256, size=B, dtype=np.uint8)
        # raw register from 0 through the byte recursion
        reg = 0
        for byte in msg:
            reg = crc._raw_step(reg, int(byte))
        bits = np.unpackbits(msg, bitorder="little")
        got = (cb.astype(np.uint32) @ bits & 1).astype(np.uint8)
        assert crc._from_bits32(got) == reg


@pytest.mark.parametrize("block_size,sub", [(512, 64), (65536, 64), (65536, 256)])
def test_block_crc_via_matrices(block_size, sub):
    """Full batched-matrix CRC pipeline (numpy model of the TPU kernel)."""
    rng = np.random.default_rng(3)
    nblocks = 4
    blocks = rng.integers(0, 256, size=(nblocks, block_size), dtype=np.uint8)
    c_sub, levels, k_const = crc.block_crc_matrices(block_size, sub)

    n = block_size // sub
    bits = np.unpackbits(blocks, axis=1, bitorder="little").reshape(nblocks, n, 8 * sub)
    # sub-block partial registers: (nblocks, n, 32)
    partial = (bits @ c_sub.T.astype(np.uint32)) & 1
    # tree combine: merge adjacent pairs, shifting the left child
    for lvl, mat in enumerate(levels):
        partial = partial.reshape(nblocks, -1, 2, 32)
        left = (partial[:, :, 0, :] @ mat.T.astype(np.uint32)) & 1
        partial = left ^ partial[:, :, 1, :]
    partial = partial.reshape(nblocks, 32)
    # fold in affine constant: crc = R xor K
    got = np.array(
        [crc._from_bits32(partial[i]) ^ k_const for i in range(nblocks)],
        dtype=np.uint32,
    )
    want = crc.block_crcs_golden(blocks)
    np.testing.assert_array_equal(got, want)
