"""Per-inode extra-attribute flags (geteattr/seteattr).

Covers the full path: wire schema skew (trailing Attr.eattr), master
op + changelog/image persistence, CLI verbs, and enforcement in the
client cache paths (NOCACHE bypasses the block cache, NOENTRYCACHE
keeps inodes out of the dentry + NFS attr caches, NOOWNER makes every
uid act as the owner).
"""

import pytest

from lizardfs_tpu.constants import (
    EATTR_NOCACHE,
    EATTR_NOENTRYCACHE,
    EATTR_NOOWNER,
)
from lizardfs_tpu.client.client import Client
from lizardfs_tpu.master.server import MasterServer
from lizardfs_tpu.proto import messages as m
from lizardfs_tpu.proto import status as st
from lizardfs_tpu.tools import cli

from tests.test_cluster import Cluster, make_goals


def test_attr_eattr_version_skew():
    """Old peers (no trailing eattr) decode as 0; an untraced new attr
    packs byte-identically to the old schema."""
    attr = m.Attr(
        inode=5, ftype=1, mode=0o644, uid=0, gid=0, atime=0, mtime=0,
        ctime=0, nlink=1, length=10, goal=1, trash_time=0,
        eattr=EATTR_NOCACHE,
    )
    body = attr.pack_body()
    old = body[:-1]
    assert m.Attr.parse(old).eattr == 0
    assert m.Attr.parse(body).eattr == EATTR_NOCACHE
    plain = m.Attr(
        inode=5, ftype=1, mode=0o644, uid=0, gid=0, atime=0, mtime=0,
        ctime=0, nlink=1, length=10, goal=1, trash_time=0,
    )
    assert plain.eattr == 0 and plain.pack_body() == old


@pytest.mark.asyncio
async def test_seteattr_roundtrip_persistence_and_perms(tmp_path):
    master = MasterServer(str(tmp_path / "master"), goals=make_goals())
    await master.start()
    c = Client("127.0.0.1", master.port)
    await c.connect()
    try:
        f = await c.create(1, "flags.bin")
        # root chowns it to 1000 so the ownership gate has a subject
        await c.setattr(f.inode, 2 | 4, uid=1000, gid=1000)
        assert await c.geteattr(f.inode) == 0
        # non-owner non-root cannot set
        with pytest.raises(st.StatusError) as e:
            await c.seteattr(f.inode, EATTR_NOCACHE, uid=2000)
        assert e.value.code == st.EPERM
        # owner can; reply carries the updated attr
        attr = await c.seteattr(
            f.inode, EATTR_NOCACHE | EATTR_NOOWNER, uid=1000
        )
        assert attr.eattr == EATTR_NOCACHE | EATTR_NOOWNER
        # with NOOWNER set, a stranger may now mutate owner-gated state
        await c.seteattr(f.inode, EATTR_NOOWNER, uid=2000)
        await c.setgoal(f.inode, 2, uid=2000)
        # invalid bits are rejected
        with pytest.raises(st.StatusError):
            await c.seteattr(f.inode, 0x80)
    finally:
        await c.close()
        await master.stop()  # dumps the image
    # restart: the flag replayed from changelog/image
    master2 = MasterServer(str(tmp_path / "master"), goals=make_goals())
    await master2.start()
    c2 = Client("127.0.0.1", master2.port)
    await c2.connect()
    try:
        a = await c2.lookup(1, "flags.bin")
        assert a.eattr == EATTR_NOOWNER and a.goal == 2
    finally:
        await c2.close()
        await master2.stop()


@pytest.mark.asyncio
async def test_nocache_bypasses_block_cache(tmp_path):
    cluster = Cluster(tmp_path, n_cs=1)
    await cluster.start()
    try:
        c = await cluster.client()
        payload = b"n" * 200_000
        cached = await c.create(1, "cached.bin")
        await c.write_file(cached.inode, payload)
        bypass = await c.create(1, "nocache.bin")
        await c.write_file(bypass.inode, payload)
        await c.seteattr(bypass.inode, EATTR_NOCACHE)
        c.cache.invalidate(cached.inode)
        c.cache.invalidate(bypass.inode)
        # plain inode: a small read fills the block cache
        assert await c.read_file(cached.inode, 0, 65536) == payload[:65536]
        assert any(
            k[0] == cached.inode for k in c.cache._entries
        ), "control inode should have cached blocks"
        # flagged inode: same read leaves the cache untouched
        assert await c.read_file(bypass.inode, 0, 65536) == payload[:65536]
        assert not any(k[0] == bypass.inode for k in c.cache._entries)
        # and repeat reads never hit (they bypass the probe entirely)
        hits = c.cache.hits
        assert await c.read_file(bypass.inode, 0, 65536) == payload[:65536]
        assert c.cache.hits == hits
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_noentrycache_keeps_dentry_out(tmp_path):
    cluster = Cluster(tmp_path, n_cs=1)
    await cluster.start()
    try:
        c = await cluster.client()
        d = await c.mkdir(1, "private")
        await c.create(d.inode, "f.txt")
        await c.resolve("/private/f.txt")
        assert (1, "private") in c._dentry  # normally cached
        await c.seteattr(d.inode, EATTR_NOENTRYCACHE)
        c._dentry.clear()
        await c.resolve("/private/f.txt")
        assert (1, "private") not in c._dentry
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_noentrycache_in_nfs_attr_cache(tmp_path):
    from lizardfs_tpu.nfs import server as nfs

    cluster = Cluster(tmp_path, n_cs=1)
    await cluster.start()
    gw = nfs.NfsGateway("127.0.0.1", cluster.master.port)
    await gw.start()
    try:
        plain = await gw.client.create(1, "plain.txt")
        flagged = await gw.client.create(1, "flagged.txt")
        await gw.client.seteattr(flagged.inode, EATTR_NOENTRYCACHE)
        await gw._attr(plain.inode)
        assert plain.inode in gw._attr_cache
        await gw._attr(flagged.inode)
        assert flagged.inode not in gw._attr_cache
    finally:
        await gw.stop()
        await cluster.stop()


@pytest.mark.asyncio
async def test_cli_geteattr_seteattr(tmp_path, capsys):
    cluster = Cluster(tmp_path, n_cs=1)
    await cluster.start()
    master = f"127.0.0.1:{cluster.master.port}"

    async def run(*argv):
        return await cli._amain(["--master", master, *argv])

    try:
        c = await cluster.client()
        await c.create(1, "x.bin")
        assert await run("geteattr", "/x.bin") == 0
        assert "eattr -" in capsys.readouterr().out
        # absolute set
        assert await run("seteattr", "nocache,noowner", "/x.bin") == 0
        out = capsys.readouterr().out
        assert "noowner" in out and "nocache" in out
        # relative edit (leading '+' keeps argparse from reading the
        # token as an option; '-flag' works after a '+' first token)
        assert await run("seteattr", "+noentrycache,-noowner", "/x.bin") == 0
        out = capsys.readouterr().out
        assert "noowner" not in out and "noentrycache" in out \
            and "nocache" in out
        # unknown flag refused
        assert await run("seteattr", "bogus", "/x.bin") == 2
        # stat shows the flags
        assert await run("stat", "/x.bin") == 0
        assert '"nocache,noentrycache"' in capsys.readouterr().out
    finally:
        await cluster.stop()
