"""Wide-stripe sharded encode over the 8-device mesh vs golden bytes."""

import jax
import numpy as np
import pytest

from lizardfs_tpu.core.encoder import CpuChunkEncoder
from lizardfs_tpu.parallel.sharded import make_mesh, sharded_encode_with_crcs


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"
    return make_mesh()


@pytest.mark.parametrize("k,m", [(32, 8), (16, 4), (8, 8)])
def test_sharded_encode_byte_identical(mesh, k, m):
    bs, nb = 512, 16
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=(k, nb * bs), dtype=np.uint8)
    run = sharded_encode_with_crcs(mesh, k, m, bs)
    parity, dcrc, pcrc = run(data)
    cpu = CpuChunkEncoder()
    wp, wd, wpc = cpu.encode_with_checksums(k, m, data, block_size=bs)
    np.testing.assert_array_equal(np.asarray(parity).reshape(m, -1), wp)
    np.testing.assert_array_equal(np.asarray(dcrc), wd)
    np.testing.assert_array_equal(np.asarray(pcrc), wpc)


def test_sharded_rejects_bad_divisibility(mesh):
    with pytest.raises(ValueError):
        sharded_encode_with_crcs(mesh, 12, 4, 512)


@pytest.mark.parametrize("stripe,block", [(4, 2), (2, 4), (8, 1)])
def test_sharded_2d_mesh_byte_identical(stripe, block):
    from lizardfs_tpu.parallel.sharded import make_mesh_2d

    mesh = make_mesh_2d(stripe, block)
    k, m, bs = 8, 4, 512
    nb = 2 * stripe * block
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, size=(k, nb * bs), dtype=np.uint8)
    run = sharded_encode_with_crcs(mesh, k, m, bs)
    parity, dcrc, pcrc = run(data)
    cpu = CpuChunkEncoder()
    wp, wd, wpc = cpu.encode_with_checksums(k, m, data, block_size=bs)
    np.testing.assert_array_equal(np.asarray(parity).reshape(m, -1), wp)
    np.testing.assert_array_equal(np.asarray(dcrc), wd)
    np.testing.assert_array_equal(np.asarray(pcrc), wpc)


def test_mesh_2d_validates_device_count():
    from lizardfs_tpu.parallel.sharded import make_mesh_2d

    with pytest.raises(ValueError):
        make_mesh_2d(3, 2)


# --- realistic geometry (VERDICT round-1 weak #6): 64 KiB blocks, big
# parts — layout/collective bugs can't hide in toy shapes -----------------

def test_sharded_1d_realistic_64k_blocks_8mib_parts(mesh):
    """1-D mesh, 64 KiB blocks, 8 MiB parts (ec(8,4): 64 MiB logical)."""
    k, m, bs = 8, 4, 64 * 1024
    nb = 128  # 8 MiB per part
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, size=(k, nb * bs), dtype=np.uint8)
    run = sharded_encode_with_crcs(mesh, k, m, bs)
    parity, dcrc, pcrc = run(data)
    cpu = CpuChunkEncoder()
    wp, wd, wpc = cpu.encode_with_checksums(k, m, data, block_size=bs)
    np.testing.assert_array_equal(np.asarray(parity).reshape(m, -1), wp)
    np.testing.assert_array_equal(np.asarray(dcrc), wd)
    np.testing.assert_array_equal(np.asarray(pcrc), wpc)


def test_sharded_2d_realistic_64k_blocks(tmp_path):
    """2-D (stripe x block) mesh at 64 KiB blocks with 8 MiB parts.
    (The ec(32,8) 64 MiB-logical geometry runs in dryrun_multichip.)"""
    from lizardfs_tpu.parallel.sharded import make_mesh_2d

    mesh = make_mesh_2d(4, 2)
    k, m, bs = 4, 2, 64 * 1024
    nb = 128  # 8 MiB per part
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, size=(k, nb * bs), dtype=np.uint8)
    run = sharded_encode_with_crcs(mesh, k, m, bs)
    parity, dcrc, pcrc = run(data)
    cpu = CpuChunkEncoder()
    wp, wd, wpc = cpu.encode_with_checksums(k, m, data, block_size=bs)
    np.testing.assert_array_equal(np.asarray(parity).reshape(m, -1), wp)
    np.testing.assert_array_equal(np.asarray(dcrc), wd)
    np.testing.assert_array_equal(np.asarray(pcrc), wpc)
