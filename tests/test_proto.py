"""Protocol tests: serializer round trips + framed RPC over a fake server
(module_mock pattern: src/unittests/mocks/module_mock.h — a real
in-process server speaking the packet protocol)."""

import asyncio

import pytest

from lizardfs_tpu.proto import framing, messages as m, status
from lizardfs_tpu.proto.codec import message_class_for
from lizardfs_tpu.runtime.config import Config, ConfigError
from lizardfs_tpu.runtime.rpc import RpcConnection


def roundtrip(msg):
    encoded = framing.encode(msg)
    decoded = framing.decode(
        int.from_bytes(encoded[0:4], "big"), encoded[8:]
    )
    assert decoded == msg
    return decoded


def test_serializer_roundtrips():
    roundtrip(m.CltomaLookup(req_id=7, parent=1, name="héllo", uid=5, gids=[5, 6]))
    roundtrip(
        m.MatoclReadChunk(
            req_id=9,
            status=0,
            chunk_id=0xDEADBEEF01234567,
            version=3,
            file_length=1 << 40,
            locations=[
                m.PartLocation(
                    addr=m.Addr(host="10.0.0.1", port=9422), part_id=650
                ),
                m.PartLocation(
                    addr=m.Addr(host="10.0.0.2", port=9423), part_id=651
                ),
            ],
        )
    )
    roundtrip(
        m.CltocsWriteData(
            req_id=1,
            chunk_id=5,
            write_id=2,
            block=3,
            offset=100,
            crc=0x12345678,
            data=b"\x00\x01" * 1000,
        )
    )
    roundtrip(
        m.CstomaRegister(
            req_id=1,
            addr=m.Addr(host="localhost", port=1234),
            label="ssd",
            chunks=[m.ChunkPartInfo(chunk_id=1, version=1, part_id=650)],
            total_space=1 << 40,
            used_space=123,
            data_port=9423,
        )
    )
    roundtrip(m.MatomlChangelogLine(version=42, line="CREATE(1,foo)"))


def test_unknown_type_and_trailing_bytes():
    with pytest.raises(KeyError):
        message_class_for(65535)
    msg = m.CltomaGetattr(req_id=1, inode=2)
    body = msg.pack_body() + b"xx"
    with pytest.raises(ValueError):
        m.CltomaGetattr.parse(body)


def test_framing_rejects_bad_version():
    encoded = bytearray(framing.encode(m.CltomaGetattr(req_id=1, inode=2)))
    encoded[8] = 99  # corrupt version byte
    with pytest.raises(framing.ProtocolError):
        framing.decode(int.from_bytes(encoded[0:4], "big"), bytes(encoded[8:]))


@pytest.mark.asyncio
async def test_rpc_over_fake_server():
    """Fake master answering lookups; push message mid-stream."""

    async def handler(reader, writer):
        try:
            await _serve(reader, writer)
        finally:
            # python 3.12: Server.wait_closed() hangs until handler
            # transports are closed, so close explicitly
            writer.close()

    async def _serve(reader, writer):
        while True:
            try:
                msg = await framing.read_message(reader)
            except (asyncio.IncompleteReadError, ConnectionError):
                break
            if isinstance(msg, m.CltomaLookup):
                # push an unsolicited changelog line first
                framing.write_message(
                    writer, m.MatomlChangelogLine(version=1, line="x")
                )
                attr = m.Attr(
                    inode=42, ftype=m.FTYPE_FILE, mode=0o644, uid=0, gid=0,
                    atime=0, mtime=0, ctime=0, nlink=1, length=0, goal=1,
                    trash_time=0,
                )
                framing.write_message(
                    writer,
                    m.MatoclAttrReply(req_id=msg.req_id, status=0, attr=attr),
                )
            elif isinstance(msg, m.CltomaGetattr):
                framing.write_message(
                    writer,
                    m.MatoclStatusReply(req_id=msg.req_id, status=status.ENOENT),
                )
            await writer.drain()

    server = await asyncio.start_server(handler, "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    pushes = []

    conn = await RpcConnection.connect("127.0.0.1", port)
    async def on_line(msg):
        pushes.append(msg.line)
    conn.on_push(m.MatomlChangelogLine, on_line)

    # concurrent pipelined calls
    replies = await asyncio.gather(
        *(conn.call(m.CltomaLookup, parent=1, name=f"f{i}", uid=0, gids=[0]) for i in range(5))
    )
    assert all(r.attr.inode == 42 for r in replies)
    assert pushes == ["x"] * 5

    with pytest.raises(status.StatusError) as ei:
        await conn.call_ok(m.CltomaGetattr, inode=999)
    assert ei.value.code == status.ENOENT

    await conn.close()
    server.close()
    await server.wait_closed()


def test_config(tmp_path):
    p = tmp_path / "test.cfg"
    p.write_text(
        """
# comment
PORT = 9420
LABEL = ssd   # trailing comment
RATIO = 1.5
ENABLE = yes
"""
    )
    cfg = Config(str(p))
    assert cfg.get_int("PORT") == 9420
    assert cfg.get_str("LABEL") == "ssd"
    assert cfg.get_float("RATIO") == 1.5
    assert cfg.get_bool("ENABLE") is True
    assert cfg.get_int("MISSING", default=7) == 7
    with pytest.raises(ConfigError):
        cfg.get_int("MISSING")
    with pytest.raises(ConfigError):
        cfg.get_int("PORT", min_value=10000)
    # ranged floats: a zero timer interval would busy-loop a daemon
    assert cfg.get_float("RATIO", min_value=1.0) == 1.5
    with pytest.raises(ConfigError):
        cfg.get_float("RATIO", min_value=2.0)
    p.write_text("PORT = 1\n")
    cfg.reload()
    assert cfg.get_int("PORT") == 1


def test_skew_tolerant_nesting_guard():
    """A skew-tolerant message's optional tail elides at pack time, so
    its encoding has no fixed length — nesting one anywhere but the
    final field (or in a list) must fail at class-definition time, not
    misalign decodes at runtime."""
    from lizardfs_tpu.proto.codec import Message

    # terminal nesting is fine (MatoclAttrReply's real shape)
    class _OkTailNest(Message):
        MSG_TYPE = None
        FIELDS = (("req_id", "u32"), ("attr", "msg:Attr"))

    with pytest.raises(TypeError):
        class _BadMidNest(Message):
            MSG_TYPE = None
            FIELDS = (("attr", "msg:Attr"), ("req_id", "u32"))

    with pytest.raises(TypeError):
        class _BadListNest(Message):
            MSG_TYPE = None
            FIELDS = (("req_id", "u32"), ("attrs", "list:msg:Attr"))
