"""Torture test: randomized file ops under continuous fault injection.

The ContinuousTests/LongSystemTests analog (reference: killing daemons
mid-IO, e.g. test_xor_overwriting_faulty_chunkservers.sh): a shadow
model of the namespace + contents is maintained locally; random
writes/reads/renames/deletes interleave with chunkserver kills and
restarts; at the end, every surviving file must read back byte-exact
and chunks must return to full health.
"""

import asyncio
import random

import pytest

from lizardfs_tpu.chunkserver.server import ChunkServer
from lizardfs_tpu.constants import MFSBLOCKSIZE
from lizardfs_tpu.proto import status as st
from lizardfs_tpu.utils import data_generator

from tests.test_cluster import Cluster, EC_GOAL, XOR_GOAL


@pytest.mark.asyncio
async def test_torture_random_ops_with_failures(tmp_path):
    rng = random.Random(0xFEED)
    cluster = Cluster(tmp_path, n_cs=7)
    await cluster.start(health_interval=0.2)
    c = await cluster.client()
    c.retries = 8
    model: dict[str, bytes] = {}  # name -> contents
    inodes: dict[str, int] = {}
    goals = [2, EC_GOAL, XOR_GOAL]
    down: list[tuple[int, ChunkServer]] = []  # (index, stopped server)
    write_target: list[str | None] = [None]  # file a failed write may tear

    async def op_create():
        name = f"f{rng.randrange(10**9)}"
        attr = await c.create(1, name)
        await c.setgoal(attr.inode, rng.choice(goals))
        size = rng.randrange(1, 3 * MFSBLOCKSIZE)
        payload = data_generator.generate(rng.randrange(10**6), size).tobytes()
        inodes[name] = attr.inode
        write_target[0] = name
        await c.write_file(attr.inode, payload)
        model[name] = payload

    async def op_overwrite():
        if not model:
            return
        name = rng.choice(sorted(model))
        off = rng.randrange(0, max(len(model[name]), 1))
        size = rng.randrange(1, 2 * MFSBLOCKSIZE)
        patch = data_generator.generate(rng.randrange(10**6), size).tobytes()
        write_target[0] = name
        await c.pwrite(inodes[name], off, patch)
        buf = bytearray(model[name])
        if off + size > len(buf):
            buf.extend(b"\0" * (off + size - len(buf)))
        buf[off : off + size] = patch
        model[name] = bytes(buf)

    async def op_read():
        if not model:
            return
        name = rng.choice(sorted(model))
        assert await c.read_file(inodes[name]) == model[name], f"read {name}"

    async def op_delete():
        if not model:
            return
        name = rng.choice(sorted(model))
        await c.unlink(1, name)
        del model[name]
        del inodes[name]

    # sustained-file churn: open a file, unlink it, verify the handle
    # still reads, then release (the open/sustained registry rides the
    # same changelog as everything else — fault injection must not
    # desync it)
    held: list[tuple[str, int, bytes, int]] = []  # (name, inode, data, handle)

    async def op_open_unlink():
        if not model or len(held) >= 3:
            return
        name = rng.choice(sorted(model))
        inode = inodes[name]
        # zero trash time: the unlink must go through the SUSTAINED
        # path (a trashed file would survive by the trash, not the
        # open handle)
        await c.settrashtime(inode, 0)
        handle = await c.open(inode)
        await c.unlink(1, name)
        assert inode in cluster.master.meta.fs.sustained
        held.append((name, inode, model.pop(name), handle))
        del inodes[name]

    async def op_read_sustained():
        if not held:
            return
        _, inode, data, _ = rng.choice(held)
        assert await c.read_file(inode) == data, "sustained read"

    async def op_release_sustained():
        if not held:
            return
        _, inode, _, handle = held.pop(rng.randrange(len(held)))
        await c.release(inode, handle)

    async def op_rename():
        if not model:
            return
        name = rng.choice(sorted(model))
        new = f"r{rng.randrange(10**9)}"
        await c.rename(1, name, 1, new)
        model[new] = model.pop(name)
        inodes[new] = inodes.pop(name)

    async def op_kill_cs():
        alive = [
            (i, s) for i, s in enumerate(cluster.chunkservers)
            if s is not None and all(i != di for di, _ in down)
        ]
        # never take down more than 2 at once: ec(3,2)/xor3 tolerate it
        if len(down) >= 2 or len(alive) <= 4:
            return
        i, victim = rng.choice(alive)
        await victim.stop()
        down.append((i, victim))

    async def op_revive_cs():
        if not down:
            return
        i, dead = down.pop(rng.randrange(len(down)))
        # fresh daemon over the same data folder (restart semantics)
        cs = ChunkServer(
            str(tmp_path / f"cs{i}"),
            master_addr=("127.0.0.1", cluster.master.port),
            wave_timeout=0.2, heartbeat_interval=0.3,
        )
        await cs.start()
        cluster.chunkservers[i] = cs

    ops = [
        (op_create, 4), (op_overwrite, 5), (op_read, 6), (op_delete, 1),
        (op_rename, 1), (op_kill_cs, 1), (op_revive_cs, 2),
        (op_open_unlink, 1), (op_read_sustained, 2),
        (op_release_sustained, 1),
    ]
    weighted = [fn for fn, w in ops for _ in range(w)]

    try:
        for step in range(60):
            fn = rng.choice(weighted)
            write_target[0] = None
            try:
                await fn()
            except st.StatusError as e:
                # transient states are acceptable mid-fault; data loss is not
                assert e.code in (st.EIO, st.NO_CHUNK_SERVERS, st.CHUNK_BUSY), (
                    f"step {step} {fn.__name__}: {e}"
                )
                # a write that failed even after the client's internal
                # retries leaves that file's contents unspecified (POSIX
                # failed-write semantics): drop it from the shadow model
                torn = write_target[0]
                if torn is not None:
                    model.pop(torn, None)
                    inodes.pop(torn, None)

        # the random walk may never have drawn the sustained ops (seed-
        # dependent): exercise the path deterministically before the
        # final verify so this test ALWAYS covers it
        if not held:
            if not model:
                await op_create()
            await op_open_unlink()
        assert held, "sustained path never exercised"
        await op_read_sustained()

        # revive everything, let the cluster heal, then verify all bytes
        while down:
            await op_revive_cs()
        for _ in range(100):
            await asyncio.sleep(0.1)
            reg = cluster.master.meta.registry
            bad = [
                ch.chunk_id for ch in reg.chunks.values()
                if reg.evaluate(ch).missing_parts
            ]
            if not bad:
                break
        for name, payload in sorted(model.items()):
            got = await c.read_file(inodes[name])
            assert got == payload, f"final verify failed for {name}"
        # sustained files still read; releasing the last handle frees
        # them. The raw RPC (not the best-effort wrapper) so a release
        # failure fails HERE, not as a mystery leak assert below.
        from lizardfs_tpu.proto import messages as m

        for name, inode, data, handle in held:
            got = await c.read_file(inode)
            assert got == data, f"sustained verify failed for {name}"
            await c._call(m.CltomaRelease, inode=inode, handle=handle)
            assert inode not in cluster.master.meta.fs.nodes
        assert len(model) > 0  # the run actually created files
    finally:
        await cluster.stop()
