"""Server-side native read streaming (native/io_native.cpp:lz_serve_read)."""

import os

import pytest

from lizardfs_tpu.core import native_io
from lizardfs_tpu.chunkserver import chunk_store

from tests.test_cluster import Cluster

pytestmark = pytest.mark.skipif(
    not native_io.available(), reason="native lib not built"
)


@pytest.mark.asyncio
async def test_native_serve_read_roundtrip(tmp_path, monkeypatch):
    """With the C++ data-plane listener off, a large read must still be
    served by the asyncio server's bulk fallback path (builds without
    the full data plane), byte-identical."""
    from lizardfs_tpu.chunkserver.server import ChunkServer

    calls = []
    real = ChunkServer._serve_read_bulk

    async def spy(self, writer, msg):
        calls.append(msg)
        return await real(self, writer, msg)

    monkeypatch.setattr(ChunkServer, "_serve_read_bulk", spy)
    cluster = Cluster(tmp_path, n_cs=2, native_data_plane=False)
    await cluster.start()
    try:
        c = await cluster.client()
        data = bytes(os.urandom(1 << 20))
        f = await c.create(1, "big")
        await c.write_file(f.inode, data)
        assert (await c.read_file(f.inode)) == data
        assert calls, "native serve path was never taken"
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_native_serve_sparse_tail(tmp_path):
    """Reads past stored data come back as zeros (sparse semantics)."""
    cluster = Cluster(tmp_path, n_cs=1)
    await cluster.start()
    try:
        c = await cluster.client()
        f = await c.create(1, "sparse")
        await c.write_file(f.inode, b"\xaa" * 1000)
        await c.truncate(f.inode, 900 * 1024)  # extend far past data
        got = await c.read_file(f.inode)
        assert got[:1000] == b"\xaa" * 1000
        assert got[1000:] == b"\0" * (900 * 1024 - 1000)
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_native_serve_detects_corruption(tmp_path):
    """Bit rot on one replica: native CRC verify rejects it and the
    client recovers from the healthy copy."""
    cluster = Cluster(tmp_path, n_cs=2)
    await cluster.start()
    try:
        c = await cluster.client()
        data = bytes(os.urandom(512 * 1024))
        f = await c.create(1, "rotten")
        await c.setgoal(f.inode, 2)
        await c.write_file(f.inode, data)

        # flip one byte in the data region of every part on CS 0
        store = cluster.chunkservers[0].store
        parts = list(store.all_parts())
        assert parts
        for cf in parts:
            with open(cf.path, "r+b") as fh:
                fh.seek(chunk_store.HEADER_SIZE + 100)
                b = fh.read(1)
                fh.seek(chunk_store.HEADER_SIZE + 100)
                fh.write(bytes([b[0] ^ 0xFF]))

        assert (await c.read_file(f.inode)) == data
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_read_pipelined_behind_write_does_not_interleave(tmp_path):
    """A large read racing an unacknowledged pipelined write on the SAME
    connection must not let native raw-fd sends interleave with the
    write-status frame still owed by a background task."""
    import asyncio

    from lizardfs_tpu.ops import crc32 as crc_mod
    from lizardfs_tpu.proto import framing, messages as m

    cluster = Cluster(tmp_path, n_cs=1)
    await cluster.start()
    try:
        cs = cluster.chunkservers[0]
        reader, writer = await asyncio.open_connection("127.0.0.1", cs.port)
        framing.write_message(writer, m.CltocsWriteInit(
            req_id=1, chunk_id=7, version=1, part_id=0, chain=[], create=True,
        ))
        await writer.drain()
        st0 = await framing.read_message(reader)
        assert isinstance(st0, m.CstoclWriteStatus) and st0.status == 0

        # fill 4 blocks, then pipeline a big read before the last write acks
        payload = os.urandom(64 * 1024)
        for blk in range(4):
            framing.write_message(writer, m.CltocsWriteData(
                req_id=2 + blk, chunk_id=7, write_id=blk, block=blk,
                offset=0, crc=crc_mod.crc32(payload), data=payload,
            ))
        framing.write_message(writer, m.CltocsRead(
            req_id=50, chunk_id=7, version=1, part_id=0,
            offset=0, size=256 * 1024,
        ))
        await writer.drain()

        acks = 0
        got = bytearray(256 * 1024)
        done = False
        while not done or acks < 4:
            msg = await asyncio.wait_for(framing.read_message(reader), 5)
            if isinstance(msg, m.CstoclWriteStatus):
                assert msg.status == 0
                acks += 1
            elif isinstance(msg, m.CstoclReadData):
                assert crc_mod.crc32(msg.data) == msg.crc
                got[msg.offset:msg.offset + len(msg.data)] = msg.data
            elif isinstance(msg, m.CstoclReadStatus):
                assert msg.status == 0
                done = True
        # the read may overtake still-unacked writes (ordering between
        # unacked writes and reads is the client's job) — but every
        # frame must parse cleanly and each block is all-or-nothing
        for blk in range(4):
            piece = bytes(got[blk * 65536:(blk + 1) * 65536])
            assert piece in (payload, b"\0" * 65536)

        # after all acks, a second big read must see every block
        framing.write_message(writer, m.CltocsRead(
            req_id=60, chunk_id=7, version=1, part_id=0,
            offset=0, size=256 * 1024,
        ))
        await writer.drain()
        got2 = bytearray(256 * 1024)
        while True:
            msg = await asyncio.wait_for(framing.read_message(reader), 5)
            if isinstance(msg, m.CstoclReadData):
                assert crc_mod.crc32(msg.data) == msg.crc
                got2[msg.offset:msg.offset + len(msg.data)] = msg.data
            elif isinstance(msg, m.CstoclReadStatus):
                assert msg.status == 0
                break
        assert bytes(got2) == payload * 4
        writer.close()
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_truncated_header_not_served_as_zeros(tmp_path):
    """A chunk file truncated inside its 5 KiB header must yield an
    error, never fabricated sparse zeros with status OK."""
    import asyncio

    from lizardfs_tpu.proto.status import StatusError

    cluster = Cluster(tmp_path, n_cs=1)
    await cluster.start()
    try:
        c = await cluster.client()
        f = await c.create(1, "trunc")
        await c.write_file(f.inode, b"\xcd" * (256 * 1024))
        store = cluster.chunkservers[0].store
        for cf in store.all_parts():
            # signature intact, CRC table cut BEFORE the slots this read
            # needs — the native load must EIO, not zero-fill
            os.truncate(cf.path, 1030)
        with pytest.raises((StatusError, OSError)):
            await asyncio.wait_for(c.read_file(f.inode), 30)
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_native_qos_budget_paces_reads(tmp_path):
    """Multi-tenant QoS on the C++ plane: a per-session byte-rate
    budget (lz_serve_qos_set) paces that session's reads — bytes stay
    identical, the deferral counter moves, and replacing the table
    with an empty one unpaces (QoS fails open)."""
    cluster = Cluster(tmp_path, n_cs=1)
    await cluster.start()
    try:
        cs = cluster.chunkservers[0]
        if cs.data_server is None:
            pytest.skip("native data-plane listener unavailable")
        c = await cluster.client()
        data = bytes(os.urandom(2 << 20))
        f = await c.create(1, "qos.bin")
        await c.write_file(f.inode, data)
        # budget this session at 512 KiB/s (burst = one second): the
        # first 512 KiB read rides the burst, later ones pace (the
        # 2 s per-op cap keeps this bounded even if misconfigured)
        assert cs.data_server.qos_set({c.session_id: 512 * 1024})
        for off in range(0, 4):
            c.cache.invalidate(f.inode)
            got = await c.read_file(f.inode, off * 512 * 1024, 512 * 1024)
            assert got == data[off * 512 * 1024:(off + 1) * 512 * 1024]
        assert cs.data_server.qos_deferrals() >= 1, \
            "budgeted session was never paced"
        # wholesale replacement with an empty table unpaces
        assert cs.data_server.qos_set({})
        before = cs.data_server.qos_deferrals()
        c.cache.invalidate(f.inode)
        assert await c.read_file(f.inode) == data
        assert cs.data_server.qos_deferrals() == before
    finally:
        await cluster.stop()
