"""bench.py tail-surviving summary line: budget regression guard.

The r05 artifact landed ``parsed: null`` because the single JSON output
line outgrew the driver's ~2000-byte stdout tail and was cut mid-JSON.
bench.py now prints a compact summary LAST; this pins that the summary
stays inside the budget even as the schema grows — structurally (the
_fit_summary drop ladder), not by hoping.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def _fat_row() -> dict:
    """A worst-case-ish full row: every key family the bench can emit,
    with realistically wide values (r05-shaped)."""
    row = {
        "metric": "ec_encode_8_4_64MiB", "value": 11943.2, "unit": "MiB/s",
        "vs_baseline": 1.07,
        "kernel_config": "verified-16K/10M (big-tile fallback)",
        "kernel_ladder": {
            "big-tile-64K/11.5M": 11943.2,
            "verified-16K/10M": 10211.9,
            "wide-32K/11M": "RESOURCE_EXHAUSTED: VMEM overrun 12.3MiB",
        },
        "tpu_error": "tunnel dead after 3 spaced attempts",
        "reconstruct_1shard_cpu_ms": 123.45,
        "reconstruct_1shard_ms": 9.87,
        "ec8_2_batch1_cpu_us": 210.4, "ec8_2_batch1_us": 35.1,
        "box_cpus": 8, "box_memcpy_GBps": 11.2, "box_pyloop_ms": 102.4,
    }
    goals = ("goal_1_1_copy", "goal_2_2_copies", "xor3", "ec3_2", "ec8_4",
             "nfs_gateway", "nfs_gateway_C_client")
    for g in goals:
        row[f"cluster_{g}_write_MBps"] = 1234.5
        row[f"cluster_{g}_read_MBps"] = 2345.6
        row[f"cluster_{g}_spread_pct"] = 116.9
        row[f"cluster_{g}_write_reps_MBps"] = [402.3, 399.8, 434.9, 431.3,
                                               428.9]
        row[f"cluster_{g}_read_reps_MBps"] = [1797.6, 1773.6, 1137.6,
                                              1733.3, 1855.0]
    for g in ("goal_2_2_copies", "ec8_4"):
        row[f"cluster_{g}_write_target_MBps"] = 450.0
        row[f"cluster_{g}_write_target_met"] = False
    row["cluster_nfs_gateway_read_target_MBps"] = 199.5
    row["cluster_nfs_gateway_read_target_met"] = True
    for g in ("xor3", "ec3_2", "ec8_4"):
        row[f"cluster_{g}_write_phases"] = {
            "encode_ms": 1234.56, "stage_ms": 345.67, "send_ms": 4567.89,
            "ack_ms": 2345.67, "commit_ms": 123.45, "wall_ms": 5678.9,
            "reps": 5,
            # round 7: the send/encode busy-fraction ratio (<= 1.0 is
            # the shm-ring target; its verdict lives in the decimals)
            # plus the named dominant phase (the acceptance question
            # "if not send, what bounds the row now" answered in-row)
            "send_over_encode": 0.87, "dominant": "encode",
        }
        # adaptive write-window fiducials (round 6: depth settled +
        # segment/credit/coalesce deltas per striped row)
        row[f"cluster_{g}_write_window"] = {
            "depth": 8, "max_depth": 8, "segments": 1234,
            "credit_waits": 56, "commits_coalesced": 12,
        }
    # read-path microscope fiducials (this round: ISSUE 18) — healthy
    # striped read phase breakdowns + the ec(8,4) degraded-read
    # (parity recovery) variant row
    for g in ("xor3", "ec3_2", "ec8_4"):
        row[f"cluster_{g}_read_phases"] = {
            "locate_ms": 123.45, "dial_ms": 23.45, "wait_ms": 345.67,
            "net_ms": 2345.67, "decode_ms": 1234.56,
            "gather_ms": 456.78, "wall_ms": 3456.78, "reps": 5,
            "dominant": "net",
        }
    row["cluster_ec8_4_degraded_read_read_MBps"] = 987.6
    row["cluster_ec8_4_degraded_read_spread_pct"] = 24.3
    row["cluster_ec8_4_degraded_read_read_reps_MBps"] = [980.1, 987.6,
                                                         995.2]
    row["cluster_ec8_4_degraded_read_read_phases"] = {
        "locate_ms": 234.56, "dial_ms": 34.56, "wait_ms": 456.78,
        "net_ms": 1456.78, "decode_ms": 2345.67, "gather_ms": 345.67,
        "wall_ms": 4567.89, "reps": 5, "dominant": "decode",
    }
    row["cluster_ec8_4_write_trace"] = {
        "rep_MBps": 431.2, "wall_ms": 297.123, "coverage_pct": 94.7,
        "by_role_ms": {"client": 401.2, "chunkserver": 233.4,
                       "master": 12.9},
        "spans": 64,
    }
    # shm-ring A/B fiducial (round 7: same-host shared-memory data
    # plane on vs LZ_SHM_RING=0 scatterv)
    row["cluster_ec8_4_write_shm"] = {
        "on_MBps": 512.3, "off_MBps": 431.2, "delta_pct": 18.8,
        "desc_parts": 1536, "engaged": True,
    }
    row["cluster_dbench8_MBps"] = 330.3
    row["cluster_dbench8_ops_per_s"] = 990.9
    row["cluster_dbench8_MBps_reps"] = [351.6, 330.3, 324.6]
    row["cluster_4k_read_native_us"] = 184.8
    row["cluster_4k_read_loop_us"] = 484.6
    # slo/flight-recorder fiducials (PR 3): worst-case-ish shape — a
    # degraded round with breaches in every class
    row["cluster_health_status"] = "degraded"
    row["cluster_slo_breaches"] = 1234
    row["cluster_slow_ops"] = 48
    row["cluster_slo_breaches_by_class"] = {
        "read": 400, "write": 400, "locate": 234, "replicate": 100,
        "nfs": 100,
    }
    # rebuild subsystem fiducials (round 6: RebuildEngine bench row)
    row["cluster_rebuild_MBps"] = 1234.5
    row["cluster_rebuild_s"] = 12.34
    row["cluster_rebuild_parts"] = 48
    # s3 gateway fiducials (this round: the third protocol front door)
    row["cluster_s3_put_MBps"] = 123.4
    row["cluster_s3_get_MBps"] = 234.5
    row["cluster_s3_list_ops"] = 45.6
    row["cluster_s3_spread_pct"] = 33.3
    row["cluster_s3_put_reps_MBps"] = [120.1, 123.4, 130.9]
    row["cluster_s3_get_reps_MBps"] = [230.0, 234.5, 240.1]
    row["cluster_s3_list_ops_reps"] = [44.1, 45.6, 47.0]
    # locate storm fiducials (round 7: shadow read replicas — the
    # metadata-plane A/B with its 1.8x aggregate-QPS target verdict)
    row["cluster_locate_qps"] = {
        "primary": 12345.6, "replica_topo": 23456.7, "x": 1.9,
        "target_x": 1.8, "target_met": True,
        "shadow_served": 123456, "stale_retries": 12,
    }
    row["cluster_locate_p99_ms"] = {"primary": 12.34, "replica_topo": 10.56}
    # per-tenant QoS A/B fiducial (this round: fair-share admission) —
    # victim p99 off->on under an abuser flood with its bound verdict
    row["cluster_qos_victim_p99_ms"] = {
        "off": 187.5, "on": 6.2, "bound_ms": 250.0,
        "abuser_sheds": 312, "target_met": True,
    }
    # hot-spot A/B fiducial (this round: the heat loop's adaptive
    # replication) — one viral 1-copy chunk, LZ_HEAT off vs on
    row["cluster_hotspot_read_MBps"] = {
        "off": 812.4, "on": 934.7, "copies": 3, "boost_s": 1.85,
        "target_met": True,
    }
    # failover RTO fiducial (this round: ISSUE 19) — the kill-primary
    # drill's detect->elect->promote->first-acked-write outage
    row["cluster_failover_rto_s"] = {
        "rto_s": 3.77, "promote_s": 0.34, "epoch": 1,
        "acked": 11, "lost": 0, "target_met": True,
    }
    row["cluster_locate_storm_detail"] = {
        "files": 100000, "servers": 1000, "populate_s": 4.2,
        "cs_ingest": {"real_cs": 128, "parts_each": 2000, "ingest_s": 1.9},
        "loop_stalls": 0, "shadow_lag": 0,
    }
    # bench-trajectory regression guard (this round): worst-case-ish —
    # a round where several fiducials regressed past tolerance
    row["bench_prev_round"] = 11
    row["bench_deltas_pct"] = {
        f"cluster_{g}_write_MBps": -31.5
        for g in ("ec8_4", "ec3_2", "xor3", "goal_2_2_copies")
    }
    row["bench_regressions"] = [
        "cluster_ec8_4_write_MBps", "cluster_ec3_2_write_MBps",
        "cluster_goal_2_2_copies_write_MBps", "cluster_xor3_write_MBps",
        "cluster_dbench8_ops_per_s",
    ]
    return row


def test_summary_line_fits_driver_tail():
    line = json.dumps(bench._summary_row(_fat_row()))
    assert len(line) <= bench.SUMMARY_BUDGET_BYTES, len(line)
    assert len(line) < 2000  # the driver's hard tail window
    parsed = json.loads(line)
    assert parsed["summary"] == 1 and parsed["full"] == "BENCH_FULL.json"
    # the verdict-bearing fields survived the compaction
    assert parsed["cluster_ec8_4_write_target_met"] is False
    assert "cluster_ec8_4_write_phases" in parsed
    # instruments on the drop ladder may be cut on a worst-case round,
    # but then the cut is RECORDED — never silent, never mid-JSON
    assert (
        parsed.get("cluster_ec8_4_write_trace", {}).get("coverage_pct")
        == 94.7
        or "ec8_4_write_trace" in parsed.get("dropped", [])
    )
    # write-window fiducials ride the tail for the target row only
    # (xor3/ec3_2 window dicts stay in BENCH_FULL.json); under budget
    # pressure the dict may drop, but then the drop is RECORDED
    assert (
        parsed.get("cluster_ec8_4_write_window", {}).get("depth") == 8
        or "ec8_4_write_window" in parsed.get("dropped", [])
    )
    assert not any("xor3_write_window" in k for k in parsed)
    # the shm on/off A/B delta rides the tail (or its drop is recorded),
    # and the send/encode ratio survives int compaction with decimals
    assert (
        parsed.get("cluster_ec8_4_write_shm", {}).get("delta_pct") == 18.8
        or "ec8_4_write_shm" in parsed.get("dropped", [])
    )
    if "cluster_ec8_4_write_phases" in parsed:
        assert parsed["cluster_ec8_4_write_phases"][
            "send_over_encode"] == 0.87
        assert parsed["cluster_ec8_4_write_phases"]["dominant"] == "encode"
    # the read-phase fiducials (ISSUE 18): the ec(8,4) roofline rides
    # the tail (or its drop is recorded); xor3/ec3_2 read phases are
    # full-file-only, per-rep arrays likewise
    assert (
        parsed.get("cluster_ec8_4_read_phases", {}).get("dominant")
        == "net"
        or "ec8_4_read_phases" in parsed.get("dropped", [])
    )
    if "cluster_ec8_4_read_phases" in parsed:
        # integer-ms compaction, dominant preserved
        assert parsed["cluster_ec8_4_read_phases"]["net_ms"] == 2346
    assert (
        parsed.get("cluster_ec8_4_degraded_read_read_phases", {})
        .get("dominant") == "decode"
        or "ec8_4_degraded_read_read_phases"
        in parsed.get("dropped", [])
    )
    assert not any("xor3_read_phases" in k for k in parsed)
    assert not any("ec3_2_read_phases" in k for k in parsed)
    # the degraded-read throughput scalar always rides (it is a
    # _read_MBps key, never on the drop ladder)
    assert parsed["cluster_ec8_4_degraded_read_read_MBps"] == 987.6
    assert "cluster_ec8_4_degraded_read_read_reps_MBps" not in parsed
    # slo fiducials ride the tail: noise attribution from the artifact
    assert parsed["cluster_health_status"] == "degraded"
    assert parsed["cluster_slo_breaches"] == 1234
    assert parsed["cluster_slow_ops"] == 48
    # the rebuild row survives compaction (RebuildEngine fiducials)
    assert parsed["cluster_rebuild_MBps"] == 1234.5
    assert parsed["cluster_rebuild_s"] == 12.34
    # the s3 gateway row rides the tail (this round's new front door);
    # on a worst-case round it may drop — recorded, never silent — and
    # per-rep arrays stay in BENCH_FULL.json either way
    for skey, sval in (("cluster_s3_put_MBps", 123.4),
                       ("cluster_s3_get_MBps", 234.5),
                       ("cluster_s3_list_ops", 45.6)):
        assert (parsed.get(skey) == sval
                or "s3_*" in parsed.get("dropped", []))
    assert "cluster_s3_put_reps_MBps" not in parsed
    # the locate-storm A/B verdict rides the tail (or its drop is
    # recorded); the detail dict is full-file-only
    assert (
        parsed.get("cluster_locate_qps", {}).get("target_met") is True
        or "locate_qps" in parsed.get("dropped", [])
    )
    assert "cluster_locate_storm_detail" not in parsed
    # the QoS A/B verdict rides the tail (or its drop is recorded)
    assert (
        parsed.get("cluster_qos_victim_p99_ms", {}).get("target_met")
        is True
        or "qos_victim_p99_ms" in parsed.get("dropped", [])
    )
    # the hot-spot A/B verdict rides the tail (or its drop is recorded)
    assert (
        parsed.get("cluster_hotspot_read_MBps", {}).get("target_met")
        is True
        or "hotspot_read_MBps" in parsed.get("dropped", [])
    )
    # the failover RTO verdict rides the tail (or its drop is recorded);
    # it sits LATE on the ladder — this round's headline fiducial
    assert (
        parsed.get("cluster_failover_rto_s", {}).get("lost") == 0
        or "failover_rto_s" in parsed.get("dropped", [])
    )
    # the C-client NFS row is full-file-only (decision-note input):
    # it must never crowd verdict-bearing rows out of the tail
    assert not any("C_client" in k for k in parsed)
    # the regression guard's verdict rides the tail (or its drop is
    # recorded); the full per-key delta map is full-file-only
    assert (
        parsed.get("bench_regressions") == _fat_row()["bench_regressions"]
        or "bench_regressions" in parsed.get("dropped", [])
    )
    assert parsed.get("bench_prev_round") == 11
    assert "bench_deltas_pct" not in parsed


def test_bench_delta_guard():
    """Round-over-round fiducial comparison: direction-aware deltas,
    tolerance-gated regressions, metric-mismatch guard on `value`."""
    prev = {
        "metric": "kernelA", "value": 1000.0,
        "cluster_ec8_4_write_MBps": 400.0,
        "cluster_dbench8_ops_per_s": 900.0,
        "reconstruct_1shard_cpu_ms": 100.0,
        "cluster_4k_read_native_us": 200.0,
        "box_memcpy_GBps": 10.0,
        "cluster_ec8_4_write_phases": {"send_ms": 1.0},  # non-scalar: skip
    }
    row = {
        "metric": "kernelA", "value": 990.0,          # -1%: fine
        "cluster_ec8_4_write_MBps": 250.0,            # -37.5%: regression
        "cluster_dbench8_ops_per_s": 1200.0,          # +33%: improvement
        "reconstruct_1shard_cpu_ms": 140.0,           # +40% latency: regression
        "cluster_4k_read_native_us": 190.0,           # faster: fine
        "box_memcpy_GBps": 9.5,
        "cluster_ec8_4_write_phases": {"send_ms": 2.0},
        "cluster_error": "oops",                      # non-numeric: skip
    }
    deltas, regs = bench.bench_deltas(row, prev)
    assert regs == [
        "cluster_ec8_4_write_MBps", "reconstruct_1shard_cpu_ms",
    ]
    assert deltas["cluster_ec8_4_write_MBps"] == -37.5
    assert deltas["cluster_dbench8_ops_per_s"] == pytest.approx(33.3, 0.1)
    assert "cluster_ec8_4_write_phases" not in deltas
    # a changed kernel metric makes `value` incomparable
    d2, _ = bench.bench_deltas({**row, "metric": "kernelB"}, prev)
    assert "value" not in d2


def test_bench_round_self_record_and_reload(tmp_path):
    """bench self-records its round file (numbered past any existing
    file, parseable or not) and the next run loads it back as the
    comparison base; a driver-captured tail cut mid-JSON contributes
    nothing (the pre-guard trajectory)."""
    # a truncated driver capture like the real BENCH_r05.json
    (tmp_path / "BENCH_r05.json").write_text(json.dumps({
        "n": 5, "tail": 'y_write_reps_MBps": [721.7, 773.6], "clus',
    }))
    assert bench._load_prev_round(str(tmp_path)) is None
    row = {"metric": "kernelA", "value": 100.0,
           "cluster_ec8_4_write_MBps": 400.0}
    bench._bench_guard(row, str(tmp_path))
    assert "bench_guard_error" not in row
    assert (tmp_path / "BENCH_r06.json").exists()
    n, prev_row = bench._load_prev_round(str(tmp_path))
    assert n == 6 and prev_row["cluster_ec8_4_write_MBps"] == 400.0
    # the next round compares against it and flags the regression
    row2 = {"metric": "kernelA", "value": 99.0,
            "cluster_ec8_4_write_MBps": 100.0}
    bench._bench_guard(row2, str(tmp_path))
    assert row2["bench_prev_round"] == 6
    assert row2["bench_regressions"] == ["cluster_ec8_4_write_MBps"]
    assert (tmp_path / "BENCH_r07.json").exists()
    # a driver tail whose LAST line is whole JSON is minable
    (tmp_path / "BENCH_r08.json").write_text(json.dumps({
        "n": 8,
        "tail": 'garbage {"cut": \n'
                + json.dumps({"summary": 1, "value": 50.0,
                              "metric": "kernelA"}) + "\n",
    }))
    n, mined = bench._load_prev_round(str(tmp_path))
    assert n == 8 and mined["value"] == 50.0


def test_bench_guard_fresh_baseline(tmp_path, capsys):
    """An empty BENCH trajectory must record a fresh round cleanly and
    SAY so — an explicit first-round DELTA line + bench_prev_round=0 in
    the row — instead of silently printing no DELTA output (which reads
    as 'guard never ran' in the driver tail)."""
    row = {"metric": "kernelA", "value": 100.0}
    bench._bench_guard(row, str(tmp_path))
    out = capsys.readouterr().out
    assert "DELTA" in out and "fresh baseline" in out
    assert row["bench_prev_round"] == 0
    assert "bench_guard_error" not in row
    assert (tmp_path / "BENCH_r01.json").exists()
    # a recorded-but-empty round is skipped as a compare base (nothing
    # to diff against), but numbering still advances past it
    (tmp_path / "BENCH_r02.json").write_text(
        json.dumps({"n": 2, "self_recorded": True, "row": {}}))
    row2 = {"metric": "kernelA", "value": 99.0}
    bench._bench_guard(row2, str(tmp_path))
    assert row2["bench_prev_round"] == 1  # compared against r01, not r02
    assert (tmp_path / "BENCH_r03.json").exists()


def test_summary_budget_guard_drops_not_truncates():
    """A pathologically fat round trims whole keys (recorded in
    ``dropped``) instead of being cut mid-JSON by the tail window."""
    row = _fat_row()
    row["kernel_ladder"] = {
        f"config-{i}": "RESOURCE_EXHAUSTED: " + "x" * 80 for i in range(12)
    }
    s = bench._summary_row(row)
    line = json.dumps(s)
    assert len(line) <= bench.SUMMARY_BUDGET_BYTES
    assert json.loads(line) == s  # whole, valid JSON
    assert "kernel_ladder" in s.get("dropped", []) or "kernel_ladder" in s


def test_summary_immune_to_unknown_row_keys():
    """Subsystems that add FILES but no fiducials (e.g. the invariant
    lint engine) must not be able to regress the tail summary: the
    summary is allowlist-built, so arbitrary new row keys — however
    many, however fat — change NOTHING about the emitted line. This
    pins that property structurally instead of hoping each new
    subsystem remembers it."""
    base = bench._summary_row(_fat_row())
    row = _fat_row()
    for i in range(50):
        row[f"lint_findings_shard_{i}"] = {"rule": "x" * 120, "n": i}
    row["lint_waivers"] = ["cross-await-race"] * 100
    polluted = bench._summary_row(row)
    assert polluted == base  # byte-identical: unknown keys never ride
    assert len(json.dumps(polluted)) <= bench.SUMMARY_BUDGET_BYTES


def test_summary_keeps_targets_under_any_drop():
    row = _fat_row()
    row["kernel_ladder"] = {f"c{i}": "e" * 200 for i in range(20)}
    s = bench._summary_row(row)
    # target verdicts are never on the drop ladder
    assert "cluster_ec8_4_write_target_met" in s
    assert "cluster_goal_2_2_copies_write_target_met" in s
    # nor are the scalar slo fiducials (only the per-class split may
    # drop under pressure)
    assert "cluster_health_status" in s
    assert "cluster_slo_breaches" in s
