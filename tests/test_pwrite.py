"""Positional writes (read-modify-write), cache, readahead."""

import asyncio

import numpy as np
import pytest

from lizardfs_tpu.client.cache import BlockCache, ReadaheadAdviser
from lizardfs_tpu.constants import MFSBLOCKSIZE
from lizardfs_tpu.utils import data_generator

from tests.test_cluster import Cluster, EC_GOAL, XOR_GOAL


def test_block_cache_lru_and_invalidate():
    c = BlockCache(max_bytes=3 * 10)
    c.put(1, 0, 0, b"x" * 10)
    c.put(1, 0, 1, b"y" * 10)
    c.put(1, 1, 0, b"z" * 10)
    assert c.get(1, 0, 0) == b"x" * 10
    c.put(2, 0, 0, b"w" * 10)  # evicts LRU (1,0,1)
    assert c.get(1, 0, 1) is None
    c.invalidate(1, 1)
    assert c.get(1, 1, 0) is None
    assert c.get(1, 0, 0) is not None
    c.invalidate(1)
    assert c.get(1, 0, 0) is None


def test_readahead_adviser_grows_and_resets():
    a = ReadaheadAdviser()
    assert a.advise(0, 100) == 0  # first access: no window
    w1 = a.advise(100, 100)  # sequential: window appears
    assert w1 > 0
    w2 = a.advise(200, 100)
    assert w2 >= w1
    assert a.advise(10_000_000, 100) == 0  # seek resets


@pytest.mark.parametrize("goal", [2, EC_GOAL, XOR_GOAL])
@pytest.mark.asyncio
async def test_pwrite_random_offsets(tmp_path, goal):
    """Shadow-model test: random pwrites vs a local bytearray."""
    cluster = Cluster(tmp_path)
    await cluster.start()
    try:
        c = await cluster.client()
        f = await c.create(1, "rw.bin")
        await c.setgoal(f.inode, goal)
        size = 6 * MFSBLOCKSIZE + 1234
        base = data_generator.generate(0, size).tobytes()
        await c.write_file(f.inode, base)
        model = bytearray(base)

        rng = np.random.default_rng(42)
        for i in range(8):
            off = int(rng.integers(0, size - 1))
            ln = int(rng.integers(1, min(size - off, 3 * MFSBLOCKSIZE)))
            patch = data_generator.generate(10_000 + i, ln).tobytes()
            await c.pwrite(f.inode, off, patch)
            model[off : off + ln] = patch
            back = await c.read_file(f.inode)
            assert back == bytes(model), f"mismatch after patch {i} at {off}+{ln}"
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_pwrite_extends_file(tmp_path):
    cluster = Cluster(tmp_path)
    await cluster.start()
    try:
        c = await cluster.client()
        f = await c.create(1, "ext.bin")
        await c.setgoal(f.inode, EC_GOAL)
        await c.write_file(f.inode, b"head")
        # write past EOF: hole of zeros in between
        await c.pwrite(f.inode, 2 * MFSBLOCKSIZE + 7, b"tail")
        attr = await c.getattr(f.inode)
        assert attr.length == 2 * MFSBLOCKSIZE + 7 + 4
        back = await c.read_file(f.inode)
        assert back[:4] == b"head"
        assert back[4 : 2 * MFSBLOCKSIZE + 7] == b"\0" * (2 * MFSBLOCKSIZE + 3)
        assert back[-4:] == b"tail"
    finally:
        await cluster.stop()


@pytest.mark.asyncio
async def test_read_cache_serves_repeat_reads(tmp_path):
    cluster = Cluster(tmp_path)
    await cluster.start()
    try:
        c = await cluster.client()
        f = await c.create(1, "cache.bin")
        await c.setgoal(f.inode, EC_GOAL)
        payload = data_generator.generate(1, 4 * MFSBLOCKSIZE).tobytes()
        await c.write_file(f.inode, payload)
        a = await c.read_file(f.inode)
        hits0 = c.cache.hits
        b = await c.read_file(f.inode)
        assert b == payload == a
        assert c.cache.hits > hits0  # second read came from cache
        # write invalidates
        await c.pwrite(f.inode, 0, b"XY")
        back = await c.read_file(f.inode)
        assert back[:2] == b"XY" and back[2:] == payload[2:]
    finally:
        await cluster.stop()
