"""Multi-PROCESS system tests: real daemons, real kill -9.

The reference's system tier launches masters + chunkservers as separate
processes and kills them mid-IO (reference: tests/tools/lizardfs.sh
setup_local_empty_lizardfs; ShortSystemTests/test_cs_failure_during_
xor_read.sh). The in-process Cluster helper can only stop daemons
gracefully — SIGKILL semantics (no clean goodbye, kernel-closed
sockets, heartbeat-timeout paths, image+changelog replay on restart)
only show up with real processes."""

import asyncio
import os
import signal
import socket
import subprocess
import sys

import pytest

from lizardfs_tpu.client.client import Client
from lizardfs_tpu.proto import status as st
from lizardfs_tpu.utils import data_generator

pytestmark = pytest.mark.asyncio

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class ProcCluster:
    """master + N chunkservers as subprocesses on localhost."""

    def __init__(self, tmp_path, n_cs=3):
        self.tmp = tmp_path
        self.n_cs = n_cs
        self.master_port = _free_port()
        self.procs: dict[str, subprocess.Popen] = {}

    def _spawn(self, name: str, module: str, cfg_text: str) -> None:
        cfg = self.tmp / f"{name}.cfg"
        cfg.write_text(cfg_text)
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
                   PALLAS_AXON_POOL_IPS="")
        self.procs[name] = subprocess.Popen(
            [sys.executable, "-m", module, str(cfg)],
            stdout=open(self.tmp / f"{name}.log", "wb"),
            stderr=subprocess.STDOUT, env=env,
        )

    async def start(self) -> None:
        (self.tmp / "goals.cfg").write_text(
            "1 one : _\n5 ec32 : $ec(3,2)\n"
        )
        self._spawn(
            "master", "lizardfs_tpu.master",
            f"DATA_PATH = {self.tmp}/master\n"
            f"LISTEN_PORT = {self.master_port}\n"
            f"GOALS_CFG = {self.tmp}/goals.cfg\n"
            "HEALTH_INTERVAL = 0.3\n",
        )
        await self._wait_port(self.master_port)
        for i in range(self.n_cs):
            self._spawn(
                f"cs{i}", "lizardfs_tpu.chunkserver",
                f"DATA_PATH = {self.tmp}/cs{i}\n"
                f"LISTEN_PORT = {_free_port()}\n"
                f"MASTER_PORT = {self.master_port}\n"
                "HEARTBEAT_INTERVAL = 0.3\n",
            )
        # all chunkservers registered
        for _ in range(100):
            if await self._cs_count() >= self.n_cs:
                return
            await asyncio.sleep(0.1)
        raise AssertionError("chunkservers never registered")

    async def _cs_count(self) -> int:
        import json

        from lizardfs_tpu.proto import framing
        from lizardfs_tpu.proto import messages as m

        try:
            r, w = await asyncio.open_connection("127.0.0.1", self.master_port)
            await framing.send_message(w, m.AdminInfo(req_id=1))
            reply = await framing.read_message(r)
            w.close()
            return sum(
                1 for s in json.loads(reply.json)["chunkservers"]
                # mirror=True entries are a shadow's passive location
                # feed — counting them would mistake a mirror-fed
                # shadow for the active during active-discovery
                if s["connected"] and not s.get("mirror")
            )
        except (ConnectionError, OSError):
            return 0

    async def _wait_port(self, port: int, timeout=15.0) -> None:
        for _ in range(int(timeout / 0.1)):
            try:
                _, w = await asyncio.open_connection("127.0.0.1", port)
                w.close()
                return
            except (ConnectionError, OSError):
                await asyncio.sleep(0.1)
        raise AssertionError(f"port {port} never came up")

    def kill9(self, name: str) -> None:
        self.procs[name].send_signal(signal.SIGKILL)
        self.procs[name].wait(timeout=10)

    def stop(self) -> None:
        for p in self.procs.values():
            if p.poll() is None:
                p.terminate()
        for p in self.procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


async def test_sigkill_chunkserver_degraded_read(tmp_path):
    """kill -9 a chunkserver mid-cluster: EC reads recover through the
    survivors, and the health engine re-replicates."""
    cluster = ProcCluster(tmp_path, n_cs=4)
    try:
        await cluster.start()  # inside try: a failed start must not leak
        c = Client("127.0.0.1", cluster.master_port, wave_timeout=0.3)
        await c.connect()
        f = await c.create(1, "victim.bin")
        await c.setgoal(f.inode, 5)  # ec(3,2)
        payload = data_generator.generate(1, 5 * 2**20 + 333).tobytes()
        await c.write_file(f.inode, payload)

        cluster.kill9("cs0")  # no goodbye, no flush
        got = await c.read_file(f.inode)
        assert got == payload, "degraded read after SIGKILL"
        # health engine restores full redundancy on the 3 survivors:
        # every part of the ec(3,2) chunks reappears somewhere live
        from lizardfs_tpu.proto import framing
        from lizardfs_tpu.proto import messages as m

        async def endangered_count() -> int:
            import json

            r, w = await asyncio.open_connection(
                "127.0.0.1", cluster.master_port
            )
            await framing.send_message(
                w, m.AdminCommand(req_id=1, command="chunks-health", json="{}")
            )
            reply = await framing.read_message(r)
            w.close()
            doc = json.loads(reply.json)
            return int(doc.get("endangered", 0)) + int(doc.get("lost", 0))

        for _ in range(200):
            if await endangered_count() == 0:
                break
            await asyncio.sleep(0.1)
        else:
            raise AssertionError("health engine never restored redundancy")
        await c.close()
    finally:
        cluster.stop()


async def test_sigkill_master_restart_replays(tmp_path):
    """kill -9 the master (no image dump): the restart replays the
    changelog and serves the same namespace and bytes."""
    cluster = ProcCluster(tmp_path, n_cs=3)
    try:
        await cluster.start()  # inside try: a failed start must not leak
        c = Client("127.0.0.1", cluster.master_port, wave_timeout=0.3)
        await c.connect()
        f = await c.create(1, "durable.bin")
        await c.setgoal(f.inode, 5)
        payload = data_generator.generate(2, 2 * 2**20).tobytes()
        await c.write_file(f.inode, payload)
        await c.mkdir(1, "docs")
        await c.close()

        cluster.kill9("master")
        cluster._spawn(
            "master", "lizardfs_tpu.master",
            f"DATA_PATH = {tmp_path}/master\n"
            f"LISTEN_PORT = {cluster.master_port}\n"
            f"GOALS_CFG = {tmp_path}/goals.cfg\n"
            "HEALTH_INTERVAL = 0.3\n",
        )
        await cluster._wait_port(cluster.master_port)
        # chunkservers reconnect on their heartbeat (0.3 s interval)
        for _ in range(200):
            if await cluster._cs_count() >= 3:
                break
            await asyncio.sleep(0.1)
        else:
            raise AssertionError("chunkservers never re-registered")

        c2 = Client("127.0.0.1", cluster.master_port, wave_timeout=0.3)
        await c2.connect()
        attr = await c2.lookup(1, "durable.bin")
        assert attr.length == len(payload)
        assert (await c2.lookup(1, "docs")).inode > 0
        got = await c2.read_file(attr.inode)
        assert got == payload, "bytes lost across master SIGKILL"
        await c2.close()
    finally:
        cluster.stop()


async def test_sigkill_active_master_shadow_process_promotes(tmp_path):
    """Real-process HA failover (reference: uraftcontroller.cc +
    lizardfs-uraft-helper.in, minus the floating IP — clients and
    chunkservers carry the full master address list instead): SIGKILL
    the ACTIVE master process mid-write-stream; a shadow PROCESS wins
    the election, promotes, chunkservers re-register to it, the client
    fails over via its address list, and every acknowledged write is
    readable byte-identically afterwards."""
    cluster = ProcCluster(tmp_path, n_cs=3)
    pa, pb, pc = _free_port(), _free_port(), _free_port()
    ea, eb, ec = _free_port(), _free_port(), _free_port()
    peers = {"a": (pa, ea), "b": (pb, eb), "c": (pc, ec)}

    def master_cfg(me: str) -> str:
        port, eport = peers[me]
        others = ",".join(
            f"{pid}=127.0.0.1:{ep}" for pid, (_, ep) in peers.items()
            if pid != me
        )
        service = ",".join(
            f"{pid}=127.0.0.1:{p}" for pid, (p, _) in peers.items()
        )
        cfg = (
            f"DATA_PATH = {tmp_path}/master_{me}\n"
            f"LISTEN_PORT = {port}\n"
            f"GOALS_CFG = {tmp_path}/goals.cfg\n"
            "HEALTH_INTERVAL = 0.3\n"
            f"ELECTION_ID = {me}\n"
            f"ELECTION_LISTEN = 127.0.0.1:{eport}\n"
            f"ELECTION_PEERS = {others}\n"
            f"MASTER_PEERS = {service}\n"
        )
        if me != "a":
            cfg += (
                "PERSONALITY = shadow\n"
                f"ACTIVE_MASTER = 127.0.0.1:{pa}\n"
            )
        return cfg

    (tmp_path / "goals.cfg").write_text("1 one : _\n5 ec32 : $ec(3,2)\n")

    async def wait_active(exclude: int | None = None) -> int:
        """Port of the master every chunkserver is registered with —
        any node may win any election, so the leader is DISCOVERED,
        never assumed."""
        for _ in range(150):
            for port, _ep in peers.values():
                if port == exclude:
                    continue
                cluster.master_port = port
                if await cluster._cs_count() >= cluster.n_cs:
                    return port
            await asyncio.sleep(0.1)
        raise AssertionError("no master has all chunkservers registered")

    # ALL spawns happen inside try/finally: a failure during setup
    # (wait_port/wait_active raising) must still tear every spawned
    # process down — early versions leaked whole clusters on failure
    try:
        for me in ("a", "b", "c"):
            cluster._spawn(
                f"master_{me}", "lizardfs_tpu.master", master_cfg(me)
            )
        await cluster._wait_port(pa)
        addrs = ",".join(f"127.0.0.1:{p}" for p, _ in peers.values())
        for i in range(cluster.n_cs):
            cluster._spawn(
                f"cs{i}", "lizardfs_tpu.chunkserver",
                f"DATA_PATH = {tmp_path}/cs{i}\n"
                f"LISTEN_PORT = {_free_port()}\n"
                f"MASTER_ADDRS = {addrs}\n"
                "HEARTBEAT_INTERVAL = 0.3\n",
            )
        active = await wait_active()
        leader_name = next(
            f"master_{pid}" for pid, (p, _) in peers.items() if p == active
        )
        c = Client(
            "127.0.0.1", active, wave_timeout=0.3,
            master_addrs=[("127.0.0.1", p) for p, _ in peers.values()],
        )
        await c.connect("ha-e2e")
        payload = data_generator.generate(7, 1 * 2**20 + 17).tobytes()
        acked: list[str] = []
        for i in range(6):  # acked BEFORE the kill
            f = await c.create(1, f"pre_{i}.bin")
            await c.setgoal(f.inode, 5)
            await c.write_file(f.inode, payload)
            acked.append(f"pre_{i}.bin")

        async def version_of(port: int) -> int:
            import json

            from lizardfs_tpu.proto import framing
            from lizardfs_tpu.proto import messages as m

            try:
                r, w = await asyncio.open_connection("127.0.0.1", port)
                await framing.send_message(w, m.AdminInfo(req_id=1))
                reply = await framing.read_message(r)
                w.close()
                return int(json.loads(reply.json)["version"])
            except (ConnectionError, OSError):
                return -1

        # replication catch-up barrier: replica divergence is visible
        # operator state (AdminInfo version) and healthy failover
        # assumes synced shadows — same rule as the reference's
        # uraft tests. The controller's leader-following keeps every
        # replica on the live leader's stream, so this converges fast.
        for _ in range(100):
            versions = [await version_of(p) for p, _ in peers.values()]
            if len(set(versions)) == 1 and versions[0] > 0:
                break
            await asyncio.sleep(0.1)
        else:
            raise AssertionError(f"replicas never converged: {versions}")

        cluster.kill9(leader_name)

        # writes CONTINUE through failover: the client retries via its
        # address list; each op that returns is an acknowledged write
        for i in range(4):
            f = await c.create(1, f"post_{i}.bin")
            await c.setgoal(f.inode, 5)
            await c.write_file(f.inode, payload)
            acked.append(f"post_{i}.bin")
        assert c.current_master_addr[1] != active, \
            "client did not fail over to a promoted shadow"

        # chunkservers re-registered with the new active master
        new_active = await wait_active(exclude=active)
        assert new_active == c.current_master_addr[1]

        # every acknowledged write survives, byte-identical
        for name in acked:
            attr = await c.lookup(1, name)
            got = await c.read_file(attr.inode)
            assert got == payload, f"acknowledged write {name} lost"
        await c.close()
    finally:
        cluster.stop()


async def test_sigkill_rebuild_engine_status_and_trace(tmp_path):
    """The RebuildEngine acceptance e2e with a REAL kill -9: a
    SIGKILLed chunkserver's ec(3,2) parts are rebuilt under a
    byte/s throttle; `rebuild-status` shows the progress, the master's
    span ring carries per-rebuild `rebuild` spans, and the replicate
    SLO class accounted the work — all over the admin wire, like an
    operator would see it."""
    import json

    from lizardfs_tpu.proto import framing
    from lizardfs_tpu.proto import messages as m

    async def admin(port, command, payload="{}"):
        r, w = await asyncio.open_connection("127.0.0.1", port)
        try:
            await framing.send_message(
                w, m.AdminCommand(req_id=1, command=command, json=payload)
            )
            return await framing.read_message(r)
        finally:
            w.close()

    cluster = ProcCluster(tmp_path, n_cs=4)
    try:
        await cluster.start()
        # throttle: generous enough to finish fast, but every rebuild
        # pays the token bucket; cap at 2 concurrent
        for name, value in (("rebuild_bps", "200000000"),
                            ("rebuild_concurrency", "2")):
            reply = await admin(
                cluster.master_port, "tweaks-set",
                json.dumps({"name": name, "value": value}),
            )
            assert reply.status == st.OK, (name, reply.json)

        c = Client("127.0.0.1", cluster.master_port, wave_timeout=0.3)
        await c.connect()
        f = await c.create(1, "rebuildme.bin")
        await c.setgoal(f.inode, 5)  # ec(3,2)
        payload = data_generator.generate(3, 4 * 2**20 + 99).tobytes()
        await c.write_file(f.inode, payload)

        cluster.kill9("cs1")  # no goodbye: heartbeat-timeout path

        async def status_doc() -> dict:
            reply = await admin(cluster.master_port, "rebuild-status")
            assert reply.status == st.OK
            return json.loads(reply.json)

        for _ in range(300):
            doc = await status_doc()
            if doc["completed"] >= 1 and doc["endangered_queue"] == 0 \
                    and not doc["active"]:
                break
            await asyncio.sleep(0.1)
        else:
            raise AssertionError(f"rebuild never finished: {doc}")

        assert doc["bytes_rebuilt"] > 0
        assert doc["throttle"] == {
            "rebuild_bps": 200000000, "rebuild_concurrency": 2,
        }
        assert doc["recent"] and any(e["ok"] for e in doc["recent"])

        # the scheduler span is in the master's ring, named by the id
        # rebuild-status reported
        tid = next(e["trace_id"] for e in doc["recent"] if e["ok"])
        reply = await admin(
            cluster.master_port, "trace-dump",
            json.dumps({"trace_id": tid}),
        )
        spans = json.loads(reply.json)["spans"]
        assert any(s["name"] == "rebuild" for s in spans), spans

        # SLO integration: the master's replicate class saw the work
        reply = await admin(cluster.master_port, "health")
        master_snap = json.loads(reply.json)["master"]
        assert master_snap["slo"]["replicate"]["ops"] >= 1

        # and the bytes still read back whole (degraded or rebuilt)
        got = await c.read_file(f.inode)
        assert got == payload
        await c.close()
    finally:
        cluster.stop()


def _lzshm_mappings(pid: int) -> int:
    """Count memfd ring segments currently mapped by a process (the
    memfd is created under the name "lzshm" — native/shm_ring.h)."""
    try:
        with open(f"/proc/{pid}/maps") as f:
            return sum(1 for line in f if "lzshm" in line)
    except OSError:
        return 0


def _data_uds_ports(before: set[str] | None = None) -> set[str]:
    """Abstract data-plane listener ports visible on this host
    (serve_native.cpp binds @lzfs-data-<host>-<port>)."""
    out = set()
    try:
        with open("/proc/net/unix") as f:
            for line in f:
                marker = "@lzfs-data-127.0.0.1-"
                idx = line.find(marker)
                if idx >= 0:
                    out.add(line[idx + len(marker):].strip())
    except OSError:
        pass
    return out - (before or set())


async def test_shm_segment_lifecycle_survives_peer_sigkill(tmp_path):
    """Ring segments are owned by the connection: a client that mapped
    a segment and got SIGKILLed (no goodbye) must leave the chunkserver
    with ZERO lingering memfd mappings once the kernel closes the
    socket — and repeated map/kill cycles must not accumulate any."""
    from lizardfs_tpu.core import native_io

    if not native_io.parts_shm_available():
        pytest.skip("native shm ring not built")
    ports_before = _data_uds_ports()
    cluster = ProcCluster(tmp_path, n_cs=1)
    try:
        await cluster.start()
        ports = _data_uds_ports(ports_before)
        assert ports, "chunkserver bound no abstract data listener"
        port = sorted(ports)[0]
        cs_pid = cluster.procs["cs0"].pid
        assert _lzshm_mappings(cs_pid) == 0

        helper_src = (
            "import sys, time\n"
            f"sys.path.insert(0, {REPO!r})\n"
            "from lizardfs_tpu.core import native_io\n"
            f"sock = native_io._blocking_socket(('127.0.0.1', {port}), 30.0)\n"
            "ring = native_io.shm_ring_handshake(sock)\n"
            "assert ring is not None, 'handshake refused'\n"
            "print('MAPPED', flush=True)\n"
            "time.sleep(60)\n"
        )
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        for cycle in range(2):
            helper = subprocess.Popen(
                [sys.executable, "-c", helper_src],
                stdout=subprocess.PIPE, env=env,
            )
            try:
                line = await asyncio.wait_for(
                    asyncio.to_thread(helper.stdout.readline), 30.0
                )
                assert b"MAPPED" in line, "helper never mapped a ring"
                # the segment is live in the SERVER's address space now
                for _ in range(100):
                    if _lzshm_mappings(cs_pid) > 0:
                        break
                    await asyncio.sleep(0.1)
                assert _lzshm_mappings(cs_pid) > 0, \
                    f"cycle {cycle}: server never mapped the segment"
            finally:
                helper.send_signal(signal.SIGKILL)
                helper.wait(timeout=10)
            for _ in range(100):
                if _lzshm_mappings(cs_pid) == 0:
                    break
                await asyncio.sleep(0.1)
            assert _lzshm_mappings(cs_pid) == 0, (
                f"cycle {cycle}: segment leaked past peer SIGKILL "
                "(proactor did not unmap on disconnect)"
            )
    finally:
        cluster.stop()


async def test_shadow_replica_reads_process_level(tmp_path):
    """ISSUE 7 e2e with real processes: a primary + shadow master pair,
    chunkservers mirror-registering to both, a client routing read RPCs
    to the shadow replica (tokened replies — counters climb on the
    client), the primary's admin `health` naming the shadow with its
    replication lag, and a SIGKILL of the shadow mid-reads degrading to
    primary-only without one failed read."""
    import json

    from lizardfs_tpu.proto import framing
    from lizardfs_tpu.proto import messages as m

    cluster = ProcCluster(tmp_path, n_cs=2)
    pp, sp = _free_port(), _free_port()
    (tmp_path / "goals.cfg").write_text("1 one : _\n5 ec32 : $ec(3,2)\n")

    async def admin(port: int, command: str) -> dict:
        r, w = await asyncio.open_connection("127.0.0.1", port)
        await framing.send_message(
            w, m.AdminCommand(req_id=1, command=command, json="{}")
        )
        reply = await framing.read_message(r)
        w.close()
        return json.loads(reply.json)

    try:
        cluster._spawn(
            "primary", "lizardfs_tpu.master",
            f"DATA_PATH = {tmp_path}/primary\n"
            f"LISTEN_PORT = {pp}\n"
            f"GOALS_CFG = {tmp_path}/goals.cfg\n"
            "HEALTH_INTERVAL = 0.3\n",
        )
        await cluster._wait_port(pp)
        cluster._spawn(
            "shadow", "lizardfs_tpu.master",
            f"DATA_PATH = {tmp_path}/shadow\n"
            f"LISTEN_PORT = {sp}\n"
            f"GOALS_CFG = {tmp_path}/goals.cfg\n"
            "HEALTH_INTERVAL = 0.3\n"
            "PERSONALITY = shadow\n"
            f"ACTIVE_MASTER = 127.0.0.1:{pp}\n",
        )
        await cluster._wait_port(sp)
        for i in range(cluster.n_cs):
            cluster._spawn(
                f"cs{i}", "lizardfs_tpu.chunkserver",
                f"DATA_PATH = {tmp_path}/cs{i}\n"
                f"LISTEN_PORT = {_free_port()}\n"
                f"MASTER_ADDRS = 127.0.0.1:{pp},127.0.0.1:{sp}\n"
                "HEARTBEAT_INTERVAL = 0.3\n",
            )
        cluster.master_port = pp
        for _ in range(100):
            if await cluster._cs_count() >= cluster.n_cs:
                break
            await asyncio.sleep(0.1)

        addrs = [("127.0.0.1", pp), ("127.0.0.1", sp)]
        c = Client("", 0, master_addrs=addrs, wave_timeout=0.3)
        await c.connect("shadow-e2e")
        assert c.shadow_reads
        f = await c.create(1, "rep.bin")
        payload = data_generator.generate(3, 2 * 65536 + 5).tobytes()
        await c.write_file(f.inode, payload)

        # reads route to the replica once it is caught up; the client
        # only accepts tokens >= its floor, so every answer is current
        for _ in range(150):
            a = await c.getattr(f.inode)
            assert a.length == len(payload)
            assert (await c.lookup(1, "rep.bin")).inode == f.inode
            if c.metrics.series["shadow_reads"].total >= 2:
                break
            await asyncio.sleep(0.1)
        assert c.metrics.series["shadow_reads"].total >= 2, \
            "client never engaged the shadow replica"

        # the PRIMARY's health rollup names the shadow and its lag
        # (MltomaAck plane, throttled to ~1/s — poll briefly)
        shadows = []
        for _ in range(50):
            h = await admin(pp, "health")
            shadows = h.get("shadows", [])
            if shadows and any(s["lag"] == 0 for s in shadows):
                break
            await asyncio.sleep(0.1)
        assert shadows, "primary health never reported the shadow"
        assert h["summary"]["shadows"] >= 1
        assert any(s["serving"] for s in shadows)

        # SIGKILL the shadow mid-reads: every read keeps answering
        # (primary fallback), fallbacks counter climbs
        cluster.kill9("shadow")
        before = c.metrics.series["shadow_fallbacks"].total
        for _ in range(20):
            a = await c.getattr(f.inode)
            assert a.length == len(payload)
            await asyncio.sleep(0.02)
        assert (await c.read_file(f.inode)) == payload
        assert c.metrics.series["shadow_fallbacks"].total > before
        await c.close()
    finally:
        cluster.stop()
