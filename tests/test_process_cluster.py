"""Multi-PROCESS system tests: real daemons, real kill -9.

The reference's system tier launches masters + chunkservers as separate
processes and kills them mid-IO (reference: tests/tools/lizardfs.sh
setup_local_empty_lizardfs; ShortSystemTests/test_cs_failure_during_
xor_read.sh). The in-process Cluster helper can only stop daemons
gracefully — SIGKILL semantics (no clean goodbye, kernel-closed
sockets, heartbeat-timeout paths, image+changelog replay on restart)
only show up with real processes."""

import asyncio
import os
import signal
import socket
import subprocess
import sys

import pytest

from lizardfs_tpu.client.client import Client
from lizardfs_tpu.proto import status as st
from lizardfs_tpu.utils import data_generator

pytestmark = pytest.mark.asyncio

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class ProcCluster:
    """master + N chunkservers as subprocesses on localhost."""

    def __init__(self, tmp_path, n_cs=3):
        self.tmp = tmp_path
        self.n_cs = n_cs
        self.master_port = _free_port()
        self.procs: dict[str, subprocess.Popen] = {}

    def _spawn(self, name: str, module: str, cfg_text: str) -> None:
        cfg = self.tmp / f"{name}.cfg"
        cfg.write_text(cfg_text)
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
                   PALLAS_AXON_POOL_IPS="")
        self.procs[name] = subprocess.Popen(
            [sys.executable, "-m", module, str(cfg)],
            stdout=open(self.tmp / f"{name}.log", "wb"),
            stderr=subprocess.STDOUT, env=env,
        )

    async def start(self) -> None:
        (self.tmp / "goals.cfg").write_text(
            "1 one : _\n5 ec32 : $ec(3,2)\n"
        )
        self._spawn(
            "master", "lizardfs_tpu.master",
            f"DATA_PATH = {self.tmp}/master\n"
            f"LISTEN_PORT = {self.master_port}\n"
            f"GOALS_CFG = {self.tmp}/goals.cfg\n"
            "HEALTH_INTERVAL = 0.3\n",
        )
        await self._wait_port(self.master_port)
        for i in range(self.n_cs):
            self._spawn(
                f"cs{i}", "lizardfs_tpu.chunkserver",
                f"DATA_PATH = {self.tmp}/cs{i}\n"
                f"LISTEN_PORT = {_free_port()}\n"
                f"MASTER_PORT = {self.master_port}\n"
                "HEARTBEAT_INTERVAL = 0.3\n",
            )
        # all chunkservers registered
        for _ in range(100):
            if await self._cs_count() >= self.n_cs:
                return
            await asyncio.sleep(0.1)
        raise AssertionError("chunkservers never registered")

    async def _cs_count(self) -> int:
        import json

        from lizardfs_tpu.proto import framing
        from lizardfs_tpu.proto import messages as m

        try:
            r, w = await asyncio.open_connection("127.0.0.1", self.master_port)
            await framing.send_message(w, m.AdminInfo(req_id=1))
            reply = await framing.read_message(r)
            w.close()
            return sum(
                1 for s in json.loads(reply.json)["chunkservers"]
                if s["connected"]
            )
        except (ConnectionError, OSError):
            return 0

    async def _wait_port(self, port: int, timeout=15.0) -> None:
        for _ in range(int(timeout / 0.1)):
            try:
                _, w = await asyncio.open_connection("127.0.0.1", port)
                w.close()
                return
            except (ConnectionError, OSError):
                await asyncio.sleep(0.1)
        raise AssertionError(f"port {port} never came up")

    def kill9(self, name: str) -> None:
        self.procs[name].send_signal(signal.SIGKILL)
        self.procs[name].wait(timeout=10)

    def stop(self) -> None:
        for p in self.procs.values():
            if p.poll() is None:
                p.terminate()
        for p in self.procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


async def test_sigkill_chunkserver_degraded_read(tmp_path):
    """kill -9 a chunkserver mid-cluster: EC reads recover through the
    survivors, and the health engine re-replicates."""
    cluster = ProcCluster(tmp_path, n_cs=4)
    await cluster.start()
    try:
        c = Client("127.0.0.1", cluster.master_port, wave_timeout=0.3)
        await c.connect()
        f = await c.create(1, "victim.bin")
        await c.setgoal(f.inode, 5)  # ec(3,2)
        payload = data_generator.generate(1, 5 * 2**20 + 333).tobytes()
        await c.write_file(f.inode, payload)

        cluster.kill9("cs0")  # no goodbye, no flush
        got = await c.read_file(f.inode)
        assert got == payload, "degraded read after SIGKILL"
        # health engine restores full redundancy on the survivors
        for _ in range(150):
            if await cluster._cs_count() == 3:
                break
            await asyncio.sleep(0.1)
        await c.close()
    finally:
        cluster.stop()


async def test_sigkill_master_restart_replays(tmp_path):
    """kill -9 the master (no image dump): the restart replays the
    changelog and serves the same namespace and bytes."""
    cluster = ProcCluster(tmp_path, n_cs=3)
    await cluster.start()
    try:
        c = Client("127.0.0.1", cluster.master_port, wave_timeout=0.3)
        await c.connect()
        f = await c.create(1, "durable.bin")
        await c.setgoal(f.inode, 5)
        payload = data_generator.generate(2, 2 * 2**20).tobytes()
        await c.write_file(f.inode, payload)
        await c.mkdir(1, "docs")
        await c.close()

        cluster.kill9("master")
        cluster._spawn(
            "master", "lizardfs_tpu.master",
            f"DATA_PATH = {tmp_path}/master\n"
            f"LISTEN_PORT = {cluster.master_port}\n"
            f"GOALS_CFG = {tmp_path}/goals.cfg\n"
            "HEALTH_INTERVAL = 0.3\n",
        )
        await cluster._wait_port(cluster.master_port)
        # chunkservers reconnect on their heartbeat (0.3 s interval)
        for _ in range(200):
            if await cluster._cs_count() >= 3:
                break
            await asyncio.sleep(0.1)
        else:
            raise AssertionError("chunkservers never re-registered")

        c2 = Client("127.0.0.1", cluster.master_port, wave_timeout=0.3)
        await c2.connect()
        attr = await c2.lookup(1, "durable.bin")
        assert attr.length == len(payload)
        assert (await c2.lookup(1, "docs")).inode > 0
        got = await c2.read_file(attr.inode)
        assert got == payload, "bytes lost across master SIGKILL"
        await c2.close()
    finally:
        cluster.stop()
