"""Cross-session client data-cache coherence.

The reference master invalidates mount data caches on mutation
(reference: src/master/matoclserv.cc client service) and mounts
revalidate cached chunk data against the version returned by
fs_readchunk (reference: src/mount/chunk_locator.h,
src/mount/mastercomm.h:67). These tests pin both layers plus the
last-resort TTL:

1. master push: B rewrites -> A's cached blocks drop well inside the TTL;
2. version revalidation: even with pushes suppressed, the next locate A
   performs drops blocks cached under the old (chunk_id, version);
3. BlockCache unit semantics for the version tagging.
"""

import asyncio

import pytest

from lizardfs_tpu.client.cache import BlockCache
from lizardfs_tpu.constants import MFSBLOCKSIZE
from lizardfs_tpu.proto import messages as m

from tests.test_cluster import Cluster

pytestmark = pytest.mark.asyncio


async def test_cross_session_write_invalidates_reader_cache(tmp_path):
    """Client A reads (cache fills), client B rewrites, client A re-reads
    within 1 s and must see the new bytes — the 3 s TTL alone would
    serve stale data here."""
    cluster = Cluster(tmp_path, n_cs=3)
    await cluster.start()
    try:
        a = await cluster.client()
        b = await cluster.client()
        f = await a.create(1, "shared.dat")
        old = b"A" * (2 * MFSBLOCKSIZE)
        await a.write_file(f.inode, old)

        # A reads -> fills its block cache (small read, below bulk bypass)
        got = await a.read_file(f.inode, 0, 4096)
        assert got == old[:4096]
        # the fast path really is armed: a repeat read hits the cache
        hits_before = a.cache.hits
        await a.read_file(f.inode, 0, 4096)
        assert a.cache.hits > hits_before

        # B rewrites through a different session
        await b.pwrite(f.inode, 0, b"FRESHBYTES")
        # one scheduler breath for the push task; far below the 3 s TTL
        await asyncio.sleep(0.2)
        got = await a.read_file(f.inode, 0, 10)
        assert got == b"FRESHBYTES"
        assert a.op_counters.get("cache_invalidate", 0) >= 1
    finally:
        await cluster.stop()


async def test_version_revalidation_catches_missed_push(tmp_path):
    """If the invalidation push is lost (handler suppressed here), the
    next locate A performs — for ANY range of the chunk — drops blocks
    cached under the old (chunk_id, version) tag."""
    cluster = Cluster(tmp_path, n_cs=3)
    await cluster.start()
    try:
        a = await cluster.client()
        b = await cluster.client()
        f = await a.create(1, "unpushed.dat")
        old = bytes(range(256)) * ((4 * MFSBLOCKSIZE) // 256)
        await a.write_file(f.inode, old)

        # A caches block 0
        assert await a.read_file(f.inode, 0, 4096) == old[:4096]
        # simulate a lost push: drop A's handler registration
        a.master._push_handlers.pop(m.MatoclCacheInvalidate, None)

        await b.pwrite(f.inode, 0, b"NEWDATA!")
        await asyncio.sleep(0.2)

        # A reads a DIFFERENT block -> miss -> locate -> note_version
        # sees the bumped chunk version and drops the stale block 0
        await a.read_file(f.inode, 3 * MFSBLOCKSIZE, 4096)
        # re-read of block 0 within the TTL must now miss and refetch
        assert (await a.read_file(f.inode, 0, 8)) == b"NEWDATA!"
    finally:
        await cluster.stop()


def test_blockcache_version_tagging():
    # call order mirrors the client: every locate note_version()s BEFORE
    # any put() of the blocks it fetched
    c = BlockCache(max_age=1000.0)
    c.note_version(7, 0, (11, 1))
    c.put(7, 0, 0, b"x" * 100, version=(11, 1))
    c.put(7, 0, 1, b"y" * 100, version=(11, 1))
    c.note_version(7, 1, (12, 1))
    c.put(7, 1, 0, b"z" * 100, version=(12, 1))  # other chunk untouched
    assert c.get(7, 0, 0) == b"x" * 100

    # same identity re-noted: nothing drops
    c.note_version(7, 0, (11, 1))
    assert c.get(7, 0, 1) == b"y" * 100

    # version bump drops only that chunk's blocks
    c.note_version(7, 0, (11, 2))
    assert c.get(7, 0, 0) is None and c.get(7, 0, 1) is None
    assert c.get(7, 1, 0) == b"z" * 100

    # chunk_id swap (truncate + regrow) also invalidates
    c.note_version(7, 1, (99, 1))
    assert c.get(7, 1, 0) is None


def test_blockcache_put_refuses_revoked_version():
    """An in-flight read finishing after an invalidation must not
    re-insert blocks under the revoked version tag — that would
    resurrect exactly the staleness the push removed."""
    c = BlockCache(max_age=1000.0)
    c.note_version(7, 2, (50, 1))
    # invalidation push lands while a read (tagged (50,1)) is in flight
    c.invalidate(7, 2)
    c.put(7, 2, 0, b"stale" * 20, version=(50, 1))  # late arrival
    assert c.get(7, 2, 0) is None
    # a put under a tag superseded by a newer locate is refused too
    c.note_version(7, 2, (50, 2))
    c.put(7, 2, 0, b"old" * 30, version=(50, 1))
    assert c.get(7, 2, 0) is None
    # the current tag caches normally
    c.put(7, 2, 0, b"new" * 30, version=(50, 2))
    assert c.get(7, 2, 0) == b"new" * 30


def test_blockcache_version_notes_bounded():
    c = BlockCache(max_age=1000.0)
    c.max_version_notes = 16
    for ino in range(100):
        c.note_version(ino, 0, (ino, 1))
    assert len(c._versions) == 16
    # an evicted note only costs a skipped fill, never a wrong read
    c.put(0, 0, 0, b"q" * 10, version=(0, 1))
    assert c.get(0, 0, 0) is None


async def test_locate_cache_hits_and_write_invalidation(tmp_path):
    """Repeat sized reads of an unchanged chunk serve their location
    from the client's locate cache (chunk_locator.h analog — one
    master RPC for the first read, zero after); any write to the inode
    drops the cached location so the next read re-locates."""
    cluster = Cluster(tmp_path, n_cs=3)
    await cluster.start()
    try:
        c = await cluster.client()
        c.locate_cache_ttl = 60.0  # pin behavior, not wall-clock speed
        from lizardfs_tpu.utils import data_generator

        f = await c.create(1, "loc.bin")
        payload = data_generator.generate(4, 8 << 20).tobytes()
        await c.write_file(f.inode, payload)
        # bulk-sized reads bypass the block cache, so every one needs a
        # location — only the FIRST may pay a master RPC
        got = await c.read_file(f.inode, 0, 4 << 20)
        assert bytes(got) == payload[: 4 << 20]
        before = dict(c.op_counters)
        for i in range(3):
            off = i * (1 << 20)
            got = await c.read_file(f.inode, off, 4 << 20)
            assert bytes(got) == payload[off: off + (4 << 20)]
        delta_locates = (
            c.op_counters.get("CltomaReadChunk", 0)
            - before.get("CltomaReadChunk", 0)
        )
        hits = (
            c.op_counters.get("locate_cache_hit", 0)
            - before.get("locate_cache_hit", 0)
        )
        assert delta_locates == 0, f"{delta_locates} extra locates"
        assert hits == 3
        # a write drops the cached location (version moved)
        await c.pwrite(f.inode, 0, b"Z" * 8192)
        before = dict(c.op_counters)
        got = await c.read_file(f.inode, 0, 4096)
        assert bytes(got) == b"Z" * 4096
        assert (
            c.op_counters.get("CltomaReadChunk", 0)
            - before.get("CltomaReadChunk", 0)
        ) == 1, "write did not invalidate the locate cache"
    finally:
        await cluster.stop()


async def test_locate_cached_mid_write_dropped_at_write_end(tmp_path):
    """A locate performed while a write to the same inode is in flight
    (between its grant and its WriteChunkEnd) reflects pre-write
    length/identity; it must not be served from the locate cache after
    the write returns (r05 review finding: the master's end-of-write
    push excludes the mutator's own session, so the client drops its
    own locates at write end)."""
    cluster = Cluster(tmp_path, n_cs=3)
    await cluster.start()
    try:
        c = await cluster.client()
        c.locate_cache_ttl = 60.0
        # EXTENSION is the sharp case: file length only grows at
        # WriteChunkEnd, so a mid-write locate caches file_length=0
        # and a post-write sized read would clamp to it, returning b""
        f = await c.create(1, "race.bin")
        mid_read: list[bytes] = []
        orig = c._push_chunk_parts

        async def hooked(grant, chunk_data):
            await orig(grant, chunk_data)
            # data pushed, WriteChunkEnd NOT yet sent: a concurrent
            # reader locates now and caches a pre-end location
            mid_read.append(bytes(await c.read_file(f.inode, 0, 8)))

        c._push_chunk_parts = hooked
        try:
            await c.write_file(f.inode, b"B" * 65536)
        finally:
            c._push_chunk_parts = orig
        assert mid_read == [b""], mid_read  # pre-end view: length 0
        got = await c.read_file(f.inode, 0, 8)
        assert bytes(got) == b"B" * 8, \
            "read clamped to a locate cached mid-write (stale length 0)"
    finally:
        await cluster.stop()
