"""S3 object gateway end-to-end tests: REST subset over a real
in-process cluster, multipart-via-appendchunks, lifecycle tiering to
tape with recall on GET, kill switches, and the satellite regressions
(appendchunks under concurrent COW writes; tape stamp-mismatch
re-queue).

`make s3-smoke` runs the `smoke`-named subset (tier-1 rides the whole
non-slow file).
"""

import asyncio
import hashlib
import os

import pytest

from lizardfs_tpu.chunkserver.server import ChunkServer
from lizardfs_tpu.master.server import MasterServer
from lizardfs_tpu.proto import status as st
from lizardfs_tpu.s3.client import S3Client, S3Error
from lizardfs_tpu.s3.server import S3Gateway
from lizardfs_tpu.tapeserver.server import TapeServer
from lizardfs_tpu.utils import data_generator

from tests.test_cluster import make_goals

pytestmark = pytest.mark.asyncio


def _payload(seed: int, n: int) -> bytes:
    return data_generator.generate(seed, n).tobytes()


async def _wait_for(cond, timeout=15.0, interval=0.1):
    for _ in range(int(timeout / interval)):
        if await cond():
            return True
        await asyncio.sleep(interval)
    return False


class S3Cluster:
    """Master + chunkservers + S3 gateway, all in-process."""

    def __init__(self, tmp_path, n_cs=3, lifecycle_interval=0.2):
        self.tmp_path = tmp_path
        self.n_cs = n_cs
        self.lifecycle_interval = lifecycle_interval
        self.master = None
        self.chunkservers = []
        self.gw = None
        self.clients = []

    async def start(self):
        self.master = MasterServer(
            str(self.tmp_path / "master"), goals=make_goals(),
            health_interval=0.2,
            lifecycle_interval=self.lifecycle_interval,
        )
        await self.master.start()
        for i in range(self.n_cs):
            cs = ChunkServer(
                str(self.tmp_path / f"cs{i}"),
                master_addr=("127.0.0.1", self.master.port),
                wave_timeout=0.2,
            )
            await cs.start()
            self.chunkservers.append(cs)
        self.gw = S3Gateway("127.0.0.1", self.master.port)
        await self.gw.start()

    async def client(self):
        from lizardfs_tpu.client.client import Client

        c = Client("127.0.0.1", self.master.port, wave_timeout=0.2)
        await c.connect()
        self.clients.append(c)
        return c

    def s3(self) -> S3Client:
        return S3Client("127.0.0.1", self.gw.port)

    async def stop(self):
        for c in self.clients:
            await c.close()
        if self.gw is not None:
            await self.gw.stop()
        for cs in self.chunkservers:
            await cs.stop()
        if self.master is not None:
            await self.master.stop()


async def test_s3_smoke(tmp_path):
    """The `make s3-smoke` round trip: buckets, PUT/GET/HEAD/DELETE,
    ListObjectsV2, and a multipart upload assembled via appendchunks,
    byte-identical on GET."""
    cluster = S3Cluster(tmp_path)
    await cluster.start()
    try:
        async with cluster.s3() as s3:
            await s3.create_bucket("demo")
            assert "demo" in await s3.list_buckets()
            # simple object round trip (+ nested key creating real dirs)
            blob = _payload(7, 300_000)
            put = await s3.put_object("demo", "a/b/hello.bin", blob)
            assert put.etag == hashlib.md5(blob).hexdigest()
            got = await s3.get_object("demo", "a/b/hello.bin")
            assert got.body == blob
            assert got.etag == put.etag
            head = await s3.head_object("demo", "a/b/hello.bin")
            assert int(head.headers["content-length"]) == len(blob)
            # ranged GET
            r = await s3.get_object("demo", "a/b/hello.bin",
                                    range_="bytes=100-199")
            assert r.status == 206 and r.body == blob[100:200]
            # overwrite is atomic + replaces content
            blob2 = _payload(8, 120_000)
            await s3.put_object("demo", "a/b/hello.bin", blob2)
            assert (await s3.get_object("demo", "a/b/hello.bin")).body == blob2

            # multipart upload: part 1 lands on a chunk-aligned tail
            # (empty object) and is assembled via the O(1) appendchunks
            # share; the non-aligned follow-up part takes the copy path
            p1 = _payload(9, 1_000_000)
            p2 = _payload(10, 700_001)
            upload = await s3.create_multipart("demo", "mpu/big.bin")
            e1 = await s3.upload_part("demo", "mpu/big.bin", upload, 1, p1)
            e2 = await s3.upload_part("demo", "mpu/big.bin", upload, 2, p2)
            await s3.complete_multipart(
                "demo", "mpu/big.bin", upload, [(1, e1), (2, e2)]
            )
            got = await s3.get_object("demo", "mpu/big.bin")
            assert got.body == p1 + p2, "multipart byte identity"
            assert got.etag.endswith("-2")
            gwm = cluster.gw.metrics
            assert gwm.counter("s3_mpu_parts_shared").total >= 1
            # upload staging is cleaned up after complete
            listing = await s3.list_objects("demo")
            assert sorted(k["key"] for k in listing["keys"]) == [
                "a/b/hello.bin", "mpu/big.bin",
            ]

            await s3.delete_object("demo", "mpu/big.bin")
            with pytest.raises(S3Error) as e:
                await s3.get_object("demo", "mpu/big.bin")
            assert e.value.status == 404
            # DELETE is idempotent
            await s3.delete_object("demo", "mpu/big.bin")
    finally:
        await cluster.stop()


async def test_s3_list_objects_v2_semantics(tmp_path):
    """prefix/delimiter/continuation-token semantics over readdir."""
    cluster = S3Cluster(tmp_path, n_cs=1)
    await cluster.start()
    try:
        async with cluster.s3() as s3:
            await s3.create_bucket("lst")
            keys = ["a.txt", "dir/one", "dir/two", "dir/sub/three",
                    "dirx", "z.txt"]
            for k in keys:
                await s3.put_object("lst", k, k.encode())
            full = await s3.list_objects("lst")
            assert [k["key"] for k in full["keys"]] == sorted(keys)
            # delimiter groups
            top = await s3.list_objects("lst", delimiter="/")
            assert [k["key"] for k in top["keys"]] == ["a.txt", "dirx",
                                                      "z.txt"]
            assert top["prefixes"] == ["dir/"]
            sub = await s3.list_objects("lst", prefix="dir/", delimiter="/")
            assert [k["key"] for k in sub["keys"]] == ["dir/one", "dir/two"]
            assert sub["prefixes"] == ["dir/sub/"]
            # pagination walks the whole set without dupes or holes
            walked, token = [], ""
            while True:
                page = await s3.list_objects("lst", max_keys=2, token=token)
                walked += [k["key"] for k in page["keys"]]
                if not page["truncated"]:
                    break
                token = page["token"]
                assert token
            assert walked == sorted(keys)
    finally:
        await cluster.stop()


async def test_s3_bucket_errors(tmp_path):
    cluster = S3Cluster(tmp_path, n_cs=1)
    await cluster.start()
    try:
        async with cluster.s3() as s3:
            with pytest.raises(S3Error) as e:
                await s3.get_object("nosuch", "k")
            assert e.value.status == 404
            with pytest.raises(S3Error) as e:
                await s3.create_bucket("Bad_Bucket")
            assert e.value.status == 400
            # reserved names can't become buckets
            with pytest.raises(S3Error):
                await s3.create_bucket("metrics")
            await s3.create_bucket("full")
            await s3.put_object("full", "x", b"1")
            with pytest.raises(S3Error) as e:
                await s3.delete_bucket("full")
            assert e.value.status == 409  # BucketNotEmpty
            await s3.delete_object("full", "x")
            await s3.delete_bucket("full")
            # keys that would escape the bucket are refused
            with pytest.raises(S3Error) as e:
                await s3.put_object("nosuch2", "k", b"")
            assert e.value.status == 404
            # DELETE is idempotent at ANY key depth (missing
            # intermediate prefixes included)
            await s3.create_bucket("deep")
            r = await s3.request("DELETE", "/deep/never/made/key")
            assert r.status == 204
            # negative max-keys is a 400, not a silent truncation
            with pytest.raises(S3Error) as e:
                await s3.request("GET", "/deep",
                                 query={"list-type": "2", "max-keys": "-1"})
            assert e.value.status == 400
            # an uploadId is bound to its bucket/key: a mismatched
            # complete/part must not touch another key's staging
            up = await s3.create_multipart("deep", "real/key")
            with pytest.raises(S3Error) as e:
                await s3.upload_part("deep", "other/key", up, 1, b"x")
            assert e.value.status == 404  # NoSuchUpload
            with pytest.raises(S3Error) as e:
                await s3.complete_multipart("deep", "other/key", up,
                                            [(1, "0" * 32)])
            assert e.value.status == 404
            await s3.abort_multipart("deep", "real/key", up)
    finally:
        await cluster.stop()


async def test_recall_write_guard_scoped_to_tape_session(tmp_path):
    """Satellite-hardening regression: during a recall only the
    recalling tape server's session may write the demoted inode — a
    concurrent client write (even same-length) is refused with
    TAPE_RECALL instead of silently merging into the restore."""
    cluster = S3Cluster(tmp_path, lifecycle_interval=3600.0)
    await cluster.start()
    ts = TapeServer(
        str(tmp_path / "tape"), ("127.0.0.1", cluster.master.port)
    )
    await ts.start()
    try:
        c = await cluster.client()
        blob = _payload(40, 200_000)
        f = await c.create(1, "cold.bin")
        await c.write_file(f.inode, blob)
        master = cluster.master
        # demote via the RPC (forced archive first)
        deadline = 100
        while deadline:
            try:
                await c.tape_demote(f.inode)
                break
            except st.StatusError as e:
                assert e.code == st.CHUNK_BUSY
                deadline -= 1
                await asyncio.sleep(0.2)
        assert f.inode in master.meta.demoted
        # freeze the restore mid-flight via the put/recall barrier:
        # reuse the tapeserver test hook by delaying its archive read —
        # simplest deterministic hold is a paused recall dispatch: mark
        # the inflight state by hand and assert the guard refuses a
        # foreign session while the (fake) tape session may pass
        import asyncio as _a

        master._recall_inflight[f.inode] = _a.get_running_loop(
        ).create_future()
        master._recall_sids[f.inode] = 424242
        assert master._recall_writer_ok(f.inode, 424242)
        assert not master._recall_writer_ok(f.inode, c.session_id)
        with pytest.raises(st.StatusError) as e:
            await c.pwrite(f.inode, 0, b"z" * len(blob))
        assert e.value.code == st.TAPE_RECALL
        # restore not dispatched yet -> nobody writes
        master._recall_sids.pop(f.inode)
        assert not master._recall_writer_ok(f.inode, 424242)
        master._recall_inflight.pop(f.inode).cancel()
        # the real recall still restores the original bytes
        await c.tape_recall(f.inode)
        c.cache.invalidate(f.inode)
        assert await c.read_file(f.inode, 0, len(blob)) == blob
    finally:
        await ts.stop()
        await cluster.stop()


async def test_s3_lifecycle_demote_and_recall_on_get(tmp_path):
    """The hot/cold hierarchy end-to-end: a bucket lifecycle rule
    demotes a cold object through the tapeserver flow (chunk data
    freed, stat unchanged), and GET triggers recall and serves the
    original bytes."""
    cluster = S3Cluster(tmp_path, lifecycle_interval=0.2)
    await cluster.start()
    ts = TapeServer(
        str(tmp_path / "tape"), ("127.0.0.1", cluster.master.port),
        label="vault",
    )
    await ts.start()
    try:
        async with cluster.s3() as s3:
            await s3.create_bucket("cold")
            blob = _payload(11, 400_000)
            await s3.put_object("cold", "archive/me.bin", blob)
            head = await s3.head_object("cold", "archive/me.bin")
            # demote immediately once a tape copy lands
            await s3.put_lifecycle("cold", demote_after_s=0.0)
            assert b"TAPE" in await s3.get_lifecycle("cold")

            master = cluster.master
            c = await cluster.client()
            attr = await c.resolve("/cold/archive/me.bin")
            inode = attr.inode

            async def demoted():
                return inode in master.meta.demoted

            assert await _wait_for(demoted, timeout=20.0), \
                master.meta.demoted
            # demote freed the chunk data but kept the object's stat
            node = master.meta.fs.nodes[inode]
            assert node.chunks == [] and node.length == len(blob)
            info = await c.tape_info(inode)
            assert info["demoted"] and info["fresh"] >= 1
            # the tape_demote op maintained the incremental metadata
            # digest exactly (shadow divergence detection depends on it)
            assert master.meta._digest == master.meta.full_digest()

            # GET recalls from tape and serves the original bytes
            got = await s3.get_object("cold", "archive/me.bin")
            assert got.body == blob, "recall byte identity"
            assert inode not in master.meta.demoted
            assert master.meta._digest == master.meta.full_digest()
            assert cluster.gw.metrics.counter("s3_recalls").total >= 1
            # a recall is not a modification: Last-Modified is stable
            head2 = await s3.head_object("cold", "archive/me.bin")
            assert (head2.headers["last-modified"]
                    == head.headers["last-modified"])
            # ... and the tape copy still reads as fresh (no re-archive
            # storm after recall)
            info = await c.tape_info(inode)
            assert info["fresh"] >= 1 and not info["demoted"]
            # the scanner demotes it again (still cold, copy fresh)
            assert await _wait_for(demoted, timeout=20.0)

            # direct POSIX read of a demoted file recalls too (the
            # locate error is transient by contract)
            c.cache.invalidate(inode)
            try:
                data = await c.read_file(inode, 0, len(blob))
            except st.StatusError as e:
                assert e.code == st.TAPE_RECALL
                await c.tape_recall(inode)
                data = await c.read_file(inode, 0, len(blob))
            assert bytes(data) == blob
    finally:
        await ts.stop()
        await cluster.stop()


async def test_s3_kill_switch_off(tmp_path, monkeypatch):
    """LZ_S3=0 (any documented off spelling) refuses to start the
    gateway; the rest of the cluster is untouched."""
    monkeypatch.setenv("LZ_S3", "0")
    gw = S3Gateway("127.0.0.1", 1)  # never dialed: the switch trips first
    with pytest.raises(RuntimeError, match="LZ_S3"):
        await gw.start()
    monkeypatch.setenv("LZ_S3", "off")
    with pytest.raises(RuntimeError, match="LZ_S3"):
        await gw.start()


async def test_s3_lifecycle_kill_switch_off(tmp_path, monkeypatch):
    """LZ_S3_LIFECYCLE=0 stops the master's demote scanner; flipping it
    back on resumes demotion without a restart."""
    cluster = S3Cluster(tmp_path, lifecycle_interval=0.1)
    await cluster.start()
    ts = TapeServer(
        str(tmp_path / "tape"), ("127.0.0.1", cluster.master.port)
    )
    await ts.start()
    try:
        monkeypatch.setenv("LZ_S3_LIFECYCLE", "0")
        async with cluster.s3() as s3:
            await s3.create_bucket("gated")
            await s3.put_object("gated", "obj", b"y" * 50_000)
            await s3.put_lifecycle("gated", demote_after_s=0.0)
            await asyncio.sleep(1.0)
            assert not cluster.master.meta.demoted, \
                "scanner demoted with LZ_S3_LIFECYCLE=0"
            monkeypatch.delenv("LZ_S3_LIFECYCLE")

            async def demoted():
                return bool(cluster.master.meta.demoted)

            assert await _wait_for(demoted, timeout=20.0)
    finally:
        await ts.stop()
        await cluster.stop()


async def test_s3_metrics_lint_and_health_rollup(tmp_path):
    """The gateway's /metrics page is metrics-lint clean and the master
    health rollup names the s3 role."""
    from tests.test_metrics_lint import lint_prometheus

    cluster = S3Cluster(tmp_path, n_cs=1)
    await cluster.start()
    try:
        async with cluster.s3() as s3:
            await s3.create_bucket("obs")
            await s3.put_object("obs", "k", b"123")
            await s3.get_object("obs", "k")
            with pytest.raises(S3Error):
                await s3.get_object("obs", "missing")
            typed = lint_prometheus(await s3.metrics())
            assert typed["lizardfs_s3_requests_total"] == "counter"
            assert typed["lizardfs_s3_bytes_out_total"] == "counter"
            assert "lizardfs_slo_s3_burn_fast" in typed
            health = cluster.master.cluster_health()
            assert health["gateways"]["s3"] >= 1
            assert "tape" in health
            # healthz names the role
            r = await s3.request("GET", "/healthz")
            assert b'"role": "s3"' in r.body
    finally:
        await cluster.stop()


async def test_appendchunks_concurrent_cow_writes(tmp_path):
    """Satellite: appendchunks under concurrent COW writes to the
    shared source chunk (the multipart-complete hot path). Byte
    identity on both sides + refcount convergence in the registry."""
    cluster = S3Cluster(tmp_path, n_cs=3)
    await cluster.start()
    try:
        c = await cluster.client()
        src_blob = _payload(20, 900_000)
        src = await c.create(1, "src.bin")
        await c.write_file(src.inode, src_blob)
        dst = await c.create(1, "dst.bin")
        await c.append_chunks(dst.inode, src.inode)
        chunk_id = cluster.master.meta.fs.nodes[dst.inode].chunks[0]
        assert cluster.master.meta.registry.chunks[chunk_id].refcount == 2

        # concurrent COW writes to the SHARED chunk from both sides
        patch_a = _payload(21, 64 * 1024)
        patch_b = _payload(22, 64 * 1024)
        await asyncio.gather(
            c.pwrite(src.inode, 128 * 1024, patch_a),
            c.pwrite(dst.inode, 256 * 1024, patch_b),
        )
        want_src = bytearray(src_blob)
        want_src[128 * 1024:128 * 1024 + len(patch_a)] = patch_a
        want_dst = bytearray(src_blob)
        want_dst[256 * 1024:256 * 1024 + len(patch_b)] = patch_b
        c.cache.invalidate(src.inode)
        c.cache.invalidate(dst.inode)
        assert await c.read_file(src.inode, 0, len(want_src)) == bytes(
            want_src
        ), "src bytes after COW"
        assert await c.read_file(dst.inode, 0, len(want_dst)) == bytes(
            want_dst
        ), "dst bytes diverged independently"
        # refcount convergence: every live chunk's refcount equals the
        # number of file slots referencing it
        refs: dict[int, int] = {}
        for node in cluster.master.meta.fs.nodes.values():
            for cid in getattr(node, "chunks", ()):
                if cid:
                    refs[cid] = refs.get(cid, 0) + 1
        for cid, chunk in cluster.master.meta.registry.chunks.items():
            assert chunk.refcount == refs.get(cid, 0), (
                f"chunk {cid}: refcount {chunk.refcount} vs "
                f"{refs.get(cid, 0)} referencing slots"
            )
    finally:
        await cluster.stop()


async def test_tape_stamp_mismatch_not_recorded_and_requeued(tmp_path):
    """Satellite: a file mutated between MatotsPutFile and
    TstomaPutDone must NOT be recorded as archived, and the lifecycle
    scanner re-queues the (forced) archive until a clean copy lands."""
    cluster = S3Cluster(tmp_path, lifecycle_interval=0.2)
    await cluster.start()
    ts = TapeServer(
        str(tmp_path / "tape"), ("127.0.0.1", cluster.master.port)
    )
    await ts.start()
    try:
        async with cluster.s3() as s3:
            await s3.create_bucket("racy")
            await s3.put_object("racy", "obj", b"OLDCONTENT" * 1000)
            await s3.put_lifecycle("racy", demote_after_s=0.0)
        c = await cluster.client()
        attr = await c.resolve("/racy/obj")
        inode = attr.inode

        # hold the tapeserver's read->ack window open and mutate the
        # file inside it
        ts.put_barrier = asyncio.Event()

        async def put_started():
            # the tapeserver read the file and is parked on the barrier
            return bool(
                inode in cluster.master._tape_inflight
            )

        assert await _wait_for(put_started, timeout=20.0)
        await asyncio.sleep(0.3)  # let the read finish into the window
        new_blob = b"NEWCONTENT" * 1500
        await c.write_file(inode, new_blob)
        ts.put_barrier.set()
        ts.put_barrier = None

        # the stale archive must never be recorded as fresh, and the
        # scanner re-queues until the new content is archived + demoted
        async def settled():
            info = await c.tape_info(inode)
            return info["fresh"] >= 1 or info["demoted"]

        assert await _wait_for(settled, timeout=30.0)
        info = await c.tape_info(inode)
        copies = info["copies"]
        node = cluster.master.meta.fs.nodes[inode]
        stamp_now = cluster.master._content_stamp(inode, node)
        for cp in copies:
            assert (cp["length"], cp["mtime"], cp.get("gen", 0)) == tuple(
                stamp_now
            ) or cp["length"] != len(b"OLDCONTENT" * 1000), (
                f"stale archive recorded as a copy: {cp}"
            )
        # and the content that finally lands on tape is the NEW one
        async with cluster.s3() as s3:
            got = await s3.get_object("racy", "obj")
            assert got.body == new_blob
    finally:
        await ts.stop()
        await cluster.stop()


async def test_demoted_state_replays_and_persists():
    """The tape_demote / tape_recall_done changelog ops replay
    identically on a second store (shadow path) and the demoted map
    survives an image round trip."""
    from lizardfs_tpu.master.metadata import MetadataStore

    ops = [
        {"op": "mknode", "parent": 1, "name": "f", "inode": 7, "ftype": 1,
         "mode": 0o644, "uid": 0, "gid": 0, "ts": 100, "goal": 1,
         "trash_time": 0},
        {"op": "create_chunk", "slice_type": 0, "chunk_id": 5,
         "version": 1, "copies": 1},
        {"op": "set_chunk", "inode": 7, "chunk_index": 0, "chunk_id": 5},
        {"op": "set_length", "inode": 7, "length": 1234, "ts": 101},
        {"op": "tape_copy", "inode": 7, "label": "_", "length": 1234,
         "mtime": 101, "gen": 2, "ts": 102},
        {"op": "tape_demote", "inode": 7, "ts": 103},
    ]
    live, shadow = MetadataStore(), MetadataStore()
    for op in ops:
        live.apply(op)
        shadow.apply(dict(op))
    assert live.demoted[7]["length"] == 1234
    assert live.fs.nodes[7].chunks == [] and live.fs.nodes[7].length == 1234
    assert 5 not in live.registry.chunks  # refcount hit zero on demote
    assert live.checksum() == shadow.checksum()
    assert live._digest == live.full_digest()
    # image round trip keeps the demoted map
    restored = MetadataStore()
    restored.load_sections(live.to_sections())
    assert restored.demoted == live.demoted
    assert restored.checksum() == live.checksum()
    # recall-done (restore=True) clears it and re-stamps the copy
    for store in (live, shadow):
        store.apply({"op": "tape_recall_done", "inode": 7, "ts": 104,
                     "restore": True})
    assert 7 not in live.demoted
    assert live.fs.nodes[7].mtime == 101  # recall is not a modification
    assert live.checksum() == shadow.checksum()
    assert live._digest == live.full_digest()


@pytest.mark.slow
async def test_multipart_fully_chunk_aligned_is_zero_copy(tmp_path):
    """A 64 MiB (chunk-aligned) part followed by a tail part assembles
    entirely through appendchunks — zero re-copied bytes."""
    from lizardfs_tpu.constants import MFSCHUNKSIZE

    cluster = S3Cluster(tmp_path, n_cs=3)
    await cluster.start()
    try:
        async with cluster.s3() as s3:
            await s3.create_bucket("aligned")
            p1 = _payload(30, MFSCHUNKSIZE)
            p2 = _payload(31, 300_000)
            up = await s3.create_multipart("aligned", "big")
            e1 = await s3.upload_part("aligned", "big", up, 1, p1)
            e2 = await s3.upload_part("aligned", "big", up, 2, p2)
            await s3.complete_multipart("aligned", "big", up,
                                        [(1, e1), (2, e2)])
            gwm = cluster.gw.metrics
            assert gwm.counter("s3_mpu_parts_shared").total == 2
            assert gwm.counter("s3_mpu_copied_bytes").total == 0
            got = await s3.get_object("aligned", "big")
            assert got.body == p1 + p2
    finally:
        await cluster.stop()
