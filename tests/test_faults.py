"""Unit tier for the fault-injection framework + unified retry policy.

Covers the LZ_FAULTS spec grammar, deterministic seeded decisions, the
frame/disk site semantics, the debug_read_delay_ms tweak alias, and the
RetryPolicy deadline-threading contract (nested retries share ONE
budget). The system tier (real clusters, seeded schedules) lives in
tests/test_chaos.py.
"""

import asyncio
import time

import pytest

from lizardfs_tpu.proto import status as st
from lizardfs_tpu.runtime import faults
from lizardfs_tpu.runtime import retry as retrymod


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# --- spec grammar -----------------------------------------------------------


def test_parse_spec_full_grammar():
    seed, rules = faults.parse_spec(
        "seed=42; chunkserver:disk_pread flip,limit=1 ;"
        "client:frame_send:CltocsWrite*:127.0.0.1:* delay=40,p=0.25,after=2;"
        "*:dial error=CRC_ERROR"
    )
    assert seed == 42 and len(rules) == 3
    assert rules[0].site == "disk_pread" and rules[0].limit == 1
    # the peer pattern is the REST of the match: host:port addresses
    # (the documented dial form) keep their colon
    assert rules[1].op == "CltocsWrite*" and rules[1].peer == "127.0.0.1:*"
    assert rules[1].ms == 40 and rules[1].prob == 0.25 and rules[1].after == 2
    assert rules[2].code == st.CRC_ERROR and rules[2].role == "*"


def test_peer_pattern_with_port_fires():
    """Regression: a host:port peer pattern (the documented dial form)
    must match — earlier parsing truncated it at the colon and the rule
    silently never fired."""
    fs = faults.FaultSet(1, [
        faults.parse_rule("client:dial:cs:10.0.0.5:9422 drop")
    ])
    assert fs.match("client", "dial", "cs", "10.0.0.5:9422") is not None
    assert fs.match("client", "dial", "cs", "10.0.0.5:9999") is None


def test_frame_recv_flip_spares_version_byte():
    """Recv-side flips corrupt CONTENT, never the leading protocol-
    version byte (a version flip would read as negotiation failure,
    not data corruption)."""
    rule = faults.parse_rule("*:frame_recv flip")
    for i in range(64):
        rule.seed(i, 0)
        data = b"\x01" + bytes(32)
        out = faults.flip_bit(data, rule, lo=1)
        assert out[0] == 1 and out != data


@pytest.mark.parametrize("bad", [
    "chunkserver:disk_pread",          # no action
    "x:y explode",                     # unknown action
    "x:y delay",                       # delay without ms
    "x:y delay=abc",                   # bad ms
    "x:y error=NO_SUCH_STATUS",        # unknown status
    "x:y drop,p=2",                    # probability out of range
    "x:y drop,frobnicate=1",           # unknown key
    "seed=zzz; x:y drop",              # bad seed
])
def test_parse_spec_rejects(bad):
    with pytest.raises(faults.FaultSpecError):
        faults.parse_spec(bad)


def test_kill_switch_discipline():
    """LZ_FAULTS unset / cleared => ACTIVE False — the one flag every
    choke point gates on (zero overhead, byte-identical)."""
    assert faults.ACTIVE is False
    faults.arm("client:dial drop")
    assert faults.ACTIVE is True
    faults.clear()
    assert faults.ACTIVE is False


# --- deterministic decisions ------------------------------------------------


def _fire_pattern(seed: int, n: int = 64) -> list[bool]:
    fs = faults.FaultSet(seed, [faults.parse_rule("client:dial drop,p=0.5")])
    return [fs.match("client", "dial", "cs", "p") is not None
            for _ in range(n)]


def test_seeded_decisions_replay_exactly():
    a, b = _fire_pattern(7), _fire_pattern(7)
    assert a == b, "same seed + same match sequence => same fires"
    assert a != _fire_pattern(8), "different seed => different stream"
    assert 5 < sum(a) < 59, "p=0.5 actually skips and fires"


def test_limit_after_and_counts():
    fs = faults.FaultSet(1, [
        faults.parse_rule("*:site1 drop,after=2,limit=2")
    ])
    hits = [fs.match("r", "site1", "", "") is not None for _ in range(6)]
    assert hits == [False, False, True, True, False, False]
    rule = fs.rules[0]
    assert rule.matched == 6 and rule.fired == 2


def test_flip_bit_deterministic_and_single_bit():
    r1 = faults.parse_rule("*:x flip")
    r1.seed(3, 0)
    r2 = faults.parse_rule("*:x flip")
    r2.seed(3, 0)
    data = bytes(range(64))
    a, b = faults.flip_bit(data, r1), faults.flip_bit(data, r2)
    assert a == b and a != data
    diff = [i for i in range(64) if a[i] != data[i]]
    assert len(diff) == 1
    assert bin(a[diff[0]] ^ data[diff[0]]).count("1") == 1


# --- site semantics ---------------------------------------------------------


class _FakeWriter:
    def __init__(self):
        self.sent = b""
        self.closed = False

    def write(self, data):
        self.sent += data

    async def drain(self):
        pass

    def close(self):
        self.closed = True

    def get_extra_info(self, _name):
        return ("127.0.0.1", 1234)


@pytest.mark.asyncio
async def test_frame_point_actions():
    w = _FakeWriter()
    data = b"HDRHDRHD" + b"\x01" + bytes(32)

    faults.arm("client:frame_send:Victim drop,limit=1")
    with pytest.raises(ConnectionResetError):
        await faults.frame_point("frame_send", "Victim", data,
                                 peer="127.0.0.1:1234", writer=w)
    assert w.closed

    faults.clear()
    faults.arm("client:frame_send:Victim flip,limit=1")
    out = await faults.frame_point("frame_send", "Victim", data, writer=w)
    assert out != data and len(out) == len(data)
    assert out[:9] == data[:9], "flip lands in the body, framing survives"

    faults.clear()
    faults.arm("client:frame_send:Victim short,limit=1")
    w2 = _FakeWriter()
    with pytest.raises(ConnectionResetError):
        await faults.frame_point("frame_send", "Victim", data, writer=w2)
    assert 0 < len(w2.sent) < len(data) and w2.closed, "torn write"

    # no matching rule: bytes pass through untouched
    out = await faults.frame_point("frame_send", "Other", data, writer=w)
    assert out == data


@pytest.mark.asyncio
async def test_frame_point_delay_and_events():
    faults.arm("client:frame_recv:* delay=30,limit=1")
    t0 = time.monotonic()
    out = await faults.frame_point("frame_recv", "Any", b"\x01abc")
    assert out == b"\x01abc"
    assert time.monotonic() - t0 >= 0.025
    desc = faults.describe()
    assert desc["rules"][0]["fired"] == 1
    assert desc["events"][-1]["action"] == "delay"


def test_disk_site_error_and_flip(tmp_path):
    from lizardfs_tpu.chunkserver.chunk_store import (
        ChunkStore, ChunkStoreError,
    )
    from lizardfs_tpu.constants import MFSBLOCKSIZE
    from lizardfs_tpu.ops import crc32 as crc_mod

    store = ChunkStore(str(tmp_path))
    store.create(0xABC, 1, 0)
    block = bytes(range(256)) * (MFSBLOCKSIZE // 256)
    store.write(0xABC, 1, 0, 0, 0, block, crc_mod.crc32(block))

    # error action surfaces as a ChunkStoreError with the asked status
    faults.arm("chunkserver:disk_pread error=CRC_ERROR,limit=1")
    with pytest.raises(ChunkStoreError) as e:
        store.read(0xABC, 1, 0, 0, MFSBLOCKSIZE)
    assert e.value.code == st.CRC_ERROR
    # next read is clean (limit spent)
    pieces = store.read(0xABC, 1, 0, 0, MFSBLOCKSIZE)
    assert bytes(pieces[0][1]) == block

    # flip: data corrupt but the ADVERTISED crc is the stored one —
    # exactly what a receiver-side CRC check must catch
    faults.clear()
    faults.arm("chunkserver:disk_pread flip,limit=1")
    pieces = store.read(0xABC, 1, 0, 0, MFSBLOCKSIZE)
    off, data, crc = pieces[0]
    assert crc_mod.crc32(bytes(data)) != crc, "flip defeats the piece CRC"

    # disk_pwrite flip = latent corruption the next read catches
    faults.clear()
    faults.arm("chunkserver:disk_pwrite flip,limit=1")
    store.write(0xABC, 1, 0, 1, 0, block, crc_mod.crc32(block))
    faults.clear()
    with pytest.raises(ChunkStoreError) as e:
        store.read(0xABC, 1, 0, MFSBLOCKSIZE, MFSBLOCKSIZE)
    assert e.value.code == st.CRC_ERROR


def test_debug_read_delay_tweak_alias(tmp_path):
    """The legacy tweak rides the framework: setting it arms the
    serve_read delay rule, zero clears it, re-setting replaces (never
    stacks), and the tweaks listing still shows the value."""
    from lizardfs_tpu.chunkserver.server import ChunkServer

    cs = ChunkServer(str(tmp_path), master_addr=None)
    assert cs.tweaks.set("debug_read_delay_ms", "150")
    desc = faults.describe()
    assert [r for r in desc["rules"] if r["alias"] == "debug_read_delay_ms"]
    assert "delay=150" in desc["rules"][0]["rule"]
    assert cs.tweaks.to_dict()["debug_read_delay_ms"] == 150
    # replace, not stack
    assert cs.tweaks.set("debug_read_delay_ms", "80")
    rules = [r for r in faults.describe()["rules"]
             if r["alias"] == "debug_read_delay_ms"]
    assert len(rules) == 1 and "delay=80" in rules[0]["rule"]
    assert cs.tweaks.set("debug_read_delay_ms", "0")
    assert not faults.describe()["rules"] and not faults.ACTIVE


# --- RetryPolicy ------------------------------------------------------------


@pytest.mark.asyncio
async def test_retry_policy_transient_then_success():
    calls = []

    async def attempt():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionResetError("boom")
        return "ok"

    policy = retrymod.RetryPolicy(attempts=5, base_delay=0.01, max_delay=0.02)
    assert await policy.run(attempt) == "ok"
    assert len(calls) == 3


@pytest.mark.asyncio
async def test_retry_policy_permanent_raises_immediately():
    calls = []

    async def attempt():
        calls.append(1)
        raise ValueError("permanent")

    with pytest.raises(ValueError):
        await retrymod.RetryPolicy(attempts=5, base_delay=0.01).run(attempt)
    assert len(calls) == 1


@pytest.mark.asyncio
async def test_retry_policy_exhaustion_wraps_last():
    async def attempt():
        raise ConnectionResetError("always")

    with pytest.raises(retrymod.RetryError) as e:
        await retrymod.RetryPolicy(attempts=3, base_delay=0.01).run(attempt)
    assert isinstance(e.value.last, ConnectionResetError)


@pytest.mark.asyncio
async def test_deadline_threads_through_nested_policies():
    """The anti-amplification contract: an inner policy with a LARGER
    deadline still finishes inside the outer budget — stacked retries
    share one end-to-end allowance."""
    async def hang():
        await asyncio.sleep(30.0)

    async def inner():
        # inner policy asks for 30 s; the ambient (outer) 0.4 s wins
        await retrymod.RetryPolicy(
            attempts=50, base_delay=0.01, deadline=30.0
        ).run(hang)

    t0 = time.monotonic()
    with pytest.raises(retrymod.RetryError):
        await retrymod.RetryPolicy(
            attempts=50, base_delay=0.01, deadline=0.4
        ).run(inner)
    assert time.monotonic() - t0 < 3.0, "outer deadline bounded everything"


@pytest.mark.asyncio
async def test_bounded_wait_inherits_ambient_deadline():
    token = retrymod._DEADLINE.set(retrymod.Deadline(0.2))
    try:
        t0 = time.monotonic()
        with pytest.raises(asyncio.TimeoutError):
            # cap says 60 s, ambient deadline says ~0.2 s: tightest wins
            await retrymod.bounded_wait(asyncio.sleep(30.0), 60.0)
        assert time.monotonic() - t0 < 2.0
    finally:
        retrymod._DEADLINE.reset(token)
    # outside any policy the cap alone applies (and None = unbounded)
    assert retrymod.budget() is None
    assert retrymod.budget(5.0) == 5.0


@pytest.mark.asyncio
async def test_labeled_fault_counters_ride_metrics():
    from lizardfs_tpu.runtime.metrics import Metrics

    mt = Metrics()
    faults.attach_metrics("client", mt)
    faults.arm("client:dial drop,limit=2")
    decisions = [
        faults.decide("dial", op="cs", peer="x", role="client")
        for _ in range(3)
    ]
    assert [d is not None for d in decisions] == [True, True, False]
    fam = mt.labeled.get("faults_injected", {})
    totals = {k: s.total for k, s in fam.items()}
    assert totals == {(("action", "drop"), ("site", "dial")): 2.0}
    assert "lizardfs_faults_injected_total{" in mt.to_prometheus()
