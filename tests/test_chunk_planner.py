"""Whole-chunk read planner: ranking among slice representations."""

from lizardfs_tpu.core import chunk_planner, geometry
from lizardfs_tpu.proto import messages as m


def _loc(host, port, type_, part):
    return m.PartLocation(
        addr=m.Addr(host=host, port=port),
        part_id=geometry.ChunkPartType(type_, part).id,
    )


STD = geometry.SliceType(geometry.STANDARD)
EC32 = geometry.ec_type(3, 2)
XOR3 = geometry.xor_type(3)


def test_prefers_complete_std_over_striped():
    locs = (
        [_loc("h1", 1, STD, 0)]
        + [_loc(f"h{i+2}", i + 2, EC32, i) for i in range(5)]
    )
    cands = chunk_planner.candidates(locs, lambda a: 1.0)
    assert [c.type for c in cands] == [STD, EC32]
    assert all(c.complete for c in cands)


def test_unhealthy_std_loses_to_healthy_striped():
    locs = (
        [_loc("sick", 1, STD, 0)]
        + [_loc(f"h{i+2}", i + 2, EC32, i) for i in range(5)]
    )
    scores = {("sick", 1): 0.05}
    cands = chunk_planner.candidates(locs, lambda a: scores.get(a, 1.0))
    assert cands[0].type == EC32


def test_degraded_slice_ranks_below_complete():
    # ec(3,2) missing one data part (recoverable) vs complete xor3
    locs = (
        [_loc(f"e{i}", 10 + i, EC32, i) for i in (0, 2, 3, 4)]  # part 1 lost
        + [_loc(f"x{i}", 20 + i, XOR3, i) for i in range(4)]
    )
    cands = chunk_planner.candidates(locs, lambda a: 1.0)
    assert cands[0].type == XOR3 and cands[0].complete
    assert cands[1].type == EC32 and not cands[1].complete
    assert cands[1].recovery_parts == 1


def test_nonviable_slices_are_dropped():
    # ec(3,2) with only 2 parts cannot serve; std viable
    locs = (
        [_loc("e0", 10, EC32, 0), _loc("e1", 11, EC32, 1)]
        + [_loc("s", 1, STD, 0)]
    )
    cands = chunk_planner.candidates(locs, lambda a: 1.0)
    assert [c.type for c in cands] == [STD]
    # nothing viable at all -> empty
    assert chunk_planner.candidates(
        [_loc("e0", 10, EC32, 0)], lambda a: 1.0
    ) == []


def test_blacklist_desperation_pass():
    locs = [_loc("only", 1, STD, 0)]
    # the sole replica is blacklisted: desperation pass still offers it
    cands = chunk_planner.candidates(locs, lambda a: 1.0, {("only", 1)})
    assert len(cands) == 1 and cands[0].type == STD


def test_xor_parity_only_not_viable():
    # xor3 parity + one data part: 2 of 3 data parts missing
    locs = [_loc("p", 1, XOR3, 0), _loc("d1", 2, XOR3, 1)]
    assert chunk_planner.candidates(locs, lambda a: 1.0) == []
    # all three data parts but no parity: viable and complete=False
    locs = [_loc(f"d{i}", i, XOR3, i) for i in (1, 2, 3)]
    [c] = chunk_planner.candidates(locs, lambda a: 1.0)
    assert c.type == XOR3 and not c.complete
