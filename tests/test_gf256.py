"""GF(2^8) field + Reed-Solomon matrix tests against first principles.

Mirrors the reference's reed_solomon_unittest.cc strategy: random
data, encode parity, erase up to m parts, recover, compare byte-identical.
The field itself is cross-checked against a bit-level carry-less multiply.
"""

import numpy as np
import pytest

from lizardfs_tpu.ops import gf256, rs


def slow_gf_mul(a: int, b: int) -> int:
    """Bitwise carry-less multiply mod 0x11d — independent oracle."""
    p = 0
    for i in range(8):
        if (b >> i) & 1:
            p ^= a << i
    for i in range(15, 7, -1):
        if (p >> i) & 1:
            p ^= 0x11D << (i - 8)
    return p


def test_mul_table_against_bitwise_oracle():
    rng = np.random.default_rng(0)
    for _ in range(2000):
        a, b = int(rng.integers(256)), int(rng.integers(256))
        assert int(gf256.gf_mul(a, b)) == slow_gf_mul(a, b)


def test_field_axioms():
    # generator powers cycle with period 255
    assert int(gf256.GF_EXP[0]) == 1
    seen = set(int(x) for x in gf256.GF_EXP[:255])
    assert len(seen) == 255
    for a in range(1, 256):
        assert int(gf256.gf_mul(a, gf256.gf_inv(a))) == 1
    assert gf256.gf_inv(0) == 0  # ISA-L convention


def test_rs_matrix_known_values():
    # Vandermonde rows: parity row r has entries (2^r)^j.
    a = gf256.gen_rs_matrix(6, 4)  # k=4, 2 parity rows
    assert (a[:4] == np.eye(4, dtype=np.uint8)).all()
    assert list(a[4]) == [1, 1, 1, 1]  # gen = 2^0 = 1
    assert list(a[5]) == [gf256.gf_pow(2, j) for j in range(4)]


def test_cauchy_matrix_known_values():
    a = gf256.gen_cauchy1_matrix(6, 4)
    assert (a[:4] == np.eye(4, dtype=np.uint8)).all()
    for i in (4, 5):
        for j in range(4):
            assert int(a[i, j]) == gf256.gf_inv(i ^ j)


def test_generator_selection_rule():
    # Cauchy iff m >= 5 or (m == 4 and k > 20)  (reed_solomon.h:168-172)
    v = gf256.rs_generator_matrix(4, 2)
    assert list(v[4]) == [1, 1, 1, 1]  # Vandermonde signature
    c = gf256.rs_generator_matrix(4, 5)
    assert int(c[4, 0]) == gf256.gf_inv(4 ^ 0)  # Cauchy signature
    c2 = gf256.rs_generator_matrix(21, 4)
    assert int(c2[21, 0]) == gf256.gf_inv(21 ^ 0)
    v2 = gf256.rs_generator_matrix(20, 4)
    assert list(v2[20]) == [1] * 20


def test_matrix_inversion():
    rng = np.random.default_rng(1)
    for n in (2, 5, 13, 32):
        # generator sub-matrices are invertible by construction
        gen = gf256.rs_generator_matrix(n, n)
        rows = sorted(rng.choice(2 * n, size=n, replace=False).tolist())
        sub = gen[rows, :]
        inv = gf256.gf_invert_matrix(sub)
        assert (gf256.gf_matmul(inv, sub) == np.eye(n, dtype=np.uint8)).all()


@pytest.mark.parametrize(
    "k,m", [(2, 1), (3, 2), (4, 4), (8, 2), (8, 4), (21, 4), (8, 5), (32, 8), (32, 32)]
)
def test_encode_recover_roundtrip(k, m):
    rng = np.random.default_rng(42)
    size = 1024
    data = [rng.integers(0, 256, size=size, dtype=np.uint8) for _ in range(k)]
    parity = rs.encode(k, m, data)
    assert len(parity) == m
    allparts = data + parity

    # erase m random parts, recover them from the remaining k
    erased = sorted(rng.choice(k + m, size=m, replace=False).tolist())
    avail = {i: allparts[i] for i in range(k + m) if i not in erased}
    rec = rs.recover(k, m, avail, erased)
    for i in erased:
        np.testing.assert_array_equal(rec[i], allparts[i], err_msg=f"part {i}")


def test_recover_only_data_path():
    # all wanted parts are data parts -> decode-row selection path
    k, m = 5, 3
    rng = np.random.default_rng(7)
    data = [rng.integers(0, 256, size=256, dtype=np.uint8) for _ in range(k)]
    parity = rs.encode(k, m, data)
    allparts = data + parity
    avail = {i: allparts[i] for i in [1, 3, 5, 6, 7]}
    rec = rs.recover(k, m, avail, [0, 2])
    np.testing.assert_array_equal(rec[0], data[0])
    np.testing.assert_array_equal(rec[2], data[2])


def test_zero_part_elision_is_transparent():
    # None parts (all zeros, elided) must give identical bytes to explicit zeros
    k, m = 6, 3
    rng = np.random.default_rng(9)
    size = 512
    data = [rng.integers(0, 256, size=size, dtype=np.uint8) for _ in range(k)]
    data_with_zero = list(data)
    data_with_zero[2] = np.zeros(size, dtype=np.uint8)
    data_elided: list = list(data)
    data_elided[2] = None
    p_full = rs.encode(k, m, data_with_zero)
    p_elided = rs.encode(k, m, data_elided)
    for a, b in zip(p_full, p_elided):
        np.testing.assert_array_equal(a, b)

    allparts = data_with_zero + p_full
    avail = {i: allparts[i] for i in range(1, k + m - 2)}
    avail[2] = None  # available but elided as zero
    rec_wanted = [0, k + m - 1]
    rec = rs.recover(k, m, avail, rec_wanted)
    np.testing.assert_array_equal(rec[0], data_with_zero[0])
    np.testing.assert_array_equal(rec[k + m - 1], p_full[-1])


def test_recover_from_parity_only_mixture():
    # lose ALL data parts (m >= k case): recover everything from parity
    k, m = 3, 4
    rng = np.random.default_rng(11)
    data = [rng.integers(0, 256, size=128, dtype=np.uint8) for _ in range(k)]
    parity = rs.encode(k, m, data)
    avail = {k + i: parity[i] for i in range(k)}  # first 3 parity parts
    rec = rs.recover(k, m, avail, [0, 1, 2])
    for i in range(k):
        np.testing.assert_array_equal(rec[i], data[i])


def test_xor_parity_roundtrip():
    rng = np.random.default_rng(13)
    parts = [rng.integers(0, 256, size=333, dtype=np.uint8) for _ in range(5)]
    parity = rs.xor_parity(parts)
    # recover part 2 from parity + others
    rec = rs.xor_parity([parity] + [p for i, p in enumerate(parts) if i != 2])
    np.testing.assert_array_equal(rec, parts[2])
