"""Direct tests for the whole-stripe native fast paths.

Round-3 shipped three fast paths that were only exercised incidentally
(an EC read had to hit exact alignment preconditions): the native
stripe scatter/gather kernels, the one-call multi-part gather read
(`lz_read_parts_gather`), and its abort path. These tests pin each
directly — a silent precondition miss now fails a test instead of
quietly forfeiting the 3x read win.

Reference analogs: the de-interleave lives in ReadPlan post-process
closures (reference: src/common/read_plan.h); the abort semantics
mirror the mount's read-task cancellation (src/mount/readdata.cc).
"""

import asyncio
import socket as socket_mod

import numpy as np
import pytest

from lizardfs_tpu.constants import MFSBLOCKSIZE, MFSCHUNKSIZE
from lizardfs_tpu.core import native, native_io
from lizardfs_tpu.utils import data_generator, striping

from tests.test_cluster import EC_GOAL, Cluster

pytestmark = pytest.mark.asyncio

B = MFSBLOCKSIZE


# --- (a) scatter/gather vs the numpy fallback, odd shapes -------------------

def _numpy_scatter(data: np.ndarray, d: int) -> np.ndarray:
    """The pure-numpy layout contract (striping.py fallback)."""
    nbytes = data.shape[0]
    nblocks = -(-nbytes // B)
    bpp = -(-nblocks // d)
    full = np.zeros(d * bpp * B, dtype=np.uint8)
    full[:nbytes] = data
    grid = full.reshape(bpp, d, B)
    return np.ascontiguousarray(grid.transpose(1, 0, 2)).reshape(d, bpp * B)


ODD_SHAPES = [
    # (d, nbytes) covering: trailing partial block, nblocks < d,
    # nblocks % d != 0, single block, exact multiples
    (3, 7 * B + 4242),       # partial tail, nblocks % d != 0
    (8, 3 * B),              # nblocks < d
    (5, 5 * B + 1),          # partial tail lands in part 0 slot 1
    (2, B - 17),             # single partial block
    (4, 16 * B),             # exact grid
    (3, 2 * B + B // 2),     # nblocks % d == 0 after pad
]


@pytest.mark.parametrize("d,nbytes", ODD_SHAPES)
def test_native_scatter_matches_numpy(d, nbytes):
    if not native.stripe_helpers_available():
        pytest.skip("native stripe helpers not built")
    data = np.frombuffer(
        data_generator.generate(d, nbytes).tobytes(), dtype=np.uint8
    )
    want = _numpy_scatter(data, d)
    got = native.stripe_scatter(data, d, want.shape[1] // B)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("d,nbytes", ODD_SHAPES)
def test_native_gather_matches_numpy(d, nbytes):
    if not native.stripe_helpers_available():
        pytest.skip("native stripe helpers not built")
    data = np.frombuffer(
        data_generator.generate(d + 100, nbytes).tobytes(), dtype=np.uint8
    )
    parts = _numpy_scatter(data, d)
    out = np.full(nbytes, 0xEE, dtype=np.uint8)
    native.stripe_gather(list(parts), nbytes, out=out)
    np.testing.assert_array_equal(out, data)


@pytest.mark.parametrize("d,nbytes", ODD_SHAPES)
def test_padded_data_parts_native_vs_fallback(d, nbytes, monkeypatch):
    """The public entry point must produce identical parts with and
    without the native kernel (the fallback is the spec)."""
    data = np.frombuffer(
        data_generator.generate(2 * d, nbytes).tobytes(), dtype=np.uint8
    )
    native_parts, plen_n = striping.padded_data_parts(data, d)
    monkeypatch.setattr(native, "stripe_helpers_available", lambda: False)
    numpy_parts, plen_f = striping.padded_data_parts(data, d)
    assert plen_n == plen_f
    for a, b in zip(native_parts, numpy_parts):
        np.testing.assert_array_equal(a, b)


# --- (b) whole-stripe gather engagement + fallback --------------------------

async def _write_aligned_ec_file(cluster, c, nbytes):
    f = await c.create(1, "stripe.bin")
    await c.setgoal(f.inode, EC_GOAL)  # ec(3,2)
    payload = data_generator.generate(3, nbytes).tobytes()
    await c.write_file(f.inode, payload)
    return f, payload


async def test_stripe_gather_fast_path_engages(tmp_path):
    """A slot-aligned bulk EC read must take the one-call native gather
    (counter proves it) and return the right bytes."""
    if not native_io.parts_gather_available():
        pytest.skip("native parts gather not built")
    cluster = Cluster(tmp_path)
    await cluster.start()
    try:
        c = await cluster.client()
        # 6 MiB: 96 blocks, d=3 -> 32 whole slots, bulk (>= 4 MiB)
        f, payload = await _write_aligned_ec_file(cluster, c, 6 * 2**20)
        back = np.zeros(len(payload), dtype=np.uint8)
        n = await c.read_file_into(f.inode, 0, back)
        assert n == len(payload) and back.tobytes() == payload
        assert c.op_counters.get("stripe_gather_fast", 0) >= 1, \
            "fast-path precondition silently missed"
        assert not c.op_counters.get("stripe_gather_fallback")
    finally:
        await cluster.stop()


async def test_stripe_gather_failure_falls_back_to_waves(tmp_path, monkeypatch):
    """A native gather failure must degrade to the wave executor and
    still return correct bytes (counter proves the degrade happened)."""
    if not native_io.parts_gather_available():
        pytest.skip("native parts gather not built")
    cluster = Cluster(tmp_path)
    await cluster.start()
    try:
        c = await cluster.client()
        f, payload = await _write_aligned_ec_file(cluster, c, 6 * 2**20)

        def boom(*a, **k):
            raise native_io.NativeIOError(5, "injected gather failure")

        monkeypatch.setattr(native_io, "read_parts_gather_blocking", boom)
        back = np.zeros(len(payload), dtype=np.uint8)
        n = await c.read_file_into(f.inode, 0, back)
        assert n == len(payload) and back.tobytes() == payload
        assert c.op_counters.get("stripe_gather_fallback", 0) >= 1
    finally:
        await cluster.stop()


async def test_stripe_gather_cs_death_still_reads(tmp_path):
    """With a data-part holder dead, the fast-path precondition fails
    (part missing) and the wave executor recovers the bytes."""
    if not native_io.parts_gather_available():
        pytest.skip("native parts gather not built")
    cluster = Cluster(tmp_path)
    await cluster.start(health_interval=30.0)  # no repair: raw recovery
    try:
        c = await cluster.client()
        f, payload = await _write_aligned_ec_file(cluster, c, 6 * 2**20)
        chunk = next(iter(cluster.master.meta.registry.chunks.values()))
        data_holder = next(cs for cs, p in sorted(chunk.parts) if p < 3)
        victim = next(
            s for s in cluster.chunkservers
            if s.port == cluster.master.meta.registry.servers[data_holder].port
        )
        await victim.stop()
        await asyncio.sleep(0.1)
        back = np.zeros(len(payload), dtype=np.uint8)
        n = await c.read_file_into(f.inode, 0, back)
        assert n == len(payload) and back.tobytes() == payload
    finally:
        await cluster.stop()


# --- (c) abort path: no buffer writes after the caller resumes --------------

async def test_abort_parts_gather_quiesces_buffer(tmp_path):
    """abort_parts_gather must unblock the executor thread promptly,
    and once the caller observes completion NOTHING may touch the
    destination buffer again (the caller immediately reuses it)."""
    if not native_io.parts_gather_available():
        pytest.skip("native parts gather not built")

    # a server that accepts, reads the request, and stalls until teardown
    # (3.12's Server.wait_closed waits for handlers — an unconditional
    # sleep here would hang the test's own cleanup)
    stalled = asyncio.Event()
    teardown = asyncio.Event()

    async def stall_handler(reader, writer):
        try:
            await reader.read(4096)
            stalled.set()
            await teardown.wait()
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            writer.close()

    server = await asyncio.start_server(stall_handler, "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    try:
        region_blocks = 6
        out = np.zeros(region_blocks * B, dtype=np.uint8)
        cell: dict = {}
        fut = asyncio.get_running_loop().run_in_executor(
            native_io.EXECUTOR,
            lambda: native_io.read_parts_gather_blocking(
                [("127.0.0.1", port)] * 3, 42, 1, [1, 2, 3], 0,
                region_blocks, out, cell,
            ),
        )
        await asyncio.wait_for(stalled.wait(), 10.0)
        t0 = asyncio.get_running_loop().time()
        native_io.abort_parts_gather(cell)
        with pytest.raises((native_io.NativeIOError, OSError)):
            await asyncio.wait_for(fut, 10.0)
        abort_latency = asyncio.get_running_loop().time() - t0
        assert abort_latency < 5.0, "abort did not unblock the thread"
        # the caller now owns the buffer again: reuse it and prove no
        # late writer clobbers it
        sentinel = np.frombuffer(
            data_generator.generate(99, out.nbytes).tobytes(), dtype=np.uint8
        )
        out[:] = sentinel
        await asyncio.sleep(0.3)
        np.testing.assert_array_equal(out, sentinel)
    finally:
        teardown.set()
        server.close()
        await server.wait_closed()


async def test_abort_before_dial_refuses_cleanly():
    """An abort that lands before the sockets are even registered must
    make the exchange refuse to start (no write to the buffer at all)."""
    if not native_io.parts_gather_available():
        pytest.skip("native parts gather not built")
    # unreachable port: acquire() would block in connect; abort first
    out = np.full(3 * B, 0x77, dtype=np.uint8)
    cell = {"aborted": True}
    sock = socket_mod.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.listen(8)  # accepts (all three dials) but nobody will speak
    try:
        with pytest.raises(native_io.NativeIOError):
            await native_io.run(
                native_io.read_parts_gather_blocking,
                [("127.0.0.1", port)] * 3, 7, 1, [1, 2, 3], 0, 3, out, cell,
            )
        assert np.all(out == 0x77)
    finally:
        sock.close()


# --- multi-part scatter WRITE fast path -------------------------------------

async def test_parts_scatter_write_engages(tmp_path):
    """Striped writes must take the one-call native multi-part path
    (counter proves it) and produce byte-identical data."""
    if not native_io.parts_scatter_available():
        pytest.skip("native parts scatter not built")
    cluster = Cluster(tmp_path)
    await cluster.start()
    try:
        c = await cluster.client()
        f = await c.create(1, "scatterw.bin")
        await c.setgoal(f.inode, EC_GOAL)
        payload = data_generator.generate(11, 3 * 2**20 + 777).tobytes()
        await c.write_file(f.inode, payload)
        assert c.op_counters.get("parts_scatter_write", 0) >= 1, \
            "scatter write path not engaged"
        back = await c.read_file(f.inode, 0, len(payload))
        assert bytes(back) == payload
    finally:
        await cluster.stop()


async def test_parts_scatter_write_failure_falls_back(tmp_path, monkeypatch):
    """A native scatter failure degrades to per-part writes with the
    same bytes on disk."""
    if not native_io.parts_scatter_available():
        pytest.skip("native parts scatter not built")
    cluster = Cluster(tmp_path)
    await cluster.start()
    try:
        c = await cluster.client()

        def boom(*a, **k):
            raise native_io.NativeIOError(5, "injected scatter failure")

        monkeypatch.setattr(native_io, "write_parts_scatter_blocking", boom)
        f = await c.create(1, "fallbackw.bin")
        await c.setgoal(f.inode, EC_GOAL)
        payload = data_generator.generate(12, 2 * 2**20).tobytes()
        await c.write_file(f.inode, payload)
        assert c.op_counters.get("parts_scatter_fallback", 0) >= 1
        back = await c.read_file(f.inode, 0, len(payload))
        assert bytes(back) == payload
    finally:
        await cluster.stop()


async def test_parts_scatter_skips_chained_copies(tmp_path):
    """goal-2 copies use relay chains (two holders per part) — the
    scatter path must stand aside and the chain path still work."""
    if not native_io.parts_scatter_available():
        pytest.skip("native parts scatter not built")
    cluster = Cluster(tmp_path, n_cs=4)
    await cluster.start()
    try:
        c = await cluster.client()
        f = await c.create(1, "chained.bin")
        await c.setgoal(f.inode, 2)  # 2 copies -> chain write
        payload = data_generator.generate(13, 1 * 2**20 + 55).tobytes()
        await c.write_file(f.inode, payload)
        back = await c.read_file(f.inode, 0, len(payload))
        assert bytes(back) == payload
    finally:
        await cluster.stop()


# --- write-abort path: zombie sender threads must die promptly --------------

async def test_abort_write_scatter_unblocks_thread():
    """abort_write must unblock a scatter-write executor thread stuck on
    an unresponsive chunkserver, and mark the cell finished so the
    caller knows the payload buffers are no longer being read."""
    if not native_io.parts_scatter_available():
        pytest.skip("native parts scatter not built")
    stalled = asyncio.Event()
    teardown = asyncio.Event()

    async def stall_handler(reader, writer):
        try:
            await reader.read(4096)  # swallow the WriteInit, never reply
            stalled.set()
            await teardown.wait()
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            writer.close()

    server = await asyncio.start_server(stall_handler, "127.0.0.1", 0)
    port = server.sockets[0].getsockname()[1]
    try:
        payloads = [np.zeros(B, dtype=np.uint8) for _ in range(3)]
        cell: dict = {"submitted": True}
        fut = asyncio.get_running_loop().run_in_executor(
            native_io.EXECUTOR,
            lambda: native_io.write_parts_scatter_blocking(
                [("127.0.0.1", port)] * 3, 42, 1, [1, 2, 3],
                payloads, [B] * 3, 0, cell,
            ),
        )
        await asyncio.wait_for(stalled.wait(), 10.0)
        t0 = asyncio.get_running_loop().time()
        native_io.abort_write(cell)
        with pytest.raises((native_io.NativeIOError, OSError)):
            await asyncio.wait_for(fut, 10.0)
        assert asyncio.get_running_loop().time() - t0 < 5.0, \
            "abort did not unblock the sender thread"
        assert cell.get("finished") is True
    finally:
        teardown.set()
        server.close()
        await server.wait_closed()


async def test_cancelled_striped_write_does_not_pool_staging(
    tmp_path, monkeypatch
):
    """A cancelled chunk write whose native sender may still be running
    must NOT return the staging buffer to the reuse pool (the zombie
    thread streams from it; pooling it lets the next chunk's scatter
    overwrite bytes mid-send) — and must abort the zombie's sockets."""
    if not (native_io.parts_scatter_available()
            and native.stripe_helpers_available()):
        pytest.skip("native fast paths not built")
    import threading
    import time as time_mod

    cluster = Cluster(tmp_path)
    await cluster.start()
    try:
        c = await cluster.client()
        # pin the scatter-batch (serial) path: the pipelined path has
        # its own session sender and is exercised below
        c.write_pipeline = False
        f = await c.create(1, "pool.bin")
        await c.setgoal(f.inode, EC_GOAL)
        full = data_generator.generate(21, MFSCHUNKSIZE).tobytes()
        # 1) clean full-chunk write pools its staging buffer
        await c.write_file(f.inode, full)
        pooled = sum(len(b) for b in c._stage_buffers.values())
        assert pooled >= 1, "full-chunk write should pool its stage"

        # 2) hung scatter + cancellation: the (reused) buffer must not
        # come back to the pool, and the cell must be aborted
        started = threading.Event()
        seen_cells: list[dict] = []

        def hang_until_abort(addrs, cid, ver, pids, payloads, lengths,
                             part_offset=0, cell=None):
            seen_cells.append(cell)
            started.set()
            deadline = time_mod.monotonic() + 15.0
            while time_mod.monotonic() < deadline:
                if cell is not None and cell.get("aborted"):
                    break
                time_mod.sleep(0.01)
            try:
                raise native_io.NativeIOError(-1, "hung exchange aborted")
            finally:
                if cell is not None:
                    cell["finished"] = True

        monkeypatch.setattr(
            native_io, "write_parts_scatter_blocking", hang_until_abort
        )
        g = await c.create(1, "pool2.bin")
        await c.setgoal(g.inode, EC_GOAL)
        task = asyncio.ensure_future(c.write_file(g.inode, full))
        await asyncio.wait_for(
            asyncio.get_running_loop().run_in_executor(None, started.wait, 10),
            15.0,
        )
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task
        assert sum(len(b) for b in c._stage_buffers.values()) == 0, \
            "staging buffer pooled while a zombie sender may hold it"
        assert any(cl and cl.get("aborted") for cl in seen_cells), \
            "cancelled write did not abort its in-flight sender"

        # 3) same invariant for the PIPELINED/WINDOWED sender: a
        # cancelled session segment must abort its cell and keep both
        # the stage and the parity send buffer out of the pool (the
        # windowed default sends via send_segment_window, the kill-
        # switch path via send_segment — hang whichever engages)
        monkeypatch.undo()
        c.write_pipeline = True
        started3 = threading.Event()
        cells3: list[dict] = []

        def hang_segment(self, payloads, lengths, part_offset, write_id):
            cells3.append(self.cell)
            started3.set()
            deadline = time_mod.monotonic() + 15.0
            while time_mod.monotonic() < deadline:
                if self.cell.get("aborted"):
                    break
                time_mod.sleep(0.01)
            self.close()
            raise native_io.NativeIOError(-1, "hung segment aborted")

        monkeypatch.setattr(
            native_io.PartsScatterSession, "send_segment", hang_segment
        )
        monkeypatch.setattr(
            native_io.PartsScatterSession, "send_segment_window",
            hang_segment,
        )
        h = await c.create(1, "pool3.bin")
        await c.setgoal(h.inode, EC_GOAL)
        task = asyncio.ensure_future(c.write_file(h.inode, full))
        await asyncio.wait_for(
            asyncio.get_running_loop().run_in_executor(
                None, started3.wait, 10
            ),
            15.0,
        )
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task
        assert sum(len(b) for b in c._stage_buffers.values()) == 0, \
            "buffers pooled while a zombie session sender may hold them"
        assert any(cl and cl.get("aborted") for cl in cells3), \
            "cancelled pipelined write did not abort its session"
    finally:
        await cluster.stop()


# --- same-host unix-socket fast path ----------------------------------------

async def test_uds_fast_path_engages(tmp_path):
    """The same-host abstract-socket fast path must actually engage:
    this pins the name contract between native_io._blocking_socket and
    serve_native.cpp's uds_data_addr — a silent format drift would
    quietly fall back to TCP and forfeit the ~2.5x per-byte win."""
    if not native_io.available():
        pytest.skip("native io not built")
    before = native_io.UDS_CONNECTS
    cluster = Cluster(tmp_path)
    await cluster.start()
    try:
        c = await cluster.client()
        f = await c.create(1, "uds.bin")
        await c.setgoal(f.inode, EC_GOAL)
        payload = data_generator.generate(17, 2 * 2**20).tobytes()
        await c.write_file(f.inode, payload)
        c.cache.invalidate(f.inode)
        back = await c.read_file(f.inode, 0, len(payload))
        assert bytes(back) == payload
        assert native_io.UDS_CONNECTS > before, \
            "no data-plane connection took the unix-socket fast path"
    finally:
        await cluster.stop()


# --- same-host shared-memory part rings (native/shm_ring.h) -----------------

async def _striped_roundtrip(cluster, c, name, nbytes, goal=None):
    f = await c.create(1, name)
    await c.setgoal(f.inode, goal if goal is not None else EC_GOAL)
    payload = data_generator.generate(29, nbytes).tobytes()
    await c.write_file(f.inode, payload)
    c.cache.invalidate(f.inode)
    back = await c.read_file(f.inode, 0, nbytes)
    assert bytes(back) == payload, "roundtrip corruption"
    return f


def test_shm_ring_unalloc_rollback_does_not_overlap_live_regions():
    """Rolling back a staged-but-failed allocation must retract the
    ring head, not advance the implied tail: a free()-based rollback
    leaves a hole the accounting stops covering, and a later alloc can
    hand out a region overlapping a sent-but-unacked segment's bytes
    (the server would then CRC-fail the descriptor it reads later)."""
    if not hasattr(native_io, "ShmRing"):
        pytest.skip("native shm ring not built")
    ring = native_io.ShmRing(native_io.shm_seg_bytes())
    try:
        ring.size = 100  # drive the allocator, not the mapping
        live = []
        for _ in range(2):  # seg1 [0,30), seg2 [30,60): sent, unacked
            off, cost = ring.alloc(30)
            live.append((off, off + 30))
        off3, cost3 = ring.alloc(20)  # seg3 staged [60,80)...
        ring.unalloc(off3, cost3, 20)  # ...then encode fails: roll back
        ring.free(30)  # seg1 acked (FIFO)
        live.pop(0)
        for nbytes in (20, 30, 20):
            got = ring.alloc(nbytes)
            if got is None:
                continue
            off, _cost = got
            for lo, hi in live:
                assert not (off < hi and off + nbytes > lo), (
                    f"alloc [{off},{off + nbytes}) overlaps "
                    f"live [{lo},{hi})"
                )
    finally:
        ring.close()


async def test_shm_ring_engages(tmp_path):
    """A same-host windowed striped write must negotiate memfd rings
    and move its parts as descriptor frames: client counters, the
    chunkserver's native shm stats, and the copy-free trace kind all
    prove the handoff — a silent precondition miss would quietly fall
    back to the socket-copy path and forfeit the send-phase win."""
    if not native_io.parts_shm_available():
        pytest.skip("native shm ring not built")
    cluster = Cluster(tmp_path)
    await cluster.start()
    try:
        c = await cluster.client()
        c.WRITE_PIPELINE_MIN_BYTES = 1
        assert c.write_window is not None
        await _striped_roundtrip(cluster, c, "ring.bin", 8 * 2**20)
        assert c.op_counters.get("write_shm", 0) >= 1, \
            "shm ring path did not engage"
        assert c.metrics.series["shm_ring_segments_mapped"].total >= 1
        assert c.metrics.series["shm_ring_desc_parts"].total >= 1
        server_desc_ops = sum(
            cs.data_server.shm_stats()["desc_ops"]
            for cs in cluster.chunkservers
            if cs.data_server is not None
        )
        assert server_desc_ops >= 1, \
            "no chunkserver landed a ring descriptor"
    finally:
        await cluster.stop()


async def test_shm_ring_engages_on_asyncio_chunkserver(tmp_path):
    """Pure-Python chunkservers have no UDS listener, so their demux's
    only reachable transport is loopback TCP: a ring-capable client
    writing to an asyncio chunkserver over 127.0.0.1 must still
    negotiate segments and ship descriptors (the fd travels as a
    /proc/<pid>/fd name instead of SCM_RIGHTS) — otherwise the
    pure-Python fallback demux is dead code."""
    if not native_io.parts_shm_available():
        pytest.skip("native shm ring not built")
    cluster = Cluster(tmp_path, n_cs=6, native_data_plane=False)
    await cluster.start()
    try:
        c = await cluster.client()
        c.WRITE_PIPELINE_MIN_BYTES = 1
        assert c.write_window is not None
        await _striped_roundtrip(cluster, c, "pyring2.bin", 8 * 2**20)
        assert c.op_counters.get("write_shm", 0) >= 1, \
            "shm ring path did not engage against the asyncio plane"
        mapped = sum(
            cs.metrics.series["shm_segments_mapped"].total
            for cs in cluster.chunkservers
            if "shm_segments_mapped" in cs.metrics.series
        )
        assert mapped >= 1, "no asyncio chunkserver mapped a segment"
    finally:
        await cluster.stop()


async def test_shm_ring_segments_released_on_session_teardown(tmp_path):
    """After writes finish and pooled connections are discarded, every
    chunkserver's active-segment gauge returns to zero (segments are
    owned by the connection, never leaked across sessions)."""
    if not native_io.parts_shm_available():
        pytest.skip("native shm ring not built")
    cluster = Cluster(tmp_path)
    await cluster.start()
    try:
        c = await cluster.client()
        c.WRITE_PIPELINE_MIN_BYTES = 1
        for rep in range(3):
            await _striped_roundtrip(
                cluster, c, f"seg{rep}.bin", 4 * 2**20
            )
        mapped = sum(
            cs.data_server.shm_stats()["segments_mapped"]
            for cs in cluster.chunkservers
            if cs.data_server is not None
        )
        assert mapped >= 1
        # pooled connections keep their segment mapped (that's the
        # point: no per-chunk renegotiation) — drop the pools and the
        # mappings must go with them (ring conns pool in RING_POOL)
        idle = []
        for pool in (native_io.POOL, native_io.RING_POOL):
            with pool._lock:
                idle += [
                    s for bucket in pool._idle.values() for s in bucket
                ]
                pool._idle.clear()
        for s in idle:
            native_io.shm_ring_drop(s)
            s.close()
        deadline = asyncio.get_event_loop().time() + 10.0
        while asyncio.get_event_loop().time() < deadline:
            active = sum(
                cs.data_server.shm_stats()["active_segments"]
                for cs in cluster.chunkservers
                if cs.data_server is not None
            )
            if active == 0:
                break
            await asyncio.sleep(0.1)
        assert active == 0, f"{active} shm segments leaked past teardown"
    finally:
        await cluster.stop()


async def test_shm_ring_full_falls_back_to_scatterv(tmp_path, monkeypatch):
    """A ring too small for a segment must fall back to the vectored
    socket-copy send mid-stripe — same bytes, fallback counted."""
    if not native_io.parts_shm_available():
        pytest.skip("native shm ring not built")
    # 64 KiB segments: smaller than any padded parity region of the
    # striped segments below, so every staging attempt fails ring-full
    monkeypatch.setenv("LZ_SHM_RING_MB", "0.0625")
    cluster = Cluster(tmp_path)
    await cluster.start()
    try:
        c = await cluster.client()
        c.WRITE_PIPELINE_MIN_BYTES = 1
        await _striped_roundtrip(cluster, c, "tiny_ring.bin", 8 * 2**20)
        fallbacks = c.metrics.series.get("shm_ring_fallbacks")
        assert fallbacks is not None and fallbacks.total >= 1, \
            "ring-full segments did not fall back to scatterv"
        # the socket-copy frames ride the SAME proactor-owned
        # connections the ring negotiated on — the windowed write must
        # survive the interleave, not degrade to the serial rewrite
        assert not c.op_counters.get("write_pipeline_fallback"), \
            "proactor rejected interleaved scatterv frames"
    finally:
        await cluster.stop()


async def test_shm_ring_kill_switch_stays_on_socket_path(tmp_path,
                                                         monkeypatch):
    """LZ_SHM_RING=0 must keep the windowed write on the PR-5 scatterv
    path: no handshake, no descriptors, no client-side ring series."""
    if not native_io.parts_shm_available():
        pytest.skip("native shm ring not built")
    monkeypatch.setenv("LZ_SHM_RING", "0")
    cluster = Cluster(tmp_path)
    await cluster.start()
    try:
        c = await cluster.client()
        c.WRITE_PIPELINE_MIN_BYTES = 1
        await _striped_roundtrip(cluster, c, "killed.bin", 8 * 2**20)
        assert c.op_counters.get("write_window", 0) >= 1
        assert not c.op_counters.get("write_shm"), \
            "kill switch did not disable the ring path"
        assert "shm_ring_desc_parts" not in c.metrics.series
        assert all(
            cs.data_server.shm_stats()["segments_mapped"] == 0
            for cs in cluster.chunkservers
            if cs.data_server is not None
        )
    finally:
        await cluster.stop()


async def test_shm_ring_kill_switch_off_spelling_disables_server(
        tmp_path, monkeypatch):
    """LZ_SHM_RING=off must kill the native server's ring acceptance
    too — spelling parity between lzshm::ring_disabled and
    native_io.shm_ring_enabled.  The client side is forced eligible so
    only the server's C-side env parse is under test: the handshake
    must be refused and the write must fall back to scatterv."""
    if not native_io.parts_shm_available():
        pytest.skip("native shm ring not built")
    monkeypatch.setenv("LZ_SHM_RING", "off")
    monkeypatch.setattr(native_io, "shm_ring_enabled", lambda: True)
    cluster = Cluster(tmp_path)
    await cluster.start()
    try:
        c = await cluster.client()
        c.WRITE_PIPELINE_MIN_BYTES = 1
        await _striped_roundtrip(cluster, c, "killed_off.bin", 8 * 2**20)
        assert not c.op_counters.get("write_shm"), \
            "server accepted a ring despite LZ_SHM_RING=off"
        assert all(
            cs.data_server.shm_stats()["segments_mapped"] == 0
            for cs in cluster.chunkservers
            if cs.data_server is not None
        )
    finally:
        await cluster.stop()


async def test_shm_ring_asyncio_fallback_demux(tmp_path):
    """The pure-Python chunkserver demuxes the same descriptor frames:
    ShmInit maps the client's memfd via /proc (StreamReader drops the
    SCM_RIGHTS cmsg), ShmWritePart lands bytes read straight from the
    mapping, and the mapping is released when the connection closes."""
    import os

    from lizardfs_tpu.ops import crc32 as crc_mod
    from lizardfs_tpu.proto import framing
    from lizardfs_tpu.proto import messages as m
    from lizardfs_tpu.proto import status as st

    if not hasattr(os, "memfd_create"):
        pytest.skip("no memfd_create")
    cluster = Cluster(tmp_path, n_cs=3, native_data_plane=False)
    await cluster.start()
    try:
        c = await cluster.client()
        f = await c.create(1, "pyring.bin")
        # goal 1 plain copy: one part, part_id 0, easy to address
        payload = data_generator.generate(31, 2 * MFSBLOCKSIZE).tobytes()
        await c.write_file(f.inode, payload)  # creates the chunk
        loc = await c.chunk_info(f.inode, 0)
        part = loc.locations[0]

        ring = native_io.ShmRing(1 << 20)
        try:
            fresh = data_generator.generate(37, 2 * MFSBLOCKSIZE).tobytes()
            ring.arr[: len(fresh)] = np.frombuffer(fresh, dtype=np.uint8)
            reader, writer = await asyncio.open_connection(
                part.addr.host, part.addr.port
            )
            try:
                await framing.send_message(writer, m.CltocsShmInit(
                    req_id=1, pid=os.getpid(), mem_fd=ring.memfd,
                    seg_size=ring.size,
                ))
                ack = await framing.read_message(reader)
                assert isinstance(ack, m.CstoclWriteStatus)
                assert ack.status == st.OK, "asyncio ShmInit refused"
                await framing.send_message(writer, m.CltocsWriteInit(
                    req_id=2, chunk_id=loc.chunk_id, version=loc.version,
                    part_id=part.part_id, chain=[], create=False,
                ))
                ack = await framing.read_message(reader)
                assert ack.status == st.OK
                crcs = [
                    crc_mod.crc32(
                        fresh[i * MFSBLOCKSIZE:(i + 1) * MFSBLOCKSIZE]
                    )
                    for i in range(2)
                ]
                await framing.send_message(writer, m.CltocsShmWritePart(
                    req_id=3, chunk_id=loc.chunk_id, write_id=3,
                    part_id=part.part_id, part_offset=0, ring_off=0,
                    length=len(fresh), crcs=crcs,
                ))
                ack = await framing.read_message(reader)
                assert ack.status == st.OK, "descriptor write refused"
                await framing.send_message(writer, m.CltocsWriteEnd(
                    req_id=4, chunk_id=loc.chunk_id,
                ))
                ack = await framing.read_message(reader)
                assert ack.status == st.OK
            finally:
                writer.close()
                try:
                    await writer.wait_closed()
                except (ConnectionError, OSError):
                    pass
        finally:
            ring.close()
        c.cache.invalidate(f.inode)
        back = await c.read_file(f.inode, 0, len(fresh))
        assert bytes(back) == fresh, "ring bytes did not land"
    finally:
        await cluster.stop()


async def test_shm_init_refused_for_remote_peers(tmp_path):
    """Server-side enforcement of the same-host contract: a ShmInit
    arriving over TCP from a non-loopback peer is refused outright —
    remote peers must not drive the /proc fd mapping or pin 1 GiB
    server-side segments (the client's own AF_UNIX gate only protects
    well-behaved clients, not the server)."""
    import os

    from lizardfs_tpu.proto import framing
    from lizardfs_tpu.proto import messages as m
    from lizardfs_tpu.proto import status as st

    cluster = Cluster(tmp_path, n_cs=1, native_data_plane=False)
    await cluster.start()
    try:
        cs = cluster.chunkservers[0]

        class _RemoteWriter:
            """Quacks like a StreamWriter on a non-loopback TCP conn."""

            def __init__(self):
                self.buf = bytearray()
                self.sock = socket_mod.socket(
                    socket_mod.AF_INET, socket_mod.SOCK_STREAM
                )

            def get_extra_info(self, key):
                if key == "socket":
                    return self.sock
                if key == "peername":
                    return ("203.0.113.9", 54321)
                return None

            def write(self, data):
                self.buf += data

            async def drain(self):
                pass

        if not hasattr(os, "memfd_create"):
            pytest.skip("no memfd_create")
        # a real, mappable segment: the refusal must come from the
        # same-host gate, not from a failed /proc open
        memfd = os.memfd_create("lzshm-test")
        os.ftruncate(memfd, 1 << 20)
        w = _RemoteWriter()
        try:
            shm_state: dict = {}
            await cs._serve_shm_init(
                w,
                m.CltocsShmInit(
                    req_id=1, pid=os.getpid(), mem_fd=memfd,
                    seg_size=1 << 20,
                ),
                shm_state,
            )
        finally:
            w.sock.close()
            os.close(memfd)
        reader = asyncio.StreamReader()
        reader.feed_data(bytes(w.buf))
        reader.feed_eof()
        ack = await framing.read_message(reader)
        assert isinstance(ack, m.CstoclWriteStatus)
        assert ack.status == st.EINVAL, "remote ShmInit must be refused"
        assert "mm" not in shm_state, "remote peer mapped a segment"
    finally:
        await cluster.stop()
