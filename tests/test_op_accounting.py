"""Exactly-once op accounting across failure/retry paths (ISSUE 14
satellite; the PR-7 double-count class).

One LOGICAL read/write must count exactly once in the new per-session
labeled counters no matter how many transient retries, replica
fallbacks, or RMW retry loops the implementation burned underneath.
Each scenario runs under the deterministic scheduler
(runtime/detsched.py) across several seeds so callback/executor
interleavings can't hide a double count: the seed that reorders the
retry against the original attempt is exactly the one a wall-clock test
never explores.
"""

import pytest

from lizardfs_tpu.runtime import detsched, faults
from lizardfs_tpu.utils import data_generator

# seed 1 rides tier-1; the rest of the seed matrix is slow-marked (each
# scenario boots a real in-process cluster under the deterministic
# loop, ~40 s apiece — the full matrix belongs to `make racehunt` /
# chaos-cadence runs, not the fast gate)
SEEDS = (
    1,
    pytest.param(2, marks=pytest.mark.slow),
    pytest.param(3, marks=pytest.mark.slow),
)


def _ops(client, op_class: str) -> int:
    """Count of the client's labeled session_ops cell for one class."""
    t = client.metrics.labeled_timings.get("session_ops", {}).get(
        (("op", op_class), ("session", f"s{client.session_id}"))
    )
    return t.count if t is not None else 0


def _bytes(client, op_class: str) -> float:
    s = client.metrics.labeled.get("session_bytes", {}).get(
        (("op", op_class), ("session", f"s{client.session_id}"))
    )
    return s.total if s is not None else 0.0


async def _read_retry_scenario(tmp_path, seed: int):
    """A degraded ec(3,2) read whose first part serve errors: the read
    recovers (decode or re-locate retry) and the logical read counts
    ONCE."""
    from tests.test_cluster import Cluster, EC_GOAL

    cluster = Cluster(tmp_path, n_cs=5, native_data_plane=False)
    await cluster.start()
    try:
        # armed BEFORE any data IO: while rules are armed the client's
        # native fast paths stand down, which the deterministic loop
        # REQUIRES — detsched runs executor jobs inline, so a blocking
        # native socket call against the in-process CS would deadlock.
        # The rule itself only matches serve_read, so the write below
        # is unaffected; the first read after the invalidate errors
        # once and must recover.
        faults.install(
            "seed=%d; chunkserver:serve_read error,limit=1" % seed
        )
        c = await cluster.client()
        f = await c.create(1, "ret.bin")
        await c.setgoal(f.inode, EC_GOAL)
        payload = data_generator.generate(3, 5 * 65536 + 17).tobytes()
        await c.write_file(f.inode, payload)
        c.cache.invalidate(f.inode)
        c._locate_cache.clear()
        before_ops = _ops(c, "read")
        before_bytes = _bytes(c, "read")
        data = await c.read_file(f.inode, 0, len(payload))
        assert data == payload
        return (_ops(c, "read") - before_ops,
                _bytes(c, "read") - before_bytes, len(payload))
    finally:
        faults.clear()
        await cluster.stop()


async def _rmw_retry_scenario(tmp_path, seed: int):
    """A partial-stripe pwrite whose first attempt tears on an injected
    disk error: the RMW retry loop reruns the attempt, the logical
    write counts ONCE."""
    from tests.test_cluster import Cluster, EC_GOAL

    cluster = Cluster(tmp_path, n_cs=5, native_data_plane=False)
    await cluster.start()
    try:
        # keep SOME rule armed for the whole scenario (native paths
        # stand down — see _read_retry_scenario); the never-firing
        # placeholder covers the base write, then the real one-shot
        # disk error replaces it for the pwrite under test
        faults.install(
            "seed=%d; chunkserver:disk_pwrite error,after=1000000" % seed
        )
        c = await cluster.client()
        f = await c.create(1, "rmw.bin")
        await c.setgoal(f.inode, EC_GOAL)
        base = data_generator.generate(5, 6 * 65536).tobytes()
        await c.write_file(f.inode, base)
        patch = b"P" * 4096
        before = _ops(c, "write")
        faults.install(
            "seed=%d; chunkserver:disk_pwrite error,limit=1" % seed
        )
        await c.pwrite(f.inode, 100, patch)
        c.cache.invalidate(f.inode)
        c._locate_cache.clear()
        # the one-shot rule already fired: reads pass through it armed
        got = await c.read_file(f.inode, 100, len(patch))
        assert got == patch
        return _ops(c, "write") - before
    finally:
        faults.clear()
        await cluster.stop()


async def _replica_fallback_scenario(tmp_path):
    """A getattr whose replica leg refuses (follow link down) falls
    back to the primary: the logical op counts once on the client AND
    once in the PRIMARY's per-session accounting — the refusing shadow
    records nothing."""
    import asyncio

    from lizardfs_tpu.chunkserver.server import ChunkServer
    from lizardfs_tpu.client.client import Client
    from lizardfs_tpu.master.server import MasterServer
    from tests.test_cluster import make_goals

    active = MasterServer(str(tmp_path / "m1"), goals=make_goals())
    await active.start()
    shadow = MasterServer(
        str(tmp_path / "m2"), goals=make_goals(),
        personality="shadow", active_addr=("127.0.0.1", active.port),
    )
    await shadow.start()
    addrs = [("127.0.0.1", active.port), ("127.0.0.1", shadow.port)]
    cs = ChunkServer(str(tmp_path / "cs0"), master_addr=addrs,
                     heartbeat_interval=0.2)
    await cs.start()
    c = Client("", 0, master_addrs=addrs)
    await c.connect()
    try:
        f = await c.create(1, "fb.bin")
        deadline = asyncio.get_running_loop().time() + 10
        while (shadow.changelog.version != active.changelog.version
               and asyncio.get_running_loop().time() < deadline):
            await asyncio.sleep(0.05)
        # prime the replica link, then break the follow stream so the
        # next replica-routed read is REFUSED -> primary fallback
        assert (await c.getattr(f.inode)).inode == f.inode
        shadow._shadow_task.cancel()
        await asyncio.sleep(0.2)
        assert not shadow._replica_ready()

        def master_meta_reads(master):
            t = master.session_ops.metrics.labeled_timings.get(
                "session_ops", {}
            ).get((("op", "meta_read"), ("session", f"s{c.session_id}")))
            return t.count if t is not None else 0

        before_cli = c.op_counters.get("CltomaGetattr", 0)
        before_active = master_meta_reads(active)
        before_shadow = master_meta_reads(shadow)
        assert (await c.getattr(f.inode)).inode == f.inode
        return (
            c.op_counters.get("CltomaGetattr", 0) - before_cli,
            master_meta_reads(active) - before_active,
            master_meta_reads(shadow) - before_shadow,
        )
    finally:
        await c.close()
        await cs.stop()
        await shadow.stop()
        await active.stop()


@pytest.mark.parametrize("seed", SEEDS)
def test_read_counts_once_across_transient_retry(tmp_path, seed):
    ops, nbytes, size = detsched.run(
        _read_retry_scenario(tmp_path, seed), seed=seed
    )
    assert ops == 1, f"seed {seed}: logical read counted {ops} times"
    assert nbytes == size, f"seed {seed}: bytes double-counted"


@pytest.mark.parametrize("seed", SEEDS)
def test_rmw_write_counts_once_across_retry(tmp_path, seed):
    ops = detsched.run(_rmw_retry_scenario(tmp_path, seed), seed=seed)
    assert ops == 1, f"seed {seed}: logical pwrite counted {ops} times"


@pytest.mark.parametrize("seed", SEEDS)
def test_replica_fallback_counts_once(tmp_path, seed):
    cli, active_n, shadow_n = detsched.run(
        _replica_fallback_scenario(tmp_path), seed=seed
    )
    assert cli == 1, f"seed {seed}: client double-counted the fallback"
    assert active_n == 1, f"seed {seed}: primary counted {active_n}"
    assert shadow_n == 0, f"seed {seed}: refusing shadow recorded the op"
