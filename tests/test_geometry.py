"""Slice/goal geometry tests — ids and part math must match the reference."""

import pytest

from lizardfs_tpu.constants import MFSBLOCKSIZE
from lizardfs_tpu.core import geometry as g


def test_slice_type_ids():
    # goal.h:108-120
    assert g.SliceType(0).is_standard
    assert g.SliceType(1).is_tape
    assert g.xor_type(2) == 2 and g.xor_type(9) == 9
    assert g.ec_type(2, 1) == 10  # kECFirst
    assert g.ec_type(32, 32) == 10 + 31 * 32 - 1  # kECLast
    assert g.ec_type(3, 2) == 10 + 32 * 1 + 1
    t = g.ec_type(8, 4)
    assert (t.data_parts, t.parity_parts, t.expected_parts) == (8, 4, 12)
    x = g.xor_type(5)
    assert (x.data_parts, x.parity_parts, x.expected_parts) == (5, 1, 6)
    assert g.SliceType(0).expected_parts == 1


def test_part_type_packing():
    cpt = g.ChunkPartType(g.ec_type(3, 2), 4)
    assert cpt.id == int(g.ec_type(3, 2)) * 64 + 4
    assert g.ChunkPartType.from_id(cpt.id) == cpt
    assert cpt.is_parity and cpt.parity_part_index == 1
    assert g.ChunkPartType(g.ec_type(3, 2), 2).is_data
    # xor: part 0 is parity, data parts 1..N with 0-based stripe index
    xp = g.ChunkPartType(g.xor_type(3), 0)
    assert xp.is_parity
    xd = g.ChunkPartType(g.xor_type(3), 2)
    assert xd.is_data and xd.data_part_index == 1
    assert cpt.to_string() == "ec(3,2):4"


@pytest.mark.parametrize(
    "k,blocks,per_part",
    [
        (3, 1024, [342, 341, 341]),
        (2, 1024, [512, 512]),
        (8, 1000, [125] * 8),
        (3, 1, [1, 0, 0]),
    ],
)
def test_number_of_blocks(k, blocks, per_part):
    t = g.ec_type(k, 2)
    for i, want in enumerate(per_part):
        cpt = g.ChunkPartType(t, i)
        assert g.number_of_blocks_in_part(cpt, blocks) == want
    # parity parts are as long as part 0
    p = g.ChunkPartType(t, k)
    assert g.number_of_blocks_in_part(p, blocks) == per_part[0]


def test_chunk_length_to_part_length():
    t = g.ec_type(3, 2)
    bs = MFSBLOCKSIZE
    # exactly 2 full stripes
    L = 2 * 3 * bs
    for part in range(3):
        assert g.chunk_length_to_part_length(g.ChunkPartType(t, part), L) == 2 * bs
    # partial stripe: 2 stripes + 1.5 blocks
    L = 2 * 3 * bs + bs + bs // 2
    assert g.chunk_length_to_part_length(g.ChunkPartType(t, 0), L) == 3 * bs
    assert g.chunk_length_to_part_length(g.ChunkPartType(t, 1), L) == 2 * bs + bs // 2
    assert g.chunk_length_to_part_length(g.ChunkPartType(t, 2), L) == 2 * bs
    # parity follows part 0
    assert g.chunk_length_to_part_length(g.ChunkPartType(t, 3), L) == 3 * bs
    # std slice gets everything
    assert g.chunk_length_to_part_length(g.standard_part(), 12345) == 12345


def test_goal_parsing_examples():
    # examples straight from doc/mfsgoals.cfg.5.txt:88-98
    cases = {
        "3 3 : _ _ _": ("3", g.STANDARD, 3),
        "8 not_important_file : _": ("not_important_file", g.STANDARD, 1),
        "12 local_copy_on_mars : mars _": ("local_copy_on_mars", g.STANDARD, 2),
        "15 default_xor3 : $xor3": ("default_xor3", g.xor_type(3), 4),
        "16 fast_read : $xor2 { ssd ssd hdd }": ("fast_read", g.xor_type(2), 3),
        "18 first_ec : $ec(3,1)": ("first_ec", g.ec_type(3, 1), 4),
        "20 ec53_mixed : $ec(5,3) { hdd ssd hdd _ _ _ _ _ }": (
            "ec53_mixed",
            g.ec_type(5, 3),
            8,
        ),
    }
    for line, (name, type_, copies) in cases.items():
        gid, goal = g.parse_goal_line(line)
        assert goal.name == name
        assert int(goal.slices[0].type) == int(type_)
        assert goal.expected_copies() == copies

    # label placement for the mixed ec goal
    _, goal = g.parse_goal_line("20 ec53_mixed : $ec(5,3) { hdd ssd hdd _ _ _ _ _ }")
    s = goal.slices[0]
    assert s.labels_of_part(0) == {"hdd": 1}
    assert s.labels_of_part(1) == {"ssd": 1}
    assert s.labels_of_part(3) == {"_": 1}


def test_goal_parsing_errors():
    for bad in [
        "0 zero : _",  # id out of range
        "41 hi : _",
        "3 bad name : _",
        "3 x : $xor1",
        "3 x : $xor10",
        "3 x : $ec(1,1)",
        "3 x : $ec(33,1)",
        "3 x : $ec",
        "3 x : $wat",
        "3 x : $xor2 ssd ssd",  # typed labels must be braced
        "nonsense",
    ]:
        with pytest.raises(g.GoalConfigError):
            g.parse_goal_line(bad)
    assert g.parse_goal_line("  # comment only") is None
    assert g.parse_goal_line("") is None


def test_load_config_keeps_defaults():
    goals = g.load_goal_config("15 x3 : $xor3\n")
    assert goals[1].expected_copies() == 1
    assert goals[3].expected_copies() == 3
    assert int(goals[15].slices[0].type) == int(g.xor_type(3))
    assert goals[40].expected_copies() == 1
