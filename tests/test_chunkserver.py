"""Chunk store + standalone chunkserver serving tests."""

import asyncio
import os

import numpy as np
import pytest

from lizardfs_tpu.chunkserver.chunk_store import (
    ChunkStore,
    ChunkStoreError,
    chunk_filename,
    parse_chunk_filename,
)
from lizardfs_tpu.chunkserver.server import ChunkServer
from lizardfs_tpu.constants import MFSBLOCKSIZE
from lizardfs_tpu.core import geometry, plans
from lizardfs_tpu.core.read_executor import execute_plan, read_part_range
from lizardfs_tpu.ops import crc32 as crc_mod
from lizardfs_tpu.proto import status as st
from lizardfs_tpu.utils import data_generator

PART = geometry.ChunkPartType(geometry.ec_type(3, 2), 1).id


def test_filename_roundtrip():
    name = chunk_filename(0xDEADBEEF12345678, PART, 7)
    assert parse_chunk_filename(name) == (0xDEADBEEF12345678, PART, 7)
    # legacy (pre-part-in-name) files parse with part None for migration
    legacy = f"chunk_{0xDEADBEEF12345678:016X}_{7:08X}.liz"
    assert parse_chunk_filename(legacy) == (0xDEADBEEF12345678, None, 7)
    assert parse_chunk_filename("chunk_zz_7.liz") is None
    assert parse_chunk_filename("foo.liz") is None


def test_store_create_write_read(tmp_path):
    store = ChunkStore(str(tmp_path))
    store.create(1, 1, PART)
    data = data_generator.generate(0, 2 * MFSBLOCKSIZE + 100)
    # write two full blocks and a piece of the third
    for b in range(2):
        piece = data[b * MFSBLOCKSIZE : (b + 1) * MFSBLOCKSIZE].tobytes()
        store.write(1, 1, PART, b, 0, piece, crc_mod.crc32(piece))
    tail = data[2 * MFSBLOCKSIZE :].tobytes()
    store.write(1, 1, PART, 2, 0, tail, crc_mod.crc32(tail))

    pieces = store.read(1, 1, PART, 0, 2 * MFSBLOCKSIZE + 100)
    got = np.concatenate([np.frombuffer(p, dtype=np.uint8) for _, p, _ in pieces])
    np.testing.assert_array_equal(got, data)

    # unaligned read inside one block
    pieces = store.read(1, 1, PART, 1000, 500)
    assert len(pieces) == 1
    off, piece, crc = pieces[0]
    assert off == 1000 and crc == crc_mod.crc32(piece)
    np.testing.assert_array_equal(
        np.frombuffer(piece, np.uint8), data[1000:1500]
    )


def test_store_errors(tmp_path):
    store = ChunkStore(str(tmp_path))
    store.create(5, 3, PART)
    with pytest.raises(ChunkStoreError) as e:
        store.create(5, 3, PART)
    assert e.value.code == st.EEXIST
    with pytest.raises(ChunkStoreError) as e:
        store.read(5, 99, PART, 0, 10)
    assert e.value.code == st.WRONG_VERSION
    with pytest.raises(ChunkStoreError) as e:
        store.read(6, 3, PART, 0, 10)
    assert e.value.code == st.NO_CHUNK
    # bad piece CRC on write
    with pytest.raises(ChunkStoreError) as e:
        store.write(5, 3, PART, 0, 0, b"hello", 0)
    assert e.value.code == st.CRC_ERROR


def test_store_corruption_detected(tmp_path):
    store = ChunkStore(str(tmp_path))
    cf = store.create(9, 1, PART)
    block = data_generator.generate(0, MFSBLOCKSIZE).tobytes()
    store.write(9, 1, PART, 0, 0, block, crc_mod.crc32(block))
    # flip a byte on disk behind the store's back
    with open(cf.path, "r+b") as f:
        f.seek(5 * 1024 + 100)
        f.write(b"\xff")
    with pytest.raises(ChunkStoreError) as e:
        store.read(9, 1, PART, 0, MFSBLOCKSIZE)
    assert e.value.code == st.CRC_ERROR
    assert store.test_part(cf) is False


def test_store_scan_and_version_gc(tmp_path):
    store = ChunkStore(str(tmp_path))
    store.create(1, 1, PART)
    store.create(2, 1, PART)
    store.set_version(2, 1, 2, PART)
    # stale version left behind manually
    stale = os.path.join(str(tmp_path), "01", chunk_filename(1, PART, 0))
    os.makedirs(os.path.dirname(stale), exist_ok=True)
    with open(os.path.join(str(tmp_path), "02", chunk_filename(2, PART, 2)), "rb") as f:
        header = f.read()
    # a second store scans the same folder from scratch
    store2 = ChunkStore(str(tmp_path))
    parts = store2.scan()
    byid = {(cf.chunk_id, cf.part_id): cf for cf in parts}
    assert byid[(1, PART)].version == 1
    assert byid[(2, PART)].version == 2


def test_store_truncate(tmp_path):
    store = ChunkStore(str(tmp_path))
    store.create(3, 1, PART)
    data = data_generator.generate(0, 2 * MFSBLOCKSIZE)
    for b in range(2):
        piece = data[b * MFSBLOCKSIZE : (b + 1) * MFSBLOCKSIZE].tobytes()
        store.write(3, 1, PART, b, 0, piece, crc_mod.crc32(piece))
    store.truncate_part(3, 1, PART, MFSBLOCKSIZE + 10)
    pieces = store.read(3, 1, PART, 0, 2 * MFSBLOCKSIZE)
    got = np.concatenate([np.frombuffer(p, np.uint8) for _, p, _ in pieces])
    np.testing.assert_array_equal(got[: MFSBLOCKSIZE + 10], data[: MFSBLOCKSIZE + 10])
    assert (got[MFSBLOCKSIZE + 10 :] == 0).all()


@pytest.mark.asyncio
async def test_chunkserver_read_write_over_network(tmp_path):
    """Standalone chunkserver: write a chain of blocks, read them back."""
    cs = ChunkServer(str(tmp_path), master_addr=None)
    await cs.start()
    try:
        from lizardfs_tpu.proto import framing, messages as m

        reader, writer = await asyncio.open_connection("127.0.0.1", cs.port)
        await framing.send_message(
            writer,
            m.CltocsWriteInit(
                req_id=1, chunk_id=42, version=1, part_id=PART, chain=[], create=True
            ),
        )
        reply = await framing.read_message(reader)
        assert reply.status == st.OK
        data = data_generator.generate(0, MFSBLOCKSIZE + 500)
        b0 = data[:MFSBLOCKSIZE].tobytes()
        b1 = data[MFSBLOCKSIZE:].tobytes()
        await framing.send_message(
            writer,
            m.CltocsWriteData(
                req_id=2, chunk_id=42, write_id=1, block=0, offset=0,
                crc=crc_mod.crc32(b0), data=b0,
            ),
        )
        await framing.send_message(
            writer,
            m.CltocsWriteData(
                req_id=3, chunk_id=42, write_id=2, block=1, offset=0,
                crc=crc_mod.crc32(b1), data=b1,
            ),
        )
        acks = [await framing.read_message(reader) for _ in range(2)]
        assert all(a.status == st.OK for a in acks)
        await framing.send_message(
            writer, m.CltocsWriteEnd(req_id=4, chunk_id=42)
        )
        end = await framing.read_message(reader)
        assert end.status == st.OK
        writer.close()

        # read back through the executor helper
        got = await read_part_range(
            ("127.0.0.1", cs.port), 42, 1, PART, 0, MFSBLOCKSIZE + 500
        )
        np.testing.assert_array_equal(got, data)

        # wrong version must be rejected
        with pytest.raises(Exception):
            await read_part_range(("127.0.0.1", cs.port), 42, 9, PART, 0, 10)
    finally:
        await cs.stop()


@pytest.mark.asyncio
async def test_chain_write_and_wave_read(tmp_path):
    """3-server chain write; then read with one server down (wave fallback).

    This is the heart of the data plane: client-side parity write via
    chain, degraded read via EC recovery.
    """
    from lizardfs_tpu.proto import framing, messages as m
    from lizardfs_tpu.utils import striping

    t = geometry.ec_type(3, 2)
    servers = []
    for i in range(5):
        cs = ChunkServer(str(tmp_path / f"cs{i}"), master_addr=None)
        await cs.start()
        servers.append(cs)
    try:
        chunk_len = 4 * MFSBLOCKSIZE + 777
        chunk = data_generator.generate(0, chunk_len)
        parts = striping.split_chunk(chunk, t)
        part_ids = {p: geometry.ChunkPartType(t, p).id for p in parts}

        # chain write: head = server 0 holding part 0, chain continues 1..4
        chain = [
            m.PartLocation(
                addr=m.Addr(host="127.0.0.1", port=servers[p].port),
                part_id=part_ids[p],
            )
            for p in range(1, 5)
        ]
        reader, writer = await asyncio.open_connection("127.0.0.1", servers[0].port)
        await framing.send_message(
            writer,
            m.CltocsWriteInit(
                req_id=1, chunk_id=7, version=1, part_id=part_ids[0],
                chain=chain, create=True,
            ),
        )
        reply = await framing.read_message(reader)
        assert reply.status == st.OK

        # each server in the chain stores ITS part -> chain write here means
        # per-part data flows; send block b of part p to the chain with
        # (part-specific payloads are delivered by write ops addressed per
        # server in the real client; for the chain smoke test write part 0's
        # bytes through the chain head only)
        nblocks = geometry.number_of_blocks_in_part(
            geometry.ChunkPartType(t, 0), 5
        )
        for b in range(nblocks):
            piece = parts[0][b * MFSBLOCKSIZE : (b + 1) * MFSBLOCKSIZE].tobytes()
            await framing.send_message(
                writer,
                m.CltocsWriteData(
                    req_id=10 + b, chunk_id=7, write_id=b + 1, block=b,
                    offset=0, crc=crc_mod.crc32(piece), data=piece,
                ),
            )
        oks = 0
        while oks < nblocks:
            msg = await framing.read_message(reader)
            assert isinstance(msg, m.CstoclWriteStatus) and msg.status == st.OK
            oks += 1
        writer.close()
        # part 0 written on server 0; chain created empty parts downstream
        assert servers[0].store.get(7, part_ids[0]) is not None
        assert servers[1].store.get(7, part_ids[1]) is not None
    finally:
        for cs in servers:
            await cs.stop()


def test_multistore_placement_and_ops(tmp_path):
    from lizardfs_tpu.chunkserver.chunk_store import MultiStore
    from lizardfs_tpu.ops import crc32 as crc_mod

    ms = MultiStore([str(tmp_path / "d0"), str(tmp_path / "d1")])
    # create several parts; both folders end up holding some
    for cid in range(8):
        ms.create(cid, 1, PART)
    folders = {cf.path.split("/")[-3] for cf in ms.all_parts()}
    assert len(ms.all_parts()) == 8
    # ops route to the owning folder
    block = data_generator.generate(0, MFSBLOCKSIZE).tobytes()
    ms.write(3, 1, PART, 0, 0, block, crc_mod.crc32(block))
    pieces = ms.read(3, 1, PART, 0, MFSBLOCKSIZE)
    assert pieces[0][1] == block
    ms.set_version(3, 1, 2, PART)
    assert ms.get(3, PART).version == 2
    ms.duplicate(3, 2, PART, 100, 1)
    assert ms.get(100, PART) is not None
    ms.delete(3, 2, PART)
    assert ms.get(3, PART) is None
    total, used = ms.space()
    assert total > 0
    # rescan from cold finds everything
    ms2 = MultiStore([str(tmp_path / "d0"), str(tmp_path / "d1")])
    found = ms2.scan()
    assert len(found) == 8  # 7 remaining + duplicate


@pytest.mark.asyncio
async def test_multidisk_chunkserver_e2e(tmp_path):
    from tests.test_cluster import make_goals
    from lizardfs_tpu.master.server import MasterServer
    from lizardfs_tpu.client.client import Client

    master = MasterServer(str(tmp_path / "m"), goals=make_goals())
    await master.start()
    servers = []
    for i in range(3):
        cs = ChunkServer(
            [str(tmp_path / f"cs{i}a"), str(tmp_path / f"cs{i}b")],
            master_addr=("127.0.0.1", master.port),
        )
        await cs.start()
        servers.append(cs)
    c = Client("127.0.0.1", master.port)
    await c.connect()
    try:
        f = await c.create(1, "multi.bin")
        payload = data_generator.generate(0, 300_000).tobytes()
        await c.write_file(f.inode, payload)
        assert (await c.read_file(f.inode)) == payload
    finally:
        await c.close()
        for cs in servers:
            await cs.stop()
        await master.stop()


def test_store_multiple_parts_of_one_chunk(tmp_path):
    """A server may hold several parts of the same chunk (more parts
    than servers, rebalancing). Regression: the part id was missing
    from the filename and the parts truncated each other."""
    store = ChunkStore(str(tmp_path))
    p1 = geometry.ChunkPartType(geometry.ec_type(8, 4), 1).id
    p2 = geometry.ChunkPartType(geometry.ec_type(8, 4), 9).id
    store.create(5, 1, p1)
    store.create(5, 1, p2)
    blk1 = bytes([0x11]) * 65536
    blk2 = bytes([0x22]) * 65536
    store.write(5, 1, p1, 0, 0, blk1, crc_mod.crc32(blk1))
    store.write(5, 1, p2, 0, 0, blk2, crc_mod.crc32(blk2))
    [(_, d1, _c1)] = store.read(5, 1, p1, 0, 65536)
    [(_, d2, _c2)] = store.read(5, 1, p2, 0, 65536)
    assert d1[:1] == b"\x11" and d2[:1] == b"\x22"
    # both survive a rescan as distinct files
    store2 = ChunkStore(str(tmp_path))
    parts = {(c.chunk_id, c.part_id) for c in store2.scan()}
    assert parts == {(5, p1), (5, p2)}


def test_store_legacy_filename_migration(tmp_path):
    """Old-format files (no part id in the name) are renamed in place
    during the scan using the signature's part id."""
    store = ChunkStore(str(tmp_path))
    cf = store.create(9, 3, PART)
    blk = bytes([0x7A]) * 65536
    store.write(9, 3, PART, 0, 0, blk, crc_mod.crc32(blk))
    legacy = os.path.join(
        os.path.dirname(cf.path), f"chunk_{9:016X}_{3:08X}.liz"
    )
    os.rename(cf.path, legacy)
    store2 = ChunkStore(str(tmp_path))
    [found] = store2.scan()
    assert found.part_id == PART and found.path != legacy
    assert os.path.basename(found.path) == chunk_filename(9, PART, 3)
    [(_, data, _c)] = store2.read(9, 3, PART, 0, 65536)
    assert data[:1] == b"\x7a"


@pytest.mark.asyncio
async def test_chunk_tester_rotates_with_budget(tmp_path):
    """The scrubber must (a) stop after ~test_budget_bytes per round and
    (b) ROTATE so every part is eventually covered — a fixed prefix
    would re-scan the same parts forever and never reach a corrupted
    part beyond it (the pre-r05 behavior)."""
    cs = ChunkServer(str(tmp_path), master_addr=None,
                     native_data_plane=False)
    block = data_generator.generate(3, MFSBLOCKSIZE).tobytes()
    crc = crc_mod.crc32(block)
    for cid in range(1, 13):
        cs.store.create(cid, 1, PART)
        cs.store.write(cid, 1, PART, 0, 0, block, crc)
    # corrupt the LAST part's data without fixing its CRC
    victim = cs.store.get(12, PART)
    with open(victim.path, "r+b") as f:
        f.seek(-17, os.SEEK_END)
        f.write(b"\xff")
    cs.test_budget_bytes = 2 * MFSBLOCKSIZE  # ~2 parts per round
    reported = []

    async def fake_send(msg):
        reported.extend(msg.chunks)

    class _FakeMaster:
        closed = False
        send = staticmethod(fake_send)

    cs.master = _FakeMaster()
    seen_cursors = set()
    for _ in range(12):  # enough rounds for a full lap at 2 parts/round
        await cs._test_chunks()
        seen_cursors.add(cs._test_cursor)
    assert any(c.chunk_id == 12 for c in reported), \
        "rotation never reached the corrupted part"
    assert len(seen_cursors) > 1, "cursor did not advance"
    # healthy parts were not reported
    assert all(c.chunk_id == 12 for c in reported)
