"""On-disk format compatibility ("upgrade") tests.

The analog of the reference's cross-version upgrade suites (reference
tests/tools/lizardfsXX.sh + tests/test_suites/*/test_upgrade_*: old
daemons write data, the current build must serve it). We have one
lineage, so the contract is pinned with a committed golden data tree
(tests/data/golden, produced by tests/make_golden_fixture.py): today's
daemons boot on a copy of it and must read every namespace feature and
every byte back. An accidental change to the metadata image format,
changelog grammar, chunk file layout, or part filename scheme fails
here first — turning a silent corruption into a deliberate format bump
(regenerate the fixture + document migration in doc/migration.md).
"""

import asyncio
import hashlib
import json
import shutil
from pathlib import Path

import pytest

from lizardfs_tpu.chunkserver.server import ChunkServer
from lizardfs_tpu.client.client import Client
from lizardfs_tpu.core import geometry
from lizardfs_tpu.master.changelog import load_image
from lizardfs_tpu.master.server import MasterServer

GOLDEN = Path(__file__).parent / "data" / "golden"

EC_GOAL = 10


def golden_goals():
    goals = geometry.default_goals()
    goals[EC_GOAL] = geometry.parse_goal_line(f"{EC_GOAL} ecgold : $ec(3,2)")[1]
    return goals


def expectations() -> dict:
    return json.loads((GOLDEN / "expect.json").read_text())


class GoldenCluster:
    """Today's daemons booted on a copy of the golden data tree."""

    def __init__(self, tmp_path: Path):
        self.tmp = tmp_path
        shutil.copytree(GOLDEN / "master", tmp_path / "master")
        for i in range(3):
            shutil.copytree(GOLDEN / f"cs{i}", tmp_path / f"cs{i}")
        self.master = None
        self.servers = []
        self.client = None

    async def __aenter__(self):
        self.master = MasterServer(str(self.tmp / "master"),
                                   goals=golden_goals(),
                                   health_interval=0.2)
        await self.master.start()
        for i in range(3):
            cs = ChunkServer(str(self.tmp / f"cs{i}"),
                             master_addr=("127.0.0.1", self.master.port))
            await cs.start()
            self.servers.append(cs)
        self.client = Client("127.0.0.1", self.master.port)
        await self.client.connect()
        return self

    async def __aexit__(self, *exc):
        await self.client.close()
        for cs in self.servers:
            await cs.stop()
        await self.master.stop()

    async def resolve(self, path: str) -> int:
        inode = 1
        for name in path.split("/"):
            inode = (await self.client.lookup(inode, name)).inode
        return inode


@pytest.mark.asyncio
async def test_golden_tree_serves_all_features(tmp_path):
    exp = expectations()
    async with GoldenCluster(tmp_path) as g:
        c = g.client
        # file payloads, replicated and EC-striped
        for path, want_sha in exp["files"].items():
            inode = await g.resolve(path)
            attr = await c.getattr(inode)
            data = await c.read_file(inode, 0, attr.length)
            assert hashlib.sha256(bytes(data)).hexdigest() == want_sha, path
        # symlink
        lnk = await g.resolve("docs/lnk")
        assert await c.readlink(lnk) == exp["symlink_target"]
        # hardlink: same inode, nlink 2
        a = await g.resolve("docs/a.bin")
        hard = await g.resolve("docs/a_hard.bin")
        assert a == hard
        assert (await c.getattr(a)).nlink == 2
        # xattr
        val = await c.get_xattr(a, exp["xattr"]["name"])
        assert bytes(val) == exp["xattr"]["value"].encode()
        # quota
        rows = await c.get_quota()
        q = exp["quota"]
        assert any(
            r.get("kind") == "user"
            and r.get("id") == q["uid"]
            and r.get("soft_inodes") == q["soft_inodes"]
            and r.get("hard_inodes") == q["hard_inodes"]
            for r in rows
        ), rows
        # trash entry survives the image/changelog round trip
        trash = await c.trash_list()
        assert any(t.get("inode") == exp["trash_inode"] for t in trash), trash


@pytest.mark.asyncio
async def test_unknown_image_format_is_rejected(tmp_path):
    shutil.copytree(GOLDEN / "master", tmp_path / "master")
    img = tmp_path / "master" / "metadata.liz"
    doc = json.loads(img.read_text())
    doc["format"] = "lizardfs-tpu-metadata-999"
    img.write_text(json.dumps(doc))
    with pytest.raises(ValueError, match="format"):
        load_image(str(tmp_path / "master"))
    # the daemon start path must surface the same failure, not boot an
    # empty namespace over good data
    master = MasterServer(str(tmp_path / "master"), goals=golden_goals())
    with pytest.raises(ValueError, match="format"):
        await master.start()


@pytest.mark.asyncio
async def test_corrupt_chunk_signature_is_quarantined(tmp_path):
    """A bad chunk magic must degrade (part skipped, EC recovers), not
    crash the scan or serve wrong bytes."""
    exp = expectations()
    # corrupt one EC part of the b.bin chunk specifically (not just the
    # first chunk file on cs0): a regenerated fixture with different
    # placement must not silently turn this into a no-op or corrupt the
    # sole copy of a goal-1 file
    victim = next(
        p
        for cs in sorted(GOLDEN.glob("cs*"))
        for p in sorted(cs.rglob("chunk_0000000000000002_P*AC*.liz"))
    )
    cs_name = victim.relative_to(GOLDEN).parts[0]
    g = GoldenCluster(tmp_path)
    bad = tmp_path / cs_name / victim.relative_to(GOLDEN / cs_name)
    raw = bytearray(bad.read_bytes())
    raw[:8] = b"NOTLIZRD"
    bad.write_bytes(bytes(raw))
    async with g:
        inode = await g.resolve("docs/inner/b.bin")
        attr = await g.client.getattr(inode)
        data = await g.client.read_file(inode, 0, attr.length)
        want = exp["files"]["docs/inner/b.bin"]
        assert hashlib.sha256(bytes(data)).hexdigest() == want
