"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Real TPU hardware (one chip behind the axon tunnel) is reserved for
bench.py; tests validate numerics and multi-chip sharding on host CPU
devices.

The axon sitecustomize imports jax and registers the TPU backend at
interpreter startup — before this conftest runs — so env vars alone don't
stick under pytest. Setting XLA_FLAGS still works (the CPU client is not
created yet), and ``jax.config.update("jax_platforms", ...)`` overrides
the platform as long as no backend has been initialized.
"""

import os

os.environ["PALLAS_AXON_POOL_IPS"] = ""  # pre-sitecustomize runs, belt+braces
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# --- minimal async test support (pytest-asyncio is not in the image) -------
import asyncio  # noqa: E402
import inspect  # noqa: E402


def pytest_pyfunc_call(pyfuncitem):
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        # racehunt mode (tools/racehunt.py): LZ_DETSCHED=<seed> runs
        # every async test under the seeded deterministic event loop so
        # each seed explores one reproducible interleaving
        from lizardfs_tpu.runtime import detsched

        seed = detsched.detsched_seed()
        if seed is not None:
            detsched.run(fn(**kwargs), seed=seed)
        else:
            asyncio.run(fn(**kwargs))
        return True
    return None


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: run test in an event loop")
    config.addinivalue_line(
        "markers",
        "slow: heavy variants excluded from tier-1 (-m 'not slow')",
    )
