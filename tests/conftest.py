"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Real TPU hardware (one chip) is reserved for bench.py; tests validate
numerics and multi-chip sharding on host CPU devices. Must run before any
jax import, hence here in the root conftest.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
