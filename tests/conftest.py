"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Real TPU hardware (one chip behind the axon tunnel) is reserved for
bench.py; tests validate numerics and multi-chip sharding on host CPU
devices.

The axon sitecustomize imports jax and registers the TPU backend at
interpreter startup — before this conftest runs — so env vars alone don't
stick under pytest. Setting XLA_FLAGS still works (the CPU client is not
created yet), and ``jax.config.update("jax_platforms", ...)`` overrides
the platform as long as no backend has been initialized.
"""

import os

os.environ["PALLAS_AXON_POOL_IPS"] = ""  # pre-sitecustomize runs, belt+braces
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
